"""Minimized Mosaic probes for the fused exact-cover kernel (VERDICT r4 #3).

The cover algebra (``models/cover.py``) is gather-heavy on its face —
``col_rows[col]``, ``elim[row]`` by dynamic per-lane index — and Mosaic
lowers no dynamic gather.  The kernel design replaces every gather with an
MXU matmul over 0/1 float32 matrices (f32 is exact for the small integers
involved), so before building the kernel this probe pins each primitive on
real v5e hardware, uint16-refutation-grade:

  P1  f32 dot_general inside a kernel: [C, R']@[R', T] and [R', C]@[C, T]
  P2  bit-unpack via select-matmul + iota shifts: packed uint32[W, T] ->
      bits int32[R', T]  (word-at-row = sel[R', W] @ halves; per-row shift
      by broadcasted_iota % 32)
  P3  bit-pack via weight-matmuls: bits[R', T] -> uint32[W, T]
      (two [W, R'] @ [R', T] matmuls, 16 bits each, f32-exact)
  P4  full-axis min over sublanes (keepdims) + ones-matmul
      re-materialization [R', 1]@[1, T], result used in a `where`
      condition (the broadcast-provenance trap `_bcast_reduce` documents)
  P5  while_loop carrying ([D, T] uint32, [S, D, T] uint32, [8, T] int32)
      with all of the above in the body

Each probe compiles + runs standalone; failures print the Mosaic error so
the wall (if any) is named precisely.  CPU interpret mode cross-checks the
algebra before the hardware compile.

Post-build finding (the kernel's first hardware run caught what this
probe's original comparison could not): BOTH Mosaic and XLA:TPU compute
f32 dots at reduced precision by default (bf16 input passes), which
rounds the 16-bit word values in the unpack matmuls — and because this
probe compared the real kernel against *interpret mode in the same TPU
process*, both sides were identically wrong and the comparison passed.
Every dot now pins ``precision=HIGHEST`` (exact f32), matching
``ops/pallas_cover.py``, and the checksum row below asserts a known
value so a same-wrong-both-sides regression cannot slip through again.
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

R, W, C, T, S, D = 224, 7, 28, 128, 8, 8  # queens-14-ish geometry


def _unpack_consts():
    """sel [R, W] f32 (row r reads word r//32); shift [R, 1] iota % 32."""
    sel = np.zeros((R, W), np.float32)
    sel[np.arange(R), np.arange(R) // 32] = 1.0
    return sel


def _pack_consts():
    """Weight matrices: packed_lo/hi = Wlo/Whi @ bits, 16 f32-exact bits each."""
    wlo = np.zeros((W, R), np.float32)
    whi = np.zeros((W, R), np.float32)
    r = np.arange(R)
    bit = r % 32
    lo = bit < 16
    wlo[r[lo] // 32, r[lo]] = (1 << bit[lo]).astype(np.float32)
    whi[r[~lo] // 32, r[~lo]] = (1 << (bit[~lo] - 16)).astype(np.float32)
    return wlo, whi


_EXACT = jax.lax.Precision.HIGHEST


def _dot(a, b):
    return jnp.dot(a, b, preferred_element_type=jnp.float32, precision=_EXACT)


def unpack_bits(packed_u32, sel_f):
    """uint32[W, T] -> int32 0/1 [R, T] via matmul + iota shifts."""
    # Mosaic has no uint32 -> f32 cast (probed); the masked halves fit int32.
    lo = (packed_u32 & jnp.uint32(0xFFFF)).astype(jnp.int32).astype(jnp.float32)
    hi = (packed_u32 >> jnp.uint32(16)).astype(jnp.int32).astype(jnp.float32)
    lo_at = _dot(sel_f, lo)
    hi_at = _dot(sel_f, hi)
    shift = jax.lax.broadcasted_iota(jnp.int32, (R, T), 0) % 32
    lo_i = lo_at.astype(jnp.int32)
    hi_i = hi_at.astype(jnp.int32)
    return jnp.where(
        shift < 16,
        (lo_i >> shift) & 1,
        (hi_i >> (shift - 16)) & 1,
    )


def pack_bits(bits_i, wlo_f, whi_f):
    """int32 0/1 [R, T] -> uint32[W, T] via two weight matmuls."""
    bf = bits_i.astype(jnp.float32)
    lo = _dot(wlo_f, bf)
    hi = _dot(whi_f, bf)
    # f32 -> int32 -> uint32 (no direct f32 -> uint32 cast in Mosaic).
    return lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << jnp.uint32(16)
    )


def kernel(inc_ref, sel_ref, wlo_ref, whi_ref, packed_ref, meta_ref,
           stack_ref, out_cnt, out_packed, out_meta, out_stack,
           *, steps: int):
    inc = inc_ref[...]          # f32 [R, C] incidence
    sel = sel_ref[...]          # f32 [R, W]
    wlo = wlo_ref[...]          # f32 [W, R]
    whi = whi_ref[...]          # f32 [W, R]
    packed = packed_ref[...]    # uint32 [W, T] avail
    meta = meta_ref[...]        # int32 [8, T]
    stack = stack_ref[...]      # uint32 [S, W, T]

    def body(c):
        packed, meta, stack, k = c
        bits = unpack_bits(packed, sel)                      # P2
        bf = bits.astype(jnp.float32)
        cnt = jax.lax.dot_general(                           # P1: [C, T]
            inc, bf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_EXACT,
        )
        # P4: lowest available row, rematerialized by ones-matmul
        r_iota = jax.lax.broadcasted_iota(jnp.int32, (R, T), 0)
        key = jnp.where(bits > 0, r_iota, jnp.int32(1 << 22))
        rmin = jnp.min(key, axis=0, keepdims=True)           # [1, T]
        ones = jnp.zeros((R, 1), jnp.float32) + 1.0
        rmin_rep = _dot(ones, rmin.astype(jnp.float32)).astype(jnp.int32)
        rowsel = jnp.where((r_iota == rmin_rep) & (bits > 0), 1, 0)
        # conflict via two matmuls: rows sharing a column with rowsel
        colset = jax.lax.dot_general(                        # [C, T]
            inc, rowsel.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_EXACT,
        )
        conflict = _dot(inc, jnp.minimum(colset, 1.0))       # [R, T]
        bits = jnp.where((conflict > 0) & (rowsel == 0), 0, bits)
        new_packed = pack_bits(bits, wlo, whi)               # P3
        meta = meta + (rmin_rep[0:8] < (1 << 22)).astype(jnp.int32)
        # Static-slot write tree (the Sudoku kernel's push idiom on [S, W, T])
        slot = meta[0:1] % S                                 # [1, T]
        slot_rep = _dot(
            jnp.zeros((W, 1), jnp.float32) + 1.0, slot.astype(jnp.float32)
        ).astype(jnp.int32)                                  # [W, T]
        stack = jnp.concatenate(
            [
                jnp.where((slot_rep == i)[None], packed[None], stack[i : i + 1])
                for i in range(S)
            ],
            axis=0,
        )
        return new_packed, meta, stack, k + 1

    packed, meta, stack, _ = jax.lax.while_loop(             # P5
        lambda c: c[3] < steps, body, (packed, meta, stack, jnp.int32(0))
    )
    bits = unpack_bits(packed, sel)
    cnt = jax.lax.dot_general(
        inc, bits.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_EXACT,
    )
    out_cnt[...] = cnt.astype(jnp.int32)
    out_packed[...] = packed
    out_meta[...] = meta
    out_stack[...] = stack


def run(interpret: bool) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    rng = np.random.default_rng(0)
    inc = (rng.random((R, C)) < 0.1).astype(np.float32)
    sel = _unpack_consts()
    wlo, whi = _pack_consts()
    packed0 = rng.integers(0, 2**32, (W, T), dtype=np.uint32)
    meta0 = np.zeros((8, T), np.int32)
    stack0 = np.zeros((S, W, T), np.uint32)

    f = pl.pallas_call(
        functools.partial(kernel, steps=3),
        out_shape=(
            jax.ShapeDtypeStruct((C, T), jnp.int32),
            jax.ShapeDtypeStruct((W, T), jnp.uint32),
            jax.ShapeDtypeStruct((8, T), jnp.int32),
            jax.ShapeDtypeStruct((S, W, T), jnp.uint32),
        ),
        interpret=interpret,
    )
    out = f(
        jnp.asarray(inc), jnp.asarray(sel), jnp.asarray(wlo),
        jnp.asarray(whi), jnp.asarray(packed0), jnp.asarray(meta0),
        jnp.asarray(stack0),
    )
    return tuple(np.asarray(o) for o in out)


def main() -> None:
    import json

    ref = run(interpret=True)
    try:
        got = run(interpret=False)
    except Exception as e:  # noqa: BLE001 — the probe's job is to name the wall
        print(json.dumps({
            "metric": "cover_kernel_probe",
            "compiles": False,
            "error": str(e)[:2000],
        }))
        sys.exit(1)
    match = all(np.array_equal(a, b) for a, b in zip(ref, got))
    # Backend-independent ground truth: interpret-on-TPU and Mosaic share
    # the dot-lowering default, so "they agree" alone proves nothing — the
    # hardware output must ALSO reproduce the value pinned from an exact
    # (precision=HIGHEST) run, or a reduced-precision regression is loose.
    checksum = int(got[1].astype(np.uint64).sum() % (1 << 31))
    assert checksum == 653337268, (
        f"packed checksum {checksum} != pinned 653337268: a dot in this "
        "probe (or its lowering) lost exactness — check precision pins"
    )
    print(json.dumps({
        "metric": "cover_kernel_probe",
        "compiles": True,
        "bit_exact_vs_interpret": bool(match),
        "packed_checksum": checksum,
    }))


if __name__ == "__main__":
    main()
