"""25x25 fused-kernel attempt (VERDICT r4 #4b / #2b, round-5 stretch).

Rounds 3-4 recorded 25x25 as "never fits and stays composite".  The
round-5 scoped-vmem re-measurement overturned the admission wall
(`_max_slots`: whole-array S<=48, gridded S<=24 now compile), so this
probe measures what the fused kernel actually BUYS on the giant board —
the geometry the reference crashes on outright:

  shallow — 60%-clue corpus (the BENCHMARKS "25x25 end-to-end" row):
            composite S=64 (the r2 protocol row) vs composite S=24 vs
            fused S=24 first pass, interleaved
  deep    — 45%-clue corpus (the 5.6 boards/s worst row): the default
            ladder under a composite vs fused FIRST pass, and the
            gridded-admitted gang rung (64, 128, 24) under composite vs
            fused rung engines (`BulkConfig.rung_step_impl`)

Every config solves the same corpus; solved counts asserted equal.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def run_matrix(grids, geom, cfgs: dict, repeat: int = 3) -> None:
    from distributed_sudoku_solver_tpu.ops.bulk import solve_bulk

    results = {k: solve_bulk(grids, geom, c) for k, c in cfgs.items()}  # warm
    walls: dict[str, list] = {k: [] for k in cfgs}
    for _ in range(repeat):
        for k, c in cfgs.items():  # interleaved: drift hits all equally
            tr: dict = {}
            t0 = time.perf_counter()
            results[k] = solve_bulk(grids, geom, c, trace=tr)
            walls[k].append((time.perf_counter() - t0, tr))
    # The documented invariant: a throughput row from an engine that did
    # not solve the same corpus must never justify a default.
    solved_counts = {k: int(r.solved.sum()) for k, r in results.items()}
    assert len(set(solved_counts.values())) == 1, solved_counts
    for k in cfgs:
        best, tr = min(walls[k], key=lambda w: w[0])
        res = results[k]
        emit(
            metric="probe25",
            config=k,
            boards=len(grids),
            boards_per_s=round(len(grids) / best, 2),
            wall_s=round(best, 3),
            solved=int(res.solved.sum()),
            searched=res.searched,
            first_pass_s=round(tr["first_pass_s"], 3),
            step_impl=tr["step_impl"],
            remaining_after_first=tr["remaining_after_first"],
            rung_wall_s=round(sum(r["wall_s"] for r in tr["rungs"]), 3),
            rungs=[
                (r["survivors_in"], r["survivors_out"], r["lanes"], r["slots"])
                for r in tr["rungs"]
            ],
        )


def main() -> None:
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    emit(metric="session", device=str(jax.devices()[0].platform))

    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    geom = geometry_for_size(25)
    which = sys.argv[1] if len(sys.argv) > 1 else "both"

    if which in ("shallow", "both"):
        grids = puzzle_batch(
            geom, 64, seed=5, n_clues=int(625 * 0.60), unique=False
        ).astype(np.int32)
        run_matrix(grids, geom, {
            "composite_s64": BulkConfig(
                chunk=64, stack_slots=64, step_impl="xla"
            ),
            "composite_s24": BulkConfig(
                chunk=64, stack_slots=24, step_impl="xla"
            ),
            "fused_s24": BulkConfig(
                chunk=64, stack_slots=24, step_impl="fused"
            ),
        })

    if which in ("deep", "both"):
        grids = puzzle_batch(
            geom, 64, seed=5, n_clues=int(625 * 0.45), unique=False
        ).astype(np.int32)
        gang24 = ((64, 128, 24),)
        run_matrix(grids, geom, {
            "deep_composite": BulkConfig(chunk=64, stack_slots=64),
            "deep_fusedfirst": BulkConfig(
                chunk=64, stack_slots=24, step_impl="fused"
            ),
            "deep_gang24_xla": BulkConfig(
                chunk=64, stack_slots=64, rungs=gang24
            ),
            "deep_gang24_fused": BulkConfig(
                chunk=64, stack_slots=64, rungs=gang24,
                rung_step_impl="fused",
            ),
        }, repeat=2)


if __name__ == "__main__":
    main()
