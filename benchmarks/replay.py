"""Deterministic trace-replay capacity planner: recorded traffic through
simnet, with the brownout controller live.

Capacity planning by guesswork ("surely 3 nodes survive Black Friday")
is what ROADMAP #6 retires: ``bench_poisson --workload-out`` records real
traffic as a versioned trace (``dsst-workload/1`` — arrival offsets,
board payloads, per-job front-door tier/route/verdict/measured wall),
and this harness replays it through ``cluster/simnet.py`` against N
*virtual nodes* — queueing models of the serving node, each with its own
live :class:`serving.brownout.BrownoutController` and
:class:`obs.slo.SloMonitor` on the **virtual clock** — so "how many
nodes before brownout engages?" is answered by a deterministic, sleep-
free, socket-free experiment instead of an opinion.

**The model.**  Each virtual node owns ``slots`` concurrent device
servers (the resident flight's ``job_slots``) behind a bounded admission
queue.  A replayed job's *service time* is its recorded end-to-end wall:
under the recorded concurrency the replay therefore reproduces the live
run (the regress.py acceptance — predicted per-tier p95 inside the noise
band of the run that produced the trace), and under scaled load / fewer
nodes the simulator's queueing adds honestly on top.  The caveat is
stated out loud: recorded walls already include the *original* run's
internal queueing, so scaled-up predictions are conservative (a real
node would serve the uncontended tail slightly faster).  Front-door
tiers cost what they cost in the trace: cache/propagation answers
consume no slot (they are host-side microseconds), native-routed jobs
run on the host pool, device/direct jobs contend for slots.

**The control loop is live.**  Completions feed each node's SLO monitor
(``solve`` stream) and queue depth feeds its pressure signals, so
overload walks the node's brownout ladder exactly as in production:
stage 1 is modelled as native-only admission, stage 2 sheds the easy
tier (503), stage 3 sheds everything that would cost a dispatch (429) —
shed responses are terminal, honest, and counted per tier/stage; cache
and propagation jobs serve at every stage, and a full device queue
answers the saturation 429 exactly like ``ResidentFlight`` (the bounded
queue is real, not cosmetic).  The artifact
(``dsst-replay/1``) reports predicted per-tier/per-route p50/p95, shed
rates, stage residency, and transition counts.

**Determinism.**  The driver is single-threaded and event-driven: it
advances the virtual clock to each arrival, drains due completions in
heap order, then routes the arrival through the simnet transport (one
delivery thread runs the node handler while the driver blocks on the
reply) — there is never more than one handler in flight, virtual
timestamps are exact, and two seeded runs produce byte-identical
artifacts (pinned in tests/test_replay.py).  ``--speed N`` optionally
paces the replay at N x recorded time for live observation; the default
(0) runs flat out — virtual time is free.

Run::

    python benchmarks/bench_poisson.py --mix easy:20,hard:6,repeat:22 \
        --workload-out trace.json --out-json live.json
    python benchmarks/replay.py trace.json --nodes 1 --out-json replay.json
    python benchmarks/regress.py replay.json live.json   # predicted vs live
    python benchmarks/replay.py trace.json --nodes 3 --rate-x 10  # capacity
"""

from __future__ import annotations

import heapq
import json
import os
import sys
import threading
from typing import Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from any cwd without installing
    sys.path.insert(0, REPO)

from distributed_sudoku_solver_tpu.cluster.simnet import SimNet
from distributed_sudoku_solver_tpu.obs import slo as slo_mod
from distributed_sudoku_solver_tpu.serving import brownout

SCHEMA = "dsst-replay/1"
WORKLOAD_SCHEMA = "dsst-workload/1"

#: Routes that consume a device slot in the model (everything the live
#: system pays a dispatch for; ``direct`` is the no-frontdoor spelling).
DEVICE_ROUTES = ("device", "direct")
#: Routes answered host-side with no slot and no gate (microseconds in
#: the live system; they serve at every brownout stage).
FREE_ROUTES = ("cache", "propagation")


def _percentiles(lats_ms: list) -> dict:
    arr = np.asarray(sorted(lats_ms), float)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 1),
        "p95_ms": round(float(np.percentile(arr, 95)), 1),
        "p99_ms": round(float(np.percentile(arr, 99)), 1),
        "mean_ms": round(float(arr.mean()), 1),
        "jobs": len(lats_ms),
    }


class VirtualNode:
    """One serving node as a queueing model with a live control plane.

    All state mutates either on the driver thread (:meth:`drain_until`)
    or on the single in-flight simnet delivery thread (:meth:`_handle`)
    — never both at once, because the driver blocks on every request's
    reply before advancing (module docstring), so the model needs no
    locking and stays deterministic.
    """

    def __init__(
        self,
        net: SimNet,
        index: int,
        slots: int,
        queue_depth: int,
        bo_config: brownout.BrownoutConfig,
        slo_spec: str,
        slo_window_s: float,
    ):
        self.net = net
        self.transport = net.transport()
        self.addr = self.transport.bind("replay", 7100 + index)
        self.addr_s = f"replay:{7100 + index}"
        self.transport.serve(self._handle)
        self.slots = slots
        self.queue_depth = queue_depth
        self.mon = slo_mod.SloMonitor(
            slo_mod.parse_slo(slo_spec),
            window_s=slo_window_s,
            clock=net.now,
        )
        self.ctrl = brownout.BrownoutController(
            bo_config,
            clock=net.now,
            signals={
                "burn": self._burn_signal,
                "queue": lambda: len(self._wait_q) / float(self.queue_depth),
            },
        )
        self._busy = 0  # device slots in service
        self._wait_q: list = []  # FIFO of (arrival_t, job) awaiting a slot
        self._running: list = []  # heap of (finish_t, seq, arrival_t, job)
        self._seq = 0
        self.completed: list = []  # (job, arrival_t, wall_s)
        self.shed: list = []  # (job, stage, status, tier)

    def _burn_signal(self) -> Optional[float]:
        # The production formula, shared (serving/brownout.max_burn): the
        # replayed ladder must never drift onto a different burn signal
        # than the one the live controller acts on.
        return brownout.max_burn(self.mon)

    # -- simnet handler (the arrival path) -----------------------------------
    def _handle(self, msg: dict) -> dict:
        if msg.get("method") != "SOLVE":
            return {"error": "unknown method"}
        job = msg["job"]
        now = self.net.now()
        route = job.get("route", "direct")
        if route in FREE_ROUTES:
            # Cache hits / propagation verdicts: host-side microseconds,
            # no slot, admitted at every brownout stage.
            self._start(job, now)
            return {"accepted": True}
        # Gate tier = the probe's classification, reconstructed from the
        # trace: a generated-easy board whose device shadow won the
        # recorded race (tier='easy', route='device') is still probe-easy
        # — production sheds it at stage 2 BEFORE any racing happens.
        tier = (
            "easy" if job.get("tier") == "easy" or route == "native"
            else "hard"
        )
        action, stage = self.ctrl.gate(tier)
        if action == brownout.SHED:
            status = 503 if stage == 2 else 429
            self.ctrl.record_shed(tier, stage)
            self.shed.append((job, stage, status, tier))
            # Shed responses are observed as NON-errors and excluded from
            # latency objectives — the production contract
            # (serving/http.py _record_solve shed=True).
            self.mon.observe(0.0, error=False, stream="solve", shed=True)
            return {
                "shed": True, "status": status, "stage": stage,
                "shed_tier": tier,
            }
        # NATIVE_ONLY needs no modelling beyond admission: the recorded
        # wall of a native-routed job IS its native service time (the
        # suppressed device shadow never won in the recorded run either,
        # or the route would say 'device').
        if not self._start(job, now):
            # Bounded admission queue, exactly like ResidentFlight: a
            # full queue answers the saturation 429 instead of queueing
            # unboundedly — without this the replay "completes" jobs
            # real clients would have been refused, and overload
            # predictions diverge exactly where they matter.
            self.shed.append((job, stage, 429, "saturated"))
            self.mon.observe(0.0, error=False, stream="solve", shed=True)
            return {"shed": True, "status": 429, "shed_tier": "saturated"}
        return {"accepted": True}

    def _start(self, job: dict, now: float) -> bool:
        """Begin (or queue) service; False = the bounded device queue is
        full (the caller answers the saturation 429)."""
        service_s = (job.get("wall_ms") or 0.0) / 1e3
        if job.get("route", "direct") in DEVICE_ROUTES:
            if self._busy >= self.slots:
                if len(self._wait_q) >= self.queue_depth:
                    return False
                self._wait_q.append((now, job))
                return True
            self._busy += 1
        self._seq += 1
        heapq.heappush(self._running, (now + service_s, self._seq, now, job))
        return True

    # -- driver surface ------------------------------------------------------
    def drain_until(self, t: float) -> None:
        """Complete every job whose finish time has passed (heap order =
        deterministic), recycle freed slots into the wait queue, feed the
        SLO monitor, and let the brownout ladder re-evaluate."""
        while self._running and self._running[0][0] <= t:
            finish_t, _seq, arrival_t, job = heapq.heappop(self._running)
            wall_s = finish_t - arrival_t
            self.completed.append((job, arrival_t, wall_s))
            self.mon.observe(wall_s, error=False, stream="solve")
            if job.get("route", "direct") in DEVICE_ROUTES:
                self._busy -= 1
                if self._wait_q:
                    q_arrival, queued = self._wait_q.pop(0)
                    self._busy += 1
                    self._seq += 1
                    service_s = (queued.get("wall_ms") or 0.0) / 1e3
                    heapq.heappush(
                        self._running,
                        (finish_t + service_s, self._seq, q_arrival, queued),
                    )
        # The control loop ticks on the virtual clock (rate-limited by
        # eval_interval_s) so stages climb under backlog and walk back
        # down through the trailing quiet window.
        self.ctrl.stage()

    def busy(self) -> bool:
        return bool(self._running or self._wait_q)

    def outstanding(self) -> int:
        """In-service + queued jobs (the routing load signal)."""
        return len(self._running) + len(self._wait_q)


def load_workload(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != WORKLOAD_SCHEMA:
        raise SystemExit(
            f"replay: {path} is not a {WORKLOAD_SCHEMA} workload trace "
            f"(schema={doc.get('schema') if isinstance(doc, dict) else '?'})"
        )
    return doc


def replay(
    workload: dict,
    nodes: int = 1,
    slots: Optional[int] = None,
    queue_depth: Optional[int] = None,
    rate_x: float = 1.0,
    speed: float = 0.0,
    seed: int = 0,
    bo_config: Optional[brownout.BrownoutConfig] = None,
    slo_spec: str = "solve_p95_ms<=2000,error_rate<=0.01",
    slo_window_s: float = 30.0,
    cooldown_s: Optional[float] = None,
) -> dict:
    """Run one replay experiment; returns the ``dsst-replay/1`` artifact.

    ``rate_x`` compresses the arrival schedule (2.0 = double the load —
    the capacity-exploration knob); ``nodes``/``slots`` scale the fleet;
    ``speed`` paces wall-clock playback (0 = flat out, virtual time is
    free).  The trailing ``cooldown_s`` of virtual quiet (default: enough
    for a full ladder walk-down) lets the brownout controllers recover so
    the artifact's final stage is the steady state, not the last burst.
    """
    slots = slots if slots is not None else int(workload.get("job_slots", 8))
    queue_depth = (
        queue_depth if queue_depth is not None
        else int(workload.get("queue_depth", 64))
    )
    bo_config = bo_config or brownout.BrownoutConfig(quiet_s=5.0, hold_s=0.5)
    if cooldown_s is None:
        # Enough quiet for the whole ladder to walk down: the SLO window
        # must age out the overload observations FIRST (burn only decays
        # once they leave the window), then one full quiet window per
        # stage.
        cooldown_s = (
            slo_window_s + bo_config.quiet_s * (brownout.MAX_STAGE + 1) + 5.0
        )
    net = SimNet(seed=seed)
    vnodes = [
        VirtualNode(
            net, i, slots, queue_depth, bo_config, slo_spec, slo_window_s
        )
        for i in range(max(1, int(nodes)))
    ]
    client = net.transport()
    trace_jobs = sorted(
        workload["jobs_trace"], key=lambda j: (j["offset_ms"], j.get("tier", ""))
    )
    pacer = threading.Event()  # never set: wait() is a bounded real yield
    replies = []
    max_stage = 0
    for i, job in enumerate(trace_jobs):
        t = (job["offset_ms"] / 1e3) / max(rate_x, 1e-9)
        dt = t - net.now()
        if dt > 0:
            if speed > 0:
                pacer.wait(dt / speed)
            net.advance(dt, settle=False)
        for vn in vnodes:
            vn.drain_until(net.now())
            max_stage = max(max_stage, vn.ctrl.stage())
        # Least-outstanding routing (ClusterNode._pick_member's policy),
        # ties to the lowest index — deterministic, and immune to the
        # round-robin/tier-pattern aliasing that parks every device job
        # on one member of a small fleet.
        target = min(vnodes, key=lambda vn: (vn.outstanding(), vn.addr_s))
        replies.append(
            client.request(target.addr_s, {"method": "SOLVE", "job": job}, 60.0)
        )
    # Drain: advance until every node is idle, then the cooldown window so
    # the ladders walk back down (the acceptance soak pins ...->0).
    while any(vn.busy() for vn in vnodes):
        net.advance(0.25, settle=False)
        for vn in vnodes:
            vn.drain_until(net.now())
            max_stage = max(max_stage, vn.ctrl.stage())
    end_of_traffic = net.now()
    while net.now() < end_of_traffic + cooldown_s:
        net.advance(1.0, settle=False)
        for vn in vnodes:
            vn.drain_until(net.now())
    net.close()

    completed = [c for vn in vnodes for c in vn.completed]
    shed = [s for vn in vnodes for s in vn.shed]
    by_tier: dict = {}
    by_route: dict = {}
    for job, _arrival, wall_s in completed:
        by_tier.setdefault(job.get("tier", "hard"), []).append(wall_s * 1e3)
        by_route.setdefault(job.get("route", "direct"), []).append(wall_s * 1e3)
    shed_by_tier: dict = {}
    shed_by_status: dict = {}
    for _job, _stage, status, tier in shed:
        shed_by_tier[tier] = shed_by_tier.get(tier, 0) + 1
        shed_by_status[str(status)] = shed_by_status.get(str(status), 0) + 1
    residency = [0.0] * (brownout.MAX_STAGE + 1)
    transitions = 0
    final_stages = []
    for vn in vnodes:
        m = vn.ctrl.metrics()
        transitions += m["transitions"]
        final_stages.append(m["stage"])
        for k, r in enumerate(m["stage_residency_s"]):
            residency[k] = round(residency[k] + r, 3)
    all_walls = [wall_s * 1e3 for _j, _a, wall_s in completed]
    artifact = {
        "schema": SCHEMA,
        "params": {
            "workload": workload.get("params", {}),
            "nodes": len(vnodes),
            "slots": slots,
            "queue_depth": queue_depth,
            # The trace's recorded shape, echoed so regress.py can tell a
            # same-shape prediction (comparable to the live run) from a
            # capacity exploration (--slots/--queue-depth overridden).
            "recorded": {
                "job_slots": workload.get("job_slots"),
                "queue_depth": workload.get("queue_depth"),
            },
            "rate_x": rate_x,
            "seed": seed,
            "slo": slo_spec,
            "brownout": {
                "enter": bo_config.enter,
                "exit": bo_config.exit,
                "quiet_s": bo_config.quiet_s,
            },
        },
        "jobs": len(trace_jobs),
        "completed": len(completed),
        "shed": {
            "total": len(shed),
            "by_tier": shed_by_tier,
            "by_status": shed_by_status,
        },
        "overall": _percentiles(all_walls) if all_walls else None,
        "tiers": {t: _percentiles(v) for t, v in sorted(by_tier.items())},
        "routes": {r: _percentiles(v) for r, v in sorted(by_route.items())},
        "stage_residency_s": residency,
        "transitions": transitions,
        "max_stage": max_stage,
        "final_stages": final_stages,
        "brownout_engaged": max_stage > 0,
    }
    # Every replayed request is accounted: completed + shed == offered,
    # and the shed REPLIES the client saw agree with the nodes' internal
    # accounting (honest 429/503s, never silent drops).
    assert len(completed) + len(shed) == len(trace_jobs), (
        len(completed), len(shed), len(trace_jobs),
    )
    assert sum(1 for r in replies if r.get("shed")) == len(shed)
    return artifact


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("workload", help="dsst-workload/1 trace "
                    "(bench_poisson --workload-out)")
    ap.add_argument("--nodes", type=int, default=1,
                    help="virtual serving nodes (least-outstanding "
                    "routing, ClusterNode._pick_member's policy)")
    ap.add_argument("--slots", type=int, default=None,
                    help="device slots per node (default: the trace's "
                    "recorded resident job_slots)")
    ap.add_argument("--queue-depth", type=int, default=None,
                    help="admission queue bound per node (default: the "
                    "trace's recorded queue depth)")
    ap.add_argument("--rate-x", type=float, default=1.0,
                    help="compress the arrival schedule by this factor "
                    "(2.0 = double the offered load — the capacity knob)")
    ap.add_argument("--speed", type=float, default=0.0,
                    help="pace playback at N x recorded time for live "
                    "observation (10/100); 0 = flat out (virtual time is "
                    "free, the default)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slo", default="solve_p95_ms<=2000,error_rate<=0.01",
                    help="the virtual nodes' SLO spec (obs/slo.py grammar) "
                    "— its burn drives the replayed brownout ladder")
    ap.add_argument("--brownout-enter", type=float, default=1.0)
    ap.add_argument("--brownout-exit", type=float, default=0.5)
    ap.add_argument("--brownout-quiet", type=float, default=5.0)
    ap.add_argument("--out-json", default=None,
                    help="write the dsst-replay/1 artifact (regress.py "
                    "compares it against a live bench_poisson --out-json "
                    "artifact of the same workload)")
    args = ap.parse_args(argv)

    workload = load_workload(args.workload)
    artifact = replay(
        workload,
        nodes=args.nodes,
        slots=args.slots,
        queue_depth=args.queue_depth,
        rate_x=args.rate_x,
        speed=args.speed,
        seed=args.seed,
        bo_config=brownout.BrownoutConfig(
            enter=args.brownout_enter,
            exit=args.brownout_exit,
            quiet_s=args.brownout_quiet,
            hold_s=0.5,
        ),
        slo_spec=args.slo,
    )
    if args.out_json:
        tmp = args.out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f)
        os.replace(tmp, args.out_json)
        print(f"artifact written: {args.out_json}", file=sys.stderr)
    print(json.dumps({k: v for k, v in artifact.items() if k != "params"},
                     indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
