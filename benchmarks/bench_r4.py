"""Round-4 measurement session: the fused kernel outside its cage.

One JSON line per experiment (BENCHMARKS.md records the adopted numbers).
Run on the TPU host; every experiment follows the measurement protocol
(warm pass first, best-of-3 interleaved where A/B, value-fetch syncs).

Experiments:
  engine    — engine flights A/B: step_impl xla vs fused serving the same
              job batch through SolverEngine (VERDICT r3 #1 evidence)
  bulk      — device-corpus A/B on 65,536 DISTINCT boards: composite vs
              fused first pass (also quantifies the tiled-vs-distinct
              corpus delta, VERDICT r3 #9)
  sharded   — fused-sharded driver on a 1-chip mesh vs unsharded fused
              (the only mesh size real hardware offers; the 8-device
              correctness story lives in the CPU-mesh suite)
  count     — enumeration A/B: count_all fused vs composite on a
              multi-solution corpus + native C++ DFS count cross-check
  diag16    — 16x16 fused-loss diagnosis: per-config counters (steps,
              sweeps, overflow escalations) for fused vs composite
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def _sync(x) -> None:
    np.asarray(x)  # value fetch: the only trustworthy sync via the tunnel


def bench_engine() -> None:
    """Jobs/s through engine flights, xla vs fused, same 256-job batch."""
    import dataclasses

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    boards = puzzle_batch(SUDOKU_9, 256, seed=31, n_clues=24).astype(np.int32)
    base = SolverConfig(lanes=256, stack_slots=16, max_steps=20_000)
    results = {}
    for impl in ("xla", "fused", "xla", "fused", "xla", "fused"):
        cfg = dataclasses.replace(base, step_impl=impl)
        eng = SolverEngine(config=cfg, max_batch=256, chunk_steps=64).start()
        try:
            t0 = time.perf_counter()
            jobs = [eng.submit(b) for b in boards]
            for j in jobs:
                assert j.wait(300), "job stuck"
                assert j.solved, j.error
            dt = time.perf_counter() - t0
            results.setdefault(impl, []).append(dt)
            metrics = eng.metrics()
        finally:
            eng.stop(timeout=5)
        emit(
            metric="engine_flight_jobs_per_s",
            impl=impl,
            value=round(len(jobs) / dt, 1),
            wall_s=round(dt, 3),
            step_wall_ms_avg=metrics.get("step_wall_ms_avg"),
            chunk_wall_ms=metrics.get("chunk_wall_ms"),
        )
    best = {k: min(v) for k, v in results.items()}
    emit(
        metric="engine_flight_ab_best",
        xla_s=round(best["xla"], 3),
        fused_s=round(best["fused"], 3),
        speedup=round(best["xla"] / best["fused"], 3),
    )


def bench_bulk_ab(b: int = 65536) -> None:
    """Composite vs fused first pass on the DISTINCT corpus; also the
    distinct-vs-tiled delta for the composite config (corpus asterisk)."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    distinct = puzzle_batch(SUDOKU_9, b - len(HARD_9), seed=7, n_clues=24)
    grids = np.concatenate([np.stack(HARD_9), distinct]).astype(np.int32)
    tiled_base = puzzle_batch(SUDOKU_9, 2048 - len(HARD_9), seed=7, n_clues=24)
    tiled = np.tile(
        np.concatenate([np.stack(HARD_9), tiled_base]).astype(np.int32),
        (b // 2048, 1, 1),
    )

    runs = {}
    for name, corpus, impl in [
        ("fused_distinct", grids, "fused"),
        ("xla_distinct", grids, "xla"),
        ("fused_tiled", tiled, "fused"),
        ("xla_tiled", tiled, "xla"),
    ]:
        cfg = BulkConfig(step_impl=impl)
        solve_bulk(corpus[: min(b, 8192)], SUDOKU_9, cfg)  # warm shapes
        runs[name] = (corpus, cfg)
    # Interleaved best-of-3 (tunnel variance is ~2x run to run).
    best: dict[str, float] = {}
    solved: dict[str, int] = {}
    for _ in range(3):
        for name, (corpus, cfg) in runs.items():
            t0 = time.perf_counter()
            res = solve_bulk(corpus, SUDOKU_9, cfg)
            dt = time.perf_counter() - t0
            best[name] = min(best.get(name, float("inf")), dt)
            solved[name] = int(res.solved.sum())
    for name, dt in best.items():
        emit(
            metric="bulk_ab",
            config=name,
            value=round(b / dt, 1),
            unit="boards/s",
            solved=solved[name],
            wall_s=round(dt, 3),
        )
    emit(
        metric="corpus_delta",
        fused_distinct_over_tiled=round(
            best["fused_tiled"] / best["fused_distinct"], 4
        ),
        xla_distinct_over_tiled=round(best["xla_tiled"] / best["xla_distinct"], 4),
    )


def bench_sharded_one_chip(b: int = 32768) -> None:
    """Fused-sharded driver on a mesh of the one real chip vs unsharded."""
    import jax

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.parallel import make_mesh
    from distributed_sudoku_solver_tpu.parallel.fused_sharded import (
        solve_batch_fused_sharded,
    )
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    grids = puzzle_batch(SUDOKU_9, 2048, seed=7, n_clues=24).astype(np.int32)
    grids = np.tile(grids, (b // 2048, 1, 1))
    cfg = SolverConfig(
        lanes=b, stack_slots=12, max_steps=4096, step_impl="fused"
    )
    mesh = make_mesh(jax.devices()[:1])
    _sync(solve_batch_fused_sharded(grids, SUDOKU_9, cfg, mesh=mesh).solved)
    _sync(solve_batch(grids, SUDOKU_9, cfg).solved)
    best = {"sharded1": float("inf"), "unsharded": float("inf")}
    for _ in range(3):
        t0 = time.perf_counter()
        r1 = solve_batch_fused_sharded(grids, SUDOKU_9, cfg, mesh=mesh)
        _sync(r1.solved)
        best["sharded1"] = min(best["sharded1"], time.perf_counter() - t0)
        t0 = time.perf_counter()
        r0 = solve_batch(grids, SUDOKU_9, cfg)
        _sync(r0.solved)
        best["unsharded"] = min(best["unsharded"], time.perf_counter() - t0)
    emit(
        metric="fused_sharded_one_chip",
        sharded_boards_per_s=round(b / best["sharded1"], 1),
        unsharded_boards_per_s=round(b / best["unsharded"], 1),
        overhead=round(best["sharded1"] / best["unsharded"], 4),
        solved=int(np.asarray(r1.solved).sum()),
    )


def bench_count_all(n_boards: int = 512) -> None:
    """Enumeration throughput: fused vs composite, counts cross-checked
    (against each other and the native C++ DFS on a sample)."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    # Unique-solution boards with 3 clues removed -> modest multi-solution
    # instances (removing more explodes counts into the millions: a board
    # with 3 blanked ROWS could not even be counted by the native DFS in
    # 120 s — measured while sizing the test corpus).
    base = puzzle_batch(SUDOKU_9, n_boards, seed=57, n_clues=26)
    rng = np.random.default_rng(3)
    boards = base.copy()
    for i in range(n_boards):
        idx = np.flatnonzero(boards[i].ravel())
        kill = rng.choice(idx, size=min(3, len(idx)), replace=False)
        boards[i].ravel()[kill] = 0
    boards = boards.astype(np.int32)

    # S=16 comfortably fits the 128-lane fused tile at 9x9 (the measured
    # ceiling is S=24, ops/pallas_step._vmem_budget) and is deep enough
    # for these shallow enumerations; same depth for the composite so the
    # A/B isolates the step impl, not the stack.
    cfgs = {
        "fused": SolverConfig(
            lanes=max(512, n_boards), stack_slots=16, max_steps=200_000,
            count_all=True, step_impl="fused",
        ),
        "xla": SolverConfig(
            lanes=max(512, n_boards), stack_slots=16, max_steps=200_000,
            count_all=True,
        ),
    }
    res = {}
    for name, cfg in cfgs.items():
        r = solve_batch(boards, SUDOKU_9, cfg)
        _sync(r.sol_count)
        res[name] = r
    best = {k: float("inf") for k in cfgs}
    for _ in range(3):
        for name, cfg in cfgs.items():
            t0 = time.perf_counter()
            r = solve_batch(boards, SUDOKU_9, cfg)
            _sync(r.sol_count)
            best[name] = min(best[name], time.perf_counter() - t0)
    cf = np.asarray(res["fused"].sol_count)
    cx = np.asarray(res["xla"].sol_count)
    agree = bool((cf == cx).all())
    native_ok = None
    if native.available():
        sample = np.random.default_rng(5).choice(n_boards, 16, replace=False)
        native_ok = all(
            native.count_solutions(boards[i], SUDOKU_9, limit=1_000_000)
            == int(cf[i])
            for i in sample
        )
    emit(
        metric="count_all_ab",
        boards=n_boards,
        total_solutions=int(cf.sum()),
        counts_agree=agree,
        native_sample_agrees=native_ok,
        fused_s=round(best["fused"], 3),
        xla_s=round(best["xla"], 3),
        speedup=round(best["xla"] / best["fused"], 3),
        complete_fused=int(np.asarray(res["fused"].unsat).sum()),
        complete_xla=int(np.asarray(res["xla"].unsat).sum()),
    )


def bench_diag16(b: int = 2048) -> None:
    """Why does 16x16 fused lose?  Counters per impl at S=12 and S=24."""
    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    g16 = geometry_for_size(16)
    boards = puzzle_batch(
        g16, 512, seed=5, n_clues=102, unique=False
    ).astype(np.int32)
    boards = np.tile(boards, (b // 512, 1, 1))
    # S=12 is the deepest 16x16 stack the 128-lane fused tile affords
    # (measured VMEM boundary); the composite also gets an S=32 row to
    # show what depth buys it.
    for slots, impls in ((12, ("fused", "xla")), (32, ("xla",))):
        for impl in impls:
            cfg = SolverConfig(
                lanes=b, stack_slots=slots, max_steps=4096, step_impl=impl
            )
            r = solve_batch(boards, g16, cfg)
            _sync(r.solved)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                r = solve_batch(boards, g16, cfg)
                _sync(r.solved)
                best = min(best, time.perf_counter() - t0)
            emit(
                metric="diag16",
                impl=impl,
                stack_slots=slots,
                boards_per_s=round(b / best, 1),
                solved=int(np.asarray(r.solved).sum()),
                overflowed=int(np.asarray(r.overflowed).sum()),
                steps=int(np.asarray(r.steps)),
                sweeps=int(np.asarray(r.sweeps)),
                expansions=int(np.asarray(r.expansions)),
                steals=int(np.asarray(r.steals)),
                wall_s=round(best, 3),
            )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "experiments",
        nargs="*",
        default=["engine", "bulk", "sharded", "count", "diag16"],
    )
    args = ap.parse_args()
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    emit(metric="session", device=str(jax.devices()[0].platform))

    for exp in args.experiments:
        {
            "engine": bench_engine,
            "bulk": bench_bulk_ab,
            "sharded": bench_sharded_one_chip,
            "count": bench_count_all,
            "diag16": bench_diag16,
        }[exp]()


if __name__ == "__main__":
    main()
