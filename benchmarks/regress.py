"""Benchmark regression gate: compare two bench_poisson artifacts.

The first hook of the bench trajectory::

    python benchmarks/bench_poisson.py --jobs 48 --out-json base.json
    # ... change the code ...
    python benchmarks/bench_poisson.py --jobs 48 --out-json new.json
    python benchmarks/regress.py base.json new.json [--tol 0.25]

Exit codes: **0** no regression, **1** regression (some p50/p95 degraded
past the noise tolerance), **2** the artifacts are not comparable
(schema/params mismatch, unreadable files).

The comparison is deliberately coarse: per engine (static / resident),
``p50_ms`` and ``p95_ms`` must satisfy ``new <= old * (1 + tol)``.  The
default tolerance (25%) reflects the CPU container's measured run-to-run
variance (BENCHMARKS.md round-8 3-run note); tighten it on quiet
hardware.  The rpc_floor estimate is *reported*, not gated — the floor
is a property of the link, and a changed floor means the environments
differ, which the report should say out loud rather than fail on.
Likewise the artifact's ``compile`` section (bench_poisson's
obs/compilewatch accounting): a cold-cache side is *labeled* — its
quantiles include compile noise, and a cold-vs-warm compare earns an
explicit "re-run warm" note instead of hiding inside the band.

Mixed-corpus artifacts (``bench_poisson --mix``, round 17) are only
comparable to artifacts with the *identical* mix: the overall quantiles
blend cache/native/device routes in mix-specific proportions, so a
cross-mix compare is a different workload (**exit 2**), not a
regression.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Union

SCHEMA = "dsst-bench-poisson/1"
SIDES = ("static", "resident")
QUANTS = ("p50_ms", "p95_ms")


def _load(path: str):
    try:
        with open(path) as f:
            return json.load(f), None
    except (OSError, json.JSONDecodeError) as e:
        return None, f"{path}: unreadable or not JSON: {e}"


def compare(old: dict, new: dict, tol: float = 0.25) -> dict:
    """-> {"comparable": bool, "errors": [...], "regressions": [...],
    "improvements": [...], "notes": [...]}.  ``regressions`` non-empty is
    the gate failure."""
    errors: List[str] = []
    for name, doc in (("old", old), ("new", new)):
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            errors.append(
                f"{name} artifact has schema "
                f"{doc.get('schema') if isinstance(doc, dict) else doc!r}, "
                f"expected {SCHEMA}"
            )
    if not errors and old.get("params") != new.get("params"):
        om = (old.get("params") or {}).get("mix")
        nm = (new.get("params") or {}).get("mix")
        if om != nm:
            # A mixed-difficulty corpus (bench_poisson --mix) measures a
            # DIFFERENT workload: its quantiles blend cache/native/device
            # routes in mix-specific proportions, so a cross-mix compare
            # is apples-to-oranges — refuse (exit 2), never call it a
            # regression.  Pre-round-17 artifacts carry no mix key and
            # compare as the all-hard corpus (mix=None).
            errors.append(
                f"artifacts measured different corpus mixes: {om!r} vs "
                f"{nm!r} — a --mix artifact is only comparable to an "
                "artifact with the identical mix"
            )
        else:
            errors.append(
                "artifacts measured different workloads: "
                f"params {old.get('params')} vs {new.get('params')} — "
                "re-run both sides with identical flags"
            )
    if errors:
        return {
            "comparable": False,
            "errors": errors,
            "regressions": [],
            "improvements": [],
            "notes": [],
        }
    regressions: List[str] = []
    improvements: List[str] = []
    notes: List[str] = []
    # Cold-cache labeling (bench_poisson's `compile` section): a run that
    # paid XLA compiles inside its measured window carries compile noise
    # in its quantiles — say so out loud instead of silently comparing it
    # inside the tolerance band.  Older artifacts without the section
    # stay label-free (comparability is unchanged).
    cold = {}
    for label, doc in (("old", old), ("new", new)):
        sec = doc.get("compile")
        if isinstance(sec, dict) and sec.get("cold"):
            cold[label] = sec
            notes.append(
                f"{label} artifact is a COLD-CACHE run "
                f"({sec.get('compiles_total', '?')} compiles, "
                f"{sec.get('wall_ms_total', 0):.0f} ms compile wall inside "
                "the measured window) — its quantiles include compile noise"
            )
    if set(cold) == {"new"}:
        notes.append(
            "cold new vs warm old: an apparent regression may be compile "
            "noise — re-run the candidate warm before trusting the gate"
        )
    elif set(cold) == {"old"}:
        notes.append(
            "warm new vs cold old: an apparent improvement may be the "
            "cache warming, not the code — re-run the baseline warm"
        )
    for side in SIDES:
        for q in QUANTS:
            o = float(old[side][q])
            n = float(new[side][q])
            limit = o * (1.0 + tol)
            if n > limit:
                regressions.append(
                    f"{side} {q}: {o:.1f} -> {n:.1f} ms "
                    f"(+{(n / o - 1) * 100:.0f}%, tolerance {tol * 100:.0f}%)"
                )
            elif n < o * (1.0 - tol):
                improvements.append(
                    f"{side} {q}: {o:.1f} -> {n:.1f} ms "
                    f"({(n / o - 1) * 100:.0f}%)"
                )
    of, nf = old.get("rpc_floor_ms"), new.get("rpc_floor_ms")
    if isinstance(of, dict) and isinstance(nf, dict):
        o_min, n_min = float(of.get("min", 0)), float(nf.get("min", 0))
        if o_min > 0 and abs(n_min - o_min) > tol * o_min:
            notes.append(
                f"rpc_floor_ms moved {o_min:.2f} -> {n_min:.2f}: the "
                "environments' sync floors differ — latency deltas may "
                "be the link, not the code"
            )
    return {
        "comparable": True,
        "errors": [],
        "regressions": regressions,
        "improvements": improvements,
        "notes": notes,
    }


def main(argv: Union[List[str], None] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline artifact (bench_poisson --out-json)")
    ap.add_argument("new", help="candidate artifact")
    ap.add_argument(
        "--tol",
        type=float,
        default=0.25,
        help="noise tolerance as a fraction (default 0.25 = 25%%)",
    )
    args = ap.parse_args(argv)
    old, err_o = _load(args.old)
    new, err_n = _load(args.new)
    for err in (err_o, err_n):
        if err:
            print(f"regress: {err}", file=sys.stderr)
    if err_o or err_n:
        return 2
    rep = compare(old, new, tol=args.tol)
    if not rep["comparable"]:
        for e in rep["errors"]:
            print(f"regress: {e}", file=sys.stderr)
        return 2
    for line in rep["notes"]:
        print(f"regress: note: {line}")
    for line in rep["improvements"]:
        print(f"regress: improved: {line}")
    if rep["regressions"]:
        for line in rep["regressions"]:
            print(f"regress: REGRESSION: {line}", file=sys.stderr)
        return 1
    print(
        f"regress: OK — no regression beyond {args.tol * 100:.0f}% "
        f"({', '.join(f'{s} {q}' for s in SIDES for q in QUANTS)})"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
