"""Benchmark regression gate: compare two bench_poisson artifacts.

The first hook of the bench trajectory::

    python benchmarks/bench_poisson.py --jobs 48 --out-json base.json
    # ... change the code ...
    python benchmarks/bench_poisson.py --jobs 48 --out-json new.json
    python benchmarks/regress.py base.json new.json [--tol 0.25]

Exit codes: **0** no regression, **1** regression (some p50/p95 degraded
past the noise tolerance), **2** the artifacts are not comparable
(schema/params mismatch, unreadable files).

The comparison is deliberately coarse: per engine (static / resident),
``p50_ms`` and ``p95_ms`` must satisfy ``new <= old * (1 + tol)``.  The
default tolerance (25%) reflects the CPU container's measured run-to-run
variance (BENCHMARKS.md round-8 3-run note); tighten it on quiet
hardware.  The rpc_floor estimate is *reported*, not gated — the floor
is a property of the link, and a changed floor means the environments
differ, which the report should say out loud rather than fail on.
Likewise the artifact's ``compile`` section (bench_poisson's
obs/compilewatch accounting): a cold-cache side is *labeled* — its
quantiles include compile noise, and a cold-vs-warm compare earns an
explicit "re-run warm" note instead of hiding inside the band.

Latency-mode artifacts (``bench_poisson --latency-mode``, round 19)
carry an additional ``megastep`` section with the same quantile shape
as static/resident; the gate includes it whenever BOTH artifacts carry
it.  The section is additive — params are unchanged, so a latency-mode
artifact still compares against a pre-round-19 artifact on the
static/resident sides (with a note that the new tier went ungated).

Ring artifacts (``bench_poisson --ring N``, round 20) carry a ``ring``
section: the gossip ring's cluster-cache hit rate vs the best per-node
L1 rate.  Gated like megastep — both sides must carry it, with equal
node counts (a different ring size is a different deployment, noted not
gated).  Two checks: the cluster hit rate must not drop past the
tolerance, and it must still strictly exceed the best per-node rate
(the DHT's reason to exist).

Mesh artifacts (``bench_poisson --mesh-devices N``, round 21) carry a
``mesh`` section: the pod-scale resident tier's latency quantiles plus
aggregate ``boards_per_s``.  Gated like megastep when both sides carry
it — quantiles upward, throughput downward — but a *different device
count* is a different machine shape (the section's slot pool and
throughput scale with it), so mismatched counts refuse the compare
(**exit 2**) rather than noting and skipping.

Mixed-corpus artifacts (``bench_poisson --mix``, round 17) are only
comparable to artifacts with the *identical* mix: the overall quantiles
blend cache/native/device routes in mix-specific proportions, so a
cross-mix compare is a different workload (**exit 2**), not a
regression.

Search-effort totals (``bench_poisson`` round 22, branch-ordering
heads) ride each side as an additive ``search`` section: ``searched``
(jobs that branched at all) and ``nodes`` (total expansions), per tier
on mixed runs.  The HARD tier's searched count is gated upward like a
quantile whenever both artifacts carry the keys — a branch-rule change
that grows the hard tail's tree fails the gate even if wall-clock hides
it.  Node totals are noted, never gated (resident stealing makes them
timing-dependent).

**Replay-vs-live** (round 18): when one artifact is a ``dsst-replay/1``
prediction (``benchmarks/replay.py``) and the other a live
``dsst-bench-poisson/1`` run, the gate compares the replay's predicted
per-tier p95 (overall resident p95 for all-hard traces) against the
live resident numbers inside the same ``--tol`` band — **two-sided**,
because a prediction can be wrong in either direction.  Comparability
requires the replay's embedded workload params to match the live
artifact's params exactly (same jobs/gaps/handicap/seed/mix — the same
contract as the cross-mix rule: a different workload is **exit 2** with
an explicit message, never a "regression").  Replay scaling knobs
(``--nodes``/``--rate-x`` != the recorded shape) also make the
prediction non-comparable to the recorded run: it predicts a different
deployment on purpose.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Union

SCHEMA = "dsst-bench-poisson/1"
REPLAY_SCHEMA = "dsst-replay/1"
SIDES = ("static", "resident")
QUANTS = ("p50_ms", "p95_ms")

#: The workload-identity keys a replay prediction must share with a live
#: artifact to be comparable (mix is normalized before comparing: the
#: trace stores the canonical spelling, the live artifact the raw flag).
WORKLOAD_KEYS = ("jobs", "mean_gap_ms", "handicap_ms", "chunk_steps", "seed")


def _norm_mix(mix) -> Optional[str]:
    """Canonicalize a --mix spelling ('hard:6,easy:20' == 'easy:20,hard:6'
    == same workload); None/absent = the all-hard corpus."""
    if not mix:
        return None
    counts = {"easy": 0, "hard": 0, "repeat": 0}
    try:
        for part in str(mix).split(","):
            tier, n = part.split(":")
            counts[tier.strip()] = int(n)
    except (ValueError, KeyError):
        return str(mix)  # unparseable: compare verbatim
    return f"easy:{counts['easy']},hard:{counts['hard']},repeat:{counts['repeat']}"


def _load(path: str):
    try:
        with open(path) as f:
            return json.load(f), None
    except (OSError, json.JSONDecodeError) as e:
        return None, f"{path}: unreadable or not JSON: {e}"


def compare(old: dict, new: dict, tol: float = 0.25) -> dict:
    """-> {"comparable": bool, "errors": [...], "regressions": [...],
    "improvements": [...], "notes": [...]}.  ``regressions`` non-empty is
    the gate failure."""
    errors: List[str] = []
    for name, doc in (("old", old), ("new", new)):
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            errors.append(
                f"{name} artifact has schema "
                f"{doc.get('schema') if isinstance(doc, dict) else doc!r}, "
                f"expected {SCHEMA}"
            )
    if not errors:
        # Kill/restart durability runs (bench_poisson --kill-at, ISSUE
        # 20): the stream was truncated at the kill, so the artifact's
        # quantiles measured an interrupted workload — never gate one,
        # in either position, even against another kill-run with
        # identical params.  Refuse explicitly (exit 2), like the
        # cross-mix and mesh-shape rules: different measurement, not a
        # regression.
        killed = [
            label
            for label, doc in (("old", old), ("new", new))
            if "kill_at_s" in (doc.get("params") or {})
        ]
        if killed:
            errors.append(
                f"{' and '.join(killed)} artifact(s) came from a "
                "kill/restart durability run (bench_poisson --kill-at): "
                "the stream was truncated mid-run, so the quantiles are "
                "not comparable workload measurements — use the "
                "'recovery' section for the durability table and re-run "
                "without --kill-at for the bench-trajectory gate"
            )
    if not errors and old.get("params") != new.get("params"):
        om = (old.get("params") or {}).get("mix")
        nm = (new.get("params") or {}).get("mix")
        if om != nm:
            # A mixed-difficulty corpus (bench_poisson --mix) measures a
            # DIFFERENT workload: its quantiles blend cache/native/device
            # routes in mix-specific proportions, so a cross-mix compare
            # is apples-to-oranges — refuse (exit 2), never call it a
            # regression.  Pre-round-17 artifacts carry no mix key and
            # compare as the all-hard corpus (mix=None).
            errors.append(
                f"artifacts measured different corpus mixes: {om!r} vs "
                f"{nm!r} — a --mix artifact is only comparable to an "
                "artifact with the identical mix"
            )
        else:
            errors.append(
                "artifacts measured different workloads: "
                f"params {old.get('params')} vs {new.get('params')} — "
                "re-run both sides with identical flags"
            )
    if errors:
        return {
            "comparable": False,
            "errors": errors,
            "regressions": [],
            "improvements": [],
            "notes": [],
        }
    regressions: List[str] = []
    improvements: List[str] = []
    notes: List[str] = []
    # Cold-cache labeling (bench_poisson's `compile` section): a run that
    # paid XLA compiles inside its measured window carries compile noise
    # in its quantiles — say so out loud instead of silently comparing it
    # inside the tolerance band.  Older artifacts without the section
    # stay label-free (comparability is unchanged).
    cold = {}
    for label, doc in (("old", old), ("new", new)):
        sec = doc.get("compile")
        if isinstance(sec, dict) and sec.get("cold"):
            cold[label] = sec
            notes.append(
                f"{label} artifact is a COLD-CACHE run "
                f"({sec.get('compiles_total', '?')} compiles, "
                f"{sec.get('wall_ms_total', 0):.0f} ms compile wall inside "
                "the measured window) — its quantiles include compile noise"
            )
    if set(cold) == {"new"}:
        notes.append(
            "cold new vs warm old: an apparent regression may be compile "
            "noise — re-run the candidate warm before trusting the gate"
        )
    elif set(cold) == {"old"}:
        notes.append(
            "warm new vs cold old: an apparent improvement may be the "
            "cache warming, not the code — re-run the baseline warm"
        )
    # The latency-mode tier (bench_poisson --latency-mode): gated only
    # when both artifacts measured it — a one-sided megastep section is
    # a flag difference, not a workload difference (params are equal or
    # we'd have exited 2 above), so note it instead of failing.
    sides: List[str] = list(SIDES)
    has_mega = {
        label: isinstance(doc.get("megastep"), dict)
        for label, doc in (("old", old), ("new", new))
    }
    if all(has_mega.values()):
        sides.append("megastep")
    elif any(has_mega.values()):
        only = "old" if has_mega["old"] else "new"
        notes.append(
            f"only the {only} artifact carries the megastep "
            "(latency-mode) tier — that tier is NOT gated; run both "
            "sides with --latency-mode to gate it"
        )
    # The DHT tier (bench_poisson --ring, round 20): the ring's
    # cluster-cache hit rate is gated DOWNWARD (a rate drop past the
    # tolerance is a regression — the cache stopped sharing), and the
    # cluster-vs-best-node gap must stay positive: a ring whose DHT no
    # longer beats its luckiest single member has lost the subsystem's
    # whole point.  Different node counts measure a different deployment
    # — noted, not gated (same doctrine as replay scaling knobs).
    has_ring = {
        label: isinstance(doc.get("ring"), dict)
        for label, doc in (("old", old), ("new", new))
    }
    if all(has_ring.values()):
        o_ring, n_ring = old["ring"], new["ring"]
        if o_ring.get("nodes") != n_ring.get("nodes"):
            notes.append(
                f"ring node counts differ ({o_ring.get('nodes')} vs "
                f"{n_ring.get('nodes')}) — a different deployment shape; "
                "the ring tier is NOT gated"
            )
        else:
            o_hit = float(o_ring.get("cluster_hit_rate", 0.0))
            n_hit = float(n_ring.get("cluster_hit_rate", 0.0))
            if o_hit > 0 and n_hit < o_hit * (1.0 - tol):
                regressions.append(
                    f"ring cluster_hit_rate: {o_hit:.3f} -> {n_hit:.3f} "
                    f"({(n_hit / o_hit - 1) * 100:.0f}%, tolerance "
                    f"{tol * 100:.0f}%)"
                )
            elif o_hit > 0 and n_hit > o_hit * (1.0 + tol):
                improvements.append(
                    f"ring cluster_hit_rate: {o_hit:.3f} -> {n_hit:.3f}"
                )
            n_best = float(n_ring.get("best_node_hit_rate", 0.0))
            if n_hit <= n_best:
                regressions.append(
                    f"ring cluster_hit_rate ({n_hit:.3f}) no longer "
                    f"exceeds the best per-node rate ({n_best:.3f}) — "
                    "the DHT stopped sharing fills"
                )
    elif any(has_ring.values()):
        only = "old" if has_ring["old"] else "new"
        notes.append(
            f"only the {only} artifact carries the ring (DHT) tier — "
            "that tier is NOT gated; run both sides with --ring to gate it"
        )
    # The pod-scale tier (bench_poisson --mesh-devices, round 21): gated
    # when both artifacts carry it — the latency quantiles ride the same
    # loop as static/resident (the section carries p50_ms/p95_ms), and
    # aggregate boards_per_s is gated DOWNWARD (a throughput drop past
    # the tolerance is the regression this tier exists to catch).  A
    # DIFFERENT device count is a different machine shape, not a code
    # delta: unlike the ring's noted-only node mismatch, the mesh
    # section's whole claim (slot pool, boards/s) scales with the device
    # count, so the compare is refused outright (exit 2).
    has_mesh = {
        label: isinstance(doc.get("mesh"), dict)
        for label, doc in (("old", old), ("new", new))
    }
    if all(has_mesh.values()):
        o_mesh, n_mesh = old["mesh"], new["mesh"]
        if o_mesh.get("devices") != n_mesh.get("devices"):
            return {
                "comparable": False,
                "errors": [
                    f"mesh device counts differ ({o_mesh.get('devices')} vs "
                    f"{n_mesh.get('devices')}) — a mesh artifact is only "
                    "comparable to an artifact measured on the same mesh "
                    "shape; re-run both sides with the same --mesh-devices"
                ],
                "regressions": [],
                "improvements": [],
                "notes": [],
            }
        sides.append("mesh")
        o_tp = float(o_mesh.get("boards_per_s", 0.0))
        n_tp = float(n_mesh.get("boards_per_s", 0.0))
        if o_tp > 0 and n_tp < o_tp * (1.0 - tol):
            regressions.append(
                f"mesh boards_per_s: {o_tp:.2f} -> {n_tp:.2f} "
                f"({(n_tp / o_tp - 1) * 100:.0f}%, tolerance "
                f"{tol * 100:.0f}%)"
            )
        elif o_tp > 0 and n_tp > o_tp * (1.0 + tol):
            improvements.append(
                f"mesh boards_per_s: {o_tp:.2f} -> {n_tp:.2f}"
            )
    elif any(has_mesh.values()):
        only = "old" if has_mesh["old"] else "new"
        notes.append(
            f"only the {only} artifact carries the mesh (pod-scale) tier "
            "— that tier is NOT gated; run both sides with --mesh-devices "
            "to gate it"
        )
    for side in sides:
        for q in QUANTS:
            o = float(old[side][q])
            n = float(new[side][q])
            limit = o * (1.0 + tol)
            if n > limit:
                regressions.append(
                    f"{side} {q}: {o:.1f} -> {n:.1f} ms "
                    f"(+{(n / o - 1) * 100:.0f}%, tolerance {tol * 100:.0f}%)"
                )
            elif n < o * (1.0 - tol):
                improvements.append(
                    f"{side} {q}: {o:.1f} -> {n:.1f} ms "
                    f"({(n / o - 1) * 100:.0f}%)"
                )
    # Search-effort gate (round 22, branch-ordering heads): the additive
    # per-side `search` section carries searched (jobs that branched at
    # all) and nodes (total expansions).  `searched` on the HARD tier is
    # gated UPWARD — a branch-rule change that grows the hard tail's
    # search tree is a regression even when wall-clock hides inside the
    # latency band.  Mixed runs gate the hard tier specifically; the
    # default all-hard corpus gates the side's overall totals.  Node
    # totals are noted, not gated: resident scheduling expands a
    # timing-dependent number of speculative nodes per run, so only the
    # coarser searched count is stable enough to fail a build on.
    for side in sides:
        o_sec = (old.get(side) or {}).get("search")
        n_sec = (new.get(side) or {}).get("search")
        if not isinstance(o_sec, dict) or not isinstance(n_sec, dict):
            if isinstance(o_sec, dict) != isinstance(n_sec, dict):
                only = "old" if isinstance(o_sec, dict) else "new"
                notes.append(
                    f"only the {only} artifact carries {side} search "
                    "totals — searched-count is NOT gated for that side"
                )
            continue
        o_hard = (o_sec.get("tiers") or {}).get("hard", o_sec)
        n_hard = (n_sec.get("tiers") or {}).get("hard", n_sec)
        o_s, n_s = int(o_hard.get("searched", 0)), int(n_hard.get("searched", 0))
        if o_s > 0 and n_s > o_s * (1.0 + tol):
            regressions.append(
                f"{side} hard-tier searched: {o_s} -> {n_s} "
                f"(+{(n_s / o_s - 1) * 100:.0f}%, tolerance {tol * 100:.0f}%)"
            )
        elif o_s > 0 and n_s < o_s * (1.0 - tol):
            improvements.append(
                f"{side} hard-tier searched: {o_s} -> {n_s} "
                f"({(n_s / o_s - 1) * 100:.0f}%)"
            )
        o_n, n_n = int(o_hard.get("nodes", 0)), int(n_hard.get("nodes", 0))
        if o_n > 0 and abs(n_n - o_n) > tol * o_n:
            notes.append(
                f"{side} hard-tier nodes moved {o_n} -> {n_n} "
                f"({(n_n / o_n - 1) * 100:+.0f}%) — node totals are "
                "timing-dependent under resident stealing, so this is "
                "informational, not gated"
            )
    of, nf = old.get("rpc_floor_ms"), new.get("rpc_floor_ms")
    if isinstance(of, dict) and isinstance(nf, dict):
        o_min, n_min = float(of.get("min", 0)), float(nf.get("min", 0))
        if o_min > 0 and abs(n_min - o_min) > tol * o_min:
            notes.append(
                f"rpc_floor_ms moved {o_min:.2f} -> {n_min:.2f}: the "
                "environments' sync floors differ — latency deltas may "
                "be the link, not the code"
            )
    return {
        "comparable": True,
        "errors": [],
        "regressions": regressions,
        "improvements": improvements,
        "notes": notes,
    }


def compare_replay(replay: dict, live: dict, tol: float = 0.25) -> dict:
    """Replay prediction vs live run: same report shape as :func:`compare`
    (``regressions`` here means *mispredictions* — the replay's number
    landed outside the two-sided tolerance band around the live one)."""
    errors: List[str] = []
    if not isinstance(replay, dict) or replay.get("schema") != REPLAY_SCHEMA:
        errors.append(
            f"replay artifact has schema "
            f"{replay.get('schema') if isinstance(replay, dict) else replay!r}, "
            f"expected {REPLAY_SCHEMA}"
        )
    if not isinstance(live, dict) or live.get("schema") != SCHEMA:
        errors.append(
            f"live artifact has schema "
            f"{live.get('schema') if isinstance(live, dict) else live!r}, "
            f"expected {SCHEMA}"
        )
    if not errors:
        rp = replay.get("params", {}) or {}
        wl = rp.get("workload", {}) or {}
        lp = live.get("params", {}) or {}
        for k in WORKLOAD_KEYS:
            if wl.get(k) != lp.get(k):
                errors.append(
                    f"replay workload {k}={wl.get(k)!r} != live {k}="
                    f"{lp.get(k)!r} — the replay predicts a DIFFERENT "
                    "workload than the live run measured; re-record the "
                    "trace from a run with identical flags"
                )
        if _norm_mix(wl.get("mix")) != _norm_mix(lp.get("mix")):
            errors.append(
                f"replay workload mix {wl.get('mix')!r} != live mix "
                f"{lp.get('mix')!r} — a replay is only comparable to the "
                "live run whose traffic it replays"
            )
        # Scaling knobs: a fleet-shape exploration predicts a different
        # deployment on purpose — honest exit 2, never a "regression".
        if rp.get("rate_x", 1.0) != 1.0:
            errors.append(
                f"replay ran at rate_x={rp.get('rate_x')} (scaled load): "
                "comparable only at the recorded rate (rate_x=1)"
            )
        if rp.get("nodes", 1) != 1:
            errors.append(
                f"replay ran {rp.get('nodes')} virtual nodes: the recorded "
                "run was one node — scale-out predictions are capacity "
                "exploration, not a live comparison"
            )
        recorded = rp.get("recorded") or {}
        for knob, rec_key in (("slots", "job_slots"),
                              ("queue_depth", "queue_depth")):
            rec_v = recorded.get(rec_key)
            if rec_v is not None and rp.get(knob) != rec_v:
                errors.append(
                    f"replay ran {knob}={rp.get(knob)} but the trace "
                    f"recorded {rec_key}={rec_v}: a reshaped node is "
                    "capacity exploration, not a live comparison"
                )
    if errors:
        return {
            "comparable": False, "errors": errors, "regressions": [],
            "improvements": [], "notes": [],
        }
    mispredictions: List[str] = []
    notes: List[str] = []
    live_res = live.get("resident", {}) or {}
    live_tiers = live_res.get("tiers") or {}
    pred_tiers = replay.get("tiers") or {}
    pairs = []
    if live_tiers:
        for tier in sorted(live_tiers):
            if tier in pred_tiers:
                pairs.append(
                    (f"tier {tier} p95", float(pred_tiers[tier]["p95_ms"]),
                     float(live_tiers[tier]["p95_ms"]))
                )
            else:
                notes.append(
                    f"live tier {tier!r} absent from the replay prediction "
                    "(all its jobs were shed?) — not compared"
                )
    elif replay.get("overall"):
        pairs.append(
            ("overall p95", float(replay["overall"]["p95_ms"]),
             float(live_res.get("p95_ms", 0.0)))
        )
    shed_total = (replay.get("shed") or {}).get("total", 0)
    if shed_total:
        notes.append(
            f"replay shed {shed_total} job(s): the recorded run shed none, "
            "so predicted quantiles cover a smaller completed set"
        )
    if not pairs:
        # A gate that compared NOTHING must not print OK: a replay that
        # shed every job (overall=None) or a live artifact with no
        # comparable quantiles is a failed comparison, not a pass.
        return {
            "comparable": False,
            "errors": [
                "no comparable quantiles between the replay prediction and "
                "the live artifact (did the replay shed every job?)"
            ],
            "regressions": [], "improvements": [], "notes": notes,
        }
    for label, pred, actual in pairs:
        if actual <= 0:
            continue
        lo, hi = actual * (1.0 - tol), actual * (1.0 + tol)
        if not (lo <= pred <= hi):
            mispredictions.append(
                f"{label}: replay predicted {pred:.1f} ms vs live "
                f"{actual:.1f} ms ({(pred / actual - 1) * 100:+.0f}%, "
                f"tolerance ±{tol * 100:.0f}%)"
            )
    return {
        "comparable": True, "errors": [], "regressions": mispredictions,
        "improvements": [], "notes": notes,
    }


def main(argv: Union[List[str], None] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline artifact (bench_poisson --out-json)")
    ap.add_argument("new", help="candidate artifact")
    ap.add_argument(
        "--tol",
        type=float,
        default=0.25,
        help="noise tolerance as a fraction (default 0.25 = 25%%)",
    )
    args = ap.parse_args(argv)
    old, err_o = _load(args.old)
    new, err_n = _load(args.new)
    for err in (err_o, err_n):
        if err:
            print(f"regress: {err}", file=sys.stderr)
    if err_o or err_n:
        return 2
    schemas = tuple(
        d.get("schema") if isinstance(d, dict) else None for d in (old, new)
    )
    replay_mode = REPLAY_SCHEMA in schemas
    if replay_mode:
        if schemas.count(REPLAY_SCHEMA) == 2:
            print(
                "regress: both artifacts are dsst-replay/1 predictions — "
                "compare a prediction against a LIVE bench_poisson "
                "--out-json artifact",
                file=sys.stderr,
            )
            return 2
        # Order-insensitive: whichever side is the replay is the
        # prediction; the live run is the ground truth.
        replay_doc, live_doc = (
            (old, new) if schemas[0] == REPLAY_SCHEMA else (new, old)
        )
        rep = compare_replay(replay_doc, live_doc, tol=args.tol)
    else:
        rep = compare(old, new, tol=args.tol)
    if not rep["comparable"]:
        for e in rep["errors"]:
            print(f"regress: {e}", file=sys.stderr)
        return 2
    for line in rep["notes"]:
        print(f"regress: note: {line}")
    for line in rep["improvements"]:
        print(f"regress: improved: {line}")
    if rep["regressions"]:
        tag = "MISPREDICTION" if replay_mode else "REGRESSION"
        for line in rep["regressions"]:
            print(f"regress: {tag}: {line}", file=sys.stderr)
        return 1
    if replay_mode:
        print(
            f"regress: OK — replay prediction within ±{args.tol * 100:.0f}% "
            "of the live run (per-tier p95)"
        )
    else:
        gated = list(SIDES)
        if all(
            isinstance(d, dict) and isinstance(d.get("megastep"), dict)
            for d in (old, new)
        ):
            gated.append("megastep")
        if all(
            isinstance(d, dict) and isinstance(d.get("mesh"), dict)
            for d in (old, new)
        ):
            gated.append("mesh")
        print(
            f"regress: OK — no regression beyond {args.tol * 100:.0f}% "
            f"({', '.join(f'{s} {q}' for s in gated for q in QUANTS)})"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
