"""uint16 candidate masks: measure or refute the roofline headroom claim.

VERDICT r3 #3: the whole framework carries candidate masks as uint32, but
9x9 needs 9 bits and 16x16 needs 16 — uint16 state would halve VMEM bytes
per lane and potentially the vector work (v5e packs 16-bit lanes 2x per
vreg).  Before refactoring three kernel files, this probe answers two
questions on hardware:

1. Does Mosaic LOWER the mask algebra (popcount / and-not folds /
   group-reduce concat trees / while fixpoint) on uint16 vregs at all?
2. If it lowers, what is the measured speedup of the propagation fixpoint
   — the op mix that dominates the fused kernel's rounds?

Method: the EXACT sweep algebra of ``ops/pallas_propagate.sweep_mosaic``
(same helpers, dtype-parametrized literals), boards-last [n, n, T] tiles,
fixpoint while-loop inside one ``pallas_call``; K=16 dispatch-chained
iterations amortize tunnel overhead (the bench_suite protocol, including
the roll-by-index defense against LICM/DCE).  A/B interleaved best-of-3.

Run:  python benchmarks/probe_uint16.py [--batch 65536] [--tile 2048]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def build(jax, jnp, dtype):
    from jax.experimental import pallas as pl

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9 as geom
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        _OR,
        _VMEM,
        _fold,
        _interpret_default,
        _ot_comb,
        _ot_lift,
        _unit_maps,
    )

    del dtype  # dtype rides the input; literals must be Python ints
    # (pallas_call rejects captured jnp scalars — the round-3 lowering rule)

    def sweep(cand):
        single = jax.lax.population_count(cand) == 1
        decided = jnp.where(single, cand, 0)
        seen = _fold(
            list(_unit_maps(decided, geom, _OR, lambda v: v, 0, 1)), _OR
        )
        cand = jnp.where(single, cand, cand & ~seen)
        forced = jnp.zeros_like(cand)
        for once, twice in _unit_maps(cand, geom, _ot_comb, _ot_lift, 0, 1):
            forced = forced | (cand & (once & ~twice))
        return jnp.where(~single & (forced != 0), forced, cand)

    def kernel(cand_ref, out_ref, *, max_sweeps):
        def cond(s):
            _, changed, k = s
            return changed & (k < max_sweeps)

        def body(s):
            cur, _, k = s
            nxt = sweep(cur)
            return nxt, jnp.any(nxt != cur), k + 1

        out, _, _ = jax.lax.while_loop(
            cond, body, (cand_ref[...], jnp.bool_(True), jnp.int32(0))
        )
        out_ref[...] = out

    interp = _interpret_default()
    vmem = dict(memory_space=_VMEM) if (_VMEM is not None and not interp) else {}

    @functools.partial(jax.jit, static_argnames=("tile",))
    def fixpoint(cand_t, tile):
        n = geom.n
        n_lanes = cand_t.shape[-1]
        spec = pl.BlockSpec((n, n, tile), lambda i: (0, 0, i), **vmem)
        return pl.pallas_call(
            functools.partial(kernel, max_sweeps=64),
            grid=(n_lanes // tile,),
            in_specs=[spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct(cand_t.shape, cand_t.dtype),
            interpret=interp,
        )(cand_t)

    return fixpoint


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--tile", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=16)
    args = ap.parse_args()
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )

    import jax
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    base = puzzle_batch(SUDOKU_9, 512, seed=7, n_clues=24)
    grids = np.tile(base, (args.batch // 512, 1, 1))
    cand32_t = np.asarray(
        encode_grid(jnp.asarray(grids), SUDOKU_9), np.uint32
    ).transpose(1, 2, 0)

    cases = {
        "uint32": (jnp.uint32, jax.device_put(jnp.asarray(cand32_t))),
        "uint16": (
            jnp.uint16,
            jax.device_put(jnp.asarray(cand32_t.astype(np.uint16))),
        ),
    }
    k = args.iters

    results: dict[str, float] = {}
    outs: dict[str, np.ndarray] = {}
    for name, (dt, cand) in cases.items():
        fixpoint = build(jax, jnp, dt)

        @jax.jit
        def chained(x, fixpoint=fixpoint):
            def body(i, acc):
                return acc | fixpoint(jnp.roll(x, i, axis=-1), tile=args.tile)

            return jax.lax.fori_loop(0, k, body, jnp.zeros_like(x))

        try:
            outs[name] = np.asarray(fixpoint(cand, tile=args.tile))
            _ = np.asarray(chained(cand))  # warm / compile
        except Exception as e:  # noqa: BLE001
            emit(metric="uint16_probe", case=name, error=repr(e)[:500])
            continue
        results[name] = float("inf")
        cases[name] = (dt, cand, chained)
    for _ in range(3):  # interleaved best-of-3
        for name, entry in cases.items():
            if len(entry) != 3 or name not in results:
                continue
            _, cand, chained = entry
            t0 = time.perf_counter()
            _ = np.asarray(chained(cand))
            results[name] = min(results[name], time.perf_counter() - t0)

    bit_equal = None
    if "uint32" in outs and "uint16" in outs:
        bit_equal = bool(
            (outs["uint32"].astype(np.uint16) == outs["uint16"]).all()
        )
    out = {
        "metric": "uint16_probe",
        "batch": args.batch,
        "tile": args.tile,
        "iters": k,
        "bit_equal_low16": bit_equal,
        "device": str(jax.devices()[0].platform),
    }
    for name, dt in results.items():
        if np.isfinite(dt):
            out[f"{name}_fixpoints_per_s"] = round(args.batch * k / dt, 1)
            out[f"{name}_wall_s"] = round(dt, 3)
    if all(np.isfinite(results.get(n, np.nan)) for n in ("uint32", "uint16")):
        out["speedup_uint16"] = round(results["uint32"] / results["uint16"], 3)
    emit(**out)


if __name__ == "__main__":
    main()
