"""Exact-cover family benchmark: device engine vs the native C++ DFS.

VERDICT r2 #7: ``models/cover.py`` had correctness coverage but zero perf
evidence.  This benchmark runs full *enumeration* (``count_all``: every
solution counted, search to exhaustion — the honest workload, nothing
first-win-lucky) on the classic instances with known counts:

* N-queens all-solutions (n=12: 14,200; n=13: 73,712; n=14: 365,596) as
  generalized exact cover (``models/nqueens.py``);
* pentomino 6x10 tilings: 9,356 raw placements = the classic 2,339
  distinct tilings x the rectangle's 4 symmetries (raw enumeration
  counts each orientation; both engines count the same raw space).

Both engines search the IDENTICAL packed cover matrix: the native side
(``native.cover_count``, recursive MRV DFS in C++) reads the same
``col_rows``/``row_cols``/``elim`` arrays the device kernels do, so the
rows compare search engines, not encodings.  Device dispatches are
step-bounded (watchdog discipline, BENCHMARKS.md "Dispatch-time bounds").

    python benchmarks/bench_cover.py            # all rows
    python benchmarks/bench_cover.py --rows q12
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # runnable from any cwd without installing


def device_count_all(
    problem, config, dispatch_steps: int = 2048, repeat: int = 3
):
    """Enumerate on-device in bounded dispatches; returns (count, nodes, s).

    Best-of-``repeat`` wall clock — one-shot numbers through the tunneled
    chip are noise (BENCHMARKS.md "Measurement protocol"; a 20x outlier
    was observed on this very workload's sub-second dispatch pattern)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.ops.frontier import (
        frontier_live,
        init_frontier,
        run_frontier,
    )
    from distributed_sudoku_solver_tpu.ops.solve import finalize_frontier

    if config.step_impl == "fused":
        from distributed_sudoku_solver_tpu.ops.pallas_cover import (
            advance_cover_fused,
            cover_fused_lanes,
        )

        config = dataclasses.replace(
            config, lanes=cover_fused_lanes(config.resolve_lanes(1))
        )
        advance = advance_cover_fused
    else:

        @functools.partial(jax.jit, static_argnames=("problem", "config"))
        def advance(state, limit, problem, config):
            return run_frontier(state, problem, config, step_limit=limit)

    roots = jnp.asarray(problem.initial_state()[None])
    state = init_frontier(roots, config)
    # Warm the compile outside the timed region.
    advance(state, jnp.int32(1), problem, config).steps.block_until_ready()

    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        state = init_frontier(roots, config)
        limit = 0
        while limit < config.max_steps:
            limit = min(limit + dispatch_steps, config.max_steps)
            state = advance(state, jnp.int32(limit), problem, config)
            if not bool(np.asarray(jnp.any(frontier_live(state)))):
                break
        best = min(best, time.perf_counter() - t0)
    res = finalize_frontier(state)
    count = int(np.asarray(res.sol_count[0]))
    assert bool(np.asarray(res.unsat[0])), "enumeration did not run to exhaustion"
    assert not bool(np.asarray(res.overflowed[0])), "overflow: count is a lower bound"
    return count, int(np.asarray(res.nodes[0])), best


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def run_row(name: str, problem, expect: int, config, fused_config=None) -> None:
    from distributed_sudoku_solver_tpu import native

    cnt, nodes, dt = device_count_all(problem, config)
    assert cnt == expect, f"{name}: device counted {cnt}, expected {expect}"
    f_cnt, f_nodes, f_dt = None, None, None
    if fused_config is not None:
        f_cnt, f_nodes, f_dt = device_count_all(problem, fused_config)
        assert f_cnt == expect, f"{name}: fused counted {f_cnt}, expected {expect}"
    n_cnt, n_nodes, n_dt = None, None, None
    if native.available():
        n_dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            n_cnt, n_nodes = native.cover_count(problem)
            n_dt = min(n_dt, time.perf_counter() - t0)
        assert n_cnt == expect, f"{name}: native counted {n_cnt}"
    emit(
        metric=f"cover_enumerate_{name}",
        value=round(cnt / dt, 1),
        unit="solutions/s",
        solutions=cnt,
        device_s=round(dt, 3),
        device_nodes=nodes,
        fused_s=round(f_dt, 3) if f_dt is not None else None,
        fused_nodes=f_nodes,
        fused_speedup=round(dt / f_dt, 2) if f_dt else None,
        native_s=round(n_dt, 3) if n_dt is not None else None,
        native_nodes=n_nodes,
        speedup_vs_native=round(n_dt / dt, 2) if n_dt else None,
        fused_speedup_vs_native=(
            round(n_dt / f_dt, 2) if (n_dt and f_dt) else None
        ),
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--rows", type=str, default="q12,q13,pento",
        help="comma-separated: q12, q13, q14, pento",
    )
    ap.add_argument("--lanes", type=int, default=4096)  # the BENCHMARKS.md config
    ap.add_argument("--stack-slots", type=int, default=128)
    ap.add_argument(
        "--no-fused", action="store_true",
        help="skip the fused-kernel column (composite + native only)",
    )
    args = ap.parse_args()

    from distributed_sudoku_solver_tpu.models.nqueens import nqueens_cover
    from distributed_sudoku_solver_tpu.models.pentomino import pentomino_cover
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

    cfg = SolverConfig(
        lanes=args.lanes,
        stack_slots=args.stack_slots,
        max_steps=1_000_000,
        count_all=True,
        steal_rounds=4,  # enumeration is a permanent gang: fan out fast
    )
    import dataclasses

    # Same lanes/depth/steal on the fused column so the A/B isolates the
    # step engine (the whole-round VMEM kernel, ops/pallas_cover.py).
    fused_cfg = (
        None
        if args.no_fused
        else dataclasses.replace(cfg, step_impl="fused")
    )
    known = {
        "q12": ("nqueens12", nqueens_cover(12), 14_200),
        "q13": ("nqueens13", nqueens_cover(13), 73_712),
        "q14": ("nqueens14", nqueens_cover(14), 365_596),
        "pento": ("pentomino6x10", pentomino_cover(6, 10), 9_356),
    }
    for key in args.rows.split(","):
        name, problem, expect = known[key]
        run_row(name, problem, expect, cfg, fused_cfg)


if __name__ == "__main__":
    main()
