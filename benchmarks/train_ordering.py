"""Offline trainers for the learned branch-ordering pieces (ROADMAP #4).

Four subcommands cover the whole loop from journal to shipped weights:

* ``record``  — build per-branch training examples.  Sources: the built-in
  hard-tail corpus (``--corpus N``: the three benchmark killers plus
  generated 24-clue boards) and/or an ordering-trace JSONL from a real
  deployment (``--trace FILE``: the sampled ``grid`` events recorded by
  ``obs/ordertrace.py``).  Each solve replays host-side with the kernel's
  own strategy (``ops/ordering.py:record_branch_examples``) and journals
  every (chosen-cell features, subtree-nodes) decision.
* ``train``   — fit the one-hidden-layer MLP on the recorded examples
  (numpy Adam, MSE on ``log2(1 + subtree_nodes)``) and emit the
  ``dsst-ordering-mlp/1`` weights JSON the ``head:mlp`` head loads.
* ``fit-threshold`` — learn the front door's ``easy_score`` routing
  threshold from recorded route/wall outcomes
  (``serving/frontdoor/learn.py``) instead of the shipped constant.
* ``eval``    — the head A/B on the hard-tail corpus: per-head searched /
  node totals with verdict-equality checks (solutions oracle-validated,
  unsat cross-checked by ``count_all``), emitted as the BENCH_r11
  artifact section.

``record``/``train``/``fit-threshold`` are numpy/stdlib only — they run
wherever the trace was captured, no accelerator needed.  ``eval`` runs
the real engine (set ``JAX_PLATFORMS=cpu`` for a host-only check).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from any cwd without installing
    sys.path.insert(0, REPO)

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9, Geometry
from distributed_sudoku_solver_tpu.obs import ordertrace
from distributed_sudoku_solver_tpu.ops import ordering


def hard_corpus(
    n_generated: int, n_clues: int = 24, seed0: int = 0, unique: bool = True
):
    """The hard-tail corpus: the three benchmark killer boards plus
    ``n_generated`` generated boards at ``n_clues``.  ``unique=False``
    skips the uniqueness check while carving — under-constrained boards
    branch much deeper, which is what the example recorder wants."""
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, make_puzzle

    boards = [np.asarray(b) for b in HARD_9]
    for seed in range(seed0, seed0 + n_generated):
        boards.append(
            np.asarray(
                make_puzzle(SUDOKU_9, seed=seed, n_clues=n_clues, unique=unique)
            )
        )
    return boards


def cmd_record(args) -> None:
    geom = SUDOKU_9
    grids = []
    if args.trace:
        for ev in ordertrace.read_events(args.trace):
            if ev.get("kind") != "grid":
                continue
            n = int(ev["n"])
            flat = [int(ch) for ch in ev["grid"]]
            grids.append(np.asarray(flat, dtype=np.int64).reshape(n, n))
        print(f"trace {args.trace}: {len(grids)} recorded grids")
    if args.corpus:
        grids.extend(
            hard_corpus(args.corpus, args.clues, unique=not args.no_unique)
        )
    if not grids:
        sys.exit("record: nothing to replay (pass --corpus N and/or --trace FILE)")
    n_examples = 0
    with open(args.out, "w", encoding="utf-8") as fh:
        for i, g in enumerate(grids):
            examples, nodes = ordering.record_branch_examples(
                g, geom, max_nodes=args.max_nodes
            )
            for ex in examples:
                fh.write(json.dumps(ex, sort_keys=True) + "\n")
            n_examples += len(examples)
            print(f"  board {i}: {len(examples)} examples, {nodes} nodes")
    print(f"wrote {n_examples} examples -> {args.out}")


def _load_examples(path: str):
    xs, ys = [], []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            ex = json.loads(line)
            xs.append(ex["features"])
            ys.append(np.log2(1.0 + float(ex["nodes"])))
    return np.asarray(xs, np.float32), np.asarray(ys, np.float32)


def cmd_train(args) -> None:
    x, y = _load_examples(args.examples)
    n, n_feat = x.shape
    hidden = args.hidden
    rng = np.random.default_rng(args.seed)
    w1 = rng.normal(0, 0.5, size=(n_feat, hidden)).astype(np.float32)
    b1 = np.zeros(hidden, np.float32)
    w2 = rng.normal(0, 0.5, size=hidden).astype(np.float32)
    b2 = np.float32(y.mean())
    params = [w1, b1, w2, b2]
    # Adam state (numpy, no deps): one moment pair per tensor.
    ms = [np.zeros_like(p) for p in params]
    vs = [np.zeros_like(p) for p in params]
    beta1, beta2, eps, lr = 0.9, 0.999, 1e-8, args.lr
    steps = 0
    for epoch in range(args.epochs):
        perm = rng.permutation(n)
        losses = []
        for lo in range(0, n, args.batch):
            idx = perm[lo : lo + args.batch]
            xb, yb = x[idx], y[idx]
            pre = xb @ params[0] + params[1]
            h = np.maximum(pre, 0.0)
            pred = h @ params[2] + params[3]
            err = pred - yb
            losses.append(float((err**2).mean()))
            # Backprop by hand: MSE -> linear -> relu -> linear.
            g_pred = 2.0 * err / len(idx)
            g_w2 = h.T @ g_pred
            g_b2 = g_pred.sum()
            g_h = np.outer(g_pred, params[2]) * (pre > 0)
            g_w1 = xb.T @ g_h
            g_b1 = g_h.sum(axis=0)
            grads = [g_w1, g_b1, g_w2, g_b2]
            steps += 1
            for i, g in enumerate(grads):
                ms[i] = beta1 * ms[i] + (1 - beta1) * g
                vs[i] = beta2 * vs[i] + (1 - beta2) * np.square(g)
                m_hat = ms[i] / (1 - beta1**steps)
                v_hat = vs[i] / (1 - beta2**steps)
                params[i] = params[i] - lr * m_hat / (np.sqrt(v_hat) + eps)
        if epoch % max(1, args.epochs // 10) == 0 or epoch == args.epochs - 1:
            print(f"epoch {epoch}: mse={np.mean(losses):.4f} (n={n})")
    w1, b1, w2, b2 = params
    doc = {
        "schema": "dsst-ordering-mlp/1",
        "w1": [[float(v) for v in row] for row in w1],
        "b1": [float(v) for v in b1],
        "w2": [float(v) for v in w2],
        "b2": float(b2),
        "meta": {
            "examples": int(n),
            "hidden": hidden,
            "epochs": args.epochs,
            "final_mse": round(float(np.mean(losses)), 4),
        },
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    print(f"weights -> {args.out}")


def cmd_fit_threshold(args) -> None:
    from distributed_sudoku_solver_tpu.serving.frontdoor.learn import (
        learned_easy_score,
    )

    threshold, report = learned_easy_score(
        args.trace, default=args.default, min_samples=args.min_samples
    )
    print(json.dumps({"easy_score": threshold, **report}, indent=1))


def cmd_eval(args) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import dataclasses

    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch

    geom = SUDOKU_9
    boards = hard_corpus(args.corpus, args.clues)
    n = geom.n

    def check_solution(g, s):
        for i in range(n):
            assert sorted(s[i, :]) == list(range(1, n + 1)), "row"
            assert sorted(s[:, i]) == list(range(1, n + 1)), "col"
        assert ((g == 0) | (g == s)).all(), "clues"

    # Per-JOB flights (one board per solve): the serving regime the heads
    # target — latency-mode and front-door device jobs fly one board each,
    # and the per-job nodes counter in the status word is the win being
    # claimed.  A wide shared batch would hide the ordering win behind
    # lane-parallel speculative expansion.
    heads = ("minrem", "head:minrem", "head:cw-slack", "head:mlp")
    out = {"corpus": len(boards), "config": {
        "lanes": args.lanes, "stack_slots": args.stack_slots,
        "step_impl": args.step_impl, "per_job": True,
    }, "heads": {}}
    base = None
    for rule in heads:
        cfg = SolverConfig(
            lanes=args.lanes, stack_slots=args.stack_slots,
            branch=rule, step_impl=args.step_impl,
        )
        verdicts, nodes_total, searched = [], 0, 0
        for g in boards:
            res = solve_batch(jnp.asarray(np.asarray(g)[None]), geom, cfg)
            solved = bool(res.solved[0])
            unsat = bool(res.unsat[0])
            nodes = int(res.nodes[0])
            if solved:
                check_solution(g, np.asarray(res.solution[0]))
            if unsat:
                # Unsat verdicts must survive the oracle: exact
                # enumeration over the same board must find zero.
                cnt = solve_batch(
                    jnp.asarray(np.asarray(g)[None]), geom,
                    dataclasses.replace(cfg, count_all=True),
                )
                assert int(cnt.sol_count[0]) == 0, \
                    "unsat verdict contradicted by count_all"
            verdicts.append((solved, unsat))
            nodes_total += nodes
            searched += 1 if nodes > 0 else 0
        row = {
            "solved": sum(1 for s, _ in verdicts if s),
            "unsat": sum(1 for _, u in verdicts if u),
            "searched": searched,
            "nodes": nodes_total,
        }
        if base is None:
            base = (row, verdicts)
            row["nodes_vs_minrem"] = 1.0
        else:
            assert verdicts == base[1], f"{rule}: verdicts differ from minrem"
            row["nodes_vs_minrem"] = round(row["nodes"] / base[0]["nodes"], 4)
        out["heads"][rule] = row
        print(
            f"{rule:<16} solved={row['solved']:>3} searched={row['searched']:>3} "
            f"nodes={row['nodes']:>6}  vs minrem x{row['nodes_vs_minrem']}"
        )
    if args.out_json:
        with open(args.out_json, "w", encoding="utf-8") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
        print(f"eval artifact -> {args.out_json}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="replay solves, journal branch examples")
    rec.add_argument("--corpus", type=int, default=24,
                     help="generated hard boards to include (0 = none)")
    rec.add_argument("--clues", type=int, default=24)
    rec.add_argument("--trace", default=None,
                     help="ordering-trace JSONL with sampled grid events")
    rec.add_argument("--no-unique", action="store_true",
                     help="skip the uniqueness check while carving the "
                     "generated boards: under-constrained boards branch "
                     "deeper and yield far more examples")
    rec.add_argument("--max-nodes", type=int, default=50_000)
    rec.add_argument("--out", default="ordering_examples.jsonl")
    rec.set_defaults(fn=cmd_record)

    tr = sub.add_parser("train", help="fit the mlp head on recorded examples")
    tr.add_argument("--examples", default="ordering_examples.jsonl")
    tr.add_argument("--hidden", type=int, default=8)
    tr.add_argument("--epochs", type=int, default=200)
    tr.add_argument("--batch", type=int, default=256)
    tr.add_argument("--lr", type=float, default=3e-3)
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument("--out", default="ordering_weights.json")
    tr.set_defaults(fn=cmd_train)

    ft = sub.add_parser("fit-threshold",
                        help="learn the front door easy_score from a trace")
    ft.add_argument("--trace", required=True)
    ft.add_argument("--default", type=int, default=64)
    ft.add_argument("--min-samples", type=int, default=8)
    ft.set_defaults(fn=cmd_fit_threshold)

    ev = sub.add_parser("eval", help="head A/B on the hard-tail corpus")
    ev.add_argument("--corpus", type=int, default=24)
    ev.add_argument("--clues", type=int, default=24)
    ev.add_argument("--lanes", type=int, default=8)
    ev.add_argument("--stack-slots", type=int, default=64)
    ev.add_argument("--step-impl", default="xla", choices=("xla", "fused"))
    ev.add_argument("--out-json", default=None)
    ev.set_defaults(fn=cmd_eval)

    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
