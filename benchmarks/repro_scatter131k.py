"""Minimal repro: XLA:TPU scatter-fusion CHECK at >65,536 frontier lanes.

BENCHMARKS.md ("XLA:TPU note") caps chunk defaults at 65,536 lanes because
131,072-lane compiles crash the backend inside ``scatter_emitter.cc``.
This script pins the failure with progressively smaller graphs:

  stage full   — the whole ``frontier_step`` (the production shape)
  stage push   — ONLY the stack push scatter ``stack.at[lane, slot].set(row)``
  stage onehot — the scatter-free reformulation of the same update (masked
                 full-stack where), to test whether avoiding scatter unlocks
                 the shape

Usage (one TPU process at a time; compile-only, no dispatch):

    python benchmarks/repro_scatter131k.py --lanes 131072 --stage full
"""

from __future__ import annotations

import argparse
import os
import sys
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=131072)
    ap.add_argument("--slots", type=int, default=12)
    ap.add_argument(
        "--stage",
        choices=("full", "push", "onehot", "loop", "wire", "solve_wire", "solve", "init"),
        default="full",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import (
        SolverConfig,
        frontier_step,
        init_frontier,
    )
    from distributed_sudoku_solver_tpu.ops.solve import sudoku_csp

    L, S = args.lanes, args.slots
    print(f"stage={args.stage} lanes={L} slots={S} backend={jax.default_backend()}")

    if args.stage in ("full", "loop"):
        from distributed_sudoku_solver_tpu.ops.frontier import run_frontier

        cfg = SolverConfig(lanes=L, stack_slots=S, propagator="slices")
        problem = sudoku_csp(SUDOKU_9, cfg)
        state = init_frontier(jnp.zeros((L, 9, 9), jnp.uint32), cfg)
        if args.stage == "full":
            fn = jax.jit(lambda st: frontier_step(st, problem, cfg))
        else:  # the whole while_loop, as the bulk first pass runs it
            fn = jax.jit(lambda st: run_frontier(st, problem, cfg))
        lowered = fn.lower(state)
    elif args.stage == "solve":
        from distributed_sudoku_solver_tpu.ops.solve import solve_batch

        cfg = SolverConfig(
            lanes=L, stack_slots=S, propagator="slices", max_steps=4096
        )
        grids = jnp.zeros((L, 9, 9), jnp.int32)
        lowered = jax.jit(
            lambda g: solve_batch(g, SUDOKU_9, cfg), static_argnums=()
        ).lower(grids)
    elif args.stage == "init":
        cfg = SolverConfig(lanes=L, stack_slots=S)
        cand = jnp.zeros((L, 9, 9), jnp.uint32)
        lowered = jax.jit(lambda c: init_frontier(c, cfg)).lower(cand)
    elif args.stage == "wire":
        from distributed_sudoku_solver_tpu.ops import wire

        packed = jnp.zeros(
            wire.pack_grids_host(np.zeros((L, 9, 9), np.int32), SUDOKU_9).shape,
            jnp.uint8,
        )
        fn = jax.jit(lambda p: wire.unpack_grids_device(p, SUDOKU_9))
        lowered = fn.lower(packed)
    elif args.stage == "solve_wire":
        from distributed_sudoku_solver_tpu.ops import wire
        from distributed_sudoku_solver_tpu.ops.solve import solve_batch_wire

        cfg = SolverConfig(
            lanes=L, stack_slots=S, propagator="slices", max_steps=4096
        )
        packed = jnp.zeros(
            wire.pack_grids_host(np.zeros((L, 9, 9), np.int32), SUDOKU_9).shape,
            jnp.uint8,
        )
        lowered = solve_batch_wire.lower(packed, SUDOKU_9, cfg)
    else:
        stack = jnp.zeros((L, S, 9, 9), jnp.uint32)
        rest = jnp.zeros((L, 9, 9), jnp.uint32)
        can_push = jnp.zeros(L, bool)
        slot = jnp.zeros(L, jnp.int32)

        if args.stage == "push":

            def push(stack, rest, can_push, slot):
                lane_idx = jnp.arange(L, dtype=jnp.int32)
                return stack.at[
                    jnp.where(can_push, lane_idx, L), jnp.clip(slot, 0, S - 1)
                ].set(rest, mode="drop")

        else:  # onehot: scatter-free masked write of the same update

            def push(stack, rest, can_push, slot):
                sel = (
                    jnp.arange(S, dtype=jnp.int32)[None, :] == slot[:, None]
                ) & can_push[:, None]
                return jnp.where(sel[:, :, None, None], rest[:, None], stack)

        lowered = jax.jit(push).lower(stack, rest, can_push, slot)

    try:
        lowered.compile()
    except Exception as e:  # noqa: BLE001 - repro: report and exit nonzero
        traceback.print_exc(limit=2)
        print(f"COMPILE FAILED at lanes={L}: {type(e).__name__}: {e}"[:2000])
        raise SystemExit(1)
    print(f"COMPILE OK at lanes={L}")


if __name__ == "__main__":
    main()
