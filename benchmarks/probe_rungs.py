"""Escalation-rung probes (VERDICT r4 #2): the rungs earn their defaults.

Rungs were named as half of the end-to-end gap, yet ran an unmeasured
propagator choice and a composite-only step engine.  Two experiments,
one JSON line each (BENCHMARKS.md records the adopted numbers):

  prop   — in-rung propagator A/B ('slices' vs 'pallas').  With the
           fused first pass (auto on TPU) the first-pass fixpoint runs
           IN-KERNEL, so `BulkConfig.propagator` only reaches the rungs:
           the A/B isolates exactly the contested choice.
  fused  — rung step-engine A/B on the default 9x9 ladder and the
           VERDICT-suggested (64, 128, 48) gang rung, composite vs
           `rung_step_impl='fused'` (admissible since the round-5
           stack-depth re-measurement: 9x9 compiles to S=128).

Both run the headline distinct corpus plus a harder 22-clue straggler
corpus (more rung survivors), reporting the trace-attributed rung wall
alongside the total so first-pass noise doesn't wash the comparison.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def corpus(b: int, n_clues: int):
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    distinct = puzzle_batch(
        SUDOKU_9, b - len(HARD_9), seed=7 if n_clues == 24 else 91,
        n_clues=n_clues,
    )
    return np.concatenate([np.stack(HARD_9), distinct]).astype(np.int32)


def run(grids, cfg, label: str) -> dict:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import solve_bulk

    solve_bulk(grids, SUDOKU_9, cfg)  # warm
    best = None
    for _ in range(3):
        tr: dict = {}
        t0 = time.perf_counter()
        res = solve_bulk(grids, SUDOKU_9, cfg, trace=tr)
        wall = time.perf_counter() - t0
        if best is None or wall < best["wall_s"]:
            best = {
                "config": label,
                "wall_s": round(wall, 3),
                "boards_per_s": round(len(grids) / wall, 1),
                "solved": int(res.solved.sum()),
                "first_pass_s": round(tr["first_pass_s"], 3),
                "rung_wall_s": round(
                    sum(r["wall_s"] for r in tr["rungs"]), 3
                ),
                "rung_dispatches": sum(r["dispatches"] for r in tr["rungs"]),
                "remaining_after_first": tr["remaining_after_first"],
                "rungs": [
                    (r["survivors_in"], r["survivors_out"], r["lanes"], r["slots"])
                    for r in tr["rungs"]
                ],
            }
    return best


def bench_prop(grids, tag: str) -> None:
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig

    for prop in ("slices", "pallas"):
        emit(
            metric="rung_propagator_ab", corpus=tag,
            **run(grids, BulkConfig(propagator=prop), prop),
        )


def bench_fused(grids, tag: str) -> None:
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig

    ladders = {
        "default": None,
        "gang12848": ((64, 128, 48),),
    }
    for lname, rungs in ladders.items():
        for impl in (None, "fused"):
            label = f"{lname}:{impl or 'xla'}"
            emit(
                metric="rung_step_ab", corpus=tag,
                **run(
                    grids,
                    BulkConfig(rungs=rungs, rung_step_impl=impl),
                    label,
                ),
            )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("experiments", nargs="*", default=["prop", "fused"])
    ap.add_argument("--b", type=int, default=65536)
    ap.add_argument("--hard-b", type=int, default=4096)
    args = ap.parse_args()
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    emit(metric="session", device=str(jax.devices()[0].platform))

    headline = corpus(args.b, 24)
    hard = corpus(args.hard_b, 22)
    for exp in args.experiments:
        fn = {"prop": bench_prop, "fused": bench_fused}[exp]
        fn(headline, "headline24")
        fn(hard, "hard22")


if __name__ == "__main__":
    main()
