"""Poisson-arrival serving benchmark: resident flights vs static flights.

The round-7 acceptance measurement (ISSUE: continuous-batching resident
flights).  A Poisson arrival process with mean inter-arrival BELOW the
single-flight duration is fired at two engines built identically except for
the scheduler:

* **static**: today's flight loop — each admitted batch launches its own
  frontier and retires whole; an arrival during a full house waits for a
  flight to drain.
* **resident**: the continuous-batching scheduler
  (``serving/scheduler.py``) — one long-lived frontier; arrivals attach to
  recycled job slots between dispatches.

Reported: per-job time-to-solution p50/p95/p99 for both, plus the
improvement ratios.  ``--handicap-ms`` applies the engine's slow-node
simulator to BOTH engines; since round 8 it is charged at the fetch seam
(``serving.engine.host_fetch``) — one sleep per HOST SYNC, which under
the one-fetch-per-chunk contract is one per chunk, but crucially the
sleep now happens while the always-ahead loop's next chunk is already on
the device, exactly as a real RPC fetch floor would (tunnel ~74-122
ms/round trip, BENCHMARKS.md "Measured link").  The round-7 numbers
charged the same floor per chunk but SERIALLY (sleep, dispatch, block,
fetch x5 for free); the round-8 delta vs that table is therefore the
measured value of overlapping the floor with device compute plus
eliminating the extra per-chunk fetches.  ``--handicap-ms 0`` measures
the raw CPU compute-bound case too.  The JSON output includes each
engine's ``dispatch_wall_ms`` / ``sync_wall_ms`` split so the overlap is
directly observable.

Run: ``python benchmarks/bench_poisson.py [--jobs 48] [--mean-ms 50]
[--handicap-ms 50] [--json]``.  The tier-1 smoke and the ``slow``-marked
assertion live in ``tests/test_scheduler.py``.

``--workload-out trace.json`` (round 18) records the resident run as a
versioned workload trace (``dsst-workload/1``: arrival offsets, board
payloads, per-job tier/route/verdict/wall) that ``benchmarks/replay.py``
re-runs through ``cluster/simnet.py`` as a deterministic, sleep-free
capacity experiment — with the brownout controller live — whose
``dsst-replay/1`` artifact ``benchmarks/regress.py`` can compare against
a live ``--out-json`` run.

``--latency-mode`` (round 19) adds a THIRD measured pass over the same
arrival schedule: an engine built with ``latency_mode=True`` plus a
megastep config, so every hard board rides the serving megastep
(``serving/megastep.py``) — N advance chunks fused into ONE donated
dispatch with in-graph early exit, ONE host status sync per *flight*
instead of one per chunk.  Under ``--handicap-ms F`` the chunked paths
pay F per chunk while the megastep pays F once per job, which is
exactly the interactive win the round-5 numbers said was left
(``rpc_floor_ms`` ~99% of hard-board p50).  The pass lands as a
``megastep`` section in ``--out-json`` (same quantile shape as
static/resident, so ``benchmarks/regress.py`` gates it whenever both
artifacts carry it) plus the per-route ``frontdoor_megastep_ms``
histogram and the flight counters (flights, chunks/flight, degrades).

``--mesh-devices N`` (round 21) adds the pod-scale resident tier: the
same all-hard stream against an engine whose resident flight is sharded
over ``N`` forced host-platform devices
(``--xla_force_host_platform_device_count``, set before jax initializes —
one process per ``N``), with 8 job slots PER SHARD so the admission pool
is ``8*N``.  Reported: aggregate ``boards_per_s`` over the drain wall
plus the usual quantiles and the flight's mesh telemetry (cross-shard
ring-steal volume, per-shard occupancy).  ``N=1`` is the single-chip
baseline row of the BENCHMARKS.md scaling table.  CPU "devices" share
one socket, so the scaling measured here is slot-pool capacity under the
per-chunk sync floor, not per-chunk compute.

``--mix easy:N,hard:M,repeat:R`` (round 17) swaps the all-hard corpus
for a realistic mixed-difficulty stream — distinct easy and hard boards
plus *symmetry-transformed* repeats of already-sent ones — and runs both
engines behind the front door (``serving/frontdoor``), reporting
per-route and per-tier percentiles beside the overall numbers.  Mixed
artifacts carry the mix in ``params``; ``benchmarks/regress.py`` refuses
to compare artifacts with different mixes (exit 2 — different workload,
not a regression).

``--kill-at S --restart-after S`` (round 21, ISSUE 20) adds the
durability pass: a journal-backed engine (``serving/journal.py``) takes
the stream, is killed abruptly S seconds in (WAL batcher dies
mid-buffer — a crash, not a drain), restarts over the same WAL
directory, and replays.  Jobs recovered + recovery wall land in a
``recovery`` artifact section; the kill params mark the whole artifact
non-comparable in ``regress.py`` (exit 2 — a truncated stream is not a
workload measurement).
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from any cwd without installing
    sys.path.insert(0, REPO)


def _percentiles(lats) -> dict:
    arr = np.asarray(sorted(lats), float)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 1),
        "p95_ms": round(float(np.percentile(arr, 95)) * 1e3, 1),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 1),
        "mean_ms": round(float(arr.mean()) * 1e3, 1),
        "jobs": len(lats),
    }


def poisson_gaps(n_boards: int, mean_gap_s: float, seed: int = 0) -> list:
    """The deterministic inter-arrival schedule (same draw order as the
    pre-round-18 inline draws, so seeded runs reproduce byte-identically):
    ``n_boards - 1`` exponential gaps.  Shared by :func:`poisson_load`
    and the workload-trace recorder, so a recorded trace's arrival
    offsets are exactly the schedule the live run fired."""
    rng = random.Random(seed)
    return [rng.expovariate(1.0 / mean_gap_s) for _ in range(max(0, n_boards - 1))]


def arrival_offsets(n_boards: int, mean_gap_s: float, seed: int = 0) -> list:
    """Cumulative arrival offsets (seconds from the first submit) of the
    :func:`poisson_gaps` schedule."""
    offsets = [0.0]
    for g in poisson_gaps(n_boards, mean_gap_s, seed):
        offsets.append(offsets[-1] + g)
    return offsets[:n_boards]


def poisson_load(engine, boards, mean_gap_s: float, seed: int = 0,
                 timeout: float = 600.0, latency: bool = False):
    """Submit ``boards`` with exponential inter-arrival gaps; returns
    ``(latencies_s, jobs)`` where latency is submit -> resolution wall
    (inf for a job that missed ``timeout``).

    ``latency=True`` submits each arrival with the per-request
    ``latency`` flag from its OWN thread: a megastep-routed submit
    resolves synchronously inside ``submit()`` (the flight IS the
    request), so an inline submit would stall the Poisson clock behind
    the flight wall.  The arrival schedule is identical either way —
    the pacing thread still sleeps the same seeded gaps."""
    gaps = poisson_gaps(len(boards), mean_gap_s, seed)
    jobs: list = [None] * len(boards)
    lats = [float("inf")] * len(boards)
    threads = []

    def waiter(i, job):
        if job.wait(timeout):
            lats[i] = time.monotonic() - job.submitted_at

    def fire(i, board):
        t0 = time.monotonic()
        job = engine.submit(np.asarray(board, np.int32), latency=True)
        jobs[i] = job
        if job.wait(timeout):
            lats[i] = time.monotonic() - t0

    for i, board in enumerate(boards):
        if latency:
            t = threading.Thread(target=fire, args=(i, board), daemon=True)
        else:
            job = engine.submit(np.asarray(board, np.int32))
            jobs[i] = job
            t = threading.Thread(target=waiter, args=(i, job), daemon=True)
        t.start()
        threads.append(t)
        if i + 1 < len(boards):
            time.sleep(gaps[i])
    for t in threads:
        t.join(timeout)
    return lats, jobs


def _corpus(n_jobs: int):
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    return [np.asarray(HARD_9[i % len(HARD_9)]) for i in range(n_jobs)]


def parse_mix(spec: str) -> dict:
    """``easy:N,hard:M,repeat:R`` -> counts dict (missing tiers = 0)."""
    mix = {"easy": 0, "hard": 0, "repeat": 0}
    for part in spec.split(","):
        try:
            tier, count = part.split(":")
            if tier.strip() not in mix:
                raise ValueError
            mix[tier.strip()] = int(count)
        except ValueError:
            raise SystemExit(
                f"bad --mix component {part!r}: expected easy:N,hard:M,repeat:R"
            ) from None
    if sum(mix.values()) < 1:
        raise SystemExit("--mix needs at least one board")
    return mix


def _mix_spec(mix: dict) -> str:
    """Canonical spelling of a mix-counts dict (``easy:N,hard:M,repeat:R``
    in fixed order) — workload traces store this normalized form so
    regress.py can compare it against a live artifact's raw ``--mix``
    string whatever order the operator typed."""
    return f"easy:{mix['easy']},hard:{mix['hard']},repeat:{mix['repeat']}"


def mixed_corpus(mix: dict, seed: int):
    """A realistic mixed-difficulty arrival stream (ISSUE 14 satellite):

    * ``easy``: distinct generated puzzles with generous clues — the
      propagation/native tier's traffic.
    * ``hard``: the published hard benchmark boards first (distinct
      orbits), then distinct sparse generated puzzles — the device tier.
    * ``repeat``: a random *symmetry transform* of a random already-sent
      board — the published-puzzle aliasing the canonical cache
      collapses (never a byte-identical resubmit, always an equivalent).

    Returns ``(boards, tiers)`` with tiers shuffled deterministically in
    ``seed`` (a repeat slot before any board was sent becomes an easy).
    """
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.serving.frontdoor.canonical import (
        apply_transform,
        random_transform,
    )
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, make_puzzle

    rng = np.random.default_rng(seed)
    tiers = (
        ["easy"] * mix["easy"] + ["hard"] * mix["hard"]
        + ["repeat"] * mix["repeat"]
    )
    rng.shuffle(tiers)
    boards, out_tiers = [], []
    n_easy = n_hard = 0
    for tier in tiers:
        if tier == "repeat" and not boards:
            tier = "easy"
        if tier == "easy":
            b = make_puzzle(SUDOKU_9, seed=seed + 1000 + n_easy, n_clues=38)
            n_easy += 1
        elif tier == "hard":
            if n_hard < len(HARD_9):
                b = np.asarray(HARD_9[n_hard])
            else:
                b = make_puzzle(SUDOKU_9, seed=seed + 5000 + n_hard, n_clues=24)
            n_hard += 1
        else:
            src = boards[int(rng.integers(len(boards)))]
            b = apply_transform(src, random_transform(SUDOKU_9, rng))
        boards.append(np.asarray(b, np.int64))
        out_tiers.append(tier)
    return boards, out_tiers


def _grouped_percentiles(lats, keys) -> dict:
    """Per-group latency percentiles, skipping empty groups and jobs
    that missed the timeout (inf)."""
    out = {}
    groups = sorted(set(keys))
    for grp in groups:
        sel = [
            lats[i]
            for i, k in enumerate(keys)
            if k == grp and lats[i] != float("inf")
        ]
        if sel:
            out[str(grp)] = _percentiles(sel)
    return out


def compare_poisson(
    n_jobs: int = 48,
    mean_gap_s: float = 0.05,
    handicap_s: float = 0.05,
    seed: int = 7,
    chunk_steps: int = 8,
    mix: Optional[dict] = None,
    record_workload: bool = False,
    latency_mode: bool = False,
    branch: str = "minrem",
) -> dict:
    """One A/B: identical arrival schedule against a static-flight engine
    and a resident-flight engine (same solver config, same chunk
    granularity, same handicap).

    With ``mix`` (parse_mix counts), the corpus is the mixed-difficulty
    stream from :func:`mixed_corpus` and BOTH engines run behind the
    front door (``serving/frontdoor``) — the configuration a
    million-user node actually serves.  Per-route and per-tier
    percentiles land beside the overall numbers: cache/native routes
    never pay the handicapped device fetch seam, so no dispatch floor
    applies to them.

    ``latency_mode=True`` adds a third pass over the same schedule: an
    engine with the serving megastep installed (``latency_mode=True``
    plus a default ``MegastepConfig``), each arrival submitted with the
    per-request ``latency`` flag so hard boards fly one-sync-per-flight.
    Its quantiles land in ``out['megastep']`` beside the flight
    counters and the ``frontdoor_megastep_ms`` histogram.

    ``record_workload=True`` captures the RESIDENT run (the production
    engine shape) as a versioned workload trace (``dsst-workload/1``,
    ``out['workload']``): per-job arrival offset, board payload, mix
    tier, measured route/verdict/wall — everything
    ``benchmarks/replay.py`` needs to re-run the exact traffic as a
    deterministic simnet capacity experiment.
    """
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

    cfg = SolverConfig(min_lanes=8, stack_slots=16, branch=branch)
    tiers = None
    if mix is not None:
        boards, tiers = mixed_corpus(mix, seed)
        n_jobs = len(boards)
    else:
        boards = _corpus(n_jobs)
    out: dict = {
        "jobs": n_jobs,
        "mean_gap_ms": mean_gap_s * 1e3,
        "handicap_ms": handicap_s * 1e3,
    }
    if mix is not None:
        out["mix"] = dict(mix)

    def _make_frontdoor():
        if mix is None:
            return None
        from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
            FrontDoorConfig,
        )

        return FrontDoorConfig()

    def _warm(engine):
        # Warm the compile caches so both sides measure scheduling, not
        # XLA — bypassing the front door so the warm board never seeds
        # the measured run's result cache.
        w = engine.submit(boards[0], frontdoor=False)
        assert w.wait(300)

    def _route_tier_sections(dst: dict, lats, jobs):
        if mix is None:
            return
        dst["routes"] = _grouped_percentiles(
            lats, [j.route or "direct" for j in jobs]
        )
        dst["tiers"] = _grouped_percentiles(lats, tiers)

    def _search_section(dst: dict, jobs):
        # Device search-effort totals (ISSUE 19 satellite): `searched` =
        # jobs that needed at least one branch node (the bulk pipeline's
        # counter, ops/bulk.py), `nodes` = total expanded nodes — the
        # quantity the branch-ordering heads exist to shrink.  Additive
        # artifact keys: regress.py gates the hard tier only when BOTH
        # artifacts carry them.
        def agg(js):
            return {
                "searched": sum(1 for j in js if j.nodes > 0),
                "nodes": int(sum(j.nodes for j in js)),
            }

        dst["search"] = agg(jobs)
        if tiers is not None:
            by_tier: dict = {}
            for t, j in zip(tiers, jobs):
                by_tier.setdefault(t, []).append(j)
            dst["search"]["tiers"] = {
                t: agg(js) for t, js in sorted(by_tier.items())
            }

    static = SolverEngine(
        config=cfg, max_batch=8, handicap_s=handicap_s,
        chunk_steps=chunk_steps, frontdoor=_make_frontdoor(),
    ).start()
    try:
        _warm(static)
        lats, jobs = poisson_load(static, boards, mean_gap_s, seed)
        assert all(j.solved for j in jobs), "static baseline failed a job"
        out["static"] = _percentiles(lats)
        _route_tier_sections(out["static"], lats, jobs)
        _search_section(out["static"], jobs)
        m = static.metrics()
        out["static_walls"] = {
            k: m[k] for k in ("dispatch_wall_ms", "sync_wall_ms") if k in m
        }
    finally:
        static.stop(timeout=2)

    resident_cfg = ResidentConfig(
        job_slots=8,
        gang_lanes=4,
        queue_depth=max(16, n_jobs),
        attach_batch=8,
        chunk_steps=chunk_steps,
    )
    resident = SolverEngine(
        config=cfg,
        max_batch=8,
        handicap_s=handicap_s,
        chunk_steps=chunk_steps,
        resident=resident_cfg,
        frontdoor=_make_frontdoor(),
    ).start()
    try:
        _warm(resident)
        lats, jobs = poisson_load(resident, boards, mean_gap_s, seed)
        assert all(j.solved for j in jobs), "resident engine failed a job"
        if record_workload:
            # The workload trace (dsst-workload/1): the resident run's
            # exact arrival schedule + boards + measured per-job
            # route/verdict/wall.  `params` carries the SAME keys as the
            # --out-json artifact params, so benchmarks/regress.py can
            # prove a replay artifact and a live artifact measured the
            # identical workload.
            offsets = arrival_offsets(len(boards), mean_gap_s, seed)
            out["workload"] = {
                "schema": "dsst-workload/1",
                "params": {
                    "jobs": n_jobs,
                    "mean_gap_ms": mean_gap_s * 1e3,
                    "handicap_ms": handicap_s * 1e3,
                    "chunk_steps": chunk_steps,
                    "seed": seed,
                    **({"mix": _mix_spec(mix)} if mix is not None else {}),
                },
                "engine": "resident",
                "job_slots": resident_cfg.job_slots,
                "queue_depth": resident_cfg.queue_depth,
                "jobs_trace": [
                    {
                        "offset_ms": round(offsets[i] * 1e3, 3),
                        "tier": tiers[i] if tiers is not None else "hard",
                        "board": np.asarray(boards[i]).tolist(),
                        "route": jobs[i].route or "direct",
                        "wall_ms": (
                            None if lats[i] == float("inf")
                            else round(lats[i] * 1e3, 3)
                        ),
                        "solved": bool(jobs[i].solved),
                        "unsat": bool(jobs[i].unsat),
                    }
                    for i in range(len(boards))
                ],
            }
        out["resident"] = _percentiles(lats)
        _route_tier_sections(out["resident"], lats, jobs)
        _search_section(out["resident"], jobs)
        m_full = resident.metrics()
        # A mixed corpus may route every board away from the device, in
        # which case no resident flight was ever built.
        rm = m_full.get("resident", {}).get("9x9", {})
        out["resident_metrics"] = rm
        if "frontdoor" in m_full:
            out["frontdoor"] = m_full["frontdoor"]
        # Normalized-artifact fields (--out-json / benchmarks/regress.py):
        # the phase histograms (mergeable obs/hist.py dicts) and the live
        # rpc_floor estimate from the run's chunk.sync samples.
        out["hist"] = m_full.get("hist")
        out["rpc_floor_ms"] = m_full.get("rpc_floor_ms")
        # The resident flight's own overlap split: chunk_wall_ms IS its
        # per-round status-sync wall; dispatch_wall_ms its async enqueues.
        out["resident_walls"] = {
            k: v
            for k, v in (
                ("dispatch_wall_ms", rm.get("dispatch_wall_ms")),
                ("sync_wall_ms", rm.get("chunk_wall_ms")),
            )
            if v is not None
        }
    finally:
        resident.stop(timeout=2)

    if latency_mode:
        from distributed_sudoku_solver_tpu.serving.megastep import (
            MegastepConfig,
        )

        mega = SolverEngine(
            config=cfg,
            max_batch=8,
            handicap_s=handicap_s,
            chunk_steps=chunk_steps,
            latency_mode=True,
            megastep=MegastepConfig(),
            frontdoor=_make_frontdoor(),
        ).start()
        try:
            # Warm the megastep jit (attach/advance/verdict) the same way
            # the other sides warm theirs — off the front door.
            w = mega.submit(boards[0], frontdoor=False, latency=True)
            assert w.wait(300)
            lats, jobs = poisson_load(
                mega, boards, mean_gap_s, seed, latency=True
            )
            assert all(
                j is not None and j.solved for j in jobs
            ), "megastep engine failed a job"
            out["megastep"] = _percentiles(lats)
            _route_tier_sections(out["megastep"], lats, jobs)
            _search_section(out["megastep"], jobs)
            mm = mega.metrics()
            out["megastep_metrics"] = mm.get("megastep", {}).get("9x9", {})
            out["megastep_metrics"]["unfit"] = mm.get("megastep_unfit", 0)
            # The per-route histogram: ONE sample per flight — the whole
            # point.  Its count vs the chunked sides' chunk.sync counts
            # is the measured sync-elimination.
            out["megastep_hist"] = {
                "frontdoor_megastep_ms": (mm.get("hist") or {}).get(
                    "frontdoor_megastep_ms"
                )
            }
        finally:
            mega.stop(timeout=2)

    for q in ("p50_ms", "p95_ms", "p99_ms"):
        if out["resident"][q] > 0:
            out[f"speedup_{q[:-3]}"] = round(
                out["static"][q] / out["resident"][q], 2
            )
        if latency_mode and out["megastep"][q] > 0:
            # vs the STATIC side: the chunked baseline the ISSUE's
            # "kill the dispatch floor" claim is measured against.
            out[f"megastep_speedup_{q[:-3]}"] = round(
                out["static"][q] / out["megastep"][q], 2
            )
    return out


def ring_pass(
    mix: dict,
    mean_gap_s: float,
    handicap_s: float,
    chunk_steps: int,
    seed: int,
    ring_nodes: int = 3,
    timeout: float = 600.0,
) -> dict:
    """The DHT tier (ISSUE 17 satellite): the SAME mixed-difficulty stream,
    round-robined across a ``ring_nodes``-member gossip ring over
    ``cluster/simnet.py`` — each member a full front-door engine whose L2
    seam reads through the cluster-wide result cache.

    Measured: the **cluster-cache hit rate** (fraction of submissions
    answered from cache, L1 or L2, anywhere in the ring) against the
    **best per-node rate** from a CONTROL pass — the identical stream,
    identically round-robined, over ``ring_nodes`` *independent* front
    doors with no cluster behind them.  The control is what makes the
    comparison honest: inside the DHT run every L2 hit is promoted into
    the requester's L1, so the ring's own L1 rates are themselves a
    product of the DHT and cannot serve as the no-DHT baseline.
    Round-robin means each member sees only 1/``ring_nodes`` of every
    repeated orbit — the gap between the two rates IS the value of
    sharing fills through the DHT.

    Wire delivery and gossip run on the simnet virtual clock (pumped from
    a background thread); engine device loops stay on the wall clock, as
    everywhere in the simnet lane.
    """
    from distributed_sudoku_solver_tpu.cluster.node import (
        ClusterConfig,
        ClusterNode,
    )
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
        FrontDoorConfig,
    )

    boards, tiers = mixed_corpus(mix, seed)
    cfg = SolverConfig(min_lanes=8, stack_slots=16)

    def _engine() -> SolverEngine:
        return SolverEngine(
            config=cfg,
            max_batch=8,
            handicap_s=handicap_s,
            chunk_steps=chunk_steps,
            frontdoor=FrontDoorConfig(),
        ).start()

    def _submit_round_robin(submit_fns) -> list:
        rng = random.Random(seed)
        jobs = []
        for i, board in enumerate(boards):
            jobs.append(submit_fns[i % len(submit_fns)](board))
            time.sleep(rng.expovariate(1.0 / mean_gap_s))
        deadline = time.monotonic() + timeout
        for j in jobs:
            assert j.wait(max(0.0, deadline - time.monotonic())), (
                "ring job never resolved"
            )
            assert j.solved or j.unsat, f"ring job failed: {j.error!r}"
        return jobs

    # Control pass FIRST (it also warms the jit caches for the ring
    # pass): independent front doors, no cluster — each member's cache
    # fills only from its own 1/N of the stream.
    solo = [_engine() for _ in range(ring_nodes)]
    best_solo = 0.0
    solo_rates = []
    try:
        w = solo[0].submit(boards[0], frontdoor=False)
        assert w.wait(300), "control warm-up solve failed"
        _submit_round_robin([e.submit for e in solo])
        for e in solo:
            fd = e.metrics()["frontdoor"]
            n_jobs = sum(fd["routes"].values())
            rate = (fd["routes"]["cache"] / n_jobs) if n_jobs else 0.0
            solo_rates.append(round(rate, 4))
            best_solo = max(best_solo, rate)
    finally:
        for e in solo:
            e.stop(timeout=2)

    ccfg = ClusterConfig(
        heartbeat_s=0.25,
        fail_factor=8.0,
        io_timeout_s=2.0,
        needwork=False,
        progress_interval_s=0.0,
        retry_delay_s=0.1,
        tombstone_probe_s=600.0,
    )
    net = SimNet()
    nodes: list = []
    try:
        for i in range(ring_nodes):
            nodes.append(
                ClusterNode(
                    _engine(),
                    anchor=nodes[0].addr if nodes else None,
                    config=ccfg,
                    transport=net.transport(),
                    clock=net.clock,
                ).start()
            )
        assert wait_until(
            net,
            lambda: all(len(n.network) == ring_nodes for n in nodes),
            timeout=120,
        ), "gossip ring never formed"

        for n in nodes:  # warm the compile caches off the front door
            w = n.engine.submit(boards[0], frontdoor=False)
            assert w.wait(300), "ring warm-up solve failed"

        # Pump virtual time while real submissions fire: gossip beats,
        # retry sleeps and CACHE_PUT backoffs live on the simnet clock.
        stop_pump = threading.Event()

        def _pump():
            while not stop_pump.is_set():
                net.advance(0.25)
                stop_pump.wait(0.002)

        pump = threading.Thread(target=_pump, name="bench-ring-pump")
        pump.start()
        try:
            _submit_round_robin([n.engine.submit for n in nodes])
        finally:
            stop_pump.set()
            pump.join()

        per_node: dict = {}
        cache_routed = 0
        l2 = {
            "lookups": 0, "local_hits": 0, "remote_hits": 0,
            "negative_hits": 0, "misses": 0, "puts_applied": 0,
            "gets_served": 0, "remote_errors": 0,
        }
        for n in nodes:
            fd = n.engine.frontdoor.metrics()
            c = fd["cache"]
            cache_routed += fd["routes"]["cache"]
            dm = n.dcache.metrics()
            for k in l2:
                l2[k] += dm[k]
            per_node[n.addr_s] = {
                "jobs": sum(fd["routes"].values()),
                "cache_routed": fd["routes"]["cache"],
                "l1_hits": c["hits"],
                "cluster_hits": fd["cluster_hits"],
            }
        return {
            "nodes": ring_nodes,
            "jobs": len(boards),
            "mix": _mix_spec(mix),
            # Cache-answered fraction across the whole ring (L1 or L2) —
            # the rate a client sees wherever its request lands.
            "cluster_hit_rate": round(cache_routed / len(boards), 4),
            # The control pass's luckiest member: the ceiling a DHT-less
            # deployment of the same ring could reach on this stream.
            "best_node_hit_rate": round(best_solo, 4),
            "solo_node_hit_rates": solo_rates,
            "l2": l2,
            "per_node": per_node,
        }
    finally:
        for n in nodes:
            n.kill()
            n.engine.stop(timeout=2)
        net.close()


def mesh_pass(
    n_jobs: int,
    mean_gap_s: float,
    handicap_s: float,
    chunk_steps: int,
    seed: int,
    mesh_devices: int,
    job_slots: int = 8,
    timeout: float = 600.0,
) -> dict:
    """The pod-scale tier (round 21): the all-hard Poisson stream against
    ONE resident engine whose flight is sharded over ``mesh_devices``
    host-platform devices (``serving/mesh_scheduler.py``).

    ``job_slots`` is the PER-SHARD slot count, so the admission pool is
    ``job_slots * mesh_devices`` — the thing that scales.  A saturating
    arrival stream (mean gap well under the flight wall) then measures
    aggregate capacity: ``boards_per_s`` is jobs over the drain wall, and
    the 1 -> 2 -> 4 scaling table in BENCHMARKS.md is three runs of this
    pass (one process each — the forced device count is fixed at jax
    init).  ``mesh_devices=1`` runs the single-chip resident flight with
    the same per-shard slot count: the honest scaling baseline.

    CPU-mesh caveat: forced host-platform devices share one socket (ONE
    core in the reference container), so per-chunk COMPUTE grows ~linearly
    with the device count here instead of staying flat the way real chips
    would.  The pass therefore runs with a deliberately high per-fetch
    sync floor (``--mesh-handicap-ms``, default 300) so the chunk cadence
    is floor-dominated — the regime a real pod serves in, where the
    scaling comes from slot-pool capacity (``slots / (chunks_per_job x
    cadence)``), not per-chunk compute.  ``attach_batch`` is sized to the
    FULL pool: a refill batch smaller than the pool caps completions per
    chunk at the refill rate and silently turns the measurement
    admission-bound (observed: 8-per-chunk refill capped a 32-slot mesh
    at ~30 boards/s that admits ~47 with full-pool refill).
    """
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

    boards = _corpus(n_jobs)
    cfg = SolverConfig(min_lanes=8, stack_slots=16)
    rc = ResidentConfig(
        job_slots=job_slots,
        gang_lanes=4,
        queue_depth=max(16, n_jobs),
        attach_batch=job_slots * max(1, mesh_devices),
        chunk_steps=chunk_steps,
        mesh_devices=mesh_devices if mesh_devices > 1 else 0,
    )
    eng = SolverEngine(
        config=cfg, max_batch=8, handicap_s=handicap_s,
        chunk_steps=chunk_steps, resident=rc,
    ).start()
    try:
        w = eng.submit(boards[0])
        assert w.wait(300), "mesh warm-up solve failed"
        t0 = time.monotonic()
        lats, jobs = poisson_load(
            eng, boards, mean_gap_s, seed, timeout=timeout
        )
        wall = time.monotonic() - t0
        assert all(j.solved for j in jobs), "mesh engine failed a job"
        m = eng.metrics()
        rm = m["resident"]["9x9"]
        if mesh_devices > 1 and "mesh" not in rm:
            # The scaling claim is meaningless if the engine silently
            # degraded to the single-chip flight (too few devices).
            raise SystemExit(
                f"mesh pass degraded to single-chip (mesh_unfit="
                f"{m.get('mesh_unfit')}): is "
                f"--xla_force_host_platform_device_count >= {mesh_devices}?"
            )
        return {
            "devices": mesh_devices,
            "job_slots_per_shard": job_slots,
            "slots": rm["slots"],
            **_percentiles(lats),
            "drain_wall_s": round(wall, 3),
            "boards_per_s": round(n_jobs / wall, 2),
            **({"mesh_metrics": rm["mesh"]} if "mesh" in rm else {}),
        }
    finally:
        eng.stop(timeout=2)


def recovery_pass(
    n_jobs: int,
    mean_gap_s: float,
    handicap_s: float,
    chunk_steps: int,
    seed: int,
    kill_at_s: float,
    restart_after_s: float,
) -> dict:
    """Kill/restart durability measurement (ISSUE 20): a journal-backed
    engine takes the Poisson stream, is killed ABRUPTLY ``kill_at_s``
    seconds in — the WAL's fsync batcher dies mid-buffer and in-flight
    finalizations never reach the disk, the crash a clean shutdown would
    hide — then after ``restart_after_s`` a fresh engine boots over the
    same WAL directory, replays every unresolved entry through the
    normal submit seam, and the replay wall is measured.  The numbers
    the durability table wants: how many accepted jobs the crash caught,
    and how long the restart took to pay them all off.
    """
    import shutil
    import tempfile

    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.journal import Journal

    cfg = SolverConfig(min_lanes=8, stack_slots=16)
    boards = _corpus(n_jobs)
    gaps = poisson_gaps(len(boards), mean_gap_s, seed)
    wal_dir = tempfile.mkdtemp(prefix="dsst-wal-")
    try:
        jr = Journal(wal_dir)
        eng = SolverEngine(
            config=cfg,
            max_batch=8,
            handicap_s=handicap_s,
            chunk_steps=chunk_steps,
            journal=jr,
        ).start()
        warm = eng.submit(boards[0])  # compile warm; resolves pre-kill
        assert warm.wait(300)
        submitted = 0
        deadline = time.monotonic() + kill_at_s
        for i, board in enumerate(boards):
            if time.monotonic() >= deadline:
                break
            eng.submit(np.asarray(board, np.int32), job_uuid=f"rec-{i}")
            submitted += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            if i + 1 < len(boards):
                time.sleep(min(gaps[i], remaining))
        # The crash: detach the journal so post-mortem finalizations never
        # reach the WAL, stop the batcher WITHOUT the final drain (its
        # buffered resolves are lost), then tear the engine down.  What
        # survives on disk is what a kill -9 would have left.
        eng.journal = None
        jr._stop.set()
        jr._batcher.join(timeout=5)
        eng.stop(timeout=2)
        time.sleep(restart_after_s)

        jr2 = Journal(wal_dir)
        uuids = [ev["uuid"] for ev in jr2.unresolved()]
        eng2 = SolverEngine(
            config=cfg,
            max_batch=8,
            handicap_s=handicap_s,
            chunk_steps=chunk_steps,
            journal=jr2,
        ).start()
        try:
            t0 = time.monotonic()
            n = eng2.recover()
            handles = [eng2._dup_job(u) for u in uuids]
            ok = all(
                h is not None and h.wait(600) and (h.solved or h.unsat)
                for h in handles
            )
            recovery_wall = time.monotonic() - t0
            jr2.sync_now()
            leftover = len(jr2.unresolved())
        finally:
            eng2.stop(timeout=2)
            jr2.shutdown()
        return {
            "kill_at_ms": round(kill_at_s * 1e3, 3),
            "restart_after_ms": round(restart_after_s * 1e3, 3),
            "jobs_submitted": submitted,
            "jobs_recovered": int(n),
            "recovery_wall_ms": round(recovery_wall * 1e3, 3),
            "replayed_ok": bool(ok),
            "wal_leftover": int(leftover),
        }
    finally:
        shutil.rmtree(wal_dir, ignore_errors=True)


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--jobs", type=int, default=48)
    ap.add_argument("--mean-ms", type=float, default=50.0)
    ap.add_argument("--handicap-ms", type=float, default=50.0)
    ap.add_argument("--chunk-steps", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--branch",
        default="minrem",
        help="branch-ordering rule for the device engines: a legacy rule "
        "(minrem/first/mixed/minrem-desc) or a scored head "
        "(head:minrem/head:cw-slack/head:mlp, ops/ordering.py).  A "
        "non-default rule lands in the artifact params, so regress.py "
        "refuses to compare across rules (different search tree, not a "
        "regression)",
    )
    ap.add_argument(
        "--mix",
        default=None,
        help="mixed-difficulty corpus 'easy:N,hard:M,repeat:R' (repeats "
        "are random symmetry transforms of already-sent boards); both "
        "engines then run behind the front door (serving/frontdoor) and "
        "per-route/per-tier percentiles are reported.  --jobs is ignored "
        "(the mix counts size the corpus).  Artifacts with different "
        "mixes are non-comparable in benchmarks/regress.py (exit 2)",
    )
    ap.add_argument(
        "--ring",
        type=int,
        default=0,
        metavar="N",
        help="also run the mixed stream round-robin across an N-member "
        "gossip ring over cluster/simnet (ISSUE 17): each member is a "
        "full front-door engine reading through the cluster-wide result "
        "cache; reports the ring's cluster-cache hit rate vs the best "
        "per-node rate of a no-DHT control pass (same stream over N "
        "independent front doors).  Requires --mix (the repeats are what "
        "the cache shares); adds a 'ring' section to the report/artifact "
        "which benchmarks/regress.py gates whenever both artifacts "
        "carry it with the same node count",
    )
    ap.add_argument(
        "--mesh-devices",
        type=int,
        default=0,
        metavar="N",
        help="also measure the pod-scale resident tier "
        "(serving/mesh_scheduler.py): the same all-hard stream against a "
        "mesh-resident engine with N forced host-platform devices (set "
        "via XLA_FLAGS before jax initializes — one process per N) and 8 "
        "job slots PER SHARD, so the admission pool is 8*N.  N=1 runs "
        "the single-chip resident flight with the same per-shard slots: "
        "the scaling baseline.  Adds a 'mesh' section to the "
        "report/artifact which benchmarks/regress.py gates whenever both "
        "artifacts carry it with the same device count (mismatched "
        "counts are non-comparable: exit 2)",
    )
    ap.add_argument(
        "--mesh-jobs",
        type=int,
        default=288,
        metavar="J",
        help="job count for the mesh pass only (default 288): large "
        "relative to the biggest admission pool so the stream saturates "
        "and ramp/drain transients amortize — the capacity regime the "
        "scaling table measures.  The main pass keeps --jobs",
    )
    ap.add_argument(
        "--mesh-handicap-ms",
        type=float,
        default=300.0,
        metavar="MS",
        help="per-fetch sync floor for the mesh pass only (default 300): "
        "high enough that the chunk cadence is floor-dominated on a "
        "forced-host CPU mesh, where every extra device adds real "
        "per-chunk compute on the same socket instead of parallel chips "
        "(the regime caveat in BENCHMARKS.md).  The main pass keeps "
        "--handicap-ms",
    )
    ap.add_argument(
        "--latency-mode",
        action="store_true",
        help="also measure a third engine with the serving megastep "
        "(serving/megastep.py): one donated dispatch, in-graph early "
        "exit, ONE host sync per flight — adds a 'megastep' section to "
        "the report/artifact which benchmarks/regress.py gates whenever "
        "both artifacts carry it",
    )
    ap.add_argument(
        "--kill-at",
        type=float,
        default=None,
        metavar="S",
        help="durability pass (ISSUE 20): run the Poisson stream against "
        "a journal-backed engine, kill it ABRUPTLY S seconds in (the "
        "WAL fsync batcher dies mid-buffer — a crash, not a drain), "
        "restart over the same WAL directory after --restart-after "
        "seconds, and measure the replay: jobs recovered + recovery "
        "wall land in a 'recovery' artifact section, and the kill "
        "params land in the artifact params — so benchmarks/regress.py "
        "refuses to gate a kill-run artifact (exit 2: the stream was "
        "truncated mid-run, its quantiles are not a workload measure)",
    )
    ap.add_argument(
        "--restart-after",
        type=float,
        default=1.0,
        metavar="S",
        help="seconds between the kill and the restart of the "
        "--kill-at durability pass (default 1.0)",
    )
    ap.add_argument("--json", action="store_true")
    ap.add_argument(
        "--trace-out",
        default=None,
        help="write the run's flight-recorder trace as Chrome-trace JSON "
        "(open in Perfetto; validate with "
        "`python -m distributed_sudoku_solver_tpu.obs.traceck <file>`)",
    )
    ap.add_argument(
        "--out-json",
        default=None,
        help="write a normalized result artifact (p50/p95 per engine, "
        "rpc_floor estimate, phase histograms) for "
        "benchmarks/regress.py — the bench-trajectory gate",
    )
    ap.add_argument(
        "--workload-out",
        default=None,
        help="record the resident run as a versioned workload trace "
        "(dsst-workload/1: arrival offsets, board payloads, per-job "
        "tier/route/verdict/wall) for benchmarks/replay.py — the "
        "deterministic trace-replay capacity planner",
    )
    args = ap.parse_args()
    if args.ring and not args.mix:
        ap.error("--ring requires --mix (repeats are what the cache shares)")
    if args.ring and args.ring < 3:
        ap.error("--ring needs at least 3 members to measure sharing")
    if args.mesh_devices < 0:
        ap.error("--mesh-devices must be >= 0")
    if args.kill_at is not None and args.kill_at <= 0:
        ap.error("--kill-at must be > 0 seconds into the stream")
    if args.restart_after < 0:
        ap.error("--restart-after must be >= 0")
    if args.mesh_devices:
        # Must land before ANY jax import (everything jax-touching in this
        # file is deliberately lazy): the forced host-platform device
        # count is read once at backend init and fixed for the process.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.mesh_devices}"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    rec = None
    if args.trace_out:
        from distributed_sudoku_solver_tpu.obs import trace as trace_mod

        rec = trace_mod.TraceRecorder(ring=1 << 16)
        trace_mod.install(rec)
    # Compile accounting for the whole run (obs/compilewatch.py): any
    # backend compile the run pays — cold process, invalidated .cache/xla
    # after an HLO change — lands in the artifact's `compile` section, so
    # benchmarks/regress.py can LABEL a cold-cache run instead of
    # silently comparing compile noise inside the tolerance band.
    from distributed_sudoku_solver_tpu.obs import (
        compilewatch as compilewatch_mod,
    )

    # A bench run's compiles are accounting, never an alarm: the warmup
    # window spans the whole run.
    watch = compilewatch_mod.CompileWatch(warmup_s=1e9)
    compilewatch_mod.install(watch)
    try:
        out = compare_poisson(
            n_jobs=args.jobs,
            mean_gap_s=args.mean_ms / 1e3,
            handicap_s=args.handicap_ms / 1e3,
            seed=args.seed,
            chunk_steps=args.chunk_steps,
            mix=parse_mix(args.mix) if args.mix else None,
            record_workload=bool(args.workload_out),
            latency_mode=args.latency_mode,
            branch=args.branch,
        )
        if args.mesh_devices:
            out["mesh"] = mesh_pass(
                n_jobs=args.mesh_jobs,
                mean_gap_s=args.mean_ms / 1e3,
                handicap_s=args.mesh_handicap_ms / 1e3,
                chunk_steps=args.chunk_steps,
                seed=args.seed,
                mesh_devices=args.mesh_devices,
            )
        if args.kill_at is not None:
            out["recovery"] = recovery_pass(
                n_jobs=args.jobs,
                mean_gap_s=args.mean_ms / 1e3,
                handicap_s=args.handicap_ms / 1e3,
                chunk_steps=args.chunk_steps,
                seed=args.seed,
                kill_at_s=args.kill_at,
                restart_after_s=args.restart_after,
            )
        if args.ring:
            out["ring"] = ring_pass(
                parse_mix(args.mix),
                mean_gap_s=args.mean_ms / 1e3,
                handicap_s=args.handicap_ms / 1e3,
                chunk_steps=args.chunk_steps,
                seed=args.seed,
                ring_nodes=args.ring,
            )
    finally:
        compilewatch_mod.install(None)
        if rec is not None:
            from distributed_sudoku_solver_tpu.obs import trace as trace_mod

            trace_mod.install(None)
            doc = rec.perfetto()
            with open(args.trace_out, "w") as f:
                json.dump(doc, f)
            print(
                f"trace written: {args.trace_out} "
                f"({len(doc['traceEvents'])} events)",
                file=sys.stderr,
            )
    wm = watch.metrics()
    out["compile"] = {
        "programs": {
            name: {
                k: v for k, v in rec_.items() if k != "wall_ms"  # hists stay off the artifact
            }
            for name, rec_ in wm["programs"].items()
        },
        "compiles_total": wm["compiles_total"],
        "wall_ms_total": round(
            sum(
                rec_.get("wall_ms_total", 0.0)
                for rec_ in wm["programs"].values()
            ),
            3,
        ),
        "cache": wm["cache"],
        # Cold = the measured run paid executable builds/loads inside its
        # window; a warm process (or fully warm persistent cache with a
        # warm jit cache) reports 0 and stays label-free in regress.
        "cold": wm["compiles_total"] > 0,
    }
    if out["compile"]["cold"]:
        print(
            f"cold-cache run: {wm['compiles_total']} compile(s), "
            f"{out['compile']['wall_ms_total']:.0f} ms compile wall "
            "inside the measured window",
            file=sys.stderr,
        )
    if args.workload_out:
        workload = out.pop("workload")
        tmp = args.workload_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(workload, f)
        os.replace(tmp, args.workload_out)  # atomic like the artifact
        print(
            f"workload trace written: {args.workload_out} "
            f"({len(workload['jobs_trace'])} jobs)",
            file=sys.stderr,
        )
    if args.out_json:
        artifact = {
            # Versioned so regress.py can refuse cross-schema compares.
            "schema": "dsst-bench-poisson/1",
            "params": {
                "jobs": out["jobs"],
                "mean_gap_ms": args.mean_ms,
                "handicap_ms": args.handicap_ms,
                "chunk_steps": args.chunk_steps,
                "seed": args.seed,
                # Only present on mixed-corpus runs: pre-round-17
                # artifacts stay byte-compatible (and comparable) for
                # the default all-hard corpus.
                **({"mix": args.mix} if args.mix else {}),
                # Only present for non-default branch ordering (round
                # 22): a different rule explores a different search
                # tree, so regress.py refuses the cross-rule compare
                # via the params mismatch; default-rule artifacts stay
                # comparable to every earlier round.
                **(
                    {"branch": args.branch}
                    if args.branch != "minrem"
                    else {}
                ),
                # Only present on kill/restart durability runs (ISSUE
                # 20): the keys mark the artifact's stream as truncated
                # mid-run, which regress.py refuses to gate (exit 2).
                **(
                    {
                        "kill_at_s": args.kill_at,
                        "restart_after_s": args.restart_after,
                    }
                    if args.kill_at is not None
                    else {}
                ),
            },
            "static": out["static"],
            "resident": out["resident"],
            "speedups": {
                q: out.get(f"speedup_{q}") for q in ("p50", "p95", "p99")
            },
            "rpc_floor_ms": out.get("rpc_floor_ms"),
            "hist": out.get("hist"),
            "compile": out.get("compile"),
            # Latency-mode tier (round 19): same quantile shape as
            # static/resident, so regress.py gates it whenever BOTH
            # artifacts carry it; params stay unchanged because the
            # megastep pass is ADDITIVE — the static/resident sections
            # still measured the identical workload and remain
            # comparable to pre-round-19 artifacts.
            **(
                {
                    "megastep": out["megastep"],
                    "megastep_detail": {
                        "metrics": out.get("megastep_metrics"),
                        "hist": out.get("megastep_hist"),
                        "speedups_vs_static": {
                            q: out.get(f"megastep_speedup_{q}")
                            for q in ("p50", "p95", "p99")
                        },
                    },
                }
                if args.latency_mode
                else {}
            ),
            # The DHT tier (round 20): additive like megastep — params
            # stay unchanged, regress.py gates the ring hit rates only
            # when both artifacts carry the section with equal node
            # counts.
            **({"ring": out["ring"]} if args.ring else {}),
            # The pod-scale tier (round 21): additive — regress.py gates
            # boards_per_s/quantiles only when both artifacts carry the
            # section with the SAME device count (a 2-device artifact vs
            # a 4-device artifact is a different machine shape, not a
            # regression: exit 2).
            **({"mesh": out["mesh"]} if args.mesh_devices else {}),
            # The durability pass (ISSUE 20): recovery time + jobs
            # recovered after an abrupt kill.  Additive like the tiers
            # above, but the params kill keys make the whole artifact
            # non-comparable in regress.py — the measured stream was
            # truncated at the kill, so its static/resident quantiles
            # describe an interrupted workload, not the benchmark's.
            **(
                {"recovery": out["recovery"]}
                if args.kill_at is not None
                else {}
            ),
        }
        tmp = args.out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f)
        os.replace(tmp, args.out_json)  # atomic like the flight recorder
        print(f"artifact written: {args.out_json}", file=sys.stderr)
    if args.json:
        print(json.dumps(out))
        return
    print(
        f"Poisson load: {out['jobs']} jobs, mean gap "
        f"{out['mean_gap_ms']:.0f} ms, per-chunk handicap "
        f"{out['handicap_ms']:.0f} ms"
    )
    print(f"{'':<10}{'p50 ms':>10}{'p95 ms':>10}{'p99 ms':>10}{'mean ms':>10}")
    for name in ("static", "resident", "megastep"):
        r = out.get(name)
        if r is None:
            continue
        print(
            f"{name:<10}{r['p50_ms']:>10}{r['p95_ms']:>10}"
            f"{r['p99_ms']:>10}{r['mean_ms']:>10}"
        )
    print(
        "speedup    p50 x{sp50}  p95 x{sp95}  p99 x{sp99}".format(
            sp50=out.get("speedup_p50"),
            sp95=out.get("speedup_p95"),
            sp99=out.get("speedup_p99"),
        )
    )
    if "megastep" in out:
        print(
            "megastep   p50 x{sp50}  p95 x{sp95}  p99 x{sp99}  (vs static)"
            .format(
                sp50=out.get("megastep_speedup_p50"),
                sp95=out.get("megastep_speedup_p95"),
                sp99=out.get("megastep_speedup_p99"),
            )
        )
        msm = out.get("megastep_metrics", {})
        print(
            f"  flights={msm.get('flights')} "
            f"chunks/flight={msm.get('chunks_per_flight')} "
            f"degraded={msm.get('degraded')} "
            f"flight_wall_ms={ (msm.get('flight_wall_ms') or {}).get('p50') }"
        )
    if "mix" in out:
        print(f"mix: {out['mix']}  (resident engine breakdown)")
        for section in ("tiers", "routes"):
            for name, r in sorted(out["resident"].get(section, {}).items()):
                print(
                    f"  {section[:-1]}:{name:<12}{r['p50_ms']:>10}"
                    f"{r['p95_ms']:>10}{r['p99_ms']:>10}{r['mean_ms']:>10}"
                    f"   n={r['jobs']}"
                )
        fd = out.get("frontdoor", {})
        if fd:
            c = fd.get("cache", {})
            print(
                f"  frontdoor: routes={fd.get('routes')} cache_hits={c.get('hits')}"
                f" canonical_dups={c.get('canonical_dups')}"
                f" native_fallback_wins={fd.get('native_fallback_wins')}"
            )
    if "mesh" in out:
        r = out["mesh"]
        print(
            f"mesh ({r['devices']} device(s), {r['slots']} slots): "
            f"{r['boards_per_s']} boards/s over {r['drain_wall_s']} s  "
            f"p50 {r['p50_ms']} ms  p95 {r['p95_ms']} ms"
        )
        mm = r.get("mesh_metrics")
        if mm:
            print(
                f"  ring_shipped={mm['ring_shipped']} "
                f"slot_occupancy={mm['slot_occupancy']} "
                f"rebuilds={mm['rebuilds']}"
            )
    if "ring" in out:
        r = out["ring"]
        print(
            f"ring ({r['nodes']} members, {r['jobs']} jobs round-robin): "
            f"cluster_hit_rate={r['cluster_hit_rate']} vs "
            f"best_node_hit_rate={r['best_node_hit_rate']}"
        )
        print(
            f"  l2: remote_hits={r['l2']['remote_hits']} "
            f"local_hits={r['l2']['local_hits']} "
            f"negative_hits={r['l2']['negative_hits']} "
            f"puts_applied={r['l2']['puts_applied']}"
        )
        if r["cluster_hit_rate"] <= r["best_node_hit_rate"]:
            print(
                "  WARNING: the DHT added nothing over the best member's "
                "own cache on this stream — expected only for repeat-free "
                "mixes",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
