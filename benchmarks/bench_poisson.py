"""Poisson-arrival serving benchmark: resident flights vs static flights.

The round-7 acceptance measurement (ISSUE: continuous-batching resident
flights).  A Poisson arrival process with mean inter-arrival BELOW the
single-flight duration is fired at two engines built identically except for
the scheduler:

* **static**: today's flight loop — each admitted batch launches its own
  frontier and retires whole; an arrival during a full house waits for a
  flight to drain.
* **resident**: the continuous-batching scheduler
  (``serving/scheduler.py``) — one long-lived frontier; arrivals attach to
  recycled job slots between dispatches.

Reported: per-job time-to-solution p50/p95/p99 for both, plus the
improvement ratios.  ``--handicap-ms`` applies the engine's slow-node
simulator to BOTH engines; since round 8 it is charged at the fetch seam
(``serving.engine.host_fetch``) — one sleep per HOST SYNC, which under
the one-fetch-per-chunk contract is one per chunk, but crucially the
sleep now happens while the always-ahead loop's next chunk is already on
the device, exactly as a real RPC fetch floor would (tunnel ~74-122
ms/round trip, BENCHMARKS.md "Measured link").  The round-7 numbers
charged the same floor per chunk but SERIALLY (sleep, dispatch, block,
fetch x5 for free); the round-8 delta vs that table is therefore the
measured value of overlapping the floor with device compute plus
eliminating the extra per-chunk fetches.  ``--handicap-ms 0`` measures
the raw CPU compute-bound case too.  The JSON output includes each
engine's ``dispatch_wall_ms`` / ``sync_wall_ms`` split so the overlap is
directly observable.

Run: ``python benchmarks/bench_poisson.py [--jobs 48] [--mean-ms 50]
[--handicap-ms 50] [--json]``.  The tier-1 smoke and the ``slow``-marked
assertion live in ``tests/test_scheduler.py``.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from any cwd without installing
    sys.path.insert(0, REPO)


def _percentiles(lats) -> dict:
    arr = np.asarray(sorted(lats), float)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 1),
        "p95_ms": round(float(np.percentile(arr, 95)) * 1e3, 1),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 1),
        "mean_ms": round(float(arr.mean()) * 1e3, 1),
        "jobs": len(lats),
    }


def poisson_load(engine, boards, mean_gap_s: float, seed: int = 0,
                 timeout: float = 600.0):
    """Submit ``boards`` with exponential inter-arrival gaps; returns
    ``(latencies_s, jobs)`` where latency is submit -> resolution wall
    (inf for a job that missed ``timeout``)."""
    rng = random.Random(seed)
    jobs: list = []
    lats = [float("inf")] * len(boards)
    threads = []

    def waiter(i, job):
        if job.wait(timeout):
            lats[i] = time.monotonic() - job.submitted_at

    for i, board in enumerate(boards):
        job = engine.submit(np.asarray(board, np.int32))
        jobs.append(job)
        t = threading.Thread(target=waiter, args=(i, job), daemon=True)
        t.start()
        threads.append(t)
        if i + 1 < len(boards):
            time.sleep(rng.expovariate(1.0 / mean_gap_s))
    for t in threads:
        t.join(timeout)
    return lats, jobs


def _corpus(n_jobs: int):
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    return [np.asarray(HARD_9[i % len(HARD_9)]) for i in range(n_jobs)]


def compare_poisson(
    n_jobs: int = 48,
    mean_gap_s: float = 0.05,
    handicap_s: float = 0.05,
    seed: int = 7,
    chunk_steps: int = 8,
) -> dict:
    """One A/B: identical arrival schedule against a static-flight engine
    and a resident-flight engine (same solver config, same chunk
    granularity, same handicap)."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

    cfg = SolverConfig(min_lanes=8, stack_slots=16)
    boards = _corpus(n_jobs)
    out: dict = {
        "jobs": n_jobs,
        "mean_gap_ms": mean_gap_s * 1e3,
        "handicap_ms": handicap_s * 1e3,
    }

    static = SolverEngine(
        config=cfg, max_batch=8, handicap_s=handicap_s, chunk_steps=chunk_steps
    ).start()
    try:
        # Warm the compile caches so both sides measure scheduling, not XLA.
        w = static.submit(boards[0])
        assert w.wait(300)
        lats, jobs = poisson_load(static, boards, mean_gap_s, seed)
        assert all(j.solved for j in jobs), "static baseline failed a job"
        out["static"] = _percentiles(lats)
        m = static.metrics()
        out["static_walls"] = {
            k: m[k] for k in ("dispatch_wall_ms", "sync_wall_ms") if k in m
        }
    finally:
        static.stop(timeout=2)

    resident = SolverEngine(
        config=cfg,
        max_batch=8,
        handicap_s=handicap_s,
        chunk_steps=chunk_steps,
        resident=ResidentConfig(
            job_slots=8,
            gang_lanes=4,
            queue_depth=max(16, n_jobs),
            attach_batch=8,
            chunk_steps=chunk_steps,
        ),
    ).start()
    try:
        w = resident.submit(boards[0])
        assert w.wait(300)
        lats, jobs = poisson_load(resident, boards, mean_gap_s, seed)
        assert all(j.solved for j in jobs), "resident engine failed a job"
        out["resident"] = _percentiles(lats)
        m_full = resident.metrics()
        rm = m_full["resident"]["9x9"]
        out["resident_metrics"] = rm
        # Normalized-artifact fields (--out-json / benchmarks/regress.py):
        # the phase histograms (mergeable obs/hist.py dicts) and the live
        # rpc_floor estimate from the run's chunk.sync samples.
        out["hist"] = m_full.get("hist")
        out["rpc_floor_ms"] = m_full.get("rpc_floor_ms")
        # The resident flight's own overlap split: chunk_wall_ms IS its
        # per-round status-sync wall; dispatch_wall_ms its async enqueues.
        out["resident_walls"] = {
            k: v
            for k, v in (
                ("dispatch_wall_ms", rm.get("dispatch_wall_ms")),
                ("sync_wall_ms", rm.get("chunk_wall_ms")),
            )
            if v is not None
        }
    finally:
        resident.stop(timeout=2)

    for q in ("p50_ms", "p95_ms", "p99_ms"):
        if out["resident"][q] > 0:
            out[f"speedup_{q[:-3]}"] = round(
                out["static"][q] / out["resident"][q], 2
            )
    return out


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--jobs", type=int, default=48)
    ap.add_argument("--mean-ms", type=float, default=50.0)
    ap.add_argument("--handicap-ms", type=float, default=50.0)
    ap.add_argument("--chunk-steps", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--json", action="store_true")
    ap.add_argument(
        "--trace-out",
        default=None,
        help="write the run's flight-recorder trace as Chrome-trace JSON "
        "(open in Perfetto; validate with "
        "`python -m distributed_sudoku_solver_tpu.obs.traceck <file>`)",
    )
    ap.add_argument(
        "--out-json",
        default=None,
        help="write a normalized result artifact (p50/p95 per engine, "
        "rpc_floor estimate, phase histograms) for "
        "benchmarks/regress.py — the bench-trajectory gate",
    )
    args = ap.parse_args()

    rec = None
    if args.trace_out:
        from distributed_sudoku_solver_tpu.obs import trace as trace_mod

        rec = trace_mod.TraceRecorder(ring=1 << 16)
        trace_mod.install(rec)
    # Compile accounting for the whole run (obs/compilewatch.py): any
    # backend compile the run pays — cold process, invalidated .cache/xla
    # after an HLO change — lands in the artifact's `compile` section, so
    # benchmarks/regress.py can LABEL a cold-cache run instead of
    # silently comparing compile noise inside the tolerance band.
    from distributed_sudoku_solver_tpu.obs import (
        compilewatch as compilewatch_mod,
    )

    # A bench run's compiles are accounting, never an alarm: the warmup
    # window spans the whole run.
    watch = compilewatch_mod.CompileWatch(warmup_s=1e9)
    compilewatch_mod.install(watch)
    try:
        out = compare_poisson(
            n_jobs=args.jobs,
            mean_gap_s=args.mean_ms / 1e3,
            handicap_s=args.handicap_ms / 1e3,
            seed=args.seed,
            chunk_steps=args.chunk_steps,
        )
    finally:
        compilewatch_mod.install(None)
        if rec is not None:
            from distributed_sudoku_solver_tpu.obs import trace as trace_mod

            trace_mod.install(None)
            doc = rec.perfetto()
            with open(args.trace_out, "w") as f:
                json.dump(doc, f)
            print(
                f"trace written: {args.trace_out} "
                f"({len(doc['traceEvents'])} events)",
                file=sys.stderr,
            )
    wm = watch.metrics()
    out["compile"] = {
        "programs": {
            name: {
                k: v for k, v in rec_.items() if k != "wall_ms"  # hists stay off the artifact
            }
            for name, rec_ in wm["programs"].items()
        },
        "compiles_total": wm["compiles_total"],
        "wall_ms_total": round(
            sum(
                rec_.get("wall_ms_total", 0.0)
                for rec_ in wm["programs"].values()
            ),
            3,
        ),
        "cache": wm["cache"],
        # Cold = the measured run paid executable builds/loads inside its
        # window; a warm process (or fully warm persistent cache with a
        # warm jit cache) reports 0 and stays label-free in regress.
        "cold": wm["compiles_total"] > 0,
    }
    if out["compile"]["cold"]:
        print(
            f"cold-cache run: {wm['compiles_total']} compile(s), "
            f"{out['compile']['wall_ms_total']:.0f} ms compile wall "
            "inside the measured window",
            file=sys.stderr,
        )
    if args.out_json:
        artifact = {
            # Versioned so regress.py can refuse cross-schema compares.
            "schema": "dsst-bench-poisson/1",
            "params": {
                "jobs": args.jobs,
                "mean_gap_ms": args.mean_ms,
                "handicap_ms": args.handicap_ms,
                "chunk_steps": args.chunk_steps,
                "seed": args.seed,
            },
            "static": out["static"],
            "resident": out["resident"],
            "speedups": {
                q: out.get(f"speedup_{q}") for q in ("p50", "p95", "p99")
            },
            "rpc_floor_ms": out.get("rpc_floor_ms"),
            "hist": out.get("hist"),
            "compile": out.get("compile"),
        }
        tmp = args.out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f)
        os.replace(tmp, args.out_json)  # atomic like the flight recorder
        print(f"artifact written: {args.out_json}", file=sys.stderr)
    if args.json:
        print(json.dumps(out))
        return
    print(
        f"Poisson load: {out['jobs']} jobs, mean gap "
        f"{out['mean_gap_ms']:.0f} ms, per-chunk handicap "
        f"{out['handicap_ms']:.0f} ms"
    )
    print(f"{'':<10}{'p50 ms':>10}{'p95 ms':>10}{'p99 ms':>10}{'mean ms':>10}")
    for name in ("static", "resident"):
        r = out[name]
        print(
            f"{name:<10}{r['p50_ms']:>10}{r['p95_ms']:>10}"
            f"{r['p99_ms']:>10}{r['mean_ms']:>10}"
        )
    print(
        "speedup    p50 x{sp50}  p95 x{sp95}  p99 x{sp99}".format(
            sp50=out.get("speedup_p50"),
            sp95=out.get("speedup_p95"),
            sp99=out.get("speedup_p99"),
        )
    )


if __name__ == "__main__":
    main()
