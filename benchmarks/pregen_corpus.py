"""Pre-generate distinct-board bench corpora into the puzzle cache.

VERDICT r3 #9 (retire the tiling asterisk): the headline bench corpus
becomes 65,536 fully distinct generated puzzles, and the 1M-board
solve-file row gets a fully distinct corpus too.  Generation is ~34 ms per
puzzle single-threaded (dozens of native uniqueness probes per carve,
``utils/puzzles.make_puzzle``), so this script parallelizes across
processes and writes results where the normal cache lookups find them:

* the headline batch lands in the ``puzzle_batch`` on-disk cache under the
  EXACT key that ``bench.py``'s call computes — the bench itself then
  loads it in milliseconds and never generates;
* the 1M solve-file corpus lands as a text file of board lines
  (``utils/dataset`` format), one distinct puzzle per line.

Deterministic: worker i carves seed ``seed + i``, identical to the
sequential ``puzzle_batch`` loop, so the cache it fills is bit-identical
to what an (impractically slow) inline generation would produce.
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python benchmarks/pregen_corpus.py` from anywhere
    sys.path.insert(0, REPO)


def _one(args) -> np.ndarray:
    seed, n_clues, unique = args
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils.puzzles import make_puzzle

    return make_puzzle(SUDOKU_9, seed, n_clues=n_clues, unique=unique)


def _carve(pool, count: int, seed: int, n_clues: int, label: str, unique=True):
    t0 = time.perf_counter()
    out = []
    for i, board in enumerate(
        pool.imap(
            _one,
            ((seed + j, n_clues, unique) for j in range(count)),
            chunksize=64,
        )
    ):
        out.append(board)
        if (i + 1) % 8192 == 0:
            rate = (i + 1) / (time.perf_counter() - t0)
            print(
                f"[{label}] {i + 1}/{count} ({rate:.0f}/s, "
                f"eta {(count - i - 1) / rate / 60:.1f} min)",
                flush=True,
            )
    return np.stack(out)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--headline", type=int, default=65536 - 3)
    ap.add_argument("--solvefile", type=int, default=0)  # e.g. 1_000_000
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--n-clues", type=int, default=24)
    ap.add_argument(
        "--solvefile-unique",
        action="store_true",
        help="uniqueness-probe the solve-file corpus too (30+ ms/puzzle of "
        "native DFS probes per carve — ~9 h for 1M boards on this "
        "container's single core; default skips the probes, which makes "
        "boards possibly multi-solution but still distinct, satisfiable, "
        "and n-clues-given — disclose the distribution wherever measured)",
    )
    ap.add_argument("--workers", type=int, default=min(16, os.cpu_count() or 1))
    args = ap.parse_args()

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils import puzzles

    cache = os.environ.get("DSST_PUZZLE_CACHE") or os.path.join(
        REPO, ".cache", "puzzles"
    )
    os.makedirs(cache, exist_ok=True)

    with mp.Pool(args.workers) as pool:
        if args.headline:
            path = puzzles.batch_cache_path(
                SUDOKU_9, args.headline, args.seed, args.n_clues,
                unique=True, cache_dir=cache,
            )
            if os.path.exists(path):
                print(f"[headline] already cached: {path}")
            else:
                batch = _carve(pool, args.headline, args.seed, args.n_clues, "headline")
                tmp = f"{path}.{os.getpid()}.tmp.npy"
                np.save(tmp, batch)
                os.replace(tmp, path)
                print(f"[headline] wrote {path}")

        if args.solvefile:
            # Non-overlapping seed range so the two corpora stay disjoint.
            sf_seed = args.seed + 1_000_000
            tag = "u" if args.solvefile_unique else "nu"
            path = os.path.join(
                cache, f"solvefile_{args.solvefile}_{sf_seed}_{tag}.txt"
            )
            if os.path.exists(path):
                print(f"[solvefile] already cached: {path}")
            else:
                batch = _carve(
                    pool, args.solvefile, sf_seed, args.n_clues, "solvefile",
                    unique=args.solvefile_unique,
                )
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    for board in batch:
                        f.write(puzzles.to_line(board) + "\n")
                os.replace(tmp, path)
                print(f"[solvefile] wrote {path}")


if __name__ == "__main__":
    main()
