"""Measure the fused Sudoku kernel's stack-depth compile boundaries.

VERDICT r4 #4a: `ops/pallas_step._max_slots` carried five geometry caps
that were guesses (n = 10, 11 inherited 12x12's S = 16; 13-15 and 25
were rejected without probes).  This probe measures every geometry's
actual boundary on hardware — gridded (two 128-lane tiles, the
double-buffered multi-tile regime) and whole-array (one 128-lane tile)
— by compiling and running ONE fused round at each depth of a ladder
until the first failure.

Round-5 context: the boundaries move, because the round-4 calibration
was unknowingly against Mosaic's default 16 MB scoped-vmem ceiling, not
against hardware (``pallas_propagate._vmem_params`` now raises it).
Whatever this probe measures becomes the new `_max_slots` table.

    python benchmarks/probe_max_slots.py             # full sweep
    python benchmarks/probe_max_slots.py --geoms 25  # one geometry
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LADDER = [8, 12, 16, 20, 24, 32, 48, 64, 96, 128]


# Box shapes per size: squares where possible, the tested rectangular
# split otherwise; primes (5, 7, 11, 13) get degenerate 1 x n boxes (the
# box unit collapses onto the row unit — still a valid, total CSP, and
# the only way those sizes exist at all).
BOXES = {
    4: (2, 2), 5: (1, 5), 6: (2, 3), 7: (1, 7), 8: (2, 4),
    9: (3, 3), 10: (2, 5), 11: (1, 11), 12: (3, 4), 13: (1, 13),
    14: (2, 7), 15: (3, 5), 16: (4, 4), 25: (5, 5),
}


def probe(n: int, s: int, lanes: int, tile: int) -> tuple[bool, float, str]:
    """Compile + run one fused round; (ok, seconds, error-head)."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import Geometry
    from distributed_sudoku_solver_tpu.ops.pallas_step import fused_rounds

    geom = Geometry(*BOXES[n])
    top = jnp.full((n, n, lanes), jnp.uint32(geom.full_mask))
    stack = jnp.zeros((s, n, n, lanes), jnp.uint32)
    has = jnp.ones(lanes, bool)
    zero = jnp.zeros(lanes, jnp.int32)
    t0 = time.time()
    try:
        out = fused_rounds(
            top, stack, has, zero, zero, geom,
            k_steps=1, tile=tile, max_sweeps=8,
        )
        np.asarray(out[2])  # force execution, not just trace
        return True, time.time() - t0, ""
    except Exception as e:  # noqa: BLE001 — the probe's output IS the error
        msg = str(e)
        key = next(
            (l for l in msg.splitlines() if "Scoped allocation" in l or "RESOURCE" in l),
            msg.splitlines()[0] if msg else "",
        )
        return False, time.time() - t0, key[:220]


def sweep(n: int) -> None:
    for mode, lanes, tile in (("whole", 128, 128), ("gridded", 256, 128)):
        best = 0
        for s in LADDER:
            ok, dt, err = probe(n, s, lanes, tile)
            print(json.dumps({
                "metric": "max_slots_probe",
                "n": n,
                "mode": mode,
                "stack_slots": s,
                "ok": ok,
                "compile_s": round(dt, 1),
                "error": err if not ok else None,
            }), flush=True)
            if not ok:
                break
            best = s
        print(json.dumps({
            "metric": "max_slots_boundary", "n": n, "mode": mode, "max": best,
        }), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--geoms", type=str, default="9,10,11,12,13,14,15,16,25")
    args = ap.parse_args()
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    print(json.dumps({
        "metric": "session", "device": str(jax.devices()[0].platform),
    }), flush=True)
    for g in args.geoms.split(","):
        sweep(int(g))


if __name__ == "__main__":
    main()
