"""Pipeline anatomy: decompose the end-to-end bulk pass stage by stage.

VERDICT r4 missing #1: the single-board latency path has a measured RPC
floor and a floor-subtracted device number, but the bulk path's ~3x gap
between device-only (304k boards/s) and end-to-end (145k) had no
accounting.  This probe produces the decomposition: measured link rates
(up/down/duplex), the per-dispatch floor, host pack/unpack walls, the
device-resident compute wall for the exact first-pass config, the
pipelined first-pass wall with `solve_bulk(trace=...)` attribution, and
the rung escalation wall with dispatch counts.  The model

    e2e_floor = max(transfer_up + transfer_down [link-serialized],
                    device_compute) + pipeline fill/drain + rung wall

is then compared against the measured e2e wall so the slack — the only
part any lever can recover — is a number, not a narrative.

Subcommands (one JSON line per finding, BENCHMARKS.md records adopted
numbers in "Pipeline anatomy (round 5)"):

  floor   — trivial dispatch+fetch round-trip floor
  link    — upload/download MB/s at several sizes + duplex overlap probe
  stages  — full decomposition of the bench.py headline pass (65,536
            distinct boards) via solve_bulk(trace=...) + device-resident
            and transfer-only controls
  sweep   — chunk x inflight grid on the full e2e pass (the r2-tuned
            32768x3 predates the 3.45x-faster fused first pass)
  fsteps  — fused_steps 8 vs 32 e2e A/B at the sweep-winning shape
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def _floor_samples(k: int = 12) -> list[float]:
    import jax.numpy as jnp

    tiny = jnp.zeros(8, jnp.int32)
    _ = np.asarray(tiny + 1)  # warm
    out = []
    for _ in range(k):
        t0 = time.perf_counter()
        _ = np.asarray(tiny + 1)
        out.append(time.perf_counter() - t0)
    return out


def bench_floor() -> None:
    f = _floor_samples()
    emit(
        metric="rpc_floor_ms",
        min=round(min(f) * 1e3, 2),
        p50=round(float(np.median(f)) * 1e3, 2),
        max=round(max(f) * 1e3, 2),
    )


def _upload(host_arr: np.ndarray) -> float:
    """Wall to move host bytes onto the device (scalar fetch proves arrival)."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    dev = jnp.asarray(host_arr)
    _ = np.asarray(dev[0])  # blocks until the upload has landed
    return time.perf_counter() - t0


def _download(dev_arr) -> float:
    t0 = time.perf_counter()
    _ = np.asarray(dev_arr)
    return time.perf_counter() - t0


def bench_link() -> None:
    import jax.numpy as jnp

    floor = min(_floor_samples(6))
    for mb in (1, 4, 16):
        nbytes = mb << 20
        host = np.random.default_rng(0).integers(
            0, 255, nbytes, dtype=np.uint8
        )
        ups, downs = [], []
        dev = jnp.asarray(host)
        _ = np.asarray(dev[0])
        for _ in range(4):
            ups.append(_upload(host))
            # Re-materialize so the fetch can't be served by a host cache.
            dev = (dev + 1).astype(jnp.uint8)
            _ = np.asarray(dev[0])  # compute done; timing below is pure fetch
            downs.append(_download(dev))
        up, down = min(ups), min(downs)
        emit(
            metric="link_rate",
            mb=mb,
            up_s=round(up, 3),
            down_s=round(down, 3),
            up_mb_s=round(nbytes / (up - floor) / 1e6, 1),
            down_mb_s=round(nbytes / (down - floor) / 1e6, 1),
            floor_ms=round(floor * 1e3, 1),
        )

    # Duplex probe: do a 4 MB upload and a 4 MB download overlap, or does
    # the tunnel serialize them?  Two threads, shared start barrier.
    nbytes = 4 << 20
    host = np.random.default_rng(1).integers(0, 255, nbytes, dtype=np.uint8)
    dev = (jnp.asarray(host) + 1).astype(jnp.uint8)
    _ = np.asarray(dev[0])
    serial = _upload(host) + _download(dev)
    walls = {}
    barrier = threading.Barrier(2)

    def run(name, fn, arg):
        barrier.wait()
        t0 = time.perf_counter()
        fn(arg)
        walls[name] = time.perf_counter() - t0

    best_overlap = float("inf")
    for _ in range(3):
        t1 = threading.Thread(target=run, args=("up", _upload, host))
        t2 = threading.Thread(target=run, args=("down", _download, dev))
        t1.start(); t2.start(); t1.join(); t2.join()
        best_overlap = min(best_overlap, max(walls.values()))
    emit(
        metric="duplex",
        mb=4,
        serial_s=round(serial, 3),
        overlapped_s=round(best_overlap, 3),
        overlap_gain=round(serial / best_overlap, 2),
    )


def _headline_corpus(b: int = 65536) -> np.ndarray:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    distinct = puzzle_batch(SUDOKU_9, b - len(HARD_9), seed=7, n_clues=24)
    return np.concatenate([np.stack(HARD_9), distinct]).astype(np.int32)


def bench_stages(b: int = 65536) -> None:
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops import wire
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch_wire

    grids = _headline_corpus(b)
    cfg = BulkConfig()
    chunk = cfg.chunk
    floor = min(_floor_samples(6))

    # --- host-only stages -------------------------------------------------
    t0 = time.perf_counter()
    packed_chunks = [
        wire.pack_grids_host(grids[lo : lo + chunk], SUDOKU_9)
        for lo in range(0, b, chunk)
    ]
    pack_s = time.perf_counter() - t0
    up_bytes = sum(p.nbytes for p in packed_chunks)

    res_shape = (chunk, wire.grid_wire_width(SUDOKU_9) + 1)
    dummy = np.zeros(res_shape, np.uint8)
    t0 = time.perf_counter()
    for _ in packed_chunks:
        wire.unpack_result_host(dummy, SUDOKU_9)
    unpack_s = time.perf_counter() - t0
    down_bytes = dummy.nbytes * len(packed_chunks)

    # --- transfer-only: same bytes, no compute ----------------------------
    up_s = min(
        sum(_upload(p) for p in packed_chunks) - floor * len(packed_chunks)
        for _ in range(3)
    )
    dev_res = [(jnp.asarray(dummy) + 0) for _ in packed_chunks]
    for d in dev_res:
        _ = np.asarray(d[0, 0])
    down_s = min(
        sum(_download(d) for d in dev_res) - floor * len(dev_res)
        for _ in range(3)
    )

    # --- device-resident compute: the exact first-pass config -------------
    first_cfg = SolverConfig(
        lanes=chunk,
        stack_slots=cfg.stack_slots,
        max_steps=min(cfg.first_pass_steps, cfg.max_steps),
        max_sweeps=cfg.max_sweeps,
        propagator="slices",
        rules=cfg.rules,
        step_impl="fused",
    )
    resident = [jnp.asarray(p) for p in packed_chunks]
    for r in resident:
        _ = np.asarray(r[0, 0])
    _ = np.asarray(solve_batch_wire(resident[0], SUDOKU_9, first_cfg)[0, 0])
    device_s = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [solve_batch_wire(r, SUDOKU_9, first_cfg) for r in resident]
        _ = np.asarray(outs[-1][0, 0])  # in-order: one sync drains all
        device_s = min(device_s, time.perf_counter() - t0 - floor)

    # --- the real pass, attributed ----------------------------------------
    solve_bulk(grids, SUDOKU_9, cfg)  # warm every rung shape
    best = {"wall_s": float("inf")}
    for _ in range(3):
        trace: dict = {}
        t0 = time.perf_counter()
        res = solve_bulk(grids, SUDOKU_9, cfg, trace=trace)
        wall = time.perf_counter() - t0
        if wall < best["wall_s"]:
            best = {"wall_s": wall, "trace": trace, "solved": int(res.solved.sum())}

    trace = best["trace"]
    rung_s = sum(r["wall_s"] for r in trace["rungs"])
    rung_dispatches = sum(r["dispatches"] for r in trace["rungs"])
    transfer_serial = up_s + down_s
    model_floor = max(transfer_serial, device_s) + rung_s + pack_s + unpack_s
    emit(
        metric="pipeline_anatomy",
        boards=b,
        e2e_wall_s=round(best["wall_s"], 3),
        e2e_boards_per_s=round(b / best["wall_s"], 1),
        solved=best["solved"],
        pack_s=round(pack_s, 3),
        unpack_s=round(unpack_s, 3),
        upload_s=round(up_s, 3),
        download_s=round(down_s, 3),
        up_bytes=up_bytes,
        down_bytes=down_bytes,
        device_first_pass_s=round(device_s, 3),
        first_pass_wall_s=round(trace["first_pass_s"], 3),
        first_pass_drain_s=round(trace["drain_s"], 3),
        first_pass_pack_s=round(trace["pack_s"], 3),
        remaining_after_first=trace["remaining_after_first"],
        rung_wall_s=round(rung_s, 3),
        rung_dispatches=rung_dispatches,
        rungs=[
            {k: (round(v, 3) if isinstance(v, float) else v) for k, v in r.items()}
            for r in trace["rungs"]
        ],
        rpc_floor_ms=round(floor * 1e3, 1),
        model_floor_s=round(model_floor, 3),
        slack_s=round(best["wall_s"] - model_floor, 3),
        slack_pct=round(100 * (best["wall_s"] - model_floor) / best["wall_s"], 1),
    )


def bench_sweep(b: int = 65536) -> None:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk

    grids = _headline_corpus(b)
    combos = [
        (8192, 4), (8192, 8),
        (16384, 3), (16384, 6),
        (32768, 2), (32768, 3), (32768, 4),
        (65536, 1),
    ]
    cfgs = {(c, i): BulkConfig(chunk=c, inflight=i) for c, i in combos}
    for cfg in cfgs.values():
        solve_bulk(grids[: cfg.chunk * 2], SUDOKU_9, cfg)  # warm shapes
    best: dict = {}
    for _ in range(3):
        for key, cfg in cfgs.items():
            t0 = time.perf_counter()
            res = solve_bulk(grids, SUDOKU_9, cfg)
            dt = time.perf_counter() - t0
            if dt < best.get(key, (float("inf"),))[0]:
                best[key] = (dt, int(res.solved.sum()))
    for (c, i), (dt, solved) in sorted(best.items()):
        emit(
            metric="chunk_inflight_sweep",
            chunk=c,
            inflight=i,
            boards_per_s=round(b / dt, 1),
            wall_s=round(dt, 3),
            solved=solved,
        )


def bench_fsteps(b: int = 65536, chunk: int = 32768, inflight: int = 3) -> None:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk

    grids = _headline_corpus(b)
    cfgs = {
        fs: BulkConfig(chunk=chunk, inflight=inflight, fused_steps=fs)
        for fs in (8, 16, 32)
    }
    best: dict = {}
    for cfg in cfgs.values():
        solve_bulk(grids[: chunk * 2], SUDOKU_9, cfg)
    for _ in range(3):
        for fs, cfg in cfgs.items():
            t0 = time.perf_counter()
            res = solve_bulk(grids, SUDOKU_9, cfg)
            dt = time.perf_counter() - t0
            if dt < best.get(fs, (float("inf"),))[0]:
                best[fs] = (dt, int(res.solved.sum()))
    for fs, (dt, solved) in sorted(best.items()):
        emit(
            metric="fused_steps_e2e",
            fused_steps=fs,
            chunk=chunk,
            inflight=inflight,
            boards_per_s=round(b / dt, 1),
            wall_s=round(dt, 3),
            solved=solved,
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "experiments", nargs="*", default=["floor", "link", "stages"]
    )
    args = ap.parse_args()
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    emit(metric="session", device=str(jax.devices()[0].platform))
    for exp in args.experiments:
        {
            "floor": bench_floor,
            "link": bench_link,
            "stages": bench_stages,
            "sweep": bench_sweep,
            "fsteps": bench_fsteps,
        }[exp]()


if __name__ == "__main__":
    main()
