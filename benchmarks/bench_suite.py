"""Full benchmark suite: one JSON line per config (see BENCHMARKS.md).

`bench.py` at the repo root is the driver-run headline (one line); this
suite covers the wider matrix: propagation backends, geometry scaling,
single-board latency, bulk end-to-end, and the native loader.  Run on the
TPU host:

    python benchmarks/bench_suite.py [--quick]

Timing protocol everywhere: warm pass first (compiles cached on disk), then
per-call `block_until_ready` — no async-dispatch flattery (the failure mode
is real: unsynced loops under-measure by 100x+, observed this session).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # runnable from any cwd without installing


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def bench_propagation(jax, jnp, B: int) -> None:
    """Device-throughput protocol: K iterations chained *inside one jit
    dispatch* (each iteration data-depends on the last), so per-call host/
    tunnel dispatch overhead (~100 ms via the axon RPC tunnel, measured) is
    amortized away and async-dispatch under-measurement (100x+, also
    measured) is structurally impossible.  A pure-copy loop calibrates the
    harness floor."""
    import functools

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        propagate_fixpoint_pallas,
        propagate_fixpoint_slices,
    )
    from distributed_sudoku_solver_tpu.ops.propagate import propagate
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    base = puzzle_batch(SUDOKU_9, 512, seed=7, n_clues=24)
    grids = np.tile(base, (B // 512, 1, 1))
    cand = jax.device_put(
        np.asarray(encode_grid(jnp.asarray(grids), SUDOKU_9))
    )
    K = 20

    def chained(fix_fn):
        # Pitfalls this harness dodges (all hit this session): a re-arm like
        # `x | (out & 0)` constant-folds so DCE deletes the backend entirely;
        # a loop-invariant input lets LICM hoist the fixpoint out of the
        # loop.  Rolling the batch by the loop index makes every iteration's
        # input distinct (same boards, same total work), and OR-ing into a
        # returned accumulator keeps every output live.
        @jax.jit
        def run(x):
            def body(i, acc):
                out, _ = fix_fn(jnp.roll(x, i, axis=0))
                return acc | out

            return jax.lax.fori_loop(0, K, body, jnp.zeros_like(x))

        return run

    backends = {
        "copy_calibration": chained(lambda c: (c, None)),
        "pallas": chained(
            lambda c: propagate_fixpoint_pallas(c, SUDOKU_9, tile=2048)
        ),
        "pallas_extended": chained(
            lambda c: propagate_fixpoint_pallas(
                c, SUDOKU_9, tile=2048, rules="extended"
            )
        ),
        "slices": chained(lambda c: propagate_fixpoint_slices(c, SUDOKU_9)),
        "boards_first_xla": chained(lambda c: propagate(c, SUDOKU_9)),
    }
    for name, run in backends.items():
        out = run(cand)
        np.asarray(out[0, 0, 0])  # block_until_ready is unreliable through
        t0 = time.perf_counter()  # the tunnel; only a value fetch blocks
        out = run(cand)
        np.asarray(out[0, 0, 0])
        ms = (time.perf_counter() - t0) / K * 1e3
        emit(
            metric=f"propagate_fixpoint_{name}",
            value=round(B / ms * 1000),
            unit="boards/s",
            batch=B,
            ms_per_fixpoint=round(ms, 3),
        )


def bench_bulk(jax, B: int) -> None:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    distinct = puzzle_batch(SUDOKU_9, 2048 - len(HARD_9), seed=7, n_clues=24)
    corpus = np.concatenate([np.stack(HARD_9), distinct]).astype(np.int32)
    grids = np.tile(corpus, (B // 2048, 1, 1))
    cfg = BulkConfig()
    solve_bulk(grids, SUDOKU_9, cfg)
    t0 = time.perf_counter()
    res = solve_bulk(grids, SUDOKU_9, cfg)
    dt = time.perf_counter() - t0
    emit(
        metric="bulk_hard9x9_end_to_end",
        value=round(len(grids) / dt, 1),
        unit="boards/s",
        batch=len(grids),
        solved=int(res.solved.sum()),
        searched=res.searched,
        wall_s=round(dt, 3),
    )


def bench_bulk_easy(jax, B: int) -> None:
    """Kaggle-1M-style workload: 36-clue boards, ~99% solved by propagation
    alone — measures the stage-1-dominated (link + fixpoint) regime."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    assert B % 2048 == 0, f"B={B} must be a multiple of the 2048-board corpus"
    easy = puzzle_batch(SUDOKU_9, 2048, seed=101, n_clues=36)
    grids = np.tile(easy, (B // 2048, 1, 1))
    cfg = BulkConfig()
    solve_bulk(grids, SUDOKU_9, cfg)
    t0 = time.perf_counter()
    res = solve_bulk(grids, SUDOKU_9, cfg)
    dt = time.perf_counter() - t0
    emit(
        metric="bulk_easy9x9_end_to_end",
        value=round(len(grids) / dt, 1),
        unit="boards/s",
        batch=len(grids),
        solved=int(res.solved.sum()),
        searched=res.searched,
        wall_s=round(dt, 3),
    )


def bench_latency(jax) -> None:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    for name, board in [("easy", EASY_9), ("escargot", HARD_9[0])]:
        cfg = SolverConfig(min_lanes=256, stack_slots=64)
        one = np.asarray(board, dtype=np.int32)[None]
        r = solve_batch(one, SUDOKU_9, cfg)
        int(np.asarray(r.steps))
        times = []
        for _ in range(9):
            t0 = time.perf_counter()
            r = solve_batch(one, SUDOKU_9, cfg)
            int(np.asarray(r.steps))  # force the value round-trip
            times.append(time.perf_counter() - t0)
        emit(
            metric=f"latency_single_{name}_p50",
            value=round(float(np.median(times)) * 1e3, 2),
            unit="ms",
            steps=int(r.steps),
        )


def bench_geometry(jax, quick: bool) -> None:
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_16, SUDOKU_25
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    configs = [(SUDOKU_16, 256, 0.5), (SUDOKU_25, 64, 0.6)]
    if quick:
        configs = [(SUDOKU_16, 64, 0.5)]
    for geom, count, frac in configs:
        grids = puzzle_batch(
            geom, count, seed=5, n_clues=int(geom.n**2 * frac), unique=False
        ).astype(np.int32)
        cfg = BulkConfig(chunk=count, stack_slots=64)
        solve_bulk(grids, geom, cfg)
        t0 = time.perf_counter()
        res = solve_bulk(grids, geom, cfg)
        dt = time.perf_counter() - t0
        emit(
            metric=f"bulk_{geom.n}x{geom.n}_end_to_end",
            value=round(count / dt, 2),
            unit="boards/s",
            batch=count,
            solved=int(res.solved.sum()),
            searched=res.searched,
            wall_s=round(dt, 3),
        )


def bench_loader() -> None:
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    if not native.available():
        return
    base = puzzle_batch(SUDOKU_9, 512, seed=7, n_clues=24).astype(np.int32)
    big = np.tile(base, (2048, 1, 1))  # 1,048,576 boards
    t0 = time.perf_counter()
    blob = native.format_boards(big)
    fmt = time.perf_counter() - t0
    t0 = time.perf_counter()
    parsed = native.parse_boards(blob, 9)
    par = time.perf_counter() - t0
    assert (parsed == big).all()
    emit(metric="loader_format", value=round(len(big) / fmt), unit="boards/s")
    emit(metric="loader_parse", value=round(len(big) / par), unit="boards/s")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    os.environ.setdefault("DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles"))
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    emit(metric="device", value=str(jax.devices()[0].device_kind), unit="")

    B = 16384 if args.quick else 65536
    bench_propagation(jax, jnp, B)
    bench_latency(jax)
    bench_bulk(jax, 8192 if args.quick else 32768)
    bench_bulk_easy(jax, 16384 if args.quick else 131072)
    bench_geometry(jax, args.quick)
    bench_loader()


if __name__ == "__main__":
    main()
