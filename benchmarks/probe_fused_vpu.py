"""Round-6 probe: the fused kernel's three named VPU losses, A/B'd in place.

The round-4 roofline (BENCHMARKS.md) put the whole-round fused kernel at
~0.23 T uint32-op/s — 6-12% of v5e VPU integer throughput — and named three
losses: (a) the S-way masked-concat slot algebra ("mostly predication"),
(b) short post-branch fixpoints amortizing the per-sweep loop machinery
poorly, (c) `fused_steps` tuned for the tunnel surface on device-resident
paths.  This probe measures each lever in isolation against the same
device-resident corpus, with the interleaved-A/B discipline of
``benchmarks/anatomy.py`` (sequential identical programs measure 17% apart
through the tunnel — every ratio here alternates its sides).

    python benchmarks/probe_fused_vpu.py              # all three levers
    python benchmarks/probe_fused_vpu.py --lever slot # one lever
    python benchmarks/probe_fused_vpu.py --check      # bit-equality only
                                                      # (runs on the CPU mesh)

On non-TPU backends the kernels run in Pallas interpret mode: the
``--check`` lane (variant bit-equality) is meaningful there and runs in
CI-ish time at --boards 64; wall-clock ratios are only meaningful on
hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _legacy_select_slot(stack, sel_slot, active):
    """The pre-round-6 masked-OR slot read, kept here as the A/B control:
    S slot compares + S masking wheres + an OR fold (exclusive masks make
    the fold exact) — the 'mostly predication' loss the mux tree replaces."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.ops.pallas_propagate import _fold

    s = stack.shape[0]
    rows = [
        jnp.where(active & (sel_slot == i), stack[i], jnp.uint32(0))
        for i in range(s)
    ]
    import operator

    return _fold(rows, operator.or_)


def check_select_slot_equivalence(slots: int = 12, lanes: int = 128) -> None:
    """Mux-tree select == legacy masked-OR select, for every slot index,
    power-of-two or not (the circular stack visits all of [0, S))."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.ops.pallas_step import _select_slot

    rng = np.random.default_rng(0)
    for s in (1, 2, 3, 5, 6, 12, 13, 16):
        stack = jnp.asarray(
            rng.integers(0, 2**31, size=(s, 9, 9, lanes), dtype=np.uint32)
        )
        sel = jnp.asarray(
            np.broadcast_to(
                rng.integers(0, s, size=lanes).astype(np.int32),
                (9, 9, lanes),
            )
        )
        active = jnp.asarray(
            np.broadcast_to(rng.integers(0, 2, size=lanes) > 0, (9, 9, lanes))
        )
        got = np.asarray(_select_slot(stack, sel, active))
        want = np.asarray(_legacy_select_slot(stack, sel, active))
        assert (got == want).all(), f"mux tree diverged at S={s}"
    print(json.dumps({"check": "select_slot", "ok": True, "slots": slots}))


def _corpus(n_boards: int):
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    distinct = puzzle_batch(
        SUDOKU_9, max(0, n_boards - len(HARD_9)), seed=7, n_clues=24
    )
    return np.concatenate([np.stack(HARD_9), distinct])[:n_boards].astype(
        np.int32
    )


def _timed_solve(grids, cfg, repeat: int = 3) -> tuple[float, object]:
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch

    g = jnp.asarray(grids)
    res = solve_batch(g, SUDOKU_9, cfg)  # warm the compile
    int(np.asarray(res.steps))
    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        res = solve_batch(g, SUDOKU_9, cfg)
        int(np.asarray(res.steps))  # value fetch: the only trustworthy sync
        best = min(best, time.perf_counter() - t0)
    return best, res


def probe_sweep_unroll(grids, repeat: int) -> dict:
    """Lever (b): the unrolled fixpoint prefix.  Bit-exact by construction
    (a sweep of a fixpoint is the identity) — assert it anyway, then time
    prefix 0 (the pre-round-6 checked-every-sweep loop) vs 2, interleaved.
    ``SolverConfig.fused_sweep_unroll`` is part of the jit key, so the two
    arms compile separately."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

    out = {"lever": "sweep_unroll"}
    for unroll in (0, 2, 0, 2):  # interleaved
        cfg = SolverConfig(
            step_impl="fused", stack_slots=12, rules="extended",
            fused_sweep_unroll=unroll,
        )
        wall, res = _timed_solve(grids, cfg, repeat=max(1, repeat // 2))
        key = f"unroll{unroll}"
        out[key] = min(out.get(key, float("inf")), wall)
        out[f"{key}_solved"] = int(np.asarray(res.solved).sum())
    assert out["unroll0_solved"] == out["unroll2_solved"]
    return out


def probe_fused_steps(grids, repeat: int) -> dict:
    """Lever (c): fused_steps on a device-resident solve (8 vs 32)."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

    out = {"lever": "fused_steps"}
    for k in (8, 32, 8, 32):  # interleaved
        cfg = SolverConfig(
            step_impl="fused", stack_slots=12, rules="extended", fused_steps=k
        )
        wall, res = _timed_solve(grids, cfg, repeat=max(1, repeat // 2))
        key = f"k{k}"
        out[key] = min(out.get(key, float("inf")), wall)
        out[f"{key}_solved"] = int(np.asarray(res.solved).sum())
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--boards", type=int, default=65536)
    ap.add_argument("--repeat", type=int, default=4)
    ap.add_argument(
        "--lever", choices=("slot", "unroll", "steps", "all"), default="all"
    )
    ap.add_argument(
        "--check", action="store_true",
        help="bit-equality checks only (CPU-mesh friendly)",
    )
    args = ap.parse_args()

    if args.check or args.lever in ("slot", "all"):
        check_select_slot_equivalence()
    if args.check:
        return

    grids = _corpus(args.boards)
    if args.lever in ("unroll", "all"):
        print(json.dumps(probe_sweep_unroll(grids, args.repeat)))
    if args.lever in ("steps", "all"):
        print(json.dumps(probe_fused_steps(grids, args.repeat)))


if __name__ == "__main__":
    main()
