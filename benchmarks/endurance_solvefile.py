"""Device-backed solve-file endurance loop (VERDICT r3 #10, r4 #7).

Runs `utils.dataset.solve_file` over a corpus repeatedly in ONE process
(so jit caches, device buffers, and transfer pools age realistically),
appending one JSON line per pass — throughput, RSS, fd count, and a
native-validator spot-check — to ``--log``.  The analysis at the end of
the run (or any time, from the log) is the same contract as the churn
soak: post-warmup RSS slope and fd stability, plus throughput
steadiness (no monotonic decay).

    python benchmarks/endurance_solvefile.py --input <corpus> --hours 3

Round-5 additions (VERDICT r4 #7):

* **Per-pass solution validation**: each pass writes its output file and
  ``--validate-k`` randomly sampled (input, output) line pairs are
  checked with the independent C++ validator — clue preservation + unit
  validity — so "100% solved x N passes" asserts *solutions*, not just
  verdict flags.
* **Bounded-RSS re-exec**: the ~43 MB/pass RSS growth lives in the
  tunnel client's transfer pool, below the framework (isolated round 4
  via a flat CPU-backend control).  When RSS crosses ``--rss-cap-mb``
  the loop re-execs itself with the remaining time budget (fresh
  process, same log), so a long soak measures the framework instead of
  inheriting the tunnel client's growth — each re-exec is visible in
  the log as a ``reexec`` record and a ``pass0`` offset.

Stops cleanly at the time budget (finishes the pass in flight), so it
can run under the TPU watchdog protocol: every device dispatch inside
solve_file is already step-capped/chunked (ops/bulk.py dispatch bounds).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def sample_validate(
    in_path: str, out_path: str, geom, k: int, seed: int
) -> dict:
    """Validate ``k`` random (puzzle, solution) line pairs independently.

    Uses the native C++ validator when built (``native.is_valid_solution``)
    and always checks clue preservation; all-zero output lines (unsat /
    unresolved) are counted separately, not failed."""
    import numpy as np

    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.utils import dataset

    def count_lines(path: str) -> int:
        n = 0
        with open(path, "rb") as f:
            for _ in f:
                n += 1
        return n

    n_in, n_out = count_lines(in_path), count_lines(out_path)
    header = 1 if n_in == n_out + 1 else 0  # tolerate an input header line
    assert n_in - header == n_out, (
        f"line mismatch: {n_in - header} in vs {n_out} out"
    )
    rng = np.random.default_rng(seed)
    idx = set(
        int(i) for i in rng.choice(n_out, size=min(k, n_out), replace=False)
    )

    def sample(path: str, skip: int) -> dict:
        # Stream, keeping only the sampled lines: reading the whole 82 MB
        # corpus into Python line lists every pass would inject hundreds
        # of MB of transient heap right where the soak samples RSS.
        out = {}
        with open(path, "rb") as f:
            for i, line in enumerate(f):
                if i - skip in idx:
                    out[i - skip] = line.rstrip(b"\n")
        return out

    in_lines = sample(in_path, header)
    out_lines = sample(out_path, 0)
    ok = bad = zero = 0
    for i in sorted(idx):
        puzzle = dataset.parse_boards(in_lines[i], geom, allow_header=False)[0]
        sol = dataset.parse_boards(out_lines[i], geom, allow_header=False)[0]
        if not sol.any():
            zero += 1  # unsat/unresolved line: all-zeros by contract
            continue
        clues_kept = bool(((puzzle == 0) | (sol == puzzle)).all())
        if native.available():
            valid = native.is_valid_solution(sol, geom)
        else:
            # Full fallback: rows, columns AND boxes (a Latin square with
            # box duplicates must fail here too).
            want = np.arange(1, geom.n + 1)
            boxes = sol.reshape(
                geom.n_vboxes, geom.box_h, geom.n_hboxes, geom.box_w
            ).transpose(0, 2, 1, 3).reshape(-1, geom.n)
            valid = bool(
                (np.sort(sol, axis=0) == want[:, None]).all()
                and (np.sort(sol, axis=1) == want[None, :]).all()
                and (np.sort(boxes, axis=1) == want[None, :]).all()
            )
        if clues_kept and valid:
            ok += 1
        else:
            bad += 1
    return {"validated": ok, "invalid": bad, "zero_lines": zero}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True)
    ap.add_argument("--hours", type=float, default=3.0)
    ap.add_argument("--size", type=int, default=9)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--log", default="/tmp/endurance_solvefile.jsonl")
    ap.add_argument("--validate-k", type=int, default=64)
    ap.add_argument("--rss-cap-mb", type=float, default=8192.0)
    ap.add_argument("--deadline-ts", type=float, default=None,
                    help=argparse.SUPPRESS)  # re-exec carries the absolute deadline
    ap.add_argument("--pass0", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )

    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig
    from distributed_sudoku_solver_tpu.utils import dataset

    geom = geometry_for_size(args.size)
    deadline = args.deadline_ts or (time.time() + args.hours * 3600)
    t_start = time.monotonic()
    n_pass = args.pass0
    # Keyed by the log basename so concurrent soaks with logs in one
    # directory never overwrite each other's solutions file.
    log_key = os.path.splitext(os.path.basename(args.log))[0]
    out_path = os.path.join(
        os.path.dirname(args.log) or "/tmp", f"{log_key}_solutions.txt"
    )
    with open(args.log, "a") as log:

        def emit(rec: dict) -> None:
            log.write(json.dumps(rec) + "\n")
            log.flush()
            print(json.dumps(rec), flush=True)

        while time.time() < deadline:
            t0 = time.perf_counter()
            stats = dataset.solve_file(
                args.input, out_path, geom, batch=args.batch,
                bulk_config=BulkConfig(),
            )
            dt = time.perf_counter() - t0
            n_pass += 1
            check = sample_validate(
                args.input, out_path, geom, args.validate_k, seed=n_pass
            )
            rec = {
                "pass": n_pass,
                "t_min": round((time.monotonic() - t_start) / 60, 2),
                "boards": stats["total"],
                "solved": stats["solved"],
                "boards_per_s": round(stats["total"] / dt, 1),
                "wall_s": round(dt, 2),
                "rss_mb": round(rss_mb(), 1),
                "fds": fd_count(),
                **check,
            }
            emit(rec)
            assert check["invalid"] == 0, f"invalid solutions: {check}"
            if rss_mb() > args.rss_cap_mb and time.time() < deadline:
                emit({
                    "reexec": True,
                    "pass": n_pass,
                    "rss_mb": round(rss_mb(), 1),
                    "cap_mb": args.rss_cap_mb,
                })
                os.execv(sys.executable, [
                    sys.executable, os.path.abspath(__file__),
                    "--input", args.input,
                    "--size", str(args.size),
                    "--batch", str(args.batch),
                    "--log", args.log,
                    "--validate-k", str(args.validate_k),
                    "--rss-cap-mb", str(args.rss_cap_mb),
                    "--deadline-ts", str(deadline),
                    "--pass0", str(n_pass),
                ])
    print(json.dumps({"done": True, "passes": n_pass}), flush=True)


if __name__ == "__main__":
    main()
