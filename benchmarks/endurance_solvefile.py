"""Device-backed solve-file endurance loop (VERDICT r3 #10).

Runs `utils.dataset.solve_file` over a corpus repeatedly in ONE process
(so jit caches, device buffers, and transfer pools age realistically),
appending one JSON line per pass — throughput, RSS, fd count — to
``--log``.  The analysis at the end of the run (or any time, from the
log) is the same contract as the churn soak: post-warmup RSS slope and
fd stability, plus throughput steadiness (no monotonic decay).

    python benchmarks/endurance_solvefile.py --input <corpus> --hours 3

Stops cleanly at the time budget (finishes the pass in flight), so it
can run under the TPU watchdog protocol: every device dispatch inside
solve_file is already step-capped/chunked (ops/bulk.py dispatch bounds).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True)
    ap.add_argument("--hours", type=float, default=3.0)
    ap.add_argument("--size", type=int, default=9)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--log", default="/tmp/endurance_solvefile.jsonl")
    args = ap.parse_args()
    os.environ.setdefault(
        "DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles")
    )

    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig
    from distributed_sudoku_solver_tpu.utils import dataset

    geom = geometry_for_size(args.size)
    deadline = time.monotonic() + args.hours * 3600
    t_start = time.monotonic()
    n_pass = 0
    with open(args.log, "a") as log:
        while time.monotonic() < deadline:
            t0 = time.perf_counter()
            stats = dataset.solve_file(
                args.input, None, geom, batch=args.batch,
                bulk_config=BulkConfig(),
            )
            dt = time.perf_counter() - t0
            n_pass += 1
            rec = {
                "pass": n_pass,
                "t_min": round((time.monotonic() - t_start) / 60, 2),
                "boards": stats["total"],
                "solved": stats["solved"],
                "boards_per_s": round(stats["total"] / dt, 1),
                "wall_s": round(dt, 2),
                "rss_mb": round(rss_mb(), 1),
                "fds": fd_count(),
            }
            log.write(json.dumps(rec) + "\n")
            log.flush()
            print(json.dumps(rec), flush=True)
    print(json.dumps({"done": True, "passes": n_pass}), flush=True)


if __name__ == "__main__":
    main()
