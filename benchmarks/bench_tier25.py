"""Inference-tier matrix on deep-search 25x25 corpora (VERDICT r2 #2).

The sparse (45%-clue) 25x25 corpus is the workload where round 2 measured
~1.1 boards/s — propagation stopped at box-line, so giant-board deep search
was nearly blind branching.  This benchmark pits the rule tiers against each
other on that exact protocol (64 boards, `puzzle_batch` seed 5,
`stack_slots=64`, one-dispatch bulk path, best-of-N warm, tiers interleaved
within each repeat so tunnel-throughput drift hits all tiers equally).

Emits one JSON line per tier: boards/s (best), searched count, total nodes,
and per-repeat wall times.  Run on the real chip:

    python benchmarks/bench_tier25.py --clues 0.45 --count 64 --repeat 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # runnable from any cwd without installing


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clues", type=float, default=0.45)
    ap.add_argument("--count", type=int, default=64)
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--size", type=int, default=25)
    ap.add_argument(
        "--tiers", type=str, default="basic,extended,subsets",
        help="comma-separated rule tiers to race",
    )
    ap.add_argument("--stack-slots", type=int, default=64)
    ap.add_argument(
        "--rungs", type=str, default=None,
        help="escalation ladder as 'jobs,lanes,slots[,steps];...' "
        "(e.g. the round-2 ladder '2048,4,64;64,64,256' used for the "
        "BENCHMARKS.md tier table); default: geometry-resolved",
    )
    args = ap.parse_args()
    rungs = (
        tuple(tuple(int(v) for v in r.split(",")) for r in args.rungs.split(";"))
        if args.rungs
        else None
    )

    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    geom = geometry_for_size(args.size)
    grids = puzzle_batch(
        geom, args.count, seed=5, n_clues=int(geom.n**2 * args.clues), unique=False
    ).astype(np.int32)
    tiers = args.tiers.split(",")
    cfgs = {
        t: BulkConfig(
            chunk=args.count, stack_slots=args.stack_slots, rules=t, rungs=rungs
        )
        for t in tiers
    }

    # Warm every tier's compile cache before any timed repeat.
    results = {t: solve_bulk(grids, geom, cfgs[t]) for t in tiers}
    walls: dict[str, list] = {t: [] for t in tiers}
    for _ in range(args.repeat):
        for t in tiers:  # interleaved: drift hits every tier equally
            t0 = time.perf_counter()
            results[t] = solve_bulk(grids, geom, cfgs[t])
            walls[t].append(round(time.perf_counter() - t0, 3))

    for t in tiers:
        res, best = results[t], min(walls[t])
        print(
            json.dumps(
                {
                    "metric": f"tier25_{int(args.clues * 100)}pct_{t}",
                    "value": round(args.count / best, 2),
                    "unit": "boards/s",
                    "solved": int(res.solved.sum()),
                    "searched": res.searched,
                    "walls_s": walls[t],
                }
            )
        )


if __name__ == "__main__":
    main()
