"""lockck: declared lock coverage for cross-thread counters.

Three review rounds found the same bug family by hand: a counter that
submit/handler threads race being bumped outside its lock
(``breaker_deflected``, ``fault_bulk_retries``, the agg counters — all
fixed in past review rounds, now annotated).  The convention this rule
enforces:

* the attribute's initialisation line declares the contract:
  ``self.rejected = 0  # lockck: guard(_lock)``;
* every other write to that attribute (plain/augmented assign, and
  mutation through a subscript like ``self.duplicates_dropped[m] = ...``)
  must sit lexically inside ``with <base>.<lock>:`` for the SAME base
  expression (``self._lock`` for ``self.rejected``; ``engine._lock`` for
  ``engine.fault_bulk_retries`` — a cross-module write);
* OR inside a method whose name ends in ``_locked`` — the repo's existing
  "caller holds the lock" convention (``_count_duplicate_locked``,
  ``_reflect_ok_locked``);
* OR carry a ``# lockck: allow(<reason>)`` waiver.

Scoping: ``self.<attr>`` writes are checked against the declarations of
the LEXICALLY ENCLOSING class only — an unrelated class with its own
(unguarded) ``admitted`` attribute is not constrained by ResidentFlight's
declaration.  Writes through any other base (``engine.fault_bulk_retries``)
cannot be class-resolved statically and check against the global registry
of guarded attribute names: satisfied by holding ANY declared lock for
that name on the same base expression.

Lexical, not a race detector: a helper called under the lock but not
named ``*_locked`` is flagged on purpose — the suffix IS the documented
contract the next reader relies on.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from distributed_sudoku_solver_tpu.analysis.common import (
    GUARD_RE,
    Finding,
    QualnameVisitor,
    SourceModule,
    finding,
)


@dataclasses.dataclass(frozen=True)
class GuardDecl:
    attr: str
    lock: str
    path: str
    line: int
    qualclass: str  # lexical class qualname of the declaration ("" = module)


def _write_target(node: ast.AST) -> Optional[ast.Attribute]:
    """The Attribute actually written by an assignment target —
    ``self.x`` directly, or ``self.d[k]`` (mutating the dict the
    attribute holds counts as writing the guarded state)."""
    if isinstance(node, ast.Attribute):
        return node
    if isinstance(node, ast.Subscript) and isinstance(
        node.value, ast.Attribute
    ):
        return node.value
    return None


class _ClassStackVisitor(QualnameVisitor):
    """QualnameVisitor that additionally tracks the class-only stack, so
    a write inside ``ResidentFlight.admit`` resolves to class
    ``ResidentFlight`` even though the full stack mixes functions in."""

    def __init__(self) -> None:
        super().__init__()
        self.class_stack: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        super().visit_ClassDef(node)
        self.class_stack.pop()

    @property
    def qualclass(self) -> str:
        return ".".join(self.class_stack)


def collect_guards(mod: SourceModule) -> List[GuardDecl]:
    out: List[GuardDecl] = []

    class V(_ClassStackVisitor):
        def _decl(self, target: ast.AST, line: int) -> None:
            comment = mod.comments.get(line, "")
            m = GUARD_RE.search(comment)
            if m is None:
                return
            attr = _write_target(target)
            if attr is None:
                return
            out.append(GuardDecl(
                attr=attr.attr,
                lock=m.group(1),
                path=mod.rel,
                line=line,
                qualclass=self.qualclass,
            ))

        def visit_Assign(self, node: ast.Assign) -> None:
            for t in node.targets:
                self._decl(t, node.lineno)
            self.generic_visit(node)

        def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
            self._decl(node.target, node.lineno)
            self.generic_visit(node)

    V().visit(mod.tree)
    return out


class _LockVisitor(_ClassStackVisitor):
    def __init__(
        self,
        mod: SourceModule,
        self_guards: Dict[Tuple[str, str, str], str],
        any_guards: Dict[str, Set[str]],
        decl_lines,
    ):
        super().__init__()
        self.mod = mod
        # (path, qualclass, attr) -> lock: self-writes resolve against
        # the lexically enclosing class's own declarations.
        self.self_guards = self_guards
        # attr -> {lock, ...}: the cross-base fallback registry.
        self.any_guards = any_guards
        self.decl_lines = decl_lines
        self.with_ctx: List[str] = []  # unparsed context exprs in scope
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        ctxs = []
        for item in node.items:
            try:
                ctxs.append(ast.unparse(item.context_expr))
            except Exception:  # pragma: no cover
                pass
        self.with_ctx.extend(ctxs)
        self.generic_visit(node)
        del self.with_ctx[len(self.with_ctx) - len(ctxs) :]

    def _check_write(self, target: ast.AST, line: int) -> None:
        attr = _write_target(target)
        if attr is None:
            return
        if (self.mod.rel, line) in self.decl_lines:
            return  # the declaration site itself
        try:
            base = ast.unparse(attr.value)
        except Exception:  # pragma: no cover
            base = "self"
        if base == "self":
            lock = self.self_guards.get(
                (self.mod.rel, self.qualclass, attr.attr)
            )
            locks = {lock} if lock is not None else set()
        else:
            locks = self.any_guards.get(attr.attr, set())
        if not locks:
            return
        if any(f"{base}.{lock}" in self.with_ctx for lock in locks):
            return
        if self.stack and self.stack[-1].endswith("_locked"):
            return
        wanted = " or ".join(
            f"`with {base}.{lock}:`" for lock in sorted(locks)
        )
        self.findings.append(finding(
            self.mod, "lockck", target,
            f"write to guarded attribute '{attr.attr}' outside {wanted} "
            "(declare the helper `*_locked` if the caller holds it, or "
            "waive with reason)",
            def_lines=tuple(self.def_lines),
        ))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_write(node.target, node.lineno)
        self.generic_visit(node)


def check_modules(mods: List[SourceModule]) -> List[Finding]:
    """Two passes over the whole scan set: collect guard declarations,
    then verify every write.  Self-writes check the declaring class's
    own guards; base-named writes (http's ``engine.fault_bulk_retries``
    bump) check the global name registry."""
    decls: List[GuardDecl] = []
    for mod in mods:
        decls.extend(collect_guards(mod))
    self_guards: Dict[Tuple[str, str, str], str] = {}
    any_guards: Dict[str, Set[str]] = {}
    findings: List[Finding] = []
    for d in decls:
        key = (d.path, d.qualclass, d.attr)
        prev = self_guards.get(key)
        if prev is not None and prev != d.lock:
            findings.append(Finding(
                "lockck", d.path, d.line,
                f"attribute '{d.attr}' declared twice in "
                f"'{d.qualclass or '<module>'}' with conflicting guards "
                f"('{prev}' vs '{d.lock}')",
            ))
        self_guards[key] = d.lock
        any_guards.setdefault(d.attr, set()).add(d.lock)
    decl_lines = {(d.path, d.line) for d in decls}
    for mod in mods:
        v = _LockVisitor(mod, self_guards, any_guards, decl_lines)
        v.visit(mod.tree)
        findings.extend(v.findings)
    return findings
