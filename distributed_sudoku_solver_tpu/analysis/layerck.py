"""layerck: prove the import-layering manifest against real import nodes.

Every ``import``/``from`` node in the tree — including ones nested inside
functions, the lazy-import idiom this codebase uses everywhere — is
resolved to a dotted target and checked against the longest-prefix rule
in ``manifest.LAYERS``.  Closed layers whitelist (stdlib + declared
siblings + declared third-party roots); open layers blacklist forbidden
prefixes with declared carve-outs.  See manifest.py for the rule
semantics and the docstring contracts each entry encodes.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from distributed_sudoku_solver_tpu.analysis.common import (
    Finding,
    SourceModule,
    finding,
    stdlib_top,
)

PACKAGE = "distributed_sudoku_solver_tpu"


def _dotted_prefix(prefix: str, name: str) -> bool:
    return name == prefix or name.startswith(prefix + ".")


def _rule_for(modname: str, layers: Dict[str, dict]) -> Optional[Tuple[str, dict]]:
    best = None
    for key, rule in layers.items():
        if _dotted_prefix(key, modname):
            if best is None or len(key) > len(best[0]):
                best = (key, rule)
    return best


def _targets(node: ast.AST, modname: str, package: str) -> List[str]:
    """Absolute dotted targets of one import node (relative imports are
    resolved against the importing module's package path)."""
    if isinstance(node, ast.Import):
        return [a.name for a in node.names]
    assert isinstance(node, ast.ImportFrom)
    if node.level:
        parts = modname.split(".")
        # level 1 = the module's own package, each extra level one up.
        keep = len(parts) - node.level
        base_parts = [package] + parts[: max(keep, 0)]
        base = ".".join(p for p in base_parts if p)
        mod = f"{base}.{node.module}" if node.module else base
        return [mod]
    mod = node.module or ""
    # Qualify by the imported names: ``from pkg.serving import faults``
    # is an import OF ``serving.faults``, and the rules (carve-outs like
    # ops' declared ``serving.faults`` seam) must see it that way.  A
    # symbol import (``from pkg.cluster.wire import WireError``) gains a
    # trailing component the dotted-prefix matching ignores.
    return [f"{mod}.{a.name}" for a in node.names] if mod else [mod]


def _internal_allowed(target: str, allow: Tuple[str, ...]) -> bool:
    # Prefix in either direction: importing the parent package to reach a
    # declared submodule keeps the same promise (manifest.py note).
    return any(
        _dotted_prefix(a, target) or _dotted_prefix(target, a) for a in allow
    )


def check_module(
    mod: SourceModule,
    layers: Dict[str, dict],
    package: str = PACKAGE,
) -> List[Finding]:
    if mod.modname is None:
        return []
    matched = _rule_for(mod.modname, layers)
    if matched is None:
        return []
    key, rule = matched
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        for target in _targets(node, mod.modname, package):
            if not target:
                continue
            if _dotted_prefix(package, target):
                internal = target[len(package) :].lstrip(".")
                if not internal:
                    continue  # bare package import: the lazy __init__
                if rule.get("closed"):
                    if not _internal_allowed(internal, rule.get("allow", ())):
                        out.append(finding(
                            mod, "layerck", node,
                            f"closed layer '{key}' imports internal module "
                            f"'{internal}' (allowed: "
                            f"{', '.join(rule.get('allow', ())) or 'none'})",
                        ))
                else:
                    for forb in rule.get("forbid", ()):
                        if _dotted_prefix(forb, internal) and not any(
                            _dotted_prefix(exc, internal)
                            for exc in rule.get("except", ())
                        ):
                            out.append(finding(
                                mod, "layerck", node,
                                f"layer '{key}' must not import '{forb}' "
                                f"(got '{internal}')",
                            ))
                            break
            elif not stdlib_top(target):
                if rule.get("closed") and target.split(".", 1)[0] not in rule.get(
                    "third_party", ()
                ):
                    out.append(finding(
                        mod, "layerck", node,
                        f"closed layer '{key}' imports third-party "
                        f"'{target}' (stdlib only"
                        + (
                            " + " + ", ".join(rule["third_party"])
                            if rule.get("third_party")
                            else ""
                        )
                        + ")",
                    ))
    return out
