"""The repo's invariant manifest: the contracts the checkers prove.

Everything here is DATA — the one place where the layering promises the
module docstrings make, the clock seams the simnet lane trusts, the
host-sync seam the round-8 rewrite paid for, and the lock conventions the
review rounds kept re-finding by hand, are written down once and enforced
by ``python -m distributed_sudoku_solver_tpu.analysis`` (see the package
docstring for the waiver grammar).

A plain Python dict/tuple module on purpose (ISSUE 10 allows
``layers.toml`` *or* a py dict): the container pins Python 3.10, which has
no ``tomllib``, and ``tests/conftest.py`` imports the runtime banned list
from here directly — one source of truth for the static AND runtime lanes.
"""

from __future__ import annotations

# -- layerck -------------------------------------------------------------
#
# Keys are package-relative dotted module prefixes; the LONGEST matching
# prefix wins (so ``serving.faults`` overrides ``serving``).  Two rule
# shapes:
#
# * closed layer (``closed=True``): stdlib + the listed internal prefixes
#   + the listed third-party roots ONLY.  An internal target matches an
#   ``allow`` entry by dotted-prefix in either direction (importing the
#   ``cluster`` package to reach ``cluster.wire`` is the same promise as
#   importing ``cluster.wire``).
# * open layer (``closed=False``): anything goes EXCEPT the ``forbid``
#   dotted prefixes, minus the ``except`` carve-outs.
#
# The rules below are the docstring promises, verbatim:
# obs/ is stdlib + its own siblings and never imports serving back
# (obs/trace.py module note); serving/faults.py is stdlib-only and
# imported by engine/scheduler/bulk/cluster, never importing back
# (faults.py docstring); cluster/wire.py is the stdlib wire layer;
# cluster/simnet.py is wire + the fault-schedule machinery and nothing
# else (simnet.py docstring); ops/ and models/ are the compute layers and
# never reach up into serving/cluster — with the ONE declared exception of
# the ``serving.faults`` injection seam at ``bulk.dispatch``.
LAYERS = {
    "obs": {"closed": True, "allow": ("obs",), "third_party": ()},
    # ...with ONE declared exception inside obs: the compile watch
    # (obs/compilewatch.py) IS the live jax-compile observability plane —
    # its jax import is lazy, behind the install seam (production with no
    # watch installed never executes it), and it reads the pure-data
    # ENTRY_POINTS registry from analysis/manifest so jaxck, the retrace
    # guard, and the production watch attribute compilations to ONE
    # shared program vocabulary.  Mirrors the analysis.jaxck carve-out.
    "obs.compilewatch": {
        "closed": True,
        "allow": ("obs", "analysis.manifest"),
        "third_party": ("jax",),
    },
    # ...and a second declared exception: obs.lockdep is the runtime
    # half of the deadck thread-plane contract (ISSUE 13) — it reads the
    # pure-data lock hierarchy (LOCK_RANKS / LOCK_EDGE_DECLARED) lazily,
    # inside install(), exactly the compilewatch pattern.
    "obs.lockdep": {
        "closed": True,
        "allow": ("obs", "analysis.manifest"),
        "third_party": (),
    },
    # faults/simnet stay stdlib-closed EXCEPT the named-lock factories:
    # obs/lockdep.py is itself stdlib-only at import, so the closed
    # layers' "no heavy deps" promise is intact — the allowance is how
    # their locks join the one named hierarchy deadck/lockdep prove.
    "serving.faults": {
        "closed": True,
        "allow": ("obs.lockdep",),
        "third_party": (),
    },
    "cluster.wire": {"closed": True, "allow": (), "third_party": ()},
    "cluster.simnet": {
        "closed": True,
        "allow": ("cluster.wire", "serving.faults", "obs.lockdep"),
        "third_party": (),
    },
    # The DHT plane (cluster/dht/, ISSUE 17) is a closed stdlib layer:
    # gossip membership, the consistent-hash ring, and the cluster-cache
    # shard are pure protocol state machines over injected seams (clock,
    # owner_fn, request_fn) — no jax, no numpy, no serving.  Entries
    # cross this layer as plain dicts; the CacheEntry glue lives in
    # cluster/node.py, which may import anything cluster already does.
    "cluster.dht": {
        "closed": True,
        "allow": ("cluster.dht", "cluster.wire", "obs.lockdep"),
        "third_party": (),
    },
    # The checker's own layer: source-only tooling.  stdlib + obs (the
    # shared *ck exit-code contract) — importing jax here would break the
    # "<5 s, no jax" acceptance the tier-1 test pins.
    "analysis": {"closed": True, "allow": ("analysis", "obs"), "third_party": ()},
    # ...with ONE declared exception: jaxck IS the jax lane.  Its jax /
    # numpy imports and its reach-down into the compute layers (the
    # eval_shape builders for canonical Frontier specs) are all lazy,
    # inside functions, behind ``--rule jaxck`` — the fast lane never
    # executes them, and tests/test_analysis.py pins that the default
    # run still never imports jax.
    "analysis.jaxck": {
        "closed": True,
        "allow": (
            "analysis",
            "obs",
            "models.geometry",
            "ops.frontier",
            "ops.solve",
            "serving.scheduler",
        ),
        "third_party": ("jax", "numpy"),
    },
    "ops": {
        "closed": False,
        "forbid": ("serving", "cluster", "analysis"),
        "except": ("serving.faults",),
    },
    "models": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    "parallel": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    "utils": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    "native": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    # The front door (serving/frontdoor, ISSUE 14) is a closed layer:
    # host-side stdlib + numpy compute (canonical.py has NO jax — the
    # whole point is answering without a device), the obs planes, its
    # serving siblings (portfolio's race_native seam; engine's Job), the
    # geometry model, and the native DFS.  Never cluster: routing happens
    # per node, and cache state is deliberately node-local.
    "serving.frontdoor": {
        "closed": True,
        "allow": ("serving", "obs", "models.geometry", "native"),
        "third_party": ("numpy",),
    },
    # The brownout controller (serving/brownout.py, ISSUE 15) is a closed
    # stdlib+obs layer: it reads the SLO plane and writes trace events,
    # but every serving-side signal reaches it as an injected callable
    # (engine_signals' duck-typed closures) — importing the engine back
    # would cycle through engine.metrics' brownout section.
    "serving.brownout": {"closed": True, "allow": ("obs",), "third_party": ()},
    # The job journal (serving/journal.py, ISSUE 20) is a closed
    # stdlib+obs layer like brownout, plus the serving.faults sites
    # (journal.append / journal.fsync) — faults is itself stdlib +
    # obs.lockdep, so the "no heavy deps" promise holds transitively and
    # the lint.yml fast lane proves the no-jax import at runtime.  The
    # engine reaches it through the install/active seam; the journal
    # never imports the engine back.
    "serving.journal": {
        "closed": True,
        "allow": ("obs", "serving.faults"),
        "third_party": (),
    },
    # serving sits BELOW cluster (cluster/node.py imports serving.engine):
    # a serving -> cluster import would be a cycle by construction.
    "serving": {"closed": False, "forbid": ("cluster",)},
}

# -- clockck -------------------------------------------------------------
#
# Directories where bare wall-clock CALLS are banned: every timing
# decision in these layers must route through an injected clock (the
# ``clock=...`` parameter/field defaults that *reference* these functions
# are the injection seam and are allowed — clockck flags calls, not
# references).  This is the static, whole-tree form of the simnet runtime
# guard's promise (tests/conftest.py).
CLOCK_SCOPED_DIRS = ("cluster", "serving", "obs")

# (module, attr) call targets that count as bare clock access.  The
# whole spelling family, not just the four the docstrings name — a rule
# that misses ``perf_counter()`` or ``monotonic_ns()`` is laundered by a
# rename (review-round finding).
CLOCK_BANNED_CALLS = (
    ("time", "time"),
    ("time", "time_ns"),
    ("time", "monotonic"),
    ("time", "monotonic_ns"),
    ("time", "perf_counter"),
    ("time", "perf_counter_ns"),
    ("time", "sleep"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
)

# Declared seams: qualname prefixes (per package-relative file) whose
# bodies may touch the real clock.  These are the places whose WHOLE JOB
# is to be the wall-clock boundary:
# * wire.SystemClock — the production clock behind ClusterNode's
#   injectable seam; late-bound on purpose so the runtime guard still
#   catches a simnet test that forgot ``clock=net.clock``.
# * SimNet.sleep/advance/settle — simnet's bounded REAL settling waits
#   (never slept on; see the ``_monotonic`` import-time capture note in
#   cluster/simnet.py).
CLOCK_SEAMS = {
    "cluster/wire.py": ("SystemClock",),
    "cluster/simnet.py": ("SimNet.sleep", "SimNet.advance", "SimNet.settle"),
}

# The runtime twin (tests/conftest.py imports this): module attributes
# monkeypatched to raise inside ``simnet``-marked tests.  Superset of the
# sleep/monotonic half of CLOCK_BANNED_CALLS (pinned by
# tests/test_analysis.py) plus the socket escapes — now including
# select/selectors-level waits, which are sleeps and socket IO in one
# call.  ``time.time`` is deliberately ABSENT from the runtime list:
# logging.LogRecord reads it on every record, so a runtime ban would fail
# any simnet test the moment a node logs — the static lane (clockck)
# covers time.time instead.
SIMNET_RUNTIME_BANNED = (
    ("socket", "socket"),
    ("socket", "create_connection"),
    ("socket", "create_server"),
    ("select", "select"),
    ("selectors", "DefaultSelector"),
    ("selectors", "SelectSelector"),
    ("selectors", "PollSelector"),
    ("selectors", "EpollSelector"),
    ("selectors", "KqueueSelector"),
    ("time", "sleep"),
    ("time", "monotonic"),
)

# -- syncck --------------------------------------------------------------
#
# Files under the round-8 "one sync per chunk" contract, and within them
# the hot-loop regions (qualname prefixes) where a device-sync-forcing
# call must either route through the ``host_fetch`` seam
# (serving/engine.py) or prove its operand host-side (assigned from a
# ``host_fetch``/``unpack_status`` result — the checker tracks that
# dataflow) or carry a reasoned syncck waiver comment.  Outside the
# hot regions the same sync-forcing calls are still flagged (waiver
# required), but the int()/float()-on-indexed-value heuristic only runs
# inside hot regions — metrics/stats plumbing coerces host ints
# everywhere and is not the hazard this rule hunts.
#
# Round 14 extends the proof beyond engine/scheduler to the other two
# chunked dispatch loops the round-8 rewrite paid for: the bulk rung
# drain loop (``ops/bulk.py`` — status-riding, buffer-donated advances;
# one status fetch per dispatch) and the portfolio racer's poll/drain
# (``serving/portfolio.py`` — the cover race's between-dispatch liveness
# poll is that loop's one deliberate sync).
# Round 19 extends it again to the latency-mode serving megastep
# (``serving/megastep.py``): its whole contract is ONE host sync per
# flight (attach through verdict), so the flight body is a hot region —
# any stray sync there silently doubles the tier's latency floor.
SYNC_SCOPED_FILES = (
    "serving/engine.py",
    "serving/scheduler.py",
    "ops/bulk.py",
    "serving/portfolio.py",
    "serving/megastep.py",
    "serving/mesh_scheduler.py",
)

SYNC_HOT_REGIONS = {
    "serving/engine.py": (
        "SolverEngine._advance_flight",
        "SolverEngine._resolve_solved",
        "SolverEngine._do_snapshot",
        "SolverEngine._do_shed",
    ),
    "serving/scheduler.py": (
        "ResidentFlight.step",
        "ResidentFlight._consume_status",
        "ResidentFlight._collect_and_detach",
        "ResidentFlight._attach_pending",
        "ResidentFlight._advance",
    ),
    "ops/bulk.py": (
        "solve_bulk.run_rung_stepped",
        "solve_bulk.drain",
    ),
    "serving/portfolio.py": (
        "race_jobs",
        "race_cover.device_entrant",
    ),
    # The megastep's one-sync-per-flight contract, PROVEN: everything
    # from admission to verdict runs inside these two bodies, so syncck
    # sees every host-transfer call the flight could ever make.
    "serving/megastep.py": (
        "MegastepFlight.solve",
        "MegastepFlight._fly",
    ),
    # The mesh-resident flight (round 21) inherits the scheduler's hot
    # round bodies verbatim; only its strategy hooks are new code — they
    # run INSIDE those pinned bodies, so they are hot regions themselves
    # (the mesh loop's one-sync-per-chunk contract is the same proof).
    "serving/mesh_scheduler.py": (
        "MeshResidentFlight._unpack",
        "MeshResidentFlight._advance_bound",
        "MeshResidentFlight._mesh_attach",
        "MeshResidentFlight._mesh_detach",
    ),
}

# Functions whose BODY is the seam (exempt) and whose results prove their
# targets host-side for the dataflow pass.
SYNC_SEAM_FUNCS = ("host_fetch",)
SYNC_HOST_SOURCES = (
    "host_fetch",
    "unpack_status",
    "unpack_mesh_status",
    # The flight's strategy hook: dispatches to unpack_status (single-chip)
    # or unpack_mesh_status (mesh) over an already-host_fetch-ed word, so
    # its result is host data by construction on either path.
    "_unpack",
)

# numpy-module call names that force a device->host transfer when handed
# a jax array (jnp.asarray is the opposite direction and exempt).
SYNC_NUMPY_CALLS = ("asarray", "ascontiguousarray")
# method calls that force a sync on any jax value.
SYNC_METHOD_CALLS = ("item", "block_until_ready")
# jax-module call names that ARE the sync primitive.
SYNC_JAX_CALLS = ("device_get",)

# -- jaxck ---------------------------------------------------------------
#
# The compiled-layer manifest: every jit entry point the serving path
# prices, declared as DATA so ``analysis/jaxck.py`` can abstractly trace
# each one at canonical tiny shapes (``jax.jit(...).trace``/``.lower()``
# — no execution, no device) and prove the four compiled-layer
# invariants: donation lowers to real ``input_output_aliases``, hot
# programs are callback-free, dtypes stay disciplined, and the
# canonicalized jaxpr fingerprint matches the committed golden
# (``analysis/goldens/jaxck.json``) so HLO drift — which invalidates
# ``.cache/xla`` for every containing program — is visible and blessed
# explicitly (``--update-golden``), never a mystery tier-1 slowdown.
#
# This module stays jax-free: everything below is strings/ints.  The
# spec mini-language is resolved by jaxck (the only rule that imports
# jax, lazily, behind ``--rule jaxck``):
#
# * array arg:     ("array", (dims...), dtype)   dims are ints or keys
#                  into JAXCK_CANON["dims"]
# * frontier arg:  ("frontier", <config name>)   an abstract Frontier via
#                  jax.eval_shape over init_frontier_roots at L lanes /
#                  J jobs of the named canonical config
# * resident arg:  ("resident",)                 the scheduler's gang
#                  frontier via eval_shape over _init_resident
# * static values: "geom" (canonical Geometry), "config"/"config_fused"/
#                  "config_gang" (canonical SolverConfigs), "mesh"
#                  (1-device mesh — pinned to ONE device so goldens are
#                  host-independent), "problem" (sudoku_csp at canon),
#                  ("dim", name), or a bare int/str literal.
JAXCK_CANON = {
    # 4x4 boards, 8 lanes, 4 jobs, 4-deep stacks: the smallest shapes
    # every entry point accepts (fused kernels included) — tracing cost
    # is shape-independent, and goldens must be cheap to re-derive.
    "geom": (2, 2),
    "dims": {"L": 8, "J": 4, "n": 4, "G": 2, "slots": 4},
    "configs": {
        "config": {"lanes": 8, "min_lanes": 8, "stack_slots": 4, "max_steps": 64},
        "config_fused": {
            "lanes": 8, "min_lanes": 8, "stack_slots": 4, "max_steps": 64,
            "step_impl": "fused", "fused_steps": 2,
        },
        # The resident-scheduler shape: slots gangs of G lanes each.
        "config_gang": {
            "lanes": 8, "min_lanes": 8, "stack_slots": 4, "max_steps": 64,
            "steal_gang": 2,
        },
        # The MESH-resident shape: what serving/mesh_scheduler._solver_config
        # actually runs — home lanes excluded as steal thieves (the attach
        # overwrite soundness flag; see SolverConfig.protect_home_lanes).
        # A separate fixture so single-chip resident goldens stay pinned to
        # the unprotected jaxpr they really compile.
        "config_mesh": {
            "lanes": 8, "min_lanes": 8, "stack_slots": 4, "max_steps": 64,
            "steal_gang": 2, "protect_home_lanes": True,
        },
        # Scored branch ordering (ISSUE 19): the head-enabled advance
        # programs are DIFFERENT jaxprs (the head's score graph replaces
        # the packed popcount key), so they carry their own canon configs
        # and their own goldens — the default ``config``/``config_fused``
        # entries above must stay byte-identical to pre-head rounds.
        # cw-slack is the canon head: pure VPU, deterministic, no weights
        # file to load at trace time.
        "config_head": {
            "lanes": 8, "min_lanes": 8, "stack_slots": 4, "max_steps": 64,
            "branch": "head:cw-slack",
        },
        "config_fused_head": {
            "lanes": 8, "min_lanes": 8, "stack_slots": 4, "max_steps": 64,
            "step_impl": "fused", "fused_steps": 2, "branch": "head:cw-slack",
        },
    },
}

# One entry per compiled program on the serving/bulk path.  Fields:
#   name     report id (module-relative dotted path)
#   display  short human name, UNIQUE across entries — the shared
#            vocabulary of the compiled layer: jaxck findings cite it,
#            the retrace guard keys on it, and the production compile
#            watch (obs/compilewatch.py) exports per-program /metrics
#            series under it ("jaxck drift blessed here is what
#            compilewatch alarms on there")
#   fn       "importable.module:attr"
#   args     dynamic (traced) arg specs, in order
#   static   static kwargs: param name -> canon spec
#   donate   flattened-arg indices declared donated (mirrors the
#            decorator — jaxck cross-checks the lowering, not this tuple)
#   donation 'threads' = every donated leaf MUST alias an output (the
#            round-8 zero-copy contract: the caller always rebinds);
#            'drains' = terminal programs whose donation frees buffers
#            rather than aliasing them — the alias count is recorded in
#            the golden (drift-visible) but not asserted
#   hot      in a serving hot loop: callback primitives are banned
ENTRY_POINTS = (
    # serving/engine.py — static-flight lifecycle
    dict(
        name="serving.engine._start_roots", display="start_roots",
        fn="distributed_sudoku_solver_tpu.serving.engine:_start_roots",
        args=(("array", ("L", "n", "n"), "uint32"), ("array", ("L",), "int32")),
        static={"n_jobs": ("dim", "J"), "config": "config"},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="serving.engine._start_packed", display="start_packed",
        fn="distributed_sudoku_solver_tpu.serving.engine:_start_packed",
        args=(("array", ("L", "n", "n"), "uint32"), ("array", ("L",), "bool")),
        static={"config": "config"},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="serving.engine._purge", display="purge",
        fn="distributed_sudoku_solver_tpu.serving.engine:_purge",
        args=(("frontier", "config"), ("array", ("J",), "bool")),
        static={},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="serving.engine._shed_jit", display="shed",
        fn="distributed_sudoku_solver_tpu.serving.engine:_shed_jit",
        args=(("frontier", "config"), ("array", (), "int32")),
        static={"k": 2},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="serving.engine._flight_verdict_jit", display="flight_verdict",
        fn="distributed_sudoku_solver_tpu.serving.engine:_flight_verdict_jit",
        args=(("frontier", "config"),),
        static={},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="serving.engine._finalize_jit", display="finalize",
        fn="distributed_sudoku_solver_tpu.serving.engine:_finalize_jit",
        args=(("frontier", "config"),),
        static={},
        donate=(0,), donation="drains", hot=True,
    ),
    # serving/scheduler.py — resident-flight lifecycle
    dict(
        name="serving.scheduler._init_resident", display="resident_init",
        fn="distributed_sudoku_solver_tpu.serving.scheduler:_init_resident",
        args=(),
        static={"geom": "geom", "config": "config_gang", "n_slots": ("dim", "slots")},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="serving.scheduler._attach_jit", display="resident_attach",
        fn="distributed_sudoku_solver_tpu.serving.scheduler:_attach_jit",
        args=(
            ("resident",),
            ("array", ("G", "n", "n"), "int32"),
            ("array", ("G",), "int32"),
        ),
        static={"geom": "geom", "gang": ("dim", "G")},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="serving.scheduler._detach_jit", display="resident_detach",
        fn="distributed_sudoku_solver_tpu.serving.scheduler:_detach_jit",
        args=(("resident",), ("array", ("slots",), "bool")),
        static={},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="serving.scheduler._verdict_jit", display="resident_verdict",
        fn="distributed_sudoku_solver_tpu.serving.scheduler:_verdict_jit",
        args=(("resident",),),
        static={},
        donate=(), donation=None, hot=True,
    ),
    # serving/portfolio.py — the cover-race device entrant's advance
    dict(
        name="serving.portfolio._advance_cover", display="cover_advance",
        fn="distributed_sudoku_solver_tpu.serving.portfolio:_advance_cover",
        args=(("frontier", "config"), ("array", (), "int32")),
        static={"problem": "problem", "config": "config"},
        donate=(), donation=None, hot=True,
    ),
    # ops/bulk.py — escalation-rung lifecycle
    dict(
        name="ops.bulk._rung_start", display="rung_start",
        fn="distributed_sudoku_solver_tpu.ops.bulk:_rung_start",
        args=(("array", ("J", "n", "n"), "uint8"),),
        static={"geom": "geom", "scfg": "config"},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="ops.bulk._rung_finish", display="rung_finish",
        fn="distributed_sudoku_solver_tpu.ops.bulk:_rung_finish",
        args=(("frontier", "config"),),
        static={"geom": "geom"},
        donate=(0,), donation="drains", hot=True,
    ),
    # utils/checkpoint.py — the composite chunked-advance programs
    dict(
        name="utils.checkpoint.start_frontier", display="start_frontier",
        fn="distributed_sudoku_solver_tpu.utils.checkpoint:start_frontier",
        args=(("array", ("J", "n", "n"), "int32"),),
        static={"geom": "geom", "config": "config"},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="utils.checkpoint.advance_frontier", display="advance",
        fn="distributed_sudoku_solver_tpu.utils.checkpoint:advance_frontier",
        args=(("frontier", "config"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config"},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="utils.checkpoint.advance_frontier_status", display="advance_status",
        fn="distributed_sudoku_solver_tpu.utils.checkpoint:advance_frontier_status",
        args=(("frontier", "config"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config"},
        donate=(0,), donation="threads", hot=True,
    ),
    # ops/frontier.py / ops/pallas_step.py — the latency-mode serving
    # megastep (round 19): N advance chunks fused into ONE donated
    # dispatch via an in-graph while_loop with early exit on
    # all-solved/all-dead.  Both scalars are TRACED (chunk_steps,
    # max_chunks) so retuning the flight budget never recompiles — the
    # compile watch alarms if it ever does.
    dict(
        name="ops.frontier.advance_megastep", display="advance_megastep",
        fn="distributed_sudoku_solver_tpu.ops.frontier:advance_megastep",
        args=(("frontier", "config"), ("array", (), "int32"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config"},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="ops.pallas_step.advance_megastep_fused", display="advance_megastep_fused",
        fn="distributed_sudoku_solver_tpu.ops.pallas_step:advance_megastep_fused",
        args=(("frontier", "config_fused"), ("array", (), "int32"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config_fused"},
        donate=(0,), donation="threads", hot=True,
    ),
    # ops/pallas_step.py — the fused twins (abstract tracing never
    # compiles Mosaic, so these prove out on any backend)
    dict(
        name="ops.pallas_step.advance_frontier_fused", display="advance_fused",
        fn="distributed_sudoku_solver_tpu.ops.pallas_step:advance_frontier_fused",
        args=(("frontier", "config_fused"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config_fused"},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="ops.pallas_step.advance_frontier_fused_status", display="advance_fused_status",
        fn="distributed_sudoku_solver_tpu.ops.pallas_step:advance_frontier_fused_status",
        args=(("frontier", "config_fused"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config_fused"},
        donate=(0,), donation="threads", hot=True,
    ),
    # Scored branch ordering (ISSUE 19): the SAME advance programs traced
    # under the head:cw-slack canon configs.  The branch head's score
    # graph is part of the jaxpr, so head drift gets its own golden pair
    # here instead of hiding inside (or perturbing) the default entries
    # above.  ``@head`` in the name is a golden-key suffix, not a module
    # path — ``fn`` is what resolves.
    dict(
        name="utils.checkpoint.advance_frontier@head", display="advance_head",
        fn="distributed_sudoku_solver_tpu.utils.checkpoint:advance_frontier",
        args=(("frontier", "config_head"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config_head"},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="ops.pallas_step.advance_frontier_fused@head", display="advance_fused_head",
        fn="distributed_sudoku_solver_tpu.ops.pallas_step:advance_frontier_fused",
        args=(("frontier", "config_fused_head"), ("array", (), "int32")),
        static={"geom": "geom", "config": "config_fused_head"},
        donate=(0,), donation="threads", hot=True,
    ),
    # parallel/ — the sharded drivers (bulk tier; no donation today, but
    # their HLO prices the multi-chip cache exactly the same way)
    dict(
        name="parallel.sharded._solve_sharded_jit", display="sharded_solve",
        fn="distributed_sudoku_solver_tpu.parallel.sharded:_solve_sharded_jit",
        args=(("array", ("J", "n", "n"), "int32"),),
        static={"geom": "geom", "config": "config", "mesh": "mesh"},
        donate=(), donation=None, hot=False,
    ),
    dict(
        name="parallel.fused_sharded._solve_fused_sharded_jit", display="fused_sharded_solve",
        fn="distributed_sudoku_solver_tpu.parallel.fused_sharded:_solve_fused_sharded_jit",
        args=(("array", ("J", "n", "n"), "int32"),),
        static={"geom": "geom", "config": "config_fused", "mesh": "mesh"},
        donate=(), donation=None, hot=False,
    ),
    dict(
        name="parallel.board_sharded._solve_banded_jit", display="banded_solve",
        fn="distributed_sudoku_solver_tpu.parallel.board_sharded:_solve_banded_jit",
        args=(("array", ("J", "n", "n"), "int32"),),
        static={"geom": "geom", "config": "config", "mesh": "mesh"},
        donate=(), donation=None, hot=False,
    ),
    # parallel/mesh_resident.py — the mesh-resident serving programs
    # (round 21): the resident flight's init/attach/detach/advance twins
    # under shard_map, donated through every state-threading dispatch like
    # their single-chip parents (serving/scheduler.py above).  The
    # canonical mesh is 1-device (goldens stay host-independent); the
    # psum/ppermute/all_gather collectives degenerate to identities there,
    # which is exactly the bit-identity-to-single-chip contract the mesh
    # tests pin at runtime.
    dict(
        name="parallel.mesh_resident.mesh_init_resident", display="mesh_resident_init",
        fn="distributed_sudoku_solver_tpu.parallel.mesh_resident:mesh_init_resident",
        args=(),
        static={"geom": "geom", "config": "config_mesh",
                "n_slots": ("dim", "slots"), "mesh": "mesh"},
        donate=(), donation=None, hot=True,
    ),
    dict(
        name="parallel.mesh_resident.mesh_attach", display="mesh_resident_attach",
        fn="distributed_sudoku_solver_tpu.parallel.mesh_resident:mesh_attach",
        args=(
            ("resident",),
            ("array", ("G", "n", "n"), "int32"),
            ("array", ("G",), "int32"),
        ),
        static={"geom": "geom", "gang": ("dim", "G"), "mesh": "mesh"},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="parallel.mesh_resident.mesh_detach", display="mesh_resident_detach",
        fn="distributed_sudoku_solver_tpu.parallel.mesh_resident:mesh_detach",
        args=(("resident",), ("array", ("slots",), "bool")),
        static={"mesh": "mesh"},
        donate=(0,), donation="threads", hot=True,
    ),
    dict(
        name="parallel.mesh_resident.mesh_advance_status", display="mesh_advance_status",
        fn="distributed_sudoku_solver_tpu.parallel.mesh_resident:mesh_advance_status",
        args=(("resident",), ("array", (), "int32")),
        static={"geom": "geom", "config": "config_mesh", "mesh": "mesh"},
        donate=(0,), donation="threads", hot=True,
    ),
)

# The ONE derivation of an entry's display name (explicit ``display``,
# else the last dotted component) — jaxck, the retrace guard, and
# obs/compilewatch all key on it, and a second copy of the fallback rule
# would let the shared vocabulary fork silently (review-round finding).
def entry_display(entry: dict) -> str:
    return entry.get("display") or entry["name"].rsplit(".", 1)[-1]


DISPLAY_BY_NAME = {e["name"]: entry_display(e) for e in ENTRY_POINTS}

# Callback primitives banned from hot jaxprs: each one is a hidden
# host round-trip syncck cannot see (it fires at run time, inside the
# compiled program).  ``debug.print`` lowers to debug_callback.
JAXCK_BANNED_CALLBACKS = ("pure_callback", "io_callback", "debug_callback")

# Hot entry points granted a DOCUMENTED callback carve-out: entry name ->
# one-line reason.  This table is the design decision the megastep issue
# (round 16) demanded be explicit rather than waived inline: IF a
# device-resident mailbox ever needs a host callback to close its loop,
# the entry is listed here with its why, jaxck notes the allowance in its
# summary, and the callback stays drift-visible in the golden.  It is
# DELIBERATELY EMPTY today — the megastep's mailbox is pure-device (the
# packed status word + early-exit chunk count ride the one per-flight
# fetch), so its programs stay callback-free like every other hot
# program.  An entry added here is a reviewed contract change, not a
# local waiver.
JAXCK_CALLBACK_CARVEOUTS: dict = {}

# -- deadck --------------------------------------------------------------
#
# The thread-plane manifest: every lock in the repo, named and ranked.
# ``analysis/deadck.py`` (static) builds the whole-tree lock-acquisition
# graph and checks each edge against this hierarchy; ``obs/lockdep.py``
# (runtime) wraps the same named locks and raises on a violating or
# cycle-forming acquisition at the moment it happens.  One contract, two
# witnesses — the layerck/simnet split.
#
# The rule: a lock may be acquired only while every held lock has a
# STRICTLY SMALLER rank ("acquire rank-upward").  Rank gaps are left on
# purpose so a new lock slots in without renumbering.  The ordering
# encodes the call structure the repo actually has:
#
#   cluster.node (10) < cluster.exec (16)
#       the node's RLock is the outermost coordinator state; it calls
#       into per-job _Exec bookkeeping, engine submits, wire egress.
#   obs.slo (24) < serving.* (30..40)
#       obs.slo is deliberately NOT an obs leaf: the burn-dump holds it
#       across metrics_fn -> engine.metrics (see LOCK_EDGE_DECLARED), so
#       it must order BEFORE the serving locks — and the reverse nesting
#       (engine._lock held into slo.observe) is exactly the ABBA
#       deadlock this rank order makes a violation.
#   serving.engine (30) < serving.scheduler (34) < serving.breaker (38)
#       submit admits into resident flights under the engine lock; the
#       flight consults its circuit breaker under its own.
#   obs leaves (60..68)
#       pure sinks: metrics/trace/histogram recording.  Holding an obs
#       leaf while acquiring ANY serving/cluster lock is a violation by
#       construction (their ranks are above every coordination lock).
#   cluster.simnet (72)
#       the virtual network's condition is the terminal leaf: the
#       injected SimClock is read/slept-on under nearly every other
#       lock, and simnet's delivery path calls handlers only OUTSIDE it.
LOCK_RANKS = {
    "cluster.node": 10,       # cluster/node.py ClusterNode._lock (RLock)
    # Gossip sits just above the node lock: _gossip_beat / _dht_sync run
    # on the heartbeat and handler threads after releasing (or never
    # taking) the node lock, but reconcile() is reachable from paths
    # that held it — node (10) -> gossip (12) must be legal, the reverse
    # never happens (membership.py takes no other lock).
    "cluster.gossip": 12,     # cluster/dht/membership.py Gossip._lock
    "cluster.exec": 16,       # cluster/node.py _Exec.lock
    "obs.slo": 24,            # obs/slo.py SloMonitor._lock (RLock)
    # Between obs.slo and the serving coordination locks: the slo
    # burn-dump's metrics_fn closure reaches engine.metrics -> the
    # brownout section while HOLDING the slo RLock (so brownout must
    # rank above 24), and the controller's own lock is a LEAF by
    # construction — signal callables are read and transition side
    # effects fired with it released (serving/brownout.py evaluate), so
    # nothing is ever acquired under it.
    "serving.brownout": 28,   # serving/brownout.py BrownoutController._lock
    "serving.engine": 30,     # serving/engine.py SolverEngine._lock
    # The WAL lock sits just above the engine lock and BELOW the fault
    # injector: record_resolved runs under engine._lock on the
    # stop/drain sweep (30 -> 32 legal), and every append fires the
    # journal.append/journal.fsync fault sites while holding it
    # (32 -> 40 legal).  Nothing else is ever acquired under it — the
    # batcher thread takes it alone.
    "serving.journal": 32,    # serving/journal.py Journal._lock
    "serving.scheduler": 34,  # serving/scheduler.py ResidentFlight._lock
    # The mesh flight's telemetry lock sits between its parent's lock and
    # the megastep: MeshResidentFlight.metrics acquires the inherited
    # scheduler lock (34) and the mesh lock sequentially; rank 35 keeps
    # even a future nested acquisition (scheduler -> mesh telemetry)
    # rank-upward, while the reverse — holding the telemetry leaf into
    # admission state — is a violation by construction.
    "serving.mesh_scheduler": 35,  # serving/mesh_scheduler.py MeshResidentFlight._mesh_lock
    # Between the scheduler and the breaker: the megastep flight
    # (serving/megastep.py, round 19) is created under engine._lock
    # (30 < 36 legal) and consults its own circuit breaker under its
    # flight lock (36 < 38 legal) — the same nesting shape as the
    # resident flight one rank below.  solve() RELEASES the flight lock
    # before engine._finish_job so no obs/slo acquisition ever nests
    # under it.
    "serving.megastep": 36,   # serving/megastep.py MegastepFlight._lock
    "serving.breaker": 38,    # serving/faults.py CircuitBreaker._lock
    "serving.injector": 40,   # serving/faults.py FaultInjector._lock
    "serving.control": 42,    # serving/engine.py _Control.lock (dataclass field)
    "cluster.dedupe": 44,     # cluster/node.py _DedupeLRU._lock
    # The front door's two locks sit above the serving coordination
    # locks: route() runs on submit/handler threads holding nothing, and
    # the device loop's cache-fill hook (engine._finish_job ->
    # FrontDoor._device_resolved) may run under a flight lock — counter
    # bookkeeping (router, 45) and the LRU store (cache, 46) must both
    # be acquirable there.  Router before cache so a future "count under
    # the router lock while filling the store" nesting is legal; today
    # neither is ever held into the other.
    "frontdoor.router": 45,   # serving/frontdoor/router.py FrontDoor._lock
    "frontdoor.cache": 46,    # serving/frontdoor/cache.py ResultCache._lock
    "frontdoor.race": 47,     # serving/portfolio.py race_native settle lock
    #   (winner claim only — never held into another acquisition)
    # The DHT cache + ring locks rank ABOVE the front-door locks: the
    # router's L2 lookup/store seam (FrontDoor.route -> ClusterCache)
    # and the ring's owner_fn both run on front-door / device-loop
    # threads that may hold frontdoor.router/cache — and NEVER the node
    # lock (ClusterNode._ring_owner guards the ring with cluster.ring,
    # not cluster.node, for exactly this reason).  Cache before ring:
    # ClusterCache.lookup calls owner_fn BEFORE taking its own lock, so
    # neither nests under the other today; the order leaves "consult the
    # ring while holding the shard" legal if replication ever needs it.
    "cluster.dhtcache": 48,   # cluster/dht/cluster_cache.py ClusterCache._lock
    "cluster.ring": 49,       # cluster/node.py ClusterNode._ring_lock
    "native.build": 50,       # native/__init__.py _lock (libcsp build)
    "utils.profile_window": 52,  # utils/profiling.py _window_lock
    "obs.compilewatch": 60,   # obs/compilewatch.py CompileWatch._lock
    "obs.critpath": 62,       # obs/critpath.py CritPathMonitor._lock
    "obs.trace": 64,          # obs/trace.py TraceRecorder._lock
    "obs.hist": 66,           # obs/hist.py LatencyHistogram._lock
    "obs.ordertrace": 67,     # obs/ordertrace.py OrderTraceRecorder._lock
    "obs.minest": 68,         # obs/hist.py MinEstimator._lock
    "utils.statwindow": 69,   # utils/profiling.py StatWindow._lock (pure leaf)
    "cluster.simnet": 72,     # cluster/simnet.py SimNet._cond
}

# Blessed edges the rank order alone does not express — each carries its
# why, so the re-entrancy contracts review rounds kept re-deriving by
# hand are DECLARED, tool-checked facts (ISSUE 13).  deadck unions these
# into its predicted graph; lockdep allows them at runtime.
# The slo burn-dump re-entrancy (obs/slo.py _dump_locked): the monitor
# holds its RLock across metrics_fn -> engine.metrics() ->
# slo.active().metrics(), which re-enters the RLock.  Safe because
# (a) obs.slo ranks BEFORE serving.engine, so the reverse nesting is a
# violation, and (b) the engine feeds observe() lock-free (_finish_job
# runs outside engine._lock).  The whole metrics-snapshot closure is
# declared — engine.metrics reads every installed plane's lock — because
# metrics_fn is an injected callable deadck cannot see through: these
# edges exist only at run time, which is exactly why the runtime witness
# cross-checks against (static edges UNION this table).  Pinned by
# tests/test_deadck.py's re-entrancy test, not tribal knowledge.
_SLO_DUMP_REASON = (
    "burn-dump evidence capture: SloMonitor._dump_locked holds the "
    "monitor RLock across metrics_fn -> engine.metrics, which reads "
    "this plane's lock; the slo read-back re-enters the RLock, and the "
    "engine never holds its own lock into observe()"
)

LOCK_EDGE_DECLARED = {
    # Virtual-clock injection: a simnet-clocked SloMonitor (the replay
    # harness's virtual nodes, benchmarks/replay.py; any simnet-lane
    # monitor) reads clock=net.now — the SimNet condition — under its
    # own RLock.  Rank-legal by construction (obs.slo 24 < cluster.simnet
    # 72, the terminal leaf every injected SimClock read lands on), and
    # invisible to statics for the same injected-callable reason as the
    # burn-dump closure below.
    ("obs.slo", "cluster.simnet"): (
        "injected virtual clock: SloMonitor(clock=net.now) reads the "
        "SimNet condition inside its locked prune/observe paths"
    ),
    # Compile-under-lock (round 19): the megastep's FIRST flight
    # jit-compiles attach/advance/verdict inside the flight lock, and an
    # installed CompileWatch's jax monitoring callback records the
    # compile wall into a LatencyHistogram synchronously on the
    # compiling thread — so the flight lock transiently precedes
    # obs.hist.  Rank-upward (serving.megastep 36 < obs.hist 66) and
    # invisible to statics: the callback is registered with jax's
    # monitoring hook, not called from megastep source.  The direct
    # serving.megastep -> obs.compilewatch hop is already static (the
    # cost-plane capture_cost seam).
    ("serving.megastep", "obs.hist"): (
        "jax monitoring callback under the flight lock: the first "
        "flight's compile fires CompileWatch.on_duration -> "
        "LatencyHistogram.record on the compiling thread"
    ),
}
LOCK_EDGE_DECLARED.update({
    ("obs.slo", target): _SLO_DUMP_REASON
    for target in (
        # engine.metrics reads the brownout controller's counters when
        # one is installed (round 18) — same injected-callable closure.
        "serving.brownout",
        "serving.engine",
        # engine.metrics reads the installed journal's counters (round
        # 23) — same injected-callable closure, same rank-upward
        # legality (obs.slo 24 < serving.journal 32).
        "serving.journal",
        "serving.scheduler",
        # engine.metrics reads the mesh flight's telemetry section
        # (round 21) — same injected-callable closure, same rank-upward
        # legality (obs.slo 24 < serving.mesh_scheduler 35).
        "serving.mesh_scheduler",
        # engine.metrics reads the megastep flight counters (round 19) —
        # same injected-callable closure, same rank-upward legality
        # (obs.slo 24 < serving.megastep 36).
        "serving.megastep",
        "serving.breaker",
        "serving.injector",
        # engine.metrics also reads the front-door counters/cache
        # metrics when a front door is installed (round 17) — same
        # injected-callable closure, same rank-upward legality.
        "frontdoor.router",
        "frontdoor.cache",
        "obs.compilewatch",
        "obs.critpath",
        "obs.trace",
        "obs.hist",
        "obs.minest",
        "utils.statwindow",
    )
})

# Cross-module receiver hints for deadck's call/lock resolution: the
# static half cannot type expressions, so the handful of conventional
# receiver names used across module boundaries are declared here as pure
# data.  Maps the receiver expression (as written) to the (file, class)
# whose methods/locks it denotes.
DEADCK_BASE_CLASSES = {
    "engine": ("serving/engine.py", "SolverEngine"),
    "self.engine": ("serving/engine.py", "SolverEngine"),
    "self.server.engine": ("serving/engine.py", "SolverEngine"),
    "self.node": ("cluster/node.py", "ClusterNode"),
    "node": ("cluster/node.py", "ClusterNode"),
    "ex": ("cluster/node.py", "_Exec"),
    "rf": ("serving/scheduler.py", "ResidentFlight"),
    "flight": ("serving/scheduler.py", "ResidentFlight"),
    "mrf": ("serving/mesh_scheduler.py", "MeshResidentFlight"),
    "mf": ("serving/megastep.py", "MegastepFlight"),
    "self.breaker": ("serving/faults.py", "CircuitBreaker"),
    "req": ("serving/engine.py", "_Control"),
    "self._dedupe": ("cluster/node.py", "_DedupeLRU"),
    "self._net": ("cluster/simnet.py", "SimNet"),
    "net": ("cluster/simnet.py", "SimNet"),
    "mon": ("obs/slo.py", "SloMonitor"),
    "rec": ("obs/trace.py", "TraceRecorder"),
    "cw": ("obs/compilewatch.py", "CompileWatch"),
    "cp": ("obs/critpath.py", "CritPathMonitor"),
    "self.frontdoor": ("serving/frontdoor/router.py", "FrontDoor"),
    "self.cache": ("serving/frontdoor/cache.py", "ResultCache"),
    "fd": ("serving/frontdoor/router.py", "FrontDoor"),
    "ctrl": ("serving/brownout.py", "BrownoutController"),
    "self.ctrl": ("serving/brownout.py", "BrownoutController"),
    "bo": ("serving/brownout.py", "BrownoutController"),
    "self.gossip": ("cluster/dht/membership.py", "Gossip"),
    "g": ("cluster/dht/membership.py", "Gossip"),
    "self.ring": ("cluster/dht/hashring.py", "HashRing"),
    "self.dcache": ("cluster/dht/cluster_cache.py", "ClusterCache"),
    "self.l2": ("cluster/node.py", "_L2Adapter"),
    # SimNet._schedule is the fault plane, not the cluster cache: both
    # carry a ``lookup`` method, and without the hint the edge pass's
    # name-based over-approximation manufactures a phantom
    # cluster.simnet -> cluster.dhtcache hold under the net condition.
    "self._schedule": ("serving/faults.py", "FaultSchedule"),
    "jr": ("serving/journal.py", "Journal"),
    "self.journal": ("serving/journal.py", "Journal"),
}

# The repo's thread roots: qualname prefixes (per file) whose bodies run
# on their own threads.  deadck's guard-inference pass walks the call
# graph from each root; a ``self.<attr>`` write reachable from >= 2
# distinct roots with no declared lockck guard is a finding — which is
# what turns lockck's annotate-only coverage into a PROVEN-complete
# contract (ISSUE 13 tentpole).
DEADCK_THREAD_ROOTS = {
    "serving/engine.py": (
        "SolverEngine._run",      # the device loop
        "SolverEngine.submit",    # client/handler threads
        "SolverEngine.cancel",
    ),
    "serving/http.py": (
        "_Handler",               # one thread per HTTP request
    ),
    "cluster/node.py": (
        "ClusterNode._hb_loop",
        "ClusterNode._progress_loop",
        "ClusterNode._broadcast_send",   # beat-spawned view broadcasts
        "ClusterNode._flush_parked",     # beat-spawned result re-offers
        "ClusterNode._handle",    # transport connection threads
        "ClusterNode.submit",     # client threads
        "_Exec._watch_local",
    ),
    "cluster/wire.py": (
        "TcpTransport._accept_loop",
        "TcpTransport._serve_conn",
        "fanout_requests",        # the per-peer ask() threads
    ),
    "cluster/simnet.py": (
        "SimNet._worker",          # pooled virtual delivery workers
        "SimNet._overflow_worker", # nested-send escape hatch
    ),
    "cluster/dht/cluster_cache.py": (
        "ClusterCache._put_loop",  # async CACHE_PUT retry daemon
    ),
    "serving/portfolio.py": (
        "race",                   # racer entrant threads (device/native)
        "race_cover",
        "race_jobs",
    ),
    "serving/megastep.py": (
        # The megastep flight resolves jobs synchronously on whichever
        # client/handler thread submitted them — every counter write in
        # the flight is reachable from concurrent submit threads, so
        # guard inference must prove them all.
        "MegastepFlight.solve",
    ),
    "serving/journal.py": (
        # The fsync batcher daemon: one fsync per interval covers every
        # append since the last — the durability write that must never
        # run on the device loop thread runs here instead.
        "Journal._fsync_loop",
    ),
    "serving/brownout.py": (
        # The controller is reached from HTTP handler threads (the front
        # door's gate), the device loop (engine.metrics), and any
        # metrics scraper — declared as its own root family so deadck's
        # guard inference PROVES every counter write is lock-guarded
        # rather than trusting the annotations.
        "BrownoutController.evaluate",
    ),
    "utils/profiling.py": (
        "_close_profile_window",  # the profile-window daemon timer
    ),
    "utils/dataset.py": (
        "solve_file",             # reader/writer pipeline threads
    ),
}

# dtypes banned anywhere in a traced program: f64/c128 double both the
# bytes-per-lane and the cache key space (x64 flips fork every program).
JAXCK_BANNED_DTYPES = ("float64", "complex128")
