"""The repo's invariant manifest: the contracts the checkers prove.

Everything here is DATA — the one place where the layering promises the
module docstrings make, the clock seams the simnet lane trusts, the
host-sync seam the round-8 rewrite paid for, and the lock conventions the
review rounds kept re-finding by hand, are written down once and enforced
by ``python -m distributed_sudoku_solver_tpu.analysis`` (see the package
docstring for the waiver grammar).

A plain Python dict/tuple module on purpose (ISSUE 10 allows
``layers.toml`` *or* a py dict): the container pins Python 3.10, which has
no ``tomllib``, and ``tests/conftest.py`` imports the runtime banned list
from here directly — one source of truth for the static AND runtime lanes.
"""

from __future__ import annotations

# -- layerck -------------------------------------------------------------
#
# Keys are package-relative dotted module prefixes; the LONGEST matching
# prefix wins (so ``serving.faults`` overrides ``serving``).  Two rule
# shapes:
#
# * closed layer (``closed=True``): stdlib + the listed internal prefixes
#   + the listed third-party roots ONLY.  An internal target matches an
#   ``allow`` entry by dotted-prefix in either direction (importing the
#   ``cluster`` package to reach ``cluster.wire`` is the same promise as
#   importing ``cluster.wire``).
# * open layer (``closed=False``): anything goes EXCEPT the ``forbid``
#   dotted prefixes, minus the ``except`` carve-outs.
#
# The rules below are the docstring promises, verbatim:
# obs/ is stdlib + its own siblings and never imports serving back
# (obs/trace.py module note); serving/faults.py is stdlib-only and
# imported by engine/scheduler/bulk/cluster, never importing back
# (faults.py docstring); cluster/wire.py is the stdlib wire layer;
# cluster/simnet.py is wire + the fault-schedule machinery and nothing
# else (simnet.py docstring); ops/ and models/ are the compute layers and
# never reach up into serving/cluster — with the ONE declared exception of
# the ``serving.faults`` injection seam at ``bulk.dispatch``.
LAYERS = {
    "obs": {"closed": True, "allow": ("obs",), "third_party": ()},
    "serving.faults": {"closed": True, "allow": (), "third_party": ()},
    "cluster.wire": {"closed": True, "allow": (), "third_party": ()},
    "cluster.simnet": {
        "closed": True,
        "allow": ("cluster.wire", "serving.faults"),
        "third_party": (),
    },
    # The checker's own layer: source-only tooling.  stdlib + obs (the
    # shared *ck exit-code contract) — importing jax here would break the
    # "<5 s, no jax" acceptance the tier-1 test pins.
    "analysis": {"closed": True, "allow": ("analysis", "obs"), "third_party": ()},
    "ops": {
        "closed": False,
        "forbid": ("serving", "cluster", "analysis"),
        "except": ("serving.faults",),
    },
    "models": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    "parallel": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    "utils": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    "native": {"closed": False, "forbid": ("serving", "cluster", "analysis")},
    # serving sits BELOW cluster (cluster/node.py imports serving.engine):
    # a serving -> cluster import would be a cycle by construction.
    "serving": {"closed": False, "forbid": ("cluster",)},
}

# -- clockck -------------------------------------------------------------
#
# Directories where bare wall-clock CALLS are banned: every timing
# decision in these layers must route through an injected clock (the
# ``clock=...`` parameter/field defaults that *reference* these functions
# are the injection seam and are allowed — clockck flags calls, not
# references).  This is the static, whole-tree form of the simnet runtime
# guard's promise (tests/conftest.py).
CLOCK_SCOPED_DIRS = ("cluster", "serving", "obs")

# (module, attr) call targets that count as bare clock access.  The
# whole spelling family, not just the four the docstrings name — a rule
# that misses ``perf_counter()`` or ``monotonic_ns()`` is laundered by a
# rename (review-round finding).
CLOCK_BANNED_CALLS = (
    ("time", "time"),
    ("time", "time_ns"),
    ("time", "monotonic"),
    ("time", "monotonic_ns"),
    ("time", "perf_counter"),
    ("time", "perf_counter_ns"),
    ("time", "sleep"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
)

# Declared seams: qualname prefixes (per package-relative file) whose
# bodies may touch the real clock.  These are the places whose WHOLE JOB
# is to be the wall-clock boundary:
# * wire.SystemClock — the production clock behind ClusterNode's
#   injectable seam; late-bound on purpose so the runtime guard still
#   catches a simnet test that forgot ``clock=net.clock``.
# * SimNet.sleep/advance/settle — simnet's bounded REAL settling waits
#   (never slept on; see the ``_monotonic`` import-time capture note in
#   cluster/simnet.py).
CLOCK_SEAMS = {
    "cluster/wire.py": ("SystemClock",),
    "cluster/simnet.py": ("SimNet.sleep", "SimNet.advance", "SimNet.settle"),
}

# The runtime twin (tests/conftest.py imports this): module attributes
# monkeypatched to raise inside ``simnet``-marked tests.  Superset of the
# sleep/monotonic half of CLOCK_BANNED_CALLS (pinned by
# tests/test_analysis.py) plus the socket escapes — now including
# select/selectors-level waits, which are sleeps and socket IO in one
# call.  ``time.time`` is deliberately ABSENT from the runtime list:
# logging.LogRecord reads it on every record, so a runtime ban would fail
# any simnet test the moment a node logs — the static lane (clockck)
# covers time.time instead.
SIMNET_RUNTIME_BANNED = (
    ("socket", "socket"),
    ("socket", "create_connection"),
    ("socket", "create_server"),
    ("select", "select"),
    ("selectors", "DefaultSelector"),
    ("selectors", "SelectSelector"),
    ("selectors", "PollSelector"),
    ("selectors", "EpollSelector"),
    ("selectors", "KqueueSelector"),
    ("time", "sleep"),
    ("time", "monotonic"),
)

# -- syncck --------------------------------------------------------------
#
# Files under the round-8 "one sync per chunk" contract, and within them
# the hot-loop regions (qualname prefixes) where a device-sync-forcing
# call must either route through the ``host_fetch`` seam
# (serving/engine.py) or prove its operand host-side (assigned from a
# ``host_fetch``/``unpack_status`` result — the checker tracks that
# dataflow) or carry a ``# syncck: allow(<reason>)`` waiver.  Outside the
# hot regions the same sync-forcing calls are still flagged (waiver
# required), but the int()/float()-on-indexed-value heuristic only runs
# inside hot regions — metrics/stats plumbing coerces host ints
# everywhere and is not the hazard this rule hunts.
SYNC_SCOPED_FILES = ("serving/engine.py", "serving/scheduler.py")

SYNC_HOT_REGIONS = {
    "serving/engine.py": (
        "SolverEngine._advance_flight",
        "SolverEngine._resolve_solved",
        "SolverEngine._do_snapshot",
        "SolverEngine._do_shed",
    ),
    "serving/scheduler.py": (
        "ResidentFlight.step",
        "ResidentFlight._consume_status",
        "ResidentFlight._collect_and_detach",
        "ResidentFlight._attach_pending",
        "ResidentFlight._advance",
    ),
}

# Functions whose BODY is the seam (exempt) and whose results prove their
# targets host-side for the dataflow pass.
SYNC_SEAM_FUNCS = ("host_fetch",)
SYNC_HOST_SOURCES = ("host_fetch", "unpack_status")

# numpy-module call names that force a device->host transfer when handed
# a jax array (jnp.asarray is the opposite direction and exempt).
SYNC_NUMPY_CALLS = ("asarray", "ascontiguousarray")
# method calls that force a sync on any jax value.
SYNC_METHOD_CALLS = ("item", "block_until_ready")
# jax-module call names that ARE the sync primitive.
SYNC_JAX_CALLS = ("device_get",)
