"""clockck: bare wall-clock CALLS banned in the clock-scoped layers.

The simnet runtime guard (tests/conftest.py) catches a stray
``time.sleep`` only on paths a simnet test happens to execute; this rule
is the same promise made static and whole-tree: inside
``manifest.CLOCK_SCOPED_DIRS`` every *call* to a banned clock
(``manifest.CLOCK_BANNED_CALLS``) is a violation unless it sits inside a
declared seam (``manifest.CLOCK_SEAMS`` qualname prefixes — e.g.
``wire.SystemClock``) or carries a ``# clockck: allow(<reason>)`` waiver.

*References* are allowed by design: ``clock: Callable[[], float] =
time.monotonic`` parameter/field defaults are exactly the injection seam
this rule exists to force timing through (the default binds the real
function at import time, which is also what keeps engines immune to the
runtime guard's monkeypatch).  Import-aliases (``import time as _time``),
from-imports (``from time import monotonic as m``) and module-level
captures (``_monotonic = _time.monotonic``) are tracked, so renaming a
banned clock does not launder the call.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from distributed_sudoku_solver_tpu.analysis.common import (
    Finding,
    QualnameVisitor,
    SourceModule,
    finding,
)


def _collect_aliases(
    tree: ast.Module, banned: Tuple[Tuple[str, str], ...]
) -> Tuple[Dict[str, str], Dict[str, str]]:
    """-> (module alias -> module name, direct callable name -> dotted)."""
    banned_mods = {m for m, _ in banned}
    banned_by_mod: Dict[str, set] = {}
    for m, a in banned:
        banned_by_mod.setdefault(m, set()).add(a)
    mod_alias: Dict[str, str] = {}
    direct: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                if al.name in banned_mods:
                    mod_alias[al.asname or al.name] = al.name
        elif isinstance(node, ast.ImportFrom) and node.module in banned_mods:
            for al in node.names:
                if al.name in banned_by_mod[node.module]:
                    direct[al.asname or al.name] = f"{node.module}.{al.name}"
                elif al.name == node.module:
                    # ``from datetime import datetime``: the class carries
                    # the same banned constructors (now/utcnow).
                    mod_alias[al.asname or al.name] = node.module
    # Module-level captures of a banned callable: X = _time.monotonic
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Attribute)
            and isinstance(node.value.value, ast.Name)
        ):
            mod = mod_alias.get(node.value.value.id)
            if mod and node.value.attr in banned_by_mod.get(mod, ()):
                direct[node.targets[0].id] = f"{mod}.{node.value.attr}"
    return mod_alias, direct


class _ClockVisitor(QualnameVisitor):
    def __init__(self, mod: SourceModule, seams, mod_alias, direct):
        super().__init__()
        self.mod = mod
        self.seams = seams
        self.mod_alias = mod_alias
        self.direct = direct
        self.banned_by_mod: Dict[str, set] = {}
        self.findings: List[Finding] = []

    def _in_seam(self) -> bool:
        q = self.qualname
        return any(q == s or q.startswith(s + ".") for s in self.seams)

    def visit_Call(self, node: ast.Call) -> None:
        dotted = None
        f = node.func
        if isinstance(f, ast.Name) and f.id in self.direct:
            dotted = self.direct[f.id]
        elif isinstance(f, ast.Attribute):
            # Walk the whole attribute chain so two-level spellings
            # (``datetime.datetime.now()`` under ``import datetime``)
            # resolve too — only `f.value is Name` used to be handled,
            # which silently laundered the most common datetime form.
            parts = [f.attr]
            base = f.value
            while isinstance(base, ast.Attribute):
                parts.append(base.attr)
                base = base.value
            if isinstance(base, ast.Name):
                mod = self.mod_alias.get(base.id)
                if mod is not None and parts[0] in self.banned_by_mod.get(
                    mod, ()
                ):
                    dotted = f"{mod}.{parts[0]}"
        if dotted is not None and not self._in_seam():
            self.findings.append(finding(
                self.mod, "clockck", node,
                f"bare clock call {dotted}() — route through an injected "
                "clock seam (a `clock=...` default referencing it is the "
                "seam; calls are not)",
                def_lines=tuple(self.def_lines),
            ))
        self.generic_visit(node)


def check_module(
    mod: SourceModule,
    scoped_dirs: Tuple[str, ...],
    banned: Tuple[Tuple[str, str], ...],
    seams: Dict[str, Tuple[str, ...]],
    scope_all: bool = False,
) -> List[Finding]:
    if not scope_all and not any(
        mod.rel.startswith(d + "/") or mod.rel.startswith(d + ".")
        for d in scoped_dirs
    ):
        return []
    mod_alias, direct = _collect_aliases(mod.tree, banned)
    if not mod_alias and not direct:
        return []
    v = _ClockVisitor(mod, seams.get(mod.rel, ()), mod_alias, direct)
    for m, a in banned:
        v.banned_by_mod.setdefault(m, set()).add(a)
    v.visit(mod.tree)
    return v.findings
