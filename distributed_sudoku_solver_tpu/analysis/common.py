"""Shared plumbing for the invariant checkers: parsed sources, comment
maps, the waiver grammar, and the finding record.

Stdlib only (``ast`` + ``tokenize``) — importing anything heavier here
would break the package's own closed-layer rule (manifest.LAYERS
``analysis``) and the "<5 s, no jax" acceptance.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import sys
import tokenize
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

#: The default fast lane: stdlib-``ast`` only, no jax, <5 s.
RULES = ("layerck", "clockck", "syncck", "lockck", "deadck")

#: Rules that lazily import heavy dependencies and therefore only run
#: when explicitly selected (``--rule jaxck``): the default lane's
#: no-jax/<5 s contract stays intact (pinned by tests/test_analysis.py).
LAZY_RULES = ("jaxck",)

ALL_RULES = RULES + LAZY_RULES

#: The waiver grammar (README "Static analysis"): a trailing comment
#: ``# <rule>: allow(<reason>)`` on the flagged line — or on the
#: enclosing ``def`` line, which waives the whole function for that rule.
#: The reason is REQUIRED: an empty ``allow()`` is itself a violation, so
#: every committed waiver carries its why.
WAIVER_RE = re.compile(
    r"#\s*(layerck|clockck|syncck|lockck|deadck|jaxck):\s*allow\(([^)]*)\)"
)

#: lockck's declaration grammar: ``# lockck: guard(<lock_attr>)`` on the
#: attribute's initialisation line declares that every other write to the
#: attribute must hold ``<base>.<lock_attr>``.
GUARD_RE = re.compile(r"#\s*lockck:\s*guard\((\w+)\)")

#: deadck's lock-identity grammar: ``# lockck: name(<tier>.<name>)`` on a
#: lock's creation line binds the lock object to its manifest identity
#: (``manifest.LOCK_RANKS``).  The same string is the literal argument of
#: the ``obs.lockdep.named_*`` factory on that line — deadck checks the
#: two agree, so the static graph and the runtime witness can never name
#: the same lock differently.
NAME_RE = re.compile(r"#\s*lockck:\s*name\(([\w.]+)\)")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    rule: str
    path: str  # scan-root-relative, posix separators
    line: int
    message: str
    waived: bool = False
    reason: str = ""  # the waiver reason, when waived

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.waived:
            d["waived"] = True
            d["reason"] = self.reason
        return d

    def render(self) -> str:
        tag = " [waived: %s]" % self.reason if self.waived else ""
        return f"{self.rule}: {self.path}:{self.line}: {self.message}{tag}"


class SourceModule:
    """One parsed source file + its comment map and waiver index."""

    def __init__(self, abspath: Path, rel: str, modname: Optional[str]):
        self.abspath = abspath
        self.rel = rel  # posix path relative to the scan root
        self.modname = modname  # package-relative dotted name, or None
        self.text = abspath.read_text(encoding="utf-8")
        self.tree = ast.parse(self.text, filename=str(abspath))
        #: (rule, comment line) waiver sites a checker actually consulted
        #: — the complement (see :func:`stale_waivers`) is a waiver whose
        #: rule no longer fires there, itself worth reporting before the
        #: committed waiver set rots.
        self.used_waiver_sites: set = set()
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.text).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # pragma: no cover - ast already parsed
            pass

    def _standalone_comment(self, line: int) -> bool:
        idx = line - 1
        if idx < 0:
            return False
        lines = self.text.splitlines()
        return idx < len(lines) and lines[idx].lstrip().startswith("#")

    def waiver(self, rule: str, line: int) -> Optional[str]:
        """The waiver reason for ``rule`` on ``line`` (None = no waiver,
        "" = waiver present but reason missing).  A waiver may sit as a
        trailing comment on the line itself, or as a STANDALONE comment
        line immediately above it — the readable form when the flagged
        statement is already long."""
        for at in (line, line - 1):
            comment = self.comments.get(at)
            if not comment or (at != line and not self._standalone_comment(at)):
                continue
            for m in WAIVER_RE.finditer(comment):
                if m.group(1) == rule:
                    self.used_waiver_sites.add((rule, at))
                    return m.group(2).strip()
        return None

    def waiver_sites(self) -> List[Tuple[str, int, str]]:
        """Every waiver comment in the file: (rule, line, reason)."""
        out = []
        for line in sorted(self.comments):
            for m in WAIVER_RE.finditer(self.comments[line]):
                out.append((m.group(1), line, m.group(2).strip()))
        return out


def finding(
    mod: SourceModule,
    rule: str,
    node: ast.AST,
    message: str,
    def_lines: Tuple[int, ...] = (),
) -> Finding:
    """Build a Finding, resolving the waiver grammar: a waiver on the
    flagged line or on any enclosing ``def`` line downgrades the finding
    to ``waived`` (an empty reason keeps it a violation, reworded)."""
    line = getattr(node, "lineno", 0)
    for at in (line,) + tuple(def_lines):
        reason = mod.waiver(rule, at)
        if reason is None:
            continue
        if not reason:
            return Finding(
                rule, mod.rel, line,
                message + " — waiver present but allow() has no reason",
            )
        return Finding(rule, mod.rel, line, message, waived=True, reason=reason)
    return Finding(rule, mod.rel, line, message)


class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the lexical class/function qualname stack
    and the line numbers of enclosing ``def`` statements (for
    function-scope waivers)."""

    def __init__(self) -> None:
        self.stack: List[str] = []
        self.def_lines: List[int] = []

    @property
    def qualname(self) -> str:
        return ".".join(self.stack)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.def_lines.append(node.lineno)
        self.generic_visit(node)
        self.def_lines.pop()
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)


def stale_waivers(
    mods: List["SourceModule"], rules: Tuple[str, ...]
) -> List[Tuple[str, int, str, str]]:
    """Waiver comments whose rule (among the rules that RAN) no longer
    fires on that line: (path, line, rule, reason), sorted.

    Must be called after the checkers, which populate
    ``used_waiver_sites`` as they resolve findings.  Scoped to the
    selected rules — a jaxck waiver is not stale just because the fast
    lane didn't run jaxck."""
    out = []
    for mod in mods:
        for rule, line, reason in mod.waiver_sites():
            if rule not in rules:
                continue
            if (rule, line) not in mod.used_waiver_sites:
                out.append((mod.rel, line, rule, reason))
    return sorted(out)


def expr_root(node: ast.AST) -> Optional[str]:
    """The base Name an expression hangs off: ``info["steps"]`` -> info,
    ``st.nodes[i]`` -> st, ``self._status["solved"]`` -> ``self._status``
    (one attribute level kept for self-attrs, so class-wide host attrs
    resolve).  None for anything not rooted in a Name."""
    n = node
    while isinstance(n, (ast.Subscript, ast.Call)):
        n = n.value if isinstance(n, ast.Subscript) else n.func
    if isinstance(n, ast.Attribute):
        base = n.value
        if isinstance(base, ast.Name) and base.id == "self":
            return f"self.{n.attr}"
        while isinstance(base, (ast.Attribute, ast.Subscript, ast.Call)):
            if isinstance(base, ast.Attribute):
                base = base.value
            elif isinstance(base, ast.Subscript):
                base = base.value
            else:
                base = base.func
        return base.id if isinstance(base, ast.Name) else None
    if isinstance(n, ast.Name):
        return n.id
    return None


def call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target (``np.asarray``,
    ``engine_mod.host_fetch``, ``host_fetch``)."""
    try:
        return ast.unparse(node.func)
    except Exception:  # pragma: no cover - unparse is total on parsed asts
        return ""


def stdlib_top(name: str) -> bool:
    top = name.split(".", 1)[0]
    return top == "__future__" or top in sys.stdlib_module_names


def iter_sources(
    root: Path, package_root: Optional[Path]
) -> Iterator[SourceModule]:
    """Yield parsed modules under ``root`` in sorted order (determinism:
    the walk order IS the report order before the final sort)."""
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        modname = None
        if package_root is not None:
            try:
                parts = path.relative_to(package_root).with_suffix("").parts
                modname = ".".join(
                    p for p in parts if p != "__init__"
                ) or "__init__"
            except ValueError:
                modname = None
        yield SourceModule(path, rel, modname)
