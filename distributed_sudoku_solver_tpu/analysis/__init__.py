"""Static invariant linter: the repo's contracts, proved on every line.

``obs/traceck.py`` and ``obs/promck.py`` lint the system's *output*
(trace JSON, Prometheus exposition); this package is the same discipline
aimed at the *source* — and, since round 14, at what XLA *compiles from*
it.  Five AST-based fast rules plus one opt-in compiled-layer rule
behind one runner::

    python -m distributed_sudoku_solver_tpu.analysis [--json] [--rule R]
                                                     [--scope benchmarks]
                                                     [--strict-waivers]
    python -m distributed_sudoku_solver_tpu.analysis --rule jaxck \
                                                     [--update-golden]

* **layerck** — the import-layering manifest (``manifest.LAYERS``):
  ``obs/``, ``serving/faults.py``, ``cluster/wire.py``,
  ``cluster/simnet.py`` are closed layers (stdlib + declared siblings,
  never importing serving back); ``ops``/``models`` never import
  ``serving``/``cluster``.  Checks real import nodes, nested-in-function
  lazy imports included.
* **clockck** — bare ``time.time``/``time.monotonic``/``time.sleep``/
  ``datetime.now`` CALLS banned in ``cluster/``, ``serving/``, ``obs/``
  outside the declared seams (``wire.SystemClock``, simnet's settling
  internals); ``clock=...`` defaults *referencing* them are the injection
  seam and pass.  The static, whole-tree form of the simnet runtime
  guard, which imports its banned-name list from ``manifest`` (one list,
  two lanes).
* **syncck** — device-sync-forcing calls in the serving hot loops must
  route through the ``host_fetch`` seam or prove their operand host-side
  (a small dataflow pass over ``host_fetch``/``unpack_status`` results).
* **lockck** — attributes declared ``# lockck: guard(_lock)`` are only
  written under ``with <base>._lock:`` (or in ``*_locked`` helpers).
* **deadck** — the thread plane (round 16): every lock is created
  through ``obs.lockdep.named_*`` with a ``# lockck: name(<tier>.<name>)``
  identity; the whole-tree lock-acquisition graph (cross-module edges
  and the ``*_locked`` convention included) must be rank-upward in
  ``manifest.LOCK_RANKS`` or declared in ``manifest.LOCK_EDGE_DECLARED``;
  cycles are findings; and a guard-inference pass over
  ``manifest.DEADCK_THREAD_ROOTS`` reports any ``self.<attr>`` write
  reachable from >= 2 thread roots with no lock held and no lockck
  guard — lockck's coverage, proven complete.  The runtime twin
  (``obs/lockdep.py``) witnesses the same hierarchy live across tier-1;
  ``tests/test_deadck.py`` cross-checks observed ⊆ predicted.
* **jaxck** (opt-in: the ONE rule that imports jax, lazily) — abstractly
  traces every ``manifest.ENTRY_POINTS`` jit program at canonical tiny
  shapes and proves the compiled layer: donation lowers to real
  ``input_output_aliases``, serving-hot jaxprs are callback-free, dtypes
  stay f64-free and scalar params pinned, and a canonicalized jaxpr
  fingerprint per program matches ``analysis/goldens/jaxck.json`` so
  HLO drift (= XLA cache invalidation) is visible and blessed with
  ``--update-golden``, never a mystery tier-1 slowdown.

Waiver grammar (all rules): a trailing ``# <rule>: allow(<reason>)`` on
the flagged line, or on the enclosing ``def`` line to waive a whole
function.  The reason string is mandatory; waived findings are reported
(and carried in ``--json``) but do not fail the run.  Waivers are
themselves checked: one whose rule ran but no longer fires on its line
is reported stale (``--strict-waivers`` makes that exit 1).

Exit codes are the *ck-family contract* (``obs/exitcodes.py``): 0 clean,
1 violations, 2 internal/usage error.  The default lane is
stdlib-``ast`` only — it never imports jax, and tier-1
(``tests/test_analysis.py``) pins both that and a clean exit over the
package tree; the jaxck lane's clean exit and golden determinism are
pinned by ``tests/test_jaxck.py``.
"""

from distributed_sudoku_solver_tpu.analysis.common import (  # noqa: F401
    ALL_RULES,
    Finding,
    LAZY_RULES,
    RULES,
)
from distributed_sudoku_solver_tpu.obs.exitcodes import (  # noqa: F401
    EXIT_CLEAN,
    EXIT_INTERNAL,
    EXIT_VIOLATIONS,
)
