"""syncck: host-sync discipline in the serving hot loops.

Round 8 bought "exactly one host sync per chunk" and routed every
device->host read through the ``host_fetch`` seam
(serving/engine.py) — and a later review still caught a stray
``np.asarray`` quietly re-adding ~100 ms/chunk through a tunneled
device.  This rule makes that catch static:

* in ``manifest.SYNC_SCOPED_FILES``, every sync-forcing call
  (``np.asarray``/``np.ascontiguousarray`` on the numpy alias,
  ``jax.device_get``, ``.item()``/``.block_until_ready()`` methods) is
  flagged unless its operand is PROVEN host-side or it carries a
  ``# syncck: allow(<reason>)`` waiver (line- or def-scoped);
* inside the declared hot-loop regions (``manifest.SYNC_HOT_REGIONS``)
  the heuristic widens to ``int(...)``/``float(...)`` over subscript/
  attribute operands — the classic shape of a scalar fetch off a live
  device value.

"Proven host-side" is a small forward dataflow pass, not a type system:
a name (or ``self.<attr>``, tracked class-wide) assigned from a
``host_fetch``/``unpack_status`` call — including tuple unpacking — is
host data, and so is anything re-assigned from an expression rooted at
one.  ``np.asarray(solutions[slot])`` over a fetched verdict tuple passes
without ceremony; ``np.asarray(state.top)`` over a live frontier does
not.  The ``host_fetch`` function body itself is the seam and exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from distributed_sudoku_solver_tpu.analysis.common import (
    Finding,
    QualnameVisitor,
    SourceModule,
    call_name,
    expr_root,
    finding,
)


def _is_host_source(node: ast.AST, host_sources: Tuple[str, ...]) -> bool:
    """Does this expression produce host data by construction?  A call
    whose (possibly dotted) name ends in a host-source function, applied
    to anything — including ``unpack_status(host_fetch(...))``."""
    if isinstance(node, ast.Call):
        name = call_name(node).rsplit(".", 1)[-1]
        return name in host_sources
    return False


def _host_attrs(tree: ast.Module, host_sources: Tuple[str, ...]) -> Set[str]:
    """Class-wide pass: ``self.X = <host source>(...)`` anywhere marks
    ``self.X`` host-side for the whole file (the scheduler's
    ``self._status`` pattern)."""
    attrs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_host_source(
            node.value, host_sources
        ):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attrs.add(f"self.{t.attr}")
    return attrs


class _SyncVisitor(QualnameVisitor):
    def __init__(
        self,
        mod: SourceModule,
        hot_regions: Tuple[str, ...],
        seam_funcs: Tuple[str, ...],
        host_sources: Tuple[str, ...],
        numpy_calls: Tuple[str, ...],
        method_calls: Tuple[str, ...],
        jax_calls: Tuple[str, ...],
        np_aliases: Set[str],
        jax_aliases: Set[str],
        host_attrs: Set[str],
    ):
        super().__init__()
        self.mod = mod
        self.hot_regions = hot_regions
        self.seam_funcs = seam_funcs
        self.host_sources = host_sources
        self.numpy_calls = numpy_calls
        self.method_calls = method_calls
        self.jax_calls = jax_calls
        self.np_aliases = np_aliases
        self.jax_aliases = jax_aliases
        self.host_attrs = host_attrs
        self.host_locals: List[Set[str]] = []  # one scope per function
        self.findings: List[Finding] = []

    # -- scope plumbing ------------------------------------------------------
    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.def_lines.append(node.lineno)
        self.host_locals.append(set())
        if node.name not in self.seam_funcs:  # the seam body is exempt
            self.generic_visit(node)
        self.host_locals.pop()
        self.def_lines.pop()
        self.stack.pop()

    def _in_hot_region(self) -> bool:
        q = self.qualname
        return any(q == r or q.startswith(r + ".") for r in self.hot_regions)

    def _is_host(self, node: ast.AST) -> bool:
        root = expr_root(node)
        if root is None:
            return False
        if root in self.host_attrs:
            return True
        return any(root in scope for scope in self.host_locals)

    def _mark_host(self, target: ast.AST) -> None:
        if not self.host_locals:
            return
        if isinstance(target, ast.Name):
            self.host_locals[-1].add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mark_host(elt)
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            self.host_attrs.add(f"self.{target.attr}")

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if _is_host_source(node.value, self.host_sources) or self._is_host(
            node.value
        ):
            for t in node.targets:
                self._mark_host(t)

    # -- the checks ----------------------------------------------------------
    def _flag(self, node: ast.Call, what: str) -> None:
        self.findings.append(finding(
            self.mod, "syncck", node,
            f"{what} outside the host_fetch seam — route the value "
            "through host_fetch (or prove it host-side / waive with "
            "reason)",
            def_lines=tuple(self.def_lines),
        ))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        arg0 = node.args[0] if node.args else None
        if isinstance(f, ast.Attribute):
            if (
                isinstance(f.value, ast.Name)
                and f.value.id in self.np_aliases
                and f.attr in self.numpy_calls
            ):
                if arg0 is None or not self._is_host(arg0):
                    self._flag(node, f"sync-forcing call np.{f.attr}()")
            elif (
                isinstance(f.value, ast.Name)
                and f.value.id in self.jax_aliases
                and f.attr in self.jax_calls
            ):
                self._flag(node, f"sync primitive jax.{f.attr}()")
            elif f.attr in self.method_calls and not self._is_host(f.value):
                self._flag(node, f".{f.attr}() call")
        elif (
            isinstance(f, ast.Name)
            and f.id in ("int", "float")
            and self._in_hot_region()
            and isinstance(arg0, (ast.Subscript, ast.Attribute))
            and not self._is_host(arg0)
        ):
            self._flag(
                node, f"hot-loop {f.id}() over an indexed/attribute value"
            )
        self.generic_visit(node)


def check_module(
    mod: SourceModule,
    scoped_files: Tuple[str, ...],
    hot_regions: Dict[str, Tuple[str, ...]],
    seam_funcs: Tuple[str, ...],
    host_sources: Tuple[str, ...],
    numpy_calls: Tuple[str, ...],
    method_calls: Tuple[str, ...],
    jax_calls: Tuple[str, ...],
) -> List[Finding]:
    if mod.rel not in scoped_files:
        return []
    np_aliases: Set[str] = set()
    jax_aliases: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                if al.name == "numpy":
                    np_aliases.add(al.asname or "numpy")
                elif al.name == "jax":
                    jax_aliases.add(al.asname or "jax")
    v = _SyncVisitor(
        mod,
        hot_regions.get(mod.rel, ()),
        seam_funcs,
        host_sources,
        numpy_calls,
        method_calls,
        jax_calls,
        np_aliases,
        jax_aliases,
        _host_attrs(mod.tree, host_sources),
    )
    v.visit(mod.tree)
    return v.findings
