"""Runner: ``python -m distributed_sudoku_solver_tpu.analysis``.

Checks the package tree (default) or ``--scope benchmarks``
(report-only: benchmark scripts ARE wall-clock tools, so clock findings
there inform rather than gate — documented in the README).  ``--rule``
narrows to one or more rules, and the exit code then reflects exactly
the selected rules — the "per-rule exit codes" contract: a CI step can
gate on one rule while another is still being burned down.

Two lanes behind one flag:

* the default fast lane (``RULES``) is stdlib-``ast`` only — no jax
  import, <5 s, byte-identical output run to run (both pinned by
  tests/test_analysis.py);
* ``--rule jaxck`` is the compiled-layer lane: it lazily imports jax,
  abstractly traces every ``manifest.ENTRY_POINTS`` program and proves
  donation/callback/dtype/HLO-golden invariants (``analysis/jaxck.py``).
  ``--update-golden`` blesses HLO drift by rewriting
  ``analysis/goldens/jaxck.json``.

Waiver hygiene rides every run: a ``# <rule>: allow(...)`` comment whose
rule (among the rules that ran) no longer fires on that line is reported
as *stale* — report-only by default, exit 1 under ``--strict-waivers``.

Deterministic by construction: sorted file walk, sorted findings,
``sort_keys`` JSON — two runs over the same tree are byte-identical
(pinned by tests/test_analysis.py for the fast lane and
tests/test_jaxck.py for the jaxck lane).
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from pathlib import Path
from typing import List, Optional, Tuple

from distributed_sudoku_solver_tpu.analysis import (
    clockck,
    deadck,
    layerck,
    lockck,
    syncck,
)
from distributed_sudoku_solver_tpu.analysis import manifest
from distributed_sudoku_solver_tpu.analysis.common import (
    ALL_RULES,
    RULES,
    Finding,
    iter_sources,
    stale_waivers,
)
from distributed_sudoku_solver_tpu.obs.exitcodes import (
    EXIT_CLEAN,
    EXIT_INTERNAL,
    EXIT_VIOLATIONS,
)

_PACKAGE_DIR = Path(__file__).resolve().parent.parent


def run(
    root: Optional[Path] = None,
    scope: str = "package",
    rules: Tuple[str, ...] = RULES,
    update_golden: bool = False,
) -> Tuple[dict, List[Finding]]:
    """Run the selected rules; returns (json-ready report, findings)."""
    if scope == "benchmarks":
        root = root or _PACKAGE_DIR.parent / "benchmarks"
        package_root = None
        clock_all = True  # no package-relative dirs out there: scan all
    else:
        root = root or _PACKAGE_DIR
        package_root = root
        clock_all = False
    mods = list(iter_sources(root, package_root))
    findings: List[Finding] = []
    if "layerck" in rules:
        for mod in mods:
            findings.extend(layerck.check_module(mod, manifest.LAYERS))
    if "clockck" in rules:
        for mod in mods:
            findings.extend(clockck.check_module(
                mod,
                manifest.CLOCK_SCOPED_DIRS,
                manifest.CLOCK_BANNED_CALLS,
                manifest.CLOCK_SEAMS,
                scope_all=clock_all,
            ))
    if "syncck" in rules:
        for mod in mods:
            findings.extend(syncck.check_module(
                mod,
                manifest.SYNC_SCOPED_FILES,
                manifest.SYNC_HOT_REGIONS,
                manifest.SYNC_SEAM_FUNCS,
                manifest.SYNC_HOST_SOURCES,
                manifest.SYNC_NUMPY_CALLS,
                manifest.SYNC_METHOD_CALLS,
                manifest.SYNC_JAX_CALLS,
            ))
    if "lockck" in rules:
        findings.extend(lockck.check_modules(mods))
    deadck_summary = None
    if "deadck" in rules:
        dk_findings, deadck_summary = deadck.check_modules(
            mods,
            ranks=manifest.LOCK_RANKS,
            declared=manifest.LOCK_EDGE_DECLARED,
            base_classes=manifest.DEADCK_BASE_CLASSES,
            thread_roots=manifest.DEADCK_THREAD_ROOTS,
        )
        findings.extend(dk_findings)
    jaxck_summary = None
    if "jaxck" in rules:
        # The lazy lane: this import chain touches jax only inside
        # jaxck's functions, and only here — the default rules tuple
        # never includes jaxck, so the fast lane stays jax-free.
        from distributed_sudoku_solver_tpu.analysis import jaxck

        jx_findings, jaxck_summary = jaxck.check_entry_points(
            mods=mods, update_golden=update_golden
        )
        findings.extend(jx_findings)
    findings.sort()
    report = {
        "scope": scope,
        "rules": {
            rule: {
                "violations": [
                    f.to_dict() for f in findings
                    if f.rule == rule and not f.waived
                ],
                "waived": [
                    f.to_dict() for f in findings
                    if f.rule == rule and f.waived
                ],
            }
            for rule in sorted(rules)
        },
        "files_scanned": len(mods),
        # Waiver hygiene: sites whose rule ran and no longer fires there.
        "stale_waivers": [
            {"path": path, "line": line, "rule": rule, "reason": reason}
            for path, line, rule, reason in stale_waivers(mods, rules)
        ],
    }
    if deadck_summary is not None:
        # The predicted thread-plane graph: tier-1's runtime witness
        # (obs/lockdep.py) must observe a SUBSET of these edges.
        report["deadck"] = deadck_summary
    if jaxck_summary is not None:
        report["jaxck"] = {
            "drifted": jaxck_summary["drifted"],
            "golden_written": jaxck_summary["golden_written"],
            "programs": len(jaxck_summary["programs"]),
        }
    return report, findings


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_sudoku_solver_tpu.analysis",
        description=(
            "invariant linter: layerck/clockck/syncck/lockck/deadck (fast, "
            "no jax) + the opt-in compiled-layer lane (--rule jaxck)"
        ),
    )
    parser.add_argument("--json", action="store_true", help="machine report")
    parser.add_argument(
        "--rule", action="append", choices=ALL_RULES,
        help="run only this rule (repeatable); exit code reflects it alone. "
        "jaxck is opt-in: it imports jax (the default lane never does)",
    )
    parser.add_argument(
        "--scope", choices=("package", "benchmarks"), default="package",
        help="'benchmarks' scans benchmarks/ report-only (always exits 0 "
        "unless the tool itself fails)",
    )
    parser.add_argument(
        "--update-golden", action="store_true",
        help="(jaxck) bless HLO drift: rewrite analysis/goldens/jaxck.json "
        "from the current tree — commit the diff with the PR that causes it",
    )
    parser.add_argument(
        "--strict-waivers", action="store_true",
        help="exit 1 when a committed waiver's rule no longer fires on its "
        "line (default: stale waivers are report-only)",
    )
    parser.add_argument(
        "--root", type=Path, default=None, help=argparse.SUPPRESS
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage errors already — keep its semantics,
        # but normalise --help's 0.
        return EXIT_INTERNAL if e.code else EXIT_CLEAN
    rules = tuple(args.rule) if args.rule else RULES
    if args.update_golden and "jaxck" not in rules:
        print(
            "analysis: --update-golden only applies to --rule jaxck",
            file=sys.stderr,
        )
        return EXIT_INTERNAL
    try:
        report, findings = run(
            root=args.root, scope=args.scope, rules=rules,
            update_golden=args.update_golden,
        )
    except Exception:  # noqa: BLE001 - the tool failing is exit 2, loudly
        traceback.print_exc()
        return EXIT_INTERNAL
    if report["files_scanned"] == 0:
        # A typo'd --root (or a pip install with no benchmarks/ next to
        # the package) must not report success while checking nothing.
        print(
            "analysis: no Python files found under the scan root "
            f"[scope={args.scope}] — refusing to report a clean tree",
            file=sys.stderr,
        )
        return EXIT_INTERNAL
    violations = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]
    stale = report["stale_waivers"]
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr if not f.waived else sys.stdout)
        for s in stale:
            print(
                f"stale-waiver: {s['path']}:{s['line']}: "
                f"# {s['rule']}: allow({s['reason']}) — {s['rule']} no "
                "longer fires here; delete the waiver"
            )
        for rule in sorted(rules):
            nv = sum(1 for f in violations if f.rule == rule)
            nw = sum(1 for f in waived if f.rule == rule)
            print(f"analysis: {rule}: {nv} violation(s), {nw} waived")
        if "jaxck" in report:
            jx = report["jaxck"]
            if jx["golden_written"]:
                print(
                    f"analysis: jaxck: goldens updated for {jx['programs']} "
                    f"program(s) ({len(jx['drifted'])} drifted) — commit "
                    "analysis/goldens/jaxck.json"
                )
            elif jx["drifted"]:
                print(
                    f"analysis: jaxck: HLO drift in {len(jx['drifted'])} "
                    "program(s) — this PR invalidates the XLA cache for: "
                    + ", ".join(jx["drifted"])
                )
        if stale:
            print(f"analysis: {len(stale)} stale waiver(s)")
        print(
            f"analysis: {len(violations)} violation(s) over "
            f"{report['files_scanned']} files [scope={args.scope}]"
        )
    if args.scope == "benchmarks":
        return EXIT_CLEAN  # report-only lane (see --scope help)
    if violations:
        return EXIT_VIOLATIONS
    if stale and args.strict_waivers:
        return EXIT_VIOLATIONS
    return EXIT_CLEAN


if __name__ == "__main__":
    raise SystemExit(main())
