"""Runner: ``python -m distributed_sudoku_solver_tpu.analysis``.

Checks the package tree (default) or ``--scope benchmarks``
(report-only: benchmark scripts ARE wall-clock tools, so clock findings
there inform rather than gate — documented in the README).  ``--rule``
narrows to one or more rules, and the exit code then reflects exactly
the selected rules — the "per-rule exit codes" contract: a CI step can
gate on one rule while another is still being burned down.

Deterministic by construction: sorted file walk, sorted findings,
``sort_keys`` JSON — two runs over the same tree are byte-identical
(pinned by tests/test_analysis.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from pathlib import Path
from typing import List, Optional, Tuple

from distributed_sudoku_solver_tpu.analysis import clockck, layerck, lockck, syncck
from distributed_sudoku_solver_tpu.analysis import manifest
from distributed_sudoku_solver_tpu.analysis.common import (
    RULES,
    Finding,
    iter_sources,
)
from distributed_sudoku_solver_tpu.obs.exitcodes import (
    EXIT_CLEAN,
    EXIT_INTERNAL,
    EXIT_VIOLATIONS,
)

_PACKAGE_DIR = Path(__file__).resolve().parent.parent


def run(
    root: Optional[Path] = None,
    scope: str = "package",
    rules: Tuple[str, ...] = RULES,
) -> Tuple[dict, List[Finding]]:
    """Run the selected rules; returns (json-ready report, findings)."""
    if scope == "benchmarks":
        root = root or _PACKAGE_DIR.parent / "benchmarks"
        package_root = None
        clock_all = True  # no package-relative dirs out there: scan all
    else:
        root = root or _PACKAGE_DIR
        package_root = root
        clock_all = False
    mods = list(iter_sources(root, package_root))
    findings: List[Finding] = []
    if "layerck" in rules:
        for mod in mods:
            findings.extend(layerck.check_module(mod, manifest.LAYERS))
    if "clockck" in rules:
        for mod in mods:
            findings.extend(clockck.check_module(
                mod,
                manifest.CLOCK_SCOPED_DIRS,
                manifest.CLOCK_BANNED_CALLS,
                manifest.CLOCK_SEAMS,
                scope_all=clock_all,
            ))
    if "syncck" in rules:
        for mod in mods:
            findings.extend(syncck.check_module(
                mod,
                manifest.SYNC_SCOPED_FILES,
                manifest.SYNC_HOT_REGIONS,
                manifest.SYNC_SEAM_FUNCS,
                manifest.SYNC_HOST_SOURCES,
                manifest.SYNC_NUMPY_CALLS,
                manifest.SYNC_METHOD_CALLS,
                manifest.SYNC_JAX_CALLS,
            ))
    if "lockck" in rules:
        findings.extend(lockck.check_modules(mods))
    findings.sort()
    report = {
        "scope": scope,
        "rules": {
            rule: {
                "violations": [
                    f.to_dict() for f in findings
                    if f.rule == rule and not f.waived
                ],
                "waived": [
                    f.to_dict() for f in findings
                    if f.rule == rule and f.waived
                ],
            }
            for rule in sorted(rules)
        },
        "files_scanned": len(mods),
    }
    return report, findings


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_sudoku_solver_tpu.analysis",
        description="AST-based invariant linter (layerck/clockck/syncck/lockck)",
    )
    parser.add_argument("--json", action="store_true", help="machine report")
    parser.add_argument(
        "--rule", action="append", choices=RULES,
        help="run only this rule (repeatable); exit code reflects it alone",
    )
    parser.add_argument(
        "--scope", choices=("package", "benchmarks"), default="package",
        help="'benchmarks' scans benchmarks/ report-only (always exits 0 "
        "unless the tool itself fails)",
    )
    parser.add_argument(
        "--root", type=Path, default=None, help=argparse.SUPPRESS
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage errors already — keep its semantics,
        # but normalise --help's 0.
        return EXIT_INTERNAL if e.code else EXIT_CLEAN
    rules = tuple(args.rule) if args.rule else RULES
    try:
        report, findings = run(root=args.root, scope=args.scope, rules=rules)
    except Exception:  # noqa: BLE001 - the tool failing is exit 2, loudly
        traceback.print_exc()
        return EXIT_INTERNAL
    if report["files_scanned"] == 0:
        # A typo'd --root (or a pip install with no benchmarks/ next to
        # the package) must not report success while checking nothing.
        print(
            "analysis: no Python files found under the scan root "
            f"[scope={args.scope}] — refusing to report a clean tree",
            file=sys.stderr,
        )
        return EXIT_INTERNAL
    violations = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr if not f.waived else sys.stdout)
        for rule in sorted(rules):
            nv = sum(1 for f in violations if f.rule == rule)
            nw = sum(1 for f in waived if f.rule == rule)
            print(f"analysis: {rule}: {nv} violation(s), {nw} waived")
        print(
            f"analysis: {len(violations)} violation(s) over "
            f"{report['files_scanned']} files [scope={args.scope}]"
        )
    if args.scope == "benchmarks":
        return EXIT_CLEAN  # report-only lane (see --scope help)
    return EXIT_VIOLATIONS if violations else EXIT_CLEAN


if __name__ == "__main__":
    raise SystemExit(main())
