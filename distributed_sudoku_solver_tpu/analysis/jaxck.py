"""jaxck: prove the compiled layer the source rules cannot see.

layerck/clockck/syncck/lockck prove source-level contracts; the
contracts that actually price the serving path live one layer down, in
what XLA compiles.  ``donate_argnums`` silently no-ops when its aliasing
precondition fails (the round-8 zero-copy win evaporates without a
traceback); a stray ``pure_callback``/``debug.print`` reintroduces the
hidden per-dispatch host syncs syncck hunts, but at run time inside the
compiled program where no AST rule can reach; and any change to
shared-op HLO invalidates ``.cache/xla`` for every containing program —
ROADMAP prices the next cold run at ~1170 s.

jaxck abstractly traces every ``manifest.ENTRY_POINTS`` program at
canonical tiny shapes (``jax.jit(...).trace`` + ``.lower()`` — no
execution, no device, works on a CPU-only container) and proves:

* **donation lowers** — every donated pytree leaf of a ``threads``
  program produces a real ``input_output_aliases`` entry in the lowered
  StableHLO (``tf.aliasing_output``); ``drains`` programs (terminal
  frees) record their alias count in the golden instead.
* **callback-free hot programs** — no ``pure_callback`` /
  ``io_callback`` / ``debug_callback`` primitive anywhere in a
  serving-hot jaxpr, sub-jaxprs included.
* **dtype discipline** — no f64/c128 aval anywhere in any traced
  program, no weak-typed entry avals, and (statically, via the package
  AST) no call site handing a bare Python numeric literal to a traced
  parameter of an entry point — a weak-type cache fork that silently
  doubles retraces.
* **HLO-drift goldens** — a canonicalized jaxpr fingerprint per entry
  point, committed to ``analysis/goldens/jaxck.json``; drift is
  reported as "this PR invalidates the XLA cache for N programs" and
  blessed explicitly with ``--update-golden``.

This is the one analysis module allowed to import jax (see
``manifest.LAYERS`` — the import is lazy, inside functions, so the
default no-jax fast lane stays byte-identical and <5 s).
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from distributed_sudoku_solver_tpu.analysis import manifest
from distributed_sudoku_solver_tpu.analysis.common import (
    Finding,
    QualnameVisitor,
    SourceModule,
    call_name,
    finding,
)

_PACKAGE = "distributed_sudoku_solver_tpu"
GOLDEN_PATH = Path(__file__).resolve().parent / "goldens" / "jaxck.json"

#: Hex addresses (bound methods, partials, callback ids) are the one
#: run-varying thing a jaxpr pretty-print can contain — canonicalize
#: them away so fingerprints are stable across processes and hosts.
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def canonicalize(jaxpr_text: str) -> str:
    return _ADDR_RE.sub("0xCANON", jaxpr_text)


def fingerprint(jaxpr_text: str) -> str:
    return hashlib.sha256(canonicalize(jaxpr_text).encode()).hexdigest()


# -- canonical-shape resolution (the spec mini-language) -----------------------


class _Canon:
    """Resolved canonical objects for one ``JAXCK_CANON`` dict.

    Everything jax-flavored is built here, once, lazily — entry checks
    share the abstract Frontier specs (eval_shape never executes)."""

    def __init__(self, canon: dict):
        import jax
        import jax.numpy as jnp

        from distributed_sudoku_solver_tpu.models.geometry import Geometry
        from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

        self._jax = jax
        self.dims = dict(canon["dims"])
        self.geom = Geometry(*canon["geom"])
        self.configs = {
            name: SolverConfig(**kw) for name, kw in canon["configs"].items()
        }
        self.dtypes = {
            "uint8": jnp.uint8,
            "uint32": jnp.uint32,
            "int32": jnp.int32,
            "float32": jnp.float32,
            "bool": jnp.bool_,
        }
        self._frontiers: Dict[str, object] = {}
        self._resident = None
        self._mesh = None
        self._problem = None

    def _dim(self, d):
        return self.dims[d] if isinstance(d, str) else int(d)

    def frontier(self, config_name: str):
        """Abstract Frontier at L lanes / J jobs of the named config."""
        if config_name not in self._frontiers:
            import functools

            import jax
            import jax.numpy as jnp

            from distributed_sudoku_solver_tpu.ops.frontier import (
                init_frontier_roots,
            )

            L, J, n = self.dims["L"], self.dims["J"], self.dims["n"]
            self._frontiers[config_name] = jax.eval_shape(
                functools.partial(
                    init_frontier_roots,
                    n_jobs=J,
                    config=self.configs[config_name],
                ),
                jax.ShapeDtypeStruct((L, n, n), jnp.uint32),
                jax.ShapeDtypeStruct((L,), jnp.int32),
            )
        return self._frontiers[config_name]

    def resident(self):
        """The scheduler's gang frontier (slots gangs of G lanes)."""
        if self._resident is None:
            import functools

            import jax

            from distributed_sudoku_solver_tpu.serving.scheduler import (
                _init_resident,
            )

            self._resident = jax.eval_shape(
                functools.partial(
                    _init_resident,
                    geom=self.geom,
                    config=self.configs["config_gang"],
                    n_slots=self.dims["slots"],
                )
            )
        return self._resident

    def mesh(self):
        # Pinned to exactly ONE device regardless of host topology, so
        # goldens derived on a TPU pod and a CPU laptop agree.
        if self._mesh is None:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            self._mesh = Mesh(np.array(jax.devices()[:1]), ("lanes",))
        return self._mesh

    def problem(self):
        if self._problem is None:
            from distributed_sudoku_solver_tpu.ops.solve import sudoku_csp

            self._problem = sudoku_csp(self.geom, self.configs["config"])
        return self._problem

    def arg(self, spec):
        import jax

        kind = spec[0]
        if kind == "array":
            _, dims, dtype = spec
            shape = tuple(self._dim(d) for d in dims)
            return jax.ShapeDtypeStruct(shape, self.dtypes[dtype])
        if kind == "frontier":
            return self.frontier(spec[1])
        if kind == "resident":
            return self.resident()
        raise ValueError(f"unknown arg spec {spec!r}")

    def static(self, spec):
        if isinstance(spec, tuple) and spec and spec[0] == "dim":
            return self._dim(spec[1])
        if spec == "geom":
            return self.geom
        if isinstance(spec, str) and spec in self.configs:
            return self.configs[spec]
        if spec == "mesh":
            return self.mesh()
        if spec == "problem":
            return self.problem()
        if isinstance(spec, (int, str)):
            return spec
        raise ValueError(f"unknown static spec {spec!r}")


# -- jaxpr walking -------------------------------------------------------------


def _sub_jaxprs(value):
    """Jaxpr-shaped things hiding inside an eqn param (ClosedJaxpr,
    Jaxpr, or lists/tuples of either — while/cond/scan/pjit/custom_*)."""
    if hasattr(value, "eqns"):
        yield value
    elif hasattr(value, "jaxpr"):
        yield value.jaxpr
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from _sub_jaxprs(item)


def iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and all nested sub-jaxprs, depth-first."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _scan_jaxpr(closed_jaxpr, banned_callbacks, banned_dtypes):
    """(callback primitive names, banned dtype names, weak invar count)."""
    jaxpr = closed_jaxpr.jaxpr
    callbacks: List[str] = []
    bad_dtypes: set = set()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in banned_callbacks:
            callbacks.append(eqn.primitive.name)
        for var in tuple(eqn.outvars):
            dt = getattr(getattr(var, "aval", None), "dtype", None)
            if dt is not None and dt.name in banned_dtypes:
                bad_dtypes.add(dt.name)
    weak = sum(
        1
        for v in tuple(jaxpr.invars) + tuple(jaxpr.outvars)
        if getattr(getattr(v, "aval", None), "weak_type", False)
    )
    return callbacks, sorted(bad_dtypes), weak


# -- the checker ---------------------------------------------------------------


def _load_entry(fnref: str):
    import importlib

    modpath, attr = fnref.split(":")
    return getattr(importlib.import_module(modpath), attr)


def _rel_modname(fnref: str) -> str:
    """'distributed_sudoku_solver_tpu.serving.engine:_purge' -> 'serving.engine'."""
    modpath = fnref.split(":")[0]
    prefix = _PACKAGE + "."
    return modpath[len(prefix):] if modpath.startswith(prefix) else modpath


class _Anchor:
    """A line-only AST stand-in so registry-level findings anchor to the
    entry point's ``def`` line and resolve waivers there."""

    def __init__(self, lineno: int):
        self.lineno = lineno


def _def_line(mod: Optional[SourceModule], attr: str) -> int:
    if mod is None:
        return 0
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == attr:
                return node.lineno
    return 0


def _entry_finding(
    mod: Optional[SourceModule], relmod: str, attr: str, message: str
) -> Finding:
    if mod is None:
        return Finding("jaxck", relmod.replace(".", "/") + ".py", 0, message)
    line = _def_line(mod, attr)
    return finding(mod, "jaxck", _Anchor(line), message, def_lines=(line,))


def _donation_report(lowered) -> Tuple[int, int]:
    """(donated flattened-arg count, realized input_output_aliases count)."""
    import jax.tree_util as jtu

    leaves = jtu.tree_leaves(
        lowered.args_info, is_leaf=lambda v: hasattr(v, "donated")
    )
    donated = sum(1 for a in leaves if a.donated)
    aliases = lowered.as_text().count("tf.aliasing_output")
    return donated, aliases


def load_golden(path: Path) -> dict:
    try:
        return json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError):
        return {"programs": {}}


def check_entry_points(
    entries: Optional[Sequence[dict]] = None,
    canon: Optional[dict] = None,
    golden_path: Optional[Path] = None,
    mods: Sequence[SourceModule] = (),
    update_golden: bool = False,
    banned_callbacks: Optional[Tuple[str, ...]] = None,
    banned_dtypes: Optional[Tuple[str, ...]] = None,
) -> Tuple[List[Finding], dict]:
    """Trace every entry point and prove the four compiled-layer
    invariants.  Returns ``(findings, summary)`` where summary carries
    ``drifted`` (program names whose fingerprint moved), ``programs``
    (the freshly derived golden table) and ``golden_written``.

    Pure tracing: nothing executes, no device is touched, so the rule
    runs identically on CPU CI and a TPU host.  ``update_golden`` writes
    the derived table to ``golden_path`` (drift findings are then
    reported as blessed, not violations).
    """
    import warnings

    entries = manifest.ENTRY_POINTS if entries is None else entries
    canon = manifest.JAXCK_CANON if canon is None else canon
    golden_path = GOLDEN_PATH if golden_path is None else Path(golden_path)
    banned_callbacks = (
        manifest.JAXCK_BANNED_CALLBACKS
        if banned_callbacks is None
        else banned_callbacks
    )
    banned_dtypes = (
        manifest.JAXCK_BANNED_DTYPES if banned_dtypes is None else banned_dtypes
    )

    import jax

    ctx = _Canon(canon)
    golden = load_golden(golden_path)
    golden_programs: Dict[str, dict] = dict(golden.get("programs", {}))
    golden_jax = golden.get("jax")
    mods_by_name = {m.modname: m for m in mods if m.modname}

    findings: List[Finding] = []
    programs: Dict[str, dict] = {}
    drifted: List[str] = []
    carved: List[dict] = []  # hot-callback allowances actually exercised

    # Display names are the compiled layer's shared vocabulary (manifest
    # note): the production compile watch keys its per-program /metrics
    # series on them, so a collision would silently merge two programs'
    # compile counts.  Uniqueness is enforced here, where the registry is
    # already being proved.
    seen_displays: Dict[str, str] = {}
    for entry in entries:
        disp = manifest.entry_display(entry)
        if disp in seen_displays:
            findings.append(
                Finding(
                    "jaxck", "analysis/manifest.py", 0,
                    f"duplicate display name {disp!r} "
                    f"({seen_displays[disp]} vs {entry['name']}) — "
                    "compilewatch would merge their compile counts",
                )
            )
        seen_displays[disp] = entry["name"]

    for entry in entries:
        name = entry["name"]
        disp = manifest.entry_display(entry)
        relmod = _rel_modname(entry["fn"])
        attr = entry["fn"].split(":")[1]
        mod = mods_by_name.get(relmod)
        # Claim the golden up front: a program that fails to resolve or
        # trace must neither double-report as a stale golden nor lose
        # its committed record on --update-golden.
        old = golden_programs.pop(name, None)

        def report(message: str) -> None:
            findings.append(_entry_finding(mod, relmod, attr, message))

        def keep_old() -> None:
            if old is not None:
                programs[name] = old

        try:
            fn = _load_entry(entry["fn"])
            args = tuple(ctx.arg(spec) for spec in entry["args"])
            static = {k: ctx.static(v) for k, v in entry["static"].items()}
        except Exception as e:  # noqa: BLE001 - a broken registry entry is a finding
            report(f"{name}: entry point failed to resolve: {type(e).__name__}: {e}")
            keep_old()
            continue

        try:
            with warnings.catch_warnings():
                # Donation-unused warnings are OUR diagnostic, counted
                # below from the lowered text, not a console spray.
                warnings.simplefilter("ignore")
                traced = fn.trace(*args, **static)
                closed = traced.jaxpr
        except Exception as e:  # noqa: BLE001 - the program not tracing is the finding
            report(f"{name}: abstract trace failed: {type(e).__name__}: {e}")
            keep_old()
            continue

        # -- invariant 2+3: callbacks / dtypes / weak entry avals ----------
        callbacks, bad_dtypes, weak = _scan_jaxpr(
            closed, banned_callbacks, banned_dtypes
        )
        if entry.get("hot") and callbacks:
            counts = {p: callbacks.count(p) for p in sorted(set(callbacks))}
            carveout = getattr(manifest, "JAXCK_CALLBACK_CARVEOUTS", {}).get(name)
            if carveout:
                # A DECLARED design decision, not a violation: the
                # manifest table carries the why, the summary carries the
                # allowance, and the callback stays drift-visible via the
                # golden fingerprint.
                carved.append(
                    {"name": name, "callbacks": counts, "reason": carveout}
                )
            else:
                report(
                    f"{name}: callback in serving-hot program: "
                    + ", ".join(f"{p} x{c}" for p, c in counts.items())
                    + " — a hidden host round-trip per dispatch syncck cannot see"
                )
        if bad_dtypes:
            report(
                f"{name}: banned dtype(s) {', '.join(bad_dtypes)} in traced "
                "program — doubles bytes/lane and forks the compile cache"
            )
        if weak:
            report(
                f"{name}: {weak} weak-typed entry aval(s) — a Python-scalar "
                "leak into the jit signature retraces per promotion context"
            )

        # -- invariant 1: donation lowers ----------------------------------
        # The lowering runs for EVERY program, not just manifest-donated
        # ones: the lowered args_info is the ground truth, so a
        # donate_argnums added to (or dropped from) a decorator that the
        # manifest doesn't agree with is itself a finding — the registry
        # can't silently under-describe the donation surface.
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                donated, aliases = _donation_report(traced.lower())
        except Exception as e:  # noqa: BLE001 - ditto: not lowering is the finding
            report(f"{name}: lowering failed: {type(e).__name__}: {e}")
            keep_old()
            continue
        if entry.get("donate"):
            if donated == 0:
                report(
                    f"{name}: manifest declares donated args but the traced "
                    "program donates nothing — donate_argnums dropped?"
                )
            elif entry.get("donation") == "threads" and aliases < donated:
                report(
                    f"{name}: donation did not lower: {aliases}/{donated} "
                    "donated buffers alias an output (input_output_aliases) "
                    "— the zero-copy rebind contract is silently broken"
                )
        elif donated:
            report(
                f"{name}: program donates {donated} buffer(s) but the "
                "manifest entry declares donate=() — update ENTRY_POINTS "
                "so the donation invariant actually covers it"
            )

        # -- invariant 4: HLO-drift golden ---------------------------------
        text = str(closed)
        fp = fingerprint(text)
        eqns = sum(1 for _ in iter_eqns(closed.jaxpr))
        programs[name] = {
            "fingerprint": fp,
            "eqns": eqns,
            "donated": donated,
            "aliases": aliases,
        }
        if old is None:
            if not update_golden:
                report(
                    f"{name}: no committed golden for this program — run "
                    "--rule jaxck --update-golden and commit the result"
                )
        elif old.get("fingerprint") != fp:
            drifted.append(name)
            if not update_golden:
                version_note = (
                    f" [goldens were derived under jax {golden_jax}, this "
                    f"run is jax {jax.__version__} — re-derive under the "
                    "pinned toolchain]"
                    if golden_jax not in (None, jax.__version__)
                    else ""
                )
                report(
                    f"{name}: HLO drift (eqns {old.get('eqns')} -> {eqns}): "
                    "this PR changes the compiled program and invalidates "
                    "the XLA cache for it — a deployed node will recompile "
                    f"it, and the compile watch will alarm on [compile "
                    f"{disp}]; if intentional, bless with "
                    "--rule jaxck --update-golden (cold tier-1 recompile "
                    "is priced in ROADMAP's timing note)" + version_note
                )

    # Registry shrank but the golden still lists the program: stale data
    # rots exactly like stale waivers.
    for name in sorted(golden_programs):
        findings.append(
            Finding(
                "jaxck",
                "analysis/goldens/jaxck.json",
                0,
                f"{name}: golden entry has no ENTRY_POINTS program — "
                "remove it (or re-run --update-golden)",
            )
        )

    findings.extend(_scalar_pin_findings(entries, mods))

    written = False
    if update_golden:
        golden_path.parent.mkdir(parents=True, exist_ok=True)
        # The deriving jax version rides along: fingerprints are stable
        # per version, not across them — a mismatch turns a wall of
        # drift findings into a one-line toolchain diagnosis.
        golden_path.write_text(
            json.dumps(
                {"jax": jax.__version__, "programs": programs},
                indent=2,
                sort_keys=True,
            )
            + "\n"
        )
        written = True

    summary = {
        "programs": programs,
        "drifted": sorted(drifted),
        "golden_written": written,
    }
    if carved:
        # Surface every exercised JAXCK_CALLBACK_CARVEOUTS allowance so a
        # carve-out is never silent — reviewers see it in the rule
        # summary, not just the manifest.
        summary["callback_carveouts"] = carved
    return findings, summary


# -- the static half: un-pinned Python scalars at entry call sites -------------


def _entry_params(entries, mods_by_name):
    """(entry modpath, attr) -> (positional param names, static names)."""
    table = {}
    for entry in entries:
        modpath = entry["fn"].split(":")[0]
        relmod = _rel_modname(entry["fn"])
        attr = entry["fn"].split(":")[1]
        mod = mods_by_name.get(relmod)
        if mod is None:
            continue
        for node in mod.tree.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == attr
            ):
                params = [a.arg for a in node.args.posonlyargs + node.args.args]
                table[(modpath, attr)] = (params, set(entry["static"].keys()))
                break
    return table


def _local_entry_names(mod: SourceModule, table) -> Dict[str, Tuple[str, str]]:
    """Names that resolve to an entry point INSIDE ``mod``: its own
    top-level defs plus ``from <entry module> import attr [as alias]``
    bindings.  Matching on resolved imports — never on a bare trailing
    name — keeps an unrelated same-named function or method elsewhere in
    the package from being judged against the entry's parameter table."""
    names: Dict[str, Tuple[str, str]] = {}
    own = f"{_PACKAGE}.{mod.modname}" if mod.modname else mod.modname
    for (modpath, attr) in table:
        if modpath in (own, mod.modname):
            names[attr] = (modpath, attr)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                key = (node.module, alias.name)
                if key in table:
                    names[alias.asname or alias.name] = key
    return names


def _scalar_pin_findings(
    entries: Sequence[dict], mods: Sequence[SourceModule]
) -> List[Finding]:
    """Flag call sites handing a bare numeric literal to a TRACED
    parameter of an entry point.  A Python scalar traces as a weak-typed
    aval, which forks the jit cache against the ``jnp.int32``-pinned
    spelling every other caller uses — one sloppy call site silently
    doubles the program's retraces.  Static parameters (part of the jit
    key by design) are exempt."""
    mods_by_name = {m.modname: m for m in mods if m.modname}
    table = _entry_params(entries, mods_by_name)
    if not table:
        return []
    out: List[Finding] = []
    for mod in mods:
        local = _local_entry_names(mod, table)
        if not local:
            continue

        class _Calls(QualnameVisitor):
            def __init__(self) -> None:
                super().__init__()
                self.sites: List[Tuple[ast.Call, str, Tuple[int, ...]]] = []

            def visit_Call(self, node: ast.Call) -> None:
                target = call_name(node)
                if target in local:
                    self.sites.append((node, target, tuple(self.def_lines)))
                self.generic_visit(node)

        visitor = _Calls()
        visitor.visit(mod.tree)
        for node, target, def_lines in visitor.sites:
            params, static_names = table[local[target]]
            flagged = []
            for pos, a in enumerate(node.args):
                pname = params[pos] if pos < len(params) else None
                if pname in static_names:
                    continue
                if (
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, (int, float))
                    and not isinstance(a.value, bool)
                ):
                    flagged.append(pname or f"arg {pos}")
            for kw in node.keywords:
                if kw.arg is None or kw.arg in static_names:
                    continue
                if (
                    isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, (int, float))
                    and not isinstance(kw.value.value, bool)
                ):
                    flagged.append(kw.arg)
            attr = local[target][1]
            for pname in flagged:
                out.append(
                    finding(
                        mod,
                        "jaxck",
                        node,
                        f"un-pinned Python scalar for traced param "
                        f"'{pname}' of {attr}() — weak-type cache fork; "
                        "wrap in jnp.int32(...)/jnp.asarray(...)",
                        def_lines=def_lines,
                    )
                )
    return out
