"""deadck: prove the thread plane — the static lock-order graph.

The suite proves imports (layerck), clocks (clockck), host syncs
(syncck), declared lock guards (lockck) and the compiled layer (jaxck);
this rule proves the substrate they all run on.  Three passes over the
whole scan set, all driven by ``analysis/manifest.py`` as pure data:

1. **Lock identity.**  Every lock is created through the
   ``obs.lockdep.named_*`` factories with a ``# lockck: name(<tier>.<name>)``
   annotation on the creation line.  deadck checks: a raw
   ``threading.Lock/RLock/Condition`` creation is a finding (unnamed
   lock); the annotation and the factory's literal argument must agree;
   the name must exist in ``manifest.LOCK_RANKS``.

2. **Lock-order graph vs the declared hierarchy.**  A conservative
   call-graph walk records every acquisition reached — lexically or
   through resolvable calls (``self.m()``, module functions, the
   ``manifest.DEADCK_BASE_CLASSES`` receiver hints, and globally
   near-unique method names), including cross-module edges (an http
   handler taking ``engine._lock``) and the ``*_locked`` caller-holds-it
   convention (a ``*_locked`` method is analyzed as holding its class's
   named locks).  Every edge (held -> acquired) must be rank-upward in
   ``manifest.LOCK_RANKS`` or declared in ``manifest.LOCK_EDGE_DECLARED``;
   any cycle in the predicted graph (declared edges included) is a
   finding.  The predicted edge set is exported in the ``--json`` report
   — tier-1 cross-checks that the runtime witness's observed graph
   (``obs/lockdep.py``) is a SUBSET of it: an observed edge deadck did
   not predict is a deadck bug (jaxck's golden discipline applied to
   concurrency).

3. **Guard inference** — the pass that closes lockck's annotate-only
   blind spot.  ``manifest.DEADCK_THREAD_ROOTS`` declares the repo's
   thread roots (the device loop, HTTP handler methods, heartbeat/
   progress loops, fan-out/racer/timer bodies); deadck walks the call
   graph from each root and reports every ``self.<attr>`` write (outside
   ``__init__``) reachable from >= 2 distinct roots whose class declares
   no lockck guard for it.  lockck's declared set thereby becomes
   *proven complete*: a cross-thread write either carries a guard
   declaration, or a reasoned waiver, or fails the gate.

Conservative by design: call resolution over-approximates (an edge that
cannot happen is harmless — the hierarchy only rejects rank-violating
shapes), and what it cannot see statically (injected callables like
``metrics_fn``) is exactly what ``LOCK_EDGE_DECLARED`` declares and the
runtime witness observes.

Stdlib-``ast`` only; stays in the <5 s no-jax fast lane.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from distributed_sudoku_solver_tpu.analysis.common import (
    NAME_RE,
    Finding,
    QualnameVisitor,
    SourceModule,
    finding,
)
from distributed_sudoku_solver_tpu.analysis.lockck import (
    _write_target,
    collect_guards,
)

#: Raw primitives whose direct use is an unnamed-lock finding.
_RAW_PRIMS = ("threading.Lock", "threading.RLock", "threading.Condition")
#: The naming factories (matched on the trailing attribute so both
#: ``lockdep.named_lock`` and a bare ``named_lock`` import resolve).
_FACTORIES = ("named_lock", "named_rlock", "named_condition")
#: The one module allowed to touch raw primitives: the factories' own
#: internals and the witness's bookkeeping lock live there.
_EXEMPT_PATHS = ("obs/lockdep.py",)
#: Bare-name call resolution falls back to the global function-name index
#: only when the name is this unambiguous; anything noisier is treated as
#: unresolvable (the runtime witness is the backstop).
_NAME_FANOUT_CAP = 8


@dataclasses.dataclass(frozen=True, order=True)
class LockDecl:
    name: str
    path: str
    line: int
    qualclass: str  # declaring class ("" = module level)
    attr: str  # attribute / variable the lock is bound to
    kind: str = "lock"  # factory kind: lock | rlock | condition


class _Collector(QualnameVisitor):
    """Pass 1 over one module: lock creations, function registry, and the
    per-function acquisition/call/write facts pass 2 consumes."""

    def __init__(self, mod: SourceModule):
        super().__init__()
        self.mod = mod
        self.class_stack: List[str] = []
        self.locks: List[LockDecl] = []
        self.findings: List[Finding] = []
        # fkey = (path, dotted qualname)
        self.functions: Dict[Tuple[str, str], dict] = {}
        self._fstack: List[dict] = []
        self._with_stacks: List[List[str]] = []  # one per function frame
        self._cur_assign: Optional[Tuple[str, str, int]] = None
        # Lock resolution registries filled by _register_lock; merged
        # tree-wide by check_modules.
        self.class_locks: Dict[Tuple[str, str, str], str] = {}
        self.module_locks: Dict[str, str] = {}

    # -- plumbing ------------------------------------------------------------
    @property
    def qualclass(self) -> str:
        return ".".join(self.class_stack)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        super().visit_ClassDef(node)
        self.class_stack.pop()

    def _visit_func(self, node) -> None:
        self.stack.append(node.name)
        self.def_lines.append(node.lineno)
        qn = ".".join(s for s in self.stack)
        fn = {
            "qualclass": self.qualclass,
            "name": node.name,
            "line": node.lineno,
            "def_lines": tuple(self.def_lines),
            "acquires": [],  # (lockname, heldset, line)
            "calls": [],  # (callname, heldset, line)
            "writes": [],  # (attr, line, heldset, def_lines)
            "order": len(self.functions),
        }
        self.functions[(self.mod.rel, qn)] = fn
        self._fstack.append(fn)
        self._with_stacks.append([])
        self.generic_visit(node)
        self._with_stacks.pop()
        self._fstack.pop()
        self.def_lines.pop()
        self.stack.pop()

    def _held(self) -> Tuple[str, ...]:
        if not self._with_stacks:
            return ()
        return tuple(self._with_stacks[-1])

    # -- lock creation -------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        target = node.targets[0] if len(node.targets) == 1 else None
        self._enter_assign(target, node.lineno)
        for t in node.targets:
            self._record_write(t, node.lineno)
        self.generic_visit(node)
        self._cur_assign = None

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._enter_assign(node.target, node.lineno)
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)
        self._cur_assign = None

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def _enter_assign(self, target, line: int) -> None:
        self._cur_assign = None
        if target is None:
            return
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id == "self":
            self._cur_assign = ("self", target.attr, line)
        elif isinstance(target, ast.Name):
            if self.class_stack and not self._fstack:
                # Class-body field (the _Control dataclass lock): an
                # instance attribute, resolved the same way self.X is.
                self._cur_assign = ("self", target.id, line)
            else:
                self._cur_assign = ("", target.id, line)

    def _record_write(self, target, line: int) -> None:
        if not self._fstack:
            return
        attr = _write_target(target)
        if attr is None:
            return
        if not (
            isinstance(attr.value, ast.Name) and attr.value.id == "self"
        ):
            return
        self._fstack[-1]["writes"].append(
            (attr.attr, line, self._held(), self._fstack[-1]["def_lines"])
        )

    def visit_Call(self, node: ast.Call) -> None:
        try:
            func = ast.unparse(node.func)
        except Exception:  # pragma: no cover
            func = ""
        if func in _RAW_PRIMS and self.mod.rel not in _EXEMPT_PATHS:
            self.findings.append(finding(
                self.mod, "deadck", node,
                f"unnamed lock: `{func}()` — create it through "
                "obs.lockdep.named_lock/named_rlock/named_condition with a "
                "`# lockck: name(<tier>.<name>)` annotation so the static "
                "graph and the runtime witness both know it",
                def_lines=self._fstack[-1]["def_lines"] if self._fstack else (),
            ))
        elif func.rsplit(".", 1)[-1] in _FACTORIES:
            if self.mod.rel not in _EXEMPT_PATHS:
                self._register_lock(node)
        elif func.rsplit(".", 1)[-1] == "field" and self.mod.rel not in _EXEMPT_PATHS:
            for kw in node.keywords:
                if kw.arg == "default_factory":
                    try:
                        v = ast.unparse(kw.value)
                    except Exception:  # pragma: no cover
                        v = ""
                    if v in _RAW_PRIMS:
                        self.findings.append(finding(
                            self.mod, "deadck", node,
                            f"unnamed lock: `default_factory={v}` — use a "
                            "lambda over an obs.lockdep factory with the "
                            "name annotation",
                        ))
        # record the call for the graph (skip the factory itself)
        if self._fstack and func.rsplit(".", 1)[-1] not in _FACTORIES:
            self._fstack[-1]["calls"].append(
                (func, self._held(), node.lineno)
            )
            if func.rsplit(".", 1)[-1] == "acquire":
                # Direct .acquire() — treated as an acquisition of the
                # receiver if it resolves to a named lock (pass 2).
                recv = func[: -len(".acquire")]
                self._fstack[-1]["acquires"].append(
                    ("?expr:" + recv, self._held(), node.lineno)
                )
        self.generic_visit(node)

    def _register_lock(self, node: ast.Call) -> None:
        try:
            factory = ast.unparse(node.func).rsplit(".", 1)[-1]
        except Exception:  # pragma: no cover
            factory = "named_lock"
        kind = {"named_rlock": "rlock", "named_condition": "condition"}.get(
            factory, "lock"
        )
        arg = None
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            arg = node.args[0].value
        ann = None
        if self._cur_assign is not None:
            m = NAME_RE.search(self.mod.comments.get(self._cur_assign[2], ""))
            if m is not None:
                ann = m.group(1)
        if arg is None:
            self.findings.append(finding(
                self.mod, "deadck", node,
                "named-lock factory needs a literal name argument",
            ))
            return
        if self._cur_assign is None:
            self.findings.append(finding(
                self.mod, "deadck", node,
                f"named lock '{arg}' created outside a simple assignment — "
                "deadck cannot bind it to an attribute",
            ))
            return
        base, attr, line = self._cur_assign
        if ann is None:
            self.findings.append(finding(
                self.mod, "deadck", node,
                f"named lock '{arg}' is missing its creation-line "
                "`# lockck: name(...)` annotation",
            ))
        elif ann != arg:
            self.findings.append(finding(
                self.mod, "deadck", node,
                f"lock name annotation '{ann}' disagrees with the factory "
                f"argument '{arg}'",
            ))
        self.locks.append(LockDecl(
            name=arg, path=self.mod.rel, line=line,
            qualclass=self.qualclass, attr=attr, kind=kind,
        ))
        if base == "self":
            self.class_locks[(self.mod.rel, self.qualclass, attr)] = arg
        else:
            self.module_locks[attr] = arg

    # -- acquisitions --------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        if self._fstack:
            for item in node.items:
                try:
                    ctx = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover
                    continue
                self._fstack[-1]["acquires"].append(
                    ("?expr:" + ctx, self._held(), node.lineno)
                )
                # Optimistically track it as held; pass 2 drops the frame
                # if the expression does not resolve to a named lock.
                self._with_stacks[-1].append("?expr:" + ctx)
                pushed += 1
        self.generic_visit(node)
        if pushed:
            del self._with_stacks[-1][-pushed:]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda body runs later (thread targets, default factories):
        # never under the lexical with-stack.  Not walked for edges — a
        # lambda substantial enough to take locks belongs in a def — but
        # a factory call inside one (the dataclass-field idiom) still
        # registers its lock.
        for sub in ast.walk(node.body):
            if isinstance(sub, ast.Call):
                try:
                    func = ast.unparse(sub.func)
                except Exception:  # pragma: no cover
                    continue
                if func.rsplit(".", 1)[-1] in _FACTORIES:
                    self._register_lock(sub)
        return


def _class_only(qualname: str, functions_meta: dict) -> str:
    return functions_meta["qualclass"]


class _Resolver:
    """Tree-wide name resolution shared by the edge and reachability
    passes."""

    def __init__(self, collectors: List[_Collector], base_classes: dict):
        self.base_classes = dict(base_classes)
        self.class_locks: Dict[Tuple[str, str, str], str] = {}
        self.module_locks: Dict[Tuple[str, str], str] = {}
        self.attr_locks: Dict[str, Set[str]] = {}
        self.methods: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        self.modfuncs: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.name_index: Dict[str, List[Tuple[str, str]]] = {}
        self.functions: Dict[Tuple[str, str], dict] = {}
        for c in collectors:
            self.class_locks.update(c.class_locks)
            for attr, name in c.module_locks.items():
                self.module_locks[(c.mod.rel, attr)] = name
            for (_p, _c, attr), name in c.class_locks.items():
                self.attr_locks.setdefault(attr, set()).add(name)
            for fkey, fn in c.functions.items():
                self.functions[fkey] = fn
                path, qn = fkey
                cls = fn["qualclass"]
                fname = fn["name"]
                if cls:
                    self.methods.setdefault((path, cls), {})[fname] = fkey
                elif "." not in qn:
                    self.modfuncs.setdefault(path, {})[fname] = fkey
                self.name_index.setdefault(fname, []).append(fkey)

    # -- locks ---------------------------------------------------------------
    def resolve_lock(self, expr: str, path: str, qualclass: str) -> Optional[str]:
        parts = expr.split(".")
        if len(parts) == 1:
            return self.module_locks.get((path, expr))
        attr = parts[-1]
        recv = ".".join(parts[:-1])
        if recv == "self" and qualclass:
            # Walk outward through nested classes.
            cls = qualclass
            while True:
                hit = self.class_locks.get((path, cls, attr))
                if hit is not None:
                    return hit
                if "." not in cls:
                    break
                cls = cls.rsplit(".", 1)[0]
        hint = self.base_classes.get(recv)
        if hint is not None:
            return self.class_locks.get((hint[0], hint[1], attr))
        if recv != "self":
            # Unhinted cross-base: unique attribute name tree-wide only.
            names = self.attr_locks.get(attr, set())
            if len(names) == 1:
                return next(iter(names))
        return None

    # -- calls ---------------------------------------------------------------
    def resolve_call(
        self, callname: str, path: str, qualclass: str, strict: bool = False
    ) -> List[Tuple[str, str]]:
        parts = callname.split(".")
        meth = parts[-1]
        if not meth.isidentifier():
            return []
        if len(parts) == 1:
            hit = self.modfuncs.get(path, {}).get(meth)
            if hit is not None:
                return [hit]
        else:
            recv = ".".join(parts[:-1])
            if recv == "self" and qualclass:
                cls = qualclass
                while True:
                    hit = self.methods.get((path, cls), {}).get(meth)
                    if hit is not None:
                        return [hit]
                    if "." not in cls:
                        break
                    cls = cls.rsplit(".", 1)[0]
            hint = self.base_classes.get(recv)
            if hint is not None:
                hit = self.methods.get(hint, {}).get(meth)
                return [hit] if hit is not None else []
        if "(" in callname:
            # A constructed receiver (``threading.Thread(...).start()``)
            # is never one of our instances — the name-index fallback
            # would bind it to unrelated classes' methods.
            return []
        cands = self.name_index.get(meth, [])
        cap = 1 if strict else _NAME_FANOUT_CAP
        if 0 < len(cands) <= cap:
            return list(cands)
        return []


def _function_facts(resolver: _Resolver, ranks: dict) -> Dict[Tuple[str, str], dict]:
    """Resolve the raw per-function facts: acquisition expressions to lock
    names, ``*_locked`` implicit holds, held-set frames that turned out
    not to be locks."""
    facts = {}
    for fkey, fn in resolver.functions.items():
        path, _qn = fkey
        cls = fn["qualclass"]

        def name_of(token: str) -> Optional[str]:
            if not token.startswith("?expr:"):
                return token
            return resolver.resolve_lock(token[6:], path, cls)

        implicit: Tuple[str, ...] = ()
        if fn["name"].endswith("_locked") and cls:
            implicit = tuple(sorted(
                name
                for (p, c, _a), name in resolver.class_locks.items()
                if p == path and c == cls
            ))
        acquires = []
        calls = []
        for token, held, line in fn["acquires"]:
            name = name_of(token)
            if name is None:
                continue
            held_names = tuple(
                h for h in (name_of(t) for t in held) if h is not None
            ) + implicit
            acquires.append((name, held_names, line))
        for callname, held, line in fn["calls"]:
            held_names = tuple(
                h for h in (name_of(t) for t in held) if h is not None
            ) + implicit
            calls.append((callname, held_names, line))
        writes = []
        for attr, line, held, def_lines in fn["writes"]:
            held_names = tuple(
                h for h in (name_of(t) for t in held) if h is not None
            ) + implicit
            writes.append((attr, line, held_names, def_lines))
        facts[fkey] = {
            "acquires": acquires,
            "calls": calls,
            "writes": writes,
            "qualclass": cls,
            "name": fn["name"],
            "def_lines": fn["def_lines"],
            "order": fn["order"],
        }
    return facts


def _may_acquire(
    facts: dict, resolver: _Resolver
) -> Dict[Tuple[str, str], Set[str]]:
    """Fixpoint: the set of lock names each function may (transitively)
    acquire."""
    may: Dict[Tuple[str, str], Set[str]] = {
        fkey: {a for a, _h, _l in fn["acquires"]} for fkey, fn in facts.items()
    }
    callees: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    strict_callees: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for fkey, fn in facts.items():
        path, _ = fkey
        seen = []
        strict_seen = []
        for callname, _held, _line in fn["calls"]:
            for g in resolver.resolve_call(callname, path, fn["qualclass"]):
                if g != fkey:
                    seen.append(g)
            for g in resolver.resolve_call(
                callname, path, fn["qualclass"], strict=True
            ):
                if g != fkey:
                    strict_seen.append(g)
        callees[fkey] = seen
        strict_callees[fkey] = strict_seen
    changed = True
    while changed:
        changed = False
        for fkey in facts:
            cur = may[fkey]
            before = len(cur)
            for g in callees[fkey]:
                cur |= may.get(g, set())
            if len(cur) != before:
                changed = True
    return may, callees, strict_callees


def check_modules(
    mods: List[SourceModule],
    ranks: dict,
    declared: dict,
    base_classes: dict,
    thread_roots: dict,
) -> Tuple[List[Finding], dict]:
    """Run all three deadck passes; returns (findings, summary) where the
    summary carries the predicted graph for ``--json`` and the runtime
    cross-check."""
    collectors = []
    findings: List[Finding] = []
    for mod in mods:
        c = _Collector(mod)
        c.visit(mod.tree)
        collectors.append(c)
        findings.extend(c.findings)
    resolver = _Resolver(collectors, base_classes)
    mod_by_rel = {c.mod.rel: c.mod for c in collectors}

    # Pass 1 tail: every named lock must exist in the manifest ranks.
    locks: List[LockDecl] = []
    for c in collectors:
        locks.extend(c.locks)
    for d in sorted(locks):
        if d.name not in ranks:
            findings.append(Finding(
                "deadck", d.path, d.line,
                f"lock name '{d.name}' is not declared in "
                "manifest.LOCK_RANKS",
            ))

    # Factory kind per name: a DIRECT re-acquisition of a held
    # non-reentrant lock is a guaranteed self-deadlock (the runtime
    # witness raises on it by object identity; this is the static twin,
    # approximated by name — waivable if two distinct instances of one
    # name are legitimately nested).
    lock_kind = {d.name: d.kind for d in locks}
    facts = _function_facts(resolver, ranks)
    may, callees, strict_callees = _may_acquire(facts, resolver)

    # Pass 2: edge emission (deterministic: modules in scan order,
    # functions in definition order, sites in line order).
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(a: str, b: str, path: str, line: int) -> None:
        if a == b or (a, b) in edges:
            return
        edges[(a, b)] = (path, line)

    ordered = sorted(
        facts.items(), key=lambda kv: (kv[0][0], kv[1]["order"])
    )
    for (path, _qn), fn in ordered:
        sites = [
            (line, "acq", name, held)
            for name, held, line in fn["acquires"]
        ] + [
            (line, "call", callname, held)
            for callname, held, line in fn["calls"]
            if held
        ]
        for line, kind, what, held in sorted(sites, key=lambda s: (s[0], s[1])):
            if kind == "acq":
                if what in held and lock_kind.get(what) == "lock":
                    mod = mod_by_rel.get(path)
                    if mod is not None:
                        findings.append(finding(
                            mod, "deadck", _FakeNode(line),
                            f"self-acquisition of non-reentrant lock "
                            f"'{what}' while already held — a guaranteed "
                            "self-deadlock if it is the same instance "
                            "(use named_rlock, or waive if these are "
                            "provably distinct instances)",
                            def_lines=fn["def_lines"],
                        ))
                for h in held:
                    add_edge(h, what, path, line)
            else:
                targets: Set[str] = set()
                for g in resolver.resolve_call(what, path, fn["qualclass"]):
                    targets |= may.get(g, set())
                for b in sorted(targets):
                    for h in held:
                        add_edge(h, b, path, line)

    for (a, b), (path, line) in sorted(edges.items()):
        if (a, b) in declared:
            continue
        ra, rb = ranks.get(a), ranks.get(b)
        if ra is None or rb is None:
            continue  # unknown-name finding already reported at creation
        if ra >= rb:
            mod = mod_by_rel.get(path)
            msg = (
                f"lock-order edge '{a}' (rank {ra}) -> '{b}' (rank {rb}) "
                "violates the declared hierarchy and is not in "
                "manifest.LOCK_EDGE_DECLARED"
            )
            if mod is not None:
                findings.append(finding(
                    mod, "deadck", _FakeNode(line), msg,
                ))
            else:  # pragma: no cover - edges only come from scanned mods
                findings.append(Finding("deadck", path, line, msg))

    # Cycles over the predicted graph (declared edges included).
    adj: Dict[str, Set[str]] = {}
    for a, b in list(edges) + list(declared):
        adj.setdefault(a, set()).add(b)
    for cycle in _find_cycles(adj):
        findings.append(Finding(
            "deadck", "analysis/manifest.py", 0,
            "cycle in the predicted lock-order graph: "
            + " -> ".join(cycle + [cycle[0]]),
        ))

    # Pass 3: guard inference from the declared thread roots.
    guards = {}
    for c in collectors:
        for g in collect_guards(c.mod):
            guards[(g.path, g.qualclass, g.attr)] = g.lock
    roots: Dict[Tuple[str, str], str] = {}
    for path, prefixes in thread_roots.items():
        for fkey in facts:
            if fkey[0] != path:
                continue
            qn = fkey[1]
            for prefix in prefixes:
                if qn == prefix or qn.startswith(prefix + "."):
                    roots[fkey] = f"{path}:{prefix}"
    reach: Dict[Tuple[str, str], Set[str]] = {f: set() for f in facts}
    for root_fkey, label in sorted(roots.items()):
        stack = [root_fkey]
        while stack:
            f = stack.pop()
            if label in reach[f]:
                continue
            reach[f].add(label)
            # Reachability resolves calls STRICTLY (unique names only):
            # the edge pass over-approximates on purpose, but inference
            # findings demand burn-down work, so a generic method name
            # ("record", "start") must not connect every root to every
            # class.  The runtime witness covers what this under-sees.
            stack.extend(g for g in strict_callees.get(f, ()) if g in reach)
    flagged: Set[Tuple[str, str, str]] = set()
    for (path, _qn), fn in ordered:
        if path.startswith("analysis/"):
            # The linter lane itself is a single-threaded CLI; its
            # lazy-cache attrs are not part of the serving thread plane.
            continue
        labels = reach.get((path, _qn), set())
        if len(labels) < 2:
            continue
        if fn["name"] in ("__init__", "__new__"):
            continue
        for attr, line, held, def_lines in fn["writes"]:
            key = (path, fn["qualclass"], attr)
            if key in flagged:
                continue
            if key in guards:
                continue
            if held:
                # Lexically under a NAMED lock (or in a *_locked method,
                # whose implicit holds ride the same tuple): the guard
                # exists — lockck's annotation then makes it durable, but
                # the write is not the unguarded-cross-thread hazard this
                # pass hunts.
                continue
            flagged.add(key)
            mod = mod_by_rel[path]
            owner = fn["qualclass"] or "<module>"
            findings.append(finding(
                mod, "deadck", _FakeNode(line),
                f"attribute '{attr}' of {owner} is written from "
                f"{len(labels)} thread roots "
                f"({', '.join(sorted(r.split(':', 1)[1] for r in labels))}) "
                "with no declared lockck guard — annotate the init site "
                "`# lockck: guard(<lock>)` or waive with reason",
                def_lines=def_lines,
            ))

    summary = {
        "locks": [
            {"name": d.name, "path": d.path, "line": d.line, "attr": d.attr}
            for d in sorted(locks)
        ],
        "edges": [
            {"from": a, "to": b, "path": p, "line": ln}
            for (a, b), (p, ln) in sorted(edges.items())
        ],
        "declared": [list(k) for k in sorted(declared)],
        "predicted": sorted(
            {(a, b) for (a, b) in edges} | set(declared)
        ),
    }
    summary["predicted"] = [list(e) for e in summary["predicted"]]
    return findings, summary


class _FakeNode:
    """Minimal lineno carrier for findings attached to derived sites."""

    def __init__(self, lineno: int):
        self.lineno = lineno


def _find_cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node (or a self-loop),
    returned as sorted node lists — deterministic output for the report."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1 or v in adj.get(v, ()):
                out.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strong(v)
    return sorted(out)
