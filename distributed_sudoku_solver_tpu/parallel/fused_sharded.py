"""Lane-sharded fused solve: the whole-round VMEM kernel under `shard_map`.

VERDICT r3 #2: the fused kernel's own driver already splits the work into
a Mosaic half (the in-VMEM rounds) and an XLA half (job harvest, purge,
steal — ``ops/pallas_step._fused_round``).  The XLA half is exactly the
shard-friendly part, so the multi-chip composition mirrors
``parallel/sharded.py`` one-to-one:

* **per chip, per round**: one ``pallas_call`` advances the chip's local
  lane tile block ``fused_steps`` rounds in VMEM, then the local XLA glue
  harvests/purges/steals — all on shard-local shapes;
* **SOLUTION_FOUND broadcast**: newly-solved flags OR-merge across chips
  with a ``psum``, winner chosen by ``pmin`` over chip index (lowest
  global lane, the composite rule — chips own contiguous lane blocks);
* **NEEDWORK/TASK**: the same receiver-initiated ring ``ppermute`` as the
  composite path, re-expressed on boards-last tensors
  (:func:`_ring_steal_t`);
* **step lockstep**: per-chip ``steps`` advance by the max in-kernel
  rounds across *local* tiles, which diverges across chips — the round
  ``pmax``es steps so the outer ``while_loop`` condition stays replicated
  (an SPMD loop whose trip counts diverge would deadlock its collectives).
  The budget approximation documented on ``solve_batch_fused`` (max
  across tiles) therefore extends to max across chips.

Reference bar: the reference's one kernel ran on every ring node
simultaneously (``/root/reference/DHT_Node.py:491-510``); this module is
that — the fused kernel on every chip with the ring around it.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    _lane_by_rank,
    init_frontier,
)
from distributed_sudoku_solver_tpu.ops.pallas_step import (
    FusedFrontier,
    _fused_live,
    _fused_round,
    frontier_to_fused,
    fused_lanes,
)
from distributed_sudoku_solver_tpu.ops.solve import SolveResult, _decode_solution
from distributed_sudoku_solver_tpu.parallel.mesh import (
    axis_size as _axis_size_compat,
    shard_map as _shard_map_compat,
)


def _ring_steal_t(
    top_t: jax.Array,
    has_top: jax.Array,
    stack_t: jax.Array,
    base: jax.Array,
    count: jax.Array,
    job: jax.Array,
    job_live: jax.Array,
    axis: str,
    k: int,
):
    """``parallel/sharded._ring_steal`` on boards-last tensors (lanes LAST).

    Same protocol: the successor advertises its idle-lane count backwards,
    the donor pops up to ``min(request, donors, k)`` bottom stack rows and
    ships them forward, the receiver installs them into idle lanes' tops.
    Work-conserving by construction (the donor removes exactly what it
    ships; the receiver's idle count cannot have shrunk — the local steal
    already ran this round and nothing else touches it).
    """
    n_dev = _axis_size_compat(axis)
    n_lanes = has_top.shape[0]
    s = stack_t.shape[0]
    k = min(k, n_lanes)
    slot_k = jnp.arange(k, dtype=jnp.int32)

    fwd = [(i, (i + 1) % n_dev) for i in range(n_dev)]  # donor -> successor
    back = [(i, (i - 1) % n_dev) for i in range(n_dev)]  # request travels back

    idle = ~has_top
    n_idle = jnp.sum(idle).astype(jnp.int32)
    request = jax.lax.ppermute(n_idle, axis, back)  # my successor's idle count

    donor = has_top & (count >= 1) & job_live
    donor_of = _lane_by_rank(donor, n_lanes)
    n_send = jnp.minimum(jnp.minimum(request, jnp.sum(donor)), k).astype(jnp.int32)
    take = slot_k < n_send
    donor_lane = jnp.where(take, donor_of[:k], n_lanes)
    safe_donor = jnp.clip(donor_lane, 0, n_lanes - 1)

    bottom = jnp.take_along_axis(
        stack_t, (base % s)[None, None, None, :], axis=0
    )[0]  # [n, n, L]: each lane's bottom stack row
    boards = jnp.where(
        take[None, None, :], bottom[:, :, safe_donor], jnp.uint32(0)
    )  # [n, n, k]
    jobs_out = jnp.where(take, job[safe_donor], jnp.int32(-1))

    donor_sel = jnp.zeros(n_lanes, bool).at[donor_lane].set(take, mode="drop")
    base = jnp.where(donor_sel, (base + 1) % s, base)
    count = jnp.where(donor_sel, count - 1, count)

    boards_in = jax.lax.ppermute(boards, axis, fwd)
    jobs_in = jax.lax.ppermute(jobs_out, axis, fwd)
    n_in = jax.lax.ppermute(n_send, axis, fwd)

    install = slot_k < n_in
    thief_of = _lane_by_rank(idle, n_lanes)
    thief_lane = jnp.where(install, thief_of[:k], n_lanes)
    top_t = top_t.at[:, :, thief_lane].set(boards_in, mode="drop")
    has_top = has_top.at[thief_lane].set(install, mode="drop")
    job = job.at[thief_lane].set(jobs_in, mode="drop")
    return top_t, has_top, base, count, job, n_in


def _fused_round_sharded(
    fs: FusedFrontier,
    geom: Geometry,
    config: SolverConfig,
    axis: str,
    rounds_fn=None,
) -> FusedFrontier:
    """One fused dispatch + local bookkeeping, then the cross-chip merges.

    ``rounds_fn`` swaps the whole-round kernel exactly as in
    ``pallas_step._fused_round`` — the exact-cover kernel shards with the
    same collectives (its states are [1, D] tensors; every merge below is
    shape-generic)."""
    n_jobs = fs.solved.shape[0]
    n_dev = _axis_size_compat(axis)
    prev_solved = fs.solved
    prev_solution_t = fs.solution_t

    # kernel + local harvest/purge/steal
    fs = _fused_round(fs, geom, config, rounds_fn)

    # --- merge job resolution across chips (the SOLUTION_FOUND broadcast) ---
    newly = fs.solved & ~prev_solved
    newly_any = jax.lax.psum(newly.astype(jnp.int32), axis) > 0
    dev = jax.lax.axis_index(axis).astype(jnp.int32)
    key = jnp.where(newly, dev, jnp.int32(n_dev))
    winner = jax.lax.pmin(key, axis)
    contrib = jnp.where(
        (newly & (key == winner))[None, None, :], fs.solution_t, jnp.uint32(0)
    )
    solution_t = jnp.where(
        newly_any[None, None, :], jax.lax.psum(contrib, axis), prev_solution_t
    )
    solved = prev_solved | newly_any
    overflowed = jax.lax.psum(fs.overflowed.astype(jnp.int32), axis) > 0

    # --- purge lanes of globally-resolved jobs, then the ICI ring steal -----
    job_safe = jnp.clip(fs.job, 0, n_jobs - 1)
    job_live = (fs.job >= 0) & ~solved[job_safe]
    has_top = fs.has_top & job_live
    count = jnp.where(job_live, fs.count, 0)
    top_t, base, job, steals = fs.top_t, fs.base, fs.job, fs.steals
    if n_dev > 1 and config.steal and config.ring_steal_k > 0:
        top_t, has_top, base, count, job, shipped = _ring_steal_t(
            top_t, has_top, fs.stack_t, base, count, job, job_live,
            axis, config.ring_steal_k,
        )
        steals = steals + shipped

    return fs._replace(
        top_t=top_t,
        has_top=has_top,
        base=base,
        count=count,
        job=job,
        solved=solved,
        solution_t=solution_t,
        overflowed=overflowed,
        # Enumeration accumulates disjoint per-chip subtree counts (psummed
        # at finalize); find-one mirrors the globally-merged solved flags.
        sol_count=fs.sol_count if config.count_all
        else solved.astype(jnp.int32),
        # Replicate the step counter: per-chip deltas are the max in-kernel
        # rounds across local tiles and diverge chip-to-chip; a diverged
        # while-loop trip count would deadlock the collectives above.
        steps=jax.lax.pmax(fs.steps, axis),
        steals=steals,
    )


def _run_fused_sharded(
    state: Frontier,
    geom: Geometry,
    config: SolverConfig,
    axis: str,
    rounds_fn=None,
) -> SolveResult:
    """Per-chip body: boards-last conversion, the solve loop, finalize psums."""
    fs = frontier_to_fused(state)

    def cond(f: FusedFrontier):
        local_live = jnp.any(_fused_live(f)).astype(jnp.int32)
        return (jax.lax.psum(local_live, axis) > 0) & (
            f.steps < config.max_steps
        )

    fs = jax.lax.while_loop(
        cond,
        lambda f: _fused_round_sharded(f, geom, config, axis, rounds_fn),
        fs,
    )

    n_jobs = fs.solved.shape[0]
    job_safe = jnp.clip(fs.job, 0, n_jobs - 1)
    has_work = jnp.zeros(n_jobs, bool).at[job_safe].max(
        _fused_live(fs), mode="drop"
    )
    has_work = jax.lax.psum(has_work.astype(jnp.int32), axis) > 0
    unsat = ~fs.solved & ~has_work & ~fs.overflowed
    if config.count_all:
        # Exact global model count: per-chip counts are disjoint-subtree
        # sums.  Per-chip first solutions DIVERGE under enumeration (no
        # resolution event ever merges them), so the solution field is
        # zeroed rather than emitted through a replicated out-spec —
        # counts, not solutions, are the product, matching the composite
        # lane-sharded contract (SolverConfig.count_all).
        sol_count = jax.lax.psum(fs.sol_count, axis)
        solution_t = jnp.zeros_like(fs.solution_t)
    else:
        sol_count = fs.sol_count  # replicated (== solved); never psummed
        solution_t = fs.solution_t  # replicated post-merge
    return SolveResult(
        solution=solution_t.transpose(2, 0, 1),
        solved=fs.solved,
        unsat=unsat,
        overflowed=fs.overflowed,
        nodes=jax.lax.psum(fs.nodes, axis),
        sol_count=sol_count,
        steps=fs.steps,
        sweeps=jax.lax.psum(fs.sweeps, axis),
        expansions=jax.lax.psum(fs.expansions, axis),
        steals=jax.lax.psum(fs.steals, axis),
    )


def _sharded_body(mesh: Mesh, axis: str, geom, cfg, rounds_fn=None):
    """The shard_map'd per-chip driver: lane-sharded state in, replicated
    result out — one definition for the Sudoku and cover entry points."""
    lane = lambda: P(axis)  # noqa: E731
    lane_specs = Frontier(
        top=lane(), has_top=lane(), stack=lane(), base=lane(), count=lane(),
        job=lane(),
        solved=P(), solution=P(), overflowed=P(), nodes=P(), sol_count=P(),
        steps=P(), sweeps=P(), expansions=P(), steals=P(),
        lane_rounds=lane(),
    )
    out_specs = SolveResult(
        solution=P(), solved=P(), unsat=P(), overflowed=P(), nodes=P(),
        sol_count=P(), steps=P(), sweeps=P(), expansions=P(), steals=P(),
    )
    return _shard_map_compat(
        functools.partial(
            _run_fused_sharded, geom=geom, config=cfg, axis=axis,
            rounds_fn=rounds_fn,
        ),
        mesh=mesh,
        in_specs=(lane_specs,),
        out_specs=out_specs,
        check_vma=False,
    )


@functools.partial(jax.jit, static_argnames=("geom", "config", "mesh"))
def _solve_fused_sharded_jit(
    grids: jax.Array, geom: Geometry, config: SolverConfig, mesh: Mesh
) -> SolveResult:
    n_jobs = grids.shape[0]
    (axis,) = mesh.axis_names
    n_dev = mesh.devices.size

    # Device-resident surface: shards live on their chips between
    # dispatches, so fused_steps=None resolves deep (FUSED_STEPS_DEVICE).
    from distributed_sudoku_solver_tpu.ops.frontier import FUSED_STEPS_DEVICE

    config = config.with_fused_steps(FUSED_STEPS_DEVICE)
    # Each chip's lane block must itself be a kernel-valid width (<= 128, or
    # a multiple of 128) — size per-chip first, then scale by the mesh.
    per_chip = -(-config.resolve_lanes(n_jobs) // n_dev)
    per_chip = fused_lanes(per_chip, geom.n, config.stack_slots)
    cfg = dataclasses.replace(config, lanes=per_chip * n_dev)

    state = init_frontier(encode_grid(grids, geom), cfg)
    body = _sharded_body(mesh, axis, geom, cfg)
    return _decode_solution(body(state))


def solve_batch_fused_sharded(
    grids,
    geom: Geometry,
    config: SolverConfig = SolverConfig(step_impl="fused"),
    mesh: Mesh | None = None,
) -> SolveResult:
    """Fused-step solve of int grids [J, n, n], lanes sharded over ``mesh``."""
    from distributed_sudoku_solver_tpu.parallel.mesh import default_mesh

    mesh = mesh if mesh is not None else default_mesh()
    return _solve_fused_sharded_jit(jnp.asarray(grids), geom, config, mesh)


@functools.partial(jax.jit, static_argnames=("problem", "config", "mesh"))
def _solve_cover_fused_sharded_jit(
    states0: jax.Array, problem, config: SolverConfig, mesh: Mesh
) -> SolveResult:
    from distributed_sudoku_solver_tpu.ops.pallas_cover import (
        _rounds_fn,
        cover_fused_lanes,
    )

    n_jobs = states0.shape[0]
    (axis,) = mesh.axis_names
    n_dev = mesh.devices.size

    # Cover keeps the shallow fused_steps default on every surface
    # (ops/pallas_cover.advance_cover_fused).
    from distributed_sudoku_solver_tpu.ops.frontier import FUSED_STEPS_LINKED

    config = config.with_fused_steps(FUSED_STEPS_LINKED)
    per_chip = -(-config.resolve_lanes(n_jobs) // n_dev)
    # Launch-time VMEM/stack admission rides the width helper: an
    # unservable (instance, stack) shape raises here, per chip, not as an
    # opaque Mosaic compile failure at first dispatch.
    per_chip = cover_fused_lanes(per_chip, problem, config.stack_slots)
    cfg = dataclasses.replace(config, lanes=per_chip * n_dev)

    state = init_frontier(states0, cfg)
    # One kernel-closure definition shared with the single-chip driver
    # (pallas_cover._rounds_fn): per-chip shards are per_chip lanes wide.
    body = _sharded_body(
        mesh, axis, None, cfg, rounds_fn=_rounds_fn(problem, cfg, per_chip)
    )
    return body(state)  # raw cover states: no Sudoku decode


def solve_csp_fused_sharded(
    states0,
    problem,
    config: SolverConfig = SolverConfig(step_impl="fused"),
    mesh: Mesh | None = None,
) -> SolveResult:
    """Fused exact-cover solve with lanes sharded over ``mesh``.

    The cover kernel (``ops/pallas_cover.py``) under the same shard_map
    composition as the Sudoku kernel: per-chip VMEM dispatches, psum
    solution broadcast, ring-``ppermute`` steal, pmax-replicated step
    counter.  Same contract as ``parallel.solve_csp_sharded`` (raw solved
    states; exact psummed counts under ``count_all``)."""
    from distributed_sudoku_solver_tpu.parallel.mesh import default_mesh

    mesh = mesh if mesh is not None else default_mesh()
    return _solve_cover_fused_sharded_jit(
        jnp.asarray(states0), problem, config, mesh
    )
