"""Mesh-resident serving programs: the resident flight's lane axis sharded
over a device mesh (ROADMAP #1, the pod-scale serving unlock).

The single-chip resident flight (``serving/scheduler.py``) keeps one
long-lived frontier per geometry and admits live traffic between chunk
dispatches.  This module is its multi-chip twin: the SAME slot/gang/attach/
detach/status contract, with the lane axis sharded over a 1-D mesh the way
the bulk tier shards a batch solve (``parallel/sharded.py``):

* **Slots are placed per-shard.**  Total slots ``J = per_shard * n_dev``;
  slot ``s`` lives on shard ``s // per_shard`` and its gang of lanes
  ``[s*gang, (s+1)*gang)`` falls entirely inside that shard (``gang`` divides
  the per-shard lane count by construction).  Attach therefore touches
  exactly one shard's lanes; per-job bookkeeping rows are replicated and
  reset identically everywhere.
* **Cross-shard steal = the bulk ring protocol, minus home lanes.**  Idle
  lanes advertise to the ring predecessor and receive bottom stack rows
  (``parallel/sharded._ring_steal``) — but a slot's HOME lane
  (``slot * gang``) may never receive foreign rows: the next
  ``attach_roots`` overwrites it unconditionally, so a stolen subtree
  parked there would be lost (a false-unsat hazard).  The install mask
  excludes lane 0 of every gang; with ``gang_lanes == 1`` there is no
  install capacity and cross-shard steal is effectively off.
* **Per-step psum solved merge.**  Same as the bulk tier: newly-solved
  flags OR-merge every round, the lowest-shard winner's solution row is
  broadcast, so the replicated ``solved`` / ``solution`` / ``overflowed``
  rows stay bit-identical across shards at every step.
* **Counters re-replicate at the chunk boundary.**  ``frontier_step``
  scatters each shard's local harvests into its replicated copy of the
  per-job ``nodes`` / ``sol_count`` rows, which therefore diverge WITHIN a
  chunk; the advance program re-replicates them before returning
  (``base + psum(delta)``), so verdict fetches between chunks read exact
  global counts from any shard.
* **One fetch per chunk, mesh edition.**  The packed status word
  (``ops/frontier.chunk_status`` layout) is computed in-graph with the
  lane reductions psummed across shards, then extended with mesh
  telemetry: ring-steal volume and per-shard live / foreign-live lane
  counts (``all_gather``).  ``unpack_mesh_status`` is the host-side
  inverse; the serving loop still does ONE ``host_fetch`` per chunk.

Composite step only: the fused Pallas kernel has its own sharded driver for
bulk solves (``parallel/fused_sharded.py``) but no resident attach/detach
twins — ``serving/mesh_scheduler.py`` downgrades a fused base config to the
composite step before building the flight.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    _pack_bits,
    frontier_live,
    status_len,
    unpack_status,
)
from distributed_sudoku_solver_tpu.ops.solve import sudoku_csp
from distributed_sudoku_solver_tpu.parallel.sharded import _sharded_step_counted
from distributed_sudoku_solver_tpu.parallel.mesh import (
    shard_map as _shard_map_compat,
)

# Mesh extension of the packed status word (ops/frontier.py layout docs):
# the base ``status_len(J)`` words are followed by
#   [base]                 ring-steal rows installed this chunk (all shards)
#   [base+1 : base+1+D]    live lanes per shard (device saturation view)
#   [base+1+D : base+1+2D] live lanes per shard working a FOREIGN job —
#                          one whose home shard is elsewhere; nonzero means
#                          cross-shard steal is actually balancing load
MESH_STATUS_RING = 0  # offsets relative to status_len(n_jobs)


def mesh_status_len(n_jobs: int, n_dev: int) -> int:
    return status_len(n_jobs) + 1 + 2 * n_dev


def unpack_mesh_status(status, n_jobs: int, n_dev: int) -> dict:
    """Host-side inverse of the mesh status word: the base
    :func:`~distributed_sudoku_solver_tpu.ops.frontier.unpack_status` dict
    plus ``{ring_shipped, shard_live int64[D], shard_foreign int64[D]}``."""
    import numpy as np

    status = np.asarray(status)
    base = status_len(n_jobs)
    out = unpack_status(status[:base], n_jobs)
    out["ring_shipped"] = int(status[base + MESH_STATUS_RING])
    out["shard_live"] = status[base + 1 : base + 1 + n_dev].astype(np.int64)
    out["shard_foreign"] = status[
        base + 1 + n_dev : base + 1 + 2 * n_dev
    ].astype(np.int64)
    return out


def _lane_specs(axis: str) -> Frontier:
    """Canonical resident shardings: lane-axis leaves sharded, per-job rows
    and scalars replicated (the bulk tier's ``lane_specs``, shared by every
    mesh-resident program so the state never bounces between layouts)."""
    return Frontier(
        top=P(axis),
        has_top=P(axis),
        stack=P(axis),
        base=P(axis),
        count=P(axis),
        job=P(axis),
        solved=P(),
        solution=P(),
        overflowed=P(),
        nodes=P(),
        sol_count=P(),
        steps=P(),
        sweeps=P(),
        expansions=P(),
        steals=P(),
        lane_rounds=P(axis),
    )


def _home_excluded(n_local: int, gang: int) -> jax.Array:
    """bool[n_local]: lanes allowed to receive ring-stolen rows.

    Gangs are shard-contained (``gang`` divides the local lane count), so
    the shard offset is a multiple of ``gang`` and home lanes are exactly
    the locally gang-aligned ones — no ``axis_index`` needed."""
    return (jnp.arange(n_local, dtype=jnp.int32) % gang) != 0


def _mesh_advance_body(
    state: Frontier,
    steps_delta: jax.Array,
    problem,
    config: SolverConfig,
    axis: str,
    n_dev: int,
):
    """Per-shard advance: the bounded-step chunk loop plus the chunk-boundary
    collectives (counter re-replication + the extended status word).

    Barrier diet (round 21): every collective on a forced-host CPU mesh is
    a thread barrier, so the loop cond rides the liveness term fused into
    the step's one psum (``_sharded_step_counted``) — one collective before
    the loop instead of one per iteration — and the whole boundary
    (counter re-replication + the psummed status reductions) collapses to
    ONE fused psum plus ONE all_gather.  The status word layout is
    byte-identical to the unfused form (``unpack_mesh_status``)."""
    n_jobs = state.solved.shape[0]
    per_shard = n_jobs // n_dev
    n_local = state.has_top.shape[0]
    prev_steps = state.steps
    prev_lane_rounds = state.lane_rounds
    base_counts = (
        state.nodes, state.sol_count, state.sweeps, state.expansions,
        state.steals,
    )
    limit = jnp.minimum(
        prev_steps + jnp.int32(steps_delta), jnp.int32(config.max_steps)
    )
    install_ok = _home_excluded(n_local, max(config.steal_gang, 1))

    go0 = (
        jax.lax.psum(jnp.any(frontier_live(state)).astype(jnp.int32), axis) > 0
    )

    def cond(carry):
        st, _, go = carry
        return go & (st.steps < limit)

    def body(carry):
        st, ring, _ = carry
        st, shipped, live_count = _sharded_step_counted(
            st, problem, config, axis, ring_install_ok=install_ok
        )
        return st, ring + shipped, live_count > 0

    st, ring, _ = jax.lax.while_loop(
        cond, body, (state, jnp.int32(0), go0)
    )

    # --- the whole chunk boundary as ONE fused psum -------------------------
    # Counter re-replication deltas (solved / solution / overflowed are
    # already psum-merged per step) + the status word's lane reductions,
    # concatenated int32.
    cur_counts = (
        st.nodes, st.sol_count, st.sweeps, st.expansions, st.steals,
    )
    live = frontier_live(st)
    job_safe = jnp.clip(st.job, 0, n_jobs - 1)
    has_work_local = jnp.zeros(n_jobs, bool).at[job_safe].max(live, mode="drop")
    delta = st.lane_rounds - prev_lane_rounds
    chunk_rounds = jnp.maximum(st.steps - prev_steps, 1)
    bucket = jnp.clip((delta * 10) // chunk_rounds, 0, 9)
    fused = jnp.concatenate(
        [jnp.atleast_1d(cur - b) for b, cur in zip(base_counts, cur_counts)]
        + [
            has_work_local.astype(jnp.int32),
            jnp.atleast_1d(jnp.sum(delta, dtype=jnp.int32)),
            jnp.zeros(10, jnp.int32).at[bucket].add(1),
            jnp.atleast_1d(ring),
        ]
    )
    fused = jax.lax.psum(fused, axis)
    widths = (n_jobs, n_jobs, 1, 1, 1, n_jobs, 1, 10, 1)
    parts, o = [], 0
    for w in widths:
        parts.append(fused[o : o + w])
        o += w
    nodes_d, sol_d, sweeps_d, exp_d, steals_d, hw, live_sum, hist, ring_sum = (
        parts
    )
    nodes = base_counts[0] + nodes_d
    sol_count = base_counts[1] + sol_d
    sweeps = base_counts[2] + sweeps_d[0]
    expansions = base_counts[3] + exp_d[0]
    steals = base_counts[4] + steals_d[0]
    has_work = hw > 0
    if not config.count_all:
        sol_count = jnp.minimum(sol_count, 1)
    st = st._replace(
        nodes=nodes, sol_count=sol_count, sweeps=sweeps,
        expansions=expansions, steals=steals,
    )

    # The packed status word (chunk_status's exact layout so the host-side
    # unpack is shared), per-shard gauges via one fused all_gather.
    my_shard = jax.lax.axis_index(axis).astype(jnp.int32)
    foreign = live & ((st.job // per_shard) != my_shard)
    gathered = jax.lax.all_gather(
        jnp.stack(
            [
                jnp.sum(live, dtype=jnp.int32),
                jnp.sum(foreign, dtype=jnp.int32),
            ]
        ),
        axis,
    )  # [D, 2]
    status = jnp.concatenate(
        [
            jnp.stack([st.steps, live_sum[0]]),
            hist,
            _pack_bits(st.solved),
            _pack_bits(has_work),
            ring_sum,
            gathered[:, 0],
            gathered[:, 1],
        ]
    )
    return st, status


@functools.partial(
    jax.jit, static_argnames=("geom", "config", "mesh"), donate_argnums=(0,)
)
def mesh_advance_status(
    state: Frontier,
    steps_delta: jax.Array,
    geom: Geometry,
    config: SolverConfig,
    mesh: Mesh,
):
    """One mesh-resident serving chunk: advance every shard in lockstep by
    at most ``steps_delta`` rounds and return ``(new_state, mesh status)``.

    The mesh twin of ``utils/checkpoint.advance_frontier_status`` — same
    donated-state, in-graph-limit, one-fetch contract; the status word is
    the extended mesh layout (:func:`unpack_mesh_status`).
    """
    (axis,) = mesh.axis_names
    specs = _lane_specs(axis)
    body = _shard_map_compat(
        functools.partial(
            _mesh_advance_body,
            problem=sudoku_csp(geom, config),
            config=config,
            axis=axis,
            n_dev=mesh.devices.size,
        ),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return body(state, jnp.int32(steps_delta))


@functools.partial(
    jax.jit, static_argnames=("geom", "config", "n_slots", "mesh")
)
def mesh_init_resident(
    geom: Geometry, config: SolverConfig, n_slots: int, mesh: Mesh
) -> Frontier:
    """The empty resident frontier, born sharded: each shard builds its own
    local lane slice (all idle), per-job rows identical zeros everywhere."""
    import dataclasses

    from distributed_sudoku_solver_tpu.ops.frontier import init_frontier_roots

    (axis,) = mesh.axis_names
    n_local = config.lanes // mesh.devices.size

    def body():
        local_cfg = dataclasses.replace(
            config, lanes=n_local, min_lanes=n_local
        )
        roots = jnp.zeros((n_local, geom.n, geom.n), jnp.uint32)
        return init_frontier_roots(
            roots, jnp.full(n_local, -1, jnp.int32), n_slots, local_cfg
        )

    return _shard_map_compat(
        body, mesh=mesh, in_specs=(), out_specs=_lane_specs(axis),
        check_vma=False,
    )()


@functools.partial(
    jax.jit, static_argnames=("geom", "gang", "mesh"), donate_argnums=(0,)
)
def mesh_attach(
    state: Frontier,
    grids: jax.Array,
    slot_ids: jax.Array,
    geom: Geometry,
    gang: int,
    mesh: Mesh,
) -> Frontier:
    """``ops/frontier.attach_roots`` on the sharded resident state.

    Lane scatters land on the one shard owning each slot's home lane
    (global lane ``slot * gang``, rebased by the shard offset; other shards
    drop them); the per-job bookkeeping resets are replicated — every shard
    applies the identical update to its identical copy."""
    (axis,) = mesh.axis_names
    roots = encode_grid(grids, geom)

    def body(st: Frontier, roots: jax.Array, slot_ids: jax.Array) -> Frontier:
        n_local = st.has_top.shape[0]
        n_jobs = st.solved.shape[0]
        off = jax.lax.axis_index(axis).astype(jnp.int32) * n_local
        ok = slot_ids >= 0
        lane_g = slot_ids * gang
        mine = ok & (lane_g >= off) & (lane_g < off + n_local)
        lane = jnp.where(mine, lane_g - off, n_local)  # OOB -> dropped
        slot_t = jnp.where(ok, slot_ids, n_jobs)
        zero_k = jnp.zeros(slot_ids.shape[0], jnp.int32)
        return st._replace(
            top=st.top.at[lane].set(roots.astype(jnp.uint32), mode="drop"),
            has_top=st.has_top.at[lane].set(mine, mode="drop"),
            job=st.job.at[lane].set(slot_ids, mode="drop"),
            base=st.base.at[lane].set(zero_k, mode="drop"),
            count=st.count.at[lane].set(zero_k, mode="drop"),
            solved=st.solved.at[slot_t].set(False, mode="drop"),
            solution=st.solution.at[slot_t].set(jnp.uint32(0), mode="drop"),
            overflowed=st.overflowed.at[slot_t].set(False, mode="drop"),
            nodes=st.nodes.at[slot_t].set(zero_k, mode="drop"),
            sol_count=st.sol_count.at[slot_t].set(zero_k, mode="drop"),
        )

    specs = _lane_specs(axis)
    return _shard_map_compat(
        body, mesh=mesh, in_specs=(specs, P(), P()), out_specs=specs,
        check_vma=False,
    )(state, roots, slot_ids)


@functools.partial(
    jax.jit, static_argnames=("mesh",), donate_argnums=(0,)
)
def mesh_detach(state: Frontier, slot_mask: jax.Array, mesh: Mesh) -> Frontier:
    """``ops/frontier.detach`` per shard: lane clearing keys on the local
    ``job`` tags (which travel with ring-stolen rows, so a leaving job's
    foreign rows clear too); the bookkeeping resets are replicated."""
    from distributed_sudoku_solver_tpu.ops.frontier import detach

    (axis,) = mesh.axis_names
    specs = _lane_specs(axis)
    return _shard_map_compat(
        detach, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False,
    )(state, slot_mask)
