"""Device mesh construction — the TPU-native "ring membership".

The reference's overlay is a coordinator-maintained ring of UDP processes
(``/root/reference/DHT_Node.py:260-330``).  On TPU the set of workers is the
device mesh: membership is static per job, the "ring" is the ICI torus, and
joining/leaving happens at the job boundary (elasticity is handled by the
host-level cluster runtime, not by the data plane).  One mesh axis shards the
frontier's *lane* dimension; collectives ride ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

# Name of the mesh axis the frontier lane dimension is sharded over.
LANE_AXIS = "lanes"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis_name: str = LANE_AXIS
) -> Mesh:
    """A 1-D mesh over ``devices`` (default: every visible device)."""
    devices = list(devices) if devices is not None else jax.devices()
    import numpy as np

    return Mesh(np.asarray(devices), (axis_name,))


def default_mesh() -> Mesh:
    return make_mesh()
