"""Device mesh construction — the TPU-native "ring membership".

The reference's overlay is a coordinator-maintained ring of UDP processes
(``/root/reference/DHT_Node.py:260-330``).  On TPU the set of workers is the
device mesh: membership is static per job, the "ring" is the ICI torus, and
joining/leaving happens at the job boundary (elasticity is handled by the
host-level cluster runtime, not by the data plane).  One mesh axis shards the
frontier's *lane* dimension; collectives ride ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

# Name of the mesh axis the frontier lane dimension is sharded over.
LANE_AXIS = "lanes"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis_name: str = LANE_AXIS
) -> Mesh:
    """A 1-D mesh over ``devices`` (default: every visible device)."""
    devices = list(devices) if devices is not None else jax.devices()
    import numpy as np

    return Mesh(np.asarray(devices), (axis_name,))


def default_mesh() -> Mesh:
    return make_mesh()


def axis_size(axis_name: str) -> int:
    """Static size of a mesh axis from inside a ``shard_map`` body.

    ``jax.lax.axis_size`` only exists on newer jax; older runtimes expose
    the same static int as ``jax.core.axis_frame(name)``.  Same shim
    rationale as :func:`shard_map` below."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    # 0.4.37-era jax returns the int directly; slightly older versions
    # return a frame object carrying it as .size.
    return getattr(frame, "size", frame)


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across the jax versions this repo meets.

    The top-level ``jax.shard_map`` (and its ``check_vma`` keyword) only
    exist on newer jax; older jaxlibs ship it as
    ``jax.experimental.shard_map.shard_map`` with the keyword spelled
    ``check_rep``.  Every ``shard_map`` call site in ``parallel/`` routes
    through this one shim so the whole multi-chip tier degrades gracefully
    instead of dying with ``AttributeError`` on the older runtime."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
