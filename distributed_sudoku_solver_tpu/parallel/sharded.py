"""Multi-chip frontier solve: `shard_map` over a device mesh.

This is the reference's whole distributed layer (SURVEY.md §1 L2+L3)
re-expressed as compiled collectives:

* **chip = ring node.**  The frontier's lane axis is sharded over the mesh;
  each chip owns ``L/D`` lanes and steps them in lockstep inside one
  ``lax.while_loop`` — there is no coordinator process, no UDP, no pickle.
* **SOLUTION_FOUND broadcast = per-step psum.**  The reference unicasts the
  solution to every member and waits 2 s (``/root/reference/
  DHT_Node.py:459-467``); here newly-solved flags are OR-merged across chips
  with a ``psum`` every step, so cross-chip cancellation latency is one step,
  not seconds.  The solution row is taken from the lowest-indexed chip that
  solved (deterministic winner, like the reference's lowest-lane harvest).
* **NEEDWORK/TASK = receiver-initiated ring ppermute.**  Each step, every
  chip tells its ring *predecessor* how many idle lanes it has (a scalar
  ``ppermute`` — literally the reference's NEEDWORK-to-predecessor,
  ``/root/reference/DHT_Node.py:246-248``); the predecessor pops up to that
  many *bottom* stack rows (largest subtrees) from its richest lanes and
  ships them forward (a payload ``ppermute``).  The donor removes exactly
  what it ships and the receiver has capacity for all of it by construction,
  so no work is ever dropped — unlike the reference, where a lost UDP TASK
  silently loses the subtree (SURVEY.md §2.5 #7).
* **STATS_REQ/RES = psum at finalize.**  Per-chip counters are summed with a
  collective instead of a 1 s gather sleep (``/root/reference/
  DHT_Node.py:566-598``).

Everything compiles to one XLA program per (J, geometry, config, mesh);
collectives ride ICI on real hardware and the same code runs unchanged on a
``--xla_force_host_platform_device_count`` CPU mesh in tests.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
from distributed_sudoku_solver_tpu.ops.csp import CSProblem
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    _lane_by_rank,
    frontier_live,
    frontier_step,
    init_frontier,
)
from distributed_sudoku_solver_tpu.ops.solve import (
    SolveResult,
    _decode_solution,
    finalize_frontier,
    sudoku_csp,
)
from distributed_sudoku_solver_tpu.parallel.mesh import (
    axis_size as _axis_size_compat,
    shard_map as _shard_map_compat,
    default_mesh,
)


def _ring_steal(
    top: jax.Array,
    has_top: jax.Array,
    stack: jax.Array,
    base: jax.Array,
    count: jax.Array,
    job: jax.Array,
    job_live: jax.Array,
    axis: str,
    k: int,
    install_ok: jax.Array | None = None,
):
    """Ship up to ``k`` bottom stack rows from this chip to its ring successor.

    Receiver-initiated and work-conserving: the successor first advertises its
    idle-lane count, the donor ships ``min(request, donors, k)`` rows and
    deletes exactly those (a circular-buffer bottom bump — no stack data
    moves donor-side), and the receiver installs every row it gets straight
    into idle lanes' working tops (its idle count cannot have shrunk in
    between — the local steal already ran this step, nothing else touches it).

    ``install_ok`` (bool[local lanes], optional) restricts which idle lanes
    may RECEIVE foreign rows.  The mesh-resident flight
    (``parallel/mesh_resident.py``) passes the non-home-lane mask: a slot's
    home lane ``slot * gang`` is overwritten unconditionally by the next
    ``attach_roots``, so a stolen row parked there would be silently lost —
    a false-unsat hazard.  ``None`` (every bulk surface) keeps the original
    any-idle-lane behavior and the exact same jaxpr.
    """
    n_dev = _axis_size_compat(axis)
    n_lanes, s = stack.shape[:2]
    k = min(k, n_lanes)
    slot_k = jnp.arange(k, dtype=jnp.int32)

    fwd = [(i, (i + 1) % n_dev) for i in range(n_dev)]  # donor -> successor
    back = [(i, (i - 1) % n_dev) for i in range(n_dev)]  # request travels back

    idle = ~has_top if install_ok is None else (~has_top & install_ok)
    n_idle = jnp.sum(idle).astype(jnp.int32)
    request = jax.lax.ppermute(n_idle, axis, back)  # my successor's idle count

    donor = has_top & (count >= 1) & job_live
    donor_of = _lane_by_rank(donor, n_lanes)
    n_send = jnp.minimum(jnp.minimum(request, jnp.sum(donor)), k).astype(jnp.int32)
    take = slot_k < n_send
    donor_lane = jnp.where(take, donor_of[:k], n_lanes)
    safe_donor = jnp.clip(donor_lane, 0, n_lanes - 1)
    boards = jnp.where(
        take[:, None, None], stack[safe_donor, base[safe_donor] % s], 0
    )
    jobs = jnp.where(take, job[safe_donor], -1)

    donor_sel = jnp.zeros(n_lanes, bool).at[donor_lane].set(take, mode="drop")
    base = jnp.where(donor_sel, (base + 1) % s, base)
    count = jnp.where(donor_sel, count - 1, count)

    # One fused forward payload (boards || job tag, plus a count row)
    # instead of three ppermutes: on a forced-host CPU mesh each collective
    # is a thread barrier, and the ring runs every step of the serving
    # chunk loop.  int32 job tags (-1 padding included) round-trip through
    # the uint32 bit-pattern exactly.
    n_cells = boards.shape[1] * boards.shape[2]
    payload = jnp.zeros((k + 1, n_cells + 1), jnp.uint32)
    payload = payload.at[:k, :n_cells].set(boards.reshape(k, n_cells))
    payload = payload.at[:k, n_cells].set(jobs.astype(jnp.uint32))
    payload = payload.at[k, 0].set(n_send.astype(jnp.uint32))
    payload = jax.lax.ppermute(payload, axis, fwd)
    boards_in = payload[:k, :n_cells].reshape(boards.shape)
    jobs_in = payload[:k, n_cells].astype(jnp.int32)
    n_in = payload[k, 0].astype(jnp.int32)

    install = slot_k < n_in
    thief_of = _lane_by_rank(idle, n_lanes)
    thief_lane = jnp.where(install, thief_of[:k], n_lanes)
    top = top.at[thief_lane].set(boards_in, mode="drop")
    has_top = has_top.at[thief_lane].set(install, mode="drop")
    job = job.at[thief_lane].set(jobs_in, mode="drop")
    return top, has_top, base, count, job, n_in


def _sharded_step(
    state: Frontier,
    problem: CSProblem,
    config: SolverConfig,
    axis: str,
    ring_install_ok: jax.Array | None = None,
) -> Frontier:
    """One lockstep round on every chip: local step, then cross-chip merges."""
    return _sharded_step_counted(state, problem, config, axis, ring_install_ok)[0]


def _sharded_step_counted(
    state: Frontier,
    problem: CSProblem,
    config: SolverConfig,
    axis: str,
    ring_install_ok: jax.Array | None = None,
):
    """:func:`_sharded_step` plus this chip's ring-installed row count.

    The mesh-resident advance loop (``parallel/mesh_resident.py``) carries
    the per-chunk ring-steal volume in its status word; ``Frontier.steals``
    cannot supply it because local (within-chip) steals accumulate into the
    same counter.  Returns ``(new_state, rows installed here this round,
    chips-with-live-work count)``.

    The whole cross-chip resolution merge rides ONE fused psum (round 21's
    barrier diet — on a forced-host CPU mesh every collective is a thread
    barrier, and the old psum + pmin + psum + psum chain dominated the
    serving chunk cadence).  Each chip contributes its newly-solved flags as
    a one-hot row over devices plus its candidate solution boards; after the
    single sum every chip picks the lowest-chip winner with a local argmax —
    bit-identical to the pmin chain, at one barrier.  The local-liveness
    term folded into the same vector lets the mesh advance loop's cond run
    collective-free; it is summed BEFORE the remote solved flags land, so a
    chip whose last job was solved elsewhere this round reads live for one
    extra (no-op) step — the loop never terminates early, only one cheap
    step late.
    """
    n_jobs = state.solved.shape[0]
    n_dev = _axis_size_compat(axis)
    prev_solved = state.solved
    prev_solution = state.solution

    st = frontier_step(state, problem, config)

    # --- merge job resolution across chips (the SOLUTION_FOUND broadcast,
    # one fused collective) --------------------------------------------------
    newly = st.solved & ~prev_solved
    dev = jax.lax.axis_index(axis).astype(jnp.int32)
    onehot = jnp.arange(n_dev, dtype=jnp.int32) == dev  # [D]
    newly_oh = newly[:, None] & onehot[None, :]  # [J, D]
    sol_by_dev = jnp.where(
        newly_oh[:, :, None, None], st.solution[:, None], jnp.uint32(0)
    )  # [J, D, n, n]
    live_local = jnp.any(frontier_live(st))
    fused = jnp.concatenate(
        [
            newly_oh.astype(jnp.uint32).reshape(-1),
            st.overflowed.astype(jnp.uint32),
            jnp.atleast_1d(live_local.astype(jnp.uint32)),
            sol_by_dev.reshape(-1),
        ]
    )
    fused = jax.lax.psum(fused, axis)
    newly_mat = fused[: n_jobs * n_dev].reshape(n_jobs, n_dev) > 0
    overflowed = fused[n_jobs * n_dev : n_jobs * n_dev + n_jobs] > 0
    live_count = fused[n_jobs * n_dev + n_jobs].astype(jnp.int32)
    sols = fused[n_jobs * n_dev + n_jobs + 1 :].reshape(
        (n_jobs, n_dev) + st.solution.shape[1:]
    )
    newly_any = jnp.any(newly_mat, axis=1)
    winner = jnp.argmax(newly_mat, axis=1)  # first True = lowest chip
    contrib = sols[jnp.arange(n_jobs), winner].astype(jnp.uint32)
    solution = jnp.where(newly_any[:, None, None], contrib, prev_solution)
    solved = prev_solved | newly_any

    # --- cross-chip work rebalance (NEEDWORK over the ICI ring) -------------
    top, has_top, base, count, job = st.top, st.has_top, st.base, st.count, st.job
    steals = st.steals
    shipped = jnp.int32(0)
    if n_dev > 1 and config.steal and config.ring_steal_k > 0:
        job_safe = jnp.clip(job, 0, n_jobs - 1)
        job_live = (job >= 0) & ~solved[job_safe]
        has_top = has_top & job_live
        count = jnp.where(job_live, count, 0)
        top, has_top, base, count, job, shipped = _ring_steal(
            top, has_top, st.stack, base, count, job, job_live,
            axis, config.ring_steal_k, ring_install_ok,
        )
        steals = steals + shipped

    return Frontier(
        top=top,
        has_top=has_top,
        stack=st.stack,
        base=base,
        count=count,
        job=job,
        solved=solved,
        solution=solution,
        overflowed=overflowed,
        nodes=st.nodes,
        sol_count=st.sol_count,
        steps=st.steps,
        sweeps=st.sweeps,
        expansions=st.expansions,
        steals=steals,
        lane_rounds=st.lane_rounds,
    ), shipped, live_count


def _run_sharded(
    state: Frontier, problem: CSProblem, config: SolverConfig, axis: str
) -> SolveResult:
    """Per-chip body: the whole solve loop plus the finalize collectives."""

    def cond(st: Frontier):
        local_live = jnp.any(frontier_live(st)).astype(jnp.int32)
        return (jax.lax.psum(local_live, axis) > 0) & (st.steps < config.max_steps)

    state = jax.lax.while_loop(
        cond, lambda st: _sharded_step(st, problem, config, axis), state
    )

    # Per-chip counters -> global (the STATS aggregation, as one psum).
    res = finalize_frontier(state)
    live_local = frontier_live(state)
    n_jobs = state.solved.shape[0]
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    has_work = jnp.zeros(n_jobs, bool).at[job_safe].max(live_local, mode="drop")
    has_work = jax.lax.psum(has_work.astype(jnp.int32), axis) > 0
    unsat = ~state.solved & ~has_work & ~state.overflowed
    # Find-one mode: two chips can each resolve the same job in the same
    # round (the solved-psum merge lands after both local harvests), so the
    # psummed per-chip sol_counts can read 2 — clamp to the documented
    # "0 or 1 normally" contract (ops/solve.py).  Enumeration counts are
    # disjoint-subtree sums and add exactly.
    sol_count = jax.lax.psum(res.sol_count, axis)
    if not config.count_all:
        sol_count = jnp.minimum(sol_count, 1)
    return SolveResult(
        solution=res.solution,
        solved=res.solved,
        unsat=unsat,
        overflowed=res.overflowed,
        nodes=jax.lax.psum(res.nodes, axis),
        sol_count=sol_count,
        steps=res.steps,
        sweeps=jax.lax.psum(res.sweeps, axis),
        expansions=jax.lax.psum(res.expansions, axis),
        steals=jax.lax.psum(res.steals, axis),
    )


@functools.partial(jax.jit, static_argnames=("problem", "config", "mesh"))
def _solve_csp_sharded_jit(
    states0: jax.Array, problem: CSProblem, config: SolverConfig, mesh: Mesh
) -> SolveResult:
    n_jobs = states0.shape[0]
    (axis,) = mesh.axis_names
    n_dev = mesh.devices.size

    # Round the lane count up to a multiple of the mesh size so the lane axis
    # shards evenly; per-job state is replicated, lane state is sharded.
    lanes = config.resolve_lanes(n_jobs)
    lanes = -(-lanes // n_dev) * n_dev
    cfg = dataclasses.replace(config, lanes=lanes)

    state = init_frontier(states0, cfg)

    lane_specs = Frontier(
        top=P(axis),
        has_top=P(axis),
        stack=P(axis),
        base=P(axis),
        count=P(axis),
        job=P(axis),
        solved=P(),
        solution=P(),
        overflowed=P(),
        nodes=P(),
        sol_count=P(),
        steps=P(),
        sweeps=P(),
        expansions=P(),
        steals=P(),
        lane_rounds=P(axis),
    )
    out_specs = SolveResult(
        solution=P(),
        solved=P(),
        unsat=P(),
        overflowed=P(),
        nodes=P(),
        sol_count=P(),
        steps=P(),
        sweeps=P(),
        expansions=P(),
        steals=P(),
    )
    body = _shard_map_compat(
        functools.partial(_run_sharded, problem=problem, config=cfg, axis=axis),
        mesh=mesh,
        in_specs=(lane_specs,),
        out_specs=out_specs,
        check_vma=False,
    )
    return body(state)


def solve_csp_sharded(
    states0,
    problem: CSProblem,
    config: SolverConfig = SolverConfig(),
    mesh: Mesh | None = None,
) -> SolveResult:
    """Solve root states [J, h, w] of any CSP, lanes sharded over ``mesh``.

    The solution field stays in raw problem-state form (like
    :func:`~distributed_sudoku_solver_tpu.ops.solve.solve_csp`).
    """
    if config.step_impl == "fused":
        # The fused kernel hardcodes the Sudoku kernels (solve_csp precedent).
        raise ValueError(
            "step_impl='fused' supports the Sudoku entry points only; "
            f"got a generic {type(problem).__name__}"
        )
    mesh = mesh if mesh is not None else default_mesh()
    return _solve_csp_sharded_jit(jnp.asarray(states0), problem, config, mesh)


@functools.partial(jax.jit, static_argnames=("geom", "config", "mesh"))
def _solve_sharded_jit(
    grids: jax.Array, geom: Geometry, config: SolverConfig, mesh: Mesh
) -> SolveResult:
    if config.step_impl == "fused":
        # One dispatch site (the solve_batch precedent): every sharded
        # Sudoku entry point — including the wire path the bulk pipeline
        # rides — honors the fused strategy.
        from distributed_sudoku_solver_tpu.parallel.fused_sharded import (
            _solve_fused_sharded_jit,
        )

        return _solve_fused_sharded_jit(grids, geom, config, mesh)
    res = _solve_csp_sharded_jit(
        encode_grid(grids, geom), sudoku_csp(geom, config), config, mesh
    )
    return _decode_solution(res)


def solve_batch_sharded(
    grids,
    geom: Geometry,
    config: SolverConfig = SolverConfig(),
    mesh: Mesh | None = None,
) -> SolveResult:
    """Solve int grids [J, n, n] with lanes sharded over every chip in ``mesh``."""
    mesh = mesh if mesh is not None else default_mesh()
    return _solve_sharded_jit(jnp.asarray(grids), geom, config, mesh)


@functools.partial(jax.jit, static_argnames=("geom", "config", "mesh"))
def solve_batch_sharded_wire(
    packed: jax.Array, geom: Geometry, config: SolverConfig, mesh: Mesh
) -> jax.Array:
    """Wire-format sharded solve (see ``ops/solve.solve_batch_wire``)."""
    from distributed_sudoku_solver_tpu.ops import wire

    grids = wire.unpack_grids_device(packed, geom)
    res = _solve_sharded_jit(grids, geom, config, mesh)
    return wire.pack_result_device(
        res.solution, res.solved, res.unsat, res.nodes > 0, geom
    )
