"""Multi-chip parallelism: device meshes, sharded frontier solve, collectives."""

from distributed_sudoku_solver_tpu.parallel.mesh import (  # noqa: F401
    LANE_AXIS,
    default_mesh,
    make_mesh,
)
from distributed_sudoku_solver_tpu.parallel.sharded import (  # noqa: F401
    solve_batch_sharded,
    solve_csp_sharded,
)
