"""Multi-chip parallelism: device meshes, sharded frontier solve, collectives."""

from distributed_sudoku_solver_tpu.parallel.mesh import (  # noqa: F401
    LANE_AXIS,
    default_mesh,
    make_mesh,
)
from distributed_sudoku_solver_tpu.parallel.board_sharded import (  # noqa: F401
    BAND_AXIS,
    BandedSudoku,
    make_band_mesh,
    solve_batch_banded,
    validate_banded_config,
)
from distributed_sudoku_solver_tpu.parallel.sharded import (  # noqa: F401
    solve_batch_sharded,
    solve_csp_sharded,
)
from distributed_sudoku_solver_tpu.parallel.fused_sharded import (  # noqa: F401
    solve_batch_fused_sharded,
    solve_csp_fused_sharded,
)
