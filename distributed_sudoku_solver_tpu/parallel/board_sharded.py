"""Board-sharded solving: one giant board's candidate tensor split over chips.

This is the framework's sequence/context-parallelism axis (SURVEY.md §5.7).
The lane-sharded path (``parallel/sharded.py``) scales the *number* of search
states over chips — the heir of the reference's guess-range splitting
(``/root/reference/DHT_Node.py:499-510``).  This module scales the *problem
dimension itself*: for giant geometries (25x25 and up) every chip owns a
horizontal band of the board, and each propagation sweep exchanges per-column
candidate aggregates with the other chips over ICI — structurally ring
attention's neighbor-exchange loop, with column constraint masks in place of
KV blocks.

Sharding layout (chosen so exactly ONE of the three unit families crosses
chips):

* The board's rows are grouped into **vertical box bands** of ``box_h`` rows;
  bands are padded up to a multiple of the mesh size and dealt contiguously,
  ``bands_per_chip`` to a chip.  Row units and box units then live entirely
  inside one chip's shard.
* Only **column units** span chips.  Their bitwise OR / once-twice aggregates
  are reduced with an explicit ``ppermute`` ring all-reduce
  (:func:`ring_or`, :func:`ring_once_twice`): D-1 hops of an [L, n] uint32
  tile around the ICI ring — a few KB per hop.

The generic lane-stack engine (``ops/frontier.py``) runs *unchanged* inside
``shard_map``: lane/stack bookkeeping is replicated, board tensors are
sharded on their row axis, and all cross-chip talk happens inside the
problem kernels below.  Because every collective is an all-reduce, each chip
ends every step with identical replicated state, so the engine's control
flow stays in lockstep — and results (solutions, node counts, branch order)
are bit-identical to the single-device solver, which the tests assert.

Pad rows hold the empty mask 0 (no candidates): they contribute the identity
to every OR/once-twice aggregate, are never branch candidates (popcount 0),
and are masked out of the solved/contradiction checks.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import (
    decode_grid,
    encode_grid,
    is_single,
    lowest_bit,
    once_twice_reduce,
    or_reduce,
    popcount,
)
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    init_frontier,
    run_frontier,
)
from distributed_sudoku_solver_tpu.ops.solve import SolveResult, finalize_frontier
from distributed_sudoku_solver_tpu.parallel.mesh import shard_map as _shard_map_compat, make_mesh

# Mesh axis the board's row-band dimension is sharded over.
BAND_AXIS = "bands"


def make_band_mesh(devices=None) -> Mesh:
    """A 1-D mesh whose axis shards board row-bands (the SP/ring axis)."""
    return make_mesh(devices, axis_name=BAND_AXIS)


# --------------------------------------------------------------------------
# Ring all-reduces: the neighbor-exchange loop (ring attention's comm shape).
# --------------------------------------------------------------------------


def _ring_perm(n_dev: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % n_dev) for i in range(n_dev)]


def ring_or(x: jax.Array, axis: str, n_dev: int) -> jax.Array:
    """Bitwise-OR all-reduce over the mesh axis via D-1 ring hops.

    Each hop forwards the accumulated tile to the ring successor over ICI;
    after D-1 hops every chip holds the global OR.  (XLA's ``all_reduce``
    would lower to the same ring on a 1-D ICI torus; spelling it out keeps
    the data path explicit and lets the combiner generalize below.)
    """
    acc, buf = x, x
    perm = _ring_perm(n_dev)
    for _ in range(n_dev - 1):
        buf = jax.lax.ppermute(buf, axis, perm)
        acc = acc | buf
    return acc


def ring_once_twice(
    once: jax.Array, twice: jax.Array, axis: str, n_dev: int
) -> tuple[jax.Array, jax.Array]:
    """All-reduce per-chip (once, twice) column aggregates around the ring.

    Combiner ((o1,t1),(o2,t2)) -> (o1|o2, t1|t2|(o1&o2)) — associative and
    commutative, so rotate-and-accumulate yields the exact global aggregate
    on every chip (``ops/bitmask.py`` ``once_twice_reduce``'s combiner).
    """
    acc_o, acc_t = once, twice
    buf_o, buf_t = once, twice
    perm = _ring_perm(n_dev)
    for _ in range(n_dev - 1):
        buf_o = jax.lax.ppermute(buf_o, axis, perm)
        buf_t = jax.lax.ppermute(buf_t, axis, perm)
        acc_o, acc_t = acc_o | buf_o, acc_t | buf_t | (acc_o & buf_o)
    return acc_o, acc_t


def _psum_any(x: jax.Array, axis: str) -> jax.Array:
    """Logical-OR all-reduce of a bool array over the mesh axis."""
    return jax.lax.psum(x.astype(jnp.int32), axis) > 0


# --------------------------------------------------------------------------
# The banded problem: Sudoku whose states are row-band shards.
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BandedSudoku:
    """Sudoku CSP over row-band shards of the board (jit-static, hashable).

    Implements the :class:`~distributed_sudoku_solver_tpu.ops.csp.CSProblem`
    protocol, but its kernels run *inside* ``shard_map``: states are the
    local shard ``uint32[L, rows_local, n]`` and the column-unit reductions
    are ring collectives over ``axis``.  Branch order matches
    :class:`~distributed_sudoku_solver_tpu.models.sudoku.SudokuCSP` exactly
    (same key, globally row-major cell index), so searches are bit-identical
    to the unsharded engine.
    """

    geom: Geometry
    axis: str
    n_dev: int
    bands_per_chip: int
    branch_rule: str = "minrem"
    max_sweeps: int = 64
    rules: str = "basic"  # 'basic' | 'extended' (+ banded box-line
    #   reductions) | 'subsets' (+ banded naked-subset eliminations)

    @property
    def rows_local(self) -> int:
        return self.bands_per_chip * self.geom.box_h

    @property
    def rows_padded(self) -> int:
        return self.rows_local * self.n_dev

    @property
    def state_shape(self) -> tuple[int, int]:
        """Global (padded) state shape; each chip holds a 1/n_dev row slice."""
        return (self.rows_padded, self.geom.n)

    # -- local geometry helpers ------------------------------------------

    def _to_boxes(self, x: jax.Array) -> jax.Array:
        """[..., rows_local, n] -> [..., local boxes, box cells] (chip-local)."""
        g = self.geom
        lead = x.shape[:-2]
        x = x.reshape(*lead, self.bands_per_chip, g.box_h, g.n_hboxes, g.box_w)
        x = jnp.swapaxes(x, -3, -2)
        return x.reshape(*lead, self.bands_per_chip * g.n_hboxes, g.n)

    def _from_boxes(self, x: jax.Array) -> jax.Array:
        g = self.geom
        lead = x.shape[:-2]
        x = x.reshape(*lead, self.bands_per_chip, g.n_hboxes, g.box_h, g.box_w)
        x = jnp.swapaxes(x, -3, -2)
        return x.reshape(*lead, self.rows_local, g.n)

    def _row_valid(self) -> jax.Array:
        """bool[rows_local]: which local rows are real board rows (not pad)."""
        chip = jax.lax.axis_index(self.axis).astype(jnp.int32)
        grow = chip * self.rows_local + jnp.arange(self.rows_local, dtype=jnp.int32)
        return grow < self.geom.n

    def _box_valid(self) -> jax.Array:
        """bool[local boxes]: boxes in real (non-pad) bands."""
        chip = jax.lax.axis_index(self.axis).astype(jnp.int32)
        band = chip * self.bands_per_chip + (
            jnp.arange(self.bands_per_chip * self.geom.n_hboxes, dtype=jnp.int32)
            // self.geom.n_hboxes
        )
        return band < self.geom.n_vboxes

    # -- propagation ------------------------------------------------------

    def _sweep(self, cand: jax.Array) -> jax.Array:
        """One sweep of ``ops/propagate.py``'s rules, columns ring-reduced."""
        single = is_single(cand)
        decided = jnp.where(single, cand, jnp.uint32(0))

        # Elimination: decided digits disappear from their row/box (local)
        # and column (one ring OR over the mesh axis).
        row_or = or_reduce(decided, -1)[..., None]
        box_or = or_reduce(self._to_boxes(decided), -1)[..., None]
        box_seen = self._from_boxes(
            jnp.broadcast_to(box_or, (*box_or.shape[:-1], self.geom.n))
        )
        col_part = or_reduce(decided, -2)  # [L, n] this chip's rows
        col_or = ring_or(col_part, self.axis, self.n_dev)
        seen = row_or | box_seen | col_or[..., None, :]
        cand = jnp.where(single, cand, cand & ~seen)

        # Hidden singles: digits with a unique home in a unit are forced.
        forced = jnp.zeros_like(cand)
        r_once, r_twice = once_twice_reduce(cand, -1)
        unique = (r_once & ~r_twice)[..., None]
        forced = forced | (cand & unique)
        boxes = self._to_boxes(cand)
        b_once, b_twice = once_twice_reduce(boxes, -1)
        b_unique = (b_once & ~b_twice)[..., None]
        forced = forced | self._from_boxes(
            boxes & jnp.broadcast_to(b_unique, boxes.shape)
        )
        c_once, c_twice = once_twice_reduce(cand, -2)  # [L, n] local partials
        c_once, c_twice = ring_once_twice(c_once, c_twice, self.axis, self.n_dev)
        c_unique = (c_once & ~c_twice)[..., None, :]
        forced = forced | (cand & c_unique)
        cand = jnp.where(~single & (forced != 0), forced, cand)
        if self.rules in ("extended", "subsets"):
            cand = self._box_line(cand)
        if self.rules == "subsets":
            cand = self._naked_subsets(cand)
        return cand

    def _naked_subsets(self, cand: jax.Array) -> jax.Array:
        """Banded naked-subset eliminations (``rules='subsets'`` twin).

        Row and box units are chip-local (a shard is a stack of complete
        bands) and reuse ``ops/propagate._naked_subset_kill`` verbatim.
        Column units need every cell of the column: unlike the basic sweep's
        once/twice aggregates, the subset test is *probe-dependent* (count
        of cells contained in each probe's mask), which no fixed-size
        associative reduce expresses — so the columns ride one
        ``all_gather`` over the band axis (XLA lowers it as the same ICI
        ring the ppermute reductions use) and the unsharded kill math runs
        on the gathered view.  Pad rows hold the empty mask 0, which the
        rule ignores on both sides (zero probes are never confined, zero
        cells never counted), so the gathered column is bit-equivalent to
        the unsharded one and the banded fixpoint stays bit-exact
        (``tests/test_subsets.py::test_subsets_banded_bit_exact``).
        """
        from distributed_sudoku_solver_tpu.ops.propagate import _naked_subset_kill

        single = is_single(cand)
        kill = _naked_subset_kill(cand)  # rows: [L, rows_local(units), n]
        kill = kill | self._from_boxes(_naked_subset_kill(self._to_boxes(cand)))
        gathered = jax.lax.all_gather(
            cand, self.axis, axis=-2, tiled=True
        )  # [L, rows_padded, n]
        col_kill_full = jnp.swapaxes(
            _naked_subset_kill(jnp.swapaxes(gathered, -1, -2)), -1, -2
        )
        chip = jax.lax.axis_index(self.axis)
        kill = kill | jax.lax.dynamic_slice_in_dim(
            col_kill_full, chip * self.rows_local, self.rows_local, axis=-2
        )
        return jnp.where(single, cand, cand & ~kill)

    def _box_line(self, cand: jax.Array) -> jax.Array:
        """Banded pointing/claiming (``ops/propagate.box_line_sweep`` twin).

        Rows direction is chip-local (a shard is a stack of complete bands:
        rows, boxes, and row-box interactions never cross chips) and reuses
        :func:`~distributed_sudoku_solver_tpu.ops.propagate.box_line_one_direction`
        verbatim.  The columns direction's cross-band aggregates ride the
        same ring collectives as the basic sweep; the "eliminate from the
        *other* units" complement uses the once/twice identity
        ``OR_{b' != b} x[b'] == (once & ~x[b]) | twice``, which turns the
        unsharded code's explicit loop over other bands into one global
        (once, twice) all-reduce.  Op order matches the unsharded sweep:
        rows direction first, then columns on its output, then the
        decided-cell guard — bit-exactness is asserted by
        ``tests/test_board_sharded.py``.
        """
        from distributed_sudoku_solver_tpu.ops.propagate import (
            box_line_one_direction,
        )

        g = self.geom
        single = is_single(cand)
        out = box_line_one_direction(
            cand, self.bands_per_chip, g.box_h, g.n_hboxes, g.box_w
        )
        out = self._box_line_cols(out)
        return jnp.where(single, cand, out)

    def _box_line_cols(self, x: jax.Array) -> jax.Array:
        """Columns direction: generic roles (nv,bh,nh,bw) -> (nh,bw,nv,bh),
        with the nv (band) axis sharded over chips."""
        g = self.geom
        nh, bw, bh = g.n_hboxes, g.box_w, g.box_h
        n_b = self.bands_per_chip
        lead = x.shape[:-2]
        # [L, rows_local, n] -> transpose -> [L, nh, bw, bands_local, bh]
        v = jnp.swapaxes(x, -1, -2).reshape(*lead, nh, bw, n_b, bh)
        seg = or_reduce(v, -1)  # [L, nh, bw, B]: column segment per band

        # Pointing: bits of box (colband, band) confined to one box-column;
        # eliminate from that global column in every *other* band.
        p_once, p_twice = once_twice_reduce(jnp.swapaxes(seg, -1, -2), -1)
        point = seg & jnp.swapaxes((p_once & ~p_twice)[..., None], -1, -2)
        l_once, l_twice = once_twice_reduce(point, -1)  # local band partials
        g_once, g_twice = ring_once_twice(l_once, l_twice, self.axis, self.n_dev)
        point_other = (g_once[..., None] & ~point) | g_twice[..., None]

        # Claiming: bits of a global column confined to one band (cross-chip
        # once/twice); eliminate from the other columns of that band's box.
        from distributed_sudoku_solver_tpu.ops.propagate import _or_others

        s_once, s_twice = once_twice_reduce(seg, -1)
        gs_once, gs_twice = ring_once_twice(s_once, s_twice, self.axis, self.n_dev)
        claim = seg & (gs_once & ~gs_twice)[..., None]
        claim_other = _or_others(claim, -2)

        kill = (point_other | claim_other)[..., None]  # broadcast over bh
        out = v & ~jnp.broadcast_to(kill, v.shape)
        return jnp.swapaxes(
            out.reshape(*lead, g.n, self.rows_local), -1, -2
        )

    def propagate(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Sweep to a fixpoint; the 'changed' flag is globally agreed (psum)
        so every chip runs the same number of ring exchanges."""

        def cond(s):
            _, changed, sweeps = s
            return changed & (sweeps < self.max_sweeps)

        def body(s):
            cur, _, sweeps = s
            nxt = self._sweep(cur)
            changed = _psum_any(jnp.any(nxt != cur), self.axis)
            return nxt, changed, sweeps + 1

        states, _, sweeps = jax.lax.while_loop(
            cond, body, (states, jnp.bool_(True), jnp.int32(0))
        )
        return states, sweeps

    # -- classification ---------------------------------------------------

    def status(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """(solved, contradiction) per lane — identical on every chip.

        Same rules as ``ops/propagate.py`` ``board_status`` (the corrected
        ``Sudoku.check``, ``/root/reference/sudoku.py:48-94``): row/box
        checks are chip-local on valid units, column checks come from the
        ring-reduced aggregates, verdicts are OR/AND-merged with a psum.
        """
        g = self.geom
        full = jnp.uint32(g.full_mask)
        single = is_single(states)
        decided = jnp.where(single, states, jnp.uint32(0))
        rv = self._row_valid()[:, None]  # [rows_local, 1]
        bv = self._box_valid()  # [local boxes]

        empty = jnp.any((states == jnp.uint32(0)) & rv, axis=(-1, -2))

        _, rd_twice = once_twice_reduce(decided, -1)  # dup digit in a row
        dup = jnp.any((rd_twice != 0) & rv[..., 0], axis=-1)
        unc = jnp.any((or_reduce(states, -1) != full) & rv[..., 0], axis=-1)

        boxes_d = self._to_boxes(decided)
        _, bd_twice = once_twice_reduce(boxes_d, -1)
        dup = dup | jnp.any((bd_twice != 0) & bv, axis=-1)
        unc = unc | jnp.any(
            (or_reduce(self._to_boxes(states), -1) != full) & bv, axis=-1
        )

        cd_once, cd_twice = once_twice_reduce(decided, -2)
        _, cd_twice = ring_once_twice(cd_once, cd_twice, self.axis, self.n_dev)
        col_or = ring_or(or_reduce(states, -2), self.axis, self.n_dev)
        col_dup = jnp.any(cd_twice != 0, axis=-1)
        col_unc = jnp.any(col_or != full, axis=-1)

        contradiction = _psum_any(empty | dup | unc, self.axis) | col_dup | col_unc
        undecided = jnp.any(~single & rv, axis=(-1, -2))
        solved = ~_psum_any(undecided, self.axis) & ~contradiction
        return solved, contradiction

    # -- branching --------------------------------------------------------

    def branch(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Lowest-digit-vs-rest split of the globally chosen cell.

        Every chip computes its best local (key, global cell) packed scalar,
        a ``pmin`` picks the global winner, and only the owning chip's shard
        actually changes.  The key reproduces ``SudokuCSP`` branch order:
        MRV (or first-undecided) with global row-major cell index tiebreak.
        """
        g = self.geom
        n_lanes = states.shape[0]
        chip = jax.lax.axis_index(self.axis).astype(jnp.int32)
        pc = popcount(states).astype(jnp.int32)  # [L, rows_local, n]
        cell0 = chip * self.rows_local * g.n
        gcell = cell0 + jnp.arange(self.rows_local * g.n, dtype=jnp.int32).reshape(
            self.rows_local, g.n
        )
        n_cells = self.rows_padded * g.n
        big = jnp.int32(2**30)
        undecided = pc > 1  # pad rows have pc == 0, never chosen
        if self.branch_rule == "minrem":
            key = jnp.where(undecided, pc * n_cells + gcell, big)
        else:  # 'first': reference's find_next_empty row-major order
            key = jnp.where(undecided, gcell, big)
        local_min = jnp.min(key.reshape(n_lanes, -1), axis=-1)
        gmin = jax.lax.pmin(local_min, self.axis)  # [L]
        chosen = gmin % jnp.int32(n_cells)
        onehot = (gcell[None] == chosen[:, None, None]) & (gmin[:, None, None] < big)

        low = lowest_bit(states)
        guess = jnp.where(onehot, low, states)
        rest = jnp.where(onehot, states & ~low, states)
        return guess, rest

    def signature(self) -> str:
        return (
            f"banded-sudoku:{self.geom.box_h}x{self.geom.box_w}"
            f":{self.n_dev}x{self.bands_per_chip}:{self.branch_rule}"
            f":{self.max_sweeps}:{self.rules}"
        )


# --------------------------------------------------------------------------
# Driver: the generic engine under shard_map with row-sharded board tensors.
# --------------------------------------------------------------------------


def validate_banded_config(config: SolverConfig) -> None:
    """Reject solver options the banded path cannot honor — loudly.

    Shared by :func:`solve_batch_banded` (the EAGER entry, so a bad
    config fails at call time, before any trace/compile work) and
    :func:`_banded_problem` (inside the jit, for callers that reach the
    problem builder directly).  The CLI offers 'mixed'/'minrem-desc' and
    the scored 'head:*' rules for the batch paths; the banded pmin-key
    branch implements exactly the two total orders a cross-chip argmin
    can reproduce, so anything else is a config error, never a silent
    fallback."""
    from distributed_sudoku_solver_tpu.ops.propagate import RULE_TIERS

    if config.rules not in RULE_TIERS:
        raise ValueError(f"unknown rules {config.rules!r}")
    if config.branch not in ("minrem", "first"):
        raise ValueError(
            f"board-sharded solve supports branch='minrem'|'first', "
            f"got {config.branch!r} ('mixed'/'minrem-desc' and the "
            f"'head:*' scored rules are batch-path features)"
        )
    if config.propagator != "xla":
        # The banded sweep has its own ring-exchange collectives; the Pallas
        # batch kernel does not apply here.  Fail loudly rather than let the
        # option silently not take effect.
        raise ValueError(
            f"board-sharded solve supports propagator='xla' only, "
            f"got {config.propagator!r}"
        )


def _banded_problem(
    geom: Geometry, config: SolverConfig, n_dev: int, axis: str
) -> BandedSudoku:
    validate_banded_config(config)
    bands_per_chip = -(-geom.n_vboxes // n_dev)
    return BandedSudoku(
        geom=geom,
        axis=axis,
        n_dev=n_dev,
        bands_per_chip=bands_per_chip,
        branch_rule=config.branch,
        max_sweeps=config.max_sweeps,
        rules=config.rules,
    )


@functools.partial(jax.jit, static_argnames=("geom", "config", "mesh"))
def _solve_banded_jit(
    grids: jax.Array, geom: Geometry, config: SolverConfig, mesh: Mesh
) -> SolveResult:
    (axis,) = mesh.axis_names
    n_dev = mesh.devices.size
    problem = _banded_problem(geom, config, n_dev, axis)

    cand = encode_grid(grids, geom)  # [J, n, n]
    pad = problem.rows_padded - geom.n
    cand = jnp.pad(cand, ((0, 0), (0, pad), (0, 0)))  # pad rows: empty mask 0

    state = init_frontier(cand, config)
    board = P(None, None, axis, None)  # stack[L, S, rows, n]: rows sharded
    specs = Frontier(
        top=P(None, axis, None),  # top[L, rows, n]: rows sharded
        has_top=P(),
        stack=board,
        base=P(),
        count=P(),
        job=P(),
        solved=P(),
        solution=P(None, axis, None),
        overflowed=P(),
        nodes=P(),
        sol_count=P(),
        steps=P(),
        sweeps=P(),
        expansions=P(),
        steals=P(),
        lane_rounds=P(),
    )
    body = _shard_map_compat(
        functools.partial(run_frontier, problem=problem, config=config),
        mesh=mesh,
        in_specs=(specs,),
        out_specs=specs,
        check_vma=False,
    )
    state = body(state)

    res = finalize_frontier(state)  # lane/job bookkeeping: replicated, global
    sol = res.solution[:, : geom.n, :]  # strip pad rows
    solution = jnp.where(res.solved[:, None, None], decode_grid(sol), jnp.int32(0))
    return res._replace(solution=solution)


def solve_batch_banded(
    grids,
    geom: Geometry,
    config: SolverConfig = SolverConfig(),
    mesh: Mesh | None = None,
) -> SolveResult:
    """Solve int grids [J, n, n] with each board's rows sharded over ``mesh``.

    The board-parallel counterpart of
    :func:`~distributed_sudoku_solver_tpu.parallel.sharded.solve_batch_sharded`:
    use that one to scale over many jobs/lanes, this one when a single board
    is the thing that must span chips (giant geometries).  Results are
    bit-identical to the single-device ``solve_batch``.
    """
    # Config-time rejection: an unsupported branch/propagator fails HERE,
    # eagerly, instead of surfacing mid-trace inside the jit.
    validate_banded_config(config)
    mesh = mesh if mesh is not None else make_band_mesh()
    return _solve_banded_jit(jnp.asarray(grids), geom, config, mesh)
