"""Typed, length-framed JSON messages over TCP — the control-plane transport.

Replaces the reference's wire layer (``/root/reference/DHT_Node.py:74-99``)
and removes its three structural flaws by construction (SURVEY.md §2.3):

* **pickle → JSON**: no RCE surface from network input (`pickle.loads` at
  ``:83,99``);
* **UDP → TCP**: no silently-lost TASK messages (§2.5 #7) — delivery either
  succeeds or raises at the sender, which can then re-dispatch;
* **1024-byte recv cap → 4-byte length prefix**: 25x25 boards (1.5 KB
  pickled, truncated by the reference — §2.5 #8) frame like anything else.

Connection discipline is datagram-style on purpose: one connection per
message (optionally one reply on the same connection), so there is no
session state to repair after a peer dies — matching the reference's
fire-and-forget model with reliability added.

"Raises at the sender" has two flavors, and retry logic must tell them
apart (:attr:`WireError.ambiguous_delivery`): a failure *before* any frame
byte reached the peer (connect refused / connect timeout) proves the
message was not delivered, so a re-dispatch cannot duplicate it; a failure
*after* bytes were written (reset mid-``sendall``, reply timeout) proves
nothing — the peer may have received and acted on the whole frame, so any
re-dispatch is at-least-once delivery and the receiver must be idempotent
(``cluster/node.py`` dedupes result-bearing messages by uuid).

This module also hosts the *production* transport/clock pair behind
``ClusterNode``'s injectable seam (:class:`TcpTransport` /
:class:`SystemClock`); the deterministic in-memory twin lives in
``cluster/simnet.py``.
"""

from __future__ import annotations

import itertools
import json
import logging
import socket
import struct
import threading
import time
from typing import Callable, Optional, Tuple

_LOG = logging.getLogger(__name__)

Addr = Tuple[str, int]

MAX_FRAME = 16 * 1024 * 1024  # generous: a 25x25 grid message is ~2 KB
_LEN = struct.Struct(">I")


class WireError(Exception):
    """Transport-level failure: peer unreachable, bad frame, oversize.

    ``ambiguous_delivery`` is the retry-relevant distinction (module
    docstring): ``False`` — the message definitely did not reach the peer;
    ``True`` — bytes were written before the failure, so the peer *may*
    have processed the message and a re-dispatch implies duplicates.
    """

    def __init__(self, message: str, ambiguous_delivery: bool = False):
        super().__init__(message)
        self.ambiguous_delivery = ambiguous_delivery


def addr_str(addr: Addr) -> str:
    return f"{addr[0]}:{addr[1]}"


def parse_addr(s: str) -> Addr:
    host, _, port = s.rpartition(":")
    return host, int(port)


def _send_frame(sock: socket.socket, msg: dict) -> None:
    data = json.dumps(msg).encode()
    if len(data) > MAX_FRAME:
        raise WireError(f"frame too large: {len(data)} bytes")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WireError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> dict:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise WireError(f"frame too large: {length} bytes")
    msg = json.loads(_recv_exact(sock, length))
    if not isinstance(msg, dict) or "method" not in msg:
        raise WireError("malformed message: expected dict with 'method'")
    return msg


def reply_msg(sock: socket.socket, msg: dict) -> None:
    _send_frame(sock, msg)


def send_msg(addr: Addr, msg: dict, timeout: float = 5.0) -> None:
    """Fire-and-forget (but reliable): deliver one message, no reply."""
    try:
        sock = socket.create_connection(addr, timeout=timeout)
    except OSError as e:
        raise WireError(f"connect to {addr_str(addr)} failed: {e}") from e
    try:
        with sock:
            _send_frame(sock, msg)
    except WireError:
        raise  # oversize: refused before any byte was written
    except OSError as e:
        # The connection existed (sendall failure, or a close()-time reset
        # surfacing on `with` exit): some — possibly all — frame bytes may
        # have reached the peer before the failure.
        raise WireError(
            f"send to {addr_str(addr)} failed after connect: {e}",
            ambiguous_delivery=True,
        ) from e


def fanout_requests(
    transport, peers, payload: dict, timeout: float, max_threads: int = 32
) -> list:
    """Parallel request/reply fan-out with per-peer timeouts — the shape
    ``stats_view`` always used, now shared with the cluster metrics pull
    (METRICS_PULL, ``GET /metrics?scope=cluster``).

    At most ``max_threads`` daemon worker threads drain the peer list,
    each request bounded by ``timeout``; a peer that fails, is
    partitioned, or answers late yields ``None`` in its slot.  The
    bounded pool is what keeps a 500-member pull from forking 500
    threads per scrape (ISSUE 17 satellite); wall time is bounded by
    ~``ceil(peers/max_threads) * timeout`` worst-case but in practice by
    the slowest stragglers, never O(peers) serial timeouts — which is
    what keeps the aggregation endpoints from ever hanging an HTTP
    handler thread on a degraded ring.  ``peers`` are addr strings or
    parsed ``Addr`` tuples."""
    results: list = [None] * len(peers)
    if not peers:
        return results
    nworkers = max(1, min(int(max_threads), len(peers)))
    cursor = itertools.count()

    def ask(i: int, peer) -> None:
        addr = peer if isinstance(peer, tuple) else parse_addr(peer)
        try:
            results[i] = transport.request(addr, payload, timeout)
        except WireError:
            pass  # slot stays None: the caller flags the peer

    def drain() -> None:
        for i in cursor:
            if i >= len(peers):
                return
            ask(i, peers[i])

    threads = [
        threading.Thread(target=drain, daemon=True) for _ in range(nworkers)
    ]
    for t in threads:
        t.start()
    # Each worker serves ~peers/nworkers requests back to back; the join
    # budget covers that plus slack, so a wedged transport still cannot
    # hang the caller.
    budget = timeout * (len(peers) / nworkers + 1.0) + 1.0
    for t in threads:
        t.join(budget)
    # Snapshot: a straggler thread finishing after its join timeout must
    # not mutate what the caller is already iterating.
    return list(results)


def request(addr: Addr, msg: dict, timeout: float = 5.0) -> dict:
    """Send one message and wait for one reply frame on the same connection."""
    try:
        sock = socket.create_connection(addr, timeout=timeout)
    except OSError as e:
        raise WireError(f"request to {addr_str(addr)} failed: {e}") from e
    try:
        with sock:
            sock.settimeout(timeout)
            try:
                _send_frame(sock, msg)
            except OSError as e:
                raise WireError(
                    f"request to {addr_str(addr)} failed mid-send: {e}",
                    ambiguous_delivery=True,
                ) from e
            try:
                return recv_msg(sock)
            except (WireError, OSError) as e:
                # The request went out whole; only the reply failed — the
                # peer may well have processed it.
                raise WireError(
                    f"request to {addr_str(addr)} failed awaiting reply: {e}",
                    ambiguous_delivery=True,
                ) from e
    except WireError:
        raise
    except OSError as e:
        # close()-time failure on `with` exit, after the request was sent.
        raise WireError(
            f"request to {addr_str(addr)} failed at close: {e}",
            ambiguous_delivery=True,
        ) from e


# -- the production transport/clock pair --------------------------------------
#
# ClusterNode takes an injectable (transport, clock): these are the real
# ones (sockets + time.monotonic/time.sleep), with zero behavior change
# from the pre-seam node.  The transport contract, duck-typed and shared
# with cluster/simnet.py:
#
#   bind(host, port) -> Addr          allocate the listening address
#   serve(handler, on_error=None, io_timeout=5.0)
#                                     start delivering inbound messages;
#                                     handler(msg) returns an optional
#                                     reply dict (request/reply methods);
#                                     handler exceptions go to on_error
#   close()                           stop serving (idempotent)
#   send(addr, msg, timeout)          one message, no reply; raises WireError
#   request(addr, msg, timeout) -> dict


class SystemClock:
    """Production clock: real monotonic time, real sleeps.  Late-bound on
    purpose: the simnet purity guard (tests/conftest.py) monkeypatches
    ``time.sleep``, and a class-level ``sleep = time.sleep`` captured at
    import would let a simnet test that forgot ``clock=net.clock`` sleep
    real wall-clock seconds without the guard ever noticing."""

    @staticmethod
    def now() -> float:
        return time.monotonic()

    @staticmethod
    def sleep(dt: float) -> None:
        time.sleep(dt)


class TcpTransport:
    """Production transport: one listener, one thread per connection — the
    exact socket behavior ClusterNode always had, factored behind the
    transport seam so the simulated plane can replace it."""

    def __init__(self):
        self._listener: Optional[socket.socket] = None
        self._closed = threading.Event()
        self._handler: Optional[Callable[[dict], Optional[dict]]] = None
        self._on_error: Optional[Callable[[BaseException], None]] = None
        self._io_timeout = 5.0

    def bind(self, host: str, port: int) -> Addr:
        self._listener = socket.create_server((host, port))
        return (host, self._listener.getsockname()[1])

    def serve(
        self,
        handler: Callable[[dict], Optional[dict]],
        on_error: Optional[Callable[[BaseException], None]] = None,
        io_timeout: float = 5.0,
    ) -> None:
        if self._listener is None:
            raise RuntimeError("serve() before bind()")
        self._handler = handler
        self._on_error = on_error
        self._io_timeout = io_timeout
        threading.Thread(
            target=self._accept_loop, daemon=True, name="wire-accept"
        ).start()

    def close(self) -> None:
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            try:
                conn.settimeout(self._io_timeout)
                msg = recv_msg(conn)
                reply = self._handler(msg)
                if reply is not None:
                    reply_msg(conn, reply)
            except Exception as e:  # noqa: BLE001 - network input must never
                # kill the serving thread; reliability comes from sender-side
                # errors + retries, not server-side recovery.
                if not self._closed.is_set():
                    if self._on_error is not None:
                        self._on_error(e)
                    else:
                        _LOG.error("bad message: %r", e)

    def send(self, addr: Addr, msg: dict, timeout: float) -> None:
        send_msg(addr, msg, timeout)

    def request(self, addr: Addr, msg: dict, timeout: float) -> dict:
        return request(addr, msg, timeout)
