"""Typed, length-framed JSON messages over TCP — the control-plane transport.

Replaces the reference's wire layer (``/root/reference/DHT_Node.py:74-99``)
and removes its three structural flaws by construction (SURVEY.md §2.3):

* **pickle → JSON**: no RCE surface from network input (`pickle.loads` at
  ``:83,99``);
* **UDP → TCP**: no silently-lost TASK messages (§2.5 #7) — delivery either
  succeeds or raises at the sender, which can then re-dispatch;
* **1024-byte recv cap → 4-byte length prefix**: 25x25 boards (1.5 KB
  pickled, truncated by the reference — §2.5 #8) frame like anything else.

Connection discipline is datagram-style on purpose: one connection per
message (optionally one reply on the same connection), so there is no
session state to repair after a peer dies — matching the reference's
fire-and-forget model with reliability added.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Tuple

Addr = Tuple[str, int]

MAX_FRAME = 16 * 1024 * 1024  # generous: a 25x25 grid message is ~2 KB
_LEN = struct.Struct(">I")


class WireError(Exception):
    """Transport-level failure: peer unreachable, bad frame, oversize."""


def addr_str(addr: Addr) -> str:
    return f"{addr[0]}:{addr[1]}"


def parse_addr(s: str) -> Addr:
    host, _, port = s.rpartition(":")
    return host, int(port)


def _send_frame(sock: socket.socket, msg: dict) -> None:
    data = json.dumps(msg).encode()
    if len(data) > MAX_FRAME:
        raise WireError(f"frame too large: {len(data)} bytes")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WireError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> dict:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_FRAME:
        raise WireError(f"frame too large: {length} bytes")
    msg = json.loads(_recv_exact(sock, length))
    if not isinstance(msg, dict) or "method" not in msg:
        raise WireError("malformed message: expected dict with 'method'")
    return msg


def reply_msg(sock: socket.socket, msg: dict) -> None:
    _send_frame(sock, msg)


def send_msg(addr: Addr, msg: dict, timeout: float = 5.0) -> None:
    """Fire-and-forget (but reliable): deliver one message, no reply."""
    try:
        with socket.create_connection(addr, timeout=timeout) as sock:
            _send_frame(sock, msg)
    except OSError as e:
        raise WireError(f"send to {addr_str(addr)} failed: {e}") from e


def request(addr: Addr, msg: dict, timeout: float = 5.0) -> dict:
    """Send one message and wait for one reply frame on the same connection."""
    try:
        with socket.create_connection(addr, timeout=timeout) as sock:
            sock.settimeout(timeout)
            _send_frame(sock, msg)
            return recv_msg(sock)
    except OSError as e:
        raise WireError(f"request to {addr_str(addr)} failed: {e}") from e
