"""Cluster node: coordinator membership + heartbeat + job dispatch + recovery.

Host-level re-design of the reference's overlay layer (SURVEY.md §1 L3,
§2.1 #8-#10) for the TPU world: each *node* is a host driving its own chip
mesh (the data plane lives in ``parallel/``).  Jobs are placed whole at
submit time (least-outstanding), and *live* jobs are additionally split
mid-flight: an idle node's NEEDWORK pulls bottom stack rows — the largest
unexplored subtrees — out of a busy peer's running frontier, exactly the
reference's dynamic guess-range split (``/root/reference/DHT_Node.py:
491-510``) lifted to the host tier.

Capability map (reference -> here):

* coordinator-mediated join (``/root/reference/DHT_Node.py:260-330``) ->
  JOIN_REQ forwarded to the coordinator, which appends to the member list
  and broadcasts UPDATE_NETWORK; ring positions (predecessor/successor) are
  *derived from list order* on every node, eliminating the reference's
  separate UPDATE_PREDECESSOR/UPDATE_NEIGHBOR splice messages and the
  inconsistency windows between them.
* heartbeat + 2x-timeout detection (``:43-62,158-163``) -> each node
  heartbeats its ring successor and watches its predecessor's arrivals.
* coordinator-led repair + self-promotion (``:167-199``) -> same roles:
  detector reports NODE_FAILED; the dead coordinator's successor-detector
  self-promotes (exactly one detector per corpse, so promotion is unique).
* re-execution from the delegator's ledger (``:47,497,509,201-209``) ->
  every forwarded job stays in ``self._ledger`` until its SOLUTION arrives;
  workers stream PROGRESS snapshots (their surviving subtree roots) back to
  the origin, so when a member dies its jobs *resume mid-subtree* from the
  last snapshot instead of restarting — strictly stronger than the
  reference's recompute-from-ledger.
* NEEDWORK work stealing (``:246-254,491-510``) -> an idle node NEEDWORKs
  its ring predecessor; the busy peer sheds bottom stack rows from its
  neediest live job (``serving/engine.shed_work``) and ships them as a
  SUBTASK; first-win cancellation and unsat-aggregation across the parts
  are handled by the per-job execution aggregate (:class:`_Exec`).
* STATS_REQ 1 s gather sleep (``:566-598``) -> parallel request/reply
  fan-out with per-peer timeouts.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import logging
import socket
import threading
import time
import uuid as uuid_mod
from typing import Callable, Optional

import numpy as np

from distributed_sudoku_solver_tpu.cluster import wire
from distributed_sudoku_solver_tpu.cluster.dht import ClusterCache, Gossip, HashRing
from distributed_sudoku_solver_tpu.cluster.wire import Addr, WireError, addr_str
from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
from distributed_sudoku_solver_tpu.obs import agg, lockdep, trace
from distributed_sudoku_solver_tpu.obs.hist import LatencyHistogram
from distributed_sudoku_solver_tpu.obs.logctx import ctx_log, job_log
from distributed_sudoku_solver_tpu.serving import brownout as brownout_mod
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine
from distributed_sudoku_solver_tpu.serving.frontdoor import cache as fd_cache
from distributed_sudoku_solver_tpu.serving.frontdoor import canonical as fd_canon

# Diagnostics go through logging (stderr via the root handler / logging's
# lastResort), not print(); failure-path messages carry the fault
# classification and keep their grep-compatible "[addr]" prefixes.
_LOG = logging.getLogger(__name__)


def local_ip() -> str:
    """Best-effort routable local address (UDP connect sends no packets)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def pack_rows(rows: np.ndarray) -> dict:
    """Subtree roots (uint32 candidate masks) -> JSON-safe wire payload.

    Little-endian raw bytes under base64: the same rows that
    ``utils/checkpoint.py`` snapshots to npz, so the checkpoint format and
    the offload/progress wire format are one representation.
    """
    r = np.ascontiguousarray(np.asarray(rows, dtype="<u4"))
    return {
        "shape": list(r.shape),
        "data": base64.b64encode(r.tobytes()).decode("ascii"),
    }


def unpack_rows(d: dict) -> np.ndarray:
    shape = tuple(int(x) for x in d["shape"])
    raw = base64.b64decode(d["data"])
    rows = np.frombuffer(raw, dtype="<u4").reshape(shape)
    return rows.astype(np.uint32)


def _config_from_dict(d: Optional[dict]):
    """SolverConfig off the wire (None-tolerant): a shed part or resumed
    snapshot searches under the same config the job was submitted with —
    a portfolio racer's heterogeneity must survive the hop."""
    if not d:
        return None
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

    return SolverConfig(**d)


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    heartbeat_s: float = 1.0
    fail_factor: float = 3.0  # declare dead after fail_factor * heartbeat_s
    io_timeout_s: float = 5.0
    stats_timeout_s: float = 2.0
    # At-least-once delivery for result-bearing messages (SOLUTION /
    # PART_RESULT): a failed send is re-attempted send_retries more times,
    # retry_delay_s apart (on the node clock, so virtual in simnet tests).
    # An ambiguous failure may already have been delivered, so receivers
    # dedupe these methods by uuid/part — see _handle's dedupe ledger.
    send_retries: int = 2
    retry_delay_s: float = 0.25
    # How long a coordinator keeps probing an evicted-but-possibly-alive
    # member with its current view (the split-brain heal channel: a
    # partitioned survivor learns the winning view and rejoins/demotes).
    # Probes ride the per-beat broadcast; a really-dead member costs one
    # failed connect per beat until the tombstone expires.
    tombstone_probe_s: float = 60.0
    # Mid-job offload + progress checkpointing:
    needwork: bool = True  # idle nodes pull subtree work from the ring
    shed_k: int = 8  # max stack rows shipped per SUBTASK
    progress_interval_s: float = 0.5  # worker -> origin snapshot cadence
    progress_max_rows: int = 4096  # skip snapshots larger than this
    # Shed parts are retained at the shedder and re-entered locally when the
    # part-executing peer leaves the network view (always on).  Optionally a
    # wall-clock deadline also re-homes parts stuck on a wedged-but-alive
    # peer; 0 disables (the failure detector covers actual deaths, and a
    # deep search can legitimately run long).
    part_deadline_s: float = 0.0
    # The DHT plane (ISSUE 17, cluster/dht/): SWIM gossip liveness +
    # consistent-hash ownership of the canonical digest space + the
    # cluster-wide result cache.  The (term,epoch) view stays the
    # membership authority; gossip adds O(1)-per-beat liveness (one PROBE
    # with piggybacked state per beat) and the ring adds cache-affine
    # routing.  ``dht=False`` restores the pre-DHT node exactly.
    dht: bool = True
    dht_vnodes: int = 32  # virtual points per member on the hash ring
    dht_piggyback: int = 8  # max gossip updates per PROBE/ACK frame
    dht_suspicion_s: float = 0.0  # 0 -> heartbeat_s * fail_factor
    dht_probe_timeout_s: float = 0.0  # 0 -> min(stats_timeout_s, heartbeat_s)
    dht_cache_entries: int = 65536  # per-node shard capacity
    dht_get_timeout_s: float = 0.0  # 0 -> min(1.0, io_timeout_s)
    # Cache-affine routing: submit() sends a cacheable board to its
    # digest owner when the owner is gossip-ALIVE and not browning, so
    # every orbit's repeats land where its entry lives.  Only engaged
    # when this node runs a front door (no front door -> no cache to be
    # affine to) — least-outstanding placement otherwise.
    dht_affinity: bool = True


class _DedupeLRU:
    """Bounded seen-set for at-least-once delivery: result/work-bearing
    messages (TASK, SUBTASK, SOLUTION, PART_RESULT) are deduped by their
    uuid so an ambiguous-failure retry that was in fact delivered twice
    executes once.  Bounded like the engine's stale-cancel ledger: a uuid
    evicted after 4096 newer ones has long since resolved, and a duplicate
    arriving later still hits the handlers' own idempotence (popped
    ledger, done-part) — this ledger exists to stop duplicate *execution*,
    not to be the only line of defense."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._seen: collections.OrderedDict = collections.OrderedDict()
        self._lock = lockdep.named_lock("cluster.dedupe")  # lockck: name(cluster.dedupe)

    def seen(self, key) -> bool:
        """True if ``key`` was recorded before; records it otherwise."""
        with self._lock:
            if key in self._seen:
                self._seen.move_to_end(key)
                return True
            self._seen[key] = None
            while len(self._seen) > self._cap:
                self._seen.popitem(last=False)
            return False


class _Exec:
    """One uuid's execution on this node: local engine job + shed parts.

    Finalization rules (the distributed counterpart of ``SolveResult``):

    * solved   — the local search or *any* part solves (first win; losers
                 are cancelled, the speculative-cancellation contract of
                 ``/root/reference/DHT_Node.py:348-387``);
    * unsat    — the local space is exhausted (nothing dropped, nothing
                 still shipped) AND every part reports its subspace
                 exhausted: the disjoint parts cover the job's space, so
                 exhaustion composes into a proof;
    * cancelled/error — propagate immediately, cancelling live parts;
    * nodes    — accumulate across the local run, all parts, and any
                 resumed predecessor (``base_nodes``).
    """

    def __init__(
        self,
        node: "ClusterNode",
        job: Job,
        on_final: Callable[[dict], None],
        base_nodes: int = 0,
    ):
        self.node = node
        self.uuid = job.uuid
        self.job = job
        self.on_final = on_final
        self.base_nodes = base_nodes
        self.parts: dict[str, dict] = {}  # part_uuid -> {peer, done, exhausted, nodes}
        self.part_failure: Optional[str] = None  # terminal part loss (see
        #   on_part_result): surfaces as the job's error if it ends unresolved
        self.progress_skip_warned = False  # one degraded-resume warning per job
        self.finalized = False
        self.lock = lockdep.named_lock("cluster.exec")  # lockck: name(cluster.exec)
        threading.Thread(
            target=self._watch_local, daemon=True, name=f"exec-{self.uuid[:8]}"
        ).start()

    def _watch_local(self) -> None:
        self.job.done.wait()
        self._maybe_finalize()

    def add_part(
        self, part_uuid: str, peer: str, rows_packed=None, config=None
    ) -> bool:
        """Register a shed part.  ``rows_packed``/``config`` are retained so
        the shedder can re-enter the subtree locally if ``peer`` dies — the
        recovery symmetry the worker-death resume path has (ADVICE r2 #1)."""
        with self.lock:
            if self.finalized:
                return False
            self.parts[part_uuid] = {
                "peer": peer,
                "done": False,
                "exhausted": False,
                "nodes": 0,
                "rows": rows_packed,
                "config": config,
                "t0": self.node._clock.now(),
                "rehomed": False,
            }
            return True

    def take_orphaned(self, live: set, deadline_s: float = 0.0) -> list:
        """Claim parts whose peer left ``live`` (or blew ``deadline_s``) for
        local re-execution: each is marked re-homed (so recovery fires once)
        and its retained rows/config returned.  ``peer`` is kept as the
        original executor so finalize still CANCELs a slow-but-alive peer
        that blew the deadline.  A false death verdict at worst duplicates
        the part's work — PART_RESULT first-wins dedupe keeps the aggregate
        sound."""
        now = self.node._clock.now()
        out = []
        with self.lock:
            if self.finalized:
                return out
            for pu, p in self.parts.items():
                if p["done"] or p["rehomed"] or p["rows"] is None:
                    continue
                if p["peer"] == self.node.addr_s:
                    continue
                dead = p["peer"] not in live
                late = deadline_s > 0 and now - p["t0"] > deadline_s
                if dead or late:
                    p["rehomed"] = True
                    out.append((pu, p["rows"], p["config"]))
        return out

    def mark_local(self, part_uuid: str) -> None:
        """Record that a part runs on this node (the WireError shed
        fallback), so view-change recovery never re-enters it."""
        with self.lock:
            p = self.parts.get(part_uuid)
            if p is not None:
                p["peer"] = self.node.addr_s
                p["rehomed"] = True

    def unmark_rehomed(self, part_uuid: str) -> None:
        """Local re-entry failed: clear the flag so a later recovery pass
        (next view change / deadline tick) can retry instead of the part
        being permanently lost."""
        with self.lock:
            p = self.parts.get(part_uuid)
            if p is not None:
                p["rehomed"] = False

    def on_part_result(self, part_uuid: str, msg: dict) -> None:
        if msg.get("error") and not msg.get("solved") and not msg.get("unsat"):
            # A FAILED execution, not an exhaustion verdict: the executor's
            # engine drained the part during shutdown, or its flight could
            # not launch (any no-verdict error qualifies — keying on one
            # error string would let other failures mark the part done,
            # free the recovery rows, and leave the subtree silently
            # unsearched; the SOLUTION-path twin of this hole lost a whole
            # job in the round-4 device-backed churn soak).
            if msg.get("local"):
                # Our own local re-entry failed — the last resort.  Mark
                # the part failed-done (exhaustion can never compose into
                # an unsat proof now) and remember the error so an
                # unresolved job surfaces it instead of hanging or looping:
                # re-entering again would fail identically forever.
                with self.lock:
                    info = self.parts.get(part_uuid)
                    if info is None or info["done"]:
                        return
                    info["done"] = True
                    info["rows"] = None
                    info["exhausted"] = False
                    self.part_failure = (
                        f"part {part_uuid} failed on its last-resort local "
                        f"re-entry: {msg['error']}"
                    )
                self._maybe_finalize()
                return
            # Remote failure: re-enter the retained rows locally right away
            # — waiting for view-change recovery would hang forever when
            # the peer stays in the view (engine restarted, node alive).
            # An already-rehomed part is left alone (a local re-entry owns
            # it; this is the original executor's late drain).  If re-entry
            # raises synchronously, the helper clears the flag so
            # deadline/view recovery retries later.
            with self.lock:
                info = self.parts.get(part_uuid)
                if info is None or info["done"] or self.finalized:
                    return
                if info["rehomed"]:
                    return
                rows_packed, cfg = info["rows"], info["config"]
                if rows_packed is None:
                    return  # nothing retained; view-change recovery owns it
                info["rehomed"] = True
            self.node._reenter_part(self, part_uuid, rows_packed, cfg)
            return
        with self.lock:
            info = self.parts.get(part_uuid)
            if info is None or info["done"]:
                return
            info["done"] = True
            # Drop the retained recovery rows: the part is done, nobody will
            # re-enter it (take_orphaned skips rows-None entries), and a
            # long-running job that sheds many parts must not hold every
            # part's packed stack rows until finalize (ADVICE r3).
            info["rows"] = None
            info["exhausted"] = bool(msg.get("unsat"))
            info["nodes"] = int(msg.get("nodes", 0))
            peer, rehomed = info["peer"], info["rehomed"]
        if rehomed:
            # A re-homed part has two executions (the original peer may be
            # alive — blown deadline / false death verdict — plus the local
            # re-entry).  First result wins: cancel both executors so the
            # loser doesn't burn an engine with no waiter (cancelling the
            # finished one is a harmless no-op).
            self.node._send_cancel(peer, part_uuid)
            if peer != self.node.addr_s:
                self.node._send_cancel(self.node.addr_s, part_uuid)
        if msg.get("solved") and msg.get("solution") is not None:
            self._finalize(
                solved=True, solution=np.asarray(msg["solution"], dtype=np.int32)
            )
            self.node.engine.cancel(self.uuid)  # stop the local loser
        else:
            self._maybe_finalize()

    def _maybe_finalize(self) -> None:
        job = self.job
        if not job.done.is_set():
            return  # local still running; parts alone conclude only via solve
        if job.solved:
            self._finalize(solved=True, solution=job.solution)
            return
        if job.cancelled:
            self._finalize(cancelled=True)
            return
        if job.error:
            self._finalize(error=job.error)
            return
        with self.lock:
            if any(not p["done"] for p in self.parts.values()):
                return  # exhausted locally, but shipped subtrees still out
            all_parts_exhausted = all(p["exhausted"] for p in self.parts.values())
            part_failure = self.part_failure
        unsat = job.exhausted and all_parts_exhausted
        if not unsat and part_failure:
            # A part's subtree was lost terminally (remote AND local
            # executions failed): the inconclusive outcome carries the
            # cause instead of reading like a mere budget exhaustion.
            self._finalize(error=part_failure)
            return
        self._finalize(unsat=unsat)

    def _finalize(
        self,
        solved: bool = False,
        solution=None,
        unsat: bool = False,
        cancelled: bool = False,
        error: Optional[str] = None,
    ) -> None:
        with self.lock:
            if self.finalized:
                return
            self.finalized = True
            part_nodes = sum(p["nodes"] for p in self.parts.values())
            losers = [
                (pu, p["peer"], p["rehomed"])
                for pu, p in self.parts.items()
                if not p["done"]
            ]
        for part_uuid, peer, rehomed in losers:
            self.node._send_cancel(peer, part_uuid)
            # A re-homed part has a *second* execution here (the original
            # peer may be alive too, e.g. a blown deadline): cancel both.
            if rehomed and peer != self.node.addr_s:
                self.node._send_cancel(self.node.addr_s, part_uuid)
        self.on_final(
            {
                "solved": solved,
                "solution": solution,
                "unsat": unsat,
                "cancelled": cancelled,
                "error": error,
                "nodes": self.base_nodes + int(self.job.nodes) + part_nodes,
            }
        )


class _L2Adapter:
    """The duck-typed L2 the front door calls (router.py's ``self.l2``),
    backed by the node's :class:`cluster.dht.ClusterCache`.

    This is the ONE place the wire's JSON-ready entry dicts and the
    front door's :class:`CacheEntry` meet: ``cluster/dht`` stays
    stdlib-closed (no numpy, no serving import) and ``serving/frontdoor``
    stays cluster-free — the conversion lives here, in the layer that
    already imports both."""

    def __init__(self, dcache: ClusterCache):
        self.dcache = dcache

    def lookup(self, digest: str, raw: str):
        d = self.dcache.lookup(digest)
        if d is None:
            return None
        verdict = d.get("verdict")
        if verdict not in (fd_cache.SOLVED, fd_cache.UNSAT):
            return None  # malformed wire entry: treat as a miss
        sol = d.get("solution")
        if verdict == fd_cache.SOLVED and sol is None:
            return None
        return fd_cache.CacheEntry(
            verdict=verdict,
            solution=None if sol is None else np.asarray(sol, dtype=np.int8),
            nodes=int(d.get("nodes", 0)),
            raw_digest=str(d.get("raw", raw)),
            route=str(d.get("route", "cluster")),
        )

    def store(self, digest: str, entry) -> None:
        self.dcache.store(
            digest,
            {
                "verdict": entry.verdict,
                "solution": None
                if entry.solution is None
                else np.asarray(entry.solution).tolist(),
                "nodes": int(entry.nodes),
                "raw": entry.raw_digest,
                "route": entry.route,
            },
        )


class ClusterNode:
    """One host in the solver cluster; wraps a local SolverEngine."""

    def __init__(
        self,
        engine: SolverEngine,
        host: str = "127.0.0.1",
        port: int = 0,
        anchor: Optional[Addr] = None,
        config: ClusterConfig = ClusterConfig(),
        advertise_host: Optional[str] = None,
        transport=None,
        clock=None,
    ):
        """``host`` is the bind address; ``advertise_host`` is the identity
        other members dial (defaults to ``host``, which is only correct for
        single-machine clusters — multi-host deployments must advertise a
        routable address, e.g. from :func:`local_ip`).

        ``transport``/``clock`` are the injectable network/time seam (the
        contract in ``cluster/wire.py``'s module note): real sockets and
        ``time.monotonic``/``time.sleep`` by default — zero production
        behavior change — or a ``cluster/simnet.py`` plane, which runs the
        identical protocol over an in-memory network with a virtual clock
        so partitions, duplicate delivery, reordering, and split-brain
        heal are deterministic, socket-free tests."""
        self.engine = engine
        self.config = config
        self._clock = clock or wire.SystemClock()
        self._transport = transport or wire.TcpTransport()
        bound = self._transport.bind(host, port)
        adv = advertise_host or bound[0]
        if adv in ("0.0.0.0", "::"):
            adv = local_ip()
        self.addr: Addr = (adv, bound[1])
        self.addr_s = addr_str(self.addr)
        self.anchor = anchor
        # Trace attribution (obs/trace.py): engine spans recorded on this
        # host carry the node's wire identity, so a stitched multi-node
        # trace shows WHICH member ran which chunk.
        engine.trace_node = self.addr_s

        self._lock = lockdep.named_rlock("cluster.node")  # lockck: name(cluster.node)
        self.network: list[str] = [self.addr_s]  # list order defines the ring
        self.coordinator: str = self.addr_s
        # Monotonic membership version, ordered as (term, epoch): the term
        # bumps on every coordinator promotion (so a successor's first view
        # supersedes anything the dead coordinator issued, even epochs the
        # detector never saw), the epoch bumps on every membership change
        # within a term.  UPDATE_NETWORK messages arrive on per-connection
        # threads, so two broadcasts can be *applied* out of order; this
        # ordering makes installation order-independent (stale views are
        # dropped), where the reference simply last-writer-wins
        # (``/root/reference/DHT_Node.py:332-336``).
        self.net_term: int = 0
        self.net_epoch: int = 0
        self._last_hb = self._clock.now()
        self._ledger: dict[str, dict] = {}  # uuid -> {grid, member, job, rows?, nodes_done?}
        self._execs: dict[str, _Exec] = {}  # uuid -> live local execution
        self._parts: dict[str, str] = {}  # part_uuid -> root uuid (parts run here)
        self._outstanding: dict[str, int] = {}  # member -> in-flight count
        self._rr = 0
        # Idempotent client resubmit (ISSUE 20): client-supplied uuid ->
        # live handle, so a retry of an in-flight/resolved job returns the
        # existing verdict instead of double-solving (the engine keeps the
        # same registry for its own jobs; this one covers REMOTE dispatch
        # too).  Bounded; error terminals are evicted at lookup so a retry
        # after an infra failure runs fresh.
        self._client_jobs: dict[str, Job] = {}  # lockck: guard(_lock)
        # Shed-part counters: bumped by concurrent NEEDWORK/SUBTASK
        # handler threads (deadck guard inference caught subtasks_run
        # outside the lock — a lost-update race since round 10).
        self.subtasks_sent = 0  # lockck: guard(_lock)
        self.subtasks_run = 0  # lockck: guard(_lock)
        # PROGRESS snapshots dropped because the frontier was wider than
        # progress_max_rows: the job still completes, but a worker death
        # degrades its resume to root re-execution.  Silent until round 6
        # (VERDICT r5 missing #3) — now counted, logged, and exported on
        # /metrics so an operator can see which deployments run resumeless.
        self.progress_skipped = 0  # lockck: guard(_lock)
        # Jobs served by a resident flight run without progress streaming
        # at all (no snapshot surface): counted so an operator can see how
        # much of the fleet's work resumes from the root on a death.
        self.progress_resident = 0  # lockck: guard(_lock)
        # At-least-once / split-brain machinery (round 10): the dedupe
        # ledger for result/work-bearing duplicates, the coordinator's
        # tombstones of suspected-dead members (probed with the current
        # view so a partitioned survivor can rejoin), per-peer
        # rate-limiting of stale-view reflections, and the fault counters
        # exported on /metrics (cluster.faults).
        self._dedupe = _DedupeLRU()
        self._evicted: dict[str, float] = {}  # member -> eviction time
        self._reflect_at: dict[str, float] = {}  # peer -> next reflect time
        self.duplicates_dropped: dict[str, int] = {}  # lockck: guard(_lock) — method -> count
        self.stale_views_rejected = 0  # lockck: guard(_lock)
        self.stale_view_reflections = 0  # lockck: guard(_lock)
        self.partitions_healed = 0  # lockck: guard(_lock)
        self.demotions = 0  # lockck: guard(_lock)
        self.rehomed_parts = 0  # lockck: guard(_lock)
        # Results whose at-least-once budget exhausted mid-partition wait
        # here for the next beat's re-offer (_flush_parked): a partition
        # longer than retries*delay degrades to a LATE delivery, not a
        # lost result.
        self._parked: list = []  # lockck: guard(_lock) — (peer, payload, first-try time)
        self.results_parked = 0  # lockck: guard(_lock)
        self.results_delivered_late = 0  # lockck: guard(_lock)
        # Cluster-scope observability (round 12, obs/): the node's own
        # mergeable wire-wall histograms (send = one egress through the
        # transport; ack = a result-bearing send's full at-least-once
        # round, retries included) — timed on the NODE clock, so the
        # simnet lane's numbers are virtual and deterministic — plus the
        # METRICS_PULL aggregation counters exported as cluster.agg.
        self._hist = {"send_ms": LatencyHistogram(), "ack_ms": LatencyHistogram()}
        # The DHT plane (ISSUE 17, cluster/dht/): gossip liveness, the
        # consistent-hash ring over the canonical digest space, and this
        # node's shard of the cluster-wide result cache.  The ring is
        # guarded by its own high-ranked lock (NOT the node lock): owner
        # lookups run on cache/front-door threads that may hold the
        # frontdoor locks, which rank above cluster.node.
        self.gossip: Optional[Gossip] = None
        self.ring: Optional[HashRing] = None
        self.dcache: Optional[ClusterCache] = None
        self._ring_lock = lockdep.named_lock("cluster.ring")  # lockck: name(cluster.ring)
        self.affinity_routed = 0  # lockck: guard(_lock) — submits sent to the digest owner
        self.affinity_declined = 0  # lockck: guard(_lock) — owner unhealthy/browning; local fallback
        if config.dht:
            suspicion = config.dht_suspicion_s or (
                config.heartbeat_s * config.fail_factor
            )
            self.gossip = Gossip(
                self.addr_s,
                self._clock.now,
                suspicion_s=suspicion,
                piggyback=config.dht_piggyback,
            )
            self.ring = HashRing(config.dht_vnodes)
            self.ring.add(self.addr_s)
            self.dcache = ClusterCache(
                self.addr_s,
                owner_fn=self._ring_owner,
                request_fn=self._dht_request,
                put_fn=self._dht_send,
                clock=self._clock,
                uuid_fn=lambda: str(uuid_mod.uuid4()),
                capacity=config.dht_cache_entries,
                get_timeout_s=config.dht_get_timeout_s
                or min(1.0, config.io_timeout_s),
                put_retries=config.send_retries,
                retry_delay_s=config.retry_delay_s,
            )
            # Wire the front door's L2 seam (router.py self.l2): L1
            # misses read through the cluster cache, fills replicate to
            # the digest owner.  No front door -> no seam (the node's
            # shard still serves CACHE_GET/CACHE_PUT for peers).
            fd = getattr(engine, "frontdoor", None)
            if fd is not None:
                fd.l2 = _L2Adapter(self.dcache)
        self.agg_pulls = 0  # lockck: guard(_lock) — peer METRICS_PULL requests issued
        self.agg_merges = 0  # lockck: guard(_lock) — cluster rollups computed
        self.agg_unreachable = 0  # lockck: guard(_lock) — pulls that found a peer unreachable
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ClusterNode":
        self._transport.serve(
            self._handle,
            on_error=self._log_bad_message,
            io_timeout=self.config.io_timeout_s,
        )
        t = threading.Thread(
            target=self._hb_loop, daemon=True, name=f"hb@{self.addr_s}"
        )
        t.start()
        self._threads.append(t)
        if self.anchor is not None:
            self._send(self.anchor, {"method": "JOIN_REQ", "addr": self.addr_s})
        return self

    def stop(self, graceful: bool = True) -> None:
        """Leave the ring (graceful drain analog of ``DHT_Node.stop``, :137-156)."""
        self._stop.set()
        if graceful and self.coordinator != self.addr_s:
            try:
                self._send(
                    self.coordinator,
                    {
                        "method": "LEAVE",
                        "addr": self.addr_s,
                        "term": self.net_term,
                        "epoch": self.net_epoch,
                    },
                )
            except WireError:
                pass
        self._transport.close()

    def kill(self) -> None:
        """Abrupt death for fault-injection tests: no LEAVE, just silence."""
        self.stop(graceful=False)

    # -- durable lifecycle (ISSUE 20) ----------------------------------------
    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful drain, cluster-aware: mark this member browning in the
        gossip plane (peers stop affinity-routing here immediately, ahead
        of the LEAVE), then walk the engine's lifecycle ladder with
        :meth:`_handoff_job` offered for every unstarted job — shipped to
        a gossip-healthy ring peer over the EXISTING TASK frame, so the
        receiving member needs no new wire surface.  Returns the engine's
        drain summary dict.  The caller still owns ``stop()``: drain
        quiesces the engine, it does not leave the ring."""
        if self.gossip is not None:
            self.gossip.set_brown(True)
        return self.engine.drain(timeout=timeout, handoff=self._handoff_job)

    def _handoff_job(self, job) -> bool:
        """Ship one detached (accepted, unstarted) job to a healthy peer
        during drain.  Placement mirrors submit(): the digest's ring owner
        when it is gossip-healthy and not us, else the least-outstanding
        healthy peer.  False (journal for restart instead) when no healthy
        peer exists or the send fails — handoff is an optimization over
        the WAL, never a second source of truth."""
        if job.grid is None:
            return False
        with self._lock:
            peers = [m for m in self.network if m != self.addr_s]
        peers = [
            m
            for m in peers
            if self.gossip is None or self.gossip.is_healthy(m)
        ]
        if not peers:
            return False
        target = None
        if (
            self.dcache is not None
            and self.config.dht_affinity
            and getattr(self.engine, "frontdoor", None) is not None
        ):
            owner = self._affinity_owner(job.grid)
            if owner is not None and owner != self.addr_s:
                target = owner
        if target is None:
            with self._lock:
                target = min(
                    (self._outstanding.get(m, 0), m) for m in peers
                )[1]
        cfg_dict = (
            dataclasses.asdict(job.config) if job.config is not None else None
        )
        payload = {
            "method": "TASK",
            "uuid": job.uuid,
            "grid": np.asarray(job.grid).tolist(),
            "origin": self.addr_s,
            "config": cfg_dict,
        }
        if trace.active() is not None:
            payload["trace"] = job.uuid
        try:
            self._send(target, payload)
        except WireError:
            return False
        self._track(target, +1)
        rec = trace.active()
        if rec is not None:
            rec.event(
                str(job.uuid), "drain.handoff", "engine.lifecycle",
                node=self.addr_s, member=target,
            )
        return True

    def recover(self) -> int:
        """Replay the WAL through the engine's normal submit seam
        (``SolverEngine.recover``) — called by the CLI after a restart
        rejoins the ring, so replayed jobs route exactly like fresh ones."""
        return self.engine.recover()

    # -- ring derivation -----------------------------------------------------
    def _ring(self) -> tuple[Optional[str], Optional[str]]:
        with self._lock:
            if len(self.network) < 2 or self.addr_s not in self.network:
                return None, None
            i = self.network.index(self.addr_s)
            pred = self.network[(i - 1) % len(self.network)]
            succ = self.network[(i + 1) % len(self.network)]
            return pred, succ

    # -- wire egress ---------------------------------------------------------
    def _send(self, peer, payload: dict) -> None:
        """The node's single wire-egress seam: every outbound cluster
        message leaves through here (all egress shares ``io_timeout_s``),
        so the fault-injection plane (``serving/faults.py``) can fail sends
        deterministically and the existing WireError recovery paths —
        ledger re-execution, part re-entry/local fallback, heartbeat
        suspicion — are exercised end to end.  ``peer`` is an addr string
        or a parsed ``Addr``.  An injected fault surfaces as
        :class:`WireError` whatever its class: to the *sender*, any failed
        send is just an undeliverable message, and the re-dispatch
        machinery (not this seam) owns the classification."""
        if faults.active() is not None:  # skip uuid extraction in production
            try:
                faults.fire(
                    "cluster.send",
                    uuids=tuple(
                        str(payload[k])
                        for k in ("uuid", "part")
                        if payload.get(k) is not None
                    ),
                )
            except faults.SimulatedFault as e:
                raise WireError(f"injected send fault: {e}") from e
        rec = trace.active()
        if rec is not None:
            # Wire-egress span for uuid-bearing frames only (heartbeats and
            # membership noise would drown the job spans); recorded BEFORE
            # the transport call so dropped sends still show in the trace.
            tid = payload.get("trace") or payload.get("uuid") or payload.get("part")
            if tid is not None:
                rec.event(
                    str(tid), f"send.{payload.get('method')}", "cluster.send",
                    node=self.addr_s,
                    peer=peer if isinstance(peer, str) else addr_str(peer),
                )
        addr = peer if isinstance(peer, tuple) else wire.parse_addr(peer)
        t0 = self._clock.now()
        self._transport.send(addr, payload, self.config.io_timeout_s)
        # Wire egress wall (mergeable, obs/hist.py): successful sends only
        # — a failed send's wall measures the failure mode, not the link.
        self._hist["send_ms"].record(self._clock.now() - t0)

    def _send_result(self, peer, payload: dict) -> bool:
        """At-least-once delivery for result-bearing messages (SOLUTION,
        PART_RESULT): a failed send is retried under a small bounded budget
        — an *ambiguous* failure (bytes written, then reset: see
        ``WireError.ambiguous_delivery``) may already have been delivered,
        so the receiver dedupes these methods by uuid (``_handle``); a
        lost-for-sure failure (connect refused/timed out) retries are what
        carry a result through a transient link fault at all.  Returns
        False when every attempt failed — the result is then PARKED and
        re-offered once per beat (``_flush_parked``) until the link heals
        or the origin stays gone past the tombstone horizon: a partition
        that outlives the retry budget must degrade to a late delivery
        (origin dedupes), never a lost result."""
        last: Optional[WireError] = None
        t0 = self._clock.now()
        for attempt in range(self.config.send_retries + 1):
            if attempt:
                self._clock.sleep(self.config.retry_delay_s)
                if self._stop.is_set():
                    return False
            try:
                self._send(peer, payload)
                # The ack wall: first attempt -> delivered, retry pacing
                # included — what a result actually pays to land.
                self._hist["ack_ms"].record(self._clock.now() - t0)
                return True
            except WireError as e:
                last = e
        if not self._stop.is_set():
            with self._lock:
                self.results_parked += 1
                self._parked.append((peer, payload, t0))
            _LOG.warning(
                "[%s] %s to %s undeliverable after %d attempts "
                "(uuid=%s): %r — parked for per-beat re-delivery",
                self.addr_s, payload.get("method"), peer,
                self.config.send_retries + 1,
                payload.get("uuid") or payload.get("part"), last,
            )
        return False

    def _flush_parked(self) -> None:
        """One re-delivery attempt per parked result (off the heartbeat
        thread: a wedged TCP connect must not starve the failure
        detector).  Items are swapped out under the lock so overlapping
        flushes never double-send; still-failing items re-park; items
        older than the tombstone horizon are dropped — by then the
        origin's own repair (ledger re-execution) owns the job."""
        now = self._clock.now()
        with self._lock:
            batch = self._parked
            self._parked = []
        keep = []
        for peer, payload, t0 in batch:
            if now - t0 > self.config.tombstone_probe_s:
                continue
            try:
                self._send(peer, payload)
                with self._lock:
                    self.results_delivered_late += 1
            except WireError:
                keep.append((peer, payload, t0))
        if keep:
            with self._lock:
                self._parked = keep + self._parked

    def _log_bad_message(self, e: BaseException) -> None:
        """Transport's handler-error sink: malformed or interrupted control
        traffic is logged-and-dropped (RuntimeError covers "engine stopped"
        during teardown; the catch is any Exception — arbitrary network
        input must never kill a serving thread or wedge a loop);
        reliability comes from sender-side errors, not server retries."""
        if not self._stop.is_set():
            _LOG.error(
                "[%s] bad message: %r [%s]", self.addr_s, e, faults.classify(e)
            )

    # -- background loops ----------------------------------------------------
    def _hb_loop(self) -> None:
        while not self._stop.is_set():
            self._clock.sleep(self.config.heartbeat_s)
            if self._stop.is_set():
                return
            with self._lock:
                is_coord = self.coordinator == self.addr_s
                have_tombstones = bool(self._evicted)
                orphaned = (
                    not is_coord and self.addr_s not in self.network
                    and len(self.network) > 0
                )
                coord = self.coordinator
                term, epoch = self.net_term, self.net_epoch
            # Coordinator re-broadcasts the view every beat: a member that
            # missed an UPDATE_NETWORK (send failure is fire-and-forget)
            # converges on the next beat instead of never.  Off-thread, so a
            # partitioned member's connect timeout cannot delay our own
            # heartbeats past the failure threshold.  Tombstoned (evicted
            # but possibly alive) members are probed with the same payload
            # — the split-brain heal channel — so the broadcast also runs
            # when the view has shrunk to just us.
            if is_coord and (have_tombstones or len(self.network) > 1):
                threading.Thread(
                    target=self._broadcast_send,
                    args=(self._broadcast_plan(),),
                    daemon=True,
                ).start()
            if orphaned:
                # Evicted from the view (false death / lost partition) and
                # the immediate rejoin in _on_update_network was lost:
                # retry every beat until a view contains us again.
                try:
                    self._send(coord, {"method": "JOIN_REQ", "addr": self.addr_s})
                except WireError:
                    pass
            # Deadline-based part re-homing must tick even for a solo node
            # (ring of one): it recovers work from wedged-but-alive peers
            # that are no longer in the view at all.
            if self.config.part_deadline_s > 0:
                self._recover_parts()
            # Parked results (at-least-once budget exhausted mid-partition)
            # get one re-offer per beat, off-thread.
            with self._lock:
                have_parked = bool(self._parked)
            if have_parked:
                threading.Thread(target=self._flush_parked, daemon=True).start()
            # SWIM beat (runs even solo/orphaned: suspicion expiry must
            # tick and a lone node's tick is a cheap no-probe).
            if self.gossip is not None:
                self._gossip_beat(term, epoch)
            pred, succ = self._ring()
            if succ is None:
                with self._lock:
                    self._last_hb = self._clock.now()
                continue
            try:
                self._send(
                    succ,
                    {
                        "method": "HEARTBEAT",
                        "from": self.addr_s,
                        "term": term,
                        "epoch": epoch,
                    },
                )
            except WireError:
                pass  # successor's own detector handles its death
            # Receiver-initiated stealing (``DHT_Node.py:246-248``): idle ->
            # ask my ring predecessor for a slice of a live search.
            if self.config.needwork and self.engine.busy_depth() == 0:
                try:
                    self._send(pred, {"method": "NEEDWORK", "addr": self.addr_s})
                except WireError:
                    pass
            limit = self.config.heartbeat_s * self.config.fail_factor
            with self._lock:
                expired = self._clock.now() - self._last_hb > limit
            if expired and pred is not None:
                self._on_peer_dead(pred)

    # -- message handling ----------------------------------------------------

    # Result/work-bearing one-shot methods and the field that identifies
    # the unit of work: duplicates (at-least-once redelivery) are dropped
    # here, before any handler runs, so a re-dispatch whose first copy DID
    # arrive executes once (duplicates_dropped counts the drops).
    _DEDUPE_KEYS = {
        "TASK": "uuid",
        "SOLUTION": "uuid",
        "SUBTASK": "part",
        "PART_RESULT": "part",
        # Cluster-cache fills are at-least-once (ClusterCache._put_loop
        # retries with the same uuid); a redelivered PUT is idempotent
        # anyway (deterministic solver), so the dedupe here exists to
        # keep puts_applied/insertions honest, not for correctness.
        "CACHE_PUT": "uuid",
    }

    @staticmethod
    def _addr_field(msg: dict, key: str) -> str:
        """Validated member-address field: membership handlers must never
        install non-address garbage into the view (a fuzzer's int joiner
        would poison ring math and every later dial)."""
        v = msg.get(key)
        if not isinstance(v, str) or ":" not in v:
            raise WireError(f"malformed {key!r} field: {v!r}")
        return v

    def _handle(self, msg: dict) -> Optional[dict]:
        """Dispatch one inbound message; returns the reply dict for
        request/reply methods (STATS_REQ), else None.  Raises on malformed
        input — the transport routes that to _log_bad_message."""
        method = msg["method"]
        dkey = self._DEDUPE_KEYS.get(method)
        if dkey is not None:
            uid = msg.get(dkey)
            if uid is not None and self._dedupe.seen((method, str(uid))):
                self._count_duplicate(method)
                return None
        if method == "JOIN_REQ":
            self._on_join_req(self._addr_field(msg, "addr"))
        elif method == "UPDATE_NETWORK":
            self._on_update_network(msg)
        elif method == "HEARTBEAT":
            self._on_heartbeat(msg)
        elif method == "NODE_FAILED":
            self._on_node_failed(
                self._addr_field(msg, "addr"),
                reporter_term=msg.get("term"),
                method="NODE_FAILED",
            )
        elif method == "LEAVE":
            # Same repair path, no suspicion (the member *chose* to go, so
            # no tombstone probing either) — and a leaver's intent is honored
            # whatever view version it held, unlike a failure *verdict*.
            self._on_node_failed(
                self._addr_field(msg, "addr"), suspected=False, method="LEAVE"
            )
        elif method == "TASK":
            self._on_task(msg)
        elif method == "SOLUTION":
            self._on_solution(msg)
        elif method == "CANCEL":
            self._on_cancel(msg["uuid"])
        elif method == "NEEDWORK":
            self._on_needwork(self._addr_field(msg, "addr"))
        elif method == "SUBTASK":
            self._on_subtask(msg)
        elif method == "PART_RESULT":
            self._on_part_result(msg)
        elif method == "PROGRESS":
            self._on_progress(msg)
        elif method == "PROBE":
            return self._on_probe(msg)
        elif method == "CACHE_GET":
            if self.dcache is None:
                return {"found": False, "entry": None}
            return self.dcache.handle_get(msg)
        elif method == "CACHE_PUT":
            if self.dcache is not None:
                self.dcache.handle_put(msg)
        elif method == "STATS_REQ":
            s = self.engine.stats()
            return {
                "method": "STATS_RES",
                "address": self.addr_s,
                "validations": s["validations"],
                "solved": s["solved"],
            }
        elif method == "METRICS_PULL":
            return self._on_metrics_pull(msg)
        else:
            _LOG.warning("[%s] unknown method %r", self.addr_s, method)
        return None

    def _count_duplicate(self, method: str) -> None:
        with self._lock:
            self.duplicates_dropped[method] = (
                self.duplicates_dropped.get(method, 0) + 1
            )
        _LOG.info("[%s] duplicate %s dropped", self.addr_s, method)

    def _on_metrics_pull(self, msg: dict) -> dict:
        """One member's half of ``GET /metrics?scope=cluster``: reply with
        the local metrics body plus our view version — the puller marks
        us ``stale`` when the versions disagree.  (term, epoch)-guarded
        like HEARTBEAT: a pull asserting a strictly older term is counted
        and gets our view reflected back (rate-limited) so a split-brain
        survivor aggregating its losing ring learns the winner — but the
        reply still carries honest data; staleness is the PULLER's flag
        to surface, not a reason to go dark."""
        term = msg.get("term")
        sender = msg.get("from")
        reflect_to = None
        with self._lock:
            if term is not None and int(term) < self.net_term:
                self.stale_views_rejected += 1
                if isinstance(sender, str) and ":" in sender:
                    reflect_to = self._reflect_ok_locked(sender)
            t, e = self.net_term, self.net_epoch
        if reflect_to:
            self._reflect_view(reflect_to)
        return {
            "method": "METRICS_RES",
            "address": self.addr_s,
            "term": t,
            "epoch": e,
            "metrics": self.metrics_view(),
        }

    def _on_heartbeat(self, msg: dict) -> None:
        """A heartbeat refreshes the failure detector — unless its sender
        holds a strictly older *term*: a pre-partition coordinator's ring
        mate must not suppress detection in the healed, promoted ring.  The
        stale sender gets our view reflected back (rate-limited) so it can
        demote/rejoin — membership-bearing messages all carry the
        (term, epoch) guard now, not just UPDATE_NETWORK."""
        term = msg.get("term")
        sender = msg.get("from")
        reflect_to = None
        with self._lock:
            if term is not None and int(term) < self.net_term:
                self.stale_views_rejected += 1
                if isinstance(sender, str) and ":" in sender:
                    reflect_to = self._reflect_ok_locked(sender)
            else:
                self._last_hb = self._clock.now()
        if reflect_to:
            self._reflect_view(reflect_to)

    # -- membership ----------------------------------------------------------
    def _broadcast_plan(self) -> tuple:
        """Snapshot the view payload and target list NOW, in the caller's
        thread.  The per-beat re-broadcast must carry the view as of the
        beat: a split-brain loser demoted between spawning its sender
        thread and the thread reading state would otherwise echo the
        winner's view instead of offering its stale one for rejection —
        the offer/reject/reflect exchange IS the heal channel."""
        now = self._clock.now()
        with self._lock:
            members = list(self.network)
            payload = {
                "method": "UPDATE_NETWORK",
                "network": members,
                "coordinator": self.coordinator,
                "term": self.net_term,
                "epoch": self.net_epoch,
                "from": self.addr_s,
            }
            # Tombstone probes: keep offering the winning view to members
            # we evicted on suspicion — a false-death or partition survivor
            # rejoins (or, if it is a rival coordinator with a HIGHER view,
            # rejects this as stale and reflects its view back, which
            # demotes us).  Expired tombstones stop being dialed.
            expired = [
                m
                for m, t in self._evicted.items()
                if now - t > self.config.tombstone_probe_s
            ]
            for m in expired:
                del self._evicted[m]
            probes = [m for m in self._evicted if m not in members]
        return payload, [m for m in members + probes if m != self.addr_s]

    def _broadcast_send(self, plan: tuple) -> None:
        payload, targets = plan
        for m in targets:
            try:
                self._send(m, payload)
            except WireError:
                pass  # its detector will notice soon enough

    def _broadcast_network(self) -> None:
        self._broadcast_send(self._broadcast_plan())

    def _on_join_req(self, joiner: str) -> None:
        if self.coordinator != self.addr_s:
            self._send(self.coordinator, {"method": "JOIN_REQ", "addr": joiner})
            return
        with self._lock:
            healed = self._evicted.pop(joiner, None) is not None
            if healed:
                # An evicted-but-alive member came back through the winner:
                # the observable end of a partition (or false death).
                self.partitions_healed += 1
            duplicate = joiner in self.network
            if not duplicate:
                self.network.append(joiner)
                self.net_epoch += 1
            self._last_hb = self._clock.now()
        if duplicate:
            # Idempotent replay: no epoch bump, no broadcast storm — the
            # per-beat view re-broadcast covers a joiner that missed ours.
            self._count_duplicate("JOIN_REQ")
            return
        self._dht_sync()
        self._broadcast_network()

    def _on_update_network(self, msg: dict) -> None:
        raw = msg["network"]
        if not isinstance(raw, list) or not all(
            isinstance(m, str) and ":" in m for m in raw
        ):
            raise WireError(f"malformed network field: {raw!r}")
        network = list(raw)
        coordinator = self._addr_field(msg, "coordinator")
        term, epoch = int(msg["term"]), int(msg["epoch"])
        sender = msg.get("from")
        rejoin = False
        reflect_to = None
        concede_to = None
        concede_payload = None
        gone: list = []
        with self._lock:
            if (term, epoch) <= (self.net_term, self.net_epoch):
                # Stale or duplicate view; ours is at least as new.  An
                # *equal* version is the steady-state per-beat re-broadcast;
                # a strictly older one is rejected loudly — and when it
                # comes from a rival coordinator (split-brain survivor
                # still broadcasting its losing view), our view is
                # reflected back so the loser can demote and rejoin.
                if (term, epoch) < (self.net_term, self.net_epoch):
                    self.stale_views_rejected += 1
                    if (
                        coordinator != self.coordinator
                        and isinstance(sender, str)
                        and ":" in sender
                    ):
                        reflect_to = self._reflect_ok_locked(sender)
                if reflect_to is None:
                    return
            else:
                if (
                    self.coordinator == self.addr_s
                    and coordinator != self.addr_s
                    and (self.net_term, self.net_epoch) > (0, 0)
                ):
                    # Split-brain resolution, losing side: someone holds a
                    # provably newer view in which we are not coordinator.
                    # Install it, stand down, and (below) rejoin if evicted
                    # — our in-flight work re-homes through the ordinary
                    # orphan paths against the new view.  A fresh node
                    # installing its anchor's first view is NOT a demotion
                    # (it was only ever coordinator of itself: (0,0) —
                    # a node that has issued no membership change).
                    self.demotions += 1
                    _LOG.warning(
                        "[%s] demoted: installing view (%d,%d) from %s "
                        "(ours was (%d,%d))",
                        self.addr_s, term, epoch, coordinator,
                        self.net_term, self.net_epoch,
                    )
                    # Concession: announce the superseded view to the
                    # winner as the last act of this coordinatorship.  The
                    # winner rejects it as stale, which leaves a durable
                    # record of the rivalry in ITS fault counters no matter
                    # which heal channel fired first (its tombstone probe
                    # teaching us, or our stale offer being reflected) —
                    # without this, a probe-first heal ends with neither
                    # side's stale_views_rejected showing a split-brain
                    # ever happened.
                    concede_to = coordinator
                    concede_payload = {
                        "method": "UPDATE_NETWORK",
                        "network": list(self.network),
                        "coordinator": self.coordinator,
                        "term": self.net_term,
                        "epoch": self.net_epoch,
                        "from": self.addr_s,
                    }
                    self._evicted.clear()  # no longer the membership authority
                self.network = network
                self.coordinator = coordinator
                self.net_term = term
                self.net_epoch = epoch
                self._last_hb = self._clock.now()
                # Evicted by a false death verdict (e.g. my heartbeats
                # starved): re-join through the coordinator rather than
                # orbiting alone.
                rejoin = self.addr_s not in network and not self._stop.is_set()
                # Only an INSTALLED view may drive re-execution — a ledger
                # scan against a rejected stale list would re-run jobs
                # whose members are perfectly alive in ours.
                gone = [
                    u
                    for u, e in self._ledger.items()
                    if e["member"] not in network
                ]
        if reflect_to:
            self._reflect_view(reflect_to)
            return
        if concede_to is not None:
            try:
                self._send(concede_to, concede_payload)
            except WireError:
                pass  # observability-only: the demotion itself is done
        self._dht_sync()
        for u in gone:
            self._reexecute(u)
        self._recover_parts()
        if rejoin:
            try:
                self._send(
                    coordinator, {"method": "JOIN_REQ", "addr": self.addr_s}
                )
            except WireError:
                pass  # retried every beat by _hb_loop while orphaned

    def _reflect_ok_locked(self, peer: str) -> Optional[str]:
        """Rate-limit stale-view reflections to one per peer per heartbeat
        (caller holds the lock); returns the peer when a reflection is due."""
        now = self._clock.now()
        if now < self._reflect_at.get(peer, 0.0):
            return None
        self._reflect_at[peer] = now + self.config.heartbeat_s
        self.stale_view_reflections += 1
        return peer

    def _reflect_view(self, peer: str) -> None:
        """Send our (newer) view to a peer that just asserted an older one —
        the anti-entropy half of split-brain healing."""
        with self._lock:
            payload = {
                "method": "UPDATE_NETWORK",
                "network": list(self.network),
                "coordinator": self.coordinator,
                "term": self.net_term,
                "epoch": self.net_epoch,
                "from": self.addr_s,
            }
        try:
            self._send(peer, payload)
        except WireError:
            pass

    def _on_node_failed(
        self,
        dead: str,
        suspected: bool = True,
        method: str = "NODE_FAILED",
        reporter_term=None,
    ) -> None:
        if dead == self.addr_s:
            # A frame naming US dead (forged, or a detector whose view is
            # hopelessly behind) must not make the node evict itself from
            # its own view; if the rest of the ring really thinks we died,
            # their next UPDATE_NETWORK triggers the rejoin path instead.
            _LOG.warning("[%s] ignoring %s naming this node", self.addr_s, method)
            return
        if self.coordinator == self.addr_s:
            with self._lock:
                if reporter_term is not None and int(reporter_term) < self.net_term:
                    # A death verdict formed under a superseded term: the
                    # reporter is behind a promotion (possibly ours); its
                    # suspicion predates the current ring and is void.
                    self.stale_views_rejected += 1
                    return
                if dead not in self.network:
                    # Already removed (duplicate report, replayed LEAVE):
                    # idempotent — no epoch bump, no broadcast storm.
                    self._count_duplicate_locked(method)
                    return
                self.network.remove(dead)
                self.net_epoch += 1
                if suspected:
                    # Keep probing: the "death" may be a partition, and the
                    # probe is how the survivor finds its way back.
                    self._evicted[dead] = self._clock.now()
                self._last_hb = self._clock.now()
                gone = [
                    u
                    for u, e in self._ledger.items()
                    if e["member"] not in self.network
                ]
            self._dht_sync()
            self._broadcast_network()
            for u in gone:
                self._reexecute(u)
            self._recover_parts()
        else:
            try:
                self._send(
                    self.coordinator,
                    {
                        "method": "NODE_FAILED",
                        "addr": dead,
                        "term": self.net_term,
                        "epoch": self.net_epoch,
                    },
                )
            except WireError:
                pass

    def _count_duplicate_locked(self, method: str) -> None:
        self.duplicates_dropped[method] = (
            self.duplicates_dropped.get(method, 0) + 1
        )

    def _on_peer_dead(self, dead: str) -> None:
        """My predecessor went silent (``check_neighbor`` analog, :158-209)."""
        with self._lock:
            if dead not in self.network:
                return
            if dead == self.coordinator:
                # I am the unique detector of the coordinator: self-promote
                # (``DHT_Node.py:191-193``).  A new term outranks every view
                # the dead coordinator issued, including epochs we missed.
                self.coordinator = self.addr_s
                self.net_term += 1
            self._last_hb = self._clock.now()
        self._on_node_failed(dead)

    # -- the DHT plane (ISSUE 17: cluster/dht/) ------------------------------
    def _dht_sync(self) -> None:
        """Reconcile gossip + ring with the authoritative (term,epoch)
        view.  Called after every installed membership change; the view
        advance doubles as the refutation channel for restarted members
        whose incarnation reset (membership.py reconcile note)."""
        if self.gossip is None:
            return
        with self._lock:
            members = list(self.network)
        self.gossip.reconcile(members)
        with self._ring_lock:
            want = set(members) | {self.addr_s}
            for m in self.ring.members():
                if m not in want:
                    self.ring.remove(m)
            for m in want:
                if m not in self.ring:
                    self.ring.add(m)

    def _ring_owner(self, digest: str) -> Optional[str]:
        """The cluster cache's owner_fn.  Runs on submit / device-loop /
        front-door threads — guarded by the ring's own high-ranked lock,
        never the node lock (frontdoor locks rank above cluster.node)."""
        if self.ring is None:
            return None
        with self._ring_lock:
            return self.ring.owner(digest)

    def _dht_request(self, peer: str, frame: dict, timeout: float) -> dict:
        """CACHE_GET request/reply (short deadline; a WireError is just
        a cache miss to the caller)."""
        return self._transport.request(wire.parse_addr(peer), frame, timeout)

    def _dht_send(self, peer: str, frame: dict) -> None:
        """CACHE_PUT egress: through the node's one egress seam so the
        fault plane, trace spans, and send-wall histogram all see it."""
        self._send(peer, frame)

    def _gossip_beat(self, term: int, epoch: int) -> None:
        """One SWIM beat: expire suspicions, probe one member with
        piggybacked state, merge the ack's piggyback.  O(1) traffic per
        beat regardless of ring size — the whole point."""
        g = self.gossip
        if g is None:
            return
        ctrl = brownout_mod.active()
        if ctrl is not None:
            # Self-report brownout on the piggyback: browning owners
            # decline cache-affine forwards at the REQUESTER, wire-free.
            g.set_brown(ctrl.stage() > 0)
        target, newly_dead = g.tick()
        for m in newly_dead:
            # Suspicion expired unrefuted: feed the existing eviction
            # machinery (coordinator evicts + tombstones; a non-
            # coordinator forwards NODE_FAILED with its term).
            self._on_node_failed(m)
        if target is None:
            return
        timeout = self.config.dht_probe_timeout_s or min(
            self.config.stats_timeout_s, self.config.heartbeat_s
        )
        payload = {
            "method": "PROBE",
            "from": self.addr_s,
            "term": term,
            "epoch": epoch,
            "updates": g.updates(),
        }
        try:
            reply = self._transport.request(
                wire.parse_addr(target), payload, timeout
            )
        except WireError:
            g.on_probe_fail(target)
            return
        g.on_ack(target)
        if isinstance(reply, dict):
            ups = reply.get("updates")
            if isinstance(ups, list):
                g.merge(ups)

    def _on_probe(self, msg: dict) -> dict:
        """PROBE handler: merge the sender's piggyback, answer with ours.
        (term,epoch)-guarded like HEARTBEAT — a probe asserting a stale
        term gets the view reflected back (rate-limited) instead of its
        gossip being trusted."""
        g = self.gossip
        if g is None:
            return {"method": "PROBE_ACK", "from": self.addr_s, "updates": []}
        term = msg.get("term")
        sender = msg.get("from")
        reflect_to = None
        with self._lock:
            if term is not None and int(term) < self.net_term:
                self.stale_views_rejected += 1
                if isinstance(sender, str) and ":" in sender:
                    reflect_to = self._reflect_ok_locked(sender)
        if reflect_to:
            self._reflect_view(reflect_to)
        elif isinstance(msg.get("updates"), list):
            g.merge(msg["updates"])
        return {"method": "PROBE_ACK", "from": self.addr_s, "updates": g.updates()}

    def _affinity_owner(self, g: np.ndarray) -> Optional[str]:
        """Cache-affine placement for submit(): the digest owner when it
        is gossip-healthy (ALIVE, not browning), else None (fall back to
        least-outstanding).  Only consulted when this node runs a front
        door — without one there is no cache to be affine to."""
        try:
            geom = geometry_for_size(g.shape[0])
            cf = fd_canon.canonicalize(g, geom)
        except Exception:
            return None  # malformed / uncanonicalizable: ordinary path
        if cf is None:
            return None
        owner = self._ring_owner(cf.digest)
        if owner is None:
            return None
        if owner != self.addr_s and (
            self.gossip is None or not self.gossip.is_healthy(owner)
        ):
            with self._lock:
                self.affinity_declined += 1
            return None
        with self._lock:
            self.affinity_routed += 1
        return owner

    def dht_view(self, owner_of: Optional[str] = None) -> dict:
        """``GET /network?scope=dht``: the gossip view (states,
        incarnations, brownout flags), ring ownership summary, and this
        node's shard counters; ``owner_of`` adds a digest's owner and
        replica set."""
        with self._lock:
            coord = self.coordinator
            view = [self.net_term, self.net_epoch]
        with self._ring_lock:
            ring = self.ring.summary()
            owner = self.ring.owner(owner_of) if owner_of else None
            replicas = self.ring.replicas(owner_of, 2) if owner_of else None
        out = {
            "address": self.addr_s,
            "coordinator": coord,
            "view": view,
            "members": self.gossip.view(),
            "ring": ring,
            "cluster_cache": self.dcache.metrics(),
        }
        if owner_of:
            out["owner"] = {
                "digest": owner_of,
                "owner": owner,
                "replicas": replicas,
            }
        return out

    # -- local execution (engine + shed parts) -------------------------------
    def _start_exec(
        self,
        on_final: Callable[[dict], None],
        grid: Optional[np.ndarray] = None,
        roots: Optional[np.ndarray] = None,
        geom=None,
        job_uuid: Optional[str] = None,
        base_nodes: int = 0,
        config=None,
        saturation: str = "fallback",
        latency: Optional[bool] = None,
    ) -> _Exec:
        """Run a job (or subtree part) on the local engine under an _Exec
        aggregate; ``on_final`` fires exactly once with the merged result.

        ``saturation`` is forwarded to ``engine.submit`` for grid jobs:
        client-facing dispatches (the HTTP ``/solve`` path through
        :meth:`submit`) pass ``'reject'`` so a saturated resident flight
        backpressures with 429 + Retry-After; internal re-dispatches
        (peer TASKs, failure re-execution, shed parts) keep the quiet
        static-flight fallback — work already accepted by the cluster must
        never bounce."""
        if roots is not None:
            ej = self.engine.submit_roots(
                roots, geom, job_uuid=job_uuid, config=config
            )
        else:
            ej = self.engine.submit(
                grid, job_uuid=job_uuid, config=config, saturation=saturation,
                latency=latency,
            )

        def wrapped(result: dict) -> None:
            with self._lock:
                self._execs.pop(ej.uuid, None)
            on_final(result)

        ex = _Exec(self, ej, wrapped, base_nodes=base_nodes)
        with self._lock:
            self._execs[ej.uuid] = ex
        return ex

    def _apply_result(self, handle: Job, r: dict) -> None:
        handle.solved = bool(r["solved"])
        handle.unsat = bool(r["unsat"])
        handle.nodes = int(r["nodes"])
        handle.cancelled = bool(r["cancelled"])
        handle.error = r["error"]
        if r["solution"] is not None:
            handle.solution = np.asarray(r["solution"], dtype=np.int32)
        handle.done.set()

    def _send_cancel(self, peer: str, job_uuid: str) -> None:
        if peer == self.addr_s:
            self._on_cancel(job_uuid)
            return
        try:
            self._send(peer, {"method": "CANCEL", "uuid": job_uuid})
        except WireError:
            pass

    def _on_cancel(self, job_uuid: str) -> None:
        self.engine.cancel(job_uuid)
        with self._lock:
            parts = [p for p, root in self._parts.items() if root == job_uuid]
        for p in parts:
            self.engine.cancel(p)

    # -- job dispatch --------------------------------------------------------
    def submit(self, grid, config=None, latency=None, job_uuid=None) -> Job:
        """Dispatch one job to the least-loaded member; ``config`` optionally
        overrides the solver strategy for this job (rides the TASK).

        ``latency`` opts a LOCAL dispatch into the engine's megastep tier
        (serving/megastep.py).  The flag deliberately does not ride the
        wire: latency-mode is a node-local serving decision — a member
        serves remote TASKs by its own engine's ``latency_mode`` default.

        ``job_uuid`` is the OPTIONAL client-supplied idempotency key
        (ISSUE 20): a resubmit of an in-flight or resolved job returns the
        existing handle — same verdict, no double solve, no double
        stats — and the uuid keys the WAL entry, so a client retrying a
        504 after a crash-restart dedupes against the replayed job."""
        g = np.asarray(grid, dtype=np.int32)
        if g.ndim != 2 or g.shape[0] != g.shape[1]:
            raise ValueError(f"grid must be square, got {g.shape}")
        if job_uuid is not None:
            with self._lock:
                prev = self._client_jobs.get(job_uuid)
                if (
                    prev is not None
                    and prev.done.is_set()
                    and prev.error is not None
                ):
                    # Infra-error terminal: evict so the retry runs fresh.
                    self._client_jobs.pop(job_uuid, None)
                    prev = None
            if prev is not None:
                return prev
        member = None
        if (
            self.dcache is not None
            and self.config.dht_affinity
            and getattr(self.engine, "frontdoor", None) is not None
        ):
            # Cache-affine routing (ISSUE 17): a cacheable board goes to
            # its canonical digest's ring owner — where the cluster-cache
            # entry lives or will live — when that owner is gossip-ALIVE
            # and not browning.  Unhealthy/browning owner: the requester
            # keeps the job (brownout-aware decline, solved locally).
            member = self._affinity_owner(g)
        if member is None:
            member = self._pick_member()
        if member == self.addr_s:
            # Client-facing dispatch: a saturated local resident flight
            # rejects (EngineSaturated -> HTTP 429 + Retry-After) instead
            # of quietly growing an unbounded queue.  Remote dispatch has
            # no cross-wire backpressure: the TASK lands in the member's
            # static path if its resident flight is full.
            handle = self._submit_local(
                g, config=config, saturation="reject", latency=latency,
                job_uuid=job_uuid,
            )
        else:
            handle = self._submit_remote(
                g, member, config=config, job_uuid=job_uuid
            )
        if job_uuid is not None:
            with self._lock:
                self._client_jobs[job_uuid] = handle
                while len(self._client_jobs) > 8192:
                    self._client_jobs.pop(next(iter(self._client_jobs)))
        return handle

    def race(self, grid, configs, timeout: Optional[float] = None):
        """Cluster-level portfolio: one racer per config, spread over the
        least-loaded members; the first verdict cancels every other racer
        (local purge + CANCEL to its executing member and any shed parts).

        The fleet analog of ``serving/portfolio.race`` — where the reference
        could only ever run its one recursive strategy per ring, a job here
        races heterogeneous strategies across *machines*, SOLUTION-style
        first-win cancellation included (``/root/reference/DHT_Node.py:
        348-387``).
        """
        from distributed_sudoku_solver_tpu.serving.portfolio import race_jobs

        if not configs:
            raise ValueError("portfolio needs at least one config")
        # Clock starts before the (blocking, wire-bound) submissions so the
        # caller's timeout bounds the whole race, not just the wait.  This
        # is deliberately the WALL clock, not self._clock: `start` must be
        # a reading of race_jobs' own (default, real-monotonic) clock —
        # racer engines and their done-events live outside the virtual
        # clock even under simnet, so a virtual `start` would corrupt the
        # deadline math.
        start = time.monotonic()  # clockck: allow(race deadline shares race_jobs' wall clock; the node clock may be virtual while racer engines are wall-bound)
        jobs = []
        try:
            for cfg in configs:
                jobs.append(self.submit(grid, config=cfg))
        except BaseException:
            for j in jobs:  # don't strand racers already placed on members
                self.cancel(j.uuid)
            raise
        res = race_jobs(jobs, cancel=self.cancel, timeout=timeout, start=start)
        if res.winner is not None:
            res.strategy = configs[res.winner_index].branch
        return res

    def cancel(self, job_uuid: str) -> None:
        self._on_cancel(job_uuid)
        with self._lock:
            entry = self._ledger.get(job_uuid)
        if entry is not None:
            self._send_cancel(entry["member"], job_uuid)

    def _pick_member(self) -> str:
        """Least-outstanding member; ties broken round-robin (load balance)."""
        with self._lock:
            members = list(self.network)
            if len(members) == 1:
                return self.addr_s
            self._rr += 1
            counts = [
                (self._outstanding.get(m, 0), (i + self._rr) % len(members), m)
                for i, m in enumerate(members)
            ]
        return min(counts)[2]

    def _track(self, member: str, delta: int) -> None:
        with self._lock:
            self._outstanding[member] = self._outstanding.get(member, 0) + delta

    def _submit_local(
        self, g: np.ndarray, config=None, saturation: str = "fallback",
        latency=None, job_uuid=None,
    ) -> Job:
        geom = geometry_for_size(g.shape[0])
        # A client-supplied uuid IS the job uuid end to end: it keys the
        # engine's resubmit registry and the WAL entry, so dedupe and
        # crash replay line up with what the client will retry with.
        ju = job_uuid if job_uuid is not None else str(uuid_mod.uuid4())
        handle = Job(uuid=ju, grid=g, geom=geom)
        self._track(self.addr_s, +1)

        def fin(r: dict) -> None:
            self._track(self.addr_s, -1)
            self._apply_result(handle, r)

        try:
            self._start_exec(
                fin, grid=g, job_uuid=ju, config=config, saturation=saturation,
                latency=latency,
            )
        except BaseException:
            # submit can raise (e.g. "engine stopped"); un-count or the +1
            # leaks and permanently skews least-outstanding placement.
            self._track(self.addr_s, -1)
            raise
        return handle

    def _submit_remote(
        self, g: np.ndarray, member: str, config=None, job_uuid=None
    ) -> Job:
        geom = geometry_for_size(g.shape[0])
        if job_uuid is not None:
            # A client-supplied uuid IS the job uuid end to end (dedupe
            # registry, WAL entry, TASK frame) — same contract as
            # _submit_local.
            ju = job_uuid
        else:
            # clockck: allow(uuid entropy, not a timing decision — ns-unique per node; virtualizing it would COLLIDE ids under simnet's frozen clock)
            ju = f"{self.addr_s}/{time.monotonic_ns()}"
        job = Job(uuid=ju, grid=g, geom=geom)
        cfg_dict = dataclasses.asdict(config) if config is not None else None
        with self._lock:
            self._ledger[job.uuid] = {
                "grid": g,
                "member": member,
                "job": job,
                "config": cfg_dict,
            }
        self._track(member, +1)
        jr = self.engine._journal()
        if jr is not None:
            # Remote dispatch never touches the local engine's submit
            # seam, so the WAL promise is kept HERE: the ORIGIN owns the
            # client's job, and an origin crash mid-dispatch must replay
            # it (the member's own journal, if any, only covers the
            # member's copy).  Discharged in _on_solution.
            jr.record_accepted(job.uuid, grid=g, config=cfg_dict)
        payload = {
            "method": "TASK",
            "uuid": job.uuid,
            "grid": g.tolist(),
            "origin": self.addr_s,
            "config": cfg_dict,
        }
        if trace.active() is not None:
            # Trace context rides the frame: the worker's spans land under
            # this uuid and ship back on the SOLUTION (obs/trace.py).
            payload["trace"] = job.uuid
        try:
            self._send(member, payload)
        except WireError:
            # Reliable transport tells us delivery failed -> immediate local
            # re-execution instead of the reference's silent loss (§2.5 #7).
            self._reexecute(job.uuid)
        return job

    def _reexecute(self, job_uuid: str) -> None:
        """Re-run a job whose worker left the network view.

        If the worker streamed PROGRESS snapshots, resume from its surviving
        subtree roots (skipping everything already searched) and carry its
        nodes counter; otherwise restart from the clue grid, like the
        reference's ledger re-queue (``DHT_Node.py:201-209``).
        """
        with self._lock:
            entry = self._ledger.pop(job_uuid, None)
        if entry is None:
            return
        self._track(entry["member"], -1)
        handle: Job = entry["job"]
        self._track(self.addr_s, +1)
        rec = trace.active()
        if rec is not None:
            rec.event(
                str(job_uuid), "recovery.reexecute", "cluster.recv",
                node=self.addr_s, member=entry["member"],
                resumed=entry.get("rows") is not None,
            )

        def fin(r: dict) -> None:
            self._track(self.addr_s, -1)
            self._apply_result(handle, r)

        rows_packed = entry.get("rows")
        try:
            if rows_packed is not None:
                rows = unpack_rows(rows_packed)
                geom = geometry_for_size(rows.shape[1])
                self._start_exec(
                    fin,
                    roots=rows,
                    geom=geom,
                    job_uuid=job_uuid,
                    base_nodes=int(entry.get("nodes_done", 0)),
                    config=_config_from_dict(entry.get("config")),
                )
            else:
                self._start_exec(
                    fin,
                    grid=entry["grid"],
                    job_uuid=job_uuid,
                    config=_config_from_dict(entry.get("config")),
                )
        except Exception as e:
            # Same counter leak as _submit_local, but swallow instead of
            # re-raise: _reexecute runs on recovery paths inside _hb_loop
            # (via _on_peer_dead -> _on_node_failed) and _on_update_network,
            # where a raise would kill the heartbeat thread and stop failure
            # detection entirely.  Fail the handle so waiters unblock.
            self._track(self.addr_s, -1)
            handle.error = f"re-execution failed: {e}"
            job_log(_LOG, job_uuid).error(
                "[%s] %s", self.addr_s, handle.error
            )
            handle.done.set()

    def _on_task(self, msg: dict) -> None:
        grid = np.asarray(msg["grid"], dtype=np.int32)
        origin = msg["origin"]
        ju = msg["uuid"]
        rec = trace.active()
        if rec is not None:
            tid = msg.get("trace")
            if isinstance(tid, str) and tid != ju:
                rec.link(ju, tid)
            rec.event(str(ju), "recv.TASK", "cluster.recv", node=self.addr_s,
                      origin=origin)

        def fin(r: dict) -> None:
            payload = {
                "method": "SOLUTION",
                "uuid": ju,
                "solved": r["solved"],
                "unsat": r["unsat"],
                "cancelled": r["cancelled"],
                "nodes": r["nodes"],
                "error": r["error"],
                "solution": r["solution"].tolist()
                if r["solution"] is not None
                else None,
            }
            rec_f = trace.active()
            if rec_f is not None:
                # Ship this node's spans for the trace back with the
                # result (bounded): the origin stitches them into ONE
                # trace for GET /trace/<uuid>.
                payload["trace"] = rec_f.resolve(ju)
                payload["spans"] = rec_f.export(ju)
            # At-least-once: retried on link faults (the origin dedupes by
            # uuid); if every attempt fails the origin died and its
            # successor's repair already re-executed the job.
            self._send_result(origin, payload)

        ex = self._start_exec(
            fin, grid=grid, job_uuid=ju, config=_config_from_dict(msg.get("config"))
        )
        if self.config.progress_interval_s > 0:
            threading.Thread(
                target=self._progress_loop,
                args=(ex, origin),
                daemon=True,
                name=f"progress-{ju[:8]}",
            ).start()

    def _progress_loop(self, ex: _Exec, origin: str) -> None:
        """Stream the job's surviving subtree roots to its origin so a death
        here resumes mid-subtree there (SURVEY.md §5.4's promise)."""
        while not self._stop.is_set() and not ex.finalized:
            self._clock.sleep(self.config.progress_interval_s)
            if ex.finalized:
                return
            if self.engine.job_is_resident(ex.uuid):
                # Resident-flight jobs (serving/scheduler.py) have no
                # snapshot surface: a death here resumes from the root via
                # the origin's ledger copy.  Degrade VISIBLY (the same
                # counter the over-cap snapshot path uses) instead of
                # polling a permanent None every interval.
                with self._lock:
                    self.progress_resident += 1
                return
            snap = self.engine.snapshot_rows(ex.uuid, timeout=2.0)
            if snap is None:
                continue
            rows, nodes, shed_parts, job_cfg = snap
            # Coverage gate: sheds and snapshots are serviced by the same
            # device-loop thread, so shed_parts==0 *at the cut* proves these
            # rows cover the job's entire remaining space.  Once anything
            # has been shed, stop streaming — the origin keeps the last
            # full-coverage snapshot (checking ex.parts here instead would
            # race the shed that _on_needwork runs before add_part).
            if shed_parts > 0:
                return
            if rows.shape[0] > self.config.progress_max_rows:
                # Too wide to ship — the origin keeps whatever snapshot it
                # last got (possibly none), so a death here re-executes from
                # the root.  Degrading VISIBLY: count + warn once per job
                # (the loop retries every interval; a per-iteration log
                # would spam a long search into the megabytes).
                if not ex.progress_skip_warned:
                    ex.progress_skip_warned = True
                    _LOG.warning(
                        "[cluster] progress snapshot for %s skipped: %d rows "
                        "> progress_max_rows=%d — resume degrades to root "
                        "re-execution (progress_skipped counter on /metrics)",
                        ex.uuid[:8], rows.shape[0], self.config.progress_max_rows,
                    )
                with self._lock:  # one _progress_loop thread PER JOB writes
                    self.progress_skipped += 1
                continue
            try:
                self._send(
                    origin,
                    {
                        "method": "PROGRESS",
                        "uuid": ex.uuid,
                        "rows": pack_rows(rows),
                        "nodes": int(nodes) + ex.base_nodes,
                        "config": job_cfg,
                    },
                )
            except WireError:
                continue  # transient link fault or origin death: keep trying
                # each interval — a PROGRESS is a pure refinement, and if the
                # origin really died the repair path reassigns regardless.

    def _on_progress(self, msg: dict) -> None:
        with self._lock:
            entry = self._ledger.get(msg["uuid"])
            if entry is not None:
                entry["rows"] = msg["rows"]
                entry["nodes_done"] = int(msg["nodes"])
                entry["config"] = msg.get("config")

    # -- mid-job offload (NEEDWORK -> SUBTASK -> PART_RESULT) ----------------
    def _on_needwork(self, requester: str) -> None:
        if requester == self.addr_s:
            return
        shed = self.engine.shed_work(k=self.config.shed_k, timeout=2.0)
        if shed is None:
            return  # nothing worth splitting (reference: no task, no range > 1)
        root_uuid, rows, job_cfg = shed
        with self._lock:
            ex = self._execs.get(root_uuid)
        # clockck: allow(uuid entropy, not a timing decision — ns-unique per node; virtualizing it would COLLIDE ids under simnet's frozen clock)
        part_uuid = f"{root_uuid}#p{time.monotonic_ns()}"
        rows_packed = pack_rows(rows)
        if ex is None or not ex.add_part(part_uuid, requester, rows_packed, job_cfg):
            return  # job resolved while we were shedding; rows are moot
        payload = {
            "method": "SUBTASK",
            "part": part_uuid,
            "root": root_uuid,
            "rows": rows_packed,
            "config": job_cfg,  # the part searches under the job's config
            "report_to": self.addr_s,
        }
        rec = trace.active()
        if rec is not None:
            # Trace context: the part's spans on the peer land under the
            # ROOT job's trace, not the derived part uuid — and the SAME
            # link is recorded HERE, on the shedder, so the part spans the
            # peer ships back in PART_RESULT (trace = part uuid) resolve
            # into the root on THIS recorder too (per-process recorders:
            # the peer's links never reach us).
            trace_id = rec.resolve(root_uuid)
            rec.link(part_uuid, trace_id)
            payload["trace"] = trace_id
        try:
            self._send(requester, payload)
            with self._lock:
                self.subtasks_sent += 1
        except WireError:
            # Requester vanished between NEEDWORK and now: run the part
            # ourselves so the shed subtrees are never lost.  Mark it local
            # first, or the requester's eviction from the view would make
            # _recover_parts re-enter the same part uuid a second time.
            ex.mark_local(part_uuid)
            self._on_subtask(payload)

    def _on_subtask(self, msg: dict) -> None:
        rows = unpack_rows(msg["rows"])
        part_uuid = msg["part"]
        root_uuid = msg["root"]
        report_to = msg["report_to"]
        geom = geometry_for_size(rows.shape[1])
        rec = trace.active()
        if rec is not None:
            tid = msg.get("trace")
            rec.link(
                str(part_uuid),
                tid if isinstance(tid, str) else str(root_uuid),
            )
            rec.event(
                str(part_uuid), "recv.SUBTASK", "cluster.recv",
                node=self.addr_s, rows=rows.shape[0],
            )
        with self._lock:
            self._parts[part_uuid] = root_uuid
            self.subtasks_run += 1

        def fin(r: dict) -> None:
            with self._lock:
                self._parts.pop(part_uuid, None)
            payload = {
                "method": "PART_RESULT",
                "part": part_uuid,
                "root": root_uuid,
                "solved": r["solved"],
                "unsat": r["unsat"],
                "nodes": r["nodes"],
                "error": r.get("error"),
                "solution": r["solution"].tolist()
                if r["solution"] is not None
                else None,
            }
            rec_f = trace.active()
            if rec_f is not None:
                payload["trace"] = rec_f.resolve(str(part_uuid))
                payload["spans"] = rec_f.export(str(part_uuid))
            if report_to == self.addr_s:
                # Tag self-reported results: a no-verdict error from a LOCAL
                # execution is terminal for the part (last resort failed),
                # where the same error from a remote executor triggers local
                # re-entry — on_part_result branches on this.
                payload["local"] = True
                self._on_part_result(payload)
                return
            # At-least-once: retried on link faults (the shedder dedupes by
            # part uuid); if every attempt fails the shedder died and the
            # origin's repair path re-covers the subtree.
            self._send_result(report_to, payload)

        self._start_exec(
            fin,
            roots=rows,
            geom=geom,
            job_uuid=part_uuid,
            config=_config_from_dict(msg.get("config")),
        )

    def _recover_parts(self) -> None:
        """Re-enter shed SUBTASK parts whose executing peer left the network
        view (or blew the optional part deadline).

        The rows were retained at shed time (:meth:`_Exec.add_part`), so the
        lost subtree re-runs locally under the same part uuid — mirroring the
        WireError fallback in :meth:`_on_needwork`.  Without this, the root
        _Exec waits forever on a dead part: the job never finalizes on the
        exhaustion path, and a solution in the lost subtree is never found
        (ADVICE r2 #1)."""
        with self._lock:
            execs = list(self._execs.values())
            live = set(self.network)
        for ex in execs:
            for part_uuid, rows_packed, cfg in ex.take_orphaned(
                live, self.config.part_deadline_s
            ):
                self._reenter_part(ex, part_uuid, rows_packed, cfg)

    def _reenter_part(self, ex: "_Exec", part_uuid: str, rows_packed, cfg) -> None:
        """Run a previously-shed part locally (recovery: its executor died,
        blew the deadline, or reported a no-verdict failure).  The caller
        must have marked the part re-homed; a synchronous re-entry failure
        clears the flag so a later recovery pass retries — and never kills
        the caller (a raise in _hb_loop would stop heartbeating entirely)."""
        try:
            self._on_subtask(
                {
                    "part": part_uuid,
                    "root": ex.uuid,
                    "rows": rows_packed,
                    "config": cfg,
                    "report_to": self.addr_s,
                }
            )
        except Exception as e:  # noqa: BLE001 - e.g. our own engine stopping
            ex.unmark_rehomed(part_uuid)
            if not self._stop.is_set():
                job_log(_LOG, part_uuid).error(
                    "[%s] part re-entry failed: %r [%s]",
                    self.addr_s, e, faults.classify(e),
                )
        else:
            with self._lock:
                self.rehomed_parts += 1
            rec = trace.active()
            if rec is not None:
                rec.event(
                    str(part_uuid), "recovery.rehome", "cluster.recv",
                    node=self.addr_s,
                )

    def _on_part_result(self, msg: dict) -> None:
        rec = trace.active()
        if rec is not None:
            part, root = msg.get("part"), msg.get("root")
            if part is not None and root is not None:
                # Defensive re-link (a restarted shedder's in-memory link
                # table is gone): the ingested part spans must resolve
                # into the root trace on this recorder.
                rec.link(str(part), rec.resolve(str(root)))
            rec.ingest(msg.get("spans"))
            if part is not None:
                rec.event(
                    str(part), "recv.PART_RESULT", "cluster.recv",
                    node=self.addr_s,
                )
        with self._lock:
            ex = self._execs.get(msg["root"])
        if ex is not None:
            ex.on_part_result(msg["part"], msg)

    def _on_solution(self, msg: dict) -> None:
        rec = trace.active()
        if rec is not None:
            rec.ingest(msg.get("spans"))
            if msg.get("uuid") is not None:
                rec.event(
                    str(msg["uuid"]), "recv.SOLUTION", "cluster.recv",
                    node=self.addr_s,
                )
        if (
            msg.get("error")
            and not msg.get("solved")
            and not msg.get("unsat")
            and not msg.get("cancelled")
        ):
            # A FAILED remote execution, not a verdict: the member's engine
            # drained the job during shutdown (a kill/stop racing the
            # dispatch), or its flight errored.  Such a result reaches us
            # BEFORE failure detection does, so without this filter it
            # would pop the ledger and finalize the client's job unsolved
            # while the death-repair re-execution path never gets its
            # chance.  Found by the round-4 device-backed churn soak (one
            # lost job in 2 h of churn; the oracle-backed lane's instant
            # solves could not hit the window).  Re-execute from the ledger
            # immediately — faster than waiting for the heartbeat deadline.
            # Since round 9 the re-dispatch decision uses the same
            # classifier as the engine's own recovery (serving/faults.py):
            # a TRANSIENT remote failure (shutdown race, preemption,
            # injected wire fault — and a remote retry-budget exhaustion,
            # whose "retry budget exhausted...: <transient fault>" text
            # classifies transient ON PURPOSE: the remote's storm may be
            # node-local, so one local re-execution is a fair last try)
            # re-executes from the ledger; a PERMANENT one (bad config,
            # poisoned job — an error retrying cannot cure) finalizes the
            # client's job with that error instead of burning a local
            # re-execution that must fail identically.
            if faults.classify_message(msg.get("error")) == faults.TRANSIENT:
                with self._lock:
                    known = msg["uuid"] in self._ledger
                if known:
                    self._reexecute(msg["uuid"])
                return
        with self._lock:
            entry = self._ledger.pop(msg["uuid"], None)
        if entry is None:
            return  # already re-executed or cancelled
        self._track(entry["member"], -1)
        handle: Job = entry["job"]
        handle.solved = bool(msg["solved"])
        handle.unsat = bool(msg["unsat"])
        handle.cancelled = bool(msg.get("cancelled", False))
        handle.nodes = int(msg["nodes"])
        handle.error = msg.get("error")
        if msg["solution"] is not None:
            handle.solution = np.asarray(msg["solution"], dtype=np.int32)
        if handle.error is None:
            # Real remote verdict: discharge the origin's WAL entry
            # (permanent remote errors stay accepted-only on purpose — a
            # restart replays them, the journal's at-least-once contract).
            jr = self.engine._journal()
            if jr is not None:
                jr.record_resolved(
                    handle.uuid,
                    {
                        "solved": bool(handle.solved),
                        "unsat": bool(handle.unsat),
                        "cancelled": bool(handle.cancelled),
                        "nodes": int(handle.nodes),
                    },
                )
        handle.done.set()

    # -- views (HTTP layer) --------------------------------------------------
    def stats_view(self) -> dict:
        """Reference `/stats` shape (``DHT_Node.py:573-586``), sleep-free.

        Per-peer requests run in parallel with individual timeouts, so a
        degraded cluster costs one timeout, not O(N) serial timeouts."""
        s = self.engine.stats()
        nodes = [{"address": self.addr_s, "validations": s["validations"]}]
        total_v, total_s = s["validations"], s["solved"]
        with self._lock:
            peers = [m for m in self.network if m != self.addr_s]
        results = wire.fanout_requests(
            self._transport, peers, {"method": "STATS_REQ"},
            self.config.stats_timeout_s,
        )
        for m, res in zip(peers, results):
            if res is None:
                nodes.append({"address": m, "validations": None})
            else:
                nodes.append(
                    {"address": res["address"], "validations": res["validations"]}
                )
                total_v += res["validations"]
                total_s += res["solved"]
        return {"all": {"solved": total_s, "validations": total_v}, "nodes": nodes}

    def metrics_view(self) -> dict:
        """Engine metrics + cluster-runtime counters (GET /metrics superset):
        membership/view version, dispatch ledger, mid-job offload traffic,
        and live local executions — the observability the reference's
        print-trace never had (SURVEY.md §5.5)."""
        body = self.engine.metrics()
        # The node's own mergeable histograms (wire send/ack walls) join
        # the engine's in one flat "hist" section, so cluster aggregation
        # sees every phase through a single key space.
        mine = {k: h.to_dict() for k, h in self._hist.items() if len(h)}
        if mine:
            body["hist"] = {**body.get("hist", {}), **mine}
        with self._lock:
            body["cluster"] = {
                "address": self.addr_s,
                "coordinator": self.coordinator,
                "members": len(self.network),
                "view": [self.net_term, self.net_epoch],
                "ledger_outstanding": len(self._ledger),
                "execs_running": len(self._execs),
                "parts_running": len(self._parts),
                "subtasks_sent": self.subtasks_sent,
                "subtasks_run": self.subtasks_run,
                # PROGRESS snapshots dropped for exceeding progress_max_rows:
                # nonzero means some jobs here run with degraded (root-only)
                # resume — VERDICT r5 missing #3 made visible.
                "progress_skipped": self.progress_skipped,
                # Jobs served resident (continuous batching) run without
                # progress streaming; slot occupancy / admission waits /
                # rejects ride the engine body's "resident" section.
                "progress_resident": self.progress_resident,
                # The cluster fault plane (round 10): what at-least-once
                # delivery and membership versioning actually absorbed.
                # duplicates_dropped — per-method redeliveries executed 0
                # extra times; stale_views_rejected — membership assertions
                # from superseded (term, epoch) views; stale_view_
                # reflections — anti-entropy replies that teach a
                # split-brain loser the winning view; partitions_healed —
                # evicted-but-alive members re-admitted (coordinator side);
                # demotions — rival coordinators that stood down (loser
                # side); rehomed_parts — shed parts re-entered locally
                # after executor death/deadline.
                # results_parked / results_delivered_late — result sends
                # whose at-least-once budget exhausted mid-partition,
                # parked and re-offered per beat until the link healed.
                "faults": {
                    "duplicates_dropped": dict(self.duplicates_dropped),
                    "stale_views_rejected": self.stale_views_rejected,
                    "stale_view_reflections": self.stale_view_reflections,
                    "partitions_healed": self.partitions_healed,
                    "demotions": self.demotions,
                    "rehomed_parts": self.rehomed_parts,
                    "results_parked": self.results_parked,
                    "results_delivered_late": self.results_delivered_late,
                },
                # Cluster-scope aggregation health (round 12): pulls =
                # peer METRICS_PULL requests issued, merges = rollups
                # computed, unreachable_peers = peers a pull could not
                # reach (each one also logged via obs/logctx).
                "agg": {
                    "pulls": self.agg_pulls,
                    "merges": self.agg_merges,
                    "unreachable_peers": self.agg_unreachable,
                },
            }
        if self.gossip is not None:
            # The DHT plane (ISSUE 17): gossip liveness counters, ring
            # shape, the node's cluster-cache shard, and cache-affine
            # routing decisions.  Rolled up by obs/agg._merge_dht and
            # rendered as dsst_dht_* prometheus families.
            with self._ring_lock:
                ring_members = len(self.ring)
            with self._lock:
                affinity = {
                    "routed": self.affinity_routed,
                    "declined": self.affinity_declined,
                }
            body["dht"] = {
                "gossip": self.gossip.metrics(),
                "ring": {
                    "members": ring_members,
                    "vnodes": self.config.dht_vnodes,
                },
                "cluster_cache": self.dcache.metrics(),
                "affinity": affinity,
            }
        return body

    def cluster_metrics_view(self, sample: int = 0) -> dict:
        """``GET /metrics?scope=cluster``: fan a METRICS_PULL over the
        current view (bounded, per-peer ``stats_timeout_s`` deadlines —
        the handler thread never hangs on a partitioned member) and merge
        the reachable members' bodies into a rollup (``obs/agg.py``:
        histograms vector-add, whitelisted counters sum, floors min).

        Degrades honestly: an unreachable peer is flagged
        ``unreachable`` (and logged with the peer identified), a peer
        whose (term, epoch) disagrees with ours is flagged ``stale`` —
        its numbers still merge (they are real samples), but the reader
        knows the membership pictures differ.  Any member can serve
        this; the fan-out runs over the caller's own view.

        ``sample`` > 0 caps the pull at that many peers for large rings
        (``GET /metrics?scope=cluster&sample=N``): an evenly spaced,
        DETERMINISTIC subset — no RNG, so repeated scrapes and the
        simnet lane pull the same members — with the rollup flagged
        ``sampled`` and ``members_total`` carrying the true ring size."""
        with self._lock:
            peers = [m for m in self.network if m != self.addr_s]
            view = (self.net_term, self.net_epoch)
            coordinator = self.coordinator
        members_total = len(peers) + 1
        sampled = bool(sample) and len(peers) > sample
        if sampled:
            stride = len(peers) / sample
            peers = [peers[int(i * stride)] for i in range(sample)]
        payload = {
            "method": "METRICS_PULL",
            "from": self.addr_s,
            "term": view[0],
            "epoch": view[1],
        }
        results = wire.fanout_requests(
            self._transport, peers, payload, self.config.stats_timeout_s
        )
        nodes: dict = {
            self.addr_s: {
                "unreachable": False,
                "stale": False,
                "view": list(view),
                "metrics": self.metrics_view(),
            }
        }
        unreachable = 0
        for m, res in zip(peers, results):
            if res is None or not isinstance(res.get("metrics"), dict):
                nodes[m] = {
                    "unreachable": True,
                    "stale": False,
                    "view": None,
                    "metrics": None,
                }
                unreachable += 1
                # The aggregation-degraded event: peer identified, so an
                # operator greps the address straight to the evidence.
                ctx_log(_LOG, "peer", m).warning(
                    "[%s] cluster metrics pull got no usable reply — "
                    "rollup degrades to %d/%d members",
                    self.addr_s, len(peers) + 1 - unreachable, len(peers) + 1,
                )
            else:
                peer_view = (int(res.get("term", -1)), int(res.get("epoch", -1)))
                nodes[m] = {
                    "unreachable": False,
                    "stale": peer_view != view,
                    "view": list(peer_view),
                    "metrics": res["metrics"],
                }
        with self._lock:
            self.agg_pulls += len(peers)
            self.agg_merges += 1
            self.agg_unreachable += unreachable
        rollup = agg.rollup(
            [n["metrics"] for n in nodes.values() if n["metrics"] is not None]
        )
        rollup["nodes"] = len(nodes)
        rollup["unreachable"] = unreachable
        rollup["members_total"] = members_total
        rollup["sampled"] = sampled
        return {
            "scope": "cluster",
            "address": self.addr_s,
            "coordinator": coordinator,
            "view": list(view),
            "nodes": nodes,
            "rollup": rollup,
        }

    def status_view(self) -> dict:
        """``GET /status``: the compact SLO/health plane derived from one
        cluster-scope pull (member reachability/staleness, cluster
        quantiles, the RPC-floor estimate, SLO state)."""
        return agg.status_from(self.cluster_metrics_view())

    def network_view(self) -> dict:
        """Reference `/network` shape (``DHT_Node.py:600-614``)."""
        with self._lock:
            members = list(self.network)
        return {
            m: [
                members[(i - 1) % len(members)],
                members[(i + 1) % len(members)],
            ]
            for i, m in enumerate(members)
        }
