"""Cluster node: coordinator membership + heartbeat + job dispatch + recovery.

Host-level re-design of the reference's overlay layer (SURVEY.md §1 L3,
§2.1 #8-#10) for the TPU world: each *node* is a host driving its own chip
mesh (the data plane lives in ``parallel/``), and the cluster layer moves
whole jobs, not subtrees — intra-job parallelism is the mesh's business.

Capability map (reference -> here):

* coordinator-mediated join (``/root/reference/DHT_Node.py:260-330``) ->
  JOIN_REQ forwarded to the coordinator, which appends to the member list
  and broadcasts UPDATE_NETWORK; ring positions (predecessor/successor) are
  *derived from list order* on every node, eliminating the reference's
  separate UPDATE_PREDECESSOR/UPDATE_NEIGHBOR splice messages and the
  inconsistency windows between them.
* heartbeat + 2x-timeout detection (``:43-62,158-163``) -> each node
  heartbeats its ring successor and watches its predecessor's arrivals.
* coordinator-led repair + self-promotion (``:167-199``) -> same roles:
  detector reports NODE_FAILED; the dead coordinator's successor-detector
  self-promotes (exactly one detector per corpse, so promotion is unique).
* re-execution from the delegator's ledger (``:47,497,509,201-209``) ->
  every forwarded job stays in ``self._ledger`` until its SOLUTION arrives;
  when a member leaves the network view, its ledger entries re-run locally.
* NEEDWORK load balancing (``:246-254``) -> receiver-independent
  least-outstanding dispatch at submit time (jobs are sized uniformly by
  the engine's batching, so proactive balance replaces reactive stealing
  at this layer; reactive stealing lives on-device, ``ops/frontier.py``).
* STATS_REQ 1 s gather sleep (``:566-598``) -> synchronous request/reply
  fan-out with per-peer timeouts.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.cluster import wire
from distributed_sudoku_solver_tpu.cluster.wire import Addr, WireError, addr_str
from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine


def local_ip() -> str:
    """Best-effort routable local address (UDP connect sends no packets)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    heartbeat_s: float = 1.0
    fail_factor: float = 3.0  # declare dead after fail_factor * heartbeat_s
    io_timeout_s: float = 5.0
    stats_timeout_s: float = 2.0


class ClusterNode:
    """One host in the solver cluster; wraps a local SolverEngine."""

    def __init__(
        self,
        engine: SolverEngine,
        host: str = "127.0.0.1",
        port: int = 0,
        anchor: Optional[Addr] = None,
        config: ClusterConfig = ClusterConfig(),
        advertise_host: Optional[str] = None,
    ):
        """``host`` is the bind address; ``advertise_host`` is the identity
        other members dial (defaults to ``host``, which is only correct for
        single-machine clusters — multi-host deployments must advertise a
        routable address, e.g. from :func:`local_ip`)."""
        self.engine = engine
        self.config = config
        self._listener = socket.create_server((host, port))
        bound_port = self._listener.getsockname()[1]
        adv = advertise_host or host
        if adv in ("0.0.0.0", "::"):
            adv = local_ip()
        self.addr: Addr = (adv, bound_port)
        self.addr_s = addr_str(self.addr)
        self.anchor = anchor

        self._lock = threading.RLock()
        self.network: list[str] = [self.addr_s]  # list order defines the ring
        self.coordinator: str = self.addr_s
        # Monotonic membership version, ordered as (term, epoch): the term
        # bumps on every coordinator promotion (so a successor's first view
        # supersedes anything the dead coordinator issued, even epochs the
        # detector never saw), the epoch bumps on every membership change
        # within a term.  UPDATE_NETWORK messages arrive on per-connection
        # threads, so two broadcasts can be *applied* out of order; this
        # ordering makes installation order-independent (stale views are
        # dropped), where the reference simply last-writer-wins
        # (``/root/reference/DHT_Node.py:332-336``).
        self.net_term: int = 0
        self.net_epoch: int = 0
        self._last_hb = time.monotonic()
        self._ledger: dict[str, dict] = {}  # uuid -> {grid, member, job}
        self._outstanding: dict[str, int] = {}  # member -> in-flight count
        self._rr = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ClusterNode":
        for target, name in ((self._accept_loop, "accept"), (self._hb_loop, "hb")):
            t = threading.Thread(target=target, daemon=True, name=f"{name}@{self.addr_s}")
            t.start()
            self._threads.append(t)
        if self.anchor is not None:
            wire.send_msg(
                self.anchor,
                {"method": "JOIN_REQ", "addr": self.addr_s},
                self.config.io_timeout_s,
            )
        return self

    def stop(self, graceful: bool = True) -> None:
        """Leave the ring (graceful drain analog of ``DHT_Node.stop``, :137-156)."""
        self._stop.set()
        if graceful and self.coordinator != self.addr_s:
            try:
                wire.send_msg(
                    wire.parse_addr(self.coordinator),
                    {"method": "LEAVE", "addr": self.addr_s},
                    self.config.io_timeout_s,
                )
            except WireError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass

    def kill(self) -> None:
        """Abrupt death for fault-injection tests: no LEAVE, just silence."""
        self.stop(graceful=False)

    # -- ring derivation -----------------------------------------------------
    def _ring(self) -> tuple[Optional[str], Optional[str]]:
        with self._lock:
            if len(self.network) < 2 or self.addr_s not in self.network:
                return None, None
            i = self.network.index(self.addr_s)
            pred = self.network[(i - 1) % len(self.network)]
            succ = self.network[(i + 1) % len(self.network)]
            return pred, succ

    # -- background loops ----------------------------------------------------
    def _accept_loop(self) -> None:
        self._listener.listen()
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            try:
                conn.settimeout(self.config.io_timeout_s)
                msg = wire.recv_msg(conn)
                self._handle(msg, conn)
            except (WireError, OSError, ValueError, KeyError) as e:
                # Malformed or interrupted control traffic is logged-and-dropped;
                # reliability comes from sender-side errors, not server retries.
                if not self._stop.is_set():
                    print(f"[{self.addr_s}] bad message: {e!r}")

    def _hb_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.config.heartbeat_s)
            # Coordinator re-broadcasts the view every beat: a member that
            # missed an UPDATE_NETWORK (send failure is fire-and-forget)
            # converges on the next beat instead of never.  Off-thread, so a
            # partitioned member's connect timeout cannot delay our own
            # heartbeats past the failure threshold.
            if self.coordinator == self.addr_s and len(self.network) > 1:
                threading.Thread(
                    target=self._broadcast_network, daemon=True
                ).start()
            pred, succ = self._ring()
            if succ is None:
                with self._lock:
                    self._last_hb = time.monotonic()
                continue
            try:
                wire.send_msg(
                    wire.parse_addr(succ),
                    {"method": "HEARTBEAT", "from": self.addr_s},
                    self.config.io_timeout_s,
                )
            except WireError:
                pass  # successor's own detector handles its death
            limit = self.config.heartbeat_s * self.config.fail_factor
            with self._lock:
                expired = time.monotonic() - self._last_hb > limit
            if expired and pred is not None:
                self._on_peer_dead(pred)

    # -- message handling ----------------------------------------------------
    def _handle(self, msg: dict, conn: socket.socket) -> None:
        method = msg["method"]
        if method == "JOIN_REQ":
            self._on_join_req(msg["addr"])
        elif method == "UPDATE_NETWORK":
            self._on_update_network(
                list(msg["network"]),
                msg["coordinator"],
                int(msg["term"]),
                int(msg["epoch"]),
            )
        elif method == "HEARTBEAT":
            with self._lock:
                self._last_hb = time.monotonic()
        elif method == "NODE_FAILED":
            self._on_node_failed(msg["addr"])
        elif method == "LEAVE":
            self._on_node_failed(msg["addr"])  # same repair path, no suspicion
        elif method == "TASK":
            self._on_task(msg)
        elif method == "SOLUTION":
            self._on_solution(msg)
        elif method == "CANCEL":
            self.engine.cancel(msg["uuid"])
        elif method == "STATS_REQ":
            s = self.engine.stats()
            wire.reply_msg(
                conn,
                {
                    "method": "STATS_RES",
                    "address": self.addr_s,
                    "validations": s["validations"],
                    "solved": s["solved"],
                },
            )
        else:
            print(f"[{self.addr_s}] unknown method {method!r}")

    # -- membership ----------------------------------------------------------
    def _broadcast_network(self) -> None:
        with self._lock:
            members = list(self.network)
            payload = {
                "method": "UPDATE_NETWORK",
                "network": members,
                "coordinator": self.coordinator,
                "term": self.net_term,
                "epoch": self.net_epoch,
            }
        for m in members:
            if m != self.addr_s:
                try:
                    wire.send_msg(wire.parse_addr(m), payload, self.config.io_timeout_s)
                except WireError:
                    pass  # its detector will notice soon enough

    def _on_join_req(self, joiner: str) -> None:
        if self.coordinator != self.addr_s:
            wire.send_msg(
                wire.parse_addr(self.coordinator),
                {"method": "JOIN_REQ", "addr": joiner},
                self.config.io_timeout_s,
            )
            return
        with self._lock:
            if joiner not in self.network:
                self.network.append(joiner)
                self.net_epoch += 1
            self._last_hb = time.monotonic()
        self._broadcast_network()

    def _on_update_network(
        self, network: list[str], coordinator: str, term: int, epoch: int
    ) -> None:
        rejoin = False
        with self._lock:
            if (term, epoch) <= (self.net_term, self.net_epoch):
                return  # stale or duplicate view; ours is at least as new
            self.network = network
            self.coordinator = coordinator
            self.net_term = term
            self.net_epoch = epoch
            self._last_hb = time.monotonic()
            # Evicted by a false death verdict (e.g. my heartbeats starved):
            # re-join through the coordinator rather than orbiting alone.
            rejoin = self.addr_s not in network and not self._stop.is_set()
            gone = [
                u for u, e in self._ledger.items() if e["member"] not in network
            ]
        for u in gone:
            self._reexecute(u)
        if rejoin:
            try:
                wire.send_msg(
                    wire.parse_addr(coordinator),
                    {"method": "JOIN_REQ", "addr": self.addr_s},
                    self.config.io_timeout_s,
                )
            except WireError:
                pass

    def _on_node_failed(self, dead: str) -> None:
        if self.coordinator == self.addr_s:
            with self._lock:
                if dead in self.network:
                    self.network.remove(dead)
                    self.net_epoch += 1
                self._last_hb = time.monotonic()
                gone = [
                    u
                    for u, e in self._ledger.items()
                    if e["member"] not in self.network
                ]
            self._broadcast_network()
            for u in gone:
                self._reexecute(u)
        else:
            try:
                wire.send_msg(
                    wire.parse_addr(self.coordinator),
                    {"method": "NODE_FAILED", "addr": dead},
                    self.config.io_timeout_s,
                )
            except WireError:
                pass

    def _on_peer_dead(self, dead: str) -> None:
        """My predecessor went silent (``check_neighbor`` analog, :158-209)."""
        with self._lock:
            if dead not in self.network:
                return
            if dead == self.coordinator:
                # I am the unique detector of the coordinator: self-promote
                # (``DHT_Node.py:191-193``).  A new term outranks every view
                # the dead coordinator issued, including epochs we missed.
                self.coordinator = self.addr_s
                self.net_term += 1
            self._last_hb = time.monotonic()
        self._on_node_failed(dead)

    # -- job dispatch --------------------------------------------------------
    def submit(self, grid) -> Job:
        g = np.asarray(grid, dtype=np.int32)
        if g.ndim != 2 or g.shape[0] != g.shape[1]:
            raise ValueError(f"grid must be square, got {g.shape}")
        member = self._pick_member()
        if member == self.addr_s:
            return self._submit_local(g)
        return self._submit_remote(g, member)

    def cancel(self, job_uuid: str) -> None:
        self.engine.cancel(job_uuid)
        with self._lock:
            entry = self._ledger.get(job_uuid)
        if entry is not None:
            try:
                wire.send_msg(
                    wire.parse_addr(entry["member"]),
                    {"method": "CANCEL", "uuid": job_uuid},
                    self.config.io_timeout_s,
                )
            except WireError:
                pass

    def _pick_member(self) -> str:
        """Least-outstanding member; ties broken round-robin (load balance)."""
        with self._lock:
            members = list(self.network)
            if len(members) == 1:
                return self.addr_s
            self._rr += 1
            counts = [
                (self._outstanding.get(m, 0), (i + self._rr) % len(members), m)
                for i, m in enumerate(members)
            ]
        return min(counts)[2]

    def _track(self, member: str, delta: int) -> None:
        with self._lock:
            self._outstanding[member] = self._outstanding.get(member, 0) + delta

    def _submit_local(self, g: np.ndarray) -> Job:
        job = self.engine.submit(g)
        self._track(self.addr_s, +1)
        threading.Thread(
            target=lambda: (job.done.wait(), self._track(self.addr_s, -1)),
            daemon=True,
        ).start()
        return job

    def _submit_remote(self, g: np.ndarray, member: str) -> Job:
        geom = geometry_for_size(g.shape[0])
        job = Job(uuid=f"{self.addr_s}/{time.monotonic_ns()}", grid=g, geom=geom)
        with self._lock:
            self._ledger[job.uuid] = {"grid": g, "member": member, "job": job}
        self._track(member, +1)
        try:
            wire.send_msg(
                wire.parse_addr(member),
                {
                    "method": "TASK",
                    "uuid": job.uuid,
                    "grid": g.tolist(),
                    "origin": self.addr_s,
                },
                self.config.io_timeout_s,
            )
        except WireError:
            # Reliable transport tells us delivery failed -> immediate local
            # re-execution instead of the reference's silent loss (§2.5 #7).
            self._reexecute(job.uuid)
        return job

    def _reexecute(self, job_uuid: str) -> None:
        with self._lock:
            entry = self._ledger.pop(job_uuid, None)
        if entry is None:
            return
        self._track(entry["member"], -1)
        handle: Job = entry["job"]
        local = self.engine.submit(entry["grid"], job_uuid=job_uuid)
        self._track(self.addr_s, +1)

        def relay():
            local.done.wait()
            self._track(self.addr_s, -1)
            handle.solution = local.solution
            handle.solved = local.solved
            handle.unsat = local.unsat
            handle.nodes = local.nodes
            handle.cancelled = local.cancelled
            handle.error = local.error
            handle.done.set()

        threading.Thread(target=relay, daemon=True).start()

    def _on_task(self, msg: dict) -> None:
        grid = np.asarray(msg["grid"], dtype=np.int32)
        origin = msg["origin"]
        job = self.engine.submit(grid, job_uuid=msg["uuid"])

        def reply():
            job.done.wait()
            payload = {
                "method": "SOLUTION",
                "uuid": job.uuid,
                "solved": job.solved,
                "unsat": job.unsat,
                "nodes": job.nodes,
                "error": job.error,
                "solution": job.solution.tolist() if job.solution is not None else None,
            }
            try:
                wire.send_msg(
                    wire.parse_addr(origin), payload, self.config.io_timeout_s
                )
            except WireError:
                pass  # origin died; its successor's repair already re-executed

        threading.Thread(target=reply, daemon=True).start()

    def _on_solution(self, msg: dict) -> None:
        with self._lock:
            entry = self._ledger.pop(msg["uuid"], None)
        if entry is None:
            return  # already re-executed or cancelled
        self._track(entry["member"], -1)
        handle: Job = entry["job"]
        handle.solved = bool(msg["solved"])
        handle.unsat = bool(msg["unsat"])
        handle.nodes = int(msg["nodes"])
        handle.error = msg.get("error")
        if msg["solution"] is not None:
            handle.solution = np.asarray(msg["solution"], dtype=np.int32)
        handle.done.set()

    # -- views (HTTP layer) --------------------------------------------------
    def stats_view(self) -> dict:
        """Reference `/stats` shape (``DHT_Node.py:573-586``), sleep-free."""
        s = self.engine.stats()
        nodes = [{"address": self.addr_s, "validations": s["validations"]}]
        total_v, total_s = s["validations"], s["solved"]
        with self._lock:
            peers = [m for m in self.network if m != self.addr_s]
        for m in peers:
            try:
                res = wire.request(
                    wire.parse_addr(m),
                    {"method": "STATS_REQ"},
                    self.config.stats_timeout_s,
                )
                nodes.append(
                    {"address": res["address"], "validations": res["validations"]}
                )
                total_v += res["validations"]
                total_s += res["solved"]
            except WireError:
                nodes.append({"address": m, "validations": None})
        return {"all": {"solved": total_s, "validations": total_v}, "nodes": nodes}

    def network_view(self) -> dict:
        """Reference `/network` shape (``DHT_Node.py:600-614``)."""
        with self._lock:
            members = list(self.network)
        return {
            m: [
                members[(i - 1) % len(members)],
                members[(i + 1) % len(members)],
            ]
            for i, m in enumerate(members)
        }
