"""Cluster control plane: typed TCP wire protocol + membership + fault tolerance."""

from distributed_sudoku_solver_tpu.cluster.node import ClusterNode  # noqa: F401
from distributed_sudoku_solver_tpu.cluster.wire import (  # noqa: F401
    Addr,
    WireError,
    recv_msg,
    request,
    send_msg,
)
