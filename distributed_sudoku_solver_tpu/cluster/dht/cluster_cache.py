"""Cluster-wide result cache: canonical digests owned by ring members.

Each node holds the authoritative shard for the digests the hash ring
assigns it, as plain JSON-ready dicts (``{"verdict", "solution",
"nodes", "raw", "route"}``) — the dict <-> ``frontdoor.CacheEntry``
glue lives in ``cluster/node.py`` so this layer never imports serving.

The consistency model is the front door's, made distributed:

* lookups are read-through — a local (L1) miss asks the digest's owner
  with a SHORT timeout; any wire error is just a miss (the requester
  solves locally — no lost job, ever).
* fills are at-least-once and ASYNC — ``store`` on a non-owner ships a
  CACHE_PUT off-thread with the wire's retry budget and a dedupe uuid,
  so the solve path never blocks on a remote.  Duplicate puts are
  idempotent (same deterministic solution for the same canonical
  digest), so at-least-once is safe; the receiver's dedupe LRU keeps
  the counters honest.
* staleness is bounded by correctness, not freshness: entries are
  verdicts of a deterministic solver over a canonical form, so a
  "stale" entry is still the right answer — the only loss mode is a
  MISS (owner died with its shard), which degrades to a local solve.

All I/O and time goes through injected callables (``owner_fn``,
``request_fn``, ``put_fn``, ``clock``, ``uuid_fn``): the simnet lane
drives this deterministically and clockck sees no bare clock.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional

from ..wire import WireError
from ...obs import lockdep

__all__ = ["ClusterCache"]

NEGATIVE = "unsat"


class ClusterCache:
    """One node's view of the cluster cache: the local shard it owns
    plus wire routing to every other digest's owner."""

    def __init__(
        self,
        self_addr: str,
        owner_fn: Callable[[str], Optional[str]],
        request_fn: Callable[[str, dict, float], dict],
        put_fn: Callable[[str, dict], None],
        clock,
        uuid_fn: Callable[[], str],
        capacity: int = 65536,
        get_timeout_s: float = 1.0,
        put_retries: int = 2,
        retry_delay_s: float = 0.25,
    ):
        self.self_addr = self_addr
        self._owner_fn = owner_fn
        self._request_fn = request_fn  # (owner, frame, timeout) -> reply; raises WireError
        self._put_fn = put_fn          # (owner, frame) -> None; raises WireError
        self._clock = clock
        self._uuid_fn = uuid_fn
        self.capacity = max(1, int(capacity))
        self.get_timeout_s = float(get_timeout_s)
        self.put_retries = max(0, int(put_retries))
        self.retry_delay_s = float(retry_delay_s)
        self._lock = lockdep.named_lock("cluster.dhtcache")  # lockck: name(cluster.dhtcache)
        self._shard: "OrderedDict[str, dict]" = OrderedDict()  # lockck: guard(_lock)
        self.lookups = 0  # lockck: guard(_lock)
        self.local_hits = 0  # lockck: guard(_lock) — this node owns the digest
        self.remote_hits = 0  # lockck: guard(_lock) — answered by the owner over the wire
        self.negative_hits = 0  # lockck: guard(_lock) — hits on UNSAT entries
        self.misses = 0  # lockck: guard(_lock)
        self.remote_errors = 0  # lockck: guard(_lock) — owner unreachable; degraded to miss
        self.puts_sent = 0  # lockck: guard(_lock) — CACHE_PUT shipped (post-retry success)
        self.puts_failed = 0  # lockck: guard(_lock) — retry budget exhausted; fill lost
        self.puts_applied = 0  # lockck: guard(_lock) — CACHE_PUT stored on this shard
        self.gets_served = 0  # lockck: guard(_lock) — CACHE_GET answered from this shard
        self.insertions = 0  # lockck: guard(_lock)
        self.evictions = 0  # lockck: guard(_lock)

    # -- read path -------------------------------------------------------

    def lookup(self, digest: str) -> Optional[dict]:
        """The entry for ``digest``, from this shard or its owner.  Any
        failure is a miss — the caller just solves locally."""
        owner = self._owner_fn(digest)
        if owner is None or owner == self.self_addr:
            entry = self._local_get(digest)
            with self._lock:
                self.lookups += 1
                if entry is None:
                    self.misses += 1
                else:
                    self.local_hits += 1
                    if entry.get("verdict") == NEGATIVE:
                        self.negative_hits += 1
            return entry
        frame = {"method": "CACHE_GET", "digest": digest}
        try:
            reply = self._request_fn(owner, frame, self.get_timeout_s)
        except WireError:
            with self._lock:
                self.lookups += 1
                self.remote_errors += 1
                self.misses += 1
            return None
        entry = reply.get("entry") if isinstance(reply, dict) and reply.get("found") else None
        with self._lock:
            self.lookups += 1
            if entry is None:
                self.misses += 1
            else:
                self.remote_hits += 1
                if entry.get("verdict") == NEGATIVE:
                    self.negative_hits += 1
        return entry

    # -- write path ------------------------------------------------------

    def store(self, digest: str, entry: dict) -> None:
        """Fill the cluster cache.  Owner-local stores are synchronous
        (dict insert); remote fills ship async so the resolving thread
        (often the device loop) never waits on the wire."""
        owner = self._owner_fn(digest)
        if owner is None or owner == self.self_addr:
            self._store_local(digest, entry)
            return
        frame = {
            "method": "CACHE_PUT",
            "uuid": self._uuid_fn(),
            "digest": digest,
            "entry": entry,
        }
        threading.Thread(
            target=self._put_loop, args=(owner, frame), daemon=True,
            name="dht-put",
        ).start()

    def _put_loop(self, owner: str, frame: dict) -> None:
        # At-least-once with the wire's retry budget: same uuid every
        # attempt, so the receiver's dedupe LRU absorbs duplicates.
        for attempt in range(1 + self.put_retries):
            try:
                self._put_fn(owner, frame)
                with self._lock:
                    self.puts_sent += 1
                return
            except WireError:
                if attempt < self.put_retries:
                    self._clock.sleep(self.retry_delay_s)
        with self._lock:
            self.puts_failed += 1  # fill lost — a future miss, never a wrong answer

    # -- wire handlers (called from the node's _handle dispatch) ---------

    def handle_get(self, frame: dict) -> dict:
        entry = self._local_get(frame.get("digest", ""))
        with self._lock:
            self.gets_served += 1
        return {"found": entry is not None, "entry": entry}

    def handle_put(self, frame: dict) -> None:
        digest = frame.get("digest")
        entry = frame.get("entry")
        if not isinstance(digest, str) or not isinstance(entry, dict):
            return
        self._store_local(digest, entry)
        with self._lock:
            self.puts_applied += 1

    # -- shard -----------------------------------------------------------

    def _local_get(self, digest: str) -> Optional[dict]:
        with self._lock:
            entry = self._shard.get(digest)
            if entry is not None:
                self._shard.move_to_end(digest)
            return entry

    def _store_local(self, digest: str, entry: dict) -> None:
        with self._lock:
            if digest in self._shard:
                # Last-write-wins, same as the L1: deterministic solver,
                # so both writes carry the same verdict.
                self._shard.move_to_end(digest)
            self._shard[digest] = entry
            self.insertions += 1
            while len(self._shard) > self.capacity:
                self._shard.popitem(last=False)
                self.evictions += 1

    # -- reads -----------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._shard)

    def metrics(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._shard),
                "capacity": self.capacity,
                "lookups": self.lookups,
                "local_hits": self.local_hits,
                "remote_hits": self.remote_hits,
                "negative_hits": self.negative_hits,
                "misses": self.misses,
                "remote_errors": self.remote_errors,
                "puts_sent": self.puts_sent,
                "puts_failed": self.puts_failed,
                "puts_applied": self.puts_applied,
                "gets_served": self.gets_served,
                "insertions": self.insertions,
                "evictions": self.evictions,
            }
