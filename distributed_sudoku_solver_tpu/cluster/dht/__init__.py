"""The DHT plane the reference paper only claimed to have (ISSUE 17).

Three stdlib-only pieces, composed by ``cluster/node.py``:

* ``hashring``   — consistent-hash ownership of the canonical-key space
  (PR 14's symmetry-canonical digest): virtual-node ring over member
  addresses, ``owner(digest)`` plus a replica set, bounded key movement
  on join/leave.
* ``membership`` — SWIM-style gossip: one probe per beat with
  piggybacked state, suspicion before death, incarnation numbers for
  refutation.  O(1) per-beat traffic regardless of ring size, riding
  the node's existing (term,epoch) guard machinery for the
  authoritative view.
* ``cluster_cache`` — the cluster-wide result cache: lookup/store
  routed to the digest's owner over CACHE_GET/CACHE_PUT frames with the
  wire's at-least-once dedupe + retry budget.  Entries are plain
  JSON-ready dicts here; the dict <-> ``frontdoor.CacheEntry`` glue
  lives in ``cluster/node.py`` so this layer stays stdlib-closed
  (layerck: ``cluster.dht`` imports no jax, no numpy, no serving).

Every timing decision routes through an injected clock and every wire
interaction through injected callables — the simnet lane drives
hundreds of virtual DHT nodes deterministically.
"""

from distributed_sudoku_solver_tpu.cluster.dht.cluster_cache import ClusterCache
from distributed_sudoku_solver_tpu.cluster.dht.hashring import HashRing
from distributed_sudoku_solver_tpu.cluster.dht.membership import (
    ALIVE,
    DEAD,
    SUSPECT,
    Gossip,
)

__all__ = ["HashRing", "Gossip", "ClusterCache", "ALIVE", "SUSPECT", "DEAD"]
