"""Consistent-hash ownership of the canonical-key space.

The front door's symmetry-canonical digest (PR 14) is a sha256 hex
string naming a whole symmetry orbit of boards.  The ring maps that key
space onto cluster members: each member contributes ``vnodes`` virtual
points (sha256 of ``"addr#i"``), keys are owned by the first point at
or clockwise-after the key's position, and a join/leave moves only the
key arcs adjacent to the member's points — O(keys/n) expected, never a
full reshuffle.  ``replicas(key, n)`` walks further clockwise for the
distinct successor members, which is the read-repair/replication set.

Pure data structure: no locks (callers synchronize — ``ClusterNode``
mutates it under the node lock, ``ClusterCache`` reads it through an
injected ``owner_fn``), no clock, no wire.  Deterministic for a given
member set by construction, which is what makes owner placement
reproducible across every node that has converged on the same view.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Tuple

__all__ = ["HashRing"]


def _position(key: str) -> int:
    # 64-bit prefix of sha256 — collision probability is irrelevant at
    # cluster scale and 8 bytes keeps bisect comparisons cheap.
    return int.from_bytes(hashlib.sha256(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Virtual-node consistent-hash ring over member address strings."""

    def __init__(self, vnodes: int = 32):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._points: List[int] = []        # sorted vnode positions
        self._owner_at: Dict[int, str] = {}  # position -> member
        self._members: Dict[str, Tuple[int, ...]] = {}  # member -> its positions

    # -- membership ------------------------------------------------------

    def add(self, member: str) -> None:
        if member in self._members:
            return
        positions = []
        for i in range(self.vnodes):
            pos = _position(f"{member}#{i}")
            # Position collisions across members are broken by address
            # order so every converged view agrees on the winner.
            held = self._owner_at.get(pos)
            if held is not None:
                if held <= member:
                    continue
                self._members[held] = tuple(  # deadck: allow(externally synchronized pure structure: every mutating caller holds ClusterNode._ring_lock (cluster.ring, rank 49) — the docstring contract; the ring itself owns no lock so converged views stay a pure function of the member set)
                    p for p in self._members[held] if p != pos
                )
            else:
                bisect.insort(self._points, pos)
            self._owner_at[pos] = member  # deadck: allow(externally synchronized pure structure: same cluster.ring contract as _members above)
            positions.append(pos)
        self._members[member] = tuple(positions)

    def remove(self, member: str) -> None:
        positions = self._members.pop(member, None)
        if positions is None:
            return
        for pos in positions:
            if self._owner_at.get(pos) == member:
                del self._owner_at[pos]
                idx = bisect.bisect_left(self._points, pos)
                if idx < len(self._points) and self._points[idx] == pos:
                    del self._points[idx]

    def members(self) -> List[str]:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    # -- ownership -------------------------------------------------------

    def owner(self, key: str) -> Optional[str]:
        """The member owning ``key``, or None on an empty ring."""
        return self._owner_at_pos(_position(key))

    def _owner_at_pos(self, pos: int) -> Optional[str]:
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, pos)
        if idx == len(self._points):
            idx = 0  # wrap past the top of the ring
        return self._owner_at[self._points[idx]]

    def replicas(self, key: str, n: int = 2) -> List[str]:
        """Owner plus the next distinct successor members, <= n total."""
        if not self._points or n < 1:
            return []
        out: List[str] = []
        pos = _position(key)
        idx = bisect.bisect_right(self._points, pos)
        for step in range(len(self._points)):
            member = self._owner_at[self._points[(idx + step) % len(self._points)]]
            if member not in out:
                out.append(member)
                if len(out) >= n:
                    break
        return out

    def summary(self, sample: int = 64) -> dict:
        """Ownership summary for /network?scope=dht: share estimates by
        sampling ``sample`` evenly spaced ring positions per member count
        (exact arc math is O(points) too — sampling keeps the view cheap
        and is plenty for an operator eyeballing balance)."""
        if not self._points:
            return {"members": 0, "points": 0, "share": {}}
        share: Dict[str, int] = {}
        span = (1 << 64) // max(1, sample)
        for i in range(sample):
            owner = self._owner_at_pos(i * span)
            share[owner] = share.get(owner, 0) + 1
        return {
            "members": len(self._members),
            "points": len(self._points),
            "share": {m: c / sample for m, c in sorted(share.items())},
        }
