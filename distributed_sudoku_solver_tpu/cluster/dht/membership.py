"""SWIM-style gossip membership: probes, suspicion, incarnations.

The node's (term,epoch) view stays AUTHORITATIVE for who is in the
cluster — joins and evictions still flow through the coordinator's
guard machinery so the ledger/rehoming semantics are untouched.  What
gossip adds is O(1)-per-beat LIVENESS: each beat every node probes one
member (round-robin over a shuffled order) and piggybacks a bounded
batch of recent state updates on the PROBE/ACK frames, so "node X looks
dead" propagates epidemically instead of through per-beat full-view
broadcasts.

The SWIM pieces, mapped onto this repo:

* suspicion before death — a failed probe marks the target SUSPECT;
  only after ``suspicion_s`` with no refutation does it become DEAD and
  get reported (the node then feeds it to the existing NODE_FAILED /
  eviction path, which is where the authoritative view catches up).
* incarnation numbers — a node seeing itself suspected in a piggyback
  refutes by bumping its own incarnation; higher incarnation always
  wins, and on a tie DEAD > SUSPECT > ALIVE.
* bounded piggyback — every state change gets a finite retransmission
  budget (``_spread_budget``); ``updates()`` returns at most
  ``piggyback`` entries, freshest spread first, self always included.

State machine only: no wire, no threads.  The node drives it from the
heartbeat loop with its injected clock and owns all I/O, so the simnet
lane runs hundreds of these deterministically.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ...obs import lockdep

__all__ = ["Gossip", "ALIVE", "SUSPECT", "DEAD"]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

_RANK = {ALIVE: 0, SUSPECT: 1, DEAD: 2}


class _Entry:
    __slots__ = ("state", "inc", "since", "brown", "spread")

    def __init__(self, state: str, inc: int, since: float):
        self.state = state
        self.inc = inc
        self.since = since   # clock time this state was entered
        self.brown = False   # peer self-reported brownout (decline affinity)
        self.spread = 0      # remaining piggyback retransmissions


class Gossip:
    """Per-node gossip table.  All methods are thread-safe (probe acks
    arrive on transport handler threads while the heartbeat loop ticks).
    """

    def __init__(
        self,
        self_addr: str,
        clock,
        suspicion_s: float,
        piggyback: int = 8,
    ):
        self.self_addr = self_addr
        self._clock = clock
        self.suspicion_s = float(suspicion_s)
        self.piggyback = max(1, int(piggyback))
        self._lock = lockdep.named_lock("cluster.gossip")  # lockck: name(cluster.gossip)
        self._members: Dict[str, _Entry] = {}  # lockck: guard(_lock)
        self._order: List[str] = []  # lockck: guard(_lock) — probe round-robin
        self._cursor = 0  # lockck: guard(_lock)
        self._self_inc = 0  # lockck: guard(_lock)
        self._self_brown = False  # lockck: guard(_lock)
        # Deterministic per-node shuffle: the simnet soak replays
        # identically for a given address set.
        self._rng = random.Random(self_addr)  # lockck: guard(_lock)
        self.refutations = 0  # lockck: guard(_lock) — self-suspicions refuted
        self.suspicions = 0  # lockck: guard(_lock)
        self.deaths = 0  # lockck: guard(_lock) — suspicions expired to DEAD
        self.resurrections = 0  # lockck: guard(_lock) — view re-admitted a DEAD member
        self.stale_ignored = 0  # lockck: guard(_lock) — lower-incarnation updates dropped
        self.merged = 0  # lockck: guard(_lock) — updates applied

    # -- view sync -------------------------------------------------------

    def reconcile(self, members: List[str]) -> None:
        """Sync with the authoritative (term,epoch) view.  New members
        start ALIVE at incarnation 0; members evicted from the view are
        dropped; a DEAD member the view re-admits (rejoin through the
        coordinator) is resurrected ALIVE — the view advance IS the
        refutation, covering restarts whose incarnation reset to 0."""
        now = self._clock()
        with self._lock:
            want = {m for m in members if m != self.self_addr}
            changed = False
            for m in list(self._members):
                if m not in want:
                    del self._members[m]
                    changed = True
            for m in want:
                ent = self._members.get(m)
                if ent is None:
                    self._members[m] = _Entry(ALIVE, 0, now)
                    changed = True
                elif ent.state == DEAD:
                    ent.state = ALIVE
                    ent.since = now
                    ent.spread = self._budget_locked()
                    self.resurrections += 1
            if changed:
                self._order = sorted(self._members)
                self._rng.shuffle(self._order)
                self._cursor = 0

    # -- beat ------------------------------------------------------------

    def tick(self) -> Tuple[Optional[str], List[str]]:
        """One heartbeat: returns (probe target or None, members whose
        suspicion just expired to DEAD — report these to eviction)."""
        now = self._clock()
        with self._lock:
            newly_dead = []
            for m, ent in self._members.items():
                if ent.state == SUSPECT and now - ent.since >= self.suspicion_s:
                    ent.state = DEAD
                    ent.since = now
                    ent.spread = self._budget_locked()
                    self.deaths += 1
                    newly_dead.append(m)
            target = None
            for _ in range(len(self._order)):
                cand = self._order[self._cursor % len(self._order)]
                self._cursor += 1
                ent = self._members.get(cand)
                if ent is not None and ent.state != DEAD:
                    target = cand
                    break
            return target, newly_dead

    # -- piggyback -------------------------------------------------------

    def set_brown(self, brown: bool) -> None:
        with self._lock:
            self._self_brown = bool(brown)

    def updates(self) -> List[dict]:
        """Bounded piggyback batch: self first, then the freshest spread
        budgets.  Decrements each included entry's budget."""
        with self._lock:
            out = [
                {
                    "m": self.self_addr,
                    "s": ALIVE,
                    "i": self._self_inc,
                    "b": self._self_brown,
                }
            ]
            pending = sorted(
                (m for m, e in self._members.items() if e.spread > 0),
                key=lambda m: (-self._members[m].spread, m),
            )
            for m in pending[: self.piggyback - 1]:
                ent = self._members[m]
                ent.spread -= 1
                out.append({"m": m, "s": ent.state, "i": ent.inc, "b": ent.brown})
            return out

    def merge(self, updates: List[dict]) -> None:
        """Apply a piggyback batch (from a PROBE we handled or an ACK we
        received).  Incarnation order; DEAD > SUSPECT > ALIVE on ties;
        self-suspicion refuted by bumping our incarnation."""
        if not updates:
            return
        now = self._clock()
        with self._lock:
            for upd in updates:
                try:
                    m = upd["m"]
                    state = upd["s"]
                    inc = int(upd["i"])
                except (KeyError, TypeError, ValueError):
                    continue
                if state not in _RANK:
                    continue
                if m == self.self_addr:
                    if state != ALIVE and inc >= self._self_inc:
                        self._self_inc = inc + 1
                        self.refutations += 1
                    continue
                ent = self._members.get(m)
                if ent is None:
                    continue  # not in the authoritative view (yet) — ignore
                if inc < ent.inc:
                    self.stale_ignored += 1
                    continue
                if inc == ent.inc and _RANK[state] <= _RANK[ent.state]:
                    if state == ent.state:
                        ent.brown = bool(upd.get("b", ent.brown))
                    continue
                if ent.state != state:
                    ent.since = now
                    ent.spread = self._budget_locked()
                    if state == SUSPECT:
                        self.suspicions += 1
                ent.state = state
                ent.inc = inc
                ent.brown = bool(upd.get("b", ent.brown))
                self.merged += 1

    # -- probe outcomes --------------------------------------------------

    def on_ack(self, target: str) -> None:
        """A probe of ``target`` answered: it is alive at >= its known
        incarnation (the ACK's own piggyback carries the fresh one)."""
        now = self._clock()
        with self._lock:
            ent = self._members.get(target)
            if ent is not None and ent.state == SUSPECT:
                ent.state = ALIVE
                ent.since = now
                ent.spread = self._budget_locked()

    def on_probe_fail(self, target: str) -> None:
        now = self._clock()
        with self._lock:
            ent = self._members.get(target)
            if ent is not None and ent.state == ALIVE:
                ent.state = SUSPECT
                ent.since = now
                ent.spread = self._budget_locked()
                self.suspicions += 1

    # -- reads -----------------------------------------------------------

    def is_healthy(self, addr: str) -> bool:
        """ALIVE and not self-reporting brownout — the affinity gate."""
        if addr == self.self_addr:
            return True
        with self._lock:
            ent = self._members.get(addr)
            return ent is not None and ent.state == ALIVE and not ent.brown

    def state_of(self, addr: str) -> Optional[str]:
        if addr == self.self_addr:
            return ALIVE
        with self._lock:
            ent = self._members.get(addr)
            return ent.state if ent is not None else None

    def view(self) -> dict:
        with self._lock:
            members = {
                m: {
                    "state": e.state,
                    "incarnation": e.inc,
                    "brown": e.brown,
                    "since": round(e.since, 6),
                }
                for m, e in sorted(self._members.items())
            }
            members[self.self_addr] = {
                "state": ALIVE,
                "incarnation": self._self_inc,
                "brown": self._self_brown,
                "since": 0.0,
            }
            return members

    def metrics(self) -> dict:
        with self._lock:
            alive = sum(1 for e in self._members.values() if e.state == ALIVE) + 1
            suspect = sum(1 for e in self._members.values() if e.state == SUSPECT)
            dead = sum(1 for e in self._members.values() if e.state == DEAD)
            return {
                "alive": alive,
                "suspect": suspect,
                "dead": dead,
                "incarnation": self._self_inc,
                "refutations": self.refutations,
                "suspicions": self.suspicions,
                "deaths": self.deaths,
                "resurrections": self.resurrections,
                "stale_ignored": self.stale_ignored,
                "merged": self.merged,
            }

    # -- internal --------------------------------------------------------

    def _budget_locked(self) -> int:
        # SWIM's lambda*log(n) retransmission budget, floored so tiny
        # rings still converge in a couple of beats.
        return max(3, (len(self._members) + 1).bit_length() + 1)
