"""Deterministic in-memory cluster plane: the wire contract without sockets.

PR 5 gave the *device/serving* layer a deterministic, sleep-free fault
plane (``serving/faults.py``); this module is the same discipline one
layer up, at the cluster/network seam.  A :class:`SimNet` is a virtual
network + virtual clock that a :class:`ClusterNode` plugs into through the
transport/clock seam (``cluster/wire.py`` module note): nodes exchange the
same JSON frames with the same ``WireError`` surface, heartbeat loops
sleep on *virtual* time, and every hard distributed failure mode is a
programmable, seeded event instead of a wall-clock accident:

* **drop** — the frame is lost after the connect succeeded: the sender
  gets a ``WireError`` with ``ambiguous_delivery=True`` (exactly the TCP
  flavor where bytes were written before the reset), so its retry is
  honest at-least-once re-dispatch and the receiver's dedupe is what is
  actually under test;
* **dup** — the frame is delivered twice (the redelivery the sender never
  learns about);
* **delay** — delivery is deferred by a bounded, deterministically drawn
  virtual delay, i.e. reordering against later traffic on any link;
* **partitions** — one-way or symmetric: a blocked link refuses the
  connect (``ambiguous_delivery=False``), the way a partitioned TCP
  connect times out with no bytes written.

Per-link faults are driven by the existing seeded schedule machinery
(``serving/faults.FaultSchedule``) over *method-scoped link sites* —
``"link:<src>-><dst>:<METHOD>"`` with a per-site event index — so a unit
test can pin "drop the first SOLUTION from b to a" exactly
(:meth:`FaultSchedule.at`) and a chaos soak can Bernoulli-sample every
link event from one seed (:meth:`FaultSchedule.seeded`), independent of
thread interleaving on other sites.

Time is virtual: nothing in this module calls ``time.sleep``, and no test
driving it needs to.  ``advance(dt)`` moves the clock, wakes sleepers
(heartbeat loops), fires due deliveries, and waits — bounded, on real
condition variables — for the woken threads to take their scheduling
slice, so ``wait_until(net, pred)`` loops are fast and deterministic
where the socket lane's ``wait_for`` loops are wall-clock-bound and
fragile under CI load.  The ``simnet`` pytest marker's conftest guard
enforces the contract: a simnet-marked test that opens a real socket or
calls ``time.sleep`` fails.
"""

from __future__ import annotations

import heapq
import json
import logging
import random
import threading
import time as _time  # real time ONLY for bounded settling waits, never slept on
import zlib
from collections import deque
from typing import Callable, Iterable, Optional, Union

from distributed_sudoku_solver_tpu.cluster.wire import (
    MAX_FRAME,
    Addr,
    WireError,
    addr_str,
)
from distributed_sudoku_solver_tpu.obs import lockdep
from distributed_sudoku_solver_tpu.serving.faults import FaultSchedule

_LOG = logging.getLogger(__name__)

# Safety cap for threads blocked on virtual time (sleepers, request
# waiters): a test that forgets to advance the clock re-checks here
# instead of hanging its daemon threads forever.
_REAL_WAIT_CAP_S = 60.0

# A woken sleeper that has not re-slept within this many REAL seconds has
# exited its loop (node stopped) — settle() stops waiting for it.  A live
# beat's work is sub-millisecond; the grace only delays settle() once per
# killed node.
_BETWEEN_GRACE_S = 0.25

# Delivery worker pool size.  Deliveries used to spawn one ephemeral
# thread each, which is fine for a 5-node ring and catastrophic for a
# 500-node soak (every advance() step forked hundreds of threads and the
# interpreter spent its time in thread setup/teardown, not handlers).  A
# small pool of persistent daemon workers drains the same queue with the
# same semantics.  The pool is safe because of a contract the repo's
# handlers already obey: a wire handler NEVER blocks on virtual time (it
# computes a reply and returns; slow work — solving, result send retries
# — happens on node/engine threads).  A handler that virtually slept
# would have broken settle() under the old design too (its delivery
# counted in ``_active`` until return).
_POOL_WORKERS = 16

_AddrLike = Union[Addr, str]

# Captured at import on purpose: the simnet purity guard
# (tests/conftest.py, banned list from analysis/manifest.py) monkeypatches
# ``time.monotonic`` itself during simnet-marked tests — a runtime-banned
# module attribute — so the bounded REAL settling waits below must hold
# the function object, not re-resolve it per call.  Never slept on.
_monotonic = _time.monotonic


def _addr_s(a: _AddrLike) -> str:
    return addr_str(a) if isinstance(a, tuple) else a


class SimClock:
    """Virtual monotonic clock over a SimNet (the node's ``clock`` seam):
    ``sleep`` blocks the calling thread until ``advance`` moves virtual
    time past the deadline — no wall-clock involvement."""

    def __init__(self, net: "SimNet"):
        self._net = net

    def now(self) -> float:
        return self._net.now()

    def sleep(self, dt: float) -> None:
        self._net.sleep(dt)


class _Reply:
    """One request's reply slot; completed by the delivery thread."""

    def __init__(self, net: "SimNet"):
        self._net = net
        self.done = False
        self.result: Optional[dict] = None
        self.error: Optional[WireError] = None

    def complete(self, result: Optional[dict], error: Optional[WireError]) -> None:
        with self._net._cond:
            if self.done:
                return  # dup fault: first delivery's reply wins
            self.done = True
            self.result = result
            self.error = error
            self._net._cond.notify_all()


class SimTransport:
    """Per-node facade implementing the wire transport contract."""

    def __init__(self, net: "SimNet"):
        self._net = net
        self._addr_s: Optional[str] = None

    def bind(self, host: str, port: int) -> Addr:
        addr = self._net._bind(host, port)
        self._addr_s = addr_str(addr)
        return addr

    def serve(self, handler, on_error=None, io_timeout: float = 5.0) -> None:
        self._net._serve(self._addr_s, handler, on_error)

    def close(self) -> None:
        if self._addr_s is not None:
            self._net._unbind(self._addr_s)

    def send(self, addr: _AddrLike, msg: dict, timeout: float) -> None:
        self._net._route(self._addr_s or "client:0", addr, msg)

    def request(self, addr: _AddrLike, msg: dict, timeout: float) -> dict:
        return self._net._request(self._addr_s or "client:0", addr, msg, timeout)


class SimNet:
    """The virtual network: address space, links, faults, and the clock.

    ``schedule`` maps ``(link site, event index) -> fault kind`` for kinds
    ``drop`` / ``dup`` / ``delay`` (``serving/faults.FAULT_KINDS``); it can
    be installed late (:meth:`set_schedule`) so a test forms its ring
    cleanly and then turns on chaos.  ``delay_range`` bounds every
    simulated delay (drawn deterministically per link event from ``seed``),
    which bounds reordering.
    """

    def __init__(
        self,
        schedule: Optional[FaultSchedule] = None,
        delay_range: tuple = (0.02, 0.2),
        seed: int = 0,
    ):
        self._schedule = schedule
        self._delay_lo, self._delay_hi = delay_range
        self._seed = seed
        self._cond = lockdep.named_condition("cluster.simnet")  # lockck: name(cluster.simnet)
        self._now = 0.0
        self._closed = False
        self._seq = 0
        self._queue: list = []  # heap of (deliver_at, seq, dst_s, payload, reply)
        self._bound: set = set()
        self._handlers: dict = {}  # addr_s -> (handler, on_error)
        self._blocked: set = set()  # directed (src_s, dst_s) pairs
        self._link_idx: dict = {}  # link site -> next event index
        self._sleepers: dict = {}  # token -> virtual deadline
        # Threads that woke from a virtual sleep and have not re-entered
        # one yet (a heartbeat loop mid-beat): settle() waits for them so
        # the beat's sends land before the driver advances time again —
        # without this, a galloping test clock can expire failure
        # detectors while the detector threads never got a real slice.
        # Entries carry the REAL wake time; one older than _BETWEEN_GRACE_S
        # belongs to a thread that exited its loop (node stopped) and is
        # purged.
        self._between: dict = {}  # thread ident -> real wake time
        self._active = 0  # deliveries enqueued or in a handler
        self._work: deque = deque()  # due deliveries awaiting a worker
        self._workers_started = 0
        self._idle_workers = 0
        self._worker_idents: set = set()  # pool + overflow thread idents
        self._next_port = 7000
        self.clock = SimClock(self)
        # Observability for tests: what the network actually did.
        self.counters = {
            "sent": 0,
            "delivered": 0,
            "dropped": 0,
            "duplicated": 0,
            "delayed": 0,
            "blocked": 0,
        }

    # -- clock ---------------------------------------------------------------
    def now(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, dt: float) -> None:
        token = object()
        tid = threading.get_ident()
        with self._cond:
            self._between.pop(tid, None)
            deadline = self._now + dt
            self._sleepers[token] = deadline
            self._cond.notify_all()
            try:
                while self._now < deadline and not self._closed:
                    self._cond.wait(_REAL_WAIT_CAP_S)
            finally:
                del self._sleepers[token]
                if not self._closed:
                    self._between[tid] = _monotonic()
                self._cond.notify_all()

    def advance(self, dt: float = 0.05, settle: bool = True) -> None:
        """Move virtual time forward: wake due sleepers, fire due
        deliveries, then (bounded, real) wait for the woken threads to get
        a scheduling slice so their reactions land before the caller's
        next predicate check."""
        with self._cond:
            self._now += dt
            while self._queue and self._queue[0][0] <= self._now:
                self._enqueue_locked(heapq.heappop(self._queue))
            self._cond.notify_all()
            # Hand the CPU to woken sleepers (heartbeat loops): each
            # removes its entry on the way out of sleep().  The real
            # deadline scales mildly with population — 500 heartbeat
            # loops legitimately need more slices than 5.
            real_deadline = _monotonic() + max(2.0, 0.01 * len(self._sleepers))
            while any(d <= self._now for d in self._sleepers.values()):
                if _monotonic() >= real_deadline:
                    break
                self._cond.wait(0.005)
        if settle:
            self.settle()

    def settle(self, real_timeout: Optional[float] = None) -> bool:
        """Wait (real, bounded) until every due delivery has been handed to
        its handler, the handler returned, and every woken sleeper (a
        heartbeat loop mid-beat) has re-entered its sleep — the yield point
        between a virtual step and the next predicate check."""
        if real_timeout is None:
            # Scales with population: a 500-node beat's probe fan has far
            # more deliveries to drain through the pool than a 3-node ring.
            with self._cond:
                real_timeout = max(10.0, 0.05 * len(self._handlers))
        deadline = _monotonic() + real_timeout
        with self._cond:
            while True:
                while self._queue and self._queue[0][0] <= self._now:
                    self._enqueue_locked(heapq.heappop(self._queue))
                now_r = _monotonic()
                for tid in [
                    t
                    for t, ts in self._between.items()
                    if now_r - ts > _BETWEEN_GRACE_S
                ]:
                    del self._between[tid]  # thread exited its loop
                if (
                    self._active == 0
                    and not self._between
                    and not (self._queue and self._queue[0][0] <= self._now)
                ):
                    return True
                if now_r >= deadline:
                    return False
                self._cond.wait(0.005)

    # -- topology ------------------------------------------------------------
    def partition(
        self, a: Iterable[_AddrLike], b: Iterable[_AddrLike], one_way: bool = False
    ) -> None:
        """Block every link from ``a`` to ``b`` (and the reverse unless
        ``one_way``): a blocked send fails like a partitioned TCP connect —
        ``WireError``, no bytes written, delivery unambiguous."""
        aa = [_addr_s(x) for x in a]
        bb = [_addr_s(x) for x in b]
        with self._cond:
            for x in aa:
                for y in bb:
                    if x != y:
                        self._blocked.add((x, y))
                        if not one_way:
                            self._blocked.add((y, x))

    def heal(self) -> None:
        """Remove every partition (links carry traffic again)."""
        with self._cond:
            self._blocked.clear()

    def set_schedule(self, schedule: Optional[FaultSchedule]) -> None:
        """Install (or clear) the link-fault schedule mid-run — e.g. after
        forming a ring cleanly.  Event indices keep counting."""
        with self._cond:
            self._schedule = schedule

    def inject(self, dst: _AddrLike, msg: dict, src: str = "test:0") -> None:
        """Deliver a forged frame (the adversarial lane's ``send_msg``)."""
        self._route(src, dst, msg)

    def transport(self) -> SimTransport:
        return SimTransport(self)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._handlers.clear()
            self._cond.notify_all()

    # -- binding (SimTransport internals) ------------------------------------
    def _bind(self, host: str, port: int) -> Addr:
        with self._cond:
            if port == 0:
                port = self._next_port
                self._next_port += 1
            addr = (host, port)
            s = addr_str(addr)
            if s in self._bound:
                raise WireError(f"address {s} already bound")
            self._bound.add(s)
            return addr

    def _serve(self, addr_s: str, handler, on_error) -> None:
        with self._cond:
            self._handlers[addr_s] = (handler, on_error)

    def _unbind(self, addr_s: str) -> None:
        with self._cond:
            self._handlers.pop(addr_s, None)
            self._bound.discard(addr_s)

    # -- routing -------------------------------------------------------------
    def _delay_for(self, site: str, idx: int) -> float:
        # Same keying discipline as FaultSchedule.seeded: packed-int seed,
        # order-independent, free of hash randomization.
        key = (
            ((self._seed & 0xFFFFFFFF) << 96)
            | (zlib.crc32(site.encode()) << 64)
            | idx
        )
        rng = random.Random(key)
        return self._delay_lo + (self._delay_hi - self._delay_lo) * rng.random()

    def _route(self, src_s: str, dst: _AddrLike, msg: dict, reply=None) -> None:
        dst_s = _addr_s(dst)
        # The JSON round-trip is the wire contract: same serializability
        # requirement, same size cap, and the receiver gets an isolated
        # copy exactly as if it had been framed over a socket.
        payload = json.dumps(msg)
        if len(payload) > MAX_FRAME:
            raise WireError(f"frame too large: {len(payload)} bytes")
        with self._cond:
            if self._closed:
                raise WireError(f"connect to {dst_s} failed: simnet closed")
            if (src_s, dst_s) in self._blocked:
                self.counters["blocked"] += 1
                raise WireError(
                    f"connect to {dst_s} timed out (simulated partition)"
                )
            if dst_s not in self._handlers:
                raise WireError(f"connect to {dst_s} refused (not listening)")
            site = f"link:{src_s}->{dst_s}:{msg.get('method')}"
            idx = self._link_idx.get(site, 0)
            self._link_idx[site] = idx + 1
            kind = self._schedule.lookup(site, idx) if self._schedule else None
            self.counters["sent"] += 1
            now = self._now
            if kind == "drop":
                self.counters["dropped"] += 1
                deliveries = []
            elif kind == "dup":
                self.counters["duplicated"] += 1
                deliveries = [now, now + self._delay_for(site, idx)]
            elif kind == "delay":
                self.counters["delayed"] += 1
                deliveries = [now + self._delay_for(site, idx)]
            else:
                deliveries = [now]
            for at in deliveries:
                self._seq += 1
                item = (at, self._seq, dst_s, payload, reply)
                if at > now:
                    heapq.heappush(self._queue, item)
                else:
                    self._enqueue_locked(item)
        if kind == "drop":
            # The sender's view of a frame lost after connect: ambiguous —
            # its retry (if any) is honest at-least-once re-dispatch.
            raise WireError(
                f"send to {dst_s} reset mid-frame (simulated drop "
                f"[site={site} #{idx}])",
                ambiguous_delivery=True,
            )

    def _enqueue_locked(self, item) -> None:
        # Caller holds self._cond.  Hands a due delivery to the worker
        # pool, growing it (up to the cap) when the backlog outruns the
        # idle workers.
        self._active += 1
        self._work.append(item)
        if self._idle_workers < len(self._work):
            if self._workers_started < _POOL_WORKERS:
                self._workers_started += 1
                threading.Thread(
                    target=self._worker,
                    daemon=True,
                    name=f"sim-worker-{self._workers_started}",
                ).start()
            elif (
                self._idle_workers == 0
                and threading.get_ident() in self._worker_idents
            ):
                # A handler running ON the last free worker just issued a
                # nested send/request (e.g. a node forwarding during
                # dispatch).  With every worker occupied that delivery
                # could starve the pool the handler is waiting on, so a
                # transient overflow worker drains until the backlog dries.
                threading.Thread(
                    target=self._overflow_worker, daemon=True,
                    name="sim-overflow",
                ).start()
        self._cond.notify_all()

    def _worker(self) -> None:
        # Persistent delivery worker: drains self._work, calling each
        # handler OUTSIDE the condition (same invariant the per-delivery
        # threads had).  Exits when the net closes and the backlog is dry.
        self._register_worker()
        while True:
            with self._cond:
                self._idle_workers += 1
                try:
                    while not self._work and not self._closed:
                        self._cond.wait(_REAL_WAIT_CAP_S)
                    if not self._work:
                        return  # closed and dry
                    item = self._work.popleft()
                finally:
                    self._idle_workers -= 1
            self._deliver(item)

    def _overflow_worker(self) -> None:
        self._register_worker()
        while True:
            with self._cond:
                if not self._work:
                    self._worker_idents.discard(threading.get_ident())
                    return
                item = self._work.popleft()
            self._deliver(item)

    def _register_worker(self) -> None:
        with self._cond:
            self._worker_idents.add(threading.get_ident())

    def _deliver(self, item) -> None:
        _at, _seq, dst_s, payload, reply = item
        try:
            with self._cond:
                entry = self._handlers.get(dst_s)
            if entry is None:
                # Receiver died between send and delivery — like a frame
                # accepted by a dying process.
                if reply is not None:
                    reply.complete(
                        None,
                        WireError(
                            f"peer {dst_s} gone", ambiguous_delivery=True
                        ),
                    )
                return
            handler, on_error = entry
            result = None
            try:
                result = handler(json.loads(payload))
            except Exception as e:  # noqa: BLE001 - mirror TcpTransport:
                # handler failures are logged-and-dropped, never fatal.
                if on_error is not None:
                    on_error(e)
                else:
                    _LOG.error("[simnet] handler error at %s: %r", dst_s, e)
            with self._cond:
                self.counters["delivered"] += 1
            if reply is not None:
                if result is None:
                    # The request WAS processed; only the reply is missing —
                    # the ambiguous flavor, like wire.request's
                    # "failed awaiting reply".
                    reply.complete(
                        None,
                        WireError(
                            f"no reply from {dst_s}", ambiguous_delivery=True
                        ),
                    )
                else:
                    reply.complete(result, None)
        finally:
            with self._cond:
                self._active -= 1
                self._cond.notify_all()

    def _request(self, src_s: str, dst: _AddrLike, msg: dict, timeout: float) -> dict:
        reply = _Reply(self)
        self._route(src_s, dst, msg, reply=reply)
        with self._cond:
            deadline = self._now + timeout
            while not reply.done and self._now < deadline and not self._closed:
                self._cond.wait(_REAL_WAIT_CAP_S)
        if not reply.done:
            raise WireError(
                f"request to {_addr_s(dst)} timed out after {timeout}s (virtual)",
                ambiguous_delivery=True,
            )
        if reply.error is not None:
            raise reply.error
        return reply.result


def wait_until(
    net: SimNet,
    pred: Callable[[], bool],
    timeout: float = 120.0,
    step: float = 0.05,
    pace_s: float = 0.002,
) -> bool:
    """The simnet twin of the socket tests' ``wait_for``: advance virtual
    time in ``step`` increments until ``pred()`` holds or ``timeout``
    *virtual* seconds elapse.  Settles between steps so node threads react
    before each check, and yields ``pace_s`` of real scheduling time per
    step so work that lives OUTSIDE the virtual clock (engine device
    loops) progresses alongside it.  No protocol timing ever depends on
    the wall clock — real waits here are bounded scheduler yields, never
    ``time.sleep``."""
    deadline = net.now() + timeout
    pacer = threading.Event()  # never set: wait() is a bounded real yield
    while True:
        net.settle()
        if pred():
            return True
        if net.now() >= deadline:
            return pred()
        net.advance(step)
        if pace_s:
            pacer.wait(pace_s)
