"""Branch-ordering heads: pluggable, batched scored branch selection.

ROADMAP #4 (ISSUE 19).  Every tier of the stack used to dispatch the same
hardwired MRV key; this module makes the *branch-cell choice* a first-class
scoring head.  A head is a frozen, hashable dataclass (jit-static, like
``SudokuCSP`` itself) exposing one seam in two layouts:

* ``score_lanes(cand, geom) -> f32[L, cells]`` — the lane-first XLA batch
  site (``models/sudoku.py:_branch_cell_onehot``).
* ``score_full(cand, geom, unit_sum) -> f32[n, n, T]`` — the boards-last
  Mosaic site (``ops/pallas_step.py:branch_onehot_full``).  ``unit_sum`` is
  injected by the kernel (its cell-uniform ``_unit_full`` reduction) so the
  head never needs pallas-internal helpers; everything a head computes here
  must stay Mosaic-legal (pure elementwise VPU ops + the injected
  reductions — no gather/scatter, no bool carries, Python-int constants).

Lower score = branch here.  :func:`pack_key` turns a score into the packed
int32 argmin key the engine already selects on (``q * n^2 + cell_index``),
which keeps tie-breaks deterministic (lowest cell wins) and makes the
``minrem`` head *bit-exact* to the legacy key: score = popcount, quant = 1
reproduces ``pc * n^2 + cell`` integer-for-integer, so the default search
tree is untouched.

Heads ship in three flavors (selected via ``SolverConfig.branch =
'head:<name>'``):

* ``minrem``  — the legacy MRV rule re-expressed as a head (bit-exact).
* ``cw-slack`` — constrainedness-weighted MRV: candidate count primary,
  peer-unit slack (sum of ``candidates - 1`` over the cell's row/col/box
  peers — the in-graph twin of ``probe_propagate``'s branching-slack
  score) as the tie-break, *tightest neighborhood first*.  Pure VPU ops.
* ``mlp``     — a tiny learned prior: one hidden layer over the cell's
  bitmask-neighborhood features, f32 matmul on the MXU lane-side, unrolled
  FMAs kernel-side.  Weights train offline (``benchmarks/train_ordering.py``)
  from per-branch (state, chosen-cell, subtree-nodes) examples recorded by
  the opt-in ordering trace (``obs/ordertrace.py``); they load via stdlib
  json only — importing this module never imports jax.

Correctness contract: the default ``minrem`` path stays byte-identical
(head dispatch is a Python-level static branch); non-default heads relax
bit-exactness to **verdict-equality** — solutions oracle-checked, unsat
cross-checked by ``count_all`` (tests/test_ordering.py).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Callable, Optional, Tuple

#: Decided/invalid cells take this key: any live score packs strictly
#: smaller, so argmin never lands on a decided cell while work remains.
#: Python int on purpose — pallas rejects captured jnp scalars.
BIG = 2**30

#: The shipped heads, in registry order.  ``head:<name>`` spellings of
#: these are valid ``SolverConfig.branch`` / ``SudokuCSP.branch_rule``
#: values; anything else is a config-time error.
HEAD_NAMES = ("minrem", "cw-slack", "mlp")

#: Legacy (non-head) branch rules, shared with SolverConfig/SudokuCSP
#: validation so the accepted set has one spelling.
LEGACY_RULES = ("minrem", "first", "mixed", "minrem-desc")

_WEIGHTS_FILE = os.path.join(os.path.dirname(__file__), "ordering_weights.json")


def is_head_rule(rule: str) -> bool:
    return isinstance(rule, str) and rule.startswith("head:")


def validate_branch(rule: str) -> None:
    """Config-time validation of a branch rule string (legacy or head).

    Raises ``ValueError`` on anything the engine would only reject at
    solve/trace time otherwise — ``SolverConfig.__post_init__`` and
    ``SudokuCSP.__post_init__`` both route through here (satellite:
    surface incompatibilities at config time, not mid-flight)."""
    if rule in LEGACY_RULES:
        return
    if is_head_rule(rule):
        name = rule[len("head:"):]
        if name in HEAD_NAMES:
            return
        raise ValueError(
            f"unknown branch head {name!r} (known: {', '.join(HEAD_NAMES)})"
        )
    raise ValueError(
        f"unknown branch rule {rule!r} (legacy: {', '.join(LEGACY_RULES)}; "
        f"heads: {', '.join('head:' + h for h in HEAD_NAMES)})"
    )


def _qmax(n: int) -> int:
    # Largest quantized score that still packs under BIG with the cell
    # index in the low bits.
    return BIG // (n * n) - 1


def pack_key(score, und, cell, n: int, quant: int):
    """f32 score -> packed int32 argmin key (``q * n^2 + cell``).

    ``und`` masks decided cells to :data:`BIG`; ``quant`` scales the score
    before round-to-nearest (a head with lexicographic structure picks a
    power of two so component boundaries stay exact in f32).  Works in
    both layouts — ``score``/``und``/``cell`` just have to agree."""
    import jax.numpy as jnp

    q = jnp.clip(jnp.round(score * quant), 0, _qmax(n)).astype(jnp.int32)
    return jnp.where(und, q * (n * n) + cell, jnp.int32(BIG))


def _unit_sums_lanes(x, geom):
    """Row/col/box sums of ``x`` [L, n, n], each broadcast back to cells."""
    import jax.numpy as jnp

    vb, hb, bh, bw = geom.n_vboxes, geom.n_hboxes, geom.box_h, geom.box_w
    lanes = x.shape[0]
    row = jnp.sum(x, axis=2, keepdims=True) + jnp.zeros_like(x)
    col = jnp.sum(x, axis=1, keepdims=True) + jnp.zeros_like(x)
    boxes = x.reshape(lanes, vb, bh, hb, bw)
    box = jnp.sum(boxes, axis=(2, 4), keepdims=True) + jnp.zeros_like(boxes)
    return row, col, box.reshape(lanes, geom.n, geom.n)


# -- the heads -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MinremHead:
    """The legacy MRV rule as a head: score = candidate count.

    quant = 1 makes ``pack_key`` reproduce the historical
    ``pc * n^2 + cell`` key integer-for-integer — selection, search tree,
    and node counts are bit-identical to ``branch='minrem'``."""

    name: str = "minrem"
    quant: int = 1

    def score_lanes(self, cand, geom):
        import jax
        import jax.numpy as jnp

        lanes = cand.shape[0]
        pc = jax.lax.population_count(cand).astype(jnp.int32)
        return pc.reshape(lanes, geom.n * geom.n).astype(jnp.float32)

    def score_full(self, cand, geom, unit_sum):
        import jax
        import jax.numpy as jnp

        return jax.lax.population_count(cand).astype(jnp.int32).astype(jnp.float32)


#: Peer-slack saturation: one less than the cw-slack quant so the slack
#: tie-break can never carry into the candidate-count component.
_SLACK_CAP = 2047


@dataclasses.dataclass(frozen=True)
class CwSlackHead:
    """Constrainedness-weighted MRV: fewest candidates first, tightest
    peer neighborhood as the tie-break.

    The tie-break term is the branching slack of the cell's peers — the
    sum of ``candidates - 1`` over undecided cells sharing its row, column
    or box (each peer counted once per shared unit), exactly the quantity
    ``probe_propagate`` scores whole boards by at the front door.  A cell
    whose neighborhood holds little slack sits in a near-decided region:
    guessing there propagates further and refutes earlier, which is what
    shrinks the tree on the hard tail.  Lexicographic packing:
    ``score = pc + min(peer_slack, 2047) / 2048`` with quant 2048 — both
    components exact in f32, candidate count always dominant."""

    name: str = "cw-slack"
    quant: int = 2048

    def _score(self, pc, row, col, box):
        # Shared arithmetic for both layouts: inputs are the int32
        # popcount map and its three unit sums of (pc - 1 over undecided).
        import jax.numpy as jnp

        excess = jnp.where(pc > 1, pc - 1, 0)
        peer = row + col + box - 3 * excess
        peer = jnp.minimum(peer, _SLACK_CAP).astype(jnp.float32)
        return pc.astype(jnp.float32) + peer * (1.0 / (_SLACK_CAP + 1))

    def score_lanes(self, cand, geom):
        import jax
        import jax.numpy as jnp

        lanes = cand.shape[0]
        pc = jax.lax.population_count(cand).astype(jnp.int32)
        excess = jnp.where(pc > 1, pc - 1, 0)
        row, col, box = _unit_sums_lanes(excess, geom)
        score = self._score(pc, row, col, box)
        return score.reshape(lanes, geom.n * geom.n)

    def score_full(self, cand, geom, unit_sum):
        import jax
        import jax.numpy as jnp

        pc = jax.lax.population_count(cand).astype(jnp.int32)
        excess = jnp.where(pc > 1, pc - 1, 0)
        row, col, box = unit_sum(excess)
        return self._score(pc, row, col, box)


def _cell_features(pc, excess, row_e, col_e, box_e, row_u, col_u, box_u, n):
    """The 7 per-cell feature maps the MLP scores, in fixed order.

    Shared by both in-graph layouts AND the numpy recorder
    (:func:`features_np`) — train/serve skew here silently mis-ranks
    every branch, so there is exactly one definition.  All features are
    ~unit-scaled so offline training needs no normalization state."""
    import jax.numpy as jnp

    f32 = jnp.float32
    inv_n = 1.0 / n
    inv_n2 = 1.0 / (n * n)
    return (
        pc.astype(f32) * inv_n,                     # own candidate count
        (row_e - excess).astype(f32) * inv_n2,      # row peer slack
        (col_e - excess).astype(f32) * inv_n2,      # col peer slack
        (box_e - excess).astype(f32) * inv_n2,      # box peer slack
        row_u.astype(f32) * inv_n,                  # undecided row peers
        col_u.astype(f32) * inv_n,
        box_u.astype(f32) * inv_n,
    )


@dataclasses.dataclass(frozen=True)
class MlpHead:
    """Tiny learned branch prior: one hidden layer over the cell's
    bitmask-neighborhood features, trained to predict log2(subtree nodes).

    Weights are tuples of Python floats (hashable — the head is jit-static
    like the problem object that names it) produced by
    ``benchmarks/train_ordering.py`` and loaded via stdlib json.  Lane-side
    the layer runs as one f32 matmul (``preferred_element_type`` pins the
    MXU accumulate); kernel-side the same arithmetic unrolls into
    per-feature FMAs so the boards-last layout stays Mosaic-legal.  The
    raw score is shifted/clipped into [0, 16) by :func:`pack_key`'s clamp;
    quant 4096 keeps ~12 bits of ranking resolution."""

    w1: Tuple[Tuple[float, ...], ...]  # [F][H]
    b1: Tuple[float, ...]              # [H]
    w2: Tuple[float, ...]              # [H]
    b2: float
    name: str = "mlp"
    quant: int = 4096

    def _features(self, cand, geom, unit_sum):
        import jax
        import jax.numpy as jnp

        pc = jax.lax.population_count(cand).astype(jnp.int32)
        und = (pc > 1).astype(jnp.int32)
        excess = jnp.where(pc > 1, pc - 1, 0)
        row_e, col_e, box_e = unit_sum(excess)
        row_u, col_u, box_u = unit_sum(und)
        return _cell_features(
            pc, excess, row_e, col_e, box_e,
            row_u - und, col_u - und, box_u - und, geom.n,
        )

    def score_lanes(self, cand, geom):
        import jax.numpy as jnp

        lanes = cand.shape[0]
        feats = self._features(
            cand, geom, unit_sum=lambda x: _unit_sums_lanes(x, geom)
        )
        x = jnp.stack(
            [f.reshape(lanes, geom.n * geom.n) for f in feats], axis=-1
        )
        w1 = jnp.asarray(self.w1, dtype=jnp.float32)
        h = jnp.maximum(
            jnp.dot(x, w1, preferred_element_type=jnp.float32)
            + jnp.asarray(self.b1, dtype=jnp.float32),
            0.0,
        )
        out = jnp.dot(
            h, jnp.asarray(self.w2, dtype=jnp.float32),
            preferred_element_type=jnp.float32,
        ) + self.b2
        return out + 8.0  # shift into pack_key's non-negative clamp range

    def score_full(self, cand, geom, unit_sum):
        import jax.numpy as jnp

        feats = self._features(cand, geom, unit_sum)
        hidden = []
        for j in range(len(self.b1)):
            acc = feats[0] * self.w1[0][j]
            for f in range(1, len(self.w1)):
                acc = acc + feats[f] * self.w1[f][j]
            hidden.append(jnp.maximum(acc + self.b1[j], 0.0))
        out = hidden[0] * self.w2[0]
        for j in range(1, len(hidden)):
            out = out + hidden[j] * self.w2[j]
        return out + (self.b2 + 8.0)


# -- registry ------------------------------------------------------------------


def _to_tuples(rows):
    return tuple(tuple(float(v) for v in row) for row in rows)


def load_mlp_weights(path: Optional[str] = None) -> MlpHead:
    """Build the mlp head from a weights json (stdlib only — no jax).

    Schema (``benchmarks/train_ordering.py train`` emits it)::

        {"schema": "dsst-ordering-mlp/1",
         "w1": [[..H floats..] x F], "b1": [..H..], "w2": [..H..], "b2": f}
    """
    with open(path or _WEIGHTS_FILE) as fh:
        data = json.load(fh)
    if data.get("schema") != "dsst-ordering-mlp/1":
        raise ValueError(f"unknown ordering weights schema {data.get('schema')!r}")
    return MlpHead(
        w1=_to_tuples(data["w1"]),
        b1=tuple(float(v) for v in data["b1"]),
        w2=tuple(float(v) for v in data["w2"]),
        b2=float(data["b2"]),
    )


@functools.lru_cache(maxsize=None)
def get_head(rule: str):
    """Resolve ``'head:<name>'`` (or a bare head name) to THE head object.

    Cached so every ``sudoku_csp(geom, config)`` call sees the identical
    hashable instance — jit caches keyed on the problem object never fork
    across lookups.  The mlp head resolves its committed default weights
    here; a custom weights file is a different head object by construction
    (build it with :func:`load_mlp_weights` and pass it explicitly)."""
    name = rule[len("head:"):] if is_head_rule(rule) else rule
    if name == "minrem":
        return MinremHead()
    if name == "cw-slack":
        return CwSlackHead()
    if name == "mlp":
        return load_mlp_weights()
    raise ValueError(
        f"unknown branch head {name!r} (known: {', '.join(HEAD_NAMES)})"
    )


# -- host-side mirror: numpy propagation + the branch-example recorder ---------
#
# The learned head trains on per-branch (state, chosen-cell, subtree-nodes)
# examples.  The device kernels cannot journal per-branch data without
# paying a host sync per node, so examples come from a host replay that
# mirrors the kernel's semantics: bitmask states, elimination +
# hidden-singles propagation, MRV/ascending-digit DFS.  numpy only — this
# path must run wherever the opt-in trace ran, jax-free.


def _np_propagate(m, geom, max_sweeps: int = 64):
    """Eliminations + hidden singles to a fixpoint on a bitmask board.

    Returns ``(m, status)`` with status 'solved' | 'unsat' | 'open' —
    the host twin of ``ops/propagate.py`` at the basic rule tier (the
    recorder's teacher solves; head training never needs the extended
    tiers, branching statistics dominate)."""
    import numpy as np

    n = geom.n
    vb, hb, bh, bw = geom.n_vboxes, geom.n_hboxes, geom.box_h, geom.box_w
    digits = np.arange(n, dtype=np.int64)
    weights = np.int64(1) << digits

    def popcounts(mm):
        return ((mm[..., None] >> digits) & 1).sum(-1)

    for _ in range(max_sweeps):
        prev = m
        pc = popcounts(m)
        if (m == 0).any():
            return m, "unsat"
        singles = np.where(pc == 1, m, 0)
        sb = (singles[..., None] >> digits) & 1
        if (sb.sum(axis=1) > 1).any() or (sb.sum(axis=0) > 1).any():
            return m, "unsat"
        if (sb.reshape(vb, bh, hb, bw, n).sum(axis=(1, 3)) > 1).any():
            return m, "unsat"
        row_or = np.bitwise_or.reduce(singles, axis=1)
        col_or = np.bitwise_or.reduce(singles, axis=0)
        box_or = np.bitwise_or.reduce(
            np.bitwise_or.reduce(singles.reshape(vb, bh, hb, bw), axis=3),
            axis=1,
        )
        box_exp = np.repeat(np.repeat(box_or, bh, axis=0), bw, axis=1)
        m = m & ~((row_or[:, None] | col_or[None, :] | box_exp) & ~singles)
        if (m == 0).any():
            return m, "unsat"
        bits = (m[..., None] >> digits) & 1
        row_u = bits.sum(axis=1) == 1
        col_u = bits.sum(axis=0) == 1
        box_u = bits.reshape(vb, bh, hb, bw, n).sum(axis=(1, 3)) == 1
        box_u_exp = np.repeat(np.repeat(box_u, bh, axis=0), bw, axis=1)
        uniq = row_u[:, None, :] | col_u[None, :, :] | box_u_exp
        hid = m & (uniq * weights).sum(-1)
        if (popcounts(hid) > 1).any():
            return m, "unsat"
        m = np.where(hid != 0, hid, m)
        if np.array_equal(m, prev):
            break
    pc = popcounts(m)
    if (pc == 1).all():
        return m, "solved"
    return m, "open"


def features_np(m, geom):
    """f32[n, n, 7] — the numpy twin of the in-graph feature maps.

    MUST rank identically to :func:`_cell_features` (pinned by
    tests/test_ordering.py's parity test): training reads these, serving
    reads those."""
    import numpy as np

    n = geom.n
    vb, hb, bh, bw = geom.n_vboxes, geom.n_hboxes, geom.box_h, geom.box_w
    digits = np.arange(n, dtype=np.int64)
    pc = ((m[..., None] >> digits) & 1).sum(-1)
    und = (pc > 1).astype(np.int64)
    excess = np.where(pc > 1, pc - 1, 0)

    def unit(x):
        row = np.repeat(x.sum(axis=1, keepdims=True), n, axis=1)
        col = np.repeat(x.sum(axis=0, keepdims=True), n, axis=0)
        box = x.reshape(vb, bh, hb, bw).sum(axis=(1, 3))
        box = np.repeat(np.repeat(box, bh, axis=0), bw, axis=1)
        return row, col, box

    row_e, col_e, box_e = unit(excess)
    row_u, col_u, box_u = unit(und)
    feats = np.stack(
        [
            pc / n,
            (row_e - excess) / (n * n),
            (col_e - excess) / (n * n),
            (box_e - excess) / (n * n),
            (row_u - und) / n,
            (col_u - und) / n,
            (box_u - und) / n,
        ],
        axis=-1,
    )
    return feats.astype(np.float32)


def record_branch_examples(grid, geom, max_nodes: int = 50_000):
    """Replay one solve host-side, journaling every branch decision.

    Returns ``(examples, nodes)`` where each example is
    ``{"features": [7 floats], "pc": int, "nodes": int}`` — the chosen
    cell's feature vector and the size of the subtree its guess opened
    (the regression target ``benchmarks/train_ordering.py`` fits).  The
    replay is the kernel's own strategy (MRV cell, ascending digits,
    binary guess/rest split) so examples cover exactly the states the
    device search visits."""
    import numpy as np

    n = geom.n
    g = np.asarray(grid, dtype=np.int64)
    full = (1 << n) - 1
    m0 = np.full((n, n), full, dtype=np.int64)
    nz = g > 0
    m0[nz] = np.int64(1) << (g[nz] - 1)
    digits = np.arange(n, dtype=np.int64)

    examples = []
    budget = [max_nodes]

    import sys

    # Rest-chains recurse one frame per candidate digit eliminated; a
    # pathological 9x9 tree can sit deeper than CPython's default 1000.
    old_limit = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old_limit, 20_000))

    def dfs(m):
        """Returns (solved, subtree_nodes) — the kernel's binary scheme:
        guess = lowest candidate digit at the MRV cell, rest = the other
        candidates as one state (MRV re-chooses on the rest child)."""
        m, status = _np_propagate(m, geom)
        if status == "solved":
            return True, 0
        if status == "unsat" or budget[0] <= 0:
            return False, 0
        budget[0] -= 1
        pc = ((m[..., None] >> digits) & 1).sum(-1)
        key = np.where(pc > 1, pc * (n * n) + np.arange(n * n).reshape(n, n), BIG)
        cell = int(key.argmin())
        r, c = divmod(cell, n)
        feats = features_np(m, geom)[r, c]
        ex = {"features": [float(v) for v in feats], "pc": int(pc[r, c]), "nodes": 0}
        examples.append(ex)
        low = m[r, c] & -m[r, c]
        guess = m.copy()
        guess[r, c] = low
        solved, sub_g = dfs(guess)
        nodes = 1 + sub_g
        if not solved:
            rest = m.copy()
            rest[r, c] &= ~low
            solved, sub_r = dfs(rest)
            nodes += sub_r
        ex["nodes"] = nodes
        return solved, nodes

    try:
        solved, total = dfs(m0)
    finally:
        sys.setrecursionlimit(old_limit)
    return examples, total
