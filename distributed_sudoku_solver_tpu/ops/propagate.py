"""Constraint propagation as batched boolean tensor ops.

This replaces the reference's only inference rule — the per-guess
``is_valid`` membership scan (``/root/reference/utils.py:27-55``) — with two
much stronger vectorized rules applied to the whole board at once:

* **elimination** (a decided cell removes its digit from its row/col/box), and
* **hidden singles** (a digit with exactly one remaining home in a unit is
  placed there),

iterated to a fixpoint inside ``lax.while_loop``.  This is where the
~10^2-10^4x search-space reduction over the reference's blind DFS comes from
(SURVEY.md §6): most easy boards solve with zero guesses, hard 17-clue boards
need orders of magnitude fewer branch nodes.

Everything here works on arbitrary leading batch dims: shape [..., n, n].
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import (
    from_boxes,
    is_single,
    once_twice_reduce,
    or_reduce,
    popcount,
    to_boxes,
)

_UNIT_AXES = ("row", "col", "box")


def _unit_views(cand: jax.Array, geom: Geometry):
    """Yield (view, undo) pairs so each unit type is a reduction over axis -1."""
    yield cand, lambda x: x  # rows: cells of a row are contiguous in axis -1
    yield jnp.swapaxes(cand, -1, -2), lambda x: jnp.swapaxes(x, -1, -2)
    yield to_boxes(cand, geom), lambda x: from_boxes(x, geom)


def propagate_sweep(cand: jax.Array, geom: Geometry) -> jax.Array:
    """One propagation sweep: eliminate decided digits, then place hidden singles."""
    single = is_single(cand)
    decided = jnp.where(single, cand, jnp.uint32(0))

    # --- elimination: remove every decided digit from its three units -------
    seen = jnp.zeros_like(cand)
    for view, undo in _unit_views(decided, geom):
        unit_or = or_reduce(view, -1)[..., None]
        seen = seen | undo(jnp.broadcast_to(unit_or, view.shape))
    # Decided cells keep their own bit; undecided cells lose all seen bits.
    cand = jnp.where(single, cand, cand & ~seen)

    # --- hidden singles: a digit with a unique home in a unit is forced -----
    forced = jnp.zeros_like(cand)
    for view, undo in _unit_views(cand, geom):
        once, twice = once_twice_reduce(view, -1)
        unique = (once & ~twice)[..., None]
        forced = forced | undo(view & jnp.broadcast_to(unique, view.shape))
    # A nonzero `forced` is a sound restriction: each forced bit *must* be this
    # cell's value (two different forced bits in one cell is an unsat board and
    # stays detectable downstream).  Never touch already-decided cells.
    cand = jnp.where(~single & (forced != 0), forced, cand)
    return cand


class BoardStatus(NamedTuple):
    solved: jax.Array  # bool[...]: fully decided and consistent
    contradiction: jax.Array  # bool[...]: provably unsatisfiable


def board_status(cand: jax.Array, geom: Geometry) -> BoardStatus:
    """Classify each board: solved / contradiction / (neither = undecided).

    The consistency rules double as the (fixed) re-implementation of the
    reference's broken ``Sudoku.check`` (``/root/reference/sudoku.py:48-94``,
    which NameErrors on valid grids — SURVEY.md §2.5 #1):
      * no cell empty of candidates,
      * no two decided cells in a unit share a digit,
      * every digit retains at least one home in every unit.
    """
    single = is_single(cand)
    decided = jnp.where(single, cand, jnp.uint32(0))
    full = jnp.uint32(geom.full_mask)

    empty_cell = jnp.any(cand == 0, axis=(-1, -2))
    dup = jnp.zeros(cand.shape[:-2], dtype=bool)
    uncovered = jnp.zeros(cand.shape[:-2], dtype=bool)
    for view, _ in _unit_views(decided, geom):
        unit_or = or_reduce(view, -1)
        unit_sum = jnp.sum(view, axis=-1)  # singleton masks: sum==or iff distinct
        dup = dup | jnp.any(unit_sum != unit_or, axis=-1)
    for view, _ in _unit_views(cand, geom):
        uncovered = uncovered | jnp.any(or_reduce(view, -1) != full, axis=-1)

    contradiction = empty_cell | dup | uncovered
    solved = jnp.all(single, axis=(-1, -2)) & ~contradiction
    return BoardStatus(solved=solved, contradiction=contradiction)


RULE_TIERS = ("basic", "extended", "subsets")


def propagate(
    cand: jax.Array, geom: Geometry, max_sweeps: int = 64, rules: str = "basic"
) -> tuple[jax.Array, jax.Array]:
    """Sweep to a fixpoint (bounded by ``max_sweeps``); returns (cand, n_sweeps).

    ``rules='extended'`` adds the box-line reductions (:func:`box_line_sweep`)
    to each sweep — strictly stronger inference (fewer branch nodes, more
    boards closed without search) at a higher per-sweep cost.
    ``rules='subsets'`` further adds naked-subset eliminations
    (:func:`naked_subsets_sweep`) — every tier is a strict superset of the
    one below, so masks only ever get tighter up the ladder.

    The loop condition is batch-global ("any board changed"), keeping the whole
    batch in one ``lax.while_loop`` — boards that stabilized early are cheap
    no-ops in later sweeps because every op is a fused elementwise pass.
    """
    if rules not in RULE_TIERS:
        raise ValueError(f"unknown rules {rules!r}")

    def cond(state):
        _, changed, sweeps = state
        return changed & (sweeps < max_sweeps)

    def body(state):
        cur, _, sweeps = state
        nxt = propagate_sweep(cur, geom)
        if rules in ("extended", "subsets"):
            nxt = box_line_sweep(nxt, geom)
        if rules == "subsets":
            nxt = naked_subsets_sweep(nxt, geom)
        return nxt, jnp.any(nxt != cur), sweeps + 1

    cand, _, sweeps = jax.lax.while_loop(
        cond, body, (cand, jnp.bool_(True), jnp.int32(0))
    )
    return cand, sweeps


def box_line_sweep(cand: jax.Array, geom: Geometry) -> jax.Array:
    """Pointing/claiming reductions (box-line interactions), bit-parallel.

    Two sound rules beyond :func:`propagate_sweep`'s basic pair:

    * **pointing**: if inside a box every candidate position of digit *d*
      lies in one box-row (box-col), then *d* is eliminated from that row
      (col) outside the box;
    * **claiming**: if inside a row (col) every candidate position of *d*
      lies in one box, then *d* is eliminated from the rest of that box.

    Both directions reduce to the same tensor computation on the
    ``[..., n_v, bh, n_h, bw]`` view: per (box, box-row) compute the digit
    bits present, find bits confined to exactly one box-row of the box
    (pointing) or one box of the row-band (claiming), and clear them from
    the complementary cells.  Everything is bitwise OR/AND on uint32 masks
    over static small axes — no per-digit loop.
    """
    # Decided cells must keep their singleton bit: these rules only ever
    # remove candidates from *other* cells of the line/box, but guard anyway
    # so a (contradictory) board can't lose its decided marker silently.
    single = is_single(cand)
    nv, nh, bh, bw = geom.n_vboxes, geom.n_hboxes, geom.box_h, geom.box_w
    out = box_line_one_direction(cand, nv, bh, nh, bw)
    out_t = box_line_one_direction(jnp.swapaxes(out, -1, -2), nh, bw, nv, bh)
    out = jnp.swapaxes(out_t, -1, -2)
    return jnp.where(single, cand, out)


def box_line_one_direction(
    x: jax.Array, nv: int, bh: int, nh: int, bw: int
) -> jax.Array:
    """Rows direction of the box-line rules on x[..., nv*bh, nh*bw].

    The columns call passes the *transposed* box layout (nh, bw, nv, bh) —
    with rectangular boxes the two layouts differ, and using the row layout
    there silently misaligns box boundaries (eliminates true digits on
    12x12).  Module-level so the board-sharded path
    (``parallel/board_sharded.py``) can reuse it verbatim for its chip-local
    rows direction: a row-band shard is just a stack of complete bands.
    """
    lead = x.shape[:-2]
    v = x.reshape(*lead, nv, bh, nh, bw)
    # seg[..., v, r, h]: digit bits present in the box-row segment
    seg = or_reduce(v, -1)

    # pointing: bits in exactly one box-row of box (v, h)
    p_once, p_twice = once_twice_reduce(jnp.swapaxes(seg, -1, -2), -1)
    # [..., v, h] -> [..., v, 1, h]: broadcast the confined-bit mask over r
    point = seg & jnp.swapaxes((p_once & ~p_twice)[..., None], -1, -2)
    # eliminate `point` bits from the same global row in *other* boxes:
    # OR_{h' != h} x[h'] == (once & ~x[h]) | twice — a bit present in >= 2
    # boxes is "other" everywhere, a bit present once is "other" exactly
    # where it is absent.  Vacuous when nh == 1 (no other box), like the
    # Mosaic twin's guard (_box_line_dir).
    point_other = _or_others(point, -1)

    # claiming: bits in exactly one box of the row (v, r)
    c_once, c_twice = once_twice_reduce(seg, -1)
    claim = seg & (c_once & ~c_twice)[..., None]
    # eliminate `claim` bits from other box-rows of the same box (vacuous
    # when bh == 1: a box one row tall has no other box-row).
    claim_other = _or_others(claim, -2)

    kill = (point_other | claim_other)[..., None]  # broadcast over bw
    return (v & ~jnp.broadcast_to(kill, v.shape)).reshape(*lead, *x.shape[-2:])


def naked_subsets_sweep(cand: jax.Array, geom: Geometry) -> jax.Array:
    """Naked-subset eliminations in every unit, all subset sizes at once.

    The rule, keyed on cell masks: for a cell with mask ``m`` (``k`` bits),
    if exactly ``k`` nonzero cells of the unit are subsets of ``m``, those
    ``k`` digits are pigeonhole-confined to those cells, so ``m``'s bits are
    eliminated from every other cell of the unit.  One formulation covers
    every naked pair (both pair cells carry the 2-bit union) plus any
    triple/quad with a *witness* cell carrying the full union (``k=1``
    degenerates to basic elimination; more than ``k`` subset cells is
    itself a pigeonhole contradiction, which the sweep *exposes* by
    clearing the subset cells too instead of leaving it latent).
    Witness-free subsets — e.g. the triple {4,5},{5,6},{4,6}, whose union
    appears in no single cell — are deliberately out of scope: detecting
    them needs probes over unions of cell pairs (O(C^2) probes instead of
    C), and the pair case that dominates in practice never needs it.

    This is the third inference tier (``rules='subsets'``), aimed at deep
    search on giant boards where basic+box-line propagation is nearly blind
    (BENCHMARKS.md, sparse 25x25).  The reference has no counterpart at any
    tier — its only rule is the per-guess membership scan
    (``/root/reference/utils.py:27-55``).

    Cost is O(C^2) pairwise subset tests per unit (C = cells per unit): the
    probe loop materializes as one broadcast compare + sum per unit view,
    which XLA fuses; the Mosaic twin (``ops/pallas_propagate.py``) runs the
    same algebra as C width-1 slices.
    """
    single = is_single(cand)
    kill = jnp.zeros_like(cand)
    for view, undo in _unit_views(cand, geom):
        kill = kill | undo(_naked_subset_kill(view))
    return jnp.where(single, cand, cand & ~kill)


def _naked_subset_kill(view: jax.Array) -> jax.Array:
    """Per-cell kill mask of the naked-subset rule on unit view [..., U, C].

    For probe cell i and tested cell j of the same unit:
    ``sub[i, j] = x[j] != 0 and x[j] subset-of x[i]``;
    ``cnt[i] = sum_j sub[i, j]``; probe i is *confined* when
    ``cnt[i] >= popcount(x[i])``; its mask then kills in every cell outside
    the subset — and everywhere (exposing the contradiction) when strictly
    overfull.  Shared verbatim by the board-sharded twin
    (``parallel/board_sharded.py``), whose units arrive here chip-local
    (rows, boxes) or gathered (columns).
    """
    m = view[..., :, None]  # probe i's mask, broadcast over tested j
    x = view[..., None, :]
    sub = ((x & ~m) == 0) & (x != 0)
    cnt = jnp.sum(sub.astype(jnp.int32), axis=-1)  # [..., U, C_i]
    k = popcount(view).astype(jnp.int32)
    confined = (view != 0) & (cnt >= k)
    over = (cnt > k)[..., None]
    hit = confined[..., None] & (~sub | over)
    return or_reduce(jnp.where(hit, jnp.broadcast_to(m, hit.shape), jnp.uint32(0)), -2)


def _or_others(x: jax.Array, axis: int) -> jax.Array:
    """Per slot along ``axis``: the OR of every *other* slot's bits.

    The complement identity ``OR_{j != i} x[j] == (once & ~x[i]) | twice``
    over the (once, twice) multiplicity aggregates — the same identity the
    board-sharded columns direction uses across chips
    (``parallel/board_sharded.py::_box_line_cols``), so "eliminate from the
    other units" is one computation everywhere.
    """
    once, twice = once_twice_reduce(x, axis)
    once = jnp.expand_dims(once, axis)
    twice = jnp.expand_dims(twice, axis)
    return (once & ~x) | twice
