"""Constraint propagation as batched boolean tensor ops.

This replaces the reference's only inference rule — the per-guess
``is_valid`` membership scan (``/root/reference/utils.py:27-55``) — with two
much stronger vectorized rules applied to the whole board at once:

* **elimination** (a decided cell removes its digit from its row/col/box), and
* **hidden singles** (a digit with exactly one remaining home in a unit is
  placed there),

iterated to a fixpoint inside ``lax.while_loop``.  This is where the
~10^2-10^4x search-space reduction over the reference's blind DFS comes from
(SURVEY.md §6): most easy boards solve with zero guesses, hard 17-clue boards
need orders of magnitude fewer branch nodes.

Everything here works on arbitrary leading batch dims: shape [..., n, n].
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import (
    from_boxes,
    is_single,
    once_twice_reduce,
    or_reduce,
    to_boxes,
)

_UNIT_AXES = ("row", "col", "box")


def _unit_views(cand: jax.Array, geom: Geometry):
    """Yield (view, undo) pairs so each unit type is a reduction over axis -1."""
    yield cand, lambda x: x  # rows: cells of a row are contiguous in axis -1
    yield jnp.swapaxes(cand, -1, -2), lambda x: jnp.swapaxes(x, -1, -2)
    yield to_boxes(cand, geom), lambda x: from_boxes(x, geom)


def propagate_sweep(cand: jax.Array, geom: Geometry) -> jax.Array:
    """One propagation sweep: eliminate decided digits, then place hidden singles."""
    single = is_single(cand)
    decided = jnp.where(single, cand, jnp.uint32(0))

    # --- elimination: remove every decided digit from its three units -------
    seen = jnp.zeros_like(cand)
    for view, undo in _unit_views(decided, geom):
        unit_or = or_reduce(view, -1)[..., None]
        seen = seen | undo(jnp.broadcast_to(unit_or, view.shape))
    # Decided cells keep their own bit; undecided cells lose all seen bits.
    cand = jnp.where(single, cand, cand & ~seen)

    # --- hidden singles: a digit with a unique home in a unit is forced -----
    forced = jnp.zeros_like(cand)
    for view, undo in _unit_views(cand, geom):
        once, twice = once_twice_reduce(view, -1)
        unique = (once & ~twice)[..., None]
        forced = forced | undo(view & jnp.broadcast_to(unique, view.shape))
    # A nonzero `forced` is a sound restriction: each forced bit *must* be this
    # cell's value (two different forced bits in one cell is an unsat board and
    # stays detectable downstream).  Never touch already-decided cells.
    cand = jnp.where(~single & (forced != 0), forced, cand)
    return cand


class BoardStatus(NamedTuple):
    solved: jax.Array  # bool[...]: fully decided and consistent
    contradiction: jax.Array  # bool[...]: provably unsatisfiable


def board_status(cand: jax.Array, geom: Geometry) -> BoardStatus:
    """Classify each board: solved / contradiction / (neither = undecided).

    The consistency rules double as the (fixed) re-implementation of the
    reference's broken ``Sudoku.check`` (``/root/reference/sudoku.py:48-94``,
    which NameErrors on valid grids — SURVEY.md §2.5 #1):
      * no cell empty of candidates,
      * no two decided cells in a unit share a digit,
      * every digit retains at least one home in every unit.
    """
    single = is_single(cand)
    decided = jnp.where(single, cand, jnp.uint32(0))
    full = jnp.uint32(geom.full_mask)

    empty_cell = jnp.any(cand == 0, axis=(-1, -2))
    dup = jnp.zeros(cand.shape[:-2], dtype=bool)
    uncovered = jnp.zeros(cand.shape[:-2], dtype=bool)
    for view, _ in _unit_views(decided, geom):
        unit_or = or_reduce(view, -1)
        unit_sum = jnp.sum(view, axis=-1)  # singleton masks: sum==or iff distinct
        dup = dup | jnp.any(unit_sum != unit_or, axis=-1)
    for view, _ in _unit_views(cand, geom):
        uncovered = uncovered | jnp.any(or_reduce(view, -1) != full, axis=-1)

    contradiction = empty_cell | dup | uncovered
    solved = jnp.all(single, axis=(-1, -2)) & ~contradiction
    return BoardStatus(solved=solved, contradiction=contradiction)


def propagate(
    cand: jax.Array, geom: Geometry, max_sweeps: int = 64
) -> tuple[jax.Array, jax.Array]:
    """Sweep to a fixpoint (bounded by ``max_sweeps``); returns (cand, n_sweeps).

    The loop condition is batch-global ("any board changed"), keeping the whole
    batch in one ``lax.while_loop`` — boards that stabilized early are cheap
    no-ops in later sweeps because every op is a fused elementwise pass.
    """

    def cond(state):
        _, changed, sweeps = state
        return changed & (sweeps < max_sweeps)

    def body(state):
        cur, _, sweeps = state
        nxt = propagate_sweep(cur, geom)
        return nxt, jnp.any(nxt != cur), sweeps + 1

    cand, _, sweeps = jax.lax.while_loop(
        cond, body, (cand, jnp.bool_(True), jnp.int32(0))
    )
    return cand, sweeps
