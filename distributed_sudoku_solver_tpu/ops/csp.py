"""The problem interface of the lane-stack engine: what a CSP must provide.

The reference hard-wires one problem (9x9 Sudoku) into its only kernel
(``/root/reference/utils.py:14-55``).  Here the search engine
(``ops/frontier.py``: DFS lane stacks, work stealing, cancellation, and the
multi-chip path in ``parallel/sharded.py``) is generic over a *problem*
object, so every family — Sudoku at any geometry, generalized exact cover
(N-queens, pentomino), and future CSPs — shares one compiled scheduler.

A problem owns the meaning of a *state*: one immutable ``uint32[h, w]``
tensor per search node (a Sudoku candidate board; a packed avail/covered
pair for exact cover).  The engine never looks inside states — it only
stacks, ships, and hands them back to the problem's three kernels, each
batched over a leading lane axis:

* ``propagate(states) -> (states, sweeps)``: run inference to a fixpoint
  (pure, monotonic: may only restrict states).
* ``status(states) -> (solved, contradiction)``: classify each state;
  neither flag set means "undecided, branch me".
* ``branch(states) -> (guess, rest)``: split each state into two children
  whose search spaces partition the parent's (guess is explored first —
  DFS).  Values for non-undecided lanes are ignored by the engine, so the
  kernels must be *total*: garbage in, garbage out, never NaN/crash.

Problem objects are jit-static: they must be hashable and equality-stable
(two equal problems must trace identically), and any instance tensors they
close over are baked into the compiled program as constants.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

import jax


@runtime_checkable
class CSProblem(Protocol):
    """Static problem definition consumed by the frontier engine."""

    @property
    def state_shape(self) -> tuple[int, int]:
        """(h, w) of one search state; states are uint32[..., h, w]."""
        ...

    def propagate(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """[L, h, w] -> (restricted states, int32 sweep count)."""
        ...

    def status(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """[L, h, w] -> (solved bool[L], contradiction bool[L])."""
        ...

    def branch(self, states: jax.Array) -> tuple[jax.Array, jax.Array]:
        """[L, h, w] -> (guess, rest): two children partitioning the parent."""
        ...

    def signature(self) -> str:
        """Stable identity string (checkpoint compatibility checks)."""
        ...
