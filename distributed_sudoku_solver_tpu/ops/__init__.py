from distributed_sudoku_solver_tpu.ops.bitmask import (  # noqa: F401
    encode_grid,
    decode_grid,
    popcount,
    lowest_bit,
)
from distributed_sudoku_solver_tpu.ops.propagate import (  # noqa: F401
    propagate,
    propagate_sweep,
    board_status,
)
