"""Bulk solver: one-dispatch-per-chunk pipeline for very large batches (DP at scale).

The throughput-oriented entry point — the workload the reference could only
express as one HTTP `POST /solve` per puzzle per ring (SURVEY.md §2.2 "Data
parallelism: NO — one puzzle at a time") becomes a few device dispatches on
``[B, n, n]`` batches with B in the 10^5-10^6 range.

Design (round 2): the old two-stage pipeline (separate propagate pass, host
compaction of survivors, then frontier search) spent more wall clock on
host<->device round trips than on compute — each dispatch+fetch costs
~100-150 ms through a tunneled device, and host-side survivor compaction
forced a full sync between the stages.  Now each chunk is **one**
``solve_batch`` dispatch: the frontier's first step *is* the propagation
pass (boards that close under propagation resolve with zero branches and
their lanes immediately become thieves for the hard ones), so the whole
propagate -> classify -> search -> gang-up cascade happens in-graph with no
host involvement.  Measured on a v5e chip this took the hard-mix corpus
from 19.8k boards/s (round 1, two-stage) to ~101k boards/s.

Escalation rungs remain for the rare stragglers that overflow the shallow
first-pass stack or hit the step cap: they re-run with OR-parallel thief
gangs and deep stacks.  Chunks are dispatched ahead with a bounded in-flight
window, so transfers overlap compute without holding the whole batch's
device results live at once.
"""

from __future__ import annotations

import dataclasses
import functools
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops import wire
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch_wire


@dataclasses.dataclass(frozen=True)
class BulkConfig:
    """Static bulk-pipeline configuration.

    Defaults come from a TPU v5e sweep (round 2): device-only throughput
    rises with chunk width up to 65536 lanes (~101k boards/s on the
    hard-mix corpus; wider shapes currently trip an XLA:TPU scatter-fusion
    compiler CHECK), but end-to-end through a tunneled link 32768-board
    chunks win (~81k vs ~57k boards/s) because chunk k+1's transfers
    overlap chunk k's compute.  A 12-slot stack is deep enough that
    first-pass overflow is rare on 9x9 while keeping the stack tensor at
    ~128 MB per chunk.
    """

    chunk: int = 32768  # boards (= frontier lanes) per dispatch
    stack_slots: int = 12  # first-pass DFS depth
    max_steps: int = 100_000
    max_sweeps: int = 64
    propagator: Optional[str] = None  # None = auto (slices on TPU, xla on CPU)
    rules: str = "extended"  # box-line reductions close ~26% more boards
    #   without search on hard-mix corpora; measured faster end-to-end
    # Escalation rungs for unresolved boards: (max jobs/chunk, lanes per job,
    # stack slots[, step budget]).  Wider-than-jobs lanes give straggler jobs
    # an OR-parallel gang of thief lanes; the optional 4th element bounds how
    # long a rung grinds before handing survivors to the next one (default:
    # max_steps).  None = geometry default (:func:`default_rungs`): the 9x9
    # ladder is wrong for giant boards, where the narrow first rung burned
    # its entire 100k-step budget at 4-lane parallelism — measured 1.9 vs
    # 5.6 boards/s on the 45%-clue 25x25 corpus (BENCHMARKS.md).
    rungs: Optional[tuple] = None
    inflight: int = 3  # dispatched-ahead chunks before draining the oldest
    # Dispatch-time bounds.  A single while_loop dispatch that runs for
    # minutes trips device/RPC watchdogs and kills the worker (observed on a
    # sparse 25x25 corpus through the tunnel: ~100k-step searches in one
    # dispatch = guaranteed "TPU worker crashed").  The first pass gets a
    # hard step cap (unresolved boards escalate); rungs advance in
    # ``dispatch_steps`` chunks so each dispatch's wall time stays bounded
    # regardless of how deep a straggler search runs.
    first_pass_steps: int = 4096
    dispatch_steps: int = 512
    rung_stack_mb: int = 768  # cap on a rung's stack tensor (lanes x slots)
    # First-pass step implementation: None = auto ('fused' whole-round VMEM
    # kernel on TPU, 3.45x the composite step device-only at 65,536 lanes —
    # see BENCHMARKS.md round 4; 'xla' elsewhere).  The rung step engine
    # is its own knob (``rung_step_impl`` below).
    step_impl: Optional[str] = None
    # Frontier rounds per fused dispatch on the first pass.  None = the
    # per-chunk-transfer surface default (frontier.FUSED_STEPS_LINKED = 8):
    # every first-pass chunk crosses the link, and 32 has never won the
    # e2e A/B there across three rounds of measurement (r5 sweep: 8 ->
    # 94k, 16 -> 80k, 32 -> 74k; the r4 and r6 sessions measured the same
    # A/B a wash — 95.1 vs 94.0 and 96 vs 91 — so at best nothing, at
    # worst 8's purge/steal reactivity pays on a transfer-bound pipeline).
    # The r4 device-resident re-sweep measured
    # 32 fastest (417k vs 359k boards/s), so the DEVICE-RESIDENT surfaces
    # — engine flights, direct batch solves, meshes, and this pipeline's
    # own escalation rungs (state stays on-device between their stepped
    # dispatches) — resolve to 32 instead (frontier.FUSED_STEPS_DEVICE,
    # BENCHMARKS.md "round 6: per-surface fused_steps").
    fused_steps: Optional[int] = None
    # Step engine for the escalation rungs.  None = auto: 'fused' on TPU
    # for GIANT geometries (n >= 16) where the kernel admits the rung
    # shape, 'xla' everywhere else.  The round-4 rationale for
    # composite-only rungs ("gang rungs live off steal reaction latency")
    # was measured wrong where it matters: the fused gang rung took the
    # deep-25x25 row 5.6 -> 20-24 boards/s (3.6-4.3x,
    # benchmarks/probe_25.py).  The auto default is restricted to the
    # geometry band that measurement covers (ADVICE r5): at 9x9-class
    # boards rungs never fire on any measured corpus
    # (benchmarks/probe_rungs.py: remaining_after_first == 0 even at
    # 22-clue hardness), so an auto-fused small-board rung would be an
    # unmeasured code path pretending to be a tuned default — pass
    # rung_step_impl='fused' explicitly to opt a small-board rung in.
    # KNOWN SEMANTIC GAP of the fused rung engine: the fused drivers run
    # exactly ONE steal pairing per k-step dispatch, ignoring the
    # steal_rounds=4 fan-out the composite gang rungs use
    # (pallas_step/pallas_cover `_fused_round`; SolverConfig.steal_rounds
    # documents the same) — a lone rich lane therefore feeds thief gangs
    # a factor fused_steps*steal_rounds slower per frontier round.  Sound
    # (steal timing never affects verdicts), and the measured 25x25 rows
    # won DESPITE it, but treat steal_rounds as inert whenever a rung
    # runs fused.
    # A rung whose shape the kernel cannot serve falls back to composite.
    rung_step_impl: Optional[str] = None

    def __post_init__(self) -> None:
        if self.propagator not in (None, "xla", "pallas", "slices"):
            raise ValueError(f"unknown propagator {self.propagator!r}")
        from distributed_sudoku_solver_tpu.ops.propagate import RULE_TIERS

        if self.rules not in RULE_TIERS:
            raise ValueError(f"unknown rules {self.rules!r}")
        if self.step_impl not in (None, "xla", "fused"):
            raise ValueError(f"unknown step_impl {self.step_impl!r}")
        if self.rung_step_impl not in (None, "xla", "fused"):
            raise ValueError(
                f"unknown rung_step_impl {self.rung_step_impl!r}"
            )


def default_rungs(geom: Geometry) -> tuple:
    """Geometry-resolved escalation ladder (``BulkConfig.rungs=None``).

    Small boards (9x9 hard-mix): a narrow 4-lane rung first — stragglers
    are plentiful and shallow, and the wide-gang rung only sees the rare
    deep survivor (the round-2 tuned ladder, ~101k boards/s device-only).
    The narrow rung gets a bounded step budget so a genuinely deep board
    stops grinding at 4-lane parallelism and escalates.

    Giant boards (16x16 up): stragglers are *deep*, so they go straight to
    128-lane OR-parallel gangs — at a 24-slot stack since round 5, the
    deepest gridded depth the fused kernel admits at 25x25
    (``pallas_step._max_slots``), so the gang rung can run the fused step
    engine: measured on the 45%-clue 25x25 corpus, the fused gang took
    the round-2-worst row 5.6 -> 20-24 boards/s (S=24 vs the old S=32
    composite gang was a wash composite-vs-composite: 5.54 vs 5.64 —
    BENCHMARKS.md "Pipeline anatomy / giant boards", round 5).  A
    deep-stack completeness rung follows: a lane whose DFS overflows its
    deferred-sibling slots drops a subtree and downgrades its verdict to
    unknown (``ops/frontier.py``), so such boards retry at 256 slots —
    narrower (16 lanes, the ``rung_stack_mb`` ceiling at 25x25) but
    overflow-proof in practice, preserving completeness.
    """
    if geom.n >= 16:
        return ((64, 128, 24), (64, 16, 256))
    return ((2048, 4, 64, 16_384), (64, 64, 256))


@dataclasses.dataclass
class BulkResult:
    """Per-board verdicts for one bulk call (host-side numpy)."""

    solution: np.ndarray  # int32[B, n, n]; zeros where unsolved
    solved: np.ndarray  # bool[B]
    unsat: np.ndarray  # bool[B]
    by_propagation: np.ndarray  # bool[B]: solved with zero search
    searched: int  # boards that needed at least one branch node


def _auto_propagator() -> str:
    # Boards-last slice sweeps win at wide lane counts on TPU; the CPU/test
    # mesh prefers the boards-first loop (no transpose round-trips).
    import jax

    return "slices" if jax.default_backend() == "tpu" else "xla"


@functools.partial(jax.jit, static_argnames=("geom", "scfg"))
def _rung_start(grids_u8, geom: Geometry, scfg: SolverConfig):
    # uint8 upload (4x fewer bytes over a tunneled link); widen in-graph
    # before mask encoding so n > 8 digits don't overflow the shift.
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.ops.frontier import init_frontier

    return init_frontier(encode_grid(grids_u8.astype(jnp.int32), geom), scfg)


@functools.partial(jax.jit, static_argnames=("geom",), donate_argnums=(0,))
def _rung_finish(state, geom: Geometry):
    """Terminal rung drain; the state is donated (dropped right after)."""
    from distributed_sudoku_solver_tpu.ops.solve import _finalize

    res = _finalize(state)
    return wire.pack_result_device(
        res.solution, res.solved, res.unsat, res.nodes > 0, geom
    )


def solve_bulk(
    grids,
    geom: Geometry,
    config: BulkConfig = BulkConfig(),
    mesh=None,
    trace: Optional[dict] = None,
) -> BulkResult:
    """Solve ``grids`` int[B, n, n] (0 = empty); B may be huge.

    Each chunk is one device dispatch (propagation *and* search in-graph);
    chunks are pipelined with a bounded in-flight window.  Everything is
    deterministic: results are independent of chunk sizes.

    With ``mesh`` (a 1-axis ``jax.sharding.Mesh``), chunks run the sharded
    frontier (`parallel/sharded.py`: ring-``ppermute`` work stealing,
    ``psum`` solution broadcast over ICI) with lanes sharded over the chips.

    With ``trace`` (a dict), per-stage wall clocks are recorded into it:
    ``pack_s``/``drain_s`` (host pack+upload vs result-fetch wall inside the
    pipelined first pass — fetch wall includes waiting out device compute),
    ``first_pass_s``, ``remaining_after_first``, and per-rung dicts under
    ``rungs`` (wall, dispatch count, survivors in/out).  The first pass is
    deliberately overlapped, so these attribute *host-observed* wall, not
    exclusive device time — the honest decomposition protocol lives in
    ``benchmarks/anatomy.py``.
    """
    # syncck: allow(caller input coercion — grids arrive as host lists/ndarrays, never device values)
    grids = np.ascontiguousarray(np.asarray(grids, dtype=np.int32))
    b, n, _ = grids.shape
    n_dev = 1 if mesh is None else int(mesh.devices.size)

    solution = np.zeros((b, n, n), dtype=np.int32)
    solved = np.zeros(b, dtype=bool)
    unsat = np.zeros(b, dtype=bool)
    branched = np.zeros(b, dtype=bool)

    from distributed_sudoku_solver_tpu.utils.puzzles import solved_board

    pad_board = solved_board(geom)
    prop = config.propagator or _auto_propagator()

    # Wire format both directions (ops/wire.py): single result array — one
    # upload, one dispatch, one fetch per chunk.  Single-chip chunks use
    # the smallest format for the geometry ('dense' 10-bit triplets at
    # 9x9: 35+36 B/board vs nibble's 41+42 — the pipeline is
    # transfer-bound, so bytes convert ~1:1 into throughput); the mesh
    # path keeps the legacy format its sharded driver speaks.
    fmt = wire.best_format(geom) if mesh is None else "packed"

    def run_chunk(batch: np.ndarray, scfg: SolverConfig):
        packed = jnp.asarray(wire.pack_grids_for(batch, geom, fmt))
        if mesh is not None:
            from distributed_sudoku_solver_tpu.parallel.sharded import (
                solve_batch_sharded_wire,
            )

            return solve_batch_sharded_wire(packed, geom, scfg, mesh)
        return solve_batch_wire(packed, geom, scfg, fmt=fmt)

    def pad_to(batch: np.ndarray, size: int) -> np.ndarray:
        # Pad with an already-complete board: its lane resolves on step one
        # and immediately turns thief, joining the OR-parallel gang on the
        # real jobs — and one compiled shape serves every partial chunk.
        if len(batch) == size:
            return batch
        pad = np.tile(pad_board[None], (size - len(batch), 1, 1))
        return np.concatenate([batch, pad])

    # --- first pass: every chunk is one dispatch --------------------------
    # Size the frontier to the workload: a small batch must not dispatch a
    # full-width (default 32k-lane) frontier of pad boards.  Power-of-two
    # rounding keeps compiled shapes O(log) across call sites.
    chunk = min(config.chunk, max(64, 1 << (max(b, 1) - 1).bit_length()))
    chunk = max(n_dev, -(-chunk // n_dev) * n_dev)
    step_impl = config.step_impl
    if step_impl is None:
        # Auto-fused wherever the (n, stack_slots) working set fits VMEM at
        # the mandatory 128-lane tile (ops/pallas_step.fused_tile) — that
        # covers 9x9-class (measured 1.45-2.4x, BENCHMARKS.md), 16x16
        # (1.1-2.0x, round 4), and since the round-5 scoped-vmem
        # re-measurement 25x25 too (fused first pass 1.14 -> 0.47 s on
        # the deep 45%-clue corpus, benchmarks/probe_25.py).  Meshes
        # qualify too: the sharded driver dispatches to
        # parallel/fused_sharded (per-chip fused rounds + ring collectives).
        from distributed_sudoku_solver_tpu.ops.pallas_step import fused_tile

        step_impl = (
            "fused"
            if (
                jax.default_backend() == "tpu"
                and fused_tile(n, config.stack_slots) > 0
            )
            else "xla"
        )
    # The first pass is a per-chunk TRANSFER surface: resolve the shallow
    # fused_steps default here rather than letting solve_batch_fused apply
    # its device-resident deep default (rungs, which advance device-resident
    # state via advance_frontier_fused, correctly get the deep one).
    from distributed_sudoku_solver_tpu.ops.frontier import FUSED_STEPS_LINKED

    first_cfg = SolverConfig(
        lanes=chunk,
        stack_slots=config.stack_slots,
        max_steps=min(config.first_pass_steps, config.max_steps),
        max_sweeps=config.max_sweeps,
        propagator=prop,
        rules=config.rules,
        step_impl=step_impl,
        fused_steps=config.fused_steps,
    ).with_fused_steps(FUSED_STEPS_LINKED)

    import time as _time

    from distributed_sudoku_solver_tpu.serving import faults

    stage = {"pack_s": 0.0, "drain_s": 0.0} if trace is not None else None

    def drain(lo: int, res) -> None:
        t0 = _time.perf_counter()
        # syncck: allow(THE one result fetch per first-pass chunk, on the drain worker so it overlaps uploads)
        fetched = np.asarray(res)
        if stage is not None:
            stage["drain_s"] += _time.perf_counter() - t0
        hi = min(lo + chunk, b)
        k = hi - lo
        r_sol, r_solved, r_unsat, r_branched = wire.unpack_result_for(
            fetched, geom, fmt
        )
        r_sol, r_solved = r_sol[:k], r_solved[:k]
        solution[lo:hi][r_solved] = r_sol[r_solved]
        solved[lo:hi] = r_solved
        unsat[lo:hi] = r_unsat[:k]
        branched[lo:hi] = r_branched[:k]

    # Result fetches run on a single worker thread: ``np.asarray`` releases
    # the GIL while it waits out device compute + the downlink, so packing
    # and uploading chunk k+2 overlaps draining chunk k (measured in the
    # round-5 anatomy: the drain wall IS most of the first-pass wall — the
    # submit loop used to sit inside it).  One worker keeps drains ordered
    # (writes into the shared result arrays race-free by construction).
    t_first = _time.perf_counter()
    pending: list = []
    with ThreadPoolExecutor(max_workers=1) as pool:
        for lo in range(0, b, chunk):
            batch = pad_to(grids[lo : lo + chunk], chunk)
            t0 = _time.perf_counter()
            # Fault-injection seam: the mass-pass twin of the rung seam
            # below (the HTTP endpoint retries transient chunk failures).
            faults.fire("bulk.dispatch")
            res = run_chunk(batch, first_cfg)
            if stage is not None:
                stage["pack_s"] += _time.perf_counter() - t0
            pending.append(pool.submit(drain, lo, res))
            if len(pending) >= max(1, config.inflight):
                pending.pop(0).result()
        for f in pending:
            f.result()

    by_propagation = solved & ~branched
    searched = int(branched.sum())
    if trace is not None:
        trace.update(stage)
        trace["first_pass_s"] = _time.perf_counter() - t_first
        trace["chunks"] = -(-b // chunk)
        trace["step_impl"] = step_impl
        trace["fused_steps"] = first_cfg.fused_steps
        trace["remaining_after_first"] = int((~solved & ~unsat).sum())
        trace["rungs"] = []

    # --- escalation rungs: re-run unresolved stragglers with gangs --------
    # Rungs run *stepped*: bounded-step advances instead of one monolithic
    # while_loop dispatch, because stragglers are exactly the boards whose
    # searches can run for minutes — long enough to trip device/RPC
    # watchdogs in a single dispatch (see BulkConfig.dispatch_steps).
    def run_rung_stepped(batch: np.ndarray, scfg: SolverConfig):
        if mesh is not None:
            # The sharded driver has its own in-graph loop; multi-chip rungs
            # keep the one-dispatch path (no tunnel in a real mesh deployment).
            from distributed_sudoku_solver_tpu.parallel.sharded import (
                solve_batch_sharded_wire,
            )

            packed = jnp.asarray(wire.pack_grids_host(batch, geom))
            res = solve_batch_sharded_wire(packed, geom, scfg, mesh)
            dispatches[0] += 1
            # syncck: allow(the one result fetch per sharded rung dispatch — the mesh driver loops in-graph)
            return wire.unpack_result_host(np.asarray(res), geom)
        # The rung drain loop (round 8): status-returning, buffer-donated
        # advances — each dispatch's liveness + step count ride the packed
        # status word in ONE small fetch, replacing the extra `_any_live`
        # dispatch-and-fetch per rung dispatch, and the frontier advances
        # in place instead of being copied every `dispatch_steps` rounds.
        # The step limit is in-graph (`steps + dispatch_steps`), so fused
        # overshoot compounds into the next limit instead of truncating
        # against an absolute ladder — purge/steal granularity only, never
        # verdicts (the composite path's boundaries are bit-identical).
        from distributed_sudoku_solver_tpu.ops.frontier import unpack_status
        from distributed_sudoku_solver_tpu.utils.checkpoint import (
            advance_frontier_status,
        )

        if scfg.step_impl == "fused":
            from distributed_sudoku_solver_tpu.ops.pallas_step import (
                advance_frontier_fused_status as _advance,
            )
        else:
            _advance = advance_frontier_status
        state = _rung_start(jnp.asarray(batch.astype(np.uint8)), geom, scfg)
        n_rung_jobs = len(batch)
        while True:
            # Fault-injection seam (serving/faults.py): a raise here fails
            # the whole rung dispatch loop; the HTTP bulk endpoint retries
            # transient chunk failures under the engine's recovery policy.
            faults.fire("bulk.dispatch")
            state, status = _advance(
                state, jnp.int32(config.dispatch_steps), geom, scfg
            )
            dispatches[0] += 1
            # syncck: allow(the one packed-status fetch per rung dispatch — the round-8 contract this region proves)
            info = unpack_status(np.asarray(status), n_rung_jobs)
            if not info["has_work"].any() or info["steps"] >= scfg.max_steps:
                break
        return wire.unpack_result_host(
            # syncck: allow(terminal rung drain — one wire-format fetch after the state is donated away)
            np.asarray(_rung_finish(state, geom)), geom
        )

    dispatches = [0]

    remaining = np.flatnonzero(~solved & ~unsat)
    rungs = default_rungs(geom) if config.rungs is None else config.rungs
    for rung in rungs:
        if len(remaining) == 0:
            break
        max_jobs, lanes_per_job, slots = rung[:3]
        rung_steps = (
            min(int(rung[3]), config.max_steps) if len(rung) > 3 else config.max_steps
        )
        # Round the chunk up to a power of two (>= 64) so each rung compiles
        # O(log) distinct shapes across calls, not one per survivor count.
        jobs_per_chunk = min(
            max_jobs, max(64, 1 << (len(remaining) - 1).bit_length())
        )
        # Cap the rung's stack tensor: gang widths were tuned on 9x9, and
        # scaling them naively to giant geometries produces multi-GB stacks
        # (observed: 4096 lanes x 256 slots x 25^2 crashes the XLA:TPU
        # compile helper outright).  Narrow the gang first, then the chunk.
        budget = config.rung_stack_mb << 20
        cell_bytes = n * n * 4
        while (
            jobs_per_chunk * lanes_per_job * slots * cell_bytes > budget
            and lanes_per_job > 1
        ):
            lanes_per_job //= 2
        while (
            jobs_per_chunk * lanes_per_job * slots * cell_bytes > budget
            and jobs_per_chunk > 64
        ):
            jobs_per_chunk //= 2
        lanes = jobs_per_chunk * lanes_per_job
        rung_lanes = -(-lanes // n_dev) * n_dev  # round up: lanes >= jobs
        rung_impl = "xla"
        want_fused = (
            config.rung_step_impl == "fused"
            or (
                # Auto-fused only where the fused gang rung was measured
                # (giant boards; see BulkConfig.rung_step_impl).
                config.rung_step_impl is None
                and jax.default_backend() == "tpu"
                and n >= 16
            )
        )
        if want_fused and mesh is None:
            from distributed_sudoku_solver_tpu.ops.pallas_step import (
                max_fused_lanes,
            )

            if rung_lanes <= max_fused_lanes(n, slots):
                rung_impl = "fused"
                rung_lanes = -(-rung_lanes // 128) * 128
        scfg = SolverConfig(
            lanes=rung_lanes,
            stack_slots=slots,
            max_steps=rung_steps,
            max_sweeps=config.max_sweeps,
            propagator=prop,
            rules=config.rules,
            step_impl=rung_impl,
            # Gang rungs (many thief lanes per job) need fast fan-out: one
            # steal pairing per step would ramp a gang up only linearly.
            steal_rounds=4 if lanes_per_job > 1 else 1,
        )
        still: list[int] = []
        t_rung = _time.perf_counter()
        dispatches[0] = 0
        for lo in range(0, len(remaining), jobs_per_chunk):
            idx = remaining[lo : lo + jobs_per_chunk]
            r_sol, r_solved, r_unsat, _ = run_rung_stepped(
                pad_to(grids[idx], jobs_per_chunk), scfg
            )
            r_sol, r_solved, r_unsat = (
                r_sol[: len(idx)], r_solved[: len(idx)], r_unsat[: len(idx)],
            )
            solution[idx] = np.where(r_solved[:, None, None], r_sol, 0)
            solved[idx] = r_solved
            unsat[idx] = r_unsat
            still.extend(idx[~r_solved & ~r_unsat])
        if trace is not None:
            trace["rungs"].append({
                "wall_s": _time.perf_counter() - t_rung,
                "rung": tuple(int(x) for x in rung),
                "lanes": int(scfg.lanes),
                "slots": int(scfg.stack_slots),
                "dispatches": dispatches[0],
                "survivors_in": len(remaining),
                "survivors_out": len(still),
            })
        # syncck: allow(host index bookkeeping — `still` is a Python list of numpy indices, no device value)
        remaining = np.asarray(still, dtype=remaining.dtype)

    return BulkResult(
        solution=solution,
        solved=solved,
        unsat=unsat,
        by_propagation=by_propagation,
        searched=searched,
    )
