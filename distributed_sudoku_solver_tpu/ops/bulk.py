"""Bulk solver: propagation-first pipeline for very large batches (DP at scale).

The throughput-oriented entry point — the workload the reference could only
express as one HTTP `POST /solve` per puzzle per ring (SURVEY.md §2.2 "Data
parallelism: NO — one puzzle at a time") becomes one call on a ``[B, n, n]``
batch with B in the 10^5-10^6 range:

* **Stage 1 — propagate**: the whole batch runs the elimination +
  hidden-singles fixpoint once.  On TPU this is the Pallas VMEM kernel
  (``ops/pallas_propagate.py``), which is HBM-bandwidth-bound — each board
  is read once and written once no matter how many sweeps it needs.  Most
  easy/medium boards (e.g. the classic Kaggle 1M corpus) finish here with
  zero search.
* **Stage 2 — search the survivors**: boards still undecided are compacted
  (host side — survivor counts are data-dependent, and XLA wants static
  shapes) and fed through the lane-stack frontier engine
  (``ops/frontier.py``) in VMEM-sized chunks.  JAX's async dispatch
  pipelines chunk k+1's transfer against chunk k's compute.

Contradictions found in stage 1 are reported as unsat without ever touching
the search engine.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid, encode_grid
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.propagate import board_status
from distributed_sudoku_solver_tpu.ops.solve import solve_batch


@dataclasses.dataclass(frozen=True)
class BulkConfig:
    """Static bulk-pipeline configuration.

    Stage-2 defaults come from a TPU v5e sweep (this session): survivor
    throughput scales with chunk width up to ~32k lanes at 1 job/lane
    (1.0k boards/s at 512 lanes -> 41.8k at 32768), so the first rung is
    wide and shallow; deeper rungs re-run the rare stragglers that
    overflow a shallow stack or hit the step cap.
    """

    chunk: int = 65536  # stage-1 dispatch granularity (boards)
    search_lanes: int = 32768  # rung-1 frontier width (jobs = lanes)
    stack_slots: int = 16  # rung-1 DFS depth
    max_steps: int = 100_000
    max_sweeps: int = 64
    propagator: Optional[str] = None  # stage 1; None = auto (pallas on TPU)
    rules: str = "basic"  # 'extended' adds box-line reductions (all backends)
    # Escalation rungs for unresolved boards: (max jobs/chunk, lanes per job,
    # stack slots).  Wider-than-jobs lanes give straggler jobs an OR-parallel
    # gang of thief lanes; deep stacks make overflow impossible in practice.
    rungs: tuple = ((2048, 4, 64), (64, 64, 256))

    def __post_init__(self) -> None:
        if self.propagator not in (None, "xla", "pallas", "slices"):
            raise ValueError(f"unknown propagator {self.propagator!r}")
        if self.rules not in ("basic", "extended"):
            raise ValueError(f"unknown rules {self.rules!r}")

@dataclasses.dataclass
class BulkResult:
    """Per-board verdicts for one bulk call (host-side numpy)."""

    solution: np.ndarray  # int32[B, n, n]; zeros where unsolved
    solved: np.ndarray  # bool[B]
    unsat: np.ndarray  # bool[B]
    by_propagation: np.ndarray  # bool[B]: solved with zero search
    searched: int  # boards that went through stage 2


def _auto_propagator() -> str:
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _to_wire_int8(grids: np.ndarray, geom: Geometry) -> np.ndarray:
    """Narrow boards to int8 for the host->device link without weakening the
    corrupt-input contract: anything outside [0, n] becomes -1, which
    ``value_to_mask`` maps to the empty mask -> a clean unsat verdict (a
    bare ``astype(int8)`` would *wrap* e.g. 257 into a legal-looking 1)."""
    out = grids.astype(np.int8)
    out[(grids < 0) | (grids > geom.n)] = -1
    return out




def _propagate_local(
    cand: jax.Array, geom: Geometry, max_sweeps: int, propagator: str,
    rules: str = "basic",
) -> jax.Array:
    if propagator == "pallas":
        from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
            propagate_fixpoint_pallas,
        )

        fixed, _ = propagate_fixpoint_pallas(cand, geom, max_sweeps, rules=rules)
    elif propagator == "slices":
        from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
            propagate_fixpoint_slices,
        )

        fixed, _ = propagate_fixpoint_slices(cand, geom, max_sweeps, rules=rules)
    elif propagator == "xla":
        from distributed_sudoku_solver_tpu.ops.propagate import propagate

        fixed, _ = propagate(cand, geom, max_sweeps, rules)
    else:
        raise ValueError(f"unknown propagator {propagator!r}")
    return fixed


def _sharded_propagator(geom, max_sweeps, propagator, rules, mesh):
    from jax.sharding import PartitionSpec as P

    (axis,) = mesh.axis_names
    return jax.shard_map(
        lambda c: _propagate_local(c, geom, max_sweeps, propagator, rules),
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )


@functools.lru_cache(maxsize=None)
def _stage1(geom: Geometry, max_sweeps: int, propagator: str, rules: str, mesh):
    """One jitted program for a whole stage-1 chunk: encode -> fixpoint ->
    status -> int8 decode.  A single device dispatch per chunk — running
    the pre/post ops eagerly costs one host round-trip *per op* (~100 ms
    each through a tunneled device; measured ~7 s/chunk, vs ~0.2 s fused).

    Memoized (rebuilding the closure per chunk re-traces every call,
    ~0.9 s/chunk measured) and keyed only on what stage 1 actually uses —
    BulkConfigs differing in stage-2 fields share one compilation.
    """

    def run(chunk8: jax.Array):
        cand = encode_grid(chunk8, geom)
        if mesh is None:
            fixed = _propagate_local(cand, geom, max_sweeps, propagator, rules)
        else:
            # Embarrassingly parallel over the mesh: each chip runs the
            # fixpoint on its batch shard, no collectives (the caller pads
            # chunks to a multiple of the mesh size with pre-solved boards).
            fixed = _sharded_propagator(
                geom, max_sweeps, propagator, rules, mesh
            )(cand)
        st = board_status(fixed, geom)
        return decode_grid(fixed).astype(jnp.int8), st.solved, st.contradiction

    return jax.jit(run)


def solve_bulk(
    grids,
    geom: Geometry,
    config: BulkConfig = BulkConfig(),
    mesh=None,
) -> BulkResult:
    """Solve ``grids`` int[B, n, n] (0 = empty); B may be huge.

    Stage-1 chunks stream through the device back to host verdict arrays;
    survivors are batched through the frontier engine.  Everything is
    deterministic: results are independent of chunk sizes.

    With ``mesh`` (a 1-axis ``jax.sharding.Mesh``), stage 1 shards the batch
    over the chips (no collectives needed) and stage 2 runs the sharded
    frontier (`parallel/sharded.py`: ring-``ppermute`` work stealing,
    ``psum`` solution broadcast over ICI).
    """
    grids = np.ascontiguousarray(np.asarray(grids, dtype=np.int32))
    b, n, _ = grids.shape
    n_dev = 1 if mesh is None else int(mesh.devices.size)

    solution = np.zeros((b, n, n), dtype=np.int32)
    solved = np.zeros(b, dtype=bool)
    unsat = np.zeros(b, dtype=bool)

    # --- stage 1: propagate every board to its fixpoint -------------------
    from distributed_sudoku_solver_tpu.utils.puzzles import solved_board

    pending: list[tuple[int, jax.Array, jax.Array, jax.Array]] = []
    for lo in range(0, b, config.chunk):
        chunk = grids[lo : lo + config.chunk]
        pad = (-len(chunk)) % n_dev
        if pad:  # shard evenly; pre-solved pads are dropped on write-back
            chunk = np.concatenate(
                [chunk, np.tile(solved_board(geom)[None], (pad, 1, 1))]
            )
        # Boards cross the host<->device link as int8 (digits <= 35): 4x
        # less transfer than int32 — on tunneled/remote setups the link and
        # the per-dispatch round-trip, not the chip, bound bulk throughput.
        prop = config.propagator or _auto_propagator()
        stage1 = _stage1(geom, config.max_sweeps, prop, config.rules, mesh)
        dec, st_solved, st_contra = stage1(
            jnp.asarray(_to_wire_int8(chunk, geom))
        )
        k = len(chunk) - pad
        pending.append((lo, dec[:k], st_solved[:k], st_contra[:k]))
    for lo, dec, st_solved, st_contra in pending:
        dec, st_solved, st_contra = (
            np.asarray(dec),
            np.asarray(st_solved),
            np.asarray(st_contra),
        )
        hi = lo + dec.shape[0]
        solution[lo:hi][st_solved] = dec[st_solved]
        solved[lo:hi] = st_solved
        unsat[lo:hi] = st_contra
    by_propagation = solved.copy()

    # --- stage 2: frontier-search the undecided remainder -----------------
    survivors = np.flatnonzero(~solved & ~unsat)
    searched = int(len(survivors))
    # Frontier propagation backend: boards-last slice sweeps win at wide
    # lane counts; at the deep rungs' narrow widths the boards-first loop
    # fuses into VMEM anyway, so 'xla' avoids the transpose round-trips.
    rungs = [(config.search_lanes, 1, config.stack_slots, "slices")] + [
        (jobs, mult, slots, "xla") for jobs, mult, slots in config.rungs
    ]
    remaining = survivors
    for max_jobs, lanes_per_job, slots, prop in rungs:
        if len(remaining) == 0:
            break
        # Round the chunk up to a power of two (>= 64) so each rung compiles
        # O(log) distinct shapes across calls, not one per survivor count.
        jobs_per_chunk = min(max_jobs, max(64, 1 << (len(remaining) - 1).bit_length()))
        scfg = SolverConfig(
            min_lanes=jobs_per_chunk * lanes_per_job,
            stack_slots=slots,
            max_steps=config.max_steps,
            max_sweeps=config.max_sweeps,
            propagator=prop,
            rules=config.rules,
            # Gang rungs (many thief lanes per job) need fast fan-out: one
            # steal pairing per step would ramp a gang up only linearly.
            steal_rounds=4 if lanes_per_job > 1 else 1,
        )
        # Pad partial chunks with an already-complete board: its lane solves
        # on step one and immediately turns thief, joining the OR-parallel
        # gang on the real jobs (padding with a survivor copy would instead
        # burn those lanes re-searching the hardest board).
        pad_board = solved_board(geom)
        still: list[int] = []
        for lo in range(0, len(remaining), jobs_per_chunk):
            idx = remaining[lo : lo + jobs_per_chunk]
            batch = grids[idx]
            if len(idx) < jobs_per_chunk:  # keep one compiled shape per rung
                pad = np.tile(pad_board[None], (jobs_per_chunk - len(idx), 1, 1))
                batch = np.concatenate([batch, pad])
            batch8 = jnp.asarray(_to_wire_int8(batch, geom))  # 4x less uplink
            if mesh is not None:
                from distributed_sudoku_solver_tpu.parallel.sharded import (
                    solve_batch_sharded,
                )

                res = solve_batch_sharded(batch8, geom, scfg, mesh=mesh)
            else:
                res = solve_batch(batch8, geom, scfg)
            # Device-side downcast so the downlink moves int8, not int32.
            r_sol = np.asarray(res.solution.astype(jnp.int8))[: len(idx)]
            r_solved = np.asarray(res.solved)[: len(idx)]
            r_unsat = np.asarray(res.unsat)[: len(idx)]
            solution[idx] = np.where(r_solved[:, None, None], r_sol, 0)
            solved[idx] = r_solved
            unsat[idx] = r_unsat
            still.extend(idx[~r_solved & ~r_unsat])
        remaining = np.asarray(still, dtype=survivors.dtype)

    return BulkResult(
        solution=solution,
        solved=solved,
        unsat=unsat,
        by_propagation=by_propagation,
        searched=searched,
    )
