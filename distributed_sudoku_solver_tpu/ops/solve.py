"""Single-device batched solve: the `POST /solve` compute path, jit-compiled.

Replaces the reference's ``perform_solving`` + ``solve_sudoku`` pair
(``/root/reference/DHT_Node.py:424-538``): instead of one recursive search
per node with a per-recursion socket poll, a whole batch of jobs shares one
lane-stack frontier and one ``lax.while_loop``.  The return contract is
richer than the reference's (which can only ever say "solved"): each job
resolves to solved, *proven unsatisfiable* (every subtree exhausted, nothing
dropped), or unknown (step budget hit / stack overflow) — detected and
reported instead of hanging forever like a lost UDP TASK (SURVEY.md §2.5 #7).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid, encode_grid
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    frontier_live,
    init_frontier,
    run_frontier,
)


class SolveResult(NamedTuple):
    solution: jax.Array  # int32[J, n, n]; all-zero rows for unsat/unknown jobs
    solved: jax.Array  # bool[J]
    unsat: jax.Array  # bool[J]: proven unsatisfiable
    overflowed: jax.Array  # bool[J]: a subtree was dropped (stack overflow)
    nodes: jax.Array  # int32[J] branch nodes expanded ("validations" analog)
    steps: jax.Array  # int32 frontier rounds
    sweeps: jax.Array  # int32 total propagation sweeps
    expansions: jax.Array  # int32 total branch expansions
    steals: jax.Array  # int32 total lane-to-lane work steals


def _finalize(state: Frontier) -> SolveResult:
    n_jobs = state.solved.shape[0]
    live = frontier_live(state)
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    job_has_work = jnp.zeros(n_jobs, bool).at[job_safe].max(live, mode="drop")
    unsat = ~state.solved & ~job_has_work & ~state.overflowed
    solution = jnp.where(
        state.solved[:, None, None], decode_grid(state.solution), jnp.int32(0)
    )
    return SolveResult(
        solution=solution,
        solved=state.solved,
        unsat=unsat,
        overflowed=state.overflowed,
        nodes=state.nodes,
        steps=state.steps,
        sweeps=state.sweeps,
        expansions=state.expansions,
        steals=state.steals,
    )


@functools.partial(jax.jit, static_argnames=("geom", "config"))
def solve_batch(
    grids: jax.Array, geom: Geometry, config: SolverConfig = SolverConfig()
) -> SolveResult:
    """Solve int grids [J, n, n] (0 = empty); one compiled program per (J, geom, config)."""
    cand0 = encode_grid(grids, geom)
    state = init_frontier(cand0, config)
    state = run_frontier(state, geom, config)
    return _finalize(state)


def solve_one(grid, geom: Geometry, config: SolverConfig = SolverConfig()):
    """Convenience: solve a single board; returns (np solution | None, SolveResult)."""
    grids = jnp.asarray(np.asarray(grid)[None])
    res = solve_batch(grids, geom, config)
    solved = bool(res.solved[0])
    sol = np.asarray(res.solution[0]) if solved else None
    return sol, res
