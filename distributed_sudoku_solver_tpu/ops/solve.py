"""Single-device batched solve: the `POST /solve` compute path, jit-compiled.

Replaces the reference's ``perform_solving`` + ``solve_sudoku`` pair
(``/root/reference/DHT_Node.py:424-538``): instead of one recursive search
per node with a per-recursion socket poll, a whole batch of jobs shares one
lane-stack frontier and one ``lax.while_loop``.  The return contract is
richer than the reference's (which can only ever say "solved"): each job
resolves to solved, *proven unsatisfiable* (every subtree exhausted, nothing
dropped), or unknown (step budget hit / stack overflow) — detected and
reported instead of hanging forever like a lost UDP TASK (SURVEY.md §2.5 #7).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.models.sudoku import SudokuCSP
from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid, encode_grid
from distributed_sudoku_solver_tpu.ops.csp import CSProblem
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    frontier_live,
    init_frontier,
    run_frontier,
)


class SolveResult(NamedTuple):
    solution: jax.Array  # solved state per job (int32 grid for Sudoku entry
    #   points; raw uint32[h, w] problem state for solve_csp); zeros if unsolved
    solved: jax.Array  # bool[J]
    unsat: jax.Array  # bool[J]: search space exhausted with no resolution —
    #   proven unsatisfiable normally; under SolverConfig.count_all it means
    #   the enumeration ran to completion (sol_count is exact)
    overflowed: jax.Array  # bool[J]: a subtree was dropped (stack overflow)
    nodes: jax.Array  # int32[J] branch nodes expanded ("validations" analog)
    sol_count: jax.Array  # int32[J] solutions found (== 1/0 normally; the
    #   exact model count under SolverConfig.count_all enumeration)
    steps: jax.Array  # int32 frontier rounds
    sweeps: jax.Array  # int32 total propagation sweeps
    expansions: jax.Array  # int32 total branch expansions
    steals: jax.Array  # int32 total lane-to-lane work steals


def finalize_frontier(state: Frontier) -> SolveResult:
    """Frontier -> verdicts; the solution stays in raw problem-state form."""
    n_jobs = state.solved.shape[0]
    live = frontier_live(state)
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    job_has_work = jnp.zeros(n_jobs, bool).at[job_safe].max(live, mode="drop")
    unsat = ~state.solved & ~job_has_work & ~state.overflowed
    return SolveResult(
        solution=state.solution,
        solved=state.solved,
        unsat=unsat,
        overflowed=state.overflowed,
        nodes=state.nodes,
        sol_count=state.sol_count,
        steps=state.steps,
        sweeps=state.sweeps,
        expansions=state.expansions,
        steals=state.steals,
    )


def _decode_solution(res: SolveResult) -> SolveResult:
    """Sudoku entry points return int grids, not candidate masks.

    ``sol_count > 0`` keeps the first-found solution visible under
    ``count_all`` enumeration, where ``solved`` stays False by design."""
    has_sol = res.solved | (res.sol_count > 0)
    solution = jnp.where(
        has_sol[:, None, None], decode_grid(res.solution), jnp.int32(0)
    )
    return res._replace(solution=solution)


def _finalize(state: Frontier) -> SolveResult:
    return _decode_solution(finalize_frontier(state))


def sudoku_csp(geom: Geometry, config: SolverConfig) -> SudokuCSP:
    """The Sudoku problem a (geom, config) pair denotes — one place, everywhere."""
    return SudokuCSP(
        geom=geom,
        branch_rule=config.branch,
        max_sweeps=config.max_sweeps,
        propagator=config.propagator,
        rules=config.rules,
    )


@functools.partial(jax.jit, static_argnames=("problem", "config"))
def solve_csp(
    states0: jax.Array, problem: CSProblem, config: SolverConfig = SolverConfig()
) -> SolveResult:
    """Solve root states [J, h, w] of any CSP; solution is the raw solved state."""
    if config.step_impl == "fused":
        from distributed_sudoku_solver_tpu.models.cover import ExactCoverCSP

        if isinstance(problem, ExactCoverCSP):
            from distributed_sudoku_solver_tpu.ops.pallas_cover import (
                solve_cover_fused,
            )

            return solve_cover_fused(jnp.asarray(states0), problem, config)
        # No fused kernel for other CSP families; a silent composite
        # fallback would mislabel A/B measurements (the branch_k precedent).
        raise ValueError(
            "step_impl='fused' supports the Sudoku and exact-cover "
            f"families only; got a generic {type(problem).__name__}"
        )
    state = init_frontier(states0, config)
    state = run_frontier(state, problem, config)
    return finalize_frontier(state)


@functools.partial(jax.jit, static_argnames=("geom", "config"))
def solve_batch(
    grids: jax.Array, geom: Geometry, config: SolverConfig = SolverConfig()
) -> SolveResult:
    """Solve int grids [J, n, n] (0 = empty); one compiled program per (J, geom, config)."""
    if config.step_impl == "fused":
        from distributed_sudoku_solver_tpu.ops.pallas_step import (
            solve_batch_fused,
        )

        return solve_batch_fused(jnp.asarray(grids), geom, config)
    cand0 = encode_grid(grids, geom)
    state = init_frontier(cand0, config)
    state = run_frontier(state, sudoku_csp(geom, config), config)
    return _finalize(state)


@functools.partial(jax.jit, static_argnames=("geom", "config", "fmt"))
def solve_batch_wire(
    packed: jax.Array,
    geom: Geometry,
    config: SolverConfig = SolverConfig(),
    fmt: str = "packed",
) -> jax.Array:
    """Wire-format solve: packed grids in, packed solution + verdicts out.

    One upload, one dispatch, one download per chunk — the bulk pipeline's
    hot entry on tunneled devices, where every extra fetch costs a ~120 ms
    round trip and every byte moves at ~10 MB/s (``ops/wire.py``).
    ``fmt``: 'packed' (nibble/byte, the legacy format every tier speaks)
    or 'dense' (10-bit digit triplets, ~15% fewer bytes at n <= 9 — the
    bulk pipeline auto-selects it where it is smaller)."""
    from distributed_sudoku_solver_tpu.ops import wire

    if fmt == "dense":
        grids = wire.unpack_grids_dense_device(packed, geom)
    else:
        grids = wire.unpack_grids_device(packed, geom)
    res = solve_batch(grids, geom, config)  # one step_impl dispatch site
    if fmt == "dense":
        return wire.pack_result_dense_device(
            res.solution, res.solved, res.unsat, res.nodes > 0, geom
        )
    return wire.pack_result_device(
        res.solution, res.solved, res.unsat, res.nodes > 0, geom
    )


def solve_one(grid, geom: Geometry, config: SolverConfig = SolverConfig()):
    """Convenience: solve a single board; returns (np solution | None, SolveResult)."""
    grids = jnp.asarray(np.asarray(grid)[None])
    res = solve_batch(grids, geom, config)
    solved = bool(res.solved[0])
    sol = np.asarray(res.solution[0]) if solved else None
    return sol, res
