"""The lane-stack frontier: the reference's worker ring as one device tensor.

TPU-native re-design of the reference's entire L2 scheduler (SURVEY.md §1,
§2.1 #6/#7).  The mapping is one-to-one:

* **lane = worker node.**  Each of L lanes owns a working board ``top[L, h, w]``
  (the state it is currently expanding) plus a private circular stack
  ``stack[L, S, h, w]`` of deferred sibling subtrees — the reference's
  per-node recursion stack and ``task_queue`` unified into device tensors.
* **branch = the reference's guess loop.**  Each step, every live lane
  propagates its top to a fixpoint and (if undecided) splits one cell
  binarily: the *lowest candidate digit* becomes the new top (explored next —
  exact ascending-digit DFS order, ``/root/reference/DHT_Node.py:522``)
  while *the rest* is pushed onto the stack.  All lanes branch in lockstep:
  one ``lax.while_loop`` iteration advances every lane.
* **work stealing = the NEEDWORK handshake, tensorized.**  Idle lanes
  (no working board, or their job already solved) are matched each step with
  working lanes and steal the *bottom* stack row — the shallowest node, i.e.
  the largest unexplored subtree, the moral equivalent of the reference's
  ``split_array_in_middle`` shipping half the guess range
  (``/root/reference/DHT_Node.py:499-510``, ``utils.py:1-9``).  No
  messages, no idle lane while any lane has deferred work.
* **speculative cancellation = the SOLUTION_FOUND purge, in-graph.**  Lanes
  whose job is solved are cleared by a mask (``/root/reference/
  DHT_Node.py:358-387``) and immediately become thieves for other jobs.

Hot-loop design notes (this file is the single-chip performance core):

* The per-lane stack is **circular** (``base``/``count`` pointers), so a
  bottom-steal is a pointer bump — never a shift of the whole stack tensor.
* Every stack access is **row-granular** (one ``[L, h, w]`` gather or
  scatter per step); the full ``[L, S, h, w]`` tensor is never rewritten.
  The previous design's full-stack ``where`` masks and shift were ~2/3 of
  the measured step cost at L=32k.
* Thief/donor pairing is **prefix-sum rank matching** (two ``cumsum``s and
  O(L) scatters), not ``argsort`` — sorting 32k lanes per step cost more
  than the propagation fixpoint itself.

Per-lane LIFO makes progress unconditional (each live lane consumes exactly
one node per step), so unlike a flat expansion pool the frontier cannot
deadlock at capacity; a stack that would overflow S drops its *rest*
sibling and records the loss per job (``overflowed``), downgrading a
would-be "unsat" verdict to "unknown" rather than ever reporting wrongly.

The engine is generic over the problem family (``ops/csp.py``): states are
opaque ``uint32[h, w]`` tensors, and propagation / classification /
branching are the problem's three kernels.  Sudoku lives in
``models/sudoku.py``; generalized exact cover in ``models/cover.py``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.ops.csp import CSProblem

# Per-surface defaults for ``SolverConfig.fused_steps`` (rounds per fused
# kernel dispatch), resolved by each entry point via
# ``SolverConfig.with_fused_steps``.  The r4 device-resident re-sweep
# measured 32 fastest (417k vs 359k boards/s at 8) while the e2e bulk A/B
# through the tunnel went the other way (8 -> 94k vs 32 -> 74k: purge/steal
# granularity costs reactivity and the pipeline is transfer-bound) — so the
# default is a property of the SURFACE, not of the solver (BENCHMARKS.md
# "round 6: per-surface fused_steps").
FUSED_STEPS_DEVICE = 32  # device-resident: engine flights, direct batch
#   solves, sharded meshes, bulk escalation rungs (state stays on-device
#   between dispatches)
FUSED_STEPS_LINKED = 8  # per-chunk transfer surfaces: the bulk first pass
#   (every chunk crosses the link) — and the cover kernel on every surface
#   (16/32 re-measured within noise there and declined, BENCHMARKS.md r5)


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Static solver configuration (hashable: becomes part of the jit key)."""

    lanes: int = 0  # total lanes; 0 = auto: max(n_jobs, min_lanes)
    min_lanes: int = 64  # speculation width floor for small job counts
    stack_slots: int = 64  # deferred-sibling stack depth per lane
    max_steps: int = 100_000  # branch rounds before giving up
    max_sweeps: int = 64  # propagation sweeps per fixpoint (Sudoku adapter)
    branch: str = "minrem"  # Sudoku branch rule: 'minrem' | 'first' (ref
    #   order, bit-exactness tests) | 'mixed' (per-state hash-diversified) |
    #   'minrem-desc' (MRV, descending digits — the portfolio-racing mirror)
    rules: str = "basic"  # propagation strength: 'basic' (elimination +
    #   hidden singles) | 'extended' (+ box-line reductions) | 'subsets'
    #   (+ naked-subset eliminations, for deep search) — all backends
    propagator: str = "xla"  # 'xla' | 'pallas' (VMEM kernel; batch solves only
    #   — the board-sharded path has its own collective sweep and rejects it)
    branch_k: int = 2  # 2 = binary guess-vs-rest; 3 = two singleton children
    #   + rest per expansion (shallower stacks, thief-ready second child;
    #   requires the problem to implement branch3 — Sudoku does)
    count_all: bool = False  # enumerate ALL solutions: jobs never resolve
    #   on a solve — each solved top bumps its job's sol_count and the lane
    #   pops its next subtree, so the search runs to exhaustion and
    #   ``sol_count`` is the exact model count (a lower bound if overflowed).
    #   Single-device entry points still return the first solution found
    #   per job; the lane-sharded path returns counts only (zeros solution).
    step_impl: str = "xla"  # 'xla' (composite step, bit-exactness contract)
    #   | 'fused' (whole-round VMEM Pallas kernel, ops/pallas_step.py:
    #   k-step dispatches, purge/steal at that granularity — sound, not
    #   bit-exact to 'xla'; serves batch solves AND engine flights via
    #   advance_frontier_fused; single-chip and lane-sharded meshes)
    fused_steps: int | None = None  # frontier rounds per fused-kernel
    #   dispatch; None = the calling surface's measured default
    #   (FUSED_STEPS_DEVICE on device-resident paths, FUSED_STEPS_LINKED on
    #   per-chunk transfer paths — resolved via ``with_fused_steps``)
    fused_sweep_unroll: int = 2  # fixpoint sweeps run as a straight-line
    #   prefix before the convergence-checked loop inside the fused kernel
    #   (pallas_propagate._fixpoint_boards_last unroll): bit-exact (a sweep
    #   of a fixpoint is the identity), amortizes the per-sweep loop
    #   machinery over the 2-5-sweep post-branch fixpoints that dominate
    #   after round 1; 0 = the pre-round-6 checked-every-sweep loop
    #   (benchmarks/probe_fused_vpu.py A/Bs the two)
    steal: bool = True  # receiver-initiated work stealing between lanes
    steal_rounds: int = 1  # pairings per step; >1 ramps idle gangs up faster
    #   (a donor serves one thief per round, so a lone rich lane feeds at
    #   most `steal_rounds` thieves per step — matters for wide-lane few-job
    #   gang search, where 1 round means linear rather than quick fan-out).
    #   NOTE: the fused drivers (pallas_step/pallas_cover `_fused_round`)
    #   run ONE pairing per k-step dispatch regardless of this knob — see
    #   ops/bulk.BulkConfig.rung_step_impl for the serving consequence.
    steal_gang: int = 0  # > 0: steal pairs only within consecutive lane
    #   gangs of this size (lane l may steal from lanes in the same
    #   floor(l / steal_gang) block).  The resident-flight invariant
    #   (serving/scheduler.py): gang g's lanes only ever hold work for the
    #   job seeded at lane g * steal_gang, so detaching that job leaves the
    #   whole gang free for the next attach — global stealing would leak
    #   other jobs' subtrees into the gang and make slot recycling unsound.
    #   0 = global pairing (every batch-solve surface).  Must divide the
    #   lane count when set.
    ring_steal_k: int = 8  # max boards shipped per step per chip pair (sharded)
    protect_home_lanes: bool = False  # home lanes (l % steal_gang == 0) never
    #   act as steal THIEVES.  The mesh-resident flight's companion to
    #   `ring_install_ok`: ring steal already never installs a foreign row
    #   on a home lane (the next attach_roots overwrites it unconditionally,
    #   losing the subtree), but without this flag the local gang-scoped
    #   steal can relay one there — a freed slot's home lane is the
    #   lowest-ranked idle lane in its gang, so it is the FIRST thief the
    #   round after a detach.  With the flag on, a home lane only ever
    #   carries its own slot's tag and the attach overwrite is sound.
    #   Single-chip resident flights keep it off: gang lanes are
    #   tag-homogeneous there, so detach always clears the home lane before
    #   the next attach.  No-op when steal_gang == 0.

    def __post_init__(self) -> None:
        # Config-time branch validation (ISSUE 19 satellite): a typo'd
        # rule or unknown scoring head used to surface only when the
        # problem object was built mid-solve; fail at construction, where
        # the CLI/engine/HTTP boundary can still answer 4xx.
        from distributed_sudoku_solver_tpu.ops import ordering

        ordering.validate_branch(self.branch)
        if self.branch_k not in (2, 3):
            raise ValueError(f"branch_k must be 2 or 3, got {self.branch_k}")
        if self.step_impl not in ("xla", "fused"):
            raise ValueError(f"unknown step_impl {self.step_impl!r}")
        if self.step_impl == "fused" and self.branch_k != 2:
            raise ValueError("step_impl='fused' supports branch_k=2 only")
        if self.fused_steps is not None and self.fused_steps < 1:
            # 0 would make every fused dispatch a no-op: the driver's outer
            # while (any live & steps < max) then spins forever in-graph.
            raise ValueError(f"fused_steps must be >= 1, got {self.fused_steps}")
        if self.fused_sweep_unroll < 0:
            raise ValueError(
                f"fused_sweep_unroll must be >= 0, got {self.fused_sweep_unroll}"
            )
        if self.steal_gang < 0:
            raise ValueError(f"steal_gang must be >= 0, got {self.steal_gang}")

    def with_fused_steps(self, surface_default: int) -> "SolverConfig":
        """Resolve ``fused_steps=None`` to the calling surface's default.

        Every fused entry point calls this with its surface constant
        (``FUSED_STEPS_DEVICE`` / ``FUSED_STEPS_LINKED``) before the config
        reaches a kernel dispatch; an explicit ``fused_steps`` always wins
        (the portfolio's reactive fused racer pins 4, tests pin 2)."""
        if self.fused_steps is not None:
            return self
        return dataclasses.replace(self, fused_steps=surface_default)

    def resolve_lanes(self, n_jobs: int) -> int:
        lanes = self.lanes if self.lanes > 0 else max(n_jobs, self.min_lanes)
        if lanes < n_jobs:
            raise ValueError(f"lanes={lanes} < n_jobs={n_jobs}")
        return lanes

    def resolve_lanes_packed(self, n_roots: int) -> int:
        """Lane count :func:`init_frontier_packed` will use for ``n_roots``
        round-robin-dealt rows — the single source of truth for callers
        (the engine's fused-width validation) that must predict it."""
        if self.lanes > 0:
            return self.lanes
        import math

        return max(self.min_lanes, math.ceil(n_roots / (1 + self.stack_slots)))


class Frontier(NamedTuple):
    """Loop-carried device state for one solve call."""

    top: jax.Array  # uint32[L, h, w] working state per lane (inert if !has_top)
    has_top: jax.Array  # bool[L] lane holds a working state
    stack: jax.Array  # uint32[L, S, h, w] deferred siblings (circular buffer)
    base: jax.Array  # int32[L] bottom slot of the circular stack
    count: jax.Array  # int32[L] deferred rows on the stack
    job: jax.Array  # int32[L] owning job; -1 = unassigned
    solved: jax.Array  # bool[J]
    solution: jax.Array  # uint32[J, h, w] (solved problem state)
    overflowed: jax.Array  # bool[J] some subtree was dropped (stack full)
    nodes: jax.Array  # int32[J] branch nodes expanded per job
    sol_count: jax.Array  # int32[J] solutions found (count_all enumeration)
    steps: jax.Array  # int32 scalar
    sweeps: jax.Array  # int32 scalar total propagation sweeps
    expansions: jax.Array  # int32 scalar total branch expansions
    steals: jax.Array  # int32 scalar total bottom-steals
    lane_rounds: jax.Array  # int32[L] rounds each lane was LIVE (held a
    #   working state for an unresolved job) — the occupancy counter behind
    #   the /metrics fused_lane_occupancy histogram (ROADMAP 4b evidence);
    #   maintained in-kernel by the fused path, per step by the composite


def _seed_inverse(n_roots: int, n_lanes: int):
    """Static inverse of the strided seed map floor(r * L / R).

    Returns ``(root_of, is_seed, safe_root)``: which root (if any) seeds
    each lane.  Injective because ``n_lanes >= n_roots``; sentinel
    ``n_roots`` marks unseeded lanes.  Host-side int64 numpy — ``r * L``
    overflows int32 beyond ~46k lanes, and shapes are static so this is
    free at trace time.  Seeding via this gather (instead of same-index
    top/has_top/job scatters) avoids XLA merging them into a variadic
    scatter whose TPU emitter hits ``scatter_emitter.cc`` ``Check failed:
    operand_indices.size() == 1 (2 vs. 1)`` at >= 131,072 lanes
    (repro: ``benchmarks/repro_scatter131k.py --stage init``).
    """
    import numpy as np

    seed_lane = (np.arange(n_roots, dtype=np.int64) * n_lanes) // n_roots
    root_of_np = np.full(n_lanes, n_roots, np.int64)
    root_of_np[seed_lane] = np.arange(n_roots)
    root_of = jnp.asarray(root_of_np, jnp.int32)
    is_seed = jnp.asarray(root_of_np < n_roots)
    safe_root = jnp.clip(root_of, 0, n_roots - 1)
    return root_of, is_seed, safe_root


def init_frontier(states0: jax.Array, config: SolverConfig) -> Frontier:
    """Seed each job's root state into its own lane (the root TASK self-send,
    ``/root/reference/DHT_Node.py:551``); extra lanes start as thieves.

    Seed lanes are *strided* across the lane axis — floor(j*L/J), strictly
    increasing since L >= J — so that when lanes are sharded over a mesh
    every chip starts with its share of root jobs instead of chip 0 holding
    everything.

    Seeding is expressed as a *gather* (``states0[root_of_lane]``) rather
    than scatters — see :func:`_seed_inverse` for the XLA:TPU variadic
    scatter crash this avoids.
    """
    n_jobs, h, w = states0.shape
    n_lanes = config.resolve_lanes(n_jobs)
    s = config.stack_slots
    root_of, is_seed, safe_root = _seed_inverse(n_jobs, n_lanes)
    top = jnp.where(
        is_seed[:, None, None], states0.astype(jnp.uint32)[safe_root], jnp.uint32(0)
    )
    has_top = is_seed
    job = jnp.where(is_seed, root_of, jnp.int32(-1))
    return Frontier(
        top=top,
        has_top=has_top,
        stack=jnp.zeros((n_lanes, s, h, w), jnp.uint32),
        base=jnp.zeros(n_lanes, jnp.int32),
        count=jnp.zeros(n_lanes, jnp.int32),
        job=job,
        solved=jnp.zeros(n_jobs, bool),
        solution=jnp.zeros((n_jobs, h, w), jnp.uint32),
        overflowed=jnp.zeros(n_jobs, bool),
        nodes=jnp.zeros(n_jobs, jnp.int32),
        sol_count=jnp.zeros(n_jobs, jnp.int32),
        steps=jnp.int32(0),
        sweeps=jnp.int32(0),
        expansions=jnp.int32(0),
        steals=jnp.int32(0),
        lane_rounds=jnp.zeros(n_lanes, jnp.int32),
    )


def init_frontier_roots(
    roots: jax.Array, job_of_root: jax.Array, n_jobs: int, config: SolverConfig
) -> Frontier:
    """Seed a frontier from R root states, each tagged with an owning job.

    Generalizes :func:`init_frontier`: a *resumed* or *offloaded* job
    re-enters the search as the disjunction of its surviving subtree roots
    (candidate-mask states extracted from a previous frontier), not as its
    original clue grid — the TPU heir of the reference shipping its current
    partially-filled grid + guess range to a thief
    (``/root/reference/DHT_Node.py:502-509``).  Roots whose ``job_of_root``
    is -1 are padding and leave their lane idle (an immediate thief).

    Gather-formulated like :func:`init_frontier` (see :func:`_seed_inverse`
    for the variadic-scatter TPU compile crash this avoids); root
    *validity* is dynamic, so it rides the gathered ``job_of_root``.
    """
    n_roots, h, w = roots.shape
    n_lanes = config.resolve_lanes(n_roots)
    _, seeded, safe_root = _seed_inverse(n_roots, n_lanes)
    is_seed = seeded & (job_of_root[safe_root] >= 0)
    top = jnp.where(
        is_seed[:, None, None], roots.astype(jnp.uint32)[safe_root], jnp.uint32(0)
    )
    has_top = is_seed
    job = jnp.where(is_seed, job_of_root[safe_root], jnp.int32(-1))
    s = config.stack_slots
    return Frontier(
        top=top,
        has_top=has_top,
        stack=jnp.zeros((n_lanes, s, h, w), jnp.uint32),
        base=jnp.zeros(n_lanes, jnp.int32),
        count=jnp.zeros(n_lanes, jnp.int32),
        job=job,
        solved=jnp.zeros(n_jobs, bool),
        solution=jnp.zeros((n_jobs, h, w), jnp.uint32),
        overflowed=jnp.zeros(n_jobs, bool),
        nodes=jnp.zeros(n_jobs, jnp.int32),
        sol_count=jnp.zeros(n_jobs, jnp.int32),
        steps=jnp.int32(0),
        sweeps=jnp.int32(0),
        expansions=jnp.int32(0),
        steals=jnp.int32(0),
        lane_rounds=jnp.zeros(n_lanes, jnp.int32),
    )


def init_frontier_packed(
    roots: jax.Array, valid: jax.Array, config: SolverConfig
) -> Frontier:
    """Seed ONE job's subtree roots at the *configured* lane width.

    Unlike :func:`init_frontier_roots` (one row per lane, so R roots force
    >= R lanes), rows are dealt round-robin: row r lands on lane ``r % L`` —
    the first as the lane's top, the rest pushed onto its stack.  A resumed
    or offloaded search therefore runs at the same width (and the same
    speculative-expansion budget) as the original, which keeps ``nodes``
    counters comparable and the jit cache keyed on the row *bucket*, not the
    exact row count.  ``valid`` masks terminal padding rows (invalid rows
    must come last, so each lane's stack slots stay contiguous).
    """
    n_roots, h, w = roots.shape
    s = config.stack_slots
    import numpy as np

    n_lanes = config.resolve_lanes_packed(n_roots)
    if n_roots > n_lanes * (1 + s):
        raise ValueError(
            f"{n_roots} roots exceed frontier capacity {n_lanes}x(1+{s})"
        )
    # Gather-formulated (see init_frontier: same-index seeding scatters get
    # merged into a variadic scatter that crashes the XLA:TPU emitter at
    # giant lane counts).  Row r lands on lane r % L, slot r // L - 1; the
    # inverse — which root belongs at (lane, slot) — is the static grid
    # r = lane + (slot+1) * L, so every seed is a gather from ``roots``.
    valid = jnp.asarray(valid, bool)
    rows = roots.astype(jnp.uint32)

    r_top = np.arange(n_lanes)
    top_exists = r_top < n_roots
    safe_top = jnp.asarray(np.minimum(r_top, n_roots - 1), jnp.int32)
    is_top = jnp.asarray(top_exists) & valid[safe_top]
    top = jnp.where(is_top[:, None, None], rows[safe_top], jnp.uint32(0))
    has_top = is_top
    job = jnp.where(is_top, jnp.int32(0), jnp.int32(-1))

    r_st = r_top[:, None] + (np.arange(s)[None, :] + 1) * n_lanes  # [L, S]
    st_exists = r_st < n_roots
    safe_st = jnp.asarray(np.minimum(r_st, n_roots - 1), jnp.int32)
    is_stack = jnp.asarray(st_exists) & valid[safe_st]
    stack = jnp.where(is_stack[:, :, None, None], rows[safe_st], jnp.uint32(0))
    count = jnp.sum(is_stack, axis=1, dtype=jnp.int32)
    return Frontier(
        top=top,
        has_top=has_top,
        stack=stack,
        base=jnp.zeros(n_lanes, jnp.int32),
        count=count,
        job=job,
        solved=jnp.zeros(1, bool),
        solution=jnp.zeros((1, h, w), jnp.uint32),
        overflowed=jnp.zeros(1, bool),
        nodes=jnp.zeros(1, jnp.int32),
        sol_count=jnp.zeros(1, jnp.int32),
        steps=jnp.int32(0),
        sweeps=jnp.int32(0),
        expansions=jnp.int32(0),
        steals=jnp.int32(0),
        lane_rounds=jnp.zeros(n_lanes, jnp.int32),
    )


def purge_jobs(state: Frontier, dead: jax.Array) -> Frontier:
    """Clear every lane owned by a job in ``dead`` (bool[J]) — the in-graph
    mid-flight CANCEL.

    The reference's kernel polls for cancellation once per recursion step
    (``/root/reference/DHT_Node.py:481-488``); here the chunked device loop
    applies this purge between bounded-step chunks, so a host ``cancel``
    frees the cancelled job's lanes within one chunk.  ``overflowed`` is set
    for purged jobs so finalize reports "unknown", never a false
    proven-unsat.
    """
    n_jobs = state.solved.shape[0]
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    lane_dead = (state.job >= 0) & dead[job_safe]
    return state._replace(
        has_top=state.has_top & ~lane_dead,
        count=jnp.where(lane_dead, 0, state.count),
        overflowed=state.overflowed | (dead & ~state.solved),
    )


def attach_roots(
    state: Frontier, roots: jax.Array, slot_ids: jax.Array, gang: int = 1
) -> Frontier:
    """Seed up to K newly admitted jobs into a *live* frontier — the attach
    half of the resident flight (``serving/scheduler.py``), jit-stable.

    ``roots`` uint32[K, h, w] (one root state per arriving job), ``slot_ids``
    int32[K] (the job slot each root occupies; -1 = padding row, ignored).
    K is a static shape, validity is dynamic, so one compiled program serves
    every admission batch.  Root k lands on its slot's *home lane*
    ``slot_ids[k] * gang``: under gang-scoped stealing
    (``SolverConfig.steal_gang == gang``) every lane of gang g only ever
    holds work for the job attached at slot g, so a slot handed out by the
    host-side allocator is guaranteed a clean, free gang.  The slot's
    bookkeeping rows are reset here (the previous tenant's verdict was
    collected before the slot re-entered the free pool), so a stale
    ``solved`` can never purge the new tenant.

    Scatters are same-index across several leaves; the known XLA:TPU
    variadic-scatter emitter crash (:func:`_seed_inverse`) starts at
    ~131k lanes — far above serving-scale resident frontiers.
    """
    n_lanes = state.has_top.shape[0]
    n_jobs = state.solved.shape[0]
    ok = slot_ids >= 0
    lane = jnp.where(ok, slot_ids * gang, n_lanes)  # OOB -> dropped
    slot_t = jnp.where(ok, slot_ids, n_jobs)
    zero_k = jnp.zeros(slot_ids.shape[0], jnp.int32)
    return state._replace(
        top=state.top.at[lane].set(roots.astype(jnp.uint32), mode="drop"),
        has_top=state.has_top.at[lane].set(ok, mode="drop"),
        job=state.job.at[lane].set(slot_ids, mode="drop"),
        base=state.base.at[lane].set(zero_k, mode="drop"),
        count=state.count.at[lane].set(zero_k, mode="drop"),
        solved=state.solved.at[slot_t].set(False, mode="drop"),
        solution=state.solution.at[slot_t].set(jnp.uint32(0), mode="drop"),
        overflowed=state.overflowed.at[slot_t].set(False, mode="drop"),
        nodes=state.nodes.at[slot_t].set(zero_k, mode="drop"),
        sol_count=state.sol_count.at[slot_t].set(zero_k, mode="drop"),
    )


def detach(state: Frontier, slot_mask: jax.Array) -> Frontier:
    """Free every lane and bookkeeping row of the jobs in ``slot_mask``
    (bool[J]) — the release half of the resident flight's slot recycling.

    Unlike :func:`purge_jobs` (a mid-flight CANCEL, which must keep the
    job's verdict honest by downgrading it to unknown), detach runs *after*
    the host collected the slot's verdict: lanes are cleared, the lane
    ``job`` tag drops to -1, and the slot rows reset to their init state so
    the next :func:`attach_roots` tenant starts clean.
    """
    n_jobs = state.solved.shape[0]
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    lane_dead = (state.job >= 0) & slot_mask[job_safe]
    keep = ~slot_mask
    return state._replace(
        has_top=state.has_top & ~lane_dead,
        count=jnp.where(lane_dead, 0, state.count),
        job=jnp.where(lane_dead, jnp.int32(-1), state.job),
        solved=state.solved & keep,
        solution=jnp.where(
            slot_mask[:, None, None], jnp.uint32(0), state.solution
        ),
        overflowed=state.overflowed & keep,
        nodes=jnp.where(slot_mask, 0, state.nodes),
        sol_count=jnp.where(slot_mask, 0, state.sol_count),
    )


def shed_rows(state: Frontier, job_id: jax.Array, k: int):
    """Extract up to ``k`` bottom stack rows of ``job_id`` for off-device work.

    The donor side of *cluster-tier* mid-job offload: bottom rows are the
    shallowest deferred siblings — the largest unexplored subtrees — exactly
    what the reference ships when it halves its live guess range for an idle
    neighbor (``/root/reference/DHT_Node.py:499-510``).  One row per donor
    lane per call (a pointer bump, like :func:`_steal`).  Returns
    ``(new_state, rows uint32[k, h, w], valid bool[k])``.
    """
    n_lanes, s = state.stack.shape[:2]
    n_jobs = state.solved.shape[0]
    job_live = (state.job == job_id) & ~state.solved[jnp.clip(state.job, 0, n_jobs - 1)]
    donor = job_live & (state.count >= 1)
    donor_of = _lane_by_rank(donor, n_lanes)
    idx = jnp.arange(k, dtype=jnp.int32)
    # k may exceed n_lanes (e.g. shed_k=8 against a 1-lane portfolio config);
    # an OOB gather clamps to the last donor entry, so without the idx mask
    # the same stack row would ship multiple times, all marked valid.
    donor_lane = donor_of[jnp.clip(idx, 0, n_lanes - 1)]  # n_lanes if absent
    valid = (idx < n_lanes) & (donor_lane < n_lanes)
    safe = jnp.clip(donor_lane, 0, n_lanes - 1)
    rows = state.stack[safe, state.base[safe] % s]
    rows = jnp.where(valid[:, None, None], rows, 0)
    # Route invalid entries OOB instead of .set(valid): duplicate clamped
    # indices land on one lane and a scatter with mixed True/False values at
    # the same index is order-undefined — a False could win and leave a
    # shipped row on the donor stack (searched twice, re-shed forever).
    donor_sel = (
        jnp.zeros(n_lanes, bool)
        .at[jnp.where(valid, donor_lane, n_lanes)]
        .set(True, mode="drop")
    )
    new_state = state._replace(
        base=jnp.where(donor_sel, (state.base + 1) % s, state.base),
        count=jnp.where(donor_sel, state.count - 1, state.count),
    )
    return new_state, rows, valid


def _rank_of(mask: jax.Array) -> jax.Array:
    """int32[L]: 0-based rank of each True lane among the True lanes."""
    return jnp.cumsum(mask.astype(jnp.int32)) - 1


def _lane_by_rank(mask: jax.Array, n_lanes: int) -> jax.Array:
    """int32[L]: lane index of the r-th True lane (n_lanes where r >= popcount)."""
    lane_idx = jnp.arange(n_lanes, dtype=jnp.int32)
    rank = jnp.where(mask, _rank_of(mask), n_lanes)
    return jnp.full(n_lanes, n_lanes, jnp.int32).at[rank].set(
        lane_idx, mode="drop"
    )


def pair_thieves_donors(
    idle: jax.Array, donor: jax.Array, n_lanes: int, gang: int = 0
):
    """Rank-match idle lanes with donor lanes; the pairing core of every
    steal variant (composite, boards-last fused, gang-scoped resident).

    Returns ``(thief_lane, donor_lane, pair, n_pairs)`` on the rank axis
    (int32[L], int32[L], bool[L], int32 scalar): entry r pairs the r-th
    idle lane with the r-th donor lane; unmatched ranks carry ``n_lanes``
    (an OOB sentinel scatters with ``mode='drop'``).  With ``gang > 0``
    ranks are computed *within* each consecutive ``gang``-lane block
    (reshape + per-row cumsum — still O(L), no sorting), so work never
    crosses a gang boundary — the resident-flight slot invariant
    (``SolverConfig.steal_gang``).
    """
    if gang > 0:
        if n_lanes % gang:
            raise ValueError(f"steal_gang={gang} does not divide lanes={n_lanes}")
        n_gangs = n_lanes // gang
        idle2 = idle.reshape(n_gangs, gang)
        donor2 = donor.reshape(n_gangs, gang)
        thief_of = jax.vmap(lambda m: _lane_by_rank(m, gang))(idle2)
        donor_of = jax.vmap(lambda m: _lane_by_rank(m, gang))(donor2)
        pairs_g = jnp.minimum(
            jnp.sum(idle2, axis=1), jnp.sum(donor2, axis=1)
        ).astype(jnp.int32)  # [G]
        rank_in_gang = jnp.arange(gang, dtype=jnp.int32)[None, :]
        pair2 = rank_in_gang < pairs_g[:, None]
        offs = (jnp.arange(n_gangs, dtype=jnp.int32) * gang)[:, None]
        # Within-gang lane -> global lane; unmatched ranks -> n_lanes.
        thief_lane = jnp.where(pair2, thief_of + offs, n_lanes).reshape(-1)
        donor_lane = jnp.where(pair2, donor_of + offs, n_lanes).reshape(-1)
        return thief_lane, donor_lane, pair2.reshape(-1), jnp.sum(pairs_g)
    lane_idx = jnp.arange(n_lanes, dtype=jnp.int32)
    n_pairs = jnp.minimum(jnp.sum(idle), jnp.sum(donor)).astype(jnp.int32)
    thief_of = _lane_by_rank(idle, n_lanes)  # rank -> thief lane
    donor_of = _lane_by_rank(donor, n_lanes)  # rank -> donor lane
    pair = lane_idx < n_pairs  # rank axis
    thief_lane = jnp.where(pair, thief_of, n_lanes)  # OOB -> dropped
    donor_lane = jnp.where(pair, donor_of, n_lanes)
    return thief_lane, donor_lane, pair, n_pairs


def _steal(
    top: jax.Array,
    has_top: jax.Array,
    stack: jax.Array,
    base: jax.Array,
    count: jax.Array,
    job: jax.Array,
    job_live: jax.Array,
    gang: int = 0,
    thief_ok: jax.Array | None = None,
):
    """Match idle lanes with working lanes; hand each thief a donor's *bottom* row.

    Receiver-initiated like the reference's NEEDWORK (``/root/reference/
    DHT_Node.py:246-254``).  Pairing is k-th idle lane with k-th donor lane
    (both in lane order) via prefix-sum ranks — O(L) scatters, no sorting;
    each donor serves at most one thief per round (``gang`` scopes the
    pairing to lane blocks, see :func:`pair_thieves_donors`).  The stolen
    row goes straight into the thief's ``top``, and the donor's bottom
    pointer bumps: no stack data moves on the donor side at all.

    ``thief_ok`` (bool[L], optional) restricts which idle lanes may steal —
    ``SolverConfig.protect_home_lanes`` passes the non-home-lane mask on the
    mesh-resident path.  ``None`` keeps the original any-idle behavior and
    the exact same jaxpr.
    """
    n_lanes, s = stack.shape[:2]

    idle = ~has_top if thief_ok is None else (~has_top & thief_ok)
    donor = has_top & (count >= 1) & job_live
    thief_lane, donor_lane, pair, n_pairs = pair_thieves_donors(
        idle, donor, n_lanes, gang
    )
    safe_donor = jnp.clip(donor_lane, 0, n_lanes - 1)

    stolen = stack[safe_donor, base[safe_donor] % s]
    top = top.at[thief_lane].set(stolen, mode="drop")
    has_top = has_top.at[thief_lane].set(pair, mode="drop")
    job = job.at[thief_lane].set(job[safe_donor], mode="drop")

    donor_sel = jnp.zeros(n_lanes, bool).at[donor_lane].set(pair, mode="drop")
    base = jnp.where(donor_sel, (base + 1) % s, base)
    count = jnp.where(donor_sel, count - 1, count)
    return top, has_top, base, count, job, n_pairs


def frontier_step(
    state: Frontier, problem: CSProblem, config: SolverConfig
) -> Frontier:
    """One lockstep round: propagate tops -> harvest/cancel -> branch/pop -> steal."""
    n_lanes, s = state.stack.shape[:2]
    n_jobs = state.solved.shape[0]
    lane_idx = jnp.arange(n_lanes, dtype=jnp.int32)

    # Lanes whose job resolved are cleared (the SOLUTION_FOUND purge).
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    job_live = (state.job >= 0) & ~state.solved[job_safe]
    live = state.has_top & job_live
    count = jnp.where(job_live, state.count, 0)

    # --- L0: propagate every live top to a fixpoint -------------------------
    tops = jnp.where(live[:, None, None], state.top, 0)  # idle tops are inert
    tops, sweeps = problem.propagate(tops)
    top_solved, top_contra = problem.status(tops)
    solved_tops = top_solved & live
    contra_tops = top_contra & live
    undecided = live & ~solved_tops & ~contra_tops

    # --- harvest solutions: deterministic lowest-lane winner per job --------
    scatter_job = jnp.where(solved_tops, state.job, n_jobs)
    first = jnp.full(n_jobs, n_lanes, jnp.int32).at[scatter_job].min(
        jnp.where(solved_tops, lane_idx, n_lanes), mode="drop"
    )
    had_sol = state.sol_count > 0
    newly = (first < n_lanes) & ~state.solved & ~had_sol
    sol_rows = tops[jnp.clip(first, 0, n_lanes - 1)]
    solution = jnp.where(newly[:, None, None], sol_rows, state.solution)
    if config.count_all:
        # Enumeration: the job never resolves on a solve — every solved top
        # this round is counted and its lane pops the next subtree below,
        # so the search runs to exhaustion and sol_count is the exact
        # model count.
        sol_count = state.sol_count.at[scatter_job].add(
            solved_tops.astype(jnp.int32), mode="drop"
        )
        solved = state.solved
    else:
        # Normal mode: exactly the job-resolution event — two lanes of one
        # job solving in the same round must still count once.
        sol_count = state.sol_count + newly.astype(jnp.int32)
        solved = state.solved | newly

    # --- branch: guess becomes the new top, sibling rows are pushed ---------
    if config.branch_k == 3 and not hasattr(problem, "branch3"):
        # A silent binary fallback would mislabel A/B measurements.
        raise ValueError(
            f"branch_k=3 requires the problem to implement branch3; "
            f"{type(problem).__name__} does not"
        )
    if config.branch_k == 3:
        # Two pushes per expansion (rest first, then the second singleton:
        # LIFO pops ascending).  The second child being a *singleton* means
        # a thief that steals it starts propagating immediately instead of
        # spending a step re-splitting a rest blob.
        guess, second, rest3, has_rest3 = problem.branch3(tops)
        push_a = undecided & has_rest3 & (count < s)
        slot_a = (state.base + count) % s
        stack = state.stack.at[
            jnp.where(push_a, lane_idx, n_lanes), jnp.clip(slot_a, 0, s - 1)
        ].set(rest3, mode="drop")
        count_a = count + push_a.astype(jnp.int32)
        push_b = undecided & (count_a < s)
        slot_b = (state.base + count_a) % s
        stack = stack.at[
            jnp.where(push_b, lane_idx, n_lanes), jnp.clip(slot_b, 0, s - 1)
        ].set(second, mode="drop")
        can_push = push_b  # the guess survives regardless; see overflow below
        count = count_a  # push_b accounted via can_push in the update below
        overflow_now = undecided & (~push_b | (has_rest3 & ~push_a))
    else:
        guess, rest = problem.branch(tops)

        can_push = undecided & (count < s)
        push_slot = (state.base + count) % s
        stack = state.stack.at[
            jnp.where(can_push, lane_idx, n_lanes), jnp.clip(push_slot, 0, s - 1)
        ].set(rest, mode="drop")

        # On overflow: keep DFS-ing the guess in place; the rest-subtree is lost.
        overflow_now = undecided & ~can_push
    overflowed = state.overflowed.at[
        jnp.where(overflow_now, state.job, n_jobs)
    ].set(True, mode="drop")
    nodes = state.nodes.at[jnp.where(undecided, state.job, n_jobs)].add(
        jnp.where(undecided, jnp.int32(1), jnp.int32(0)), mode="drop"
    )

    # --- resolved lanes pop their next deferred sibling ---------------------
    resolved = solved_tops | contra_tops
    can_pop = resolved & (count > 0)
    pop_slot = (state.base + count - 1) % s
    popped = state.stack[lane_idx, jnp.clip(pop_slot, 0, s - 1)]

    top = jnp.where(undecided[:, None, None], guess, state.top)
    top = jnp.where(can_pop[:, None, None], popped, top)
    has_top = state.has_top & job_live & ~(resolved & ~can_pop)
    count = count + can_push.astype(jnp.int32) - can_pop.astype(jnp.int32)

    # --- work stealing ------------------------------------------------------
    job_live = (state.job >= 0) & ~solved[job_safe]
    has_top = has_top & job_live
    count = jnp.where(job_live, count, 0)
    base = state.base
    n_steals = jnp.int32(0)
    job_arr = state.job
    if config.steal:
        thief_ok = None
        if config.protect_home_lanes and config.steal_gang > 0:
            thief_ok = (lane_idx % config.steal_gang) != 0
        for _ in range(max(1, config.steal_rounds)):
            top, has_top, base, count, job_arr, k = _steal(
                top, has_top, stack, base, count, job_arr, job_live,
                gang=config.steal_gang, thief_ok=thief_ok,
            )
            job_live = (job_arr >= 0) & ~solved[jnp.clip(job_arr, 0, n_jobs - 1)]
            n_steals = n_steals + k

    return Frontier(
        top=top,
        has_top=has_top,
        stack=stack,
        base=base,
        count=count,
        job=job_arr,
        solved=solved,
        solution=solution,
        overflowed=overflowed,
        nodes=nodes,
        sol_count=sol_count,
        steps=state.steps + 1,
        sweeps=state.sweeps + sweeps,
        expansions=state.expansions + jnp.sum(undecided).astype(jnp.int32),
        steals=state.steals + n_steals,
        lane_rounds=state.lane_rounds + live.astype(jnp.int32),
    )


def frontier_live(state: Frontier) -> jax.Array:
    """bool[L]: lanes still holding unexplored work for an unsolved job."""
    n_jobs = state.solved.shape[0]
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    return state.has_top & (state.job >= 0) & ~state.solved[job_safe]


# -- packed chunk status -------------------------------------------------------
#
# The serving hot loops (engine static flights, resident scheduler, bulk
# rungs) used to learn a chunk's outcome through a full-state
# ``block_until_ready`` plus five-plus separate device->host value fetches —
# each one a ~74-122 ms RPC through a tunneled device (BENCHMARKS.md
# "Measured link"), and a host-stalls-device serialization even on attached
# hosts.  Everything those fetches carried is tiny, so it is computed
# IN-GRAPH at the end of each advance dispatch and packed into one small
# int32 vector fetched once per chunk:
#
#   [0]               absolute ``steps`` (the authoritative counter — hosts
#                     track deltas instead of fetching the scalar)
#   [1]               sum over lanes of the chunk's ``lane_rounds`` delta
#                     (mean lane-occupancy fraction = [1] / (L * steps_delta))
#   [2:12]            10-bin decile histogram of per-lane live-rounds /
#                     rounds-advanced for the chunk (the /metrics
#                     ``fused_lane_occupancy`` data, previously a host-side
#                     bincount over two full lane_rounds fetches)
#   [12 : 12+w]       per-job ``solved`` bitmask, 32 jobs per word
#   [12+w : 12+2w]    per-job has-work bitmask (any live lane owned by the
#                     job); ``any_live`` of the whole frontier is "any bit
#                     set" — the resident scheduler's poll and the static
#                     loop's liveness check are the same word
#
# where ``w = ceil(n_jobs / 32)``.  ``status_len(n_jobs)`` is the vector
# length; :func:`unpack_status` is the host-side (numpy) inverse.

STATUS_STEPS = 0
STATUS_LIVE_SUM = 1
STATUS_HIST = 2  # .. STATUS_BITS: 10 decile bins
STATUS_BITS = 12


def status_len(n_jobs: int) -> int:
    return STATUS_BITS + 2 * ((n_jobs + 31) // 32)


def _pack_bits(bits: jax.Array) -> jax.Array:
    """bool[J] -> int32[ceil(J/32)], bit b of word w = job 32*w + b."""
    j = bits.shape[0]
    w = (j + 31) // 32
    padded = jnp.pad(bits, (0, w * 32 - j))
    words = jnp.sum(
        padded.reshape(w, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32),
        axis=1,
        dtype=jnp.uint32,
    )
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def chunk_status(
    prev_steps: jax.Array, prev_lane_rounds: jax.Array, new: Frontier
) -> jax.Array:
    """int32[status_len(J)]: the packed per-chunk status word (see above).

    ``prev_steps`` / ``prev_lane_rounds`` are the pre-advance values of the
    same frontier, so the occupancy delta histogram needs no host-side
    before/after bookkeeping — the advance program computes it from its own
    input and output.
    """
    n_jobs = new.solved.shape[0]
    live = frontier_live(new)
    job_safe = jnp.clip(new.job, 0, n_jobs - 1)
    has_work = jnp.zeros(n_jobs, bool).at[job_safe].max(live, mode="drop")
    delta = new.lane_rounds - prev_lane_rounds
    # steps_delta == 0 (budget already exhausted): the guarded divisor keeps
    # the bins well-defined; hosts ignore the histogram for empty chunks.
    steps_delta = jnp.maximum(new.steps - prev_steps, 1)
    bucket = jnp.clip((delta * 10) // steps_delta, 0, 9)
    hist = jnp.zeros(10, jnp.int32).at[bucket].add(1)
    return jnp.concatenate(
        [
            jnp.stack([new.steps, jnp.sum(delta, dtype=jnp.int32)]),
            hist,
            _pack_bits(new.solved),
            _pack_bits(has_work),
        ]
    )


def unpack_status(status, n_jobs: int) -> dict:
    """Host-side inverse of :func:`chunk_status` (pure numpy, no device
    work): ``{steps, live_sum, hist int64[10], solved bool[J],
    has_work bool[J]}``."""
    import numpy as np

    status = np.asarray(status)
    w = (n_jobs + 31) // 32

    def bits(words):
        # int64 sign-extension only touches bits >= 32; bits 0..31 survive.
        return (
            ((words.astype(np.int64)[:, None] >> np.arange(32)) & 1)
            .astype(bool)
            .reshape(-1)[:n_jobs]
        )

    return {
        "steps": int(status[STATUS_STEPS]),
        "live_sum": int(status[STATUS_LIVE_SUM]),
        "hist": status[STATUS_HIST:STATUS_BITS].astype(np.int64),
        "solved": bits(status[STATUS_BITS : STATUS_BITS + w]),
        "has_work": bits(status[STATUS_BITS + w : STATUS_BITS + 2 * w]),
    }


def run_frontier(
    state: Frontier,
    problem: CSProblem,
    config: SolverConfig,
    step_limit: jax.Array | None = None,
) -> Frontier:
    """Drive steps until every job resolves (solved or search space exhausted).

    ``step_limit`` is a *dynamic* cap (defaults to ``config.max_steps``): the
    checkpointing driver advances the same compiled program in bounded chunks
    by passing successive limits, without a recompile per chunk.
    """
    limit = jnp.int32(config.max_steps) if step_limit is None else step_limit
    limit = jnp.minimum(limit, jnp.int32(config.max_steps))

    def cond(st: Frontier):
        return jnp.any(frontier_live(st)) & (st.steps < limit)

    return jax.lax.while_loop(
        cond, lambda s: frontier_step(s, problem, config), state
    )


# -- latency-mode megastep -----------------------------------------------------
#
# The serving chunk loop (one advance dispatch + one status fetch per
# ``chunk_steps`` rounds) pays the host round-trip once per CHUNK — on a
# tunneled device that RPC floor is ~99% of interactive latency for a hard
# board (BENCH_r05: 1.06 ms device-only vs 79.4 ms end-to-end).  The
# megastep moves the chunk loop itself in-graph: ONE donated dispatch runs
# up to ``max_chunks`` chunks inside an outer ``lax.while_loop``, recomputes
# the round-8 packed status word after each inner chunk, and EARLY-EXITS the
# moment the status' has-work words go all-zero (every job solved or
# exhausted).  The host then syncs once per *flight* instead of once per
# chunk — the latency-mode serving path (``serving/megastep.py``).


def run_frontier_megastep(
    state: Frontier,
    problem: CSProblem,
    config: SolverConfig,
    chunk_steps: jax.Array,
    max_chunks: jax.Array,
):
    """In-graph chunk loop: advance until all-solved/all-dead or the chunk
    budget runs out, re-deriving the packed status per inner chunk.

    Returns ``(new_state, status, chunks)`` where ``status`` is the packed
    word of :func:`chunk_status` computed against the FLIGHT-START baselines
    (``state.steps`` / ``state.lane_rounds`` at entry), so the single fetched
    word reports the whole flight: absolute steps, cumulative live-rounds
    delta, the flight-scope occupancy histogram, and the final solved /
    has-work bitmasks.  ``chunks`` is the early-exit round count — how many
    inner chunks actually ran (>= 1; the first chunk is unconditional).

    Both ``chunk_steps`` and ``max_chunks`` are dynamic scalars: one
    compiled program serves every flight shape.  The loop also stops at
    ``config.max_steps`` exactly like the chunked path, so a budget
    exhaustion surfaces as has-work-still-set in the returned status.
    """
    n_jobs = state.solved.shape[0]
    w = (n_jobs + 31) // 32
    steps0 = state.steps
    rounds0 = state.lane_rounds
    chunk = jnp.int32(chunk_steps)
    budget = jnp.int32(config.max_steps)

    def one_chunk(st: Frontier):
        new = run_frontier(st, problem, config, step_limit=st.steps + chunk)
        return new, chunk_status(steps0, rounds0, new)

    def cond(carry):
        st, status, chunks = carry
        # Early exit: any nonzero has-work word means some job still holds
        # live lanes (the same bits the chunked loops fetch per chunk).
        alive = jnp.any(status[STATUS_BITS + w : STATUS_BITS + 2 * w] != 0)
        return alive & (chunks < jnp.int32(max_chunks)) & (st.steps < budget)

    def body(carry):
        st, _, chunks = carry
        new, status = one_chunk(st)
        return new, status, chunks + jnp.int32(1)

    st, status = one_chunk(state)
    st, status, chunks = jax.lax.while_loop(
        cond, body, (st, status, jnp.int32(1))
    )
    return st, status, chunks


@functools.partial(
    jax.jit, static_argnames=("geom", "config"), donate_argnums=(0,)
)
def advance_megastep(
    state: Frontier, chunk_steps: jax.Array, max_chunks: jax.Array, geom, config: SolverConfig
):
    """One latency-mode flight as ONE donated dispatch (the serving entry
    point of :func:`run_frontier_megastep`; ``serving/megastep.py`` drives
    it and pairs it with a single verdict fetch).  ``state`` is donated
    exactly like ``utils.checkpoint.advance_frontier_status`` — callers
    must rebind and never touch the old reference again."""
    from distributed_sudoku_solver_tpu.ops.solve import sudoku_csp

    return run_frontier_megastep(
        state, sudoku_csp(geom, config), config, chunk_steps, max_chunks
    )
