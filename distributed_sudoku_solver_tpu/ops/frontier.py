"""The lane-stack frontier: the reference's worker ring as one device tensor.

TPU-native re-design of the reference's entire L2 scheduler (SURVEY.md §1,
§2.1 #6/#7).  The mapping is one-to-one:

* **lane = worker node.**  Each of L lanes owns a private DFS stack
  ``stack[L, S, n, n]`` of partial boards (candidate bitmasks) with stack
  pointer ``sp[L]`` — the reference's per-node recursion stack and
  ``task_queue`` unified into one tensor.
* **branch = the reference's guess loop.**  Each step, every live lane pops
  its top board, propagates it to a fixpoint, and (if undecided) splits one
  cell binarily: the *lowest candidate digit* (pushed on top, explored next —
  exact ascending-digit DFS order, ``/root/reference/DHT_Node.py:522``)
  vs. *the rest* (left underneath).  All lanes branch in lockstep: one
  ``lax.while_loop`` iteration advances every lane.
* **work stealing = the NEEDWORK handshake, tensorized.**  Idle lanes
  (empty stack, or their job already solved) are matched each step with the
  richest lanes, and steal the *bottom* stack entry — the shallowest node,
  i.e. the largest unexplored subtree, the moral equivalent of the
  reference's ``split_array_in_middle`` shipping half the guess range
  (``/root/reference/DHT_Node.py:499-510``, ``utils.py:1-9``).  No
  messages, no idle chip while any lane has depth >= 2.
* **speculative cancellation = the SOLUTION_FOUND purge, in-graph.**  Lanes
  whose job is solved are cleared by a mask (``/root/reference/
  DHT_Node.py:358-387``) and immediately become thieves for other jobs.

Per-lane LIFO makes progress unconditional (pop 1, push <= 2 per step), so
unlike a flat expansion pool the frontier cannot deadlock at capacity; a
stack that would overflow S drops its *rest* sibling and records the loss
per job (``overflowed``), downgrading a would-be "unsat" verdict to
"unknown" rather than ever reporting wrongly.

The engine is generic over the problem family (``ops/csp.py``): states are
opaque ``uint32[h, w]`` tensors, and propagation / classification /
branching are the problem's three kernels.  Sudoku lives in
``models/sudoku.py``; generalized exact cover in ``models/cover.py``.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.ops.csp import CSProblem


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Static solver configuration (hashable: becomes part of the jit key)."""

    lanes: int = 0  # total lanes; 0 = auto: max(n_jobs, min_lanes)
    min_lanes: int = 64  # speculation width floor for small job counts
    stack_slots: int = 64  # DFS stack depth per lane
    max_steps: int = 100_000  # branch rounds before giving up
    max_sweeps: int = 64  # propagation sweeps per fixpoint (Sudoku adapter)
    branch: str = "minrem"  # Sudoku branch rule: 'minrem' | 'first' (ref
    #   order, bit-exactness tests) | 'mixed' (per-state hash-diversified)
    rules: str = "basic"  # propagation strength: 'basic' (elimination +
    #   hidden singles) | 'extended' (+ box-line reductions, all backends)
    propagator: str = "xla"  # 'xla' | 'pallas' (VMEM kernel; batch solves only
    #   — the board-sharded path has its own collective sweep and rejects it)
    steal: bool = True  # receiver-initiated work stealing between lanes
    steal_rounds: int = 1  # pairings per step; >1 ramps idle gangs up faster
    #   (a donor serves one thief per round, so a lone rich lane feeds at
    #   most `steal_rounds` thieves per step — matters for wide-lane few-job
    #   gang search, where 1 round means linear rather than quick fan-out)
    ring_steal_k: int = 8  # max boards shipped per step per chip pair (sharded)

    def resolve_lanes(self, n_jobs: int) -> int:
        lanes = self.lanes if self.lanes > 0 else max(n_jobs, self.min_lanes)
        if lanes < n_jobs:
            raise ValueError(f"lanes={lanes} < n_jobs={n_jobs}")
        return lanes


class Frontier(NamedTuple):
    """Loop-carried device state for one solve call."""

    stack: jax.Array  # uint32[L, S, h, w] problem states
    sp: jax.Array  # int32[L] stack pointer (0 = empty lane)
    job: jax.Array  # int32[L] owning job; -1 = unassigned
    solved: jax.Array  # bool[J]
    solution: jax.Array  # uint32[J, h, w] (solved problem state)
    overflowed: jax.Array  # bool[J] some subtree was dropped (stack full)
    nodes: jax.Array  # int32[J] branch nodes expanded per job
    steps: jax.Array  # int32 scalar
    sweeps: jax.Array  # int32 scalar total propagation sweeps
    expansions: jax.Array  # int32 scalar total branch expansions
    steals: jax.Array  # int32 scalar total bottom-steals


def init_frontier(states0: jax.Array, config: SolverConfig) -> Frontier:
    """Seed each job's root state into its own lane (the root TASK self-send,
    ``/root/reference/DHT_Node.py:551``); extra lanes start as thieves.

    Seed lanes are *strided* across the lane axis — floor(j*L/J), strictly
    increasing since L >= J — so that when lanes are sharded over a mesh
    every chip starts with its share of root jobs instead of chip 0 holding
    everything.
    """
    n_jobs, h, w = states0.shape
    n_lanes = config.resolve_lanes(n_jobs)
    s = config.stack_slots
    seed_lane = (jnp.arange(n_jobs, dtype=jnp.int32) * n_lanes) // n_jobs
    stack = jnp.zeros((n_lanes, s, h, w), jnp.uint32)
    stack = stack.at[seed_lane, 0].set(states0.astype(jnp.uint32))
    sp = jnp.zeros(n_lanes, jnp.int32).at[seed_lane].set(1)
    job = jnp.full(n_lanes, -1, jnp.int32).at[seed_lane].set(
        jnp.arange(n_jobs, dtype=jnp.int32)
    )
    return Frontier(
        stack=stack,
        sp=sp,
        job=job,
        solved=jnp.zeros(n_jobs, bool),
        solution=jnp.zeros((n_jobs, h, w), jnp.uint32),
        overflowed=jnp.zeros(n_jobs, bool),
        nodes=jnp.zeros(n_jobs, jnp.int32),
        steps=jnp.int32(0),
        sweeps=jnp.int32(0),
        expansions=jnp.int32(0),
        steals=jnp.int32(0),
    )


def _steal(
    stack: jax.Array, sp: jax.Array, job: jax.Array, job_live: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Match idle lanes with the richest lanes; move each donor's *bottom* row.

    Receiver-initiated like the reference's NEEDWORK (``/root/reference/
    DHT_Node.py:246-254``); donors are served richest-first so the deepest
    backlogs drain first, and each donor serves at most one thief per step.
    """
    n_lanes = sp.shape[0]
    lane_idx = jnp.arange(n_lanes, dtype=jnp.int32)

    idle = sp == 0
    donor = (sp >= 2) & job_live
    # Thieves in lane order; donors richest-first.  argsort is a permutation,
    # so donors are distinct; pair k-th thief with k-th donor.
    thief_order = jnp.argsort(jnp.where(idle, lane_idx, n_lanes + lane_idx))
    donor_order = jnp.argsort(jnp.where(donor, -sp, jnp.int32(1)), stable=True)
    n_pairs = jnp.minimum(jnp.sum(idle), jnp.sum(donor)).astype(jnp.int32)
    pair = lane_idx < n_pairs

    thief_lane = jnp.where(pair, thief_order, n_lanes)  # OOB -> dropped
    donor_lane = jnp.where(pair, donor_order, n_lanes)

    stolen = stack[jnp.clip(donor_lane, 0, n_lanes - 1), 0]
    stolen_job = job[jnp.clip(donor_lane, 0, n_lanes - 1)]

    # Thieves: bottom row becomes their whole stack.
    stack = stack.at[thief_lane, 0].set(stolen, mode="drop")
    sp = sp.at[thief_lane].set(jnp.where(pair, 1, 0), mode="drop")
    job = job.at[thief_lane].set(stolen_job, mode="drop")

    # Donors: shift their stack down one slot.
    donor_sel = jnp.zeros(n_lanes, bool).at[donor_lane].set(pair, mode="drop")
    shifted = jnp.concatenate([stack[:, 1:], stack[:, -1:]], axis=1)
    stack = jnp.where(donor_sel[:, None, None, None], shifted, stack)
    sp = jnp.where(donor_sel, sp - 1, sp)
    return stack, sp, job, n_pairs


def frontier_step(
    state: Frontier, problem: CSProblem, config: SolverConfig
) -> Frontier:
    """One lockstep round: pop+propagate tops -> harvest/cancel -> branch -> steal."""
    n_lanes, s = state.stack.shape[:2]
    n_jobs = state.solved.shape[0]
    lane_idx = jnp.arange(n_lanes, dtype=jnp.int32)

    # Lanes whose job resolved are cleared (the SOLUTION_FOUND purge).
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    job_live = (state.job >= 0) & ~state.solved[job_safe]
    sp = jnp.where(job_live, state.sp, 0)
    live = sp > 0

    # --- L0: propagate every live top to a fixpoint -------------------------
    top_idx = jnp.clip(sp - 1, 0, s - 1)
    tops = state.stack[lane_idx, top_idx]
    tops = jnp.where(live[:, None, None], tops, 0)  # idle tops are inert zeros
    tops, sweeps = problem.propagate(tops)
    top_solved, top_contra = problem.status(tops)
    solved_tops = top_solved & live
    contra_tops = top_contra & live
    undecided = live & ~solved_tops & ~contra_tops

    # --- harvest solutions: deterministic lowest-lane winner per job --------
    scatter_job = jnp.where(solved_tops, state.job, n_jobs)
    first = jnp.full(n_jobs, n_lanes, jnp.int32).at[scatter_job].min(
        jnp.where(solved_tops, lane_idx, n_lanes), mode="drop"
    )
    newly = (first < n_lanes) & ~state.solved
    sol_rows = tops[jnp.clip(first, 0, n_lanes - 1)]
    solution = jnp.where(newly[:, None, None], sol_rows, state.solution)
    solved = state.solved | newly

    # --- branch: replace parent with `rest`, push `guess` on top ------------
    guess, rest = problem.branch(tops)

    full_stack = sp >= s
    push = undecided & ~full_stack
    # On overflow: keep DFS-ing the guess in place; the rest-subtree is lost.
    in_place = jnp.where(
        undecided[:, None, None], jnp.where(push[:, None, None], rest, guess), tops
    )
    slot = jnp.arange(s, dtype=jnp.int32)[None, :]
    at_top = slot == top_idx[:, None]
    at_push = slot == sp[:, None]
    stack = jnp.where(
        (undecided[:, None] & at_top)[:, :, None, None], in_place[:, None], state.stack
    )
    stack = jnp.where(
        (push[:, None] & at_push)[:, :, None, None], guess[:, None], stack
    )
    sp = sp + push.astype(jnp.int32) - (solved_tops | contra_tops).astype(jnp.int32)

    overflow_now = undecided & full_stack
    overflowed = state.overflowed.at[
        jnp.where(overflow_now, state.job, n_jobs)
    ].set(True, mode="drop")

    nodes = state.nodes.at[jnp.where(undecided, state.job, n_jobs)].add(
        jnp.where(undecided, jnp.int32(1), jnp.int32(0)), mode="drop"
    )

    # --- work stealing ------------------------------------------------------
    job_live = (state.job >= 0) & ~solved[job_safe]
    sp = jnp.where(job_live, sp, 0)
    n_steals = jnp.int32(0)
    job_arr = state.job
    if config.steal:
        for _ in range(max(1, config.steal_rounds)):
            stack, sp, job_arr, k = _steal(stack, sp, job_arr, job_live)
            job_live = (job_arr >= 0) & ~solved[jnp.clip(job_arr, 0, n_jobs - 1)]
            n_steals = n_steals + k

    return Frontier(
        stack=stack,
        sp=sp,
        job=job_arr,
        solved=solved,
        solution=solution,
        overflowed=overflowed,
        nodes=nodes,
        steps=state.steps + 1,
        sweeps=state.sweeps + sweeps,
        expansions=state.expansions + jnp.sum(undecided).astype(jnp.int32),
        steals=state.steals + n_steals,
    )


def frontier_live(state: Frontier) -> jax.Array:
    """bool[L]: lanes still holding unexplored work for an unsolved job."""
    n_jobs = state.solved.shape[0]
    job_safe = jnp.clip(state.job, 0, n_jobs - 1)
    return (state.sp > 0) & (state.job >= 0) & ~state.solved[job_safe]


def run_frontier(
    state: Frontier,
    problem: CSProblem,
    config: SolverConfig,
    step_limit: jax.Array | None = None,
) -> Frontier:
    """Drive steps until every job resolves (solved or search space exhausted).

    ``step_limit`` is a *dynamic* cap (defaults to ``config.max_steps``): the
    checkpointing driver advances the same compiled program in bounded chunks
    by passing successive limits, without a recompile per chunk.
    """
    limit = jnp.int32(config.max_steps) if step_limit is None else step_limit
    limit = jnp.minimum(limit, jnp.int32(config.max_steps))

    def cond(st: Frontier):
        return jnp.any(frontier_live(st)) & (st.steps < limit)

    return jax.lax.while_loop(
        cond, lambda s: frontier_step(s, problem, config), state
    )
