"""Pallas TPU kernel: constraint propagation to a fixpoint, resident in VMEM.

The hot op of the whole framework is ``ops.propagate.propagate`` — the
elimination + hidden-singles fixpoint that replaces the reference's per-guess
``is_valid`` scan (``/root/reference/utils.py:27-55``).  The XLA path runs it
as a ``lax.while_loop`` whose every sweep is a separate pass over the batch
tensor with a batch-global convergence check between sweeps.

This kernel moves the whole fixpoint on-chip:

* the batch is tiled over a 1-D grid; each program DMAs its ``[tile, n, n]``
  block of candidate bitmasks into **VMEM** once,
* the full sweep loop runs against that VMEM block,
* convergence is *per-tile*: a tile of easy boards stops after 2-3 sweeps
  instead of every board paying for the slowest board in the whole batch,
* only the fixpoint is written back — one HBM round-trip instead of one per
  sweep.

Mosaic (the Pallas TPU compiler) rejects the lane/sublane-mixing reshapes the
XLA path uses for its box-unit view (``ops.bitmask.to_boxes``), strided
sublane slices, and unsigned-integer ``sum`` reductions — all verified
empirically on TPU v5.  The sweep here is therefore re-derived from scratch
on Mosaic's supported set: static unit-width slices, ``concat``, bitwise ops,
``population_count``, and balanced fold trees.  The boolean algebra is
identical (OR / once-twice reductions are associative and exact), so the
kernel is bit-identical to ``ops.propagate.propagate_sweep`` — pinned by
``tests/test_pallas.py`` on random and corpus boards.

Used by ``models/sudoku.py`` when ``SudokuCSP.propagator == 'pallas'``
(plumbed from ``SolverConfig.propagator``).  On non-TPU backends the kernel
runs in Pallas interpreter mode, so the test suite exercises the same kernel
code path on CPU.
"""

from __future__ import annotations

import functools
import operator

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.propagate import RULE_TIERS

try:  # pltpu imports on all jaxlib builds we target; guard for exotic ones
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover - non-TPU jaxlib
    pltpu = None
    _VMEM = _SMEM = None


# --------------------------------------------------------------------------
# Mosaic-friendly unit reductions: static slices + balanced folds only.
# --------------------------------------------------------------------------


def _fold(vals: list, comb):
    """Balanced fold tree (log depth, association-order-independent math)."""
    while len(vals) > 1:
        nxt = [comb(vals[i], vals[i + 1]) for i in range(0, len(vals) - 1, 2)]
        if len(vals) % 2:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def _slice1(x, axis: int, i: int):
    """Width-1 static slice along ``axis``; maps over (once, twice) pairs."""

    def f(v: jax.Array) -> jax.Array:
        idx = [slice(None)] * v.ndim
        idx[axis] = slice(i, i + 1)
        return v[tuple(idx)]

    return jax.tree.map(f, x)


def _axis_len(x, axis: int) -> int:
    return jax.tree.leaves(x)[0].shape[axis]


def _concat(parts: list, axis: int):
    if len(parts) == 1:
        return parts[0]
    return jax.tree.map(lambda *vs: jnp.concatenate(vs, axis=axis), *parts)


def _group_reduce(x, axis: int, group: int, comb):
    """Reduce contiguous groups of ``group`` elements along ``axis`` to size 1
    each; result keeps the axis with length ``n // group``."""
    n = _axis_len(x, axis)
    groups = [
        _fold([_slice1(x, axis, g * group + k) for k in range(group)], comb)
        for g in range(n // group)
    ]
    return _concat(groups, axis)


def _expand(x, axis: int, times: int):
    """Repeat each element ``times`` times along ``axis`` (inverse of
    ``_group_reduce``'s shape), built from slices + concat only."""
    parts = [
        _slice1(x, axis, i) for i in range(_axis_len(x, axis)) for _ in range(times)
    ]
    return _concat(parts, axis)


_OR = operator.or_


def _ot_comb(a, b):
    """(once, twice) pair semiring: bits seen >=1 / >=2 times."""
    return a[0] | b[0], a[1] | b[1] | (a[0] & b[0])


def _ot_lift(x):
    return x, jnp.zeros_like(x)


def _unit_maps(x: jax.Array, geom: Geometry, comb, lift, row_ax: int, col_ax: int):
    """Per-cell unit reduction for rows / cols / boxes, broadcast back to
    ``x.shape``.  Yields one reduced value per unit type, in the same order
    as ``ops.propagate._unit_views``.  ``row_ax``/``col_ax`` name the board
    axes, so the same code serves both the XLA layout ``[..., n, n]`` and the
    kernel's boards-last layout ``[n, n, T]``."""
    n, bh, bw = geom.n, geom.box_h, geom.box_w
    # rows: reduce the lane axis
    row = _group_reduce(lift(x), col_ax, n, comb)
    yield jax.tree.map(lambda v: jnp.broadcast_to(v, x.shape), row)
    # cols: reduce the sublane axis
    col = _group_reduce(lift(x), row_ax, n, comb)
    yield jax.tree.map(lambda v: jnp.broadcast_to(v, x.shape), col)
    # boxes: two-stage group reduce, then expand both axes back
    q = _group_reduce(_group_reduce(lift(x), row_ax, bh, comb), col_ax, bw, comb)
    yield jax.tree.map(lambda v: _expand(_expand(v, row_ax, bh), col_ax, bw), q)


def sweep_mosaic(
    cand: jax.Array,
    geom: Geometry,
    row_ax: int | None = None,
    col_ax: int | None = None,
) -> jax.Array:
    """One propagation sweep, bit-identical to ``propagate_sweep`` but built
    exclusively from Mosaic-supported ops (see module docstring).

    The board axes default to the last two (the XLA layout); the kernel calls
    it with ``row_ax=0, col_ax=1`` on boards-last ``[n, n, T]`` tiles so the
    batch rides the 128-wide lane axis — with boards in the *leading* dims
    Mosaic unrolls one op per board and compilation explodes (observed: a
    ``[256, 9, 9]`` tile takes >6 min to compile; ``[9, 9, 256]`` is sub-s).
    """
    if row_ax is None:
        row_ax, col_ax = cand.ndim - 2, cand.ndim - 1
    single = jax.lax.population_count(cand) == 1
    decided = jnp.where(single, cand, jnp.uint32(0))

    seen = _fold(
        list(_unit_maps(decided, geom, _OR, lambda v: v, row_ax, col_ax)), _OR
    )
    cand = jnp.where(single, cand, cand & ~seen)

    forced = jnp.zeros_like(cand)
    for once, twice in _unit_maps(cand, geom, _ot_comb, _ot_lift, row_ax, col_ax):
        forced = forced | (cand & (once & ~twice))
    cand = jnp.where(~single & (forced != 0), forced, cand)
    return cand


# --------------------------------------------------------------------------
# The fixpoint kernel.
# --------------------------------------------------------------------------


def box_line_mosaic(
    cand: jax.Array, geom: Geometry, row_ax: int, col_ax: int
) -> jax.Array:
    """Box-line reductions (pointing + claiming) from Mosaic-supported ops.

    Same boolean algebra as ``ops.propagate.box_line_sweep`` (bit-equality
    pinned by tests) but built purely from width-1 static slices, concat,
    and fold trees — no reshapes — so it runs inside Pallas kernels and the
    boards-last XLA path.  One call covers the *rows* direction; callers
    invoke it twice with (row_ax, col_ax) and box dims swapped.
    """
    single = jax.lax.population_count(cand) == 1
    out = _box_line_dir(cand, geom.box_h, geom.box_w, row_ax, col_ax)
    out = _box_line_dir(out, geom.box_w, geom.box_h, col_ax, row_ax)
    return jnp.where(single, cand, out)


def _box_line_dir(
    x: jax.Array, bh: int, bw: int, row_ax: int, col_ax: int
) -> jax.Array:
    n = _axis_len(x, row_ax)
    nh = n // bw
    # seg: digit bits present per (row, box-of-that-row) segment.
    seg = _group_reduce(x, col_ax, bw, _OR)

    # pointing: bits confined to one row of their (band, box) segment stack.
    p_once, p_twice = _group_reduce(_ot_lift(seg), row_ax, bh, _ot_comb)
    confined = _expand(p_once & ~p_twice, row_ax, bh)
    point = seg & confined
    # claiming: bits confined within the row to one box.
    c_once, c_twice = _group_reduce(_ot_lift(seg), col_ax, nh, _ot_comb)
    claim = seg & jnp.broadcast_to(c_once & ~c_twice, seg.shape)

    # point eliminates from the rest of the row (other boxes): OR over h'!=h.
    cols = [_slice1(point, col_ax, h) for h in range(nh)]
    point_other = _concat(
        [_fold([cols[h2] for h2 in range(nh) if h2 != h], _OR) for h in range(nh)],
        col_ax,
    ) if nh > 1 else jax.tree.map(jnp.zeros_like, seg)
    # claim eliminates from the other rows of the box (within the band).
    rows = [_slice1(claim, row_ax, r) for r in range(n)]
    claim_other = _concat(
        [
            _fold(
                [rows[(r // bh) * bh + k] for k in range(bh) if k != r % bh], _OR
            )
            if bh > 1
            else jax.tree.map(jnp.zeros_like, rows[r])
            for r in range(n)
        ],
        row_ax,
    )

    kill = _expand(point_other | claim_other, col_ax, bw)
    return x & ~kill


def naked_subsets_mosaic(
    cand: jax.Array, geom: Geometry, row_ax: int, col_ax: int
) -> jax.Array:
    """Naked-subset eliminations from Mosaic-supported ops.

    Same boolean algebra as ``ops.propagate.naked_subsets_sweep`` (the three
    unit kills are computed from the same input and OR-combined, then applied
    under the decided-cell guard), but the O(C^2) pairwise subset test is
    expressed as C *probes*: one width-1 slice broadcast against the whole
    block per probe, so no reshapes and no [C, C] intermediates — the same
    slice-tree style as :func:`_box_line_dir`.
    """
    single = jax.lax.population_count(cand) == 1
    kill = _subset_kill_line(cand, col_ax)  # row units: cells vary along cols
    kill = kill | _subset_kill_line(cand, row_ax)  # column units
    kill = kill | _subset_kill_box(cand, geom, row_ax, col_ax)
    return jnp.where(single, cand, cand & ~kill)


def _subset_kill_line(x: jax.Array, axis: int) -> jax.Array:
    """Kill mask of the naked-subset rule for the line units along ``axis``."""
    n = _axis_len(x, axis)
    nz = x != jnp.uint32(0)
    kill = jnp.zeros_like(x)
    for i in range(n):
        m = jnp.broadcast_to(_slice1(x, axis, i), x.shape)
        sub = ((x & ~m) == 0) & nz
        cnt = jnp.broadcast_to(
            _group_reduce(sub.astype(jnp.int32), axis, n, operator.add), x.shape
        )
        k = jax.lax.population_count(m).astype(jnp.int32)
        confined = (m != jnp.uint32(0)) & (cnt >= k)
        hit = confined & (~sub | (cnt > k))
        kill = kill | jnp.where(hit, m, jnp.uint32(0))
    return kill


def _axis_indicator(shape, axis: int, b: int):
    """Bool masks [r]: index-along-axis % b == r.  Built from an in-graph
    ``broadcasted_iota`` (not a host constant): ``pallas_call`` rejects
    kernels that capture constants, and Mosaic supports >=2-D iota."""
    idx = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis)
    return [(idx % b) == r for r in range(b)]


def _subset_kill_box(
    x: jax.Array, geom: Geometry, row_ax: int, col_ax: int
) -> jax.Array:
    """Kill mask of the naked-subset rule for the box units.

    Box probes: for each in-box offset (r, c), select that cell of *every*
    box at once (constant indicator masks — no strided slices, which Mosaic
    rejects), box-OR-reduce + expand to broadcast the probe's mask over its
    box, and run the same confined/overfull algebra as the line units.
    """
    bh, bw = geom.box_h, geom.box_w
    nz = x != jnp.uint32(0)
    kill = jnp.zeros_like(x)
    rsel = _axis_indicator(x.shape, row_ax, bh)
    csel = _axis_indicator(x.shape, col_ax, bw)

    def box_broadcast(v, comb):
        red = _group_reduce(_group_reduce(v, row_ax, bh, comb), col_ax, bw, comb)
        return _expand(_expand(red, row_ax, bh), col_ax, bw)

    for r in range(bh):
        for c in range(bw):
            sel = jnp.where(rsel[r] & csel[c], x, jnp.uint32(0))
            m = box_broadcast(sel, _OR)
            sub = ((x & ~m) == 0) & nz
            cnt = box_broadcast(sub.astype(jnp.int32), operator.add)
            k = jax.lax.population_count(m).astype(jnp.int32)
            confined = (m != jnp.uint32(0)) & (cnt >= k)
            hit = confined & (~sub | (cnt > k))
            kill = kill | jnp.where(hit, m, jnp.uint32(0))
    return kill


def _fixpoint_boards_last(
    cand_t: jax.Array,
    geom: Geometry,
    max_sweeps: int,
    rules: str = "basic",
    unroll: int = 0,
):
    """Sweep a boards-last ``[n, n, B]`` block to its fixpoint.

    The single definition of the convergence loop shared by the Pallas
    kernel and the plain-XLA slices backend — so the two can never diverge.
    Returns ``(fixpoint, n_sweeps)``.

    ``unroll`` runs that many sweeps as a straight-line prefix BEFORE the
    convergence-checked ``while_loop`` — the fused kernel's fixpoint
    amortization (round 6): after the first frontier round most tiles
    converge in 2-5 sweeps, so the per-sweep loop machinery (the carried
    full-tile yield plus the any-changed reduce) dominates short fixpoints.
    The prefix is *bit-exact*: a sweep of a fixpoint is the identity
    (sweeps are monotone eliminations), so extra prefix sweeps past
    convergence change nothing, and the loop entry condition is seeded
    from the last prefix sweep's delta — a tile already converged inside
    the prefix never enters the loop at all.  ``n_sweeps`` counts executed
    sweeps (prefix included), keeping the cost counter honest.
    """
    unroll = min(unroll, max_sweeps)
    cur, changed = cand_t, jnp.bool_(True)

    def one_sweep(cur):
        nxt = sweep_mosaic(cur, geom, row_ax=0, col_ax=1)
        if rules in ("extended", "subsets"):
            nxt = box_line_mosaic(nxt, geom, row_ax=0, col_ax=1)
        if rules == "subsets":
            nxt = naked_subsets_mosaic(nxt, geom, row_ax=0, col_ax=1)
        return nxt

    for _ in range(unroll):
        prev, cur = cur, one_sweep(cur)
    if unroll:
        changed = jnp.any(cur != prev)

    def cond(state):
        _, changed, sweeps = state
        return changed & (sweeps < max_sweeps)

    def body(state):
        cur, _, sweeps = state
        nxt = one_sweep(cur)
        return nxt, jnp.any(nxt != cur), sweeps + 1

    out, _, sweeps = jax.lax.while_loop(
        cond, body, (cur, changed, jnp.int32(unroll))
    )
    return out, sweeps


def _fixpoint_kernel(
    cand_ref, out_ref, sweeps_ref, *, geom: Geometry, max_sweeps: int, rules: str
):
    """One grid program: sweep its VMEM-resident tile of boards to a fixpoint.

    The tile is boards-last ``[n, n, tile]`` — see :func:`sweep_mosaic`.
    """
    cand, sweeps = _fixpoint_boards_last(cand_ref[...], geom, max_sweeps, rules)
    out_ref[...] = cand
    # The sweep-count buffer is unblocked (every program sees the whole
    # [n_tiles, 1] SMEM array — TPU grids run sequentially) because Mosaic
    # only allows (1, 1) blocks when they equal the full array shape.
    sweeps_ref[pl.program_id(0), 0] = sweeps


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# The scoped-VMEM ceiling every kernel compiles against (_vmem_params
# passes it to Mosaic; pallas_cover's launch-time admission estimate
# compares against the SAME constant, so retuning it cannot silently
# desynchronize the admission check from the compiler limit).
VMEM_LIMIT_BYTES = 100 * 1024 * 1024


def _vmem_params(interp: bool) -> dict:
    """``pallas_call`` kwargs raising the scoped-vmem ceiling on TPU.

    Mosaic's default scoped-VMEM limit is 16 MB — far below v5e's physical
    VMEM — and it, not hardware, set several measured compile walls (the
    cover kernel's multi-block OOM missed it by 396 KB; the Sudoku
    kernel's ``_max_slots`` stack-depth caps were calibrated against it
    in round 4).  Raising the ceiling lets the measured probes find the
    real boundary instead of the default's."""
    if interp:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    return {
        "compiler_params": pltpu.CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES
        )
    }


def propagate_fixpoint_slices(
    cand: jax.Array, geom: Geometry, max_sweeps: int = 64, rules: str = "basic"
) -> tuple[jax.Array, jax.Array]:
    """Boards-last fixpoint in plain XLA (no Pallas): transpose, sweep with
    the slice-tree algebra, transpose back.

    Same math as both other backends; the payoff is layout.  XLA lays out a
    ``[B, n, n]`` batch with the tiny board dims in the tiled (sublane, lane)
    positions — at B=8192 a fixpoint costs ~1.5 s on TPU v5e; boards-last it
    is ~1.5 ms (measured this session).  Used by the frontier engine for
    large lane counts, where it beats the Pallas kernel by skipping the
    per-while-step ``pallas_call`` overhead.
    """
    if rules not in RULE_TIERS:
        raise ValueError(f"unknown rules {rules!r}")
    out_t, sweeps = _fixpoint_boards_last(
        jnp.transpose(cand, (1, 2, 0)), geom, max_sweeps, rules
    )
    return jnp.transpose(out_t, (2, 0, 1)), sweeps


@functools.partial(
    jax.jit, static_argnames=("geom", "max_sweeps", "tile", "interpret", "rules")
)
def propagate_fixpoint_pallas(
    cand: jax.Array,
    geom: Geometry,
    max_sweeps: int = 64,
    tile: int = 256,
    interpret: bool | None = None,
    rules: str = "basic",
) -> tuple[jax.Array, jax.Array]:
    """Drop-in for :func:`ops.propagate.propagate` on a ``[B, n, n]`` batch.

    Returns ``(cand_fixpoint, n_sweeps)`` where ``n_sweeps`` is the max sweep
    count over tiles — the same "rounds until the whole batch stabilized"
    meaning as the XLA path's loop counter.
    """
    if cand.ndim != 3:
        raise ValueError(f"expected [B, n, n], got {cand.shape}")
    if rules not in RULE_TIERS:
        raise ValueError(f"unknown rules {rules!r}")
    b, n, _ = cand.shape
    interp = _interpret_default() if interpret is None else interpret

    tile = min(tile, b)
    pad = (-b) % tile
    if pad:
        # Zero boards (no candidates anywhere) are already at fixpoint, so
        # padding never inflates a tile's sweep count.
        cand = jnp.concatenate([cand, jnp.zeros((pad, n, n), cand.dtype)], axis=0)
    n_tiles = cand.shape[0] // tile

    # Boards-last for the kernel: the batch rides the 128-wide lane axis
    # (see sweep_mosaic on why boards-first is catastrophic for Mosaic).
    cand_t = jnp.transpose(cand, (1, 2, 0))

    kernel = functools.partial(
        _fixpoint_kernel, geom=geom, max_sweeps=max_sweeps, rules=rules
    )
    vmem = dict(memory_space=_VMEM) if (_VMEM is not None and not interp) else {}
    smem = dict(memory_space=_SMEM) if (_SMEM is not None and not interp) else {}
    out_t, sweeps = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((n, n, tile), lambda i: (0, 0, i), **vmem)],
        out_specs=(
            pl.BlockSpec((n, n, tile), lambda i: (0, 0, i), **vmem),
            pl.BlockSpec(**smem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(cand_t.shape, cand.dtype),
            jax.ShapeDtypeStruct((n_tiles, 1), jnp.int32),
        ),
        interpret=interp,
    )(cand_t)
    return jnp.transpose(out_t, (2, 0, 1))[:b], jnp.max(sweeps)
