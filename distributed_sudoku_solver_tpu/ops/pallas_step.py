"""Whole-frontier-step Pallas kernel: pop/propagate/branch/push in VMEM.

ROADMAP #2 (named since round 1, demanded by VERDICT r2 #1): the XLA
composite step pays, per frontier round, a dispatch of ~30 fused XLA ops,
two layout transposes inside the propagation backend, and HBM round trips
between the propagate / classify / branch / push stages.  This module runs
``k_steps`` *whole rounds* for a VMEM-resident tile of lanes inside ONE
``pallas_call``:

* the tile's tops, stacks, and per-lane counters load into VMEM once per
  dispatch instead of once per round;
* the state stays **boards-last** ``[n, n, T]`` across rounds (the layout
  Mosaic vectorizes; the composite path transposes in and out every round);
* the propagation fixpoint converges **per tile per round** — a tile of
  easy lanes stops sweeping while another tile's hard lanes keep going,
  where the composite path sweeps every lane until the *batch-global*
  fixpoint;
* branch, stack push (circular, ``(base+count) % S``), pop, solution
  capture, and overflow accounting are slice-algebra on the same VMEM
  block (``.at[].set`` scatters don't lower in Mosaic — pushes/pops are
  static-S lane-masked concat trees).

What stays OUTSIDE the kernel (XLA, between dispatches): job-level
bookkeeping — first-lane-wins solution harvest, solved-job purge, per-job
node accounting — and cross-lane work stealing.  Both need gather/scatter
by *dynamic job id*, which Mosaic cannot express; batching them at
``k_steps`` granularity changes only reaction latency (a lane may expand
up to ``k_steps`` extra speculative nodes before purge/steal reaches it),
never soundness.  The fused path is therefore a **gated strategy**
(``SolverConfig.step_impl='fused'``) with its own verdict-soundness tests
(``tests/test_fused_step.py``), not a bit-exact re-encoding of the XLA
step — same contract as ``branch_k``.

Enumeration (``SolverConfig.count_all``) rides the kernel since round 4:
in count mode a solved lane pops its next subtree instead of freezing,
and a per-lane solution counter scatter-adds into job counts per dispatch
— measured 3.31x over the composite step with bit-identical exact counts
(BENCHMARKS.md).  Scope: the kernel hardcodes the SUDOKU propagation /
status / branch algebra (the fixpoint, the unit reductions, the digit
branch).  The generalized exact-cover family has its own whole-round
VMEM kernel since round 5 (``ops/pallas_cover.py`` — the packed
row-conflict algebra as MXU matmuls, sharing this module's XLA driver
via the ``rounds_fn`` seam in :func:`_fused_round`), measured 1.5-2.3x
over the composite step on single-block instances (BENCHMARKS.md).

Reference bar: this is the hot loop of ``/root/reference/DHT_Node.py:
474-538`` (recursive guess/validate/backtrack) as one resident TPU kernel.
"""

from __future__ import annotations

import functools
import operator
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
    _OR,
    _VMEM,
    _fixpoint_boards_last,
    _group_reduce,
    _interpret_default,
    _vmem_params,
)

# meta rows (int32[META_ROWS, T]): kernel input state / output state+deltas
_HAS_TOP, _BASE, _COUNT = 0, 1, 2
_IN_ROWS = 3
_SOLVED, _OVERFLOW, _NODES, _SWEEPS, _STEPS = 3, 4, 5, 6, 7
_OUT_ROWS = 8

# Python int (not a jnp scalar): pallas_call rejects captured constants.
_BIG = 2**30


def _max_slots(n: int, whole_array: bool) -> int:
    """Deepest stack the kernel compiles at this geometry (measured, v5e).

    Round-5 re-measurement (``benchmarks/probe_max_slots.py``, every
    geometry 9-16 + 25 probed on hardware — VERDICT r4 #4a retired the
    five guessed caps): the round-4 boundaries were calibrated against
    Mosaic's **default 16 MB scoped-vmem ceiling**, not against hardware
    — ``pallas_propagate._vmem_params`` now raises the ceiling and every
    boundary moved far outward.  The binding constraint is still the
    static-S concat trees' temporaries (S x n^2 x tile), but at the real
    limit:

    * 4x4-13x13: S=128 compiles in BOTH tile modes — the probe's ladder
      max, recorded as the cap (deeper stacks than 128 deferred siblings
      have no measured workload).  Every point probed: 4-13 inclusive,
      incl. rectangular (10, 12) and degenerate 1 x n prime boxes
      (5, 7, 11, 13)
    * 14x14-16x16: whole-array S=128; gridded S=96 ok / S=128 OOM
    * 25x25: **whole-array S=48 / gridded S=24** — the geometry that
      "never fits" in rounds 3-4 now compiles and runs; the r4 caps
      (9x9 gridded 24, 16x16 gridded 12...) were ceiling artifacts
    * compile TIME grows steeply with S x n^2 (9x9 S=128 gridded: 45 s;
      25x25 S=48 whole-array: ~4 min) — admission is about compiling at
      all; serving defaults stay at measured-fast shapes

    Whether deep-S shapes RUN fast is a separate, measured question —
    the slot trees cost O(S) VPU work per round, so e.g. the bulk
    first-pass default stays S=12 and rung/25x25 engines are chosen by
    the A/B rows in BENCHMARKS.md, not by this admission cap.
    """
    if n <= 13:
        return 128
    if n <= 16:
        return 128 if whole_array else 96
    if n <= 25:
        return 48 if whole_array else 24
    return 0  # beyond the probed range: no admission


def fused_tile(n: int, stack_slots: int) -> int:
    """128 if a 128-lane (gridded) tile compiles at this geometry/stack,
    else 0.

    Mosaic requires the block's lane dimension to be a multiple of 128 (or
    equal to the whole array), so 128 is the ONLY viable tile width once
    lanes exceed 128 — there is no "shrink the tile" escape hatch.  See
    :func:`_max_slots` for the measured compile boundaries.
    """
    return 128 if stack_slots <= _max_slots(n, whole_array=False) else 0


def max_fused_lanes(n: int, stack_slots: int) -> int:
    """Widest lane count the fused kernel serves at this geometry/stack.

    Three regimes, from the measured compile boundaries (:func:`_max_slots`):
    unbounded (a 128-lane gridded tile compiles, so any multiple of 128
    does too), 128 (only the whole-array tile fits — e.g. 9x9 at S=32,
    where the gridded cap is S=24 but the whole-array cap is S=48), or 0
    (nothing fits; the caller must fall back to the composite step).  The
    engine uses this to SPLIT an oversized fused flight group into fitting
    flights rather than downgrading work the kernel could serve."""
    if fused_tile(n, stack_slots) > 0:
        return 1 << 30
    if stack_slots <= _max_slots(n, whole_array=True):
        return 128
    return 0


def _bcast_reduce(x: jax.Array, axis: int, comb) -> jax.Array:
    """Reduce ``axis`` to 1, then *materialize* the replication back to the
    input shape with ``_expand`` (a concat of slice copies).

    Deliberately NOT ``jnp.broadcast_to``: Mosaic tracks broadcast
    provenance through elementwise ops, and a ``where`` whose CONDITION
    has broadcast provenance poisons the layout of everything downstream —
    loop-carried state then fails to legalize (``scf.yield``) or trips
    ``array.h`` limit CHECKs (both observed on v5e).  ``_expand`` is the
    sweep kernel's proven box-path idiom and yields natural layouts."""
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import _expand

    r = _group_reduce(x, axis, x.shape[axis], comb)
    return _expand(r, axis, x.shape[axis])


def _full_any_i(x_i: jax.Array) -> jax.Array:
    """int32 0/1 [n, n, T] -> cell-uniform board OR (still int32 0/1).

    The whole status algebra stays in int32: ``_expand``/concat chains over
    vector-i1 make Mosaic emit an invalid i1->i32 vreg bitcast (v5e).
    """
    return _bcast_reduce(_bcast_reduce(x_i, 0, _OR), 1, _OR)


def _full_sum(x: jax.Array) -> jax.Array:
    """int32[n, n, T] -> cell-uniform board sum."""
    return _bcast_reduce(_bcast_reduce(x, 0, operator.add), 1, operator.add)


def _full_min(x: jax.Array) -> jax.Array:
    """int32[n, n, T] -> cell-uniform board minimum."""
    return _bcast_reduce(_bcast_reduce(x, 0, jnp.minimum), 1, jnp.minimum)


def _unit_full(x: jax.Array, geom: Geometry, comb):
    """Unit reductions replicated back over [n, n, T] (rows/cols/boxes) —
    ``_expand``-materialized, never broadcast (see :func:`_bcast_reduce`)."""
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import _expand

    n, bh, bw = geom.n, geom.box_h, geom.box_w
    row = _expand(_group_reduce(x, 1, n, comb), 1, n)
    col = _expand(_group_reduce(x, 0, n, comb), 0, n)
    box = _group_reduce(_group_reduce(x, 0, bh, comb), 1, bw, comb)
    box = _expand(_expand(box, 0, bh), 1, bw)
    return row, col, box


def _unit_full_ot(x: jax.Array, geom: Geometry):
    """(once, twice) unit reductions replicated over [n, n, T].

    The sweep kernel's bits-seen->=1 / >=2 semiring (``_ot_comb``) over the
    three unit views; one fold yields both what a plain OR family gives
    (``once``) and the duplicate evidence (``twice``) — half the
    slice/expand traffic of running an OR family and an int-sum family
    separately, which is what :func:`status_full` used to do."""
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        _expand,
        _ot_comb,
        _ot_lift,
    )

    n, bh, bw = geom.n, geom.box_h, geom.box_w
    # _group_reduce/_expand tree-map over the (once, twice) pair leaves.
    row = _expand(_group_reduce(_ot_lift(x), 1, n, _ot_comb), 1, n)
    col = _expand(_group_reduce(_ot_lift(x), 0, n, _ot_comb), 0, n)
    box = _group_reduce(
        _group_reduce(_ot_lift(x), 0, bh, _ot_comb), 1, bw, _ot_comb
    )
    box = _expand(_expand(box, 0, bh), 1, bw)
    return row, col, box


def status_full(cand: jax.Array, geom: Geometry):
    """Mosaic twin of ``ops.propagate.board_status`` on [n, n, T].

    Same rules (no empty cell, no duplicated decided digit in a unit,
    every digit keeps a home in every unit); returns cell-uniform
    ``(solved, contra)`` as int32 0/1 masks — int32 end to end, see
    :func:`_full_any_i` and :func:`_bcast_reduce` for the two Mosaic
    layout/lowering constraints that shape this code.

    Round-6 restructure (the roofline's classify share): the duplicate
    check rides the once/twice semiring (``twice != 0`` <=> some decided
    singleton appears >= 2x in the unit <=> the old ``sum != or`` test —
    exact, no int casts), and every per-cell badness condition ORs into ONE
    mask before a single board any-reduce, instead of one
    :func:`_full_any_i` (two reduce+expand families) per condition.  Two
    materialized unit families and two board reductions, down from three
    families and eight board reductions — bit-identical verdicts.
    """
    single = jax.lax.population_count(cand) == 1
    decided = jnp.where(single, cand, jnp.uint32(0))
    full = jnp.uint32(geom.full_mask)

    bad_cell = cand == jnp.uint32(0)  # empty cell
    for (_, twice), unit_or in zip(
        _unit_full_ot(decided, geom), _unit_full(cand, geom, _OR)
    ):
        # twice != 0: a decided digit duplicated in the unit; unit_or !=
        # full: a digit with no home left in the unit.
        bad_cell = bad_cell | (twice != jnp.uint32(0)) | (unit_or != full)

    bad = _full_any_i(jnp.where(bad_cell, 1, 0))
    undecided_any = _full_any_i(jnp.where(single, 0, 1))
    contra = bad
    solved = jnp.where((undecided_any == 0) & (bad == 0), 1, 0)
    return solved, contra


def branch_onehot_full(cand: jax.Array, geom: Geometry, rule: str):
    """Mosaic twin of ``SudokuCSP._branch_cell_onehot`` on [n, n, T].

    Identical cell choice: the packed key ``pc * n^2 + cell`` (or ``cell``
    for 'first') is unique per cell, so its board-minimum IS the argmin
    with the same lowest-cell tie-break.  Returns bool[n, n, T].
    """
    n = geom.n
    pc = jax.lax.population_count(cand).astype(jnp.int32)
    und = pc > 1
    cell = (
        jax.lax.broadcasted_iota(jnp.int32, cand.shape, 0) * n
        + jax.lax.broadcasted_iota(jnp.int32, cand.shape, 1)
    )
    minrem_key = jnp.where(und, pc * (n * n) + cell, _BIG)
    first_key = jnp.where(und, cell, _BIG)
    if rule in ("minrem", "minrem-desc"):
        key = minrem_key
    elif rule == "first":
        key = first_key
    else:  # 'mixed': per-state hash picks the heuristic (parity of h)
        h = _full_sum(pc * (cell + 1))
        key = jnp.where((h & 1) == 0, minrem_key, first_key)
    return (key == _full_min(key)) & und


def _lowest_bit(x: jax.Array) -> jax.Array:
    return x & (~x + jnp.uint32(1))


def _highest_bit(x: jax.Array) -> jax.Array:
    for s in (1, 2, 4, 8, 16):
        x = x | (x >> jnp.uint32(s))
    return x ^ (x >> jnp.uint32(1))


def _select_slot(stack: jax.Array, sel_slot: jax.Array, active: jax.Array):
    """Read stack[slot_l, :, :, l] per lane: log-depth multiplexer tree.

    ``sel_slot`` int32[n, n, T] (cell-uniform per-lane slot), ``active``
    bool[n, n, T]; inactive lanes read 0.

    Round-6 rewrite of the roofline's "mostly predication" loss: the old
    form materialized S lane-masked rows (S slot compares + S masking
    ``where``s + an S-1-node OR fold, all on [n, n, T] tiles).  The mux
    tree instead selects pairwise on the BITS of ``sel_slot``: level k
    pairs subtrees whose covered index ranges differ exactly in bit k
    ([2j*2^k, (2j+1)*2^k) vs [(2j+1)*2^k, (2j+2)*2^k)), so one bit test
    per level drives every pair at that level, and an odd tail node —
    covering the aligned range [m*2^k, S) — is chosen at a later level
    exactly when its bit says "upper half", which holds for every index in
    the range.  Correct for any ``sel_slot`` in [0, S) at any S, powers of
    two or not.  Cost: S-1 ``where``s + ceil(log2 S) bit tests vs the old
    ~3S ops — the measured slot-read share of the round phases drops ~3x
    (BENCHMARKS.md "round 6", probed by ``benchmarks/probe_fused_vpu.py``).
    """
    s = stack.shape[0]
    rows = [stack[i] for i in range(s)]
    bit = 1
    while len(rows) > 1:
        take_hi = (sel_slot & bit) != 0
        nxt = [
            jnp.where(take_hi, rows[j + 1], rows[j])
            for j in range(0, len(rows) - 1, 2)
        ]
        if len(rows) % 2:
            nxt.append(rows[-1])
        rows = nxt
        bit <<= 1
    return jnp.where(active, rows[0], jnp.uint32(0))


def _write_slot(
    stack: jax.Array, sel_slot: jax.Array, active: jax.Array, row: jax.Array
) -> jax.Array:
    """Write ``row`` into stack[slot_l, :, :, l] per active lane.

    Static-S concat (``.at[].set`` scatters don't lower in Mosaic).  Every
    slot must be rewritten either way (the block is stored whole), so the
    write stays O(S); the round-6 trim folds ``active`` into the slot key
    ONCE (inactive lanes get key -1, matching no slot) instead of paying a
    separate AND against ``active`` per slot."""
    s = stack.shape[0]
    key = jnp.where(active, sel_slot, -1)
    parts = [
        jnp.where((key == i)[None], row[None], stack[i : i + 1])
        for i in range(s)
    ]
    return jnp.concatenate(parts, axis=0)


def _fused_kernel(
    top_ref,
    stack_ref,
    has_ref,
    base_ref,
    cnt_ref,
    out_top,
    out_stack,
    out_has,
    out_base,
    out_cnt,
    out_solved,
    out_over,
    out_nodes,
    out_solcnt,
    out_live,
    out_sweeps,
    out_steps,
    out_sol,
    *,
    geom: Geometry,
    rules: str,
    branch_rule: str,
    max_sweeps: int,
    k_steps: int,
    count_mode: bool,
    sweep_unroll: int,
):
    """Run up to ``k_steps`` whole frontier rounds on one VMEM lane tile.

    EVERY loop-carried per-lane quantity is a cell-uniform full-board
    tensor [n, n, T] (the same value replicated across all n^2 cells): the
    only layouts Mosaic reliably carries through ``lax.while_loop`` are
    the sweep kernel's full-shape ones — [1, T] / [1, 1, T] lane rows and
    double-reduced aggregates all fail to legalize the loop yield
    (observed on v5e; see :func:`_bcast_reduce`).  The redundancy is free:
    VPU lanes compute the same value n^2 times instead of once, and a tile
    carries ~20 x 83 KB at 9x9.
    """
    top = top_ref[...]
    stack = stack_ref[...]
    shape = top.shape
    s = stack.shape[0]
    # Refs are full-shape cell-uniform [n, n, T] (the XLA driver
    # materializes the replication in HBM): reads need no broadcast, so
    # every kernel tensor starts with a natural layout.
    has_top = has_ref[...]  # int32 0/1 cell-uniform ([n, n, T])
    base = base_ref[...]
    count = cnt_ref[...]
    sol = jnp.zeros_like(top)
    # Lane masks ride as int32 0/1, not bool: vector-i1 loop carries make
    # Mosaic emit an invalid i1->i32 vreg bitcast on v5e.
    solved_f = jnp.zeros(shape, jnp.int32)
    overflow_f = jnp.zeros(shape, jnp.int32)
    nodes_d = jnp.zeros(shape, jnp.int32)
    sols_d = jnp.zeros(shape, jnp.int32)  # count_mode: solutions this dispatch
    liv_d = jnp.zeros(shape, jnp.int32)  # rounds each lane was live (occupancy)
    sweeps_d = jnp.int32(0)
    steps_d = jnp.int32(0)
    pick_low = branch_rule != "minrem-desc"

    def cond(c):
        (top, stack, has_top, base, count, sol, solved_f, overflow_f,
         nodes_d, sols_d, liv_d, sweeps_d, steps_d) = c
        return jnp.any(has_top > 0) & (steps_d < k_steps)

    def body(c):
        (top, stack, has_top, base, count, sol, solved_f, overflow_f,
         nodes_d, sols_d, liv_d, sweeps_d, steps_d) = c
        live = has_top > 0
        liv_d = liv_d + jnp.where(live, 1, 0)
        tops = jnp.where(live, top, jnp.uint32(0))
        tops, n_sweeps = _fixpoint_boards_last(
            tops, geom, max_sweeps, rules, unroll=sweep_unroll
        )
        slv, con = status_full(tops, geom)  # int32 0/1
        top_solved = (slv > 0) & live
        top_contra = (con > 0) & live

        # First-solution capture (both modes; job-level first-win and the
        # purge of sibling lanes happen in XLA between dispatches).
        newly = top_solved & (solved_f == 0)
        sol = jnp.where(newly, tops, sol)
        solved_f = jnp.where(newly, 1, solved_f)
        if count_mode:
            # Enumeration (VERDICT r3 #5): EVERY solved top counts, and the
            # lane does not freeze — it pops its next deferred subtree like
            # a contradiction does, so the search runs to exhaustion.
            sols_d = sols_d + jnp.where(top_solved, 1, 0)

        undecided = live & ~top_solved & ~top_contra
        onehot = _branch_dispatch_full(tops, geom, branch_rule)
        pick = _lowest_bit(tops) if pick_low else _highest_bit(tops)
        guess = jnp.where(onehot, pick, tops)
        rest = jnp.where(onehot, tops & ~pick, tops)

        can_push = undecided & (count < s)
        push_slot = (base + count) % s
        stack = _write_slot(stack, push_slot, can_push, rest)
        overflow_f = jnp.where(undecided & ~can_push, 1, overflow_f)
        nodes_d = nodes_d + jnp.where(undecided, 1, 0)

        if count_mode:
            resolved = top_solved | top_contra  # solved lanes pop too
        else:
            resolved = top_contra  # solved lanes freeze; contra lanes pop
        can_pop = resolved & (count > 0)
        pop_slot = (base + count - 1) % s
        popped = _select_slot(stack, pop_slot, can_pop)

        top = jnp.where(undecided, guess, tops)
        top = jnp.where(can_pop, popped, top)
        if count_mode:
            has_top = jnp.where(live & ~(resolved & ~can_pop), 1, 0)
        else:
            has_top = jnp.where(
                live & ~top_solved & ~(resolved & ~can_pop), 1, 0
            )
        count = count + jnp.where(can_push, 1, 0) - jnp.where(can_pop, 1, 0)
        return (top, stack, has_top, base, count, sol, solved_f, overflow_f,
                nodes_d, sols_d, liv_d, sweeps_d + n_sweeps, steps_d + 1)

    (top, stack, has_top, base, count, sol, solved_f, overflow_f,
     nodes_d, sols_d, liv_d, sweeps_d, steps_d) = jax.lax.while_loop(
        cond, body,
        (top, stack, has_top, base, count, sol, solved_f, overflow_f,
         nodes_d, sols_d, liv_d, sweeps_d, steps_d),
    )

    out_top[...] = top
    out_stack[...] = stack
    out_sol[...] = sol
    # Cell-uniform carries collapse to one [1, 1, T] slice at store time.
    zero_row = jnp.zeros((1, 1, shape[-1]), jnp.int32)
    out_has[...] = has_top[0:1, 0:1]
    out_base[...] = base[0:1, 0:1]
    out_cnt[...] = count[0:1, 0:1]
    out_solved[...] = solved_f[0:1, 0:1]
    out_over[...] = overflow_f[0:1, 0:1]
    out_nodes[...] = nodes_d[0:1, 0:1]
    out_solcnt[...] = sols_d[0:1, 0:1]
    out_live[...] = liv_d[0:1, 0:1]
    out_sweeps[...] = zero_row + sweeps_d
    out_steps[...] = zero_row + steps_d


# Sweeps executed as a straight-line prefix before the convergence-checked
# fixpoint loop inside the fused kernel (see _fixpoint_boards_last's
# ``unroll``): after round 1 most tiles converge in 2-5 sweeps, so skipping
# the loop machinery for the first two pays on nearly every round while the
# prefix stays bit-exact (sweeping a fixpoint is the identity).
_SWEEP_UNROLL = 2


@functools.partial(
    jax.jit,
    static_argnames=(
        "geom", "rules", "branch_rule", "max_sweeps", "k_steps", "tile",
        "count_mode", "interpret", "sweep_unroll",
    ),
)
def fused_rounds(
    top_t: jax.Array,
    stack_t: jax.Array,
    has_top: jax.Array,
    base: jax.Array,
    count: jax.Array,
    geom: Geometry,
    rules: str = "extended",
    branch_rule: str = "minrem",
    max_sweeps: int = 64,
    k_steps: int = 8,
    tile: int = 256,
    count_mode: bool = False,
    interpret: bool | None = None,
    sweep_unroll: int = _SWEEP_UNROLL,
):
    """Advance every lane up to ``k_steps`` frontier rounds in VMEM tiles.

    Boards-last state: ``top_t`` uint32[n, n, L], ``stack_t`` uint32
    [S, n, n, L]; per-lane int32/bool vectors.  Returns ``(top_t, stack_t,
    has_top, base, count, lane_solved, lane_sol_t, lane_overflow,
    nodes_delta, sols_delta, live_rounds_delta, sweeps_total, steps_max)``.
    With ``count_mode`` (enumeration), solved lanes pop and continue
    instead of freezing, and ``sols_delta`` counts every solved top;
    ``lane_solved`` / ``lane_sol_t`` still report each lane's FIRST
    solution this dispatch.  ``live_rounds_delta`` int32[L] counts the
    in-kernel rounds each lane held live work — the per-dispatch occupancy
    counter row behind ``/metrics fused_lane_occupancy`` (ROADMAP 4b).
    """
    n = geom.n
    n_lanes = top_t.shape[-1]
    s = stack_t.shape[0]
    interp = _interpret_default() if interpret is None else interpret
    tile = min(tile, n_lanes)
    if n_lanes % tile:
        raise ValueError(f"lanes {n_lanes} not a multiple of tile {tile}")
    n_tiles = n_lanes // tile

    # Per-lane inputs ride as full-shape cell-uniform [n, n, L] HBM
    # tensors (XLA materializes the broadcast): the kernel then never
    # broadcasts on load.  ~3 extra [n, n, L] copies per dispatch, amortized
    # over k_steps rounds.
    full = lambda v: jnp.broadcast_to(  # noqa: E731
        v.astype(jnp.int32)[None, None], (n, n, n_lanes)
    )
    kernel = functools.partial(
        _fused_kernel,
        geom=geom,
        rules=rules,
        branch_rule=branch_rule,
        max_sweeps=max_sweeps,
        k_steps=k_steps,
        count_mode=count_mode,
        sweep_unroll=sweep_unroll,
    )
    vmem = dict(memory_space=_VMEM) if (_VMEM is not None and not interp) else {}
    lane_spec = lambda *lead: pl.BlockSpec(  # noqa: E731
        (*lead, tile), lambda i: (*(0,) * len(lead), i), **vmem
    )
    row_shape = jax.ShapeDtypeStruct((1, 1, n_lanes), jnp.int32)
    (out_top, out_stack, o_has, o_base, o_cnt, o_solved, o_over, o_nodes,
     o_solcnt, o_live, o_sweeps, o_steps, out_sol) = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            lane_spec(n, n),
            lane_spec(s, n, n),
            lane_spec(n, n),
            lane_spec(n, n),
            lane_spec(n, n),
        ],
        out_specs=(
            lane_spec(n, n),
            lane_spec(s, n, n),
            *([lane_spec(1, 1)] * 10),
            lane_spec(n, n),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(top_t.shape, jnp.uint32),
            jax.ShapeDtypeStruct(stack_t.shape, jnp.uint32),
            *([row_shape] * 10),
            jax.ShapeDtypeStruct(top_t.shape, jnp.uint32),
        ),
        interpret=interp,
        **_vmem_params(interp),
    )(top_t, stack_t, full(has_top), full(base), full(count))

    # Per-tile scalars live broadcast in their rows; sum one lane per tile.
    tile_starts = jnp.arange(n_tiles, dtype=jnp.int32) * tile
    sweeps_total = jnp.sum(o_sweeps[0, 0][tile_starts])
    steps_max = jnp.max(o_steps[0, 0][tile_starts])
    return (
        out_top,
        out_stack,
        o_has[0, 0] > 0,
        o_base[0, 0],
        o_cnt[0, 0],
        o_solved[0, 0] > 0,
        out_sol,
        o_over[0, 0] > 0,
        o_nodes[0, 0],
        o_solcnt[0, 0],
        o_live[0, 0],
        sweeps_total,
        steps_max,
    )


# --------------------------------------------------------------------------
# XLA driver: job bookkeeping + cross-lane stealing between kernel dispatches.
# --------------------------------------------------------------------------


class FusedFrontier(NamedTuple):
    """Boards-last loop state for the fused driver (lane axis LAST)."""

    top_t: jax.Array  # uint32[n, n, L]
    stack_t: jax.Array  # uint32[S, n, n, L]
    has_top: jax.Array  # bool[L]
    base: jax.Array  # int32[L]
    count: jax.Array  # int32[L]
    job: jax.Array  # int32[L]
    solved: jax.Array  # bool[J]
    solution_t: jax.Array  # uint32[n, n, J]
    overflowed: jax.Array  # bool[J]
    nodes: jax.Array  # int32[J]
    sol_count: jax.Array  # int32[J] (== solved in find-one mode)
    steps: jax.Array  # int32
    sweeps: jax.Array  # int32
    expansions: jax.Array  # int32
    steals: jax.Array  # int32
    lane_rounds: jax.Array  # int32[L] rounds each lane was live (occupancy)


def frontier_to_fused(state) -> FusedFrontier:
    """Lane-first ``ops.frontier.Frontier`` -> boards-last fused state.

    The transposes are per-dispatch-loop, not per-round: the fused driver
    keeps everything boards-last across all its kernel dispatches, and the
    engine's flight bookkeeping (purge / shed / snapshot / finalize) runs on
    the lane-first form between chunks."""
    return FusedFrontier(
        top_t=state.top.transpose(1, 2, 0),
        stack_t=state.stack.transpose(1, 2, 3, 0),
        has_top=state.has_top,
        base=state.base,
        count=state.count,
        job=state.job,
        solved=state.solved,
        solution_t=state.solution.transpose(1, 2, 0),
        overflowed=state.overflowed,
        nodes=state.nodes,
        sol_count=state.sol_count,
        steps=state.steps,
        sweeps=state.sweeps,
        expansions=state.expansions,
        steals=state.steals,
        lane_rounds=state.lane_rounds,
    )


def fused_to_frontier(fs: FusedFrontier):
    """Boards-last fused state -> lane-first ``ops.frontier.Frontier``."""
    from distributed_sudoku_solver_tpu.ops.frontier import Frontier

    return Frontier(
        top=fs.top_t.transpose(2, 0, 1),
        has_top=fs.has_top,
        stack=fs.stack_t.transpose(3, 0, 1, 2),
        base=fs.base,
        count=fs.count,
        job=fs.job,
        solved=fs.solved,
        solution=fs.solution_t.transpose(2, 0, 1),
        overflowed=fs.overflowed,
        nodes=fs.nodes,
        sol_count=fs.sol_count,
        steps=fs.steps,
        sweeps=fs.sweeps,
        expansions=fs.expansions,
        steals=fs.steals,
        lane_rounds=fs.lane_rounds,
    )


def fused_lanes(n_lanes: int, n: int, stack_slots: int) -> int:
    """Round ``n_lanes`` up to a fused-kernel-valid lane count.

    Mosaic accepts a lane-tile that is either the whole array (any width
    <= 128 here) or a multiple of 128 (:func:`fused_tile`), so beyond 128
    lanes the count rounds up to the next multiple of 128.  Either way the
    stack depth must sit inside the measured compile boundary for the
    tile shape (:func:`_max_slots`), so an unfittable config raises HERE
    — a clean launch-time error, not an opaque Mosaic scoped-VMEM compile
    failure at first dispatch."""
    if n_lanes <= 128:
        if stack_slots > _max_slots(n, whole_array=True):
            raise ValueError(
                f"step_impl='fused' would overflow scoped VMEM at n={n}, "
                f"stack_slots={stack_slots} (whole-array tile compiles to "
                f"S={_max_slots(n, True)}); use step_impl='xla' or a "
                f"shallower stack"
            )
        return n_lanes
    if fused_tile(n, stack_slots) == 0:
        raise ValueError(
            f"step_impl='fused' would overflow scoped VMEM at n={n}, "
            f"stack_slots={stack_slots} beyond 128 lanes (128-lane tile "
            f"compiles to S={_max_slots(n, False)}); use step_impl='xla' "
            f"or a shallower stack"
        )
    return -(-n_lanes // 128) * 128


def _steal_t(top_t, has_top, stack_t, base, count, job, job_live, gang=0):
    """``ops.frontier._steal`` on boards-last tensors (lane axis last).

    Same prefix-sum rank pairing (``gang`` scopes it to lane blocks — the
    resident flight's slot invariant, ``SolverConfig.steal_gang``); row
    movement is a slot gather (``take_along_axis`` over S) + lane-axis
    gather/scatter.
    """
    from distributed_sudoku_solver_tpu.ops.frontier import pair_thieves_donors

    n_lanes = has_top.shape[0]
    s = stack_t.shape[0]

    idle = ~has_top
    donor = has_top & (count >= 1) & job_live
    thief_lane, donor_lane, pair, n_pairs = pair_thieves_donors(
        idle, donor, n_lanes, gang
    )
    safe_donor = jnp.clip(donor_lane, 0, n_lanes - 1)

    bottom = jnp.take_along_axis(
        stack_t, (base % s)[None, None, None, :], axis=0
    )[0]  # [n, n, L]: each lane's bottom stack row
    stolen = bottom[:, :, safe_donor]
    top_t = top_t.at[:, :, thief_lane].set(stolen, mode="drop")
    has_top = has_top.at[thief_lane].set(pair, mode="drop")
    job = job.at[thief_lane].set(job[safe_donor], mode="drop")

    donor_sel = (
        jnp.zeros(n_lanes, bool)
        .at[jnp.where(pair, donor_lane, n_lanes)]
        .set(True, mode="drop")
    )
    base = jnp.where(donor_sel, (base + 1) % s, base)
    count = jnp.where(donor_sel, count - 1, count)
    return top_t, has_top, base, count, job, n_pairs


def _fused_round(
    fs: FusedFrontier, geom: Geometry, config, rounds_fn=None
) -> FusedFrontier:
    """One kernel dispatch (k_steps rounds) + the XLA-side job bookkeeping.

    ``rounds_fn`` (FusedFrontier -> the 13-tuple :func:`fused_rounds`
    returns) swaps in a different whole-round kernel — the exact-cover
    kernel (``ops/pallas_cover.py``) shares every piece of this job
    bookkeeping (harvest, purge, steal) by providing its own; ``None``
    dispatches the Sudoku kernel."""
    n_jobs = fs.solved.shape[0]
    n_lanes = fs.has_top.shape[0]
    job_safe = jnp.clip(fs.job, 0, n_jobs - 1)

    if rounds_fn is None:
        rounds_fn = lambda f: fused_rounds(  # noqa: E731
            f.top_t, f.stack_t, f.has_top, f.base, f.count,
            geom,
            rules=config.rules,
            branch_rule=config.branch,
            max_sweeps=config.max_sweeps,
            k_steps=config.fused_steps,
            # Lanes were validated/rounded by solve_batch_fused: <= 128
            # lanes use one full-array tile, beyond that 128-lane tiles.
            tile=min(128, n_lanes),
            count_mode=config.count_all,
            sweep_unroll=config.fused_sweep_unroll,
        )
    (top_t, stack_t, has_top, base, count, lane_solved, lane_sol_t,
     lane_over, nodes_d, sols_d, liv_d, sweeps_t, steps_m) = rounds_fn(fs)

    live_jobs = fs.job >= 0
    lane_ids = jnp.arange(n_lanes, dtype=jnp.int32)
    if config.count_all:
        # Enumeration: jobs never resolve; every solved top adds to the
        # job's model count, and the job keeps the first solution any of
        # its lanes captured (which solution — not whether/how many — may
        # differ from the composite path: lanes run fused_steps rounds
        # between harvests, the same approximation as purge/steal).
        sol_count = fs.sol_count.at[
            jnp.where(live_jobs, fs.job, n_jobs)
        ].add(sols_d, mode="drop")
        had_sol = fs.sol_count > 0
        eligible = lane_solved & live_jobs & ~had_sol[job_safe]
        scatter_job = jnp.where(eligible, fs.job, n_jobs)
        first = jnp.full(n_jobs, n_lanes, jnp.int32).at[scatter_job].min(
            jnp.where(eligible, lane_ids, n_lanes), mode="drop"
        )
        newly = (first < n_lanes) & ~had_sol
        sol_rows = lane_sol_t[:, :, jnp.clip(first, 0, n_lanes - 1)]
        solution_t = jnp.where(newly[None, None, :], sol_rows, fs.solution_t)
        solved = fs.solved
    else:
        # First-lane-wins harvest per job (the composite step's exact rule).
        eligible = lane_solved & live_jobs & ~fs.solved[job_safe]
        scatter_job = jnp.where(eligible, fs.job, n_jobs)
        first = jnp.full(n_jobs, n_lanes, jnp.int32).at[scatter_job].min(
            jnp.where(eligible, lane_ids, n_lanes), mode="drop"
        )
        newly = (first < n_lanes) & ~fs.solved
        sol_rows = lane_sol_t[:, :, jnp.clip(first, 0, n_lanes - 1)]
        solution_t = jnp.where(newly[None, None, :], sol_rows, fs.solution_t)
        solved = fs.solved | newly
        sol_count = solved.astype(jnp.int32)

    overflowed = fs.overflowed.at[
        jnp.where(lane_over & live_jobs, fs.job, n_jobs)
    ].set(True, mode="drop")
    nodes = fs.nodes.at[jnp.where(live_jobs, fs.job, n_jobs)].add(
        nodes_d, mode="drop"
    )

    # Purge lanes of resolved jobs, then rebalance (receiver-initiated).
    job_live = live_jobs & ~solved[job_safe]
    has_top = has_top & job_live
    count = jnp.where(job_live, count, 0)
    job = fs.job
    n_steals = jnp.int32(0)
    if config.steal:
        top_t, has_top, base, count, job, n_steals = _steal_t(
            top_t, has_top, stack_t, base, count, job, job_live,
            gang=getattr(config, "steal_gang", 0),
        )

    return FusedFrontier(
        top_t=top_t,
        stack_t=stack_t,
        has_top=has_top,
        base=base,
        count=count,
        job=job,
        solved=solved,
        solution_t=solution_t,
        overflowed=overflowed,
        nodes=nodes,
        sol_count=sol_count,
        steps=fs.steps + steps_m,
        sweeps=fs.sweeps + sweeps_t,
        expansions=fs.expansions + jnp.sum(nodes_d),
        steals=fs.steals + n_steals,
        lane_rounds=fs.lane_rounds + liv_d,
    )


def _fused_live(fs: FusedFrontier) -> jax.Array:
    """bool[L]: lanes still holding unexplored work for an unresolved job."""
    n_jobs = fs.solved.shape[0]
    job_safe = jnp.clip(fs.job, 0, n_jobs - 1)
    return fs.has_top & (fs.job >= 0) & ~fs.solved[job_safe]


def _run_fused(
    fs: FusedFrontier, geom: Geometry, config, limit: jax.Array, rounds_fn=None
) -> FusedFrontier:
    """Dispatch fused rounds until nothing is live or ``steps`` hits ``limit``.

    ``limit`` is dynamic (the engine's chunk driver passes successive
    limits against one compiled program).  ``steps`` advances in
    ``fused_steps`` quanta — the max in-kernel rounds across tiles per
    dispatch — so the loop may overshoot ``limit`` by up to
    ``fused_steps - 1`` rounds (see :func:`solve_batch_fused` on the step
    accounting approximation).  ``rounds_fn`` swaps the whole-round kernel
    (see :func:`_fused_round`); ``geom`` is unused when it is given."""

    def cond(f: FusedFrontier):
        return jnp.any(_fused_live(f)) & (f.steps < limit)

    return jax.lax.while_loop(
        cond, lambda f: _fused_round(f, geom, config, rounds_fn), fs
    )


def _advance_fused(state, step_limit, geom: Geometry, config):
    """Shared body of the two public fused advance programs: resolve the
    device-resident ``fused_steps`` default, clamp the limit to
    ``max_steps``, and run the kernel rounds on the boards-last form.
    One recipe, so the serving (status) and legacy twins cannot drift."""
    from distributed_sudoku_solver_tpu.ops.frontier import FUSED_STEPS_DEVICE

    config = config.with_fused_steps(FUSED_STEPS_DEVICE)
    limit = jnp.minimum(jnp.int32(step_limit), jnp.int32(config.max_steps))
    fs = frontier_to_fused(state)
    return fused_to_frontier(_run_fused(fs, geom, config, limit))


@functools.partial(
    jax.jit, static_argnames=("geom", "config"), donate_argnums=(0,)
)
def advance_frontier_fused(
    state, step_limit: jax.Array, geom: Geometry, config
):
    """Fused-kernel twin of ``utils.checkpoint.advance_frontier``.

    Takes and returns a lane-first ``ops.frontier.Frontier``, advancing it
    via whole-round VMEM kernel dispatches until every job resolves or
    ``state.steps`` reaches ``step_limit``.  This is the serving
    integration seam (VERDICT r3 #1): the engine's chunked flight loop
    calls this in place of the composite ``advance_frontier``, and every
    piece of flight bookkeeping between chunks — mid-flight cancel purge,
    shed, snapshot, finalize — keeps operating on the unchanged
    lane-first ``Frontier`` form.  The boards-last transposes happen once
    per chunk, amortized over ``chunk_steps`` rounds.

    The caller must have sized the frontier with :func:`fused_lanes`
    (lane counts beyond 128 must be multiples of 128).

    A device-resident surface: the frontier never crosses the link between
    dispatches, so ``fused_steps=None`` resolves to the deep default
    (``FUSED_STEPS_DEVICE`` — r4 re-sweep: 32 measured +16% device-only
    over 8; the reactivity cost only matters where chunks cross a link).
    """
    return _advance_fused(state, step_limit, geom, config)


@functools.partial(
    jax.jit, static_argnames=("geom", "config"), donate_argnums=(0,)
)
def advance_frontier_fused_status(state, steps_delta: jax.Array, geom: Geometry, config):
    """Fused twin of ``utils.checkpoint.advance_frontier_status``: one
    serving chunk — advance by at most ``steps_delta`` MORE rounds (the
    limit is computed in-graph from the frontier's own ``steps``, so the
    host can dispatch chunk k+1 without knowing chunk k's outcome) and
    return ``(new_state, packed status word)``
    (``ops/frontier.chunk_status``).  ``state`` is donated.  ``steps`` may
    overshoot the limit by up to ``fused_steps - 1`` rounds exactly like
    :func:`advance_frontier_fused`; the returned status carries the
    authoritative value.
    """
    from distributed_sudoku_solver_tpu.ops.frontier import chunk_status

    new = _advance_fused(
        state, state.steps + jnp.int32(steps_delta), geom, config
    )
    return new, chunk_status(state.steps, state.lane_rounds, new)


@functools.partial(
    jax.jit, static_argnames=("geom", "config"), donate_argnums=(0,)
)
def advance_megastep_fused(
    state, chunk_steps: jax.Array, max_chunks: jax.Array, geom: Geometry, config
):
    """Fused twin of ``ops.frontier.advance_megastep``: the latency-mode
    in-graph chunk loop over whole-round VMEM kernel dispatches.

    One donated dispatch runs up to ``max_chunks`` fused chunks inside an
    outer ``lax.while_loop`` and early-exits on all-solved/all-dead, so the
    latency-mode serving path (``serving/megastep.py``) syncs once per
    FLIGHT.  The state stays boards-last across every inner chunk — the
    lane-first transposes happen once per flight, not once per chunk — and
    the packed status word is recomputed per inner chunk directly on the
    ``FusedFrontier`` (``chunk_status`` only touches fields the two frontier
    forms share: has_top / job / solved / lane_rounds / steps).

    Returns ``(new_state, status, chunks)`` with the same flight-start
    status baselines and early-exit round count as the composite twin;
    ``steps`` may overshoot each inner chunk's limit by up to
    ``fused_steps - 1`` rounds exactly like :func:`advance_frontier_fused`.
    """
    from distributed_sudoku_solver_tpu.ops.frontier import (
        FUSED_STEPS_DEVICE,
        STATUS_BITS,
        chunk_status,
    )

    config = config.with_fused_steps(FUSED_STEPS_DEVICE)
    n_jobs = state.solved.shape[0]
    w = (n_jobs + 31) // 32
    fs0 = frontier_to_fused(state)
    steps0 = fs0.steps
    rounds0 = fs0.lane_rounds
    chunk = jnp.int32(chunk_steps)
    budget = jnp.int32(config.max_steps)

    def one_chunk(fs: FusedFrontier):
        new = _run_fused(
            fs, geom, config, jnp.minimum(fs.steps + chunk, budget)
        )
        return new, chunk_status(steps0, rounds0, new)

    def cond(carry):
        fs, status, chunks = carry
        alive = jnp.any(status[STATUS_BITS + w : STATUS_BITS + 2 * w] != 0)
        return alive & (chunks < jnp.int32(max_chunks)) & (fs.steps < budget)

    def body(carry):
        fs, _, chunks = carry
        new, status = one_chunk(fs)
        return new, status, chunks + jnp.int32(1)

    fs, status = one_chunk(fs0)
    fs, status, chunks = jax.lax.while_loop(
        cond, body, (fs, status, jnp.int32(1))
    )
    return fused_to_frontier(fs), status, chunks


@functools.partial(jax.jit, static_argnames=("geom", "config"))
def solve_batch_fused(
    grids: jax.Array, geom: Geometry, config
):
    """Fused-step batched Sudoku solve (``SolverConfig.step_impl='fused'``).

    Same contract as ``ops.solve.solve_batch`` (solved / proven-unsat /
    unknown verdicts, int-grid solutions; exact ``sol_count`` model counts
    under ``count_all`` enumeration) under the fused round semantics:
    purge/steal react at ``fused_steps`` granularity, so node counts — and
    under ``count_all``, *which* first-found solution is reported (never
    the count) — differ from the composite step while every verdict stays
    sound (``tests/test_fused_step.py``).

    Step accounting is an approximation (ADVICE r3): each dispatch advances
    ``steps`` by the MAX in-kernel rounds across tiles, so a lane in a tile
    that exited its while-loop early consumes the ``max_steps`` budget at
    the fastest tile's rate — it may be cut off having run fewer actual
    rounds than ``max_steps``.  Verdicts stay sound (a budget cutoff is
    "unknown", never a wrong answer), but ``steps`` is not comparable
    lane-for-lane with the composite path's exact per-round count.
    """
    import dataclasses

    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.ops.frontier import (
        FUSED_STEPS_DEVICE,
        init_frontier,
    )
    from distributed_sudoku_solver_tpu.ops.solve import (
        SolveResult,
        _decode_solution,
    )

    # Device-resident surface: grids stay on-device across dispatches, so
    # fused_steps=None resolves to the deep default (see
    # advance_frontier_fused).
    config = config.with_fused_steps(FUSED_STEPS_DEVICE)

    # Round the lane count up to a multiple of the kernel tile so the
    # grid divides evenly — the composite path has no such constraint, and
    # a raise on e.g. 200 lanes would leak a kernel implementation detail.
    # Extra lanes start idle and join as thieves, exactly like min_lanes
    # slack.
    n_jobs = grids.shape[0]
    lanes = fused_lanes(
        config.resolve_lanes(n_jobs), geom.n, config.stack_slots
    )
    config = dataclasses.replace(config, lanes=lanes)

    state = init_frontier(encode_grid(grids, geom), config)
    n_jobs = state.solved.shape[0]
    fs = frontier_to_fused(state)

    fs = _run_fused(fs, geom, config, jnp.int32(config.max_steps))

    job_safe = jnp.clip(fs.job, 0, n_jobs - 1)
    job_has_work = jnp.zeros(n_jobs, bool).at[job_safe].max(
        _fused_live(fs), mode="drop"
    )
    unsat = ~fs.solved & ~job_has_work & ~fs.overflowed
    res = SolveResult(
        solution=fs.solution_t.transpose(2, 0, 1),
        solved=fs.solved,
        unsat=unsat,
        overflowed=fs.overflowed,
        nodes=fs.nodes,
        sol_count=fs.sol_count,
        steps=fs.steps,
        sweeps=fs.sweeps,
        expansions=fs.expansions,
        steals=fs.steals,
    )
    return _decode_solution(res)


def _branch_dispatch_full(cand: jax.Array, geom: Geometry, rule: str):
    """Trace-time branch-rule dispatch for the fused kernel (ISSUE 19).

    The rule is a static Python string, so this is a pure Python ``if``:
    legacy rules reach :func:`branch_onehot_full` unchanged (same jaxpr,
    eqn for eqn), scored heads take :func:`_head_branch_full` below.

    Defined at the BOTTOM of this module — and substituted into the
    kernel body as a one-line call — on purpose: the jaxpr embeds source
    LINES from this file (``_fused_kernel``'s def via pallas_call's
    name_and_src_info, the BlockMapping index_map lambdas in
    :func:`fused_rounds`), so any net line inserted above them would
    drift every default-rule golden without changing a single equation.
    """
    if rule.startswith("head:"):
        return _head_branch_full(cand, geom, rule)
    return branch_onehot_full(cand, geom, rule)


def _head_branch_full(cand: jax.Array, geom: Geometry, rule: str):
    """Scored-head twin of ``branch_onehot_full`` on [n, n, T] (ISSUE 19).

    The head's boards-last f32 score packs through the same quantized key
    (``ordering.pack_key``): unique per cell, so the board-minimum IS the
    argmin with the identical lowest-cell tie-break.  The kernel's
    cell-uniform ``_unit_full`` sums are injected as the head's reduction
    seam — ``ops/ordering.py`` never reaches into pallas internals, and
    everything a head emits is elementwise VPU work (plus MXU matmuls for
    the mlp head) over [n, n, T].  The lazy ``ordering`` import keeps the
    module header line-stable (see :func:`_branch_dispatch_full`).
    """
    from distributed_sudoku_solver_tpu.ops import ordering

    n = geom.n
    pc = jax.lax.population_count(cand).astype(jnp.int32)
    und = pc > 1
    cell = (
        jax.lax.broadcasted_iota(jnp.int32, cand.shape, 0) * n
        + jax.lax.broadcasted_iota(jnp.int32, cand.shape, 1)
    )
    head = ordering.get_head(rule)
    score = head.score_full(
        cand, geom, unit_sum=lambda x: _unit_full(x, geom, operator.add)
    )
    key = ordering.pack_key(score, und, cell, n, head.quant)
    return (key == _full_min(key)) & und
