"""Host<->device wire packing for the bulk path.

The reference's wire problem was a 1,024-byte UDP recv cap truncating 25x25
TASK pickles (``/root/reference/DHT_Node.py:94``, SURVEY.md §2.5 #8).  The
TPU build's equivalent constraint is the *host<->device link*: on tunneled
devices (axon RPC) the measured link runs at ~10-15 MB/s with ~120 ms per
round trip, so at 10^5-board batches the transfer — not the chip — bounds
end-to-end throughput.  Two countermeasures, both transparent to callers:

* **nibble packing** (geometries with n <= 14): 4 bits per cell, two cells
  per byte, halving both directions vs int8 cells.  The spare code point 15
  marks corrupt input (out-of-range host values), which the mask encoder
  maps to the empty candidate mask -> a clean unsat verdict, preserving the
  corrupt-input contract of ``value_to_mask`` (``ops/bitmask.py:49-60``).
* **single-fetch results**: solution cells and the per-board verdict
  (solved / unsat / branched bits) ride one device array, so a chunk costs
  one upload, one dispatch, one download — each extra fetch is a full
  tunnel round trip (~120 ms) regardless of size.

Formats (chosen statically by geometry):

* ``nibble`` (n <= 14): grids ``uint8[B, ceil(n²/2)]``; results
  ``uint8[B, ceil(n²/2) + 1]`` (cells then verdict byte).
* ``byte`` (n > 14): grids ``int8[B, n²]`` (corrupt -> -1); results
  ``int8[B, n² + 1]``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry

NIBBLE_MAX_N = 14  # 15 is the corrupt marker, so digits must stay <= 14

VERDICT_SOLVED = 1
VERDICT_UNSAT = 2
VERDICT_BRANCHED = 4


def uses_nibbles(geom: Geometry) -> bool:
    return geom.n <= NIBBLE_MAX_N


def grid_wire_width(geom: Geometry) -> int:
    n2 = geom.n * geom.n
    return (n2 + 1) // 2 if uses_nibbles(geom) else n2


def pack_grids_host(grids: np.ndarray, geom: Geometry) -> np.ndarray:
    """int grids [B, n, n] -> wire bytes (numpy, host side)."""
    b = grids.shape[0]
    flat = np.ascontiguousarray(grids).reshape(b, -1).astype(np.int64)
    bad = (flat < 0) | (flat > geom.n)
    if not uses_nibbles(geom):
        out = flat.astype(np.int8)
        out[bad] = -1
        return out
    cells = np.where(bad, 15, flat).astype(np.uint8)
    if cells.shape[1] % 2:
        cells = np.concatenate([cells, np.zeros((b, 1), np.uint8)], axis=1)
    return cells[:, 0::2] | (cells[:, 1::2] << 4)


def unpack_grids_device(packed: jnp.ndarray, geom: Geometry) -> jnp.ndarray:
    """Wire bytes -> int32 grids [B, n, n] (traced, device side)."""
    b = packed.shape[0]
    n2 = geom.n * geom.n
    if not uses_nibbles(geom):
        return packed.astype(jnp.int32).reshape(b, geom.n, geom.n)
    u = packed.astype(jnp.uint8)
    cells = jnp.stack([u & 15, u >> 4], axis=-1).reshape(b, -1)[:, :n2]
    return cells.astype(jnp.int32).reshape(b, geom.n, geom.n)


def pack_result_device(
    solution: jnp.ndarray,
    solved: jnp.ndarray,
    unsat: jnp.ndarray,
    branched: jnp.ndarray,
    geom: Geometry,
) -> jnp.ndarray:
    """(solution int[B,n,n], verdict bools[B]) -> one wire array (traced)."""
    b = solution.shape[0]
    verdict = (
        solved.astype(jnp.uint8) * VERDICT_SOLVED
        | unsat.astype(jnp.uint8) * VERDICT_UNSAT
        | branched.astype(jnp.uint8) * VERDICT_BRANCHED
    )
    flat = solution.reshape(b, -1)
    if not uses_nibbles(geom):
        return jnp.concatenate(
            [flat.astype(jnp.int8), verdict.astype(jnp.int8)[:, None]], axis=1
        )
    cells = flat.astype(jnp.uint8)
    if cells.shape[1] % 2:
        cells = jnp.concatenate([cells, jnp.zeros((b, 1), jnp.uint8)], axis=1)
    packed = cells[:, 0::2] | (cells[:, 1::2] << 4)
    return jnp.concatenate([packed, verdict[:, None]], axis=1)


def unpack_result_host(wire: np.ndarray, geom: Geometry):
    """Wire result -> (solution int32[B,n,n], solved, unsat, branched) (host)."""
    wire = np.asarray(wire)
    b = wire.shape[0]
    n2 = geom.n * geom.n
    verdict = wire[:, -1].astype(np.uint8)
    cells = wire[:, :-1]
    if uses_nibbles(geom):
        u = cells.astype(np.uint8)
        cells = np.stack([u & 15, u >> 4], axis=-1).reshape(b, -1)[:, :n2]
    solution = cells.astype(np.int32).reshape(b, geom.n, geom.n)
    return (
        solution,
        (verdict & VERDICT_SOLVED) > 0,
        (verdict & VERDICT_UNSAT) > 0,
        (verdict & VERDICT_BRANCHED) > 0,
    )
