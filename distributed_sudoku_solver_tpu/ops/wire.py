"""Host<->device wire packing for the bulk path.

The reference's wire problem was a 1,024-byte UDP recv cap truncating 25x25
TASK pickles (``/root/reference/DHT_Node.py:94``, SURVEY.md §2.5 #8).  The
TPU build's equivalent constraint is the *host<->device link*: on tunneled
devices (axon RPC) the measured link runs at ~10-15 MB/s with ~120 ms per
round trip, so at 10^5-board batches the transfer — not the chip — bounds
end-to-end throughput.  Two countermeasures, both transparent to callers:

* **nibble packing** (geometries with n <= 14): 4 bits per cell, two cells
  per byte, halving both directions vs int8 cells.  The spare code point 15
  marks corrupt input (out-of-range host values), which the mask encoder
  maps to the empty candidate mask -> a clean unsat verdict, preserving the
  corrupt-input contract of ``value_to_mask`` (``ops/bitmask.py:49-60``).
* **single-fetch results**: solution cells and the per-board verdict
  (solved / unsat / branched bits) ride one device array, so a chunk costs
  one upload, one dispatch, one download — each extra fetch is a full
  tunnel round trip (~120 ms) regardless of size.

Formats (chosen statically by geometry):

* ``nibble`` (n <= 14): grids ``uint8[B, ceil(n²/2)]``; results
  ``uint8[B, ceil(n²/2) + 1]`` (cells then verdict byte).
* ``byte`` (n > 14): grids ``int8[B, n²]`` (corrupt -> -1); results
  ``int8[B, n² + 1]``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry

NIBBLE_MAX_N = 14  # 15 is the corrupt marker, so digits must stay <= 14
DENSE_MAX_N = 9  # triplet base-(n+1) must fit 10 bits: (n+1)^3 <= 1024

VERDICT_SOLVED = 1
VERDICT_UNSAT = 2
VERDICT_BRANCHED = 4


def uses_nibbles(geom: Geometry) -> bool:
    return geom.n <= NIBBLE_MAX_N


def grid_wire_width(geom: Geometry) -> int:
    n2 = geom.n * geom.n
    return (n2 + 1) // 2 if uses_nibbles(geom) else n2


def pack_grids_host(grids: np.ndarray, geom: Geometry) -> np.ndarray:
    """int grids [B, n, n] -> wire bytes (numpy, host side)."""
    b = grids.shape[0]
    flat = np.ascontiguousarray(grids).reshape(b, -1).astype(np.int64)
    bad = (flat < 0) | (flat > geom.n)
    if not uses_nibbles(geom):
        out = flat.astype(np.int8)
        out[bad] = -1
        return out
    cells = np.where(bad, 15, flat).astype(np.uint8)
    if cells.shape[1] % 2:
        cells = np.concatenate([cells, np.zeros((b, 1), np.uint8)], axis=1)
    return cells[:, 0::2] | (cells[:, 1::2] << 4)


def unpack_grids_device(packed: jnp.ndarray, geom: Geometry) -> jnp.ndarray:
    """Wire bytes -> int32 grids [B, n, n] (traced, device side)."""
    b = packed.shape[0]
    n2 = geom.n * geom.n
    if not uses_nibbles(geom):
        return packed.astype(jnp.int32).reshape(b, geom.n, geom.n)
    u = packed.astype(jnp.uint8)
    cells = jnp.stack([u & 15, u >> 4], axis=-1).reshape(b, -1)[:, :n2]
    return cells.astype(jnp.int32).reshape(b, geom.n, geom.n)


def pack_result_device(
    solution: jnp.ndarray,
    solved: jnp.ndarray,
    unsat: jnp.ndarray,
    branched: jnp.ndarray,
    geom: Geometry,
) -> jnp.ndarray:
    """(solution int[B,n,n], verdict bools[B]) -> one wire array (traced)."""
    b = solution.shape[0]
    verdict = (
        solved.astype(jnp.uint8) * VERDICT_SOLVED
        | unsat.astype(jnp.uint8) * VERDICT_UNSAT
        | branched.astype(jnp.uint8) * VERDICT_BRANCHED
    )
    flat = solution.reshape(b, -1)
    if not uses_nibbles(geom):
        return jnp.concatenate(
            [flat.astype(jnp.int8), verdict.astype(jnp.int8)[:, None]], axis=1
        )
    cells = flat.astype(jnp.uint8)
    if cells.shape[1] % 2:
        cells = jnp.concatenate([cells, jnp.zeros((b, 1), jnp.uint8)], axis=1)
    packed = cells[:, 0::2] | (cells[:, 1::2] << 4)
    return jnp.concatenate([packed, verdict[:, None]], axis=1)


def unpack_result_host(wire: np.ndarray, geom: Geometry):
    """Wire result -> (solution int32[B,n,n], solved, unsat, branched) (host)."""
    wire = np.asarray(wire)
    b = wire.shape[0]
    n2 = geom.n * geom.n
    verdict = wire[:, -1].astype(np.uint8)
    cells = wire[:, :-1]
    if uses_nibbles(geom):
        u = cells.astype(np.uint8)
        cells = np.stack([u & 15, u >> 4], axis=-1).reshape(b, -1)[:, :n2]
    solution = cells.astype(np.int32).reshape(b, geom.n, geom.n)
    return (
        solution,
        (verdict & VERDICT_SOLVED) > 0,
        (verdict & VERDICT_UNSAT) > 0,
        (verdict & VERDICT_BRANCHED) > 0,
    )


# --------------------------------------------------------------------------
# Dense format (round 5): 10-bit digit triplets — ~15% fewer bytes than
# nibbles each way at n <= 9.  Three digits base-(n+1) pack into one 10-bit
# group ((n+1)^3 <= 1024 for n <= 9); four groups ride a 5-byte block
# (lo-uint32 + one high byte, so the device side never needs 64-bit math —
# x64 is off under jit).  The corrupt-input contract changes vehicle: there
# is no spare code point, so a board with any out-of-range cell is replaced
# host-side by a canonical CONTRADICTORY board (two 1s in row 0), which the
# solver proves unsat — same observable verdict as the nibble format's 15
# marker, no extra wire bits.  Measured round 5 (BENCHMARKS.md "Pipeline
# anatomy"): the bulk pipeline is transfer-bound through the tunnel, so
# wire bytes convert ~1:1 into end-to-end throughput.
# --------------------------------------------------------------------------


def uses_dense(geom: Geometry) -> bool:
    return geom.n <= DENSE_MAX_N


def _dense_geometry(geom: Geometry) -> tuple[int, int, int]:
    """(cells, groups, blocks): 3 cells/group, 4 groups/5-byte block."""
    n2 = geom.n * geom.n
    groups = -(-n2 // 3)
    blocks = -(-groups // 4)
    return n2, groups, blocks


def grid_dense_width(geom: Geometry) -> int:
    return 5 * _dense_geometry(geom)[2]


def _digits_to_blocks_np(cells: np.ndarray, geom: Geometry) -> np.ndarray:
    """uint16 digits [B, n^2] -> packed uint8 [B, 5*blocks] (host numpy)."""
    b = cells.shape[0]
    n2, groups, blocks = _dense_geometry(geom)
    base = geom.n + 1
    pad = np.zeros((b, groups * 3 - n2), np.uint32)
    d = np.concatenate([cells.astype(np.uint32), pad], axis=1)
    d = d.reshape(b, groups, 3)
    g = d[:, :, 0] + base * d[:, :, 1] + base * base * d[:, :, 2]
    gpad = np.zeros((b, blocks * 4 - groups), np.uint32)
    g = np.concatenate([g, gpad], axis=1).reshape(b, blocks, 4)
    lo = g[:, :, 0] | (g[:, :, 1] << 10) | (g[:, :, 2] << 20) | ((g[:, :, 3] & 3) << 30)
    hi = (g[:, :, 3] >> 2).astype(np.uint8)
    out = np.empty((b, blocks, 5), np.uint8)
    for i in range(4):
        out[:, :, i] = (lo >> (8 * i)).astype(np.uint8)
    out[:, :, 4] = hi
    return out.reshape(b, blocks * 5)


def _blocks_to_digits_np(packed: np.ndarray, geom: Geometry) -> np.ndarray:
    """Inverse of :func:`_digits_to_blocks_np` -> int32 [B, n^2] (host)."""
    b = packed.shape[0]
    n2, groups, blocks = _dense_geometry(geom)
    base = geom.n + 1
    raw = packed.reshape(b, blocks, 5).astype(np.uint32)
    lo = raw[:, :, 0] | (raw[:, :, 1] << 8) | (raw[:, :, 2] << 16) | (raw[:, :, 3] << 24)
    g = np.stack(
        [
            lo & 1023,
            (lo >> 10) & 1023,
            (lo >> 20) & 1023,
            ((lo >> 30) & 3) | (raw[:, :, 4] << 2),
        ],
        axis=2,
    ).reshape(b, blocks * 4)[:, :groups]
    d = np.stack([g % base, (g // base) % base, g // (base * base)], axis=2)
    return d.reshape(b, groups * 3)[:, :n2].astype(np.int32)


def pack_grids_dense_host(grids: np.ndarray, geom: Geometry) -> np.ndarray:
    """int grids [B, n, n] -> dense wire bytes; corrupt boards -> canonical
    contradictory board (the solver proves it unsat, preserving the
    corrupt-input contract without a wire code point)."""
    b = grids.shape[0]
    flat = np.ascontiguousarray(grids).reshape(b, -1).astype(np.int64)
    bad = ((flat < 0) | (flat > geom.n)).any(axis=1)
    cells = flat.astype(np.uint16)
    if bad.any():
        contra = np.zeros(geom.n * geom.n, np.uint16)
        contra[0] = contra[1] = 1  # two 1s in row 0: proven unsat
        cells[bad] = contra
    return _digits_to_blocks_np(cells, geom)


def unpack_grids_dense_device(packed: jnp.ndarray, geom: Geometry) -> jnp.ndarray:
    """Dense wire bytes -> int32 grids [B, n, n] (traced, device side)."""
    b = packed.shape[0]
    n2, groups, blocks = _dense_geometry(geom)
    base = geom.n + 1
    raw = packed.reshape(b, blocks, 5).astype(jnp.uint32)
    lo = raw[:, :, 0] | (raw[:, :, 1] << 8) | (raw[:, :, 2] << 16) | (raw[:, :, 3] << 24)
    g = jnp.stack(
        [
            lo & 1023,
            (lo >> 10) & 1023,
            (lo >> 20) & 1023,
            ((lo >> 30) & 3) | (raw[:, :, 4] << 2),
        ],
        axis=2,
    ).reshape(b, blocks * 4)[:, :groups]
    d = jnp.stack([g % base, (g // base) % base, g // (base * base)], axis=2)
    cells = d.reshape(b, groups * 3)[:, :n2]
    return cells.astype(jnp.int32).reshape(b, geom.n, geom.n)


def pack_result_dense_device(
    solution: jnp.ndarray,
    solved: jnp.ndarray,
    unsat: jnp.ndarray,
    branched: jnp.ndarray,
    geom: Geometry,
) -> jnp.ndarray:
    """(solution, verdicts) -> dense wire array [B, 5*blocks + 1] (traced)."""
    b = solution.shape[0]
    n2, groups, blocks = _dense_geometry(geom)
    base = geom.n + 1
    verdict = (
        solved.astype(jnp.uint8) * VERDICT_SOLVED
        | unsat.astype(jnp.uint8) * VERDICT_UNSAT
        | branched.astype(jnp.uint8) * VERDICT_BRANCHED
    )
    flat = solution.reshape(b, -1).astype(jnp.uint32)
    pad = jnp.zeros((b, groups * 3 - n2), jnp.uint32)
    d = jnp.concatenate([flat, pad], axis=1).reshape(b, groups, 3)
    g = d[:, :, 0] + base * d[:, :, 1] + base * base * d[:, :, 2]
    gpad = jnp.zeros((b, blocks * 4 - groups), jnp.uint32)
    g = jnp.concatenate([g, gpad], axis=1).reshape(b, blocks, 4)
    lo = g[:, :, 0] | (g[:, :, 1] << 10) | (g[:, :, 2] << 20) | ((g[:, :, 3] & 3) << 30)
    hi = (g[:, :, 3] >> 2).astype(jnp.uint8)
    parts = [(lo >> (8 * i)).astype(jnp.uint8)[:, :, None] for i in range(4)]
    out = jnp.concatenate([*parts, hi[:, :, None]], axis=2).reshape(b, blocks * 5)
    return jnp.concatenate([out, verdict[:, None]], axis=1)


def unpack_result_dense_host(wire_bytes: np.ndarray, geom: Geometry):
    """Dense wire result -> (solution, solved, unsat, branched) (host)."""
    wire_bytes = np.asarray(wire_bytes)
    b = wire_bytes.shape[0]
    verdict = wire_bytes[:, -1].astype(np.uint8)
    solution = _blocks_to_digits_np(wire_bytes[:, :-1], geom).reshape(
        b, geom.n, geom.n
    )
    return (
        solution,
        (verdict & VERDICT_SOLVED) > 0,
        (verdict & VERDICT_UNSAT) > 0,
        (verdict & VERDICT_BRANCHED) > 0,
    )


def best_format(geom: Geometry) -> str:
    """'dense' where it is strictly smaller than the legacy packing, else
    'packed' (dense LOSES at tiny boards: 4x4 dense is 10 B vs 8 nibble)."""
    if uses_dense(geom) and grid_dense_width(geom) < grid_wire_width(geom):
        return "dense"
    return "packed"


def pack_grids_for(grids: np.ndarray, geom: Geometry, fmt: str) -> np.ndarray:
    return (
        pack_grids_dense_host(grids, geom)
        if fmt == "dense"
        else pack_grids_host(grids, geom)
    )


def unpack_result_for(wire_arr: np.ndarray, geom: Geometry, fmt: str):
    return (
        unpack_result_dense_host(wire_arr, geom)
        if fmt == "dense"
        else unpack_result_host(wire_arr, geom)
    )
