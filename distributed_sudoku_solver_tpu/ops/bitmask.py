"""Bitmask primitives for the candidate-tensor board encoding.

TPU-native replacement for the reference's list-of-lists grid + per-guess
membership scans (``/root/reference/utils.py:27-55`` ``is_valid`` walks the
row, column and box in Python per call).  Here a board is a ``uint32[n, n]``
tensor of candidate bitmasks — bit ``d`` set means digit ``d+1`` is still
possible — and every constraint check in the framework is a vectorized
boolean/integer op on that tensor, batched over an arbitrary leading shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import Geometry


def popcount(x: jax.Array) -> jax.Array:
    """Number of set bits per element (candidate count of a cell)."""
    return jax.lax.population_count(x)


def lowest_bit(x: jax.Array) -> jax.Array:
    """Isolate the lowest set bit: the *ascending-digit* branch choice.

    Matches the reference's guess order (``for number in arr`` ascending,
    ``/root/reference/DHT_Node.py:522``) so branch-and-bound explores digits
    low-to-high and unique-solution puzzles decode bit-exactly.
    """
    return x & (~x + jnp.uint32(1))


def highest_bit(x: jax.Array) -> jax.Array:
    """Isolate the highest set bit: the *descending-digit* branch choice.

    The value-order mirror of :func:`lowest_bit` — the portfolio axis
    (SURVEY.md §2.2 EP analog): a solution living in high digits is found
    orders of magnitude faster descending than ascending, and vice versa,
    so racing both hedges worst-case DFS order.  Bit smear then keep the
    top edge; 0 stays 0.
    """
    x = jnp.asarray(x, jnp.uint32)
    for s in (1, 2, 4, 8, 16):
        x = x | (x >> jnp.uint32(s))
    return x ^ (x >> jnp.uint32(1))


def is_single(x: jax.Array) -> jax.Array:
    """True where the cell is decided (exactly one candidate)."""
    return popcount(x) == 1


def mask_to_value(x: jax.Array) -> jax.Array:
    """Singleton mask -> digit value in 1..n; non-singletons -> 0.

    Uses count-leading-zeros so it needs no lookup table at any geometry.
    """
    x = x.astype(jnp.uint32)
    bit_index = 31 - jax.lax.clz(x).astype(jnp.int32)
    return jnp.where(is_single(x), bit_index + 1, 0).astype(jnp.int32)


def value_to_mask(v: jax.Array, geom: Geometry) -> jax.Array:
    """Digit value (1..n; 0 = empty) -> candidate mask (empty -> full mask).

    Out-of-range values (negative or > n) map to the empty mask 0, which is a
    contradiction: corrupt input yields a clean "unsat" verdict instead of
    being silently clipped into a legal-looking clue.
    """
    v = v.astype(jnp.int32)
    given = jnp.uint32(1) << jnp.clip(v - 1, 0, geom.n - 1).astype(jnp.uint32)
    out = jnp.where(v > 0, given, jnp.uint32(geom.full_mask))
    in_range = (v >= 0) & (v <= geom.n)
    return jnp.where(in_range, out, jnp.uint32(0))


def encode_grid(grid: jax.Array, geom: Geometry) -> jax.Array:
    """int grid [..., n, n] (0 = empty) -> candidate tensor uint32 [..., n, n]."""
    return value_to_mask(jnp.asarray(grid), geom)


def decode_grid(cand: jax.Array) -> jax.Array:
    """Candidate tensor -> int32 grid; undecided/contradicted cells -> 0."""
    return mask_to_value(cand)


def or_reduce(x: jax.Array, axis: int) -> jax.Array:
    """Bitwise-OR reduction along one axis (the 'digits seen in this unit' op).

    Log-depth tree of static slices + ``|`` rather than ``jax.lax.reduce``
    with a custom combiner: the same primitive-free shape works everywhere —
    XLA fuses it identically, and it lowers cleanly inside Pallas/Mosaic
    kernels (``ops/pallas_propagate.py``) where custom reduce combiners don't.
    """
    axis = axis % x.ndim
    x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    pow2 = 1 << (n - 1).bit_length()
    if pow2 != n:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, pow2 - n)]
        x = jnp.pad(x, pad)
    while x.shape[-1] > 1:
        h = x.shape[-1] // 2
        x = x[..., :h] | x[..., h:]
    return x[..., 0]


def once_twice_reduce(x: jax.Array, axis: int) -> tuple[jax.Array, jax.Array]:
    """Along ``axis``: bits set in >=1 element (``once``) and >=2 (``twice``).

    ``once & ~twice`` is the hidden-singles mask: digits with exactly one home
    in the unit.  The combine ((o1,t1),(o2,t2)) -> (o1|o2, t1|t2|(o1&o2)) is
    associative, so a log-depth tree reduction keeps the XLA graph small even
    for 25-wide units.
    """
    axis = axis % x.ndim
    x = jnp.moveaxis(x, axis, -1)
    once, twice = x, jnp.zeros_like(x)
    n = x.shape[-1]
    # Pad to a power of two with identity (0, 0) elements.
    pow2 = 1 << (n - 1).bit_length()
    if pow2 != n:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, pow2 - n)]
        once = jnp.pad(once, pad)
        twice = jnp.pad(twice, pad)
    while once.shape[-1] > 1:
        h = once.shape[-1] // 2
        o1, o2 = once[..., :h], once[..., h:]
        t1, t2 = twice[..., :h], twice[..., h:]
        once, twice = o1 | o2, t1 | t2 | (o1 & o2)
    return once[..., 0], twice[..., 0]


def to_boxes(cand: jax.Array, geom: Geometry) -> jax.Array:
    """[..., n, n] -> [..., n_boxes, cells_per_box] view of the box units.

    Rows split as (n_vboxes, box_h), cols as (n_hboxes, box_w); transposing the
    middle axes groups each box's cells contiguously.  Cell order inside a box
    is row-major, matching the reference checker's box walk
    (``/root/reference/sudoku.py:48-68``).
    """
    lead = cand.shape[:-2]
    x = cand.reshape(*lead, geom.n_vboxes, geom.box_h, geom.n_hboxes, geom.box_w)
    x = jnp.swapaxes(x, -3, -2)
    return x.reshape(*lead, geom.n, geom.n)


def from_boxes(boxes: jax.Array, geom: Geometry) -> jax.Array:
    """Inverse of :func:`to_boxes`."""
    lead = boxes.shape[:-2]
    x = boxes.reshape(*lead, geom.n_vboxes, geom.n_hboxes, geom.box_h, geom.box_w)
    x = jnp.swapaxes(x, -3, -2)
    return x.reshape(*lead, geom.n, geom.n)
