"""Whole-round fused VMEM kernel for generalized exact cover (VERDICT r4 #3).

``ops/pallas_step.py`` gave the Sudoku family a whole-round kernel; this
module is the promised second kernel over the packed row-conflict algebra
of ``models/cover.py`` (n-queens, pentomino, any ``ExactCoverCSP``), so
the cover family — whose headline workload IS enumeration — gets the same
dispatch-amortized VMEM treatment that bought Sudoku `count_all` 3.31x.

The cover algebra looks Mosaic-hostile on its face: ``col_rows[col]`` and
``elim[row]`` are gathers by *dynamic per-lane index*, and Mosaic lowers
no dynamic gather.  The kernel's central design move is that on TPU every
one of those gathers is an **MXU matmul** over 0/1 float32 matrices
(exact for all integers involved, < 2^24):

* per-column candidate counts: ``cnt = inc_primᵀ @ avail``  — [C, T]
* "rows of the chosen column": ``inc_prim @ colsel``        — [R, T]
* conflict elimination for a chosen row (replacing the R x R ``elim``
  matrix, which at pentomino scale would be 17 MB of VMEM):
  ``colset = inc_fullᵀ @ rowsel`` then ``inc_full @ colset`` — two
  matmuls through the full incidence (primary + secondary columns)
* bitmask unpack/pack between the frontier's packed ``uint32[D]`` state
  and the kernel's unpacked 0/1 row/column tensors: word-select and
  bit-weight matmuls (16 f32-exact bits per half)
* one-hot re-materialization of sublane min-reductions (lowest forced
  column, lowest available row, MRV column): ``ones @ min`` — a matmul
  materialization with natural layout, sidestepping the
  broadcast-provenance trap ``pallas_step._bcast_reduce`` documents.

Every primitive above was pinned on real v5e hardware by a minimized
probe before this module was built (``benchmarks/probe_cover_kernel.py``,
bit-exact vs interpret mode; two named walls found and routed around:
Mosaic has no uint32<->f32 cast in either direction, so all casts go
through int32).

Search semantics mirror the composite engine exactly (``models/cover.py``
propagate/status/branch: one forced take per lane per sweep, MRV column
branch, lowest-row guess vs row-exclusion rest), under the same
fused-round contract as the Sudoku kernel: purge/steal/harvest batch at
``fused_steps`` granularity in the XLA driver between dispatches
(``pallas_step._fused_round`` — shared, not duplicated), so node counts
may differ from the composite step while every verdict stays sound.

Reference bar: SURVEY.md §7.2 step 6 ("N-queens/pentomino on the same
kernel"); the reference's one kernel (``/root/reference/DHT_Node.py:
474-538``) was its only engine for everything it could express.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from distributed_sudoku_solver_tpu.models.cover import ExactCoverCSP, _unpack_bits
from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
    _VMEM,
    VMEM_LIMIT_BYTES,
    _interpret_default,
    _vmem_params,
)

_BIG = 1 << 22  # f32-exact sentinel for row/column argmin keys

# meta rows: input state [8, T]; output state + per-dispatch deltas [16, T]
_HAS, _BASE, _COUNT = 0, 1, 2
_SOLVED, _OVER, _NODES, _SOLS, _SWEEPS, _STEPS = 3, 4, 5, 6, 7, 8
_LIVE = 9  # rounds the lane held live work this dispatch (occupancy row)


# Rows per in-kernel block.  A compile-boundary sweep on v5e (synthetic
# instances, S=8, 128 lanes) put the wall between R'=1024 (compiles) and
# R'=1536 (tpu_compile_helper exit 1) for the UNBLOCKED dataflow — the
# scoped-VMEM working set scales with the unpacked row tensor, and lane
# tiles below 128 don't help (the lane dim pads to 128 regardless).  The
# kernel therefore streams the row space in <= 1024-row word-aligned
# blocks, keeping ``avail`` packed between passes; instances of any row
# count compile, paying one extra unpack per pass.
_BLOCK_WORDS = 32


class CoverConsts(NamedTuple):
    """Per-instance constant matrices the kernel consumes (host numpy).

    The row space is padded to ``n_blocks * _BLOCK_WORDS * 32`` rows so
    every block shares one selector/weight set; padding rows have all-zero
    incidence and are never available."""

    inc_full: np.ndarray  # f32[R', C_full] full incidence (primary first)
    sel_b: np.ndarray  # f32[BR, BW]  word selector for one row block
    wlo_b: np.ndarray  # f32[BW, BR]  pack weights, bits 0-15
    whi_b: np.ndarray  # f32[BW, BR]  pack weights, bits 16-31
    sel_c: np.ndarray  # f32[C', W_c]
    wlo_c: np.ndarray  # f32[W_c, C']
    whi_c: np.ndarray  # f32[W_c, C']


def _sel_weights(w: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    kp = w * 32
    sel = np.zeros((kp, w), np.float32)
    sel[np.arange(kp), np.arange(kp) // 32] = 1.0
    wlo = np.zeros((w, kp), np.float32)
    whi = np.zeros((w, kp), np.float32)
    r = np.arange(kp)
    bit = r % 32
    lo = bit < 16
    wlo[r[lo] // 32, r[lo]] = (1 << bit[lo]).astype(np.float32)
    whi[r[~lo] // 32, r[~lo]] = (1 << (bit[~lo] - 16)).astype(np.float32)
    return sel, wlo, whi


def cover_block_words(problem: ExactCoverCSP) -> int:
    """Words per row block: whole packed width when it fits one block."""
    return min(_BLOCK_WORDS, problem.w_rows)


@functools.lru_cache(maxsize=32)
def cover_consts(problem: ExactCoverCSP) -> CoverConsts:
    if problem.incidence is None:
        raise ValueError(
            "fused cover kernel needs the full incidence matrix; rebuild the "
            "instance via models.cover.build_cover (older pickles lack it)"
        )
    # Sentinel-soundness admission (ADVICE r5): every argmin key in the
    # kernel must stay strictly below the _BIG sentinel AND inside f32-exact
    # integer range (the keys flow through HIGHEST-precision f32 matmuls,
    # exact only < 2^24).  The branch key is cnt * n_primary + column index
    # (cnt <= n_rows, column index < n_cols_full-padded) and row keys run to
    # the padded row count; past either bound a real key collides with the
    # sentinel and argmin silently picks a wrong branch/row — corrupt
    # SEARCH RESULTS, not a crash, so oversized instances must fail loudly
    # here instead.
    bw_adm = cover_block_words(problem)
    r_pad_adm = -(-problem.w_rows // bw_adm) * bw_adm * 32
    key_ceiling = problem.n_rows * problem.n_primary + problem.n_cols_full
    if key_ceiling >= _BIG or r_pad_adm >= _BIG:
        raise ValueError(
            f"fused cover kernel cannot serve {problem.name!r}: argmin key "
            f"range (rows {problem.n_rows} x primary {problem.n_primary} + "
            f"cols {problem.n_cols_full} = {key_ceiling}, padded rows "
            f"{r_pad_adm}) reaches the f32-exact sentinel bound {_BIG}; "
            "use the composite engine (step_impl='xla') for instances this "
            "large"
        )
    inc = _unpack_bits(
        problem.incidence, problem.n_cols_full
    ).astype(np.float32)  # [R, C_full]
    bw = cover_block_words(problem)
    n_blocks = -(-problem.w_rows // bw)
    r_pad = n_blocks * bw * 32
    inc_full = np.zeros((r_pad, inc.shape[1]), np.float32)
    inc_full[: inc.shape[0]] = inc
    sel_b, wlo_b, whi_b = _sel_weights(bw)
    sel_c, wlo_c, whi_c = _sel_weights(problem.w_cols)
    # covered unpacks to c_pad = w_cols*32 rows; rows beyond n_primary
    # unpack pad bits that are always zero — harmless.
    return CoverConsts(
        inc_full=inc_full,
        sel_b=sel_b,
        wlo_b=wlo_b,
        whi_b=whi_b,
        sel_c=sel_c,
        wlo_c=wlo_c,
        whi_c=whi_c,
    )


def _f32(x_i: jax.Array) -> jax.Array:
    return x_i.astype(jnp.float32)


# XLA:TPU computes f32 dots at reduced precision by default (bf16 input
# passes, 8-bit mantissa): the 16-bit word values flowing through the
# unpack matmuls round to garbage — observed as a spurious "forced" take
# on the 6-queens root in interpret mode on the TPU backend while the
# identical program is exact on CPU.  HIGHEST forces exact f32 products;
# every integer here is < 2^24 so f32 accumulation is exact.
_EXACT = jax.lax.Precision.HIGHEST


def _dot(a, b):
    return jnp.dot(a, b, preferred_element_type=jnp.float32, precision=_EXACT)


def _unpack(packed_u32, sel_f):
    """uint32[W, T] -> int32 0/1 [W*32, T] (word-select matmul + iota shift).

    Casts route through int32: Mosaic has no uint32 -> f32 cast (probed)."""
    k = sel_f.shape[0]
    lo = (packed_u32 & jnp.uint32(0xFFFF)).astype(jnp.int32)
    hi = (packed_u32 >> jnp.uint32(16)).astype(jnp.int32)
    lo_at = _dot(sel_f, _f32(lo))
    hi_at = _dot(sel_f, _f32(hi))
    shift = jax.lax.broadcasted_iota(jnp.int32, (k, packed_u32.shape[-1]), 0) % 32
    lo_i = lo_at.astype(jnp.int32)
    hi_i = hi_at.astype(jnp.int32)
    return jnp.where(shift < 16, (lo_i >> shift) & 1, (hi_i >> (shift - 16)) & 1)


def _pack(bits_i, wlo_f, whi_f):
    """int32 0/1 [W*32, T] -> uint32[W, T] (two 16-bit weight matmuls)."""
    bf = _f32(bits_i)
    lo = _dot(wlo_f, bf)
    hi = _dot(whi_f, bf)
    return lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << jnp.uint32(16)
    )


def _rep(row_1t: jax.Array, k: int) -> jax.Array:
    """int32[1, T] -> int32[k, T] via ones-matmul (values must be < 2^24)."""
    ones = jnp.zeros((k, 1), jnp.float32) + 1.0
    return _dot(ones, _f32(row_1t)).astype(jnp.int32)


def _contract_rows(mat_f, x_i):
    """f32[K, C] x int32[K, T] -> int32[C, T]: contract the leading axis."""
    return jax.lax.dot_general(
        mat_f, _f32(x_i), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_EXACT,
    ).astype(jnp.int32)


def _matvec(mat_f, x_i):
    """f32[K, C] x int32[C, T] -> int32[K, T]."""
    return _dot(mat_f, _f32(x_i)).astype(jnp.int32)


def _cover_kernel(
    inc_f_ref, sel_b_ref, wlo_b_ref, whi_b_ref,
    sel_c_ref, wlo_c_ref, whi_c_ref,
    top_ref, stack_ref, meta_ref,
    out_top, out_stack, out_sol, out_meta,
    *,
    n_primary: int,
    w_rows: int,
    max_sweeps: int,
    k_steps: int,
    count_mode: bool,
):
    """Up to ``k_steps`` whole cover rounds for one VMEM lane tile.

    State layout: top/stack rows are the frontier's packed cover state
    (``models/cover.py``: W_r avail words then W_c covered words); per-lane
    scalars ride distinct rows of the int32 ``meta`` block (loop-carried
    [8, T] / [16, T] blocks legalize — probed — unlike [1, T] carries).

    The row space streams in word-aligned blocks (``_BLOCK_WORDS``):
    ``avail`` stays PACKED between passes and each pass unpacks one
    <= 1024-row block at a time — the unblocked dataflow's [R', T] working
    set hits the scoped-VMEM compile wall between R' = 1024 and 1536."""
    inc_f = inc_f_ref[...]  # f32[R', C_full]
    sel_b = sel_b_ref[...]  # f32[BR, BW]
    wlo_b = wlo_b_ref[...]
    whi_b = whi_b_ref[...]
    sel_c = sel_c_ref[...]
    wlo_c = wlo_c_ref[...]
    whi_c = whi_c_ref[...]
    top = top_ref[...]  # uint32[D, T]
    stack = stack_ref[...]  # uint32[S, D, T]
    meta_in = meta_ref[...]  # int32[8, T]

    t = top.shape[-1]
    s = stack.shape[0]
    br, bw = sel_b.shape[0], sel_b.shape[1]
    r_pad = inc_f.shape[0]
    n_blocks = r_pad // br
    w_pad = n_blocks * bw
    c_pad = sel_c.shape[0]
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (c_pad, t), 0)
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (br, t), 0)
    prim = (c_iota < n_primary).astype(jnp.int32)

    def split(packed):
        return packed[:w_rows], packed[w_rows:]

    def pad_words(ap):
        if w_pad == w_rows:
            return ap
        return jnp.concatenate(
            [ap, jnp.zeros((w_pad - w_rows, t), jnp.uint32)], axis=0
        )

    def inc_blk(b):
        return inc_f[b * br : (b + 1) * br]

    def bits_blk(ap, b):
        return _unpack(ap[b * bw : (b + 1) * bw], sel_b)  # int32 0/1 [BR, T]

    def pad_cols(x):
        if x.shape[0] == c_pad:
            return x
        return jnp.concatenate(
            [x, jnp.zeros((c_pad - x.shape[0], t), jnp.int32)], axis=0
        )

    def counts(ap, covered):
        cnt = jnp.zeros((n_primary, t), jnp.int32)
        for b in range(n_blocks):
            cnt = cnt + _contract_rows(
                inc_blk(b)[:, :n_primary], bits_blk(ap, b)
            )
        unc = jnp.where((covered == 0) & (prim > 0), 1, 0)
        return pad_cols(cnt), unc

    def rowmask_blk(ap, b, colsel):
        """Available rows of the per-lane chosen column, within block b."""
        rowm = _matvec(inc_blk(b)[:, :n_primary], colsel[:n_primary])
        return jnp.where((rowm > 0) & (bits_blk(ap, b) > 0), 1, 0)

    def rowmin(ap, colsel):
        """Lowest available row of the chosen column (global index, [1, T])."""
        rmin = jnp.full((1, t), _BIG, jnp.int32)
        for b in range(n_blocks):
            key = jnp.where(
                rowmask_blk(ap, b, colsel) > 0, b_iota + b * br, _BIG
            )
            rmin = jnp.minimum(rmin, jnp.min(key, axis=0, keepdims=True))
        return rmin

    def colset_of(ap, colsel, rmin_rep):
        """Full column set of the selected row ([C_full, T], entries 0/1)."""
        colset = jnp.zeros((inc_f.shape[1], t), jnp.int32)
        for b in range(n_blocks):
            rowsel = jnp.where(
                (b_iota + b * br == rmin_rep)
                & (rowmask_blk(ap, b, colsel) > 0),
                1, 0,
            )
            colset = colset + _contract_rows(inc_blk(b), rowsel)
        return colset

    def apply_take(ap, colsel, rmin_rep, colset, act_take, act_rest=None):
        """Blockwise conflict elimination (and optional row-exclusion rest).

        Returns guess packed [W_r, T] (conflicts of the selected row
        dropped, the row itself kept, where ``act_take``) and, when
        ``act_rest`` is given, rest packed (the selected row excluded)."""
        act_t = _rep(act_take, br)
        act_r = None if act_rest is None else _rep(act_rest, br)
        csat = jnp.minimum(colset, 1)
        g_words, r_words = [], []
        for b in range(n_blocks):
            bits = bits_blk(ap, b)
            mask = rowmask_blk(ap, b, colsel)
            rowsel = jnp.where(
                (b_iota + b * br == rmin_rep) & (mask > 0), 1, 0
            )
            conflict = _matvec(inc_blk(b), csat)
            g_bits = jnp.where(
                (act_t > 0) & (conflict > 0) & (rowsel == 0), 0, bits
            )
            g_words.append(_pack(g_bits, wlo_b, whi_b))
            if act_r is not None:
                r_bits = jnp.where((act_r > 0) & (rowsel > 0), 0, bits)
                r_words.append(_pack(r_bits, wlo_b, whi_b))
        guess = jnp.concatenate(g_words, axis=0)[:w_rows]
        rest = (
            None if act_r is None
            else jnp.concatenate(r_words, axis=0)[:w_rows]
        )
        return guess, rest

    def covered_after(covered, colset, act_take):
        act_c = _rep(act_take, c_pad)
        return covered | jnp.where(
            act_c > 0, jnp.minimum(pad_cols(colset[:n_primary]), 1), 0
        )

    def body(c):
        top, stack, meta, sol, k = c
        has = meta[_HAS : _HAS + 1]  # [1, T] 0/1
        base = meta[_BASE : _BASE + 1]
        cnt_s = meta[_COUNT : _COUNT + 1]
        avail_p, cov_p = split(top)
        covered = _unpack(cov_p, sel_c)  # [C', T]
        live_w = _rep(has, w_pad)
        ap = jnp.where(live_w > 0, pad_words(avail_p), jnp.uint32(0))

        # -- propagate: one forced take per lane per sweep, to a fixpoint --
        def p_cond(st):
            _, _, changed, sw = st
            return changed & (sw < max_sweeps)

        def p_body(st):
            ap, covered, _, sw = st
            cnt, unc = counts(ap, covered)
            forced = jnp.where((unc > 0) & (cnt == 1), 1, 0)
            has_forced = jnp.max(forced, axis=0, keepdims=True)  # [1, T]
            colsel = lowest_col(forced)
            rmin = rowmin(ap, colsel)
            rmin_rep = _rep(rmin, br)
            colset = colset_of(ap, colsel, rmin_rep)
            guess, _ = apply_take(ap, colsel, rmin_rep, colset, has_forced)
            covered = covered_after(covered, colset, has_forced)
            return (
                pad_words(guess), covered, jnp.any(has_forced > 0), sw + 1
            )

        def lowest_col(mask_i):
            key = jnp.where(mask_i > 0, c_iota, _BIG)
            kmin_rep = _rep(jnp.min(key, axis=0, keepdims=True), c_pad)
            return jnp.where((c_iota == kmin_rep) & (mask_i > 0), 1, 0)

        ap, covered, _, n_sweeps = jax.lax.while_loop(
            p_cond, p_body, (ap, covered, jnp.bool_(True), jnp.int32(0))
        )

        # -- status ---------------------------------------------------------
        cnt, unc = counts(ap, covered)
        contra_1t = jnp.max(
            jnp.where((unc > 0) & (cnt == 0), 1, 0), axis=0, keepdims=True
        )
        any_unc = jnp.max(unc, axis=0, keepdims=True)
        slv = jnp.where((any_unc == 0) & (contra_1t == 0) & (has > 0), 1, 0)
        con = jnp.where((contra_1t > 0) & (has > 0), 1, 0)

        # -- solution capture ----------------------------------------------
        state_p = jnp.concatenate(
            [ap[:w_rows], _pack(covered, wlo_c, whi_c)], axis=0
        )
        solved_f = meta[_SOLVED : _SOLVED + 1]
        newly = jnp.where((slv > 0) & (solved_f == 0), 1, 0)
        d = state_p.shape[0]
        newly_d = _rep(newly, d)
        sol = jnp.where(newly_d > 0, state_p, sol)
        solved_f = jnp.maximum(solved_f, slv)
        sols_row = meta[_SOLS : _SOLS + 1] + (slv if count_mode else 0)

        # -- branch: MRV column, lowest-row guess vs row-exclusion rest ----
        undecided = jnp.where((has > 0) & (slv == 0) & (con == 0), 1, 0)
        branchable = jnp.where((unc > 0) & (cnt >= 1), 1, 0)
        bkey = jnp.where(
            branchable > 0, cnt * n_primary + c_iota, _BIG
        )
        bmin = jnp.min(bkey, axis=0, keepdims=True)
        bmin_rep = _rep(bmin, c_pad)
        colsel = jnp.where((bkey == bmin_rep) & (branchable > 0), 1, 0)
        rmin = rowmin(ap, colsel)
        rmin_rep = _rep(rmin, br)
        colset = colset_of(ap, colsel, rmin_rep)
        g_ap, rest_ap = apply_take(
            ap, colsel, rmin_rep, colset, undecided, act_rest=undecided
        )
        g_covered = covered_after(covered, colset, undecided)
        cov_words = _pack(covered, wlo_c, whi_c)
        rest_p = jnp.concatenate([rest_ap, cov_words], axis=0)
        guess_p = jnp.concatenate(
            [g_ap, _pack(g_covered, wlo_c, whi_c)], axis=0
        )

        # -- push rest ------------------------------------------------------
        can_push = jnp.where((undecided > 0) & (cnt_s < s), 1, 0)
        push_slot = (base + cnt_s) % s
        push_slot_d = _rep(push_slot, d)
        can_push_d = _rep(can_push, d)
        stack = jnp.concatenate(
            [
                jnp.where(
                    ((push_slot_d == i) & (can_push_d > 0))[None],
                    rest_p[None],
                    stack[i : i + 1],
                )
                for i in range(s)
            ],
            axis=0,
        )
        over_row = jnp.maximum(
            meta[_OVER : _OVER + 1],
            jnp.where((undecided > 0) & (can_push == 0), 1, 0),
        )
        nodes_row = meta[_NODES : _NODES + 1] + undecided

        # -- pop ------------------------------------------------------------
        resolved = jnp.maximum(con, slv) if count_mode else con
        can_pop = jnp.where((resolved > 0) & (cnt_s > 0), 1, 0)
        pop_slot = (base + cnt_s - 1) % s
        pop_slot_d = _rep(pop_slot, d)
        can_pop_d = _rep(can_pop, d)
        popped = jnp.zeros_like(top)
        for i in range(s):
            popped = popped | jnp.where(
                (pop_slot_d == i) & (can_pop_d > 0), stack[i], jnp.uint32(0)
            )

        und_d = _rep(undecided, d)
        new_top = jnp.where(und_d > 0, guess_p, state_p)
        new_top = jnp.where(can_pop_d > 0, popped, new_top)
        if count_mode:
            new_has = jnp.where(
                (has > 0) & ((resolved == 0) | (can_pop > 0)), 1, 0
            )
        else:
            new_has = jnp.where(
                (has > 0) & (slv == 0) & ((resolved == 0) | (can_pop > 0)),
                1, 0,
            )
        new_cnt = cnt_s + can_push - can_pop

        meta = jnp.concatenate(
            [
                new_has,
                base,
                new_cnt,
                solved_f,
                over_row,
                nodes_row,
                sols_row,
                meta[_SWEEPS : _SWEEPS + 1] + n_sweeps,
                meta[_STEPS : _STEPS + 1] + 1,
                meta[_LIVE : _LIVE + 1] + has,  # occupancy counter row
                jnp.zeros((16 - 10, t), jnp.int32),
            ],
            axis=0,
        )
        return new_top, stack, meta, sol, k + 1

    meta = jnp.concatenate(
        [meta_in, jnp.zeros((16 - meta_in.shape[0], t), jnp.int32)], axis=0
    )
    sol0 = jnp.zeros_like(top)

    def cond(c):
        _, _, meta, _, k = c
        return jnp.any(meta[_HAS] > 0) & (k < k_steps)

    top, stack, meta, sol, _ = jax.lax.while_loop(
        cond, body, (top, stack, meta, sol0, jnp.int32(0))
    )
    out_top[...] = top
    out_stack[...] = stack
    out_sol[...] = sol
    out_meta[...] = meta


@functools.partial(
    jax.jit,
    static_argnames=(
        "problem", "max_sweeps", "k_steps", "tile", "count_mode", "interpret"
    ),
)
def cover_fused_rounds(
    top_t: jax.Array,  # uint32[1, D, L]
    stack_t: jax.Array,  # uint32[S, 1, D, L]
    has_top: jax.Array,  # bool[L]
    base: jax.Array,  # int32[L]
    count: jax.Array,  # int32[L]
    problem: ExactCoverCSP,
    max_sweeps: int = 64,
    k_steps: int = 8,
    tile: int = 128,
    count_mode: bool = False,
    interpret: bool | None = None,
):
    """Advance every lane up to ``k_steps`` cover rounds in VMEM tiles.

    Same 13-tuple contract as ``pallas_step.fused_rounds`` (including the
    per-lane live-rounds occupancy row) so the shared XLA driver
    (``_fused_round``: harvest/purge/steal between dispatches) serves both
    kernels unchanged."""
    n_lanes = top_t.shape[-1]
    d = top_t.shape[1]
    s = stack_t.shape[0]
    interp = _interpret_default() if interpret is None else interpret
    tile = min(tile, n_lanes)
    if n_lanes % tile:
        raise ValueError(f"lanes {n_lanes} not a multiple of tile {tile}")
    n_tiles = n_lanes // tile

    consts = cover_consts(problem)
    meta = jnp.concatenate(
        [
            has_top.astype(jnp.int32)[None],
            base.astype(jnp.int32)[None],
            count.astype(jnp.int32)[None],
            jnp.zeros((5, n_lanes), jnp.int32),
        ],
        axis=0,
    )
    kernel = functools.partial(
        _cover_kernel,
        n_primary=problem.n_primary,
        w_rows=problem.w_rows,
        max_sweeps=max_sweeps,
        k_steps=k_steps,
        count_mode=count_mode,
    )
    vmem = dict(memory_space=_VMEM) if (_VMEM is not None and not interp) else {}
    lane_spec = lambda *lead: pl.BlockSpec(  # noqa: E731
        (*lead, tile), lambda i: (*(0,) * len(lead), i), **vmem
    )
    const_spec = lambda a: pl.BlockSpec(  # noqa: E731
        a.shape, lambda i: (0,) * a.ndim, **vmem
    )
    # The default scoped-vmem limit (16 MB) is what multi-block instances
    # hit first — pentomino 6x10 missed it by 396 KB with everything else
    # in place (``pallas_propagate._vmem_params``).
    out_top, out_stack, out_sol, out_meta = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        **_vmem_params(interp),
        in_specs=[
            *(const_spec(np.asarray(c)) for c in consts),
            lane_spec(d),
            lane_spec(s, d),
            lane_spec(8),
        ],
        out_specs=(
            lane_spec(d),
            lane_spec(s, d),
            lane_spec(d),
            lane_spec(16),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((d, n_lanes), jnp.uint32),
            jax.ShapeDtypeStruct((s, d, n_lanes), jnp.uint32),
            jax.ShapeDtypeStruct((d, n_lanes), jnp.uint32),
            jax.ShapeDtypeStruct((16, n_lanes), jnp.int32),
        ),
        interpret=interp,
    )(
        *(jnp.asarray(c) for c in consts),
        top_t[0],
        stack_t[:, 0],
        meta,
    )

    tile_starts = jnp.arange(n_tiles, dtype=jnp.int32) * tile
    sweeps_total = jnp.sum(out_meta[_SWEEPS][tile_starts])
    steps_max = jnp.max(out_meta[_STEPS][tile_starts])
    return (
        out_top[None],
        out_stack[:, None],
        out_meta[_HAS] > 0,
        out_meta[_BASE],
        out_meta[_COUNT],
        out_meta[_SOLVED] > 0,
        out_sol[None],
        out_meta[_OVER] > 0,
        out_meta[_NODES],
        out_meta[_SOLS],
        out_meta[_LIVE],
        sweeps_total,
        steps_max,
    )


def _rounds_fn(problem: ExactCoverCSP, config, lanes: int):
    def rounds(f):
        return cover_fused_rounds(
            f.top_t, f.stack_t, f.has_top, f.base, f.count,
            problem,
            max_sweeps=config.max_sweeps,
            k_steps=config.fused_steps,
            tile=min(128, lanes),
            count_mode=config.count_all,
        )

    return rounds


@functools.partial(jax.jit, static_argnames=("problem", "config"))
def advance_cover_fused(state, step_limit: jax.Array, problem, config):
    """Cover twin of ``pallas_step.advance_frontier_fused``: advance a
    lane-first generic frontier by fused dispatches until every job
    resolves or ``steps`` reaches ``step_limit`` (dynamic — the stepped
    drivers pass successive limits against one compiled program, keeping
    each device dispatch wall-bounded for the watchdog discipline).

    The cover kernel keeps the shallow ``fused_steps`` default on EVERY
    surface: the r5 re-measurement ran 16/32 within noise on both the
    winning (queens) and losing (pentomino) rows, so the deep
    device-resident default the Sudoku kernel adopted has no measured
    payoff here (BENCHMARKS.md round 5)."""
    from distributed_sudoku_solver_tpu.ops.frontier import FUSED_STEPS_LINKED
    from distributed_sudoku_solver_tpu.ops.pallas_step import (
        _run_fused,
        frontier_to_fused,
        fused_to_frontier,
    )

    config = config.with_fused_steps(FUSED_STEPS_LINKED)
    limit = jnp.minimum(jnp.int32(step_limit), jnp.int32(config.max_steps))
    lanes = state.has_top.shape[0]
    fs = frontier_to_fused(state)
    fs = _run_fused(
        fs, None, config, limit, rounds_fn=_rounds_fn(problem, config, lanes)
    )
    return fused_to_frontier(fs)


# Scoped-VMEM ceiling the kernels compile against — the same constant
# _vmem_params hands Mosaic (imported at the top), so the admission check
# and the compiler limit can never disagree.
_VMEM_CEILING_BYTES = VMEM_LIMIT_BYTES


def cover_vmem_bytes(problem: ExactCoverCSP, stack_slots: int, tile: int = 128) -> int:
    """Lower-bound estimate of one lane tile's scoped-VMEM working set.

    Counts what provably must be resident: the constant matrices
    (incidence + pack/unpack selectors), the in/out state blocks (top,
    stack, solution, meta), and the per-block streaming temporaries the
    kernel body keeps live (~10 [BR, T] int32 tensors plus the column-space
    tensors).  Deliberately a LOWER bound — Mosaic's own temporaries only
    add to it — so exceeding the ceiling here is a proof of non-compilation,
    never a false rejection of a shape the kernel could serve."""
    bw = cover_block_words(problem)
    br = bw * 32
    r_pad = -(-problem.w_rows // bw) * bw * 32
    # Full UNPACKED column count: cover_consts unpacks the bit-packed
    # incidence to [R', n_cols_full] f32 — problem.incidence.shape[1] is
    # the packed word count, 32x smaller, and would gut the estimate.
    c_full = max(problem.n_cols_full, problem.n_primary)
    c_pad = problem.w_cols * 32
    d = problem.w_rows + problem.w_cols
    t = min(tile, 128)
    consts = (
        r_pad * c_full  # inc_full
        + 3 * br * bw  # sel_b / wlo_b / whi_b
        + 3 * c_pad * problem.w_cols  # sel_c / wlo_c / whi_c
    )
    state = t * (2 * stack_slots * d + 3 * d + 2 * 16)  # stack io + top/sol + meta
    working = t * (10 * br + 3 * c_pad + 2 * c_full)
    return 4 * (consts + state + working)


def cover_fused_lanes(
    n_lanes: int,
    problem: Optional[ExactCoverCSP] = None,
    stack_slots: Optional[int] = None,
) -> int:
    """Round a cover lane count to a fused-kernel-valid width (128-multiples
    beyond one whole-array tile, the Mosaic lane-tiling rule).

    With ``problem`` + ``stack_slots`` this is also the launch-time
    admission check mirroring ``pallas_step.fused_lanes`` (ADVICE r5): a
    (instance, stack) shape whose tile working set provably overflows the
    scoped-VMEM ceiling raises an actionable ``ValueError`` HERE instead of
    an opaque Mosaic scoped-VMEM failure at first dispatch."""
    if problem is not None and stack_slots is not None:
        est = cover_vmem_bytes(problem, stack_slots)
        if est > _VMEM_CEILING_BYTES:
            raise ValueError(
                f"fused cover kernel tile for {problem.name!r} needs >= "
                f"{est >> 20} MB scoped VMEM at stack_slots={stack_slots} "
                f"(ceiling {_VMEM_CEILING_BYTES >> 20} MB); use "
                "step_impl='xla' or a shallower stack"
            )
    if n_lanes <= 128:
        return n_lanes
    return -(-n_lanes // 128) * 128


@functools.partial(jax.jit, static_argnames=("problem", "config"))
def solve_cover_fused(states0: jax.Array, problem: ExactCoverCSP, config):
    """Fused-step cover solve: ``solve_csp``'s contract under fused rounds.

    Root states [J, 1, D] (packed avail/covered, ``models/cover.py``); the
    solution field of the result is the raw solved state, decodable with
    the family's ``chosen_rows``/``decode_*`` helpers, exactly like the
    composite path."""
    import dataclasses

    from distributed_sudoku_solver_tpu.ops.frontier import (
        FUSED_STEPS_LINKED,
        init_frontier,
    )
    from distributed_sudoku_solver_tpu.ops.solve import finalize_frontier
    from distributed_sudoku_solver_tpu.ops.pallas_step import (
        _run_fused,
        frontier_to_fused,
        fused_to_frontier,
    )

    # Cover keeps the shallow default everywhere (see advance_cover_fused).
    config = config.with_fused_steps(FUSED_STEPS_LINKED)
    n_jobs = states0.shape[0]
    lanes = cover_fused_lanes(
        config.resolve_lanes(n_jobs), problem, config.stack_slots
    )
    config = dataclasses.replace(config, lanes=lanes)

    state = init_frontier(states0, config)
    fs = frontier_to_fused(state)
    fs = _run_fused(
        fs, None, config, jnp.int32(config.max_steps),
        rounds_fn=_rounds_fn(problem, config, lanes),
    )
    return finalize_frontier(fused_to_frontier(fs))
