"""distributed_sudoku_solver_tpu — TPU-native constraint-satisfaction framework.

A brand-new JAX / XLA / Pallas / pjit framework with the capabilities of the
reference P2P distributed Sudoku solver (see SURVEY.md): batched bitmask
constraint propagation + speculative-parallel search on TPU, sharded over a
device mesh, fronted by the reference-compatible HTTP API.

Layer map (TPU-native re-design of SURVEY.md §1):

  L0  compute kernel   ops/            jit-compiled bitmask propagation + frontier step
  L2  scheduler        ops/solve.py    frontier tensor IS the work pool; branching,
                                       stealing and cancellation are in-graph
  L2' multi-chip       parallel/       shard_map over a Mesh; steal/solved
                                       broadcast as ICI collectives
  L3  membership/FT    cluster/        typed TCP control plane (join, heartbeat,
                                       failure detection, re-dispatch)
  L4  client API       serving/        engine job queue + POST /solve, GET /stats,
                                       GET /network
  L5  CLI/config       cli.py, models/geometry.py
  --  native oracle    native/         C++ bit-exact CPU reference (ctypes-bound)
"""

__version__ = "0.1.0"

# Lazy top-level exports (PEP 562): importing the bare package must stay
# jax-free.  The geometry conveniences used to be eager, which pulled
# jax.numpy into EVERY `import distributed_sudoku_solver_tpu` — including
# `python -m distributed_sudoku_solver_tpu.analysis`, whose whole
# contract is "stdlib ast, <5 s, no jax import" (tests/test_analysis.py
# pins it).  `from distributed_sudoku_solver_tpu import Geometry` still
# works; it just resolves on first touch.
_GEOMETRY_EXPORTS = (
    "Geometry",
    "SUDOKU_4",
    "SUDOKU_9",
    "SUDOKU_16",
    "SUDOKU_25",
    "geometry_for_size",
)


def __getattr__(name: str):
    if name in _GEOMETRY_EXPORTS:
        from distributed_sudoku_solver_tpu.models import geometry

        return getattr(geometry, name)
    # Attribute-style subpackage access (`pkg.models` after a bare
    # `import distributed_sudoku_solver_tpu`) used to work as a side
    # effect of the eager geometry import; keep it working lazily.
    import importlib

    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError as e:
        if e.name == f"{__name__}.{name}":
            # The submodule itself does not exist: a genuine attribute
            # miss.  Anything else (e.g. jax absent inside an existing
            # submodule) is a real import failure and must surface as
            # one, not be masked as an AttributeError.
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}"
            ) from None
        raise


def __dir__():
    return sorted(list(globals()) + list(_GEOMETRY_EXPORTS))
