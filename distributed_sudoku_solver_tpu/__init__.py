"""distributed_sudoku_solver_tpu — TPU-native constraint-satisfaction framework.

A brand-new JAX / XLA / Pallas / pjit framework with the capabilities of the
reference P2P distributed Sudoku solver (see SURVEY.md): batched bitmask
constraint propagation + speculative-parallel search on TPU, sharded over a
device mesh, fronted by the reference-compatible HTTP API.

Layer map (TPU-native re-design of SURVEY.md §1):

  L0  compute kernel   ops/            jit-compiled bitmask propagation + frontier step
  L2  scheduler        ops/solve.py    frontier tensor IS the work pool; branching,
                                       stealing and cancellation are in-graph
  L2' multi-chip       parallel/       shard_map over a Mesh; steal/solved
                                       broadcast as ICI collectives
  L3  membership/FT    cluster/        typed TCP control plane (join, heartbeat,
                                       failure detection, re-dispatch)
  L4  client API       serving/        engine job queue + POST /solve, GET /stats,
                                       GET /network
  L5  CLI/config       cli.py, models/geometry.py
  --  native oracle    native/         C++ bit-exact CPU reference (ctypes-bound)
"""

__version__ = "0.1.0"

from distributed_sudoku_solver_tpu.models.geometry import (  # noqa: F401
    Geometry,
    SUDOKU_4,
    SUDOKU_9,
    SUDOKU_16,
    SUDOKU_25,
    geometry_for_size,
)
