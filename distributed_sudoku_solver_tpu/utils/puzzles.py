"""Puzzle corpus + deterministic generator for tests and benchmarks.

The reference ships no fixtures at all (SURVEY.md §4); its course was driven
by hand-typed grids.  Here we keep (a) a tiny embedded corpus of well-known
public benchmark boards, validated at test time by the oracle, and (b) a
seeded generator able to produce unlimited boards at any geometry — including
the 16x16 / 25x25 configs the reference could never run (its wire format
truncates 25x25 tasks, ``/root/reference/DHT_Node.py:94``, SURVEY.md §2.5 #8).
"""

from __future__ import annotations

import functools as _functools
import os
from typing import Optional

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.utils.oracle import count_solutions as _py_count


# Bump when random_solution/make_puzzle output could change for a given seed:
# it keys the on-disk batch cache, so stale boards are never served.
_GENERATOR_VERSION = 1


def _count_solutions_fast(grid, geom: Optional[Geometry] = None, limit: int = 2) -> int:
    """Uniqueness probe; prefers the native C++ oracle (~1000x the Python
    one — carving a puzzle runs dozens of these, so generation time is
    entirely this call).  Distinct from ``utils.oracle.count_solutions``,
    which stays pure Python on purpose: it is the independent authority the
    native library itself is tested against (tests/test_native.py)."""
    from distributed_sudoku_solver_tpu import native

    if native.available():
        return native.count_solutions(grid, geom, limit=limit)
    return _py_count(grid, geom, limit=limit)


def parse_line(line: str, n: int = 9) -> np.ndarray:
    """Parse an n*n-char puzzle string ('0' or '.' = empty) to int64[n, n]."""
    line = line.strip().replace(".", "0")
    if len(line) != n * n:
        raise ValueError(f"expected {n * n} chars, got {len(line)}")
    vals = [int(ch, 36) for ch in line]  # base36 so 16x16 strings fit one char
    if any(v > n for v in vals):
        raise ValueError(f"digit out of range for a {n}x{n} board")
    return np.asarray(vals, dtype=np.int64).reshape(n, n)


def to_line(grid) -> str:
    g = np.asarray(grid).ravel()
    return "".join(np.base_repr(int(v), 36).lower() for v in g)


# Classic public example board (easy; solvable by propagation alone).
EASY_9 = parse_line(
    "530070000600195000098000060800060003"
    "400803001700020006060000280000419005000080079"
)

# Widely published hard benchmark boards (validated unique by tests).
HARD_9_LINES = [
    # "AI Escargot" (Inkala)
    "100007090030020008009600500005300900010080002600004000300000010040000007007000300",
    # Inkala 2010
    "800000000003600000070090200050007000000045700000100030001000068008500010090000400",
    # 17-clue board popularized by Norvig's solver essay
    "000000010400000000020000000000050407008000300001090000300400200050100000000806000",
]
HARD_9 = [parse_line(s) for s in HARD_9_LINES]


def random_solution(geom: Geometry, seed: int) -> np.ndarray:
    """A uniformly-shuffled valid complete board (deterministic in ``seed``).

    Starts from the standard shifted-pattern Latin construction and applies
    symmetry-preserving shuffles: digit relabel, row/col permutations within
    bands/stacks, band/stack permutations, optional transpose.
    """
    rng = np.random.default_rng(seed)
    n, bh, bw = geom.n, geom.box_h, geom.box_w
    base = np.empty((n, n), dtype=np.int64)
    for r in range(n):
        shift = (r % bh) * bw + (r // bh)
        for c in range(n):
            base[r, c] = (c + shift) % n + 1

    relabel = np.concatenate([[0], rng.permutation(n) + 1])
    base = relabel[base]

    row_order = np.concatenate(
        [band * bh + rng.permutation(bh) for band in rng.permutation(geom.n_vboxes)]
    )
    col_order = np.concatenate(
        [stack * bw + rng.permutation(bw) for stack in rng.permutation(geom.n_hboxes)]
    )
    base = base[row_order][:, col_order]
    if bh == bw and rng.integers(2):
        base = base.T.copy()
    return base


def make_puzzle(
    geom: Geometry,
    seed: int,
    n_clues: Optional[int] = None,
    unique: bool = True,
    max_probe: Optional[int] = None,
) -> np.ndarray:
    """Carve a puzzle out of a random solution (deterministic in ``seed``).

    Removes cells in a random order down toward ``n_clues`` givens; with
    ``unique=True`` every removal is checked to preserve solution uniqueness
    (skipping removals that would break it), so the result is always a proper
    puzzle — possibly with more clues than requested if the target is
    unreachable along this removal order.
    """
    sol = random_solution(geom, seed)
    rng = np.random.default_rng(seed + 0x9E3779B9)
    n = geom.n
    if n_clues is None:
        n_clues = int(n * n * 0.35)
    puzzle = sol.copy()
    order = rng.permutation(n * n)
    remaining = n * n
    probes = 0
    for idx in order:
        if remaining <= n_clues:
            break
        if max_probe is not None and probes >= max_probe:
            break
        r, c = divmod(int(idx), n)
        saved = puzzle[r, c]
        puzzle[r, c] = 0
        if unique:
            probes += 1
            if _count_solutions_fast(puzzle, geom, limit=2) != 1:
                puzzle[r, c] = saved
                continue
        remaining -= 1
    return puzzle


def batch_cache_path(
    geom: Geometry,
    count: int,
    seed: int = 0,
    n_clues: Optional[int] = None,
    unique: bool = True,
    cache_dir: Optional[str] = None,
) -> Optional[str]:
    """On-disk cache path :func:`puzzle_batch` uses for these parameters
    (None when no cache dir is configured) — the single definition of the
    key format, shared with out-of-process generators
    (``benchmarks/pregen_corpus.py``) so a key-format change can never
    silently orphan a pre-generated corpus."""
    cache_dir = cache_dir or os.environ.get("DSST_PUZZLE_CACHE")
    if not cache_dir:
        return None
    key = (
        f"v{_GENERATOR_VERSION}_{geom.box_h}x{geom.box_w}"
        f"_{count}_{seed}_{n_clues}_{int(unique)}"
    )
    return os.path.join(cache_dir, f"puzzles_{key}.npy")


def puzzle_batch(
    geom: Geometry,
    count: int,
    seed: int = 0,
    n_clues: Optional[int] = None,
    unique: bool = True,
    cache_dir: Optional[str] = None,
) -> np.ndarray:
    """Stack ``count`` generated puzzles into int64[count, n, n].

    With ``cache_dir`` (or env ``DSST_PUZZLE_CACHE``), the batch is memoized
    on disk keyed by every generation parameter — benchmarks regenerate
    nothing across runs.  Generation is deterministic, so the cache changes
    results never, only latency.
    """
    path = batch_cache_path(geom, count, seed, n_clues, unique, cache_dir)
    if path and os.path.exists(path):
        return np.load(path)
    batch = np.stack(
        [make_puzzle(geom, seed + i, n_clues=n_clues, unique=unique) for i in range(count)]
    )
    if path:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # np.save appends '.npy' unless the name already ends with it.
        tmp = f"{path}.{os.getpid()}.tmp.npy"
        np.save(tmp, batch)
        os.replace(tmp, path)
    return batch


@_functools.lru_cache(maxsize=None)
def solved_board(geom: Geometry) -> np.ndarray:
    """A complete valid board for ``geom`` (cached; read-only).

    The canonical zero-work padding job: batch paths pad partial chunks with
    it so the padding lanes resolve on step one and join the steal pool as
    thieves for the real jobs.
    """
    board = random_solution(geom, seed=0).astype(np.int32)
    board.setflags(write=False)
    return board
