"""Bulk puzzle dataset IO: file -> int32 batches -> bulk solver -> file.

The data-loader layer of the framework (the reference has none — every board
arrives as one hand-POSTed HTTP body, ``/root/reference/DHT_Node.py:546-549``).
Parsing is delegated to the multithreaded native loader
(``native/src/loader.cc``) when available, with a pure-Python fallback, and
batches stream so a million-board file never materializes as Python objects.

File format: one board per line, n*n chars, '.' or '0' = empty, digits then
lowercase base-36 letters ('a'=10) for larger geometries; Kaggle-style CSVs
work too (first comma-separated field is the board, header auto-skipped).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.utils.puzzles import parse_line, to_line


def _parse_python(data: bytes, n: int, allow_header: bool) -> np.ndarray:
    boards = []
    lines = [ln.strip() for ln in data.decode().splitlines() if ln.strip()]
    for i, raw in enumerate(lines):
        field = raw.split(",")[0].strip()
        # Header semantics must match loader.cc exactly: only a first line
        # whose field *length* differs from n*n may be skipped as a header;
        # a right-length line with a bad character is an error anywhere.
        if i == 0 and allow_header and len(field) != n * n:
            continue
        try:
            boards.append(parse_line(field, n))
        except ValueError:
            raise ValueError(f"malformed board at data line {len(boards)}")
    if not boards:
        return np.zeros((0, n, n), dtype=np.int32)
    return np.stack(boards).astype(np.int32)


def parse_boards(data: bytes, geom: Geometry, allow_header: bool = True) -> np.ndarray:
    """Board lines -> int32[B, n, n]; native multithreaded parse if possible.

    ``allow_header=False`` forbids the skip-unparseable-first-line heuristic,
    so a malformed line raises instead of being dropped — used for every
    chunk after the first when streaming, to keep output line-aligned.
    """
    from distributed_sudoku_solver_tpu import native

    if native.available():
        return native.parse_boards(data, geom.n, allow_header=allow_header)
    return _parse_python(data, geom.n, allow_header)


def load_boards(path: str, geom: Geometry) -> np.ndarray:
    with open(path, "rb") as f:
        return parse_boards(f.read(), geom)


def iter_board_batches(
    path: str, geom: Geometry, batch: int = 65536
) -> Iterator[np.ndarray]:
    """Stream ``[<=batch, n, n]`` arrays from a board file of any size.

    Reads in ~batch-line byte chunks aligned to line boundaries, so memory
    stays O(batch) regardless of file size.
    """
    # +2 covers a solutions CSV column; a too-small guess only means more
    # read calls, never wrong results (the remainder carries over).
    approx_line = 2 * geom.n * geom.n + 2
    chunk_bytes = batch * approx_line
    with open(path, "rb") as f:
        rest = b""
        first = True
        n_done = 0
        while True:
            blob = f.read(chunk_bytes)
            if not blob:
                break
            data = rest + blob
            cut = data.rfind(b"\n")
            if cut < 0:
                rest = data
                continue
            rest, data = data[cut + 1 :], data[: cut + 1]
            # Only the true file head may hold a header line; later chunks
            # must parse every line or raise, to stay line-aligned.
            boards = _parse_chunk(data, geom, allow_header=first, offset=n_done)
            first = False
            n_done += len(boards)
            for lo in range(0, len(boards), batch):
                yield boards[lo : lo + batch]
        if rest.strip():
            boards = _parse_chunk(rest + b"\n", geom, allow_header=first, offset=n_done)
            for lo in range(0, len(boards), batch):
                yield boards[lo : lo + batch]


def _parse_chunk(data: bytes, geom: Geometry, allow_header: bool, offset: int):
    """parse_boards, rewriting chunk-relative error indices to file-absolute."""
    try:
        return parse_boards(data, geom, allow_header=allow_header)
    except ValueError as e:
        import re

        m = re.search(r"data line (\d+)", str(e))
        if m:
            raise ValueError(
                f"malformed board at data line {offset + int(m.group(1))}"
            ) from None
        raise


def save_boards(path: str, boards) -> None:
    """int[B, n, n] -> one base-36 line per board (atomic replace)."""
    g = np.ascontiguousarray(np.asarray(boards), dtype=np.int32)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(_format_lines(g))
    os.replace(tmp, path)


def _format_lines(boards: np.ndarray) -> bytes:
    from distributed_sudoku_solver_tpu import native

    if native.available():
        return native.format_boards(boards)
    return ("\n".join(to_line(b) for b in boards) + "\n").encode() if len(boards) else b""


def solve_file(
    in_path: str,
    out_path: Optional[str],
    geom: Geometry,
    batch: int = 65536,
    bulk_config=None,
    resume: bool = True,
):
    """Solve every board in a file; returns aggregate stats.

    With ``out_path``, solutions are written line-aligned with the input
    (unsolved lines all-zeros), streamed batch-by-batch to a temp file and
    atomically renamed — peak memory stays O(batch) end to end.

    **Crash-resumable** (the reference re-solves everything after any crash;
    here a sidecar ``{out_path}.progress`` records boards done, output bytes
    flushed, and running stats after every batch).  A rerun with ``resume``
    truncates the partial output to the last recorded byte, skips the
    already-solved boards, and appends — producing a byte-identical file to
    an uninterrupted run (solves are deterministic).  Both sidecars are
    removed on success.

    Stats: ``unresolved`` counts boards that exhausted every escalation rung
    (possible at 16x16/25x25 with tight ``max_steps``) — they end neither
    solved nor unsat and are written as all-zero lines, indistinguishable
    from unsat lines in the output file, so only this count exposes them.
    """
    import hashlib
    import json

    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk

    cfg = bulk_config or BulkConfig()
    stats = {"total": 0, "solved": 0, "unsat": 0, "searched": 0}
    tmp = f"{out_path}.partial" if out_path else None
    prog_path = f"{out_path}.progress" if out_path else None

    # A progress sidecar only matches a run with the same input file (head
    # hash + size), geometry, batch and solver config — resuming someone
    # else's sidecar would silently splice two runs into one output file.
    run_sig = None
    if tmp:
        st = os.stat(in_path)
        with open(in_path, "rb") as f:
            head = hashlib.sha256(f.read(65536)).hexdigest()[:16]
        run_sig = json.dumps(
            {
                "input": [head, st.st_size],
                "geom": [geom.box_h, geom.box_w],
                "batch": batch,
                "config": repr(cfg),
            }
        )

    # Open-then-lock-then-decide: the single lock holder makes every
    # truncate/resume decision, so concurrent runs cannot interleave.
    skip = 0
    out_f = open(tmp, "ab") if tmp else None
    if out_f is not None:
        # One writer per output path: resume needs a stable partial-file
        # name, so concurrent runs would otherwise interleave appends.
        # flock releases on crash; a second writer fails loudly instead.
        import fcntl

        try:
            fcntl.flock(out_f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            out_f.close()
            raise RuntimeError(
                f"another solve_file run is writing {out_path!r} "
                f"(lock on {tmp!r} is held)"
            ) from None
        prog = None
        if resume and os.path.exists(prog_path):
            with open(prog_path) as pf:
                prog = json.load(pf)
        if prog is not None and prog.get("run_sig") == run_sig:
            skip = int(prog["boards_done"])
            stats.update(prog["stats"])
            out_f.truncate(int(prog["bytes_done"]))  # drop post-record bytes
        else:  # fresh run, or stale sidecar from a different input/config
            out_f.truncate(0)
        out_f.seek(0, os.SEEK_END)
    # Software pipeline around the device: a reader thread prefetches and
    # parses batch k+1 and a writer thread formats/fsyncs batch k-1 while
    # the device solves batch k — wall clock becomes max(solve, io) instead
    # of their sum (measured: 1M boards 16.4 s serial -> ~12 s overlapped).
    # The writer alone touches the output file and the progress sidecar, in
    # batch order, so the crash-resume contract is unchanged.
    import queue as queue_mod
    import threading

    read_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=2)
    stop_reading = threading.Event()

    def _put_cooperative(item) -> bool:
        """Bounded put that gives up when the consumer is gone — otherwise an
        error path would leak this thread (parked on a full queue forever)
        plus the open input-file handle, one per failed call."""
        while not stop_reading.is_set():
            try:
                read_q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def reader() -> None:
        try:
            for b in iter_board_batches(in_path, geom, batch):
                if not _put_cooperative(b):
                    return
            _put_cooperative(None)
        except BaseException as e:  # noqa: BLE001 - relayed to the main thread
            _put_cooperative(e)

    write_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=2)
    write_err: list = []

    def writer() -> None:
        try:
            while True:
                item = write_q.get()
                if item is None:
                    return
                solution, stats_snapshot = item
                out_f.write(_format_lines(solution))
                out_f.flush()
                os.fsync(out_f.fileno())
                ptmp = f"{prog_path}.tmp"
                with open(ptmp, "w") as pf:
                    json.dump(
                        {
                            "run_sig": run_sig,
                            "boards_done": stats_snapshot["total"],
                            "bytes_done": out_f.tell(),
                            "stats": stats_snapshot,
                        },
                        pf,
                    )
                os.replace(ptmp, prog_path)
        except BaseException as e:  # noqa: BLE001
            write_err.append(e)
            while write_q.get() is not None:  # unblock the producer
                pass

    reader_t = threading.Thread(target=reader, daemon=True, name="solve-file-read")
    reader_t.start()
    writer_t = None
    if out_f:
        writer_t = threading.Thread(
            target=writer, daemon=True, name="solve-file-write"
        )
        writer_t.start()
    try:
        while True:
            boards = read_q.get()
            if boards is None:
                break
            if isinstance(boards, BaseException):
                raise boards
            if skip >= len(boards):  # already solved in the interrupted run
                skip -= len(boards)
                continue
            if skip:
                boards = boards[skip:]
                skip = 0
            res = solve_bulk(boards, geom, cfg)
            stats["total"] += len(boards)
            stats["solved"] += int(res.solved.sum())
            stats["unsat"] += int(res.unsat.sum())
            stats["searched"] += res.searched
            if out_f:
                if write_err:
                    raise write_err[0]
                write_q.put((res.solution, dict(stats)))
        if out_f:
            write_q.put(None)
            writer_t.join()
            if write_err:
                raise write_err[0]
            out_f.close()
            out_f = None
            os.replace(tmp, out_path)
            if os.path.exists(prog_path):
                os.unlink(prog_path)
    finally:
        stop_reading.set()
        reader_t.join(10)
        if out_f:
            if writer_t is not None and writer_t.is_alive():
                write_q.put(None)
                writer_t.join(10)
            if writer_t is None or not writer_t.is_alive():
                out_f.close()  # keep tmp + progress: the next run resumes them
            # else: writer is wedged mid-write (e.g. a stalled fsync) — leave
            # the fd to it rather than close under an in-progress write; the
            # sidecar's bytes_done keeps any later resume byte-exact.
    stats["unresolved"] = stats["total"] - stats["solved"] - stats["unsat"]
    return stats
