"""Checkpoint / resume: the frontier tensor *is* the checkpoint.

The reference has no persistence of any kind — a dead node's in-progress
subtree is recomputed from the delegator's ledger copy (SURVEY.md §5.4,
``/root/reference/DHT_Node.py:201-209``).  Here the entire search state of
every in-flight job is one pytree of device arrays (``ops/frontier.Frontier``),
so checkpointing is: advance the compiled solve in bounded-step chunks,
snapshot the state to host between chunks, and resume = reload + keep
stepping.  No recomputation, ever — a restore continues mid-subtree.

Format: a single ``.npz`` (atomic rename on save) holding every Frontier leaf
plus the static solve signature (geometry + config repr) for mismatch
detection at load time.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import tempfile
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sudoku_solver_tpu.models.geometry import Geometry
from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
from distributed_sudoku_solver_tpu.ops.frontier import (
    Frontier,
    SolverConfig,
    chunk_status,
    frontier_live,
    init_frontier,
    run_frontier,
)
from distributed_sudoku_solver_tpu.ops.solve import SolveResult, _finalize, sudoku_csp


@functools.partial(jax.jit, static_argnames=("geom", "config"))
def start_frontier(grids: jax.Array, geom: Geometry, config: SolverConfig) -> Frontier:
    return init_frontier(encode_grid(grids, geom), config)


@functools.partial(
    jax.jit, static_argnames=("geom", "config"), donate_argnums=(0,)
)
def advance_frontier(
    state: Frontier, step_limit: jax.Array, geom: Geometry, config: SolverConfig
) -> Frontier:
    """Run until every job resolves or ``state.steps`` reaches ``step_limit``.

    ``state`` is DONATED: the input frontier's buffers are reused for the
    output (the stack tensor alone is lanes x S x n^2 uint32 — a fresh copy
    per chunk was hundreds of MB of avoidable HBM traffic at bulk shapes).
    Callers must rebind (``state = advance_frontier(state, ...)``) and never
    touch the old reference again; reads of a donated-away array raise.
    """
    return run_frontier(state, sudoku_csp(geom, config), config, step_limit=step_limit)


@functools.partial(
    jax.jit, static_argnames=("geom", "config"), donate_argnums=(0,)
)
def advance_frontier_status(
    state: Frontier, steps_delta: jax.Array, geom: Geometry, config: SolverConfig
):
    """One serving chunk: advance by at most ``steps_delta`` MORE rounds and
    return ``(new_state, packed status)`` — the composite-step half of the
    one-fetch serving contract (``ops/frontier.chunk_status``).

    The step limit is computed IN-GRAPH (``state.steps + steps_delta``,
    clamped to ``config.max_steps`` by ``run_frontier``), so the host never
    needs the current step counter to dispatch the next chunk — which is
    what lets the serving loops enqueue chunk k+1 before consuming chunk
    k's status.  ``state`` is donated (see :func:`advance_frontier`).
    """
    new = run_frontier(
        state,
        sudoku_csp(geom, config),
        config,
        step_limit=state.steps + jnp.int32(steps_delta),
    )
    return new, chunk_status(state.steps, state.lane_rounds, new)


def frontier_done(state: Frontier) -> bool:
    return not bool(jnp.any(frontier_live(state)))


def _signature(
    geom: Geometry, config: SolverConfig, grids_hash: Optional[str] = None
) -> str:
    return json.dumps(
        {
            "problem": sudoku_csp(geom, config).signature(),
            "config": dataclasses.asdict(config),
            "grids": grids_hash,
        }
    )


def grids_digest(grids) -> str:
    """Content hash of the job batch: a checkpoint resumes only its own inputs."""
    import hashlib

    arr = np.ascontiguousarray(np.asarray(grids, dtype=np.int32))
    return hashlib.sha256(arr.tobytes() + str(arr.shape).encode()).hexdigest()[:16]


def save_frontier(
    path: str,
    state: Frontier,
    geom: Geometry,
    config: SolverConfig,
    grids_hash: Optional[str] = None,
) -> None:
    """Atomic snapshot: device -> host -> tmpfile -> rename."""
    host = {k: np.asarray(v) for k, v in state._asdict().items()}
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)) or ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(
                f, __signature__=np.frombuffer(
                    _signature(geom, config, grids_hash).encode(), dtype=np.uint8
                ), **host,
            )
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_frontier(
    path: str,
    geom: Geometry,
    config: SolverConfig,
    grids_hash: Optional[str] = None,
) -> Frontier:
    with np.load(path) as data:
        sig = bytes(data["__signature__"]).decode()
        want = _signature(geom, config, grids_hash)
        if sig != want:
            raise ValueError(
                f"checkpoint signature mismatch: saved {sig}, requested {want}"
            )
        return Frontier(**{k: jnp.asarray(data[k]) for k in Frontier._fields})


def solve_batch_checkpointed(
    grids,
    geom: Geometry,
    config: SolverConfig = SolverConfig(),
    checkpoint_path: Optional[str] = None,
    chunk_steps: int = 256,
    resume: bool = True,
    on_chunk: Optional[Callable[[Frontier], None]] = None,
) -> SolveResult:
    """Solve with periodic snapshots (and resume from an existing one).

    If ``checkpoint_path`` exists and ``resume``, the run continues exactly
    where the file left off — same compiled program, same search order, so
    the result is bit-identical to an uninterrupted run.  The file is
    removed on successful completion.

    ``on_chunk`` observes the post-chunk frontier between dispatches.
    Reading it inside the callback is safe (the chunk is synced), but the
    next chunk DONATES the state's buffers to XLA — a reference retained
    past the callback's return is invalidated, and a later read raises.
    To keep a snapshot, copy to host first: ``jax.device_get(state)``.
    """
    grids = jnp.asarray(grids)
    ghash = grids_digest(grids)
    state = None
    if checkpoint_path and resume and os.path.exists(checkpoint_path):
        state = load_frontier(checkpoint_path, geom, config, grids_hash=ghash)
    if state is None:
        state = start_frontier(grids, geom, config)

    while True:
        limit = jnp.int32(min(int(state.steps) + chunk_steps, config.max_steps))
        state = advance_frontier(state, limit, geom, config)
        jax.block_until_ready(state)
        if frontier_done(state) or int(state.steps) >= config.max_steps:
            break
        if checkpoint_path:
            save_frontier(checkpoint_path, state, geom, config, grids_hash=ghash)
        if on_chunk is not None:
            on_chunk(state)

    if checkpoint_path and os.path.exists(checkpoint_path):
        os.unlink(checkpoint_path)
    return jax.jit(_finalize)(state)
