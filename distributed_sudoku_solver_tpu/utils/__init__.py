from distributed_sudoku_solver_tpu.utils.oracle import (  # noqa: F401
    solve_oracle,
    is_valid_solution,
)
