"""Tracing / profiling subsystem (SURVEY.md §5.1).

The reference's only observability is accidental print-noise (its log guard
is tautological, ``/root/reference/DHT_Node.py:223``) plus a wall-clock
``duration`` in the HTTP reply.  Here:

* :func:`device_trace` wraps ``jax.profiler`` — TensorBoard-compatible
  device traces (op timeline, HBM, fusion) for any code region;
* :class:`StatWindow` keeps a bounded ring of recent samples (per-job
  latencies, batch sizes) with percentile readout, surfaced by the engine
  under ``GET /metrics``;
* the per-solve counters (steps, sweeps, expansions, steals — every
  ``SolveResult`` carries them) come from the device loop itself, not a
  sampling sleep like the reference's 1 s `/stats` gather window.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

import numpy as np


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``logdir`` (TensorBoard).

    Usage::

        with device_trace("/tmp/trace"):
            solve_batch(...)  # traced region
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass  # already stopped (bounded --profile-secs window fired)


class StatWindow:
    """Bounded ring buffer of numeric samples with percentile snapshots.

    Unit-agnostic (latencies in seconds, batch sizes in jobs, ...).
    Single-writer friendly: the device loop records; any thread reads a
    consistent-enough snapshot — readers tolerate torn windows, the same
    contract as the engine's counters.
    """

    def __init__(self, capacity: int = 1024):
        self._buf = np.zeros(capacity, dtype=np.float64)
        self._n = 0
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1

    def snapshot(self) -> Optional[dict]:
        """None if empty, else ``{"count", "total", "p50", "p95", "p99"}``:
        percentiles over the current window (its size is ``count``) and the
        lifetime sample count as ``total``."""
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return None
            window = self._buf[:n].copy()
            total = self._n
        p50, p95, p99 = np.percentile(window, [50, 95, 99])
        return {
            "count": int(n),
            "total": int(total),
            "p50": float(p50),
            "p95": float(p95),
            "p99": float(p99),
        }
