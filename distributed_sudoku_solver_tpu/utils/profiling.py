"""Tracing / profiling subsystem (SURVEY.md §5.1).

The reference's only observability is accidental print-noise (its log guard
is tautological, ``/root/reference/DHT_Node.py:223``) plus a wall-clock
``duration`` in the HTTP reply.  Here:

* :func:`device_trace` wraps ``jax.profiler`` — TensorBoard-compatible
  device traces (op timeline, HBM, fusion) for any code region;
* :class:`StatWindow` keeps a bounded ring of recent samples (per-job
  latencies, batch sizes) with percentile readout, surfaced by the engine
  under ``GET /metrics``;
* the per-solve counters (steps, sweeps, expansions, steals — every
  ``SolveResult`` carries them) come from the device loop itself, not a
  sampling sleep like the reference's 1 s `/stats` gather window.
"""

from __future__ import annotations

import contextlib
import logging
import threading

from distributed_sudoku_solver_tpu.obs import lockdep
from typing import Iterator, Optional

import numpy as np

_LOG = logging.getLogger(__name__)


def _stop_trace_quietly() -> None:
    """Stop the jax profiler, swallowing ONLY the documented already-
    stopped case (a bounded window timer or a concurrent stop got there
    first — jax raises ``RuntimeError("No profile started")``-shaped
    errors for it).  Anything else is a *real* profiler failure (e.g. a
    trace-export error losing the capture) and is logged instead of
    hidden — the pre-round-11 bare ``except RuntimeError: pass`` could
    mask those forever."""
    import jax

    try:
        jax.profiler.stop_trace()
    except RuntimeError as e:
        msg = str(e).lower()
        if "no profile" in msg or "not started" in msg:
            return
        _LOG.error("[profiling] stop_trace failed: %r", e)


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace into ``logdir`` (TensorBoard).

    Usage::

        with device_trace("/tmp/trace"):
            solve_batch(...)  # traced region
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        _stop_trace_quietly()


# -- bounded serving profile window (POST /profile) ---------------------------
#
# The serving wire-up of device_trace: one bounded capture window at a
# time, started by an HTTP request and closed by a daemon timer — a
# long-lived node must never be left tracing unboundedly because a client
# forgot a second request.

_window_lock = lockdep.named_lock("utils.profile_window")  # lockck: name(utils.profile_window)
_window_active = False


def profile_window_active() -> bool:
    with _window_lock:
        return _window_active


def start_profile_window(logdir: str, secs: float) -> bool:
    """Start a jax.profiler capture into ``logdir`` that self-stops after
    ``secs``.  Returns False if a window is already open (the caller
    answers 409); propagates the profiler's own error if the start itself
    fails (e.g. a ``--profile-dir`` lifetime trace already running)."""
    global _window_active
    import jax

    with _window_lock:
        if _window_active:
            return False
        jax.profiler.start_trace(logdir)
        _window_active = True
    timer = threading.Timer(secs, _close_profile_window)
    timer.daemon = True
    timer.start()
    return True


def _close_profile_window() -> None:
    global _window_active
    with _window_lock:
        _stop_trace_quietly()
        _window_active = False


class StatWindow:
    """Bounded ring buffer of numeric samples with percentile snapshots.

    Unit-agnostic (latencies in seconds, batch sizes in jobs, ...).
    Single-writer friendly: the device loop records; any thread reads a
    consistent-enough snapshot — readers tolerate torn windows, the same
    contract as the engine's counters.
    """

    def __init__(self, capacity: int = 1024):
        self._buf = np.zeros(capacity, dtype=np.float64)
        self._n = 0
        self._lock = lockdep.named_lock("utils.statwindow")  # lockck: name(utils.statwindow)

    def record(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1

    def snapshot(self) -> Optional[dict]:
        """None if empty, else ``{"count", "total", "p50", "p95", "p99"}``:
        percentiles over the current window (its size is ``count``) and the
        lifetime sample count as ``total``."""
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return None
            window = self._buf[:n].copy()
            total = self._n
        p50, p95, p99 = np.percentile(window, [50, 95, 99])
        return {
            "count": int(n),
            "total": int(total),
            "p50": float(p50),
            "p95": float(p95),
            "p99": float(p99),
        }
